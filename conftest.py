"""Test harness config: force an 8-device virtual CPU mesh before JAX loads.

Mirrors the reference's "multi-node without a cluster" strategy (SURVEY §4.5:
N gb processes on loopback) — here N virtual JAX CPU devices so the sharded
query plane (shard_map over the mesh) is exercised without TPU hardware.
Must run before any ``import jax`` in the test session.
"""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("JAX_ENABLE_X64", "1")

# the axon TPU plugin ignores JAX_PLATFORMS; the config route sticks
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running multi-process integration test")
    # never resolve real DNS from tests: the sandbox's resolver path can
    # hang, and every distinct host would pay the lookup timeout. The
    # deterministic pseudo-IP keeps per-IP politeness/sharding semantics
    # exercised (same host → same IP) without the network.
    from open_source_search_engine_tpu.utils import ipresolve
    ipresolve.resolver_override = ipresolve._pseudo_ip
