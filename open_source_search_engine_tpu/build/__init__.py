"""Build layer: document pipeline (crawl → parse → index).

Reference layer L4 (SURVEY §2.6): ``XmlDoc.cpp`` (56k LoC lazy DAG),
``Xml``/``Words``/``Phrases``/``Pos``/``Sections`` (tokenize + positions),
``Spider.cpp`` (crawl scheduler), ``Msg13`` (fetcher), ``PageInject``
(direct injection). Here the pipeline is a straight-line function over
columnar arrays instead of a 200-stage callback DAG: tokenize → rank
vectors → vectorized posdb key pack → one batched Rdb add per database.
"""
