"""Document indexer — tokens → database records (the XmlDoc equivalent).

Reference: ``XmlDoc::indexDoc`` (``XmlDoc.cpp:2455``) → ``getMetaList``
(``XmlDoc.cpp:23825``) assembles every database's records for one document:
posdb postings via ``hashAll`` (``XmlDoc.cpp:28957``), the compressed
TitleRec (``XmlDoc.cpp:5385``), the clusterdb record, spiderdb outlink
requests and linkdb records; deletion/reindex regenerates the *old*
document's meta list with tombstone keys.

TPU-first: instead of a 200-stage callback DAG, one straight-line function
computes columnar token arrays, vectorized rank vectors, and a single
batched ``pack`` per database.

Rank semantics (kept faithful so scoring matches):

* density rank = ``MAXDENSITYRANK - (alnum words in sentence - 1)``,
  clamped to ≥1; whole-string count for title/meta/inlink groups
  (reference ``getDensityRanks``, ``XmlDoc.cpp:41733``).
* word spam rank: 15 = no spam (weight (r+1)/16, ``Posdb.cpp``
  initWeights); a simple repetition heuristic lowers it.
* diversity rank: stored but weights are disabled at query time
  (``initWeights`` sets all 1.0), so we store MAXDIVERSITYRANK.
* a content-checksum term sharded by termid (``shardbytermid=1``) is
  emitted for duplicate detection (reference checksum terms,
  ``Posdb.h`` 'N' bit note).
"""

from __future__ import annotations

import time
from collections import Counter
from dataclasses import dataclass, field

import numpy as np

from ..index import clusterdb, posdb, titledb
from ..index.collection import Collection
from ..utils import ghash
from ..utils.lang import detect_language
from ..utils.log import get_logger
from ..utils.membudget import g_membudget
from ..utils.url import normalize
from .tokenizer import (_WORD_RE, TokenizedDoc, tokenize_html,
                        tokenize_text)

log = get_logger("build")

CONTENT_HASH_PREFIX = "gbcontenthash"
SITE_PREFIX = "site"


@dataclass
class MetaList:
    """Everything one document contributes to the databases (the reference's
    serialized 'meta list', ``XmlDoc::getMetaList``)."""

    docid: int
    posdb_keys: np.ndarray
    titledb_key: np.ndarray
    title_rec: bytes
    clusterdb_key: np.ndarray
    links: list[tuple[str, str]]
    langid: int
    site: str
    words: list[str] | None = None  # doc vocabulary (feeds the Speller)
    #: linkees whose anchor set this add/remove touched — the next
    #: propagation wave (consumed by :func:`refresh_linkees`)
    refresh_targets: list = field(default_factory=list)
    #: resolved outlink edges [(linkee Url, anchor)] and the linkee →
    #: site-boundary map FROZEN at build time (stored in the TitleRec,
    #: so the delete path tombstones linkdb edges under the exact keys
    #: the add wrote, even if tagdb boundaries changed since)
    edges: list = field(default_factory=list)
    edge_sites: dict = field(default_factory=dict)
    #: this page's section content hashes (sectiondb records) and the
    #: subset demoted as boilerplate at build time — both stored in the
    #: TitleRec so tombstones regenerate the exact same postings even
    #: after the site's section votes move
    sections: list = field(default_factory=list)
    boiler_sections: list = field(default_factory=list)
    #: structured document fields (qajson-style): every extracted field
    #: (strings included — facet source) plus fielddb records for the
    #: numeric subset and the built-in ``date``
    fields: dict = field(default_factory=dict)
    fielddb_keys: np.ndarray | None = None
    fielddb_blobs: list = field(default_factory=list)


def doc_section_hashes(tdoc: TokenizedDoc) -> dict[int, int]:
    """section id → 32-bit content hash (Sections.cpp section content
    hashes): the repeatable-across-pages identity of each second-level
    container's word content."""
    from ..index.sectiondb import MIN_SECTION_WORDS
    nat = getattr(tdoc, "native", None)
    if nat is not None:
        return {int(p): ghash.hash64(c) & 0xFFFFFFFF
                for p, wc, c in zip(nat.sect_hash, nat.sect_words,
                                    nat.sect_content)
                if wc >= MIN_SECTION_WORDS}
    by_sid: dict[int, list[str]] = {}
    for sid, w in zip(tdoc.section_ids, tdoc.words):
        if sid:
            by_sid.setdefault(sid, []).append(w)
    return {sid: ghash.hash64(" ".join(ws)) & 0xFFFFFFFF
            for sid, ws in by_sid.items()
            if len(ws) >= MIN_SECTION_WORDS}


def _density_ranks(hashgroups: np.ndarray, sentences: np.ndarray) -> np.ndarray:
    """Vectorized getDensityRanks: per-sentence word counts for body/
    heading (and inlink text, where each anchor is its own sentence —
    the reference runs getDensityRanks over each link text string),
    whole-group counts for the rest."""
    n = len(hashgroups)
    out = np.empty(n, dtype=np.uint64)
    per_sentence = (hashgroups == posdb.HASHGROUP_BODY) | (
        hashgroups == posdb.HASHGROUP_HEADING) | (
        hashgroups == posdb.HASHGROUP_INLINKTEXT)
    if per_sentence.any():
        sent = sentences[per_sentence]
        uniq, inv, counts = np.unique(sent, return_inverse=True,
                                      return_counts=True)
        dr = posdb.MAXDENSITYRANK - (counts[inv] - 1)
        out[per_sentence] = np.clip(dr, 1, posdb.MAXDENSITYRANK)
    if (~per_sentence).any():
        hg = hashgroups[~per_sentence]
        uniq, inv, counts = np.unique(hg, return_inverse=True,
                                      return_counts=True)
        dr = posdb.MAXDENSITYRANK - (counts[inv] - 1)
        out[~per_sentence] = np.clip(dr, 1, posdb.MAXDENSITYRANK)
    return out


def _spam_ranks(words: list[str]) -> np.ndarray:
    """15 = clean. Words filling >12.5% of a ≥40-word doc get docked in
    proportion — a cheap stand-in for the reference's repetition-pattern
    detector (``Spam.cpp``-era logic folded into XmlDoc)."""
    n = len(words)
    ranks = np.full(n, posdb.MAXWORDSPAMRANK, dtype=np.uint64)
    if n < 40:
        return ranks
    uniq, inv, counts = np.unique(np.asarray(words, dtype=object),
                                  return_inverse=True,
                                  return_counts=True)
    frac = counts[inv] / n
    docked = np.maximum(
        2, (posdb.MAXWORDSPAMRANK * (1.0 - frac) * 0.8).astype(np.int64)
    ).astype(np.uint64)
    return np.where(frac > 0.125, docked, ranks)


def extract_fields(content: str) -> dict:
    """Structured document fields (the qajson/qaxml ingestion path,
    ``qa.cpp:2910``): a JSON document's scalars become fields (nested
    objects flatten with dots). Strings feed facets; numbers feed
    fielddb columns (gbmin/gbmax/gbsortby). HTML documents contribute
    only the built-in ``date`` field, taken from the page's date
    ``<meta>`` tags (``tdoc.meta_date``) in ``build_meta_list``."""
    import json as _json
    fields: dict = {}
    stripped = content.lstrip()
    if stripped.startswith("{"):
        try:
            obj = _json.loads(stripped)
        except ValueError:
            obj = None
        if isinstance(obj, dict):
            def flat(prefix, o):
                for k, v in o.items():
                    key = f"{prefix}{k}" if not prefix else \
                        f"{prefix}.{k}"
                    if isinstance(v, dict):
                        flat(key, v)
                    elif isinstance(v, (int, float, str)) \
                            and not isinstance(v, bool):
                        fields[key.lower()] = v
            flat("", obj)
    return fields


def _parse_date(val) -> float | None:
    """Best-effort document date → epoch seconds (meta tags carry
    ISO-8601 mostly)."""
    if isinstance(val, (int, float)):
        return float(val)
    if not isinstance(val, str) or not val:
        return None
    from datetime import datetime, timezone
    for fmt in ("%Y-%m-%dT%H:%M:%S", "%Y-%m-%d %H:%M:%S", "%Y-%m-%d",
                "%Y/%m/%d"):
        try:
            dt = datetime.strptime(val[:19], fmt)
            return dt.replace(tzinfo=timezone.utc).timestamp()
        except ValueError:
            continue
    return None


def _tokenize_doc(content: str, url: str, is_html: bool,
                  fields: dict | None = None) -> TokenizedDoc:
    """Structured (JSON) docs tokenize their string field values as the
    searchable text; everything else goes through the HTML/plain
    tokenizers. The gate and the text source are ALWAYS re-derived
    from the content itself: augmented fields (catdb categories, the
    built-in date — present in every stored titlerec) must never
    hijack tokenization, and add/tombstone must tokenize identically
    regardless of which fields dict the caller holds."""
    jf = extract_fields(content)
    if jf:
        text = " . ".join(str(v) for v in jf.values()
                          if isinstance(v, str))
        if text:
            return tokenize_text(text)
    return (tokenize_html(content, url) if is_html
            else tokenize_text(content))


def build_meta_list(
    url: str,
    content: str,
    *,
    is_html: bool = True,
    siterank: int = 0,
    langid: int | None = None,
    delete: bool = False,
    ts: float | None = None,
    inlinks: list | None = None,
    site: str | None = None,
    site_resolver=None,
    linkee_sites: dict | None = None,
    tdoc: TokenizedDoc | None = None,
    boiler_sections: list | None = None,
    sect_of: dict[int, int] | None = None,
    fields: dict | None = None,
) -> MetaList:
    """Compute every record one document contributes. ``delete=True``
    produces the same records as tombstones (reference: the old doc's
    meta list with negative keys, ``XmlDoc::getMetaList`` del path).

    ``inlinks`` is the harvested [(anchor text, linker siterank)] list
    (Msg25 LinkInfo): each anchor's words become HASHGROUP_INLINKTEXT
    postings with the linker's siterank in the wordspamrank slot
    (``XmlDoc::hashIncomingLinkText``; LINKER_WEIGHTS applies
    sqrt(1+siterank), ``Posdb.cpp:1136``). The snapshot is stored in the
    TitleRec so the delete path regenerates the exact same postings.

    ``site`` overrides the url-derived site boundary (SiteGetter/tagdb
    ``sitepathdepth`` — a subdirectory site on a hosting domain); it
    flows into the site: term, the clusterdb sitehash, and the stored
    TitleRec, so clustering and fielded search honor the boundary.
    ``site_resolver`` (normally ``Tagdb.site_of``) freezes each
    outlink's site boundary into the TitleRec; ``linkee_sites`` replays
    a stored map on the tombstone path so delete keys match add keys."""
    u = normalize(url)
    site = site or u.site
    docid = ghash.doc_id(u.full)
    if fields is None:
        fields = extract_fields(content)
    if tdoc is None:
        tdoc = _tokenize_doc(content, u.full, is_html, fields)
    edges = resolve_links(tdoc.links, u.full)
    if linkee_sites is None:
        resolver = site_resolver or (lambda lu: lu.site)
        linkee_sites = {lk.full: resolver(lk) for lk, _ in edges}
    if sect_of is None:
        sect_of = doc_section_hashes(tdoc)
    boiler = set(boiler_sections or [])

    nat = getattr(tdoc, "native", None)
    doc_words = list(tdoc.words)
    words = list(doc_words)

    if langid is None:
        langid = detect_language(doc_words, text=tdoc.text)

    # inlink anchor tokens: each anchor is its own sentence, in its own
    # position neighborhood (gaps > NONBODY_DIST_CAP=50 so words of
    # different anchors never look adjacent to pair scoring)
    inlinks = [(t, int(sr)) for t, sr in (inlinks or []) if t]
    il_words: list[str] = []
    il_wp: list[int] = []
    il_sent: list[int] = []
    il_spam: list[int] = []
    il_den: list[int] = []
    if inlinks:
        pos0 = (max(tdoc.wordpos) if tdoc.wordpos else 0) + 100
        sent0 = (max(tdoc.sentence_ids) if tdoc.sentence_ids else 0) + 1
        for j, (text, linker_sr) in enumerate(inlinks):
            aw = [w.lower() for w in _WORD_RE.findall(text)][:64]
            dr = int(np.clip(posdb.MAXDENSITYRANK - (len(aw) - 1), 1,
                             posdb.MAXDENSITYRANK))
            for i, w in enumerate(aw):
                il_words.append(w)
                il_wp.append(min(pos0 + i, posdb.MAXWORDPOS))
                il_sent.append(sent0 + j)
                il_spam.append(min(max(linker_sr, 0),
                                   posdb.MAXWORDSPAMRANK))
                il_den.append(dr)
            pos0 += len(aw) + 100
        words += il_words

    def _cat(a, b, dtype):
        ba = np.array(b, dtype=dtype)
        return np.concatenate([np.asarray(a, dtype=dtype), ba]) \
            if len(b) else np.asarray(a, dtype=dtype)

    if nat is not None:
        wordpos = _cat(nat.wordpos, il_wp, np.uint64)
        hashgroups = _cat(
            nat.hashgroup,
            [posdb.HASHGROUP_INLINKTEXT] * len(il_words), np.uint64)
        sentences = _cat(nat.sentence, il_sent, np.uint64)
    else:
        wordpos = _cat(tdoc.wordpos, il_wp, np.uint64)
        hashgroups = _cat(
            tdoc.hashgroups,
            [posdb.HASHGROUP_INLINKTEXT] * len(il_words), np.uint64)
        sentences = _cat(tdoc.sentence_ids, il_sent, np.uint64)

    delbit = 0 if delete else 1

    if len(words):
        if nat is not None:
            # native fast path: termids/density/spam precomputed in C++
            # for the doc+url tokens; the inlink block (Python-side
            # extras) computes per-anchor ranks by the same formulas
            termids = _cat(
                nat.termid,
                [ghash.term_id(w) for w in il_words], np.uint64)
            density = _cat(nat.density, il_den, np.uint64)
            doc_spam = nat.spam.astype(np.uint64)
        else:
            termids = np.array([ghash.term_id(w) for w in words],
                               dtype=np.uint64)
            density = _density_ranks(hashgroups, sentences)
            doc_spam = _spam_ranks(doc_words)
        if boiler:
            # boilerplate-section demotion (the Sections dup-vote →
            # score-weight flow): tokens of a section repeated across
            # the site get their spam rank docked
            from ..index.sectiondb import BOILER_SPAMRANK
            if nat is not None:
                bpaths = np.array(
                    [p for p, ch in sect_of.items() if ch in boiler],
                    dtype=np.uint64)
                bmask = np.isin(nat.sect, bpaths)
            else:
                bmask = np.array(
                    [sect_of.get(sid) in boiler
                     for sid in tdoc.section_ids], dtype=bool)
            doc_spam = np.where(bmask,
                                np.minimum(doc_spam, BOILER_SPAMRANK),
                                doc_spam)
        spam = _cat(doc_spam, il_spam, np.uint64)
        # bigrams: consecutive words within a sentence and hashgroup get a
        # combined term at the first word's position (reference Phrases.cpp;
        # bigram keys share the leading word's position — Posdb.cpp comment
        # "the wordpositions are exactly the same")
        bi = np.empty(0, np.int64)
        bids = np.empty(0, np.uint64)
        if nat is not None:
            # doc-token bigrams come precomputed; inlink bigrams (pairs
            # within one anchor) are appended with the same rule
            bi_parts = [nat.b_src.astype(np.int64)] \
                if len(nat.b_src) else []
            bid_parts = [nat.b_termid] if len(nat.b_termid) else []
            if len(il_words) > 1:
                n0 = len(nat.termid)
                ils = np.array(il_sent)
                pair = np.nonzero(ils[1:] == ils[:-1])[0]
                if len(pair):
                    bi_parts.append(pair + n0)
                    bid_parts.append(np.array(
                        [ghash.bigram_id(il_words[i], il_words[i + 1])
                         for i in pair], dtype=np.uint64))
            if bid_parts:
                bi = np.concatenate(bi_parts)
                bids = np.concatenate(bid_parts)
        elif len(words) > 1:
            same_sent = sentences[1:] == sentences[:-1]
            same_hg = hashgroups[1:] == hashgroups[:-1]
            # no phrases from positionless groups (url words, meta tags) —
            # their tokens aren't genuinely adjacent prose
            phrasable = (hashgroups[:-1] != posdb.HASHGROUP_INURL) & (
                hashgroups[:-1] != posdb.HASHGROUP_INMETATAG)
            bi = np.nonzero(same_sent & same_hg & phrasable)[0]
            if len(bi):
                bids = np.array(
                    [ghash.bigram_id(words[i], words[i + 1]) for i in bi],
                    dtype=np.uint64)
        # ONE pack per document: word keys + bigram keys + the site: and
        # checksum extra terms (reference hashUrl/checksum terms) — the
        # per-call broadcast overhead of separate packs measured as a
        # top indexing cost
        site_tid = ghash.term_id(site, prefix=SITE_PREFIX)
        content_hash = ghash.hash64(tdoc.text or content)
        chk_tid = ghash.term_id(f"{content_hash:x}",
                                prefix=CONTENT_HASH_PREFIX)
        two0 = np.zeros(2, np.uint64)
        n_all = len(termids) + len(bids) + 2
        sbt = np.zeros(n_all, np.uint64)
        sbt[-1] = 1  # checksum term shards by termid
        posdb_keys = posdb.pack(
            termid=np.concatenate(
                [termids, bids,
                 np.array([site_tid, chk_tid], np.uint64)]),
            docid=docid,
            wordpos=np.concatenate([wordpos, wordpos[bi], two0]),
            densityrank=np.concatenate([density, density[bi], two0]),
            wordspamrank=np.concatenate(
                [spam, spam[bi],
                 np.full(2, posdb.MAXWORDSPAMRANK, np.uint64)]),
            siterank=siterank,
            hashgroup=np.concatenate(
                [hashgroups, hashgroups[bi],
                 np.full(2, posdb.HASHGROUP_INURL, np.uint64)]),
            langid=langid, delbit=delbit, shardbytermid=sbt,
        )
    else:
        site_tid = ghash.term_id(site, prefix=SITE_PREFIX)
        content_hash = ghash.hash64(tdoc.text or content)
        posdb_keys = posdb.pack(
            termid=[site_tid,
                    ghash.term_id(f"{content_hash:x}",
                                  prefix=CONTENT_HASH_PREFIX)],
            docid=docid, wordpos=0, siterank=siterank, langid=langid,
            hashgroup=posdb.HASHGROUP_INURL, delbit=delbit,
            shardbytermid=[0, 1],
        )

    # structured fields: resolve the built-in date ONCE and store the
    # resolved dict in the titlerec, so the tombstone path regenerates
    # byte-identical fielddb records (same resolution the posdb
    # tombstones rely on)
    fields = dict(fields)
    dv = _parse_date(fields.get("date"))
    if dv is None:
        # HTML pages: the date <meta> tag (article:published_time etc.)
        dv = _parse_date(getattr(tdoc, "meta_date", "") or None)
    fields["date"] = dv if dv is not None else float(
        ts if ts is not None else time.time())
    from ..index import fielddb as fielddb_mod
    numeric = {f: v for f, v in fields.items()
               if isinstance(v, (int, float))
               and not isinstance(v, bool)}
    fdb_keys, fdb_blobs = fielddb_mod.make_records(
        docid, numeric, delbit=0 if delete else 1)

    if delete:
        title_rec = b""  # tombstone payload; skip the pointless compress
    else:
        # first heading run → the h1 title-fallback source (Title.cpp
        # falls back title → h1 → anchor → url; stored as lowercased
        # tokens — the tokenizer's columnar stream is the one source
        # both the python and native paths share). Vectorized: one
        # nonzero over the hashgroup column, not a per-token loop.
        h1 = ""
        hgarr = nat.hashgroup if nat is not None else \
            np.asarray(tdoc.hashgroups, dtype=np.uint64)
        hidx = np.nonzero(hgarr == posdb.HASHGROUP_HEADING)[0]
        if len(hidx):
            a = int(hidx[0])
            k = 0  # length of the contiguous first run, capped at 16
            while k < min(len(hidx), 16) and int(hidx[k]) == a + k:
                k += 1
            h1 = " ".join(tdoc.words[a:a + k])
        title_rec = titledb.make_title_rec(
            url=u.full, title=tdoc.title.strip(), text=tdoc.text,
            links=tdoc.links, site=site, langid=langid, siterank=siterank,
            content_hash=content_hash,
            ts=ts if ts is not None else time.time(),
            extra={"content": content, "is_html": is_html,
                   "h1": h1,
                   "meta_description": tdoc.meta_description,
                   "inlinks": [[t, sr] for t, sr in inlinks],
                   "linkee_sites": linkee_sites,
                   "sections": sorted(set(sect_of.values())),
                   "boiler_sections": sorted(boiler),
                   "fields": fields},
        )
    sitehash = ghash.hash64(site) & ((1 << clusterdb.SITEHASH_BITS) - 1)
    return MetaList(
        docid=docid,
        posdb_keys=posdb_keys,
        titledb_key=titledb.pack_key(docid, titledb.urlhash32(u.full), delbit),
        title_rec=title_rec,
        clusterdb_key=clusterdb.pack_key(docid, sitehash, langid, 0, delbit),
        links=tdoc.links,
        langid=langid,
        site=site,
        words=doc_words,
        edges=edges,
        edge_sites=linkee_sites,
        sections=sorted(set(sect_of.values())),
        boiler_sections=sorted(boiler),
        fields=fields,
        fielddb_keys=fdb_keys,
        fielddb_blobs=fdb_blobs,
    )


def absolutize(base: str, href: str) -> str | None:
    """Resolve an outlink href against its page URL (skip non-http)."""
    from urllib.parse import urldefrag, urljoin
    if href.startswith(("javascript:", "mailto:", "#")):
        return None
    absu = urldefrag(urljoin(base, href))[0] or None
    if absu and not absu.startswith(("http://", "https://")):
        return None
    return absu


def resolve_links(links: list[tuple[str, str]], linker_url: str):
    """Normalized (linkee, anchor) pairs for raw hrefs — the linkdb
    records the reference's meta list carries."""
    out = []
    for href, anchor in links:
        absu = absolutize(linker_url, href)
        if not absu:
            continue
        try:
            linkee = normalize(absu)
        except Exception:  # noqa: BLE001 — junk hrefs abound
            continue
        out.append((linkee, anchor))
    return out




def needs_link_refresh(fresh: list, stored: list) -> bool:
    """Should a linkee reindex to pick up its changed anchor set?
    Removals and changes always refresh (a stale weight-16 signal is
    worse than a missing one); growth refreshes exactly while small,
    then on doublings — the reference's deferred LinkInfo update
    interval, made deterministic, bounding hub-page reindexes to
    O(log inlinkers) during a crawl."""
    if sorted(fresh) == sorted(stored):
        return False
    if len(fresh) <= len(stored):
        return True
    if len(stored) < 8:
        return True
    return len(fresh) >= 2 * len(stored)


#: bound on anchor-refresh cascades along link chains (the reference
#: defers LinkInfo updates, so long chains settle over multiple crawl
#: rounds rather than in one synchronous walk)
MAX_REFRESH_DEPTH = 8


def refresh_linkees(linkees, own_site: str, *, get_doc, linkdb_of,
                    reindex, max_depth: int = MAX_REFRESH_DEPTH,
                    site_of=None) -> None:
    """Shared propagate step (single-node and sharded flows): for each
    external linkee already indexed, compare its stored inlink snapshot
    with a fresh harvest and reindex when stale.

    Propagation is an iterative breadth-first worklist with a visited
    set and depth cap — NOT recursion: ``reindex(linkee, rec)`` must
    perform a non-propagating reindex and return its ``MetaList`` (or
    None); the next wave is that list's ``refresh_targets``, enqueued
    here. Long link chains therefore cannot blow the Python stack, and
    a page is refreshed at most once per propagation."""
    from collections import deque

    site_of = site_of or (lambda u: u.site)
    seen: set[str] = set()
    work = deque((lk, own_site, 0) for lk in linkees)
    while work:
        linkee, src_site, depth = work.popleft()
        lk_site = site_of(linkee)
        if lk_site == src_site or linkee.full in seen:
            continue
        seen.add(linkee.full)
        rec = get_doc(linkee)
        if rec is None:
            continue
        fresh = linkdb_of(lk_site).inlinks_for_url(lk_site, linkee.full)
        stored = [tuple(x) for x in rec.get("inlinks") or []]
        if needs_link_refresh(fresh, stored):
            ml = reindex(linkee, rec)
            if ml is not None and depth + 1 < max_depth:
                work.extend((l2, linkee.site, depth + 1)
                            for l2 in ml.refresh_targets)


def index_document(coll: Collection, url: str, content: str, *,
                   is_html: bool = True, siterank: int = 0,
                   langid: int | None = None,
                   propagate: bool = True) -> MetaList:
    """Index (or re-index) one document into a collection — the
    ``XmlDoc::indexDoc`` flow: tombstone the old version if present,
    harvest this URL's inlink anchor text from linkdb (Msg25 LinkInfo),
    add the new records, record outlink edges, and re-index any already-
    indexed linkee whose anchor set changed — including linkees the OLD
    version linked to and the new one doesn't (their anchor goes away).

    Tagdb gates the whole flow (XmlDoc::indexDoc's EDOCBANNED path): a
    ``manualban`` on a containing site drops any indexed version and
    returns None; ``sitepathdepth`` widens the site boundary;
    ``siterank`` pins site quality over the link-derived rank."""
    u = normalize(url)
    banned, site, sr_override = coll.tagdb.index_gate(u)
    if banned:
        remove_document(coll, url, propagate=propagate)
        log.info("tagdb manualban: %s not indexed", url)
        return None
    if sr_override is not None:
        siterank = sr_override
    old = remove_document(coll, url, _count=False, propagate=False)
    inlinks = coll.linkdb.inlinks_for_url(site, u.full)
    # boilerplate gate (Sections dup votes): sections this page shares
    # with enough sibling pages of the site demote at build time
    flds = extract_fields(content)
    # directory taxonomy (Catdb): a filed site's docs carry catid/
    # category fields — gbmin:catid:/gbfacet:category do the rest
    flds.update(coll.catdb.doc_fields(site))
    tdoc = _tokenize_doc(content, u.full, is_html, flds)
    sect_of = doc_section_hashes(tdoc)
    boiler = coll.sectiondb.boiler_set(site, sect_of.values())
    ml = build_meta_list(url, content, is_html=is_html, siterank=siterank,
                         langid=langid, inlinks=inlinks, site=site,
                         site_resolver=coll.tagdb.site_of, tdoc=tdoc,
                         boiler_sections=boiler, sect_of=sect_of,
                         fields=flds)
    coll.posdb.add(ml.posdb_keys)
    coll.titledb.add(ml.titledb_key.reshape(1), [ml.title_rec])
    coll.clusterdb.add(ml.clusterdb_key.reshape(1))
    if ml.fielddb_keys is not None and len(ml.fielddb_keys):
        coll.fielddb.add(ml.fielddb_keys, ml.fielddb_blobs)
    coll.sectiondb.add_page_sections(site, u.full, ml.sections)
    coll.titlerec_cache.pop(ml.docid, None)
    if ml.words:
        coll.speller.add_doc_words(ml.words)
    if old is None:
        coll.doc_added()
    # record outlink edges with anchor text (this page's siterank is the
    # linker rank riding each edge), then refresh affected linkees:
    # the new edge set plus any former linkees whose edge was tombstoned
    edges = ml.edges
    for linkee, anchor in edges:
        coll.linkdb.add_link(
            ml.edge_sites.get(linkee.full, linkee.site), site, u.full,
            linkee_url=linkee.full, anchor_text=anchor,
            linker_siterank=siterank)
    ml.refresh_targets = [e[0] for e in edges]
    if old is not None:
        ml.refresh_targets += old.refresh_targets
    if propagate:
        refresh_linkees(
            ml.refresh_targets, site,
            get_doc=lambda lk: get_document(coll, url=lk.full),
            linkdb_of=lambda _site: coll.linkdb,
            reindex=lambda lk, rec: reindex_document(
                coll, lk.full, propagate=False),
            site_of=coll.tagdb.site_of)
    log.debug("indexed %s docid=%d keys=%d inlinks=%d", url, ml.docid,
              len(ml.posdb_keys), len(inlinks))
    return ml


def index_batch(coll: Collection, docs, *, is_html: bool = True,
                siterank: int = 0, langid: int | None = None,
                propagate: bool = True) -> list[MetaList | None]:
    """Bulk indexing: N documents in one pass — the TPU-era shape of
    the reference's fully-async build pipeline (SURVEY §2.5). Same
    records as N ``index_document`` calls, restructured into three
    phases so per-document overheads amortize:

    * **reads first** (tagdb gates, existing-doc probes, inlink
      harvests, boilerplate votes) — no writes interleave, so the
      memtables seal ONCE per batch instead of once per document
      (the seal-thrash was a top indexing cost);
    * **compute** (tokenize + meta lists) — pure, per document;
    * **writes last**, one batched Rdb add per database: a single
      concatenated posdb add, one titledb/clusterdb add, then linkdb
      edges and section votes.

    Documents already in the index (re-adds) and within-batch duplicate
    URLs fall back to the sequential path — bulk loads are
    overwhelmingly fresh URLs. Returns one MetaList (or None for
    banned/failed docs) per input, in order."""
    out: list[MetaList | None] = [None] * len(docs)
    seen_urls: dict[str, int] = {}
    leftovers: list[tuple[int, str, str]] = []  # dups/re-adds, last
    work = []  # (i, u, url, content, site, siterank)
    for i, (url, content) in enumerate(docs):
        try:
            u = normalize(url)
        except Exception:  # noqa: BLE001 — junk URLs abound in bulk
            continue
        banned, site, sr_override = coll.tagdb.index_gate(u)
        if banned:
            remove_document(coll, url, propagate=propagate)
            log.info("tagdb manualban: %s not indexed", url)
            continue
        if u.full in seen_urls or get_document(coll, url=u.full) \
                is not None:
            # duplicate within batch or re-add → sequential fallback,
            # DEFERRED until after the batch's records are written:
            # indexing it now would race phase C (the first occurrence
            # isn't in the Rdb yet, so newest-wins would resurrect it
            # and doc accounting would double-count)
            leftovers.append((i, url, content))
            continue
        seen_urls[u.full] = i
        work.append((i, u, url, content, site,
                     siterank if sr_override is None else sr_override))

    # --- phase A reads: inlink harvests + boilerplate votes ---
    reads = []
    for i, u, url, content, site, sr in work:
        inlinks = coll.linkdb.inlinks_for_url(site, u.full)
        flds = extract_fields(content)
        flds.update(coll.catdb.doc_fields(site))
        tdoc = _tokenize_doc(content, u.full, is_html, flds)
        sect_of = doc_section_hashes(tdoc)
        boiler = coll.sectiondb.boiler_set(site, sect_of.values())
        reads.append((inlinks, tdoc, boiler, sect_of, flds))

    # --- phase B compute: meta lists (pure) ---
    metas = []
    for (i, u, url, content, site, sr), \
            (inlinks, tdoc, boiler, sect_of, flds) in zip(work, reads):
        ml = build_meta_list(url, content, is_html=is_html,
                             siterank=sr, langid=langid,
                             inlinks=inlinks, site=site,
                             site_resolver=coll.tagdb.site_of,
                             tdoc=tdoc, boiler_sections=boiler,
                             sect_of=sect_of, fields=flds)
        metas.append(ml)
        out[i] = ml

    def _run_leftovers():
        for i, url, content in leftovers:
            out[i] = index_document(coll, url, content,
                                    is_html=is_html,
                                    siterank=siterank, langid=langid,
                                    propagate=propagate)

    if not metas:
        _run_leftovers()
        return out
    # --- phase C writes: ONE add per Rdb, gated by the memory budget.
    # Over budget the batch SHEDS: split in half and write the halves
    # separately, so the concatenated key images stay bounded and the
    # memtable can dump between chunks (the g_mem degradation arm for
    # the build pipeline — slower, never OOM).
    def _phase_c_estimate(chunk):
        return (sum(int(ml.posdb_keys.nbytes) for ml in chunk)
                + sum(len(ml.title_rec) for ml in chunk)
                + 64 * len(chunk))  # small keys (title/cluster/field)

    def _phase_c_write(chunk):
        coll.posdb.add(np.concatenate([ml.posdb_keys for ml in chunk]))
        coll.titledb.add(
            np.concatenate([ml.titledb_key.reshape(1) for ml in chunk]),
            [ml.title_rec for ml in chunk])
        coll.clusterdb.add(
            np.concatenate([ml.clusterdb_key.reshape(1) for ml in chunk]))
        withf = [ml for ml in chunk
                 if ml.fielddb_keys is not None and len(ml.fielddb_keys)]
        if withf:
            coll.fielddb.add(
                np.concatenate([ml.fielddb_keys for ml in withf]),
                [b for ml in withf for b in ml.fielddb_blobs])

    pending = [metas]
    while pending:
        chunk = pending.pop(0)
        with g_membudget.reserving(
                "docproc", _phase_c_estimate(chunk)) as granted:
            if not granted and len(chunk) > 1:
                mid = len(chunk) // 2
                log.warning("index_batch: %d-doc write over memory "
                            "budget — shedding to halves", len(chunk))
                pending[:0] = [chunk[:mid], chunk[mid:]]
                continue
            # a refused SINGLE doc still writes: correctness beats the
            # budget once degradation has nothing left to shed
            _phase_c_write(chunk)
    for (i, u, url, content, site, sr), ml in zip(work, metas):
        coll.sectiondb.add_page_sections(site, u.full, ml.sections)
        coll.titlerec_cache.pop(ml.docid, None)
        if ml.words:
            coll.speller.add_doc_words(ml.words)
        coll.doc_added()
        for linkee, anchor in ml.edges:
            coll.linkdb.add_link(
                ml.edge_sites.get(linkee.full, linkee.site), site,
                u.full, linkee_url=linkee.full, anchor_text=anchor,
                linker_siterank=sr)
        ml.refresh_targets = [e[0] for e in ml.edges]
    if propagate:
        for (i, u, url, content, site, sr), ml in zip(work, metas):
            if ml.refresh_targets:
                refresh_linkees(
                    ml.refresh_targets, site,
                    get_doc=lambda lk: get_document(coll, url=lk.full),
                    linkdb_of=lambda _site: coll.linkdb,
                    reindex=lambda lk, rec: reindex_document(
                        coll, lk.full, propagate=False),
                    site_of=coll.tagdb.site_of)
    _run_leftovers()
    return out


def reindex_document(coll: Collection, url: str, *,
                     propagate: bool = True) -> MetaList | None:
    """Re-index a document from its stored content — fresh inlink
    harvest + recomputed link-derived siterank (the reference's reindex
    path, ``Repair.cpp``/``PageReindex`` semantics)."""
    from ..spider.linkdb import site_rank
    rec = get_document(coll, url=url)
    if rec is None:
        return None
    u = normalize(url)
    return index_document(
        coll, url, rec.get("content", rec["text"]),
        is_html=rec.get("is_html", True),
        siterank=site_rank(
            coll.linkdb.site_num_inlinks(coll.tagdb.site_of(u))),
        langid=rec.get("langid"), propagate=propagate)


def tombstone_meta_list(rec: dict) -> MetaList:
    """Regenerate a stored document's records as tombstones (the
    reference's delete/reindex path rebuilds the OLD doc's meta list with
    negative keys, ``XmlDoc::getMetaList`` del path). Shared by the
    single-shard and sharded delete flows so the regeneration contract
    lives in one place."""
    return build_meta_list(rec["url"], rec.get("content", rec["text"]),
                           is_html=rec.get("is_html", True),
                           siterank=rec.get("siterank", 0),
                           langid=rec.get("langid"), delete=True,
                           ts=rec.get("ts"),
                           inlinks=[tuple(x) for x in
                                    rec.get("inlinks") or []],
                           site=rec.get("site"),
                           linkee_sites=rec.get("linkee_sites"),
                           boiler_sections=rec.get("boiler_sections"),
                           fields=rec.get("fields"))


def remove_document(coll: Collection, url: str, _count: bool = True,
                    propagate: bool = True) -> MetaList | None:
    """Delete a document: regenerate its records from the stored TitleRec
    content and add them as tombstones (the reference's reindex/del path
    regenerates the old meta list the same way). Returns the tombstone
    meta list (truthy) so re-index callers can diff old/new edge sets."""
    u = normalize(url)
    docid = ghash.doc_id(u.full)
    existing = coll.titledb.get_list(titledb.start_key(docid),
                                     titledb.end_key(docid))
    # discriminate 38-bit docid collisions by the urlhash packed in the key
    # (reference: probable-docid collision handling in Titledb/XmlDoc)
    want = titledb.urlhash32(u.full)
    match = np.nonzero(
        titledb.unpack_key(existing.keys)["urlhash32"] == np.uint64(want)
    )[0] if len(existing) else np.empty(0, dtype=np.int64)
    if not len(match):
        return None
    rec = titledb.read_title_rec(existing.payload(int(match[-1])))
    ml = tombstone_meta_list(rec)
    coll.posdb.add(ml.posdb_keys)
    coll.titledb.add(ml.titledb_key.reshape(1), [b""])
    coll.clusterdb.add(ml.clusterdb_key.reshape(1))
    if ml.fielddb_keys is not None and len(ml.fielddb_keys):
        coll.fielddb.add(ml.fielddb_keys, ml.fielddb_blobs)
    coll.sectiondb.remove_page_sections(
        ml.site, u.full, rec.get("sections") or [])
    coll.titlerec_cache.pop(ml.docid, None)
    # tombstone this page's outlink edges so its anchors stop feeding
    # linkee rankings (the old meta list's linkdb records, negated)
    from ..spider.linkdb import pack_key as link_key
    edges = ml.edges
    for linkee, _anchor in edges:
        # delete under the boundary FROZEN at add time (stored in the
        # titlerec); legacy recs without the map fall back to tagdb
        lk_site = ml.edge_sites.get(linkee.full) \
            or coll.tagdb.site_of(linkee)
        if lk_site == ml.site:
            continue
        coll.linkdb.rdb.delete(
            link_key(lk_site, linkee.full, ml.site, u.full).reshape(1))
    if ml.words:
        coll.speller.remove_doc_words(ml.words)
    if _count:
        coll.doc_removed()
    ml.refresh_targets = [e[0] for e in edges]
    if propagate:
        # former linkees lose this page's anchor — refresh them
        refresh_linkees(
            ml.refresh_targets, ml.site,
            get_doc=lambda lk: get_document(coll, url=lk.full),
            linkdb_of=lambda _site: coll.linkdb,
            reindex=lambda lk, _rec: reindex_document(
                coll, lk.full, propagate=False),
            site_of=coll.tagdb.site_of)
    return ml


def get_document(coll: Collection, url: str | None = None,
                 docid: int | None = None) -> dict | None:
    """TitleRec lookup by url or docid (reference Msg22 titlerec fetch +
    PageGet cached-page view), behind the collection's RdbCache-style
    parsed-rec cache."""
    want = None
    if docid is None:
        assert url is not None
        full = normalize(url).full
        docid = ghash.doc_id(full)
        want = titledb.urlhash32(full)
    elif docid in coll.titlerec_cache:
        return coll.titlerec_cache[docid]
    lst = coll.titledb.get_list(titledb.start_key(docid),
                                titledb.end_key(docid))
    rec = None
    if len(lst):
        idx = len(lst) - 1
        if want is not None:  # docid-collision discrimination
            match = np.nonzero(
                titledb.unpack_key(lst.keys)["urlhash32"]
                == np.uint64(want))[0]
            idx = int(match[-1]) if len(match) else -1
        if idx >= 0:
            payload = lst.payload(idx)
            rec = titledb.read_title_rec(payload) if payload else None
    if want is None:  # only docid-keyed lookups are cacheable
        if len(coll.titlerec_cache) >= coll.titlerec_cache_max:
            coll.titlerec_cache.clear()
        coll.titlerec_cache[docid] = rec
    return rec
