"""Document indexer — tokens → database records (the XmlDoc equivalent).

Reference: ``XmlDoc::indexDoc`` (``XmlDoc.cpp:2455``) → ``getMetaList``
(``XmlDoc.cpp:23825``) assembles every database's records for one document:
posdb postings via ``hashAll`` (``XmlDoc.cpp:28957``), the compressed
TitleRec (``XmlDoc.cpp:5385``), the clusterdb record, spiderdb outlink
requests and linkdb records; deletion/reindex regenerates the *old*
document's meta list with tombstone keys.

TPU-first: instead of a 200-stage callback DAG, one straight-line function
computes columnar token arrays, vectorized rank vectors, and a single
batched ``pack`` per database.

Rank semantics (kept faithful so scoring matches):

* density rank = ``MAXDENSITYRANK - (alnum words in sentence - 1)``,
  clamped to ≥1; whole-string count for title/meta/inlink groups
  (reference ``getDensityRanks``, ``XmlDoc.cpp:41733``).
* word spam rank: 15 = no spam (weight (r+1)/16, ``Posdb.cpp``
  initWeights); a simple repetition heuristic lowers it.
* diversity rank: stored but weights are disabled at query time
  (``initWeights`` sets all 1.0), so we store MAXDIVERSITYRANK.
* a content-checksum term sharded by termid (``shardbytermid=1``) is
  emitted for duplicate detection (reference checksum terms,
  ``Posdb.h`` 'N' bit note).
"""

from __future__ import annotations

import time
from collections import Counter
from dataclasses import dataclass

import numpy as np

from ..index import clusterdb, posdb, titledb
from ..index.collection import Collection
from ..utils import ghash
from ..utils.lang import detect_language
from ..utils.log import get_logger
from ..utils.url import normalize
from .tokenizer import TokenizedDoc, tokenize_html, tokenize_text

log = get_logger("build")

CONTENT_HASH_PREFIX = "gbcontenthash"
SITE_PREFIX = "site"


@dataclass
class MetaList:
    """Everything one document contributes to the databases (the reference's
    serialized 'meta list', ``XmlDoc::getMetaList``)."""

    docid: int
    posdb_keys: np.ndarray
    titledb_key: np.ndarray
    title_rec: bytes
    clusterdb_key: np.ndarray
    links: list[tuple[str, str]]
    langid: int
    site: str
    words: list[str] | None = None  # doc vocabulary (feeds the Speller)


def _density_ranks(hashgroups: np.ndarray, sentences: np.ndarray) -> np.ndarray:
    """Vectorized getDensityRanks: per-sentence word counts for body/heading,
    whole-group counts for the rest."""
    n = len(hashgroups)
    out = np.empty(n, dtype=np.uint64)
    per_sentence = (hashgroups == posdb.HASHGROUP_BODY) | (
        hashgroups == posdb.HASHGROUP_HEADING)
    if per_sentence.any():
        sent = sentences[per_sentence]
        uniq, inv, counts = np.unique(sent, return_inverse=True,
                                      return_counts=True)
        dr = posdb.MAXDENSITYRANK - (counts[inv] - 1)
        out[per_sentence] = np.clip(dr, 1, posdb.MAXDENSITYRANK)
    if (~per_sentence).any():
        hg = hashgroups[~per_sentence]
        uniq, inv, counts = np.unique(hg, return_inverse=True,
                                      return_counts=True)
        dr = posdb.MAXDENSITYRANK - (counts[inv] - 1)
        out[~per_sentence] = np.clip(dr, 1, posdb.MAXDENSITYRANK)
    return out


def _spam_ranks(words: list[str]) -> np.ndarray:
    """15 = clean. Words filling >12.5% of a ≥40-word doc get docked in
    proportion — a cheap stand-in for the reference's repetition-pattern
    detector (``Spam.cpp``-era logic folded into XmlDoc)."""
    n = len(words)
    ranks = np.full(n, posdb.MAXWORDSPAMRANK, dtype=np.uint64)
    if n < 40:
        return ranks
    counts = Counter(words)
    for i, w in enumerate(words):
        frac = counts[w] / n
        if frac > 0.125:
            ranks[i] = max(2, int(posdb.MAXWORDSPAMRANK * (1.0 - frac) * 0.8))
    return ranks


def build_meta_list(
    url: str,
    content: str,
    *,
    is_html: bool = True,
    siterank: int = 0,
    langid: int | None = None,
    delete: bool = False,
    ts: float | None = None,
) -> MetaList:
    """Compute every record one document contributes. ``delete=True``
    produces the same records as tombstones (reference: the old doc's
    meta list with negative keys, ``XmlDoc::getMetaList`` del path)."""
    u = normalize(url)
    docid = ghash.doc_id(u.full)
    tdoc: TokenizedDoc = (tokenize_html(content, u.full) if is_html
                          else tokenize_text(content))

    words = [t.word for t in tdoc.tokens]
    wordpos = np.array([t.wordpos for t in tdoc.tokens], dtype=np.uint64)
    hashgroups = np.array([t.hashgroup for t in tdoc.tokens], dtype=np.uint64)
    sentences = np.array([t.sentence_id for t in tdoc.tokens], dtype=np.uint64)

    if langid is None:
        langid = detect_language(words)

    delbit = 0 if delete else 1

    if len(words):
        termids = np.array([ghash.term_id(w) for w in words], dtype=np.uint64)
        density = _density_ranks(hashgroups, sentences)
        spam = _spam_ranks(words)
        keys = [posdb.pack(
            termid=termids, docid=docid, wordpos=wordpos,
            densityrank=density, wordspamrank=spam, siterank=siterank,
            hashgroup=hashgroups, langid=langid, delbit=delbit,
        )]
        # bigrams: consecutive words within a sentence and hashgroup get a
        # combined term at the first word's position (reference Phrases.cpp;
        # bigram keys share the leading word's position — Posdb.cpp comment
        # "the wordpositions are exactly the same")
        if len(words) > 1:
            same_sent = sentences[1:] == sentences[:-1]
            same_hg = hashgroups[1:] == hashgroups[:-1]
            # no phrases from positionless groups (url words, meta tags) —
            # their tokens aren't genuinely adjacent prose
            phrasable = (hashgroups[:-1] != posdb.HASHGROUP_INURL) & (
                hashgroups[:-1] != posdb.HASHGROUP_INMETATAG)
            bi = np.nonzero(same_sent & same_hg & phrasable)[0]
            if len(bi):
                bids = np.array(
                    [ghash.bigram_id(words[i], words[i + 1]) for i in bi],
                    dtype=np.uint64)
                keys.append(posdb.pack(
                    termid=bids, docid=docid, wordpos=wordpos[bi],
                    densityrank=density[bi], wordspamrank=spam[bi],
                    siterank=siterank, hashgroup=hashgroups[bi],
                    langid=langid, delbit=delbit,
                ))
        posdb_keys = np.concatenate(keys)
    else:
        posdb_keys = np.empty(0, dtype=posdb.KEY_DTYPE)

    # site: term for fielded search (reference hashUrl/hashIncomingLinkText
    # emit site:/inurl: prefixed terms)
    site_tid = ghash.term_id(u.site, prefix=SITE_PREFIX)
    content_hash = ghash.hash64(tdoc.text or content)
    extra_terms = posdb.pack(
        termid=[site_tid,
                ghash.term_id(f"{content_hash:x}", prefix=CONTENT_HASH_PREFIX)],
        docid=docid, wordpos=0, siterank=siterank, langid=langid,
        hashgroup=posdb.HASHGROUP_INURL, delbit=delbit,
        shardbytermid=[0, 1],
    )
    posdb_keys = np.concatenate([posdb_keys, extra_terms]) if len(posdb_keys) \
        else extra_terms

    if delete:
        title_rec = b""  # tombstone payload; skip the pointless compress
    else:
        title_rec = titledb.make_title_rec(
            url=u.full, title=tdoc.title.strip(), text=tdoc.text,
            links=tdoc.links, site=u.site, langid=langid, siterank=siterank,
            content_hash=content_hash,
            ts=ts if ts is not None else time.time(),
            extra={"content": content, "is_html": is_html,
                   "meta_description": tdoc.meta_description},
        )
    sitehash = ghash.hash64(u.site) & ((1 << clusterdb.SITEHASH_BITS) - 1)
    return MetaList(
        docid=docid,
        posdb_keys=posdb_keys,
        titledb_key=titledb.pack_key(docid, titledb.urlhash32(u.full), delbit),
        title_rec=title_rec,
        clusterdb_key=clusterdb.pack_key(docid, sitehash, langid, 0, delbit),
        links=tdoc.links,
        langid=langid,
        site=u.site,
        words=words,
    )


def index_document(coll: Collection, url: str, content: str, *,
                   is_html: bool = True, siterank: int = 0,
                   langid: int | None = None) -> MetaList:
    """Index (or re-index) one document into a collection — the
    ``XmlDoc::indexDoc`` flow: tombstone the old version if present, add
    the new records, bump counters."""
    old = remove_document(coll, url, _count=False)
    ml = build_meta_list(url, content, is_html=is_html, siterank=siterank,
                         langid=langid)
    coll.posdb.add(ml.posdb_keys)
    coll.titledb.add(ml.titledb_key.reshape(1), [ml.title_rec])
    coll.clusterdb.add(ml.clusterdb_key.reshape(1))
    coll.titlerec_cache.pop(ml.docid, None)
    if ml.words:
        coll.speller.add_doc_words(ml.words)
    if not old:
        coll.doc_added()
    log.debug("indexed %s docid=%d keys=%d", url, ml.docid, len(ml.posdb_keys))
    return ml


def tombstone_meta_list(rec: dict) -> MetaList:
    """Regenerate a stored document's records as tombstones (the
    reference's delete/reindex path rebuilds the OLD doc's meta list with
    negative keys, ``XmlDoc::getMetaList`` del path). Shared by the
    single-shard and sharded delete flows so the regeneration contract
    lives in one place."""
    return build_meta_list(rec["url"], rec.get("content", rec["text"]),
                           is_html=rec.get("is_html", True),
                           siterank=rec.get("siterank", 0),
                           langid=rec.get("langid"), delete=True,
                           ts=rec.get("ts"))


def remove_document(coll: Collection, url: str, _count: bool = True) -> bool:
    """Delete a document: regenerate its records from the stored TitleRec
    content and add them as tombstones (the reference's reindex/del path
    regenerates the old meta list the same way)."""
    u = normalize(url)
    docid = ghash.doc_id(u.full)
    existing = coll.titledb.get_list(titledb.start_key(docid),
                                     titledb.end_key(docid))
    # discriminate 38-bit docid collisions by the urlhash packed in the key
    # (reference: probable-docid collision handling in Titledb/XmlDoc)
    want = titledb.urlhash32(u.full)
    match = np.nonzero(
        titledb.unpack_key(existing.keys)["urlhash32"] == np.uint64(want)
    )[0] if len(existing) else np.empty(0, dtype=np.int64)
    if not len(match):
        return False
    rec = titledb.read_title_rec(existing.payload(int(match[-1])))
    ml = tombstone_meta_list(rec)
    coll.posdb.add(ml.posdb_keys)
    coll.titledb.add(ml.titledb_key.reshape(1), [b""])
    coll.clusterdb.add(ml.clusterdb_key.reshape(1))
    coll.titlerec_cache.pop(ml.docid, None)
    if ml.words:
        coll.speller.remove_doc_words(ml.words)
    if _count:
        coll.doc_removed()
    return True


def get_document(coll: Collection, url: str | None = None,
                 docid: int | None = None) -> dict | None:
    """TitleRec lookup by url or docid (reference Msg22 titlerec fetch +
    PageGet cached-page view), behind the collection's RdbCache-style
    parsed-rec cache."""
    want = None
    if docid is None:
        assert url is not None
        full = normalize(url).full
        docid = ghash.doc_id(full)
        want = titledb.urlhash32(full)
    elif docid in coll.titlerec_cache:
        return coll.titlerec_cache[docid]
    lst = coll.titledb.get_list(titledb.start_key(docid),
                                titledb.end_key(docid))
    rec = None
    if len(lst):
        idx = len(lst) - 1
        if want is not None:  # docid-collision discrimination
            match = np.nonzero(
                titledb.unpack_key(lst.keys)["urlhash32"]
                == np.uint64(want))[0]
            idx = int(match[-1]) if len(match) else -1
        if idx >= 0:
            payload = lst.payload(idx)
            rec = titledb.read_title_rec(payload) if payload else None
    if want is None:  # only docid-keyed lookups are cacheable
        if len(coll.titlerec_cache) >= coll.titlerec_cache_max:
            coll.titlerec_cache.clear()
        coll.titlerec_cache[docid] = rec
    return rec
