"""Document converters — pdf/doc/ps → indexable text.

Reference: ``XmlDoc.cpp:19206-19227`` shells to external tools
(``pdftohtml``, ``antiword``, ``pstotext``) with a timeout and indexes
the converted text. Same shape here:

* external converters run when their binary exists on PATH, under a
  subprocess timeout, output capped, stdin/stdout pipes only (no shell
  interpolation, no temp-file name games — content rides stdin where
  the tool allows it);
* PDFs additionally have a BUILT-IN minimal extractor (uncompressed
  and Flate content streams, Tj/TJ show-text operators) so the
  pdf→index path works on boxes without poppler — real deployments
  install ``pdftotext`` and get full fidelity.

``convert_to_text`` is the one entry point; docproc indexes the result
with ``is_html=False`` through the ordinary tokenizer.
"""

from __future__ import annotations

import re
import shutil
import subprocess
import zlib

from ..utils.log import get_logger

log = get_logger("convert")

CONVERT_TIMEOUT_S = 20.0
MAX_TEXT_BYTES = 2 << 20

#: content-type / extension → kind
_KINDS = {
    "application/pdf": "pdf",
    "application/msword": "doc",
    "application/postscript": "ps",
}
_EXT_KINDS = {".pdf": "pdf", ".doc": "doc", ".ps": "ps", ".eps": "ps"}


def kind_of(content_type: str, url: str = "") -> str | None:
    k = _KINDS.get((content_type or "").split(";")[0].strip().lower())
    if k:
        return k
    low = url.lower().split("?")[0]
    for ext, kk in _EXT_KINDS.items():
        if low.endswith(ext):
            return kk
    return None


def is_convertible(content_type: str, url: str = "") -> bool:
    return kind_of(content_type, url) is not None


def _run_tool(argv: list[str], data: bytes) -> str | None:
    """One converter subprocess: stdin→stdout, timeout, output cap;
    None on any failure (missing binary, crash, timeout)."""
    if shutil.which(argv[0]) is None:
        return None
    try:
        p = subprocess.run(argv, input=data,
                           capture_output=True,
                           timeout=CONVERT_TIMEOUT_S)
        if p.returncode != 0:
            return None
        return p.stdout[:MAX_TEXT_BYTES].decode("utf-8", "replace")
    except Exception as e:  # noqa: BLE001 — converter faults are data
        log.warning("converter %s failed: %s", argv[0], e)
        return None


# --- built-in minimal PDF text extraction ------------------------------

_PDF_STREAM_RE = re.compile(
    rb"<<(.*?)>>\s*stream\r?\n(.*?)\r?\nendstream", re.DOTALL)
_PDF_TEXT_OP_RE = re.compile(
    rb"\((?P<s>(?:\\.|[^()\\])*)\)\s*Tj"
    rb"|\[(?P<a>(?:\\.|[^\]\\])*)\]\s*TJ"
    rb"|(?P<nl>T\*|TD|Td|TL)", re.DOTALL)
_PDF_ARRAY_STR_RE = re.compile(rb"\((?:\\.|[^()\\])*\)", re.DOTALL)
_PDF_ESC_RE = re.compile(rb"\\([nrtbf()\\]|[0-7]{1,3})")


def _pdf_unescape(raw: bytes) -> bytes:
    def sub(m):
        e = m.group(1)
        if e.isdigit():
            return bytes([int(e, 8) & 0xFF])
        return {b"n": b"\n", b"r": b"\r", b"t": b"\t", b"b": b"\b",
                b"f": b"\f", b"(": b"(", b")": b")",
                b"\\": b"\\"}.get(e, e)
    return _PDF_ESC_RE.sub(sub, raw)


def pdf_text_builtin(data: bytes) -> str:
    """Show-text operators out of (optionally Flate-compressed) content
    streams — covers straightforward text PDFs; complex encodings
    (CID fonts, hex strings) need the external tool."""
    parts: list[bytes] = []
    for m in _PDF_STREAM_RE.finditer(data):
        head, body = m.group(1), m.group(2)
        if b"FlateDecode" in head:
            try:
                body = zlib.decompress(body)
            except zlib.error:
                continue
        elif b"Filter" in head:
            continue  # other encodings: external tool territory
        for tm in _PDF_TEXT_OP_RE.finditer(body):
            if tm.group("nl") is not None:
                parts.append(b"\n")
            elif tm.group("s") is not None:
                parts.append(_pdf_unescape(tm.group("s")))
            else:
                for sm in _PDF_ARRAY_STR_RE.finditer(tm.group("a")):
                    parts.append(_pdf_unescape(sm.group(0)[1:-1]))
        parts.append(b"\n")
        if sum(map(len, parts)) > MAX_TEXT_BYTES:
            break
    text = b"".join(parts).decode("latin-1", "replace")
    return re.sub(r"[ \t]+", " ", text).strip()


def convert_to_text(data: bytes, content_type: str = "",
                    url: str = "") -> str | None:
    """Binary document bytes → plain text; None = not convertible
    (unknown kind, converter missing AND builtin failed)."""
    kind = kind_of(content_type, url)
    if kind is None:
        return None
    if kind == "pdf":
        out = _run_tool(["pdftotext", "-q", "-", "-"], data)
        if out is None:
            out = pdf_text_builtin(data) or None
        return out
    if kind == "doc":
        # antiword reads a file path only in some builds; catdoc does
        # stdin — try both
        return _run_tool(["catdoc", "-"], data) \
            or _run_tool(["antiword", "-"], data)
    if kind == "ps":
        return _run_tool(["pstotext", "-"], data) \
            or _run_tool(["ps2ascii"], data)
    return None
