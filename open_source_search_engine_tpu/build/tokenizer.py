"""HTML tokenizer → positioned, hashgroup-tagged word stream.

Reference: ``Xml.cpp``/``XmlNode.cpp`` (tag/text node tokenizer),
``Words.cpp`` (word segmentation), ``Pos.cpp`` (word position counting:
~+1 per alnum word, +2 at sentence punctuation), ``Sections.cpp`` (section
tree — we keep a flat sentence model), and the hashgroup assignment done in
``XmlDoc::hashAll`` (``XmlDoc.cpp:28957``): body/title/heading/list/menu/
meta/url tokens are hashed into distinct HASHGROUP_* spaces (``Posdb.h:74``).

Output is columnar: parallel lists of (word, wordpos, hashgroup,
sentence_id) ready for vectorized rank computation and key packing.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from functools import lru_cache
from html.parser import HTMLParser

from ..index.posdb import (
    HASHGROUP_BODY, HASHGROUP_HEADING, HASHGROUP_INLIST, HASHGROUP_INMENU,
    HASHGROUP_INMETATAG, HASHGROUP_INTAG, HASHGROUP_INURL, HASHGROUP_TITLE,
    MAXWORDPOS,
)

_WORD_RE = re.compile(r"\w+", re.UNICODE)
_SENT_SPLIT_RE = re.compile(r"[.!?;:]+")

@lru_cache(maxsize=1 << 18)
def _sect_hash(parent_hash: int, tag: str, ordinal: int) -> int:
    """Section path hash, memoized — page structures repeat across a
    crawl, so the same (parent, tag, ordinal) triples hash constantly."""
    from ..utils import ghash
    return ghash.hash64(f"{parent_hash}:{tag}:{ordinal}")


_HEADING_TAGS = {"h1", "h2", "h3", "h4", "h5", "h6"}
_SKIP_TAGS = {"script", "style", "noscript", "template", "svg"}
_LIST_TAGS = {"li", "dd", "dt"}
_MENU_TAGS = {"nav", "menu"}
_BLOCK_TAGS = {
    "p", "div", "br", "tr", "td", "table", "ul", "ol", "section", "article",
    "header", "footer", "blockquote", "pre", "h1", "h2", "h3", "h4", "h5",
    "h6", "li", "title",
}

#: extra position gap at sentence punctuation (Pos.cpp adds 2)
SENT_GAP = 2
#: extra position gap at block-tag boundaries (section breaks)
BLOCK_GAP = 4


@dataclass
class Token:
    word: str
    wordpos: int
    hashgroup: int
    sentence_id: int
    #: tag-path section hash (Sections.cpp tree, flattened to the
    #: SECOND-level container: wrapper <div>s collapse to their
    #: children, so header/nav/main/footer blocks — the boilerplate
    #: granularity — each get a stable cross-page id). 0 = no section.
    section_id: int = 0


#: tags that open a section scope (containers with matching end tags;
#: void tags like <br>/<meta> never push)
_SECTION_TAGS = {
    "div", "section", "article", "header", "footer", "aside", "nav",
    "menu", "table", "ul", "ol", "dl", "form", "blockquote", "p",
    "li", "tr", "td", "th", "dd", "dt", "pre",
    "h1", "h2", "h3", "h4", "h5", "h6",
}


@dataclass
class TokenizedDoc:
    """The parse product consumed by the indexer (docproc).

    Columnar: parallel lists (word, wordpos, hashgroup, sentence id,
    section id) — the indexer consumes columns directly instead of
    attribute-walking 10⁵ Token objects per batch. ``tokens`` stays as
    a materializing compatibility view."""

    words: list[str] = field(default_factory=list)
    wordpos: list[int] = field(default_factory=list)
    hashgroups: list[int] = field(default_factory=list)
    sentence_ids: list[int] = field(default_factory=list)
    section_ids: list[int] = field(default_factory=list)
    title: str = ""
    meta_description: str = ""
    #: page date from <meta> (article:published_time / date / pubdate),
    #: raw string — build_meta_list parses it into the date field
    meta_date: str = ""
    links: list[tuple[str, str]] = field(default_factory=list)  # (href, anchor text)
    text: str = ""  # visible text, for titlerec/snippets

    @property
    def tokens(self) -> list[Token]:
        return [Token(w, p, h, s, sid) for w, p, h, s, sid in
                zip(self.words, self.wordpos, self.hashgroups,
                    self.sentence_ids, self.section_ids)]


class _HtmlTok(HTMLParser):
    def __init__(self):
        super().__init__(convert_charrefs=True)
        self.doc = TokenizedDoc()
        self._pos = 0
        self._sent = 0
        self._skip_depth = 0
        self._title_depth = 0
        self._heading_depth = 0
        self._list_depth = 0
        self._menu_depth = 0
        self._anchor_href: str | None = None
        self._anchor_words: list[str] = []
        self._text_parts: list[str] = []
        #: section stack: (tag, pathhash, child-ordinal counters)
        self._sect_stack: list[tuple[str, int, dict]] = []
        self._root_ordinals: dict = {}

    def _sect_push(self, tag: str) -> None:
        if self._sect_stack:
            parent_hash = self._sect_stack[-1][1]
            counters = self._sect_stack[-1][2]
        else:
            parent_hash = 0
            counters = self._root_ordinals
        ordinal = counters.get(tag, 0)
        counters[tag] = ordinal + 1
        self._sect_stack.append((tag, _sect_hash(parent_hash, tag,
                                                 ordinal), {}))

    def _sect_pop(self, tag: str) -> None:
        # pop to the nearest matching open tag (HTML is messy; an
        # unmatched end tag pops nothing)
        for i in range(len(self._sect_stack) - 1, -1, -1):
            if self._sect_stack[i][0] == tag:
                del self._sect_stack[i:]
                return

    @property
    def _section_id(self) -> int:
        if not self._sect_stack:
            return 0
        return self._sect_stack[min(1, len(self._sect_stack) - 1)][1]

    # -- tag events --

    def handle_starttag(self, tag, attrs):
        if tag in _SKIP_TAGS:
            self._skip_depth += 1
            return
        if self._skip_depth:  # no bookkeeping inside <noscript>/<svg>/...
            return
        if tag == "title":
            self._title_depth += 1
        elif tag in _HEADING_TAGS:
            self._heading_depth += 1
        elif tag in _LIST_TAGS:
            self._list_depth += 1
        elif tag in _MENU_TAGS:
            self._menu_depth += 1
        elif tag == "a":
            d = dict(attrs)
            self._anchor_href = d.get("href")
            self._anchor_words = []
        elif tag == "meta":
            d = dict(attrs)
            name = (d.get("name") or d.get("property") or "").lower()
            content = d.get("content") or ""
            if content and name in ("article:published_time", "date",
                                    "pubdate", "og:published_time",
                                    "dc.date"):
                if not self.doc.meta_date:
                    self.doc.meta_date = content
            if name in ("description", "keywords") and content:
                if name == "description":
                    self.doc.meta_description = content
                # each meta tag is its own sentence so words from different
                # tags never look adjacent (no cross-tag bigrams)
                self._sent += 1
                self._emit_words(content, HASHGROUP_INMETATAG)
                self._sent += 1
        if tag in _SECTION_TAGS:
            self._sect_push(tag)
        if tag in _BLOCK_TAGS:
            self._pos += BLOCK_GAP
            self._sent += 1

    def handle_endtag(self, tag):
        if tag in _SKIP_TAGS:
            self._skip_depth = max(0, self._skip_depth - 1)
            return
        if self._skip_depth:
            return
        if tag in _SECTION_TAGS:
            self._sect_pop(tag)
        if tag == "title":
            self._title_depth = max(0, self._title_depth - 1)
        elif tag in _HEADING_TAGS:
            self._heading_depth = max(0, self._heading_depth - 1)
        elif tag in _LIST_TAGS:
            self._list_depth = max(0, self._list_depth - 1)
        elif tag in _MENU_TAGS:
            self._menu_depth = max(0, self._menu_depth - 1)
        elif tag == "a" and self._anchor_href is not None:
            self.doc.links.append(
                (self._anchor_href, " ".join(self._anchor_words))
            )
            self._anchor_href = None
            self._anchor_words = []
        if tag in _BLOCK_TAGS:
            self._pos += BLOCK_GAP
            self._sent += 1

    # -- text events --

    def handle_data(self, data):
        if self._skip_depth:
            return
        if self._title_depth:
            self.doc.title += data
            self._emit_words(data, HASHGROUP_TITLE)
            return
        hg = HASHGROUP_BODY
        if self._heading_depth:
            hg = HASHGROUP_HEADING
        elif self._list_depth:
            hg = HASHGROUP_INLIST
        elif self._menu_depth:
            hg = HASHGROUP_INMENU
        if self._anchor_href is not None:
            self._anchor_words.extend(
                w.lower() for w in _WORD_RE.findall(data)
            )
        self._text_parts.append(data)
        self._emit_words(data, hg)

    # -- word emission with Pos.cpp-style position advance --

    def _emit_words(self, data: str, hashgroup: int) -> None:
        sid = self._section_id
        doc = self.doc
        words, wpos = doc.words, doc.wordpos
        hgs, sents, sids = (doc.hashgroups, doc.sentence_ids,
                            doc.section_ids)
        for chunk in _SENT_SPLIT_RE.split(data):
            found = _WORD_RE.findall(chunk)
            if found:
                pos = self._pos
                for w in found:
                    words.append(w.lower())
                    wpos.append(pos if pos < MAXWORDPOS else MAXWORDPOS)
                    pos += 1
                self._pos = pos
                n = len(found)
                hgs.extend([hashgroup] * n)
                sents.extend([self._sent] * n)
                sids.extend([sid] * n)
            self._pos += SENT_GAP
            self._sent += 1
        # undo the trailing split's gap when data had no sentence break
        self._pos -= SENT_GAP
        self._sent -= 1


def _native_tdoc(content: str, url: str | None,
                 is_html: bool) -> TokenizedDoc | None:
    """Native (C++) tokenize+hash+rank path — the host build plane the
    reference keeps in C++ (XmlDoc::hashAll, Words.cpp/Pos.cpp). Fills
    the TokenizedDoc compat lists AND attaches the columnar product as
    ``.native`` so docproc skips per-word Python hashing entirely.
    None = lib unavailable or disabled (OSSE_NATIVE_TOKENIZE=0)."""
    import os
    if os.environ.get("OSSE_NATIVE_TOKENIZE", "1") == "0":
        return None
    from .. import native
    try:
        cols = native.tokenize_native(content, url, is_html)
    except Exception:  # noqa: BLE001 — any native fault → Python path
        return None
    if cols is None:
        return None
    doc = TokenizedDoc(
        words=list(cols.words),
        wordpos=cols.wordpos.tolist(),
        hashgroups=cols.hashgroup.tolist(),
        sentence_ids=cols.sentence.tolist(),
        section_ids=cols.sect.tolist(),
        title=cols.title, meta_description=cols.desc,
        meta_date=cols.date, links=list(cols.links), text=cols.text)
    doc.native = cols
    return doc


def tokenize_html(html: str, url: str | None = None) -> TokenizedDoc:
    """Tokenize an HTML document; URL path words are added to
    HASHGROUP_INURL (reference hashes the url into its own group,
    ``XmlDoc.cpp`` ``hashUrl``). Dispatches to the native C++ core when
    available (bit-identical for ASCII documents; the Python
    HTMLParser path remains the fallback and the reference semantics).
    Input is NFC-normalized first (UCNormalizer.cpp role) so composed
    and decomposed forms index as one term on BOTH paths."""
    from ..utils.unicodenorm import nfc
    html = nfc(html)
    doc = _native_tdoc(html, url, True)
    if doc is not None:
        return doc
    p = _HtmlTok()
    p.feed(html)
    p.close()
    doc = p.doc
    doc.text = re.sub(r"\s+", " ", " ".join(p._text_parts)).strip()
    if url:
        for m in _WORD_RE.finditer(url.lower()):
            doc.words.append(m.group(0))
            doc.wordpos.append(0)
            doc.hashgroups.append(HASHGROUP_INURL)
            doc.sentence_ids.append(0)
            doc.section_ids.append(0)
    return doc


def tokenize_text(text: str, hashgroup: int = HASHGROUP_BODY) -> TokenizedDoc:
    """Tokenize plain text (injection of non-HTML content; reference doc
    converters produce plain text fed through the same path)."""
    from ..utils.unicodenorm import nfc
    text = nfc(text)
    if hashgroup == HASHGROUP_BODY:
        doc = _native_tdoc(text, None, False)
        if doc is not None:
            return doc
    p = _HtmlTok()
    p._emit_words(text, hashgroup)
    doc = p.doc
    doc.text = re.sub(r"\s+", " ", text).strip()
    return doc
