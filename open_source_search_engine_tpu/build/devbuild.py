"""Device ingest plane — the posting sort/dedup/pack pipeline on-chip.

Reference seam (SURVEY §7 hard part (d)): ``RdbDump`` writes sorted
runs, ``RdbMerge``/``Msg5`` N-way-merges them with newest-wins dedup
and +/- annihilation, and ``Msg4``/``addsinprogress.bin`` folds fresh
adds in behind serving. Here those stages are jitted sort/scan
programs over the 18-byte posdb keys, so a full base build is one
device program instead of ~450 s of host NumPy (BENCH_r04):

1. **merge**: the runs' key columns are concatenated host-side (no
   host sort — enforced by the ``host-sort`` osselint rule), split
   into uint32 words, and sorted on-device by (key-sans-delbit asc,
   recency desc) — a stable ``lexsort``, so ties resolve exactly like
   ``rdblite._dedup_newest``. First-of-group survives; surviving
   tombstones annihilate; survivors compact to the front with a
   stable flag sort.
2. **docidx**: distinct docids rank by a second on-device sort (the
   ``np.unique``/``searchsorted`` collapse).
3. **derive**: occurrence ranks (cummax scan), the ``occ < P`` store
   cap, run starts, per-(term,doc) impact bounds, packed payload and
   docc columns, and the term directory — all segmented scans and
   scatters over bucketed static shapes (jitwatch-clean: repeated
   same-bucket batches reuse one trace).

Bit-exactness contract: every output column is bitwise identical to
the host pipeline in ``query/devindex.py`` (``_build_base`` /
``_build_delta``), which stays as the parity oracle and the fallback
path. The float-sensitive part is the impact sum: NumPy's
``add.reduceat`` folds each (term, doc) pair's candidate scores
left-to-right, so the kernel scatters each pair's contributions into
per-position slots and folds them with :data:`MAX_POSITIONS` explicit
adds in the same order (x + 0.0 is exact for x ≥ +0.0, so interleaved
zero contributions don't perturb the sum). Candidate ranking reuses
the monotone bitcast trick: for non-negative f32, descending value
order equals ascending ``~bitcast_u32`` order, dodging any -0.0
float-comparator divergence between XLA and NumPy sorts.

uint64 never touches the device: the 18-byte key splits into five
uint32 words (n0 | n1 lo/hi | n2 lo/hi) and docids ride as 32+6 bit
pairs, so the kernels run identically with and without jax x64.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..index import posdb
from ..utils import devwatch, jitwatch
from ..utils.log import get_logger
from ..utils.stats import g_stats
from ..query import weights
from ..query.packer import IMPACT_SCALE, MAX_POSITIONS, _bucket

log = get_logger("devbuild")

# the ingest plane is a jit entry point of its own (bench BENCH_BUILD
# imports it before any query module) — same opt-in as devindex
jitwatch.maybe_enable()
devwatch.maybe_enable()

#: column bucket quantum — mirrors devindex.COL_QUANTUM (kept numeric
#: here: devindex imports this module, not the other way round)
COL_QUANTUM = 1 << 15

P = MAX_POSITIONS

_U32 = jnp.uint32


def enabled() -> bool:
    """OSSE_DEVBUILD gates the device ingest plane (default ON); the
    host NumPy pipeline stays available as oracle and fallback."""
    return os.environ.get("OSSE_DEVBUILD", "1") != "0"


# ---------------------------------------------------------------------------
# small shared scan/segment helpers (traced inside the programs)
# ---------------------------------------------------------------------------


def _neq_prev(*cols):
    """Boolean "differs from previous row" over parallel columns; row 0
    is always True (the host pipelines' ``np.ones`` + shifted
    compare)."""
    n = cols[0].shape[0]
    diff = jnp.zeros(n - 1, bool)
    for c in cols:
        diff = diff | (c[1:] != c[:-1])
    return jnp.concatenate([jnp.ones((1,), bool), diff])


def _compact(order, *cols):
    return tuple(c[order] for c in cols)


def _count_true(m):
    return jnp.sum(m, dtype=jnp.int32)


def _seg_pos(start_flags, idx):
    """Position of each row within its segment (segments marked by
    ``start_flags``) — the running-max scan both host ``_occ_ranks``
    and the impact ranker use."""
    return idx - lax.cummax(jnp.where(start_flags, idx, 0))


# ---------------------------------------------------------------------------
# field math (bit-identical ports of posdb.unpack / pack_payload /
# _posscore_np / demote_impacts)
# ---------------------------------------------------------------------------


def _posscore(hg, den, spam):
    """BASE·posw² per posting — same table gathers and multiply
    association as ``devindex._posscore_np`` (f32 throughout)."""
    hgw = jnp.asarray(weights.HASH_GROUP_WEIGHTS)[hg]
    denw = jnp.asarray(weights.DENSITY_WEIGHTS)[den]
    is_il = hg == posdb.HASHGROUP_INLINKTEXT
    spamw = jnp.where(is_il,
                      jnp.asarray(weights.LINKER_WEIGHTS)[spam],
                      jnp.asarray(weights.WORD_SPAM_WEIGHTS)[spam])
    posw = hgw * denw * spamw
    return jnp.float32(weights.BASE_SCORE) * posw * posw, is_il


def _demote(a):
    """``packer.demote_impacts`` on device: f32 → f16 at 1/IMPACT_SCALE
    rounded UP (nextafter == bits+1 for positive finite f16, including
    the 0 → smallest-subnormal step)."""
    s = a * jnp.float32(1.0 / IMPACT_SCALE)
    h = s.astype(jnp.float16)
    low = h.astype(jnp.float32) < s
    bits = lax.bitcast_convert_type(h, jnp.uint16) + jnp.uint16(1)
    h = jnp.where(low, lax.bitcast_convert_type(bits, jnp.float16), h)
    return jnp.maximum(h, jnp.float16(
        np.finfo(np.float16).smallest_subnormal))


# ---------------------------------------------------------------------------
# the shared derive stage: sorted (term, doc) rows → base/delta columns
# ---------------------------------------------------------------------------


def _derive(tid_lo, tid_hi, docidx, hg, den, spam, wp, sr, lg, n):
    """Everything downstream of the sort, shared by base and delta:
    occurrence ranks, the store cap, run boundaries, the term
    directory, packed payload/docc and the exact impact bounds.

    Inputs are padded to the working bucket; ``n`` (traced scalar)
    marks the valid prefix. Rows must already be sorted by
    (termid, docidx[, wordpos]) — both callers' sorts guarantee it.
    Output columns are zero beyond their own counters (matching the
    host ``_pad_col`` convention), so callers can slice/pad them
    straight into device column buffers."""
    N = tid_lo.shape[0]
    idx = jnp.arange(N, dtype=jnp.int32)
    valid = idx < n

    # --- pre-cap boundaries: term change + (term, doc) pair change ---
    tch0 = _neq_prev(tid_lo, tid_hi) & valid
    np0 = (_neq_prev(tid_lo, tid_hi) | _neq_prev(docidx)) & valid
    occ = _seg_pos(np0, idx)

    # df BEFORE the store cap (the Msg36 termfreq precompute): distinct
    # (term, doc) pairs per term — integer scatter-add, deterministic
    trank0 = jnp.cumsum(tch0.astype(jnp.int32)) - 1
    n_terms = _count_true(tch0)
    df = jnp.zeros(N, jnp.int32).at[
        jnp.where(valid, trank0, N)].add(np0.astype(jnp.int32),
                                         mode="drop")
    d_tid_lo = jnp.zeros(N, _U32).at[
        jnp.where(tch0, trank0, N)].set(tid_lo, mode="drop")
    d_tid_hi = jnp.zeros(N, _U32).at[
        jnp.where(tch0, trank0, N)].set(tid_hi, mode="drop")

    # --- store cap: scoring consumes ≤ P positions per pair ---
    keep = (occ < P) & valid
    oc = jnp.argsort(~keep, stable=True)
    (tid_lo, tid_hi, docidx, hg, den, spam, wp, sr, lg,
     occ) = _compact(oc, tid_lo, tid_hi, docidx, hg, den, spam, wp,
                     sr, lg, occ)
    nk = _count_true(keep)
    valid = idx < nk

    payload = jnp.where(
        valid,
        wp | (hg << 18) | (den << 22) | (spam << 27), _U32(0))
    docc = jnp.where(
        valid, (docidx.astype(_U32) << 4) | occ.astype(_U32), _U32(0))

    # --- doc-level runs: one entry per (term, doc) pair ---
    newpair = (_neq_prev(tid_lo, tid_hi) | _neq_prev(docidx)) & valid
    pair_id = jnp.cumsum(newpair.astype(jnp.int32)) - 1
    n_pairs = _count_true(newpair)
    pair_tgt = jnp.where(newpair, pair_id, N)
    runstart = jnp.zeros(N, jnp.int32).at[pair_tgt].set(idx, mode="drop")
    doc_col = jnp.zeros(N, jnp.int32).at[pair_tgt].set(
        docidx, mode="drop")
    count = jnp.zeros(N, jnp.int32).at[
        jnp.where(valid, pair_id, N)].add(1, mode="drop")
    cnt_col = jnp.minimum(count, P).astype(jnp.uint8)

    tch = _neq_prev(tid_lo, tid_hi) & valid
    trank = jnp.cumsum(tch.astype(jnp.int32)) - 1
    term_tgt = jnp.where(tch, trank, N)
    # pair index at a term start == searchsorted(runstart, tstart)
    dir_dstart = jnp.zeros(N, jnp.int32).at[term_tgt].set(
        pair_id, mode="drop")
    dir_pstart = jnp.zeros(N, jnp.int32).at[term_tgt].set(
        idx, mode="drop")

    # --- exact impacts (the _impacts_np candidate-rank-sum, on-chip) --
    ps, il = _posscore(hg.astype(jnp.int32), den.astype(jnp.int32),
                       spam.astype(jnp.int32))
    mhg = jnp.asarray(weights.MAPPED_HASHGROUP)[hg.astype(jnp.int32)]
    pid_key = jnp.where(valid, pair_id, jnp.int32(N))
    o = jnp.lexsort((mhg, pid_key))
    ps_o, il_o, mh_o, pid_o, valid_o = _compact(
        o, ps, il, mhg, pid_key, valid)
    gch = (_neq_prev(pid_o) | _neq_prev(mh_o)) & valid_o
    gid = jnp.cumsum(gch.astype(jnp.int32)) - 1
    gmax = jnp.zeros(N, jnp.float32).at[
        jnp.where(valid_o, gid, N)].max(ps_o, mode="drop")
    cand = (il_o | gch) & valid_o
    cval = jnp.where(il_o, ps_o, gmax[jnp.where(valid_o, gid, 0)])
    pch = _neq_prev(pid_o) & valid_o
    # rank candidates within each pair, descending cval: stable sort by
    # (pair, non-candidate-last, ~bitcast(cval)) — monotone for f32 ≥ 0
    ckey = ~lax.bitcast_convert_type(cval, _U32)
    o3 = jnp.lexsort((ckey, (~cand).astype(_U32), pid_o))
    seg = _neq_prev(pid_o[o3])
    rank = jnp.zeros(N, jnp.int32).at[o3].set(_seg_pos(seg, idx))
    contrib = jnp.where(cand & (rank < weights.MAX_TOP), cval,
                        jnp.float32(0.0))
    # pair sums folded LEFT-TO-RIGHT like np.add.reduceat: position-q
    # rows scatter to unique pair slots, then P sequential adds
    q = _seg_pos(pch, idx)
    acc = jnp.zeros(N, jnp.float32)
    for j in range(P):
        sel = (q == j) & valid_o
        acc = acc + jnp.zeros(N, jnp.float32).at[
            jnp.where(sel, pid_o, N)].set(contrib, mode="drop")
    pvalid = idx < n_pairs
    imp32 = jnp.where(pvalid, jnp.maximum(acc, jnp.float32(1e-30)),
                      jnp.float32(0.0))
    imp16 = jnp.where(pvalid, _demote(imp32), jnp.float16(0.0))

    return dict(
        payload=payload, docc=docc, pocc=jnp.where(
            valid, occ, jnp.uint32(0)).astype(jnp.uint8),
        docidx=jnp.where(valid, docidx, 0),
        siterank=jnp.where(valid, sr, _U32(0)).astype(jnp.uint8),
        langid=jnp.where(valid, lg, _U32(0)).astype(jnp.uint8),
        doc_col=doc_col, imp32=imp32, imp16=imp16,
        rs=jnp.where(pvalid, runstart, 0),
        cnt=jnp.where(pvalid, cnt_col, jnp.uint8(0)),
        dir_tid_lo=d_tid_lo, dir_tid_hi=d_tid_hi, df=df,
        dir_dstart=dir_dstart, dir_pstart=dir_pstart,
        counters=jnp.stack([nk, n_pairs, n_terms]))


# ---------------------------------------------------------------------------
# base program: N-way run merge + annihilation + docidx + derive
# ---------------------------------------------------------------------------


@jax.jit
def _base_program(n0, n1lo, n1hi, n2lo, n2hi, rec, n):
    """Full base build from concatenated run key words. One traced
    program per input bucket; ``n``/``rec`` ride as traced operands so
    corpus size changes inside a bucket never retrace."""
    N = n0.shape[0]
    idx = jnp.arange(N, dtype=jnp.int32)
    valid = idx < n

    # --- RdbMerge/Msg5: newest-wins dedup + tombstone annihilation ---
    n0c = n0 & ~_U32(1)
    negrec = _U32(0x7FFFFFFF) - rec
    order = jnp.lexsort((negrec, n0c, n1lo, n1hi, n2lo, n2hi,
                         (~valid).astype(_U32)))
    n0_s, n0c_s, l1, h1, l2, h2, valid_s = _compact(
        order, n0, n0c, n1lo, n1hi, n2lo, n2hi, valid)
    first = _neq_prev(n0c_s, l1, h1, l2, h2)
    keep = first & (n0_s & _U32(1)).astype(bool) & valid_s
    oc = jnp.argsort(~keep, stable=True)
    n0_s, l1, h1, l2, h2 = _compact(oc, n0_s, l1, h1, l2, h2)
    n_merged = _count_true(keep)
    valid = idx < n_merged

    # --- posdb.unpack, bit-split (no uint64 on device) ---
    tid_lo = (h2 << 16) | (l2 >> 16)
    tid_hi = h2 >> 16
    d_lo = ((l2 & _U32(0x3FF)) << 22) | (h1 >> 10)   # docid bits 0..31
    d_hi = (l2 >> 10) & _U32(0x3F)                   # docid bits 32..37
    sr = (h1 >> 5) & _U32(0xF)
    lg = (h1 & _U32(0x1F)) | (((n0_s >> 3) & _U32(1)) << 5)
    wp = l1 >> 14
    hg = (l1 >> 10) & _U32(0xF)
    spam = (l1 >> 6) & _U32(0xF)
    den = (n0_s >> 11) & _U32(0x1F)

    # --- docidx: rank of each distinct docid (np.unique collapse) ---
    od = jnp.lexsort((d_lo, d_hi, (~valid).astype(_U32)))
    dl_s, dh_s, v_s = _compact(od, d_lo, d_hi, valid)
    newdoc = _neq_prev(dl_s, dh_s) & v_s
    docrank = jnp.cumsum(newdoc.astype(jnp.int32)) - 1
    n_docs = _count_true(newdoc)
    docidx = jnp.zeros(N, jnp.int32).at[od].set(docrank)
    docidx = jnp.where(valid, docidx, 0)
    doc_tgt = jnp.where(newdoc, docrank, N)
    bd_lo = jnp.zeros(N, _U32).at[doc_tgt].set(dl_s, mode="drop")
    bd_hi = jnp.zeros(N, _U32).at[doc_tgt].set(dh_s, mode="drop")

    out = _derive(tid_lo, tid_hi, docidx, hg, den, spam, wp, sr, lg,
                  n_merged)
    out.update(bd_lo=bd_lo, bd_hi=bd_hi,
               base_counters=jnp.stack([n_merged, n_docs]))
    return out


# ---------------------------------------------------------------------------
# delta program: sort the memtable positives, then the shared derive
# ---------------------------------------------------------------------------


@jax.jit
def _delta_program(tid_lo, tid_hi, docidx, hg, den, spam, wp, sr, lg, m):
    """Delta fold: the memtable positives sorted by (termid, docidx,
    wordpos) — new docs' indexes aren't docid-monotonic, same key as
    the host path — then the shared derive stage."""
    N = tid_lo.shape[0]
    valid = jnp.arange(N, dtype=jnp.int32) < m
    o = jnp.lexsort((wp, docidx, tid_lo, tid_hi,
                     (~valid).astype(_U32)))
    tid_lo, tid_hi, docidx, hg, den, spam, wp, sr, lg = _compact(
        o, tid_lo, tid_hi, docidx, hg, den, spam, wp, sr, lg)
    return _derive(tid_lo, tid_hi, docidx, hg, den, spam, wp, sr, lg, m)


# ---------------------------------------------------------------------------
# doc-meta and cube-row kernels (shared by base + delta paths)
# ---------------------------------------------------------------------------


@jax.jit
def _doc_meta(sr_tab, dl_tab, docidx, sr_rows, lg_rows, n):
    """First-posting-per-doc siterank/langid (the reference
    getSiteRank(miniMergedList[0]) role): segment-min picks each doc's
    first capped row; docs with no rows keep their table entry."""
    N = docidx.shape[0]
    D = sr_tab.shape[0]
    idx = jnp.arange(N, dtype=jnp.int32)
    valid = idx < n
    firstrow = jnp.full(D, N, jnp.int32).at[
        jnp.where(valid, docidx, D)].min(idx, mode="drop")
    has = firstrow < N
    g = jnp.clip(firstrow, 0, N - 1)
    return (jnp.where(has, sr_rows[g], sr_tab),
            jnp.where(has, lg_rows[g], dl_tab))


@partial(jax.jit, static_argnames=("D", "n_positions", "total",
                                   "n_lanes"))
def _cube_rows(payload, docc, starts, cum, D: int, n_positions: int,
               total: int, n_lanes: int):
    """Materialized [Vc, P, D] cube rows by one flattened scatter. The
    scatter destination is derived from the resident docc column
    (docidx<<4 | occ), so the host ships only the per-slot (start,
    cumlen) descriptors — no posting-sized upload on either build
    path."""
    R = starts.shape[0]
    lane = jnp.arange(n_lanes, dtype=jnp.int32)
    row = jnp.clip(jnp.searchsorted(cum, lane, side="right") - 1,
                   0, R - 1).astype(jnp.int32)
    src = jnp.clip(starts[row] + lane - cum[row], 0,
                   payload.shape[0] - 1)
    dv = docc[src]
    occ = (dv & _U32(0xF)).astype(jnp.int32)
    dxi = (dv >> 4).astype(jnp.int32)
    dst = jnp.where(lane < cum[-1],
                    (row * n_positions + occ) * D + dxi, total)
    return jnp.zeros((total,), _U32).at[dst].set(payload[src],
                                                 mode="drop")


# ---------------------------------------------------------------------------
# host-facing results
# ---------------------------------------------------------------------------


@dataclass
class DeviceBuild:
    """One derive-stage result: small directory tables fetched to host
    (exact host-pipeline dtypes), heavy columns still in HBM."""

    n: int                    # stored postings (post store-cap)
    n_pairs: int              # (term, doc) pairs
    dir_termids: np.ndarray   # uint64 [T]
    df: np.ndarray            # int64 [T] distinct-doc counts (pre-cap)
    dir_dstart: np.ndarray    # int64 [T+1]
    dir_pstart: np.ndarray    # int64 [T+1]
    cols: dict                # device columns, padded to the bucket
    # base-only (None for delta folds):
    base_docids: np.ndarray | None = None   # uint64 [Db]
    h_doc_col: np.ndarray | None = None     # int32 [n_pairs]


def _u64(lo: np.ndarray, hi: np.ndarray) -> np.ndarray:
    return (hi.astype(np.uint64) << np.uint64(32)) | lo.astype(np.uint64)


def _bslice(col, count: int, quantum: int):
    """Device-side slice to the bucketed length before a host fetch —
    bucketed shapes keep the eager-slice compile set bounded while
    shipping ~count elements instead of the whole working bucket."""
    return col[:min(_bucket(max(count, 1), quantum), col.shape[0])]


def fit(col, size: int):
    """Fit a derived device column to an exact tail capacity (columns
    are zero past their counters, so both directions preserve the
    host ``_pad_col`` zero-padding convention)."""
    n = col.shape[0]
    if n >= size:
        return col[:size]
    return jnp.concatenate([col, jnp.zeros(size - n, col.dtype)])


def _fetch_dir(out, counters, quantum: int):
    """Directory tables + counters → host, in host-pipeline dtypes."""
    nk, n_pairs, n_terms = (int(x) for x in counters)
    tid_lo, tid_hi, df, dd, dp = (np.asarray(_bslice(out[k], n_terms,
                                                     quantum))
                                  for k in ("dir_tid_lo", "dir_tid_hi",
                                            "df", "dir_dstart",
                                            "dir_pstart"))
    return nk, n_pairs, dict(
        dir_termids=_u64(tid_lo, tid_hi)[:n_terms],
        df=df[:n_terms].astype(np.int64),
        dir_dstart=np.r_[dd[:n_terms], n_pairs].astype(np.int64),
        dir_pstart=np.r_[dp[:n_terms], nk].astype(np.int64))


def build_base(run_keys: list[np.ndarray], put,
               quantum: int = COL_QUANTUM) -> DeviceBuild | None:
    """Merge + derive the base columns from the Rdb runs' key arrays
    (oldest → newest, the merge_batches recency order). Returns None
    when the merged base is empty (caller keeps its empty-branch
    handling). ``put`` is the caller's device-pinning ``device_put``."""
    total = sum(len(k) for k in run_keys)
    if total == 0:
        return None
    N = _bucket(total, quantum)

    # plain concatenate + bit-split staging (the only host work; the
    # host-sort lint rule keeps every ordering operation on-device)
    n0 = np.concatenate([k["n0"] for k in run_keys]).astype(np.uint32)
    n1 = np.concatenate([k["n1"] for k in run_keys])
    n2 = np.concatenate([k["n2"] for k in run_keys])
    rec = np.concatenate([np.full(len(k), i, np.uint32)
                          for i, k in enumerate(run_keys)])

    def stage(a):
        return put(np.concatenate(
            [a.astype(np.uint32, copy=False),
             np.zeros(N - total, np.uint32)]))

    out = _base_program(
        stage(n0),
        stage(n1 & np.uint64(0xFFFFFFFF)), stage(n1 >> np.uint64(32)),
        stage(n2 & np.uint64(0xFFFFFFFF)), stage(n2 >> np.uint64(32)),
        stage(rec), np.int32(total))
    n_merged, n_docs = (int(x) for x in out["base_counters"])
    if n_merged == 0:
        return None
    nk, n_pairs, dirs = _fetch_dir(out, out["counters"], quantum)
    bd_lo = np.asarray(_bslice(out["bd_lo"], n_docs, quantum))
    bd_hi = np.asarray(_bslice(out["bd_hi"], n_docs, quantum))
    h_doc = np.asarray(_bslice(out["doc_col"], n_pairs, quantum))
    g_stats.count("build.device_base")
    if devwatch.enabled():
        # transient ingest staging in the HBM ledger — the consumer
        # (devindex refresh) drops the slice once fit() folded the
        # columns into the resident plane
        devwatch.note_columns("(ingest)", "build", out)
    return DeviceBuild(
        n=nk, n_pairs=n_pairs, dir_termids=dirs["dir_termids"],
        df=dirs["df"], dir_dstart=dirs["dir_dstart"],
        dir_pstart=dirs["dir_pstart"], cols=out,
        base_docids=_u64(bd_lo, bd_hi)[:n_docs],
        h_doc_col=h_doc[:n_pairs].copy())


def build_delta(fp_: dict, docidx: np.ndarray, put,
                quantum: int = COL_QUANTUM) -> DeviceBuild | None:
    """Sort + derive the delta tail from the memtable positives
    (fields unpacked, docidx already assigned against the base docid
    directory — the cheap O(memtable) host prep stays on host)."""
    m = len(docidx)
    if m == 0:
        return None
    N = _bucket(m, quantum)

    def stage(a, dt=np.uint32):
        return put(np.concatenate(
            [a.astype(dt, copy=False), np.zeros(N - m, dt)]))

    t = fp_["termid"]
    out = _delta_program(
        stage(t & np.uint64(0xFFFFFFFF)), stage(t >> np.uint64(32)),
        stage(docidx, np.int32), stage(fp_["hashgroup"]),
        stage(fp_["densityrank"]), stage(fp_["wordspamrank"]),
        stage(fp_["wordpos"]), stage(fp_["siterank"]),
        stage(fp_["langid"]), np.int32(m))
    nk, n_pairs, dirs = _fetch_dir(out, out["counters"], quantum)
    g_stats.count("build.device_delta")
    if devwatch.enabled():
        devwatch.note_columns("(ingest)", "build", out)
    return DeviceBuild(
        n=nk, n_pairs=n_pairs, dir_termids=dirs["dir_termids"],
        df=dirs["df"], dir_dstart=dirs["dir_dstart"],
        dir_pstart=dirs["dir_pstart"], cols=out)


def doc_meta(sr_tab, dl_tab, dv: DeviceBuild):
    """Apply first-posting-per-doc siterank/langid onto [D_cap] tables
    (zeros for a base build, the resident tables for a delta fold)."""
    return _doc_meta(sr_tab, dl_tab, dv.cols["docidx"],
                     dv.cols["siterank"], dv.cols["langid"],
                     np.int32(dv.n))


def offset_runstarts(dv: DeviceBuild, offset: int, size: int):
    """Delta run starts rebased onto the combined column ([Nb, Nb+n2))
    with the pad rows kept zero — the host rs2 = Nb + runstart2 line."""
    rs = fit(dv.cols["rs"], size)
    live = jnp.arange(size, dtype=jnp.int32) < np.int32(dv.n_pairs)
    return jnp.where(live, rs + np.int32(offset), 0)
