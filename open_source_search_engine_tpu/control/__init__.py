"""Control plane: process lifecycle, autosave, liveness, parm sync.

The reference's L7 (SURVEY §2.8): ``Process.cpp`` orderly save/shutdown +
autosave, ``PingServer`` heartbeats and dead-host handling, Parms 0x3f
broadcast. Host-side supervision around the data/query planes.
"""

from .process import Heartbeat, Process

__all__ = ["Heartbeat", "Process"]
