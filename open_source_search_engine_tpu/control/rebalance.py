"""Rebalance + Repair — topology growth and index rebuilds.

Reference:

* ``Rebalance.h:13`` (``gb scale``, ``main.cpp:2356``): after changing
  the shard count in hosts.conf, every Rdb is rescanned and records
  whose owning shard changed are Msg1'd to the new owner and deleted
  locally. Without this, changing ``n_shards`` silently mis-routes every
  existing record (the round-2 verdict's exact words).
* ``Repair.h:20-44`` (``g_repairMode``): walk titledb and rebuild chosen
  Rdbs into secondary instances, then swap — the recovery path for a
  corrupted/wiped index or a scoring/tokenizer change, without
  re-crawling.

Ours are offline, immutable-run-friendly variants: ``rebalance`` scans
each source shard's Rdbs ONCE and routes raw records by the same
key→shard maps the build plane uses (posdb by docid — with the
termid-sharded checksum exception — titledb/clusterdb by docid, linkdb
by the linkee sitehash embedded in the key), writing a fresh shard grid;
``repair`` wipes the derived Rdbs and reindexes every titlerec through
the normal document pipeline (titlerecs store the original content,
exactly the reference's titledb-walk rebuild).
"""

from __future__ import annotations

import re
from pathlib import Path

import numpy as np

from ..index import posdb, titledb
from ..index.collection import Collection
from ..spider import linkdb as linkdb_mod
from ..utils.log import get_logger

log = get_logger("rebalance")

_WORD_RE = re.compile(r"\w+", re.UNICODE)


def _route_batch(batch, shard_of, n_shards: int, add_fns) -> int:
    """Scatter one Rdb's merged records to the new owners."""
    if not len(batch):
        return 0
    shards = shard_of(batch.keys, n_shards)
    for s in range(n_shards):
        m = shards == s
        if not m.any():
            continue
        idx = np.nonzero(m)[0]
        keys = batch.keys[idx]
        blobs = [batch.payload(int(i)) for i in idx] if batch.has_data \
            else None
        for add in add_fns(s):
            add(keys, blobs)
    return len(batch)


def rebalance(name: str, src_dir, dst_dir: str | Path,
              old_n_shards: int, new_n_shards: int,
              n_replicas: int = 1):
    """Re-shard a collection grid: ``src_dir`` (old_n shards, a path or
    a live ShardedCollection) → ``dst_dir`` (new_n shards × replicas).
    Returns the new ShardedCollection (saved)."""
    from ..parallel.sharded import ShardedCollection

    src = (src_dir if isinstance(src_dir, ShardedCollection)
           else ShardedCollection(name, src_dir, n_shards=old_n_shards))
    dst = ShardedCollection(name, dst_dir, n_shards=new_n_shards,
                            n_replicas=n_replicas)
    moved = 0
    for old_shard in src.grid:
        c = old_shard[0]  # replica 0 holds the full shard state
        moved += _route_batch(
            c.posdb.get_all(), posdb.shard_of_keys, new_n_shards,
            lambda s: [r.posdb.add for r in dst.replicas_of(s)])
        tb = c.titledb.get_all()
        moved += _route_batch(
            tb,
            lambda k, n: posdb.shard_of_docid(
                titledb.unpack_key(k)["docid"], n),
            new_n_shards,
            lambda s: [r.titledb.add for r in dst.replicas_of(s)])
        moved += _route_batch(
            c.clusterdb.get_all(),
            lambda k, n: posdb.shard_of_docid(
                titledb.unpack_key(k)["docid"], n),
            new_n_shards,
            lambda s: [r.clusterdb.add for r in dst.replicas_of(s)])
        moved += _route_batch(
            c.linkdb.rdb.get_all(), linkdb_mod.shard_of_keys,
            new_n_shards,
            lambda s: [r.linkdb.rdb.add for r in dst.replicas_of(s)])
        # per-shard doc counts + speller dictionaries follow the
        # titledb records (the speller is per-shard persisted state —
        # "did you mean" must survive the re-shard)
        docs = titledb.unpack_key(tb.keys)["docid"] if len(tb) else \
            np.empty(0, np.uint64)
        owners = posdb.shard_of_docid(docs, new_n_shards)
        for s in range(new_n_shards):
            m = owners == s
            n = int(m.sum())
            for r in dst.replicas_of(s):
                r.num_docs += n
            if n:
                for i in np.nonzero(m)[0]:
                    rec = titledb.read_title_rec(tb.payload(int(i)))
                    ws = _WORD_RE.findall(
                        (rec.get("title", "") + " "
                         + rec.get("text", "")).lower())
                    for r in dst.replicas_of(s):
                        r.speller.add_doc_words(ws)
    for row in dst.grid:
        for c in row:
            c.save()
    log.info("rebalance %s: %d→%d shards, %d records routed",
             name, old_n_shards, new_n_shards, moved)
    return dst


def repair(coll: Collection) -> int:
    """Rebuild posdb/clusterdb/linkdb (and the speller dictionary) from
    titledb — the Repair.h titledb-walk rebuild for one collection.
    Returns the number of documents reindexed.

    Two passes, both with anchor propagation off: the first refills
    linkdb edges (and everything else) from scratch, the second
    reindexes with the full link graph present so inlink anchor-text
    postings and sitereanks match a from-scratch build — without the
    O(docs × inlinks) refresh cascade per-doc propagation would cost.
    Titlerecs stream lazily (the recovery tool must not need corpus-
    sized RAM)."""
    from ..build import docproc

    tb = coll.titledb.get_all()
    coll.posdb.wipe()
    coll.clusterdb.wipe()
    coll.linkdb.rdb.wipe()
    coll.titlerec_cache.clear()
    if hasattr(coll, "_device_index"):
        del coll._device_index

    def _reindex_pass():
        n = 0
        for i in range(len(tb)):
            blob = tb.payload(i)
            if not blob:
                continue
            rec = titledb.read_title_rec(blob)
            docproc.index_document(
                coll, rec["url"], rec.get("content", rec.get("text", "")),
                is_html=rec.get("is_html", True),
                siterank=rec.get("siterank", 0),
                langid=rec.get("langid"), propagate=False)
            n += 1
        return n

    _reindex_pass()
    n = _reindex_pass()
    coll.save()
    log.info("repair %s: %d docs reindexed from titledb", coll.name, n)
    return n
