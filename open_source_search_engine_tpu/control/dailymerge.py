"""DailyMerge — scheduled quiet-hours full merges.

Reference: ``DailyMerge.h:11`` — once a day, inside a configured quiet
window, every Rdb gets a full (forced) merge so daytime serving reads
one file per Rdb instead of a deepening stack. The ``merge_quiet_hours``
parm ("HH-HH", e.g. "2-5"; empty = disabled) carries the window, like
the reference's daily-merge start/end conf.
"""

from __future__ import annotations

import threading
import time
from datetime import datetime

from ..utils import threads
from ..utils.log import get_logger

log = get_logger("dailymerge")


def parse_window(spec: str) -> tuple[int, int] | None:
    """"2-5" → (2, 5); None when disabled/malformed. A wrapped window
    ("22-4") is allowed — it spans midnight."""
    try:
        lo, hi = spec.strip().split("-")
        lo, hi = int(lo), int(hi)
        if 0 <= lo <= 23 and 0 <= hi <= 23:
            return lo, hi
    except (ValueError, AttributeError):
        pass
    return None


def in_window(hour: int, window: tuple[int, int]) -> bool:
    lo, hi = window
    if lo <= hi:
        return lo <= hour < hi
    return hour >= lo or hour < hi  # spans midnight


class DailyMerge:
    """One merge sweep per day inside the quiet window."""

    def __init__(self, colls, conf, check_interval_s: float = 60.0):
        """``colls``: iterable (or callable returning one) of objects
        with ``rdbs()``; ``conf`` supplies ``merge_quiet_hours``."""
        self._colls = colls
        self._conf = conf
        self._interval = check_interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.last_merge_day: str | None = None
        self.merges = 0

    def _targets(self):
        c = self._colls
        return c() if callable(c) else c

    def tick(self, now: datetime | None = None) -> bool:
        """One scheduler check; returns True when a sweep ran."""
        window = parse_window(
            getattr(self._conf, "merge_quiet_hours", "") or "")
        if window is None:
            return False
        now = now or datetime.now()
        day = now.strftime("%Y-%m-%d")
        if self.last_merge_day == day or not in_window(now.hour, window):
            return False
        n = 0
        for coll in self._targets():
            for name, rdb in coll.rdbs().items():
                try:
                    before = len(rdb.runs)
                    rdb.attempt_merge(force=True)
                    if len(rdb.runs) < before:
                        n += 1
                except Exception:  # noqa: BLE001 — keep sweeping
                    log.exception("daily merge failed for %s", name)
        self.last_merge_day = day
        self.merges += 1
        log.info("daily merge sweep done (%d rdbs merged)", n)
        return True

    def start(self) -> None:
        def loop():
            while not self._stop.wait(self._interval):
                self.tick()
        self._thread = threads.spawn("dailymerge", loop)

    def stop(self) -> None:
        self._stop.set()
