"""Process supervisor — autosave, orderly shutdown, crash-safe state.

Reference: ``Process.cpp/h`` — autosave every N minutes
(``Process.cpp:1299-1331`` → ``saveRdbTrees``/``saveRdbMaps``
``Process.cpp:1444-1449``), orderly save+shutdown on request, urgent save
on fatal signals (``Process.cpp:1595-1612``); plus Msg4's
``addsinprogress.dat`` crash journal (``Msg4.cpp:86,115``) — here the
memtable ``saved`` runs serve the same role: every registered savable's
in-RAM state persists so a clean restart is lossless.
"""

from __future__ import annotations

import signal
import threading
import time

from ..utils import threads
from ..utils.log import get_logger

log = get_logger("process")


class Process:
    """Owns savable components; runs the autosave clock; handles signals."""

    def __init__(self, autosave_minutes: float = 5.0):
        self._savables: list = []       # objects with .save()
        self._closers: list = []        # extra shutdown callbacks
        self.autosave_minutes = autosave_minutes
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.saves = 0

    @property
    def stopping(self) -> bool:
        """True once shutdown (or a signal) was requested."""
        return self._stop.is_set()

    def register(self, savable) -> None:
        """Register anything with a .save() (collections, spider state…)."""
        self._savables.append(savable)

    def on_shutdown(self, fn) -> None:
        self._closers.append(fn)

    def save_all(self) -> None:
        """The 'all just save' admin op (``gb save`` broadcast,
        main.cpp:2392)."""
        for s in self._savables:
            try:
                s.save()
            except Exception as e:  # noqa: BLE001 — save what we can
                log.warning("save failed for %r: %s", s, e)
        self.saves += 1

    # --- autosave clock (Process.cpp:1299 sleep callback) ---

    def start_autosave(self) -> None:
        if self._thread is not None:
            return

        def loop():
            while not self._stop.wait(self.autosave_minutes * 60.0):
                log.info("autosave")
                self.save_all()

        self._thread = threads.spawn("autosave", loop)

    # --- orderly shutdown (Process::shutdown) ---

    def shutdown(self) -> None:
        self._stop.set()
        self.save_all()
        for fn in self._closers:
            try:
                fn()
            except Exception as e:  # noqa: BLE001
                log.warning("closer failed: %s", e)
        log.info("shutdown complete (%d saves)", self.saves)

    def install_signal_handlers(self) -> None:
        """Urgent save on SIGTERM/SIGINT (Process.cpp:1595 does it for
        SEGV/HUP too; Python can't catch SEGV meaningfully)."""
        def handler(signum, frame):
            log.info("signal %d: saving and exiting", signum)
            self.shutdown()
            raise SystemExit(0)

        signal.signal(signal.SIGTERM, handler)
        signal.signal(signal.SIGINT, handler)


def make_rpc_probe(conf, transport=None, timeout_s: float = 1.5):
    """A :class:`Heartbeat` probe that pings real node processes:
    ``probe(shard) -> bool`` hits ``/rpc/ping`` on each of the shard's
    twins over the pooled transport and reports the shard alive when
    ANY twin answers — the cross-process PingServer, for fleets spawned
    by ``parallel.fleet.FleetManager`` from the same hosts.conf map."""
    def probe(shard: int) -> bool:
        # runtime import: control/ must not pull the transport stack
        # (and its jax-adjacent deps) at module import time
        from ..parallel import transport as transport_mod

        t = transport or transport_mod.g_transport
        return any(t.probe(addr, timeout=timeout_s) is not None
                   for addr in conf.addresses[shard])

    return probe


class Heartbeat:
    """Shard liveness prober (PingServer: ``sendPingsToAll``
    ``PingServer.h:61`` + dead marking feeding Multicast failover).

    In-process shards don't die independently, so the probe is
    pluggable: multi-host deployments hand ``probe(shard_id) -> bool``
    a real RPC ping (:func:`make_rpc_probe` over a hosts.conf map);
    tests flip it to simulate failures. Dead shards are skipped by the
    query path (degraded serving) until they pass a probe again.
    """

    def __init__(self, hostmap, probe=None, interval_s: float = 2.0):
        self.hostmap = hostmap
        self.probe = probe or (lambda shard: True)
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def check_once(self) -> None:
        for s in range(self.hostmap.n_shards):
            alive = False
            try:
                alive = bool(self.probe(s))
            except Exception:  # noqa: BLE001 — probe failure = dead
                alive = False
            if alive:
                self.hostmap.mark_alive(s)
            else:
                if self.hostmap.alive[s]:
                    log.warning("shard %d marked dead", s)
                self.hostmap.mark_dead(s)

    def start(self) -> None:
        def loop():
            while not self._stop.wait(self.interval_s):
                self.check_once()

        self._thread = threads.spawn("heartbeat", loop)

    def stop(self) -> None:
        self._stop.set()
