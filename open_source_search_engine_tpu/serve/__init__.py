"""HTTP API / UI plane (SURVEY §2 L6): the reference's HttpServer + Pages
routing + PageResults/PageGet/PageAddUrl endpoints, host-side.

The device mesh never sees HTTP: requests terminate here, queries cross
into the jitted query plane, results render as JSON/XML/CSV/HTML.
"""

from .server import SearchHTTPServer, serve

__all__ = ["SearchHTTPServer", "serve"]
