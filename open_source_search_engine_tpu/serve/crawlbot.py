"""Crawlbot — the REST bulk-crawl API.

Reference: ``PageCrawlBot.cpp`` (~5k LoC, the diffbot-era "crawlbot"
API): REST calls create named crawl jobs (each backed by its own
collection + url filters), report status, pause/resume, and expose the
crawled corpus. Endpoints here (admin-gated like injection):

* ``/crawlbot?name=X&seeds=url1,url2&maxpages=N&maxhops=H`` — create
  and start a job: a dedicated collection ``crawl_X`` with a durable
  per-IP frontier, crawled by a background loop.
* ``/crawlbot?name=X`` — job status (indexed/fetched/errors/frontier).
* ``/crawlbot?name=X&action=pause|resume|delete``.
* ``/crawlbot`` — list jobs.

Searches over a job's corpus use the normal ``/search?c=crawl_X``.
"""

from __future__ import annotations

import shutil
import threading
import time
from dataclasses import dataclass, field

from ..spider.fetcher import Fetcher
from ..spider.loop import SpiderLoop
from ..spider.scheduler import UrlFilterRule
from ..spider.spiderdb import DurableSpiderScheduler
from ..utils import threads
from ..utils.log import get_logger

log = get_logger("crawlbot")


@dataclass
class CrawlJob:
    name: str
    loop: SpiderLoop
    max_pages: int
    thread: threading.Thread | None = None
    paused: bool = False
    done: bool = False
    error: str = ""
    lock: threading.Lock = field(default_factory=threading.Lock)

    def status(self) -> dict:
        st = self.loop.stats
        return {
            "name": self.name,
            "indexed": st.indexed, "fetched": st.fetched,
            "errors": st.errors, "robots_blocked": st.robots_blocked,
            "links_found": st.links_found,
            "frontier": len(self.loop.sched),
            "maxPages": self.max_pages,
            "paused": self.paused, "done": self.done,
            "jobError": self.error,
        }


class CrawlBot:
    """Registry + runner for REST-created crawl jobs."""

    def __init__(self, colldb, fetcher_factory=None):
        self.colldb = colldb
        #: injectable for tests (FakeFetcher); None = a real Fetcher
        #: with the collection's SpiderProxy pool
        self.fetcher_factory = fetcher_factory
        self.jobs: dict[str, CrawlJob] = {}
        self._lock = threading.Lock()

    def create(self, name: str, seeds: list[str], max_pages: int = 100,
               max_hops: int = 3, same_host_only: bool = True,
               delay_s: float = 0.25) -> CrawlJob:
        with self._lock:
            if name in self.jobs:
                raise ValueError(f"job {name!r} already exists")
            coll = self.colldb.get(f"crawl_{name}")
            sched = DurableSpiderScheduler(
                coll.dir / "spider",
                filters=[UrlFilterRule("*", delay_s=delay_s)],
                max_hops=max_hops, same_host_only=same_host_only,
                banned=coll.tagdb.is_banned)
            loop = SpiderLoop(coll, scheduler=sched,
                              fetcher=(self.fetcher_factory()
                                       if self.fetcher_factory
                                       else None))
            job = CrawlJob(name=name, loop=loop, max_pages=max_pages)
            self.jobs[name] = job

        def run():
            try:
                # seed in the background: each new host resolves its
                # first-IP, which can take seconds — the REST handler
                # must not hold the server lock through that
                for u in seeds:
                    loop.add_url(u)
                while (not job.done
                       and job.loop.stats.indexed < job.max_pages
                       and not job.loop.sched.exhausted):
                    if job.paused:
                        time.sleep(0.2)
                        continue
                    before = job.loop.stats.fetched
                    with job.lock:
                        job.loop.crawl_step()
                    if job.loop.stats.fetched == before:
                        # every IP inside its politeness window —
                        # sleep instead of spinning (SpiderLoop.crawl's
                        # backoff)
                        time.sleep(0.05)
            except Exception as e:  # noqa: BLE001 — surface, don't die
                job.error = str(e)
                log.exception("crawl job %s failed", name)
            finally:
                job.done = True
                try:
                    job.loop.sched.save()
                    self.colldb.get(f"crawl_{name}").save()
                except Exception as exc:  # noqa: BLE001 — job is done
                    log.warning("crawl job %s final save failed: %s",
                                name, exc)

        job.thread = threads.spawn(f"crawlbot-{name}", run)
        log.info("crawl job %s started (%d seeds, max %d pages)", name,
                 len(seeds), max_pages)
        return job

    def get(self, name: str) -> CrawlJob | None:
        return self.jobs.get(name)

    def delete(self, name: str) -> bool:
        """Unregister AND purge the job's corpus + frontier: a
        recreated job of the same name must start fresh (the durable
        spiderdb seen-set would otherwise dedup the new seeds away and
        the job would 'finish' with nothing crawled)."""
        with self._lock:
            job = self.jobs.pop(name, None)
        if job is None:
            return False
        job.done = True
        if job.thread is not None:
            job.thread.join(5.0)  # let the loop notice before purging
        cname = f"crawl_{name}"
        try:
            # delColl must unserve before it purges: stop the resident
            # loop and release the device gauge (serve.tenancy), then
            # zero the membudget accounting (Collection.close) — a
            # deleted corpus must not keep answering from HBM or keep
            # billing the budget
            from .tenancy import g_residency
            g_residency.release(cname)
            coll = self.colldb.drop(cname)
            cdir = coll.dir if coll is not None else None
            if cdir is None:
                base = self.colldb.base_dir / "coll" / cname
                cdir = base if base.exists() else None
            if cdir is not None:
                shutil.rmtree(cdir, ignore_errors=True)
        except Exception:  # noqa: BLE001 — purge is best-effort
            log.exception("crawl job %s purge failed", name)
        return True

    def list_jobs(self) -> list[dict]:
        return [j.status() for j in self.jobs.values()]
