"""HTTP front end — search, cached pages, injection, admin.

Reference: ``HttpServer.cpp`` (nonblocking HTTP server) + ``Pages.cpp``
page table routing (``Pages.cpp:44,577``) + per-page handlers:
``PageResults.cpp`` (SERP in HTML/XML/JSON/CSV, ``PageResults.cpp:274``),
``PageGet.cpp`` (cached page w/ highlighting), ``PageInject.cpp``/
``PageAddUrl.cpp`` (content/url injection), ``PageStats``/``PageHosts``
(admin). Python stdlib threading server — the accept/parse plane is not
the bottleneck (queries are); a C++ front end can slot in front later
exactly like the reference's ``gb proxy`` mode.

Endpoints (reference query-string names kept: ``q``, ``n``, ``c``):

* ``GET /search?q=...&n=10&c=main&format=json|xml|html``
* ``GET /get?d=<docid>&q=...`` — cached page, query terms highlighted
* ``GET|POST /inject?u=<url>`` (body = content) — index a document
* ``GET /addurl?u=<url>`` — queue a url for the spider
* ``GET /admin/stats`` — counters; ``GET /admin/hosts`` — shard map
* ``GET /`` — minimal search form
"""

from __future__ import annotations

import html as html_mod
import json
import os
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

from ..index.collection import CollectionDb
from ..query import devcheck, engine
from ..query.summary import highlight
from ..utils import chaos as chaos_mod
from ..utils import deadline as deadline_mod
from ..utils import threads
from ..utils.lockcheck import make_lock, make_rlock
from ..utils.log import get_logger
from ..utils.membudget import g_membudget
from ..utils import parms as parms_mod
from ..utils import priority as priority_mod
from ..utils import trace as trace_mod
from ..utils.parms import Conf
from ..utils.stats import g_stats
from ..utils.trace import g_tracer
from . import admission as admission_mod

log = get_logger("http")


class QueryBatcher:
    """Msg40 micro-batching: concurrent /search requests coalesce into
    ONE device dispatch (vmap over the query axis — SURVEY §7.8's
    throughput mode, which a one-lock-per-request server can never
    reach: its ceiling is 1/latency qps regardless of device speed).

    Requests enqueue and wait; a single worker drains the queue in
    same-parameter batches of ≤ MAX_B. Errors propagate to every waiter
    of the failing batch."""

    MAX_B = 64
    WINDOW_S = 0.002  # brief collect window once a first query arrives
    #: bounded admission: an overload burst fails fast with QueueFull
    #: (the serve edge sheds stale-or-503) instead of growing host
    #: memory without bound
    MAX_QUEUE = 512
    #: per-waiter footprint estimate charged to the membudget "serve"
    #: label (query string + holder + span/deadline refs)
    QUEUE_ENTRY_COST = 4096

    def __init__(self, run_batch):
        #: run_batch((coll_name, topk, offset), [queries]) → [results]
        self._run_batch = run_batch
        self._cv = threading.Condition()
        #: (key, query, holder, parent span | None)
        self._queue: list[tuple] = []
        self._inflight = 0  # device waves currently dispatched
        self._alive = True
        # two executors so batch N's host post-processing (titledb
        # reads, clustering) overlaps batch N+1's device waves
        # (device_get releases the GIL)
        from concurrent.futures import ThreadPoolExecutor
        self._pool = ThreadPoolExecutor(2)
        self._thread = threads.spawn("query-batcher", self._loop)

    @property
    def alive(self) -> bool:
        return self._alive

    def stop(self) -> None:
        """Kill the worker; fail queued waiters fast (they'd otherwise
        hang to their own timeout)."""
        with self._cv:
            self._alive = False
            for e in self._queue:
                e[2]["err"] = RuntimeError("query batcher stopped")
            self._queue.clear()
            self._gauge_locked()
            self._cv.notify_all()
        self._pool.shutdown(wait=False)

    def _gauge_locked(self) -> None:
        g_membudget.set_gauge(
            "serve", self, len(self._queue) * self.QUEUE_ENTRY_COST)

    def search(self, key: tuple, q: str, timeout: float = 60.0):
        holder: dict = {}
        # wait bounded by own timeout AND any bound query deadline —
        # whichever is sooner (the hedged-transport merge rule)
        dl = deadline_mod.current()
        deadline = deadline_mod.Deadline.after(timeout)
        if dl is not None and dl.at < deadline.at:
            deadline = dl
        with self._cv:
            if len(self._queue) >= self.MAX_QUEUE:
                g_stats.count("admission.queue_full")
                raise priority_mod.QueueFull(
                    "query batcher queue full")
            self._queue.append((key, q, holder,
                                trace_mod.current_span(), dl,
                                priority_mod.current_tier(),
                                priority_mod.current_tenant()))
            self._gauge_locked()
            self._cv.notify_all()
            while "res" not in holder and "err" not in holder:
                left = deadline.remaining()
                if left <= 0:
                    if dl is not None and dl.expired():
                        raise deadline_mod.DeadlineExceeded(
                            "query deadline exceeded in batcher")
                    raise TimeoutError("query batcher timeout")
                self._cv.wait(timeout=left)
        if "err" in holder:
            raise holder["err"]
        return holder["res"]

    def _loop(self) -> None:
        while True:
            with self._cv:
                while self._alive and not self._queue:
                    self._cv.wait()
                if not self._alive:
                    return
                # fill-or-flush: a wave already in flight buys a
                # collect window (up to WINDOW_S hoping to fill a
                # same-key batch); an IDLE device launches immediately
                # with whatever is queued — queueing in front of idle
                # hardware is pure added latency
                if self._inflight > 0:
                    w = deadline_mod.Deadline.after(self.WINDOW_S)
                    while (self._alive and self._inflight > 0
                           and len(self._queue) < self.MAX_B):
                        left = w.remaining()
                        if left <= 0:
                            break
                        self._cv.wait(timeout=left)
                else:
                    g_stats.count("admission.wave.idle_flush")
                if not self._alive:
                    return
                if not self._queue:  # stop() drained it mid-window
                    continue
                key = self._queue[0][0]
                batch = [e for e in self._queue if e[0] == key][: self.MAX_B]
                for e in batch:
                    self._queue.remove(e)
                self._gauge_locked()
                self._inflight += 1
            try:
                self._pool.submit(self._run_one, key, batch)
            except RuntimeError as exc:  # pool shut down by stop()
                with self._cv:
                    self._inflight -= 1
                    for e in batch:
                        e[2]["err"] = exc
                    self._cv.notify_all()
                return

    def _run_one(self, key, batch) -> None:
        try:
            self._run_one_inner(key, batch)
        finally:
            with self._cv:
                self._inflight -= 1
                self._cv.notify_all()  # wake the fill-or-flush window

    def _run_one_inner(self, key, batch) -> None:
        try:
            # worker thread = empty contextvars context; re-attach the
            # first traced waiter's span so the coalesced dispatch
            # lands in SOME trace, and mark the other waiters' traces
            # with a completed "coalesced" marker covering the interval
            parents = [e[3] for e in batch if len(e) > 3 and
                       e[3] is not None]
            # the coalesced dispatch runs under the LONGEST rider
            # budget (a short-deadline rider must not abandon every
            # other rider's wave; its own wait still times out)
            dls = [e[4] for e in batch
                   if len(e) > 4 and e[4] is not None]
            dl = max(dls, key=lambda d: d.at) if dls else None
            # ...and under the HIGHEST rider tier: a crawlbot rider
            # must not demote an interactive rider's coalesced wave
            tiers = [e[5] for e in batch
                     if len(e) > 5 and e[5] is not None]
            tier = (min(tiers, key=priority_mod.TIERS.index)
                    if tiers else None)
            # riders of one wave share a key => share a collection =>
            # share a tenant; carry the first one forward so the wave
            # bills (and sheds) against the right quota downstream
            tenants = [e[6] for e in batch
                       if len(e) > 6 and e[6] is not None]
            tenant = tenants[0] if tenants else None
            t0 = time.perf_counter()
            with trace_mod.attach(parents[0] if parents else None), \
                    deadline_mod.bind(dl), \
                    priority_mod.bind_tier(tier), \
                    priority_mod.bind_tenant(tenant):
                res = self._run_batch(key, [e[1] for e in batch])
            for p in parents[1:]:
                p.record("query.device_batch", t0, coalesced=True,
                         batch=len(batch))
            with self._cv:
                for e, r in zip(batch, res):
                    e[2]["res"] = r
                self._cv.notify_all()
        except Exception as exc:  # noqa: BLE001 — waiters must wake
            with self._cv:
                for e in batch:
                    e[2]["err"] = exc
                self._cv.notify_all()


def _xml_escape(s: str) -> str:
    return html_mod.escape(s, quote=True)


def render_results(res: engine.SearchResults, fmt: str,
                   trace_id: str | None = None) -> tuple[str, str]:
    """SERP rendering (PageResults.cpp HTML/XML/JSON/CSV).

    ``trace_id`` (``debug=1`` requests) is echoed in the body so a
    user-visible query can be looked up on ``/admin/traces``."""
    if fmt == "json":
        payload = {
            "query": res.query,
            "totalMatches": res.total_matches,
            "clustered": res.clustered,
            "suggestion": res.suggestion,
            "facets": {f: [[v, c] for v, c in pairs]
                       for f, pairs in (res.facets or {}).items()},
            "results": [
                {"docId": r.docid, "score": r.score, "url": r.url,
                 "title": r.title, "snippet": r.snippet, "site": r.site}
                for r in res.results
            ],
        }
        if trace_id:
            payload["traceId"] = trace_id
        return json.dumps(payload), "application/json"
    if fmt == "xml":
        rows = "".join(
            f"<result><docId>{r.docid}</docId>"
            f"<score>{r.score}</score>"
            f"<url>{_xml_escape(r.url)}</url>"
            f"<title>{_xml_escape(r.title)}</title>"
            f"<snippet>{_xml_escape(r.snippet)}</snippet></result>"
            for r in res.results)
        tid = (f"<traceId>{_xml_escape(trace_id)}</traceId>"
               if trace_id else "")
        return (f'<?xml version="1.0" encoding="UTF-8"?>'
                f"<response><query>{_xml_escape(res.query)}</query>"
                f"<totalMatches>{res.total_matches}</totalMatches>"
                f"{tid}{rows}</response>", "text/xml")
    if fmt == "csv":
        lines = ["docid,score,url,title"]
        for r in res.results:
            t = r.title.replace('"', '""')
            lines.append(f'{r.docid},{r.score},"{r.url}","{t}"')
        return "\n".join(lines), "text/csv"
    # html
    items = "".join(
        f'<li><a href="{html_mod.escape(r.url)}">'
        f"{html_mod.escape(r.title) or html_mod.escape(r.url)}</a>"
        f"<br><small>{html_mod.escape(r.snippet)}</small>"
        f"<br><code>{html_mod.escape(r.url)}</code> "
        f"<i>{r.score:.1f}</i></li>"
        for r in res.results)
    tid = (f'<p><small>trace <a href="/admin/traces?id='
           f'{html_mod.escape(trace_id)}">{html_mod.escape(trace_id)}'
           f"</a></small></p>" if trace_id else "")
    return (f"<html><head><title>{html_mod.escape(res.query)} - search"
            f"</title></head><body>"
            f'<form action="/search"><input name="q" '
            f'value="{html_mod.escape(res.query)}"><input type="submit" '
            f'value="search"></form>'
            f"<p>{res.total_matches} matches</p><ol>{items}</ol>{tid}"
            f"</body></html>", "text/html")


class SearchHTTPServer:
    """Owns the collections + (optionally) a sharded index and serves the
    reference's public endpoints."""

    def __init__(self, base_dir, host: str = "127.0.0.1", port: int = 8000,
                 sharded=None, spider=None, cluster=None,
                 conf: Conf | None = None):
        self.colldb = CollectionDb(base_dir)
        self.sharded = sharded  # ShardedCollection | None (in-process mesh)
        self.cluster = cluster  # ClusterClient | None (multi-process plane)
        self.spider = spider    # spider queue hook (addurl)
        self.host = host
        self.port = port
        self.conf = conf or Conf()
        gbconf = Path(base_dir) / "gb.conf"
        if conf is None and gbconf.exists():
            self.conf.load(gbconf)
        # guardrail wiring: the process memory budget tracks the live
        # max_mem parm (Conf::m_maxMem → g_mem), and the checkify parm
        # arms the device-plane harness (OSSE_CHECKIFY equivalent)
        g_membudget.set_limit(self.conf.max_mem)
        if self.conf.checkify:
            devcheck.set_enabled(True)
        # trace plane wiring: sampling + slow-query threshold from the
        # parms, slowlog next to statsdb (process-global tracer — the
        # last server constructed in a process owns the slowlog path)
        g_tracer.configure(sample_n=self.conf.trace_sample,
                           slow_ms=self.conf.slow_query_ms,
                           slowlog_path=Path(base_dir) / "slowlog.jsonl",
                           host=f"{host}:{port}")
        self.conf.on_update(self._on_guardrail_parm)
        self.stats = {"queries": 0, "injects": 0, "addurls": 0,
                      "gets": 0, "errors": 0, "auth_denied": 0}
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        # the Rdb/MemTable/caches are single-writer structures (the
        # reference's whole core is single-threaded event-driven,
        # SURVEY §1); the threaded accept plane serializes at this lock
        self._lock = make_rlock("server.core")
        #: /search micro-batching (flat device path only; the sharded
        #: and cluster planes batch at their own layers)
        self._batcher = QueryBatcher(self._run_device_batch)
        #: admission plane: bounded, tiered gate in front of the
        #: dispatch planes — sheds stale-or-503 before the membudget
        #: ever has to refuse real work (serve/admission.py)
        self.admission = admission_mod.AdmissionGate()
        #: tenant plane: the residency manager owns every collection's
        #: (DeviceIndex, ResidentLoop) lifecycle; its hot-set count
        #: rides the tenant_hot parm, its byte bound the membudget
        #: "device" label cap (device_budget parm, 0 = uncapped)
        from .tenancy import g_residency
        g_residency.configure(
            max_resident=int(getattr(self.conf, "tenant_hot", 0)))
        g_residency.attach(g_membudget)
        if int(getattr(self.conf, "device_budget", 0)) > 0:
            g_membudget.set_label_cap(
                "device", int(self.conf.device_budget))
        #: statsdb persistence (reference Statsdb: an on-disk ring of
        #: timestamped metric samples behind PagePerf graphs)
        self._statsdb_path = Path(base_dir) / "statsdb.jsonl"
        self._sampler: threading.Thread | None = None
        self._stop_sampling = threading.Event()
        #: crawlbot job registry (lazy; PageCrawlBot.cpp role) and an
        #: injectable fetcher factory for tests
        self._crawlbot = None
        self.crawl_fetcher_factory = None
        #: AutoBan (AutoBan.cpp): per-IP query rate limiting. hits =
        #: ip → recent request timestamps; banned = ip → ban expiry
        self._ab_lock = make_lock("server.autoban")
        self._ab_hits: dict[str, list[float]] = {}
        self._ab_banned: dict[str, float] = {}
        #: niceness gate: background requests yield to interactive
        from ..utils.nice import NicenessGate
        self.nice_gate = NicenessGate()
        #: Msg17/Msg40Cache: rendered result pages on the cache plane.
        #: Generation-keyed per request via _result_gen — local index
        #: version single-node, the shard/cluster generation vector on
        #: the distributed planes (so a remote write invalidates the
        #: SERP too, closing the stale-after-delete window the old
        #: fixed-TTL cache had)
        from ..cache import g_cacheplane
        self._result_cache = g_cacheplane.register(
            "server.results", ttl_s=30.0, max_entries=2048,
            desc="rendered result pages (Msg17/Msg40Cache role)")
        #: per-user admin accounts (Users.cpp / users.txt)
        from ..utils.users import Users
        self.users = Users(base_dir)

    def _on_guardrail_parm(self, name: str, value) -> None:
        """Live parm updates feeding the guardrail planes (the 0x3f
        broadcast applies here too via attach_conf → set)."""
        if name == "max_mem":
            g_membudget.set_limit(int(value))
        elif name == "checkify":
            # False reverts to the env default rather than forcing off,
            # so OSSE_CHECKIFY=1 test runs survive a parm sync
            devcheck.set_enabled(True if value else None)
        elif name == "trace_sample":
            g_tracer.configure(sample_n=int(value))
        elif name == "slow_query_ms":
            g_tracer.configure(slow_ms=float(value))
        elif name == "tenant_hot":
            from .tenancy import g_residency
            g_residency.configure(max_resident=int(value))
        elif name == "device_budget":
            g_membudget.set_label_cap("device", int(value))

    BAN_COOLDOWN_S = 60.0

    def _autobanned(self, ip: str, limit_qps: int) -> bool:
        """Sliding 1-second window per client IP; exceeding the limit
        bans the IP for BAN_COOLDOWN_S (reference AutoBan bans abusive
        query sources and returns an error page)."""
        if not limit_qps or not ip:
            return False
        now = time.monotonic()
        with self._ab_lock:
            until = self._ab_banned.get(ip, 0.0)
            if until > now:
                return True
            hits = self._ab_hits.setdefault(ip, [])
            hits.append(now)
            del hits[: max(0, len(hits) - 4 * limit_qps)]
            recent = [t for t in hits if t > now - 1.0]
            if len(recent) > limit_qps:
                self._ab_banned[ip] = now + self.BAN_COOLDOWN_S
                # the cooldown IS the penalty: drop the window so the
                # first post-ban request is judged on fresh traffic —
                # stale pre-ban hits must not re-ban it on sight (a
                # banned client could otherwise never requalify)
                self._ab_hits.pop(ip, None)
                if len(self._ab_banned) > 4096:
                    self._ab_banned = {
                        k: v for k, v in self._ab_banned.items()
                        if v > now}
                log.warning("autoban: %s exceeded %d qps", ip,
                            limit_qps)
                return True
            if len(self._ab_hits) > 8192:  # bound the tracking table
                self._ab_hits = {ip: hits}
        return False

    def _run_device_batch(self, key: tuple, queries: list[str]):
        cname, topk, offset = key
        # resident-loop dispatch: the device wave runs OUTSIDE the core
        # lock (the ResidentLoop serializes issue/collect itself), so a
        # wave in flight no longer blocks injects or the next batch.
        # The lock still covers the collection lookup and — via
        # results_lock — the host post-processing, which reads the
        # single-writer Rdb/titledb structures.
        with self._lock:
            coll = self.colldb.get(cname)
        return engine.search_device_batch(
            coll, queries, topk=topk, offset=offset,
            resident=True, results_lock=self._lock)

    def _authorized(self, query: dict,
                    min_role: str = "admin") -> bool:
        """Auth gate for /admin and mutating endpoints: the master
        password (Conf::m_masterPwds) OR a per-user credential from
        the users table (Users.cpp — ``user=``/``upwd=`` params) at
        the required role. Empty master password AND empty user table
        = open instance."""
        pwd = self.conf.master_password
        has_users = bool(self.users.names())
        if not pwd and not has_users:
            return True
        if pwd and query.get("pwd", "") == pwd:
            return True
        u = query.get("user", "")
        if u and self.users.check(u, query.get("upwd", ""),
                                  min_role=min_role):
            return True
        return False

    # --- request handling -------------------------------------------------

    def handle(self, method: str, path: str, query: dict,
               body: bytes, client_ip: str = "",
               niceness: int = 0,
               tier: str | None = None,
               tenant: str | None = None) -> tuple[int, str, str]:
        """Route one request → (status, payload, content_type).
        The Pages.cpp s_pages[] table, as a method. Background
        (niceness-1) requests yield to in-flight interactive ones
        (UdpProtocol.h niceness bit). ``tier``/``tenant`` are
        propagated X-OSSE-Priority / X-OSSE-Tenant verdicts, if the
        caller carried them."""
        # drop any extra response headers a previous request left on
        # this thread's context (direct handle() callers never pop)
        admission_mod.pop_response_headers()
        self.nice_gate.enter(niceness)
        try:
            return self._handle_inner(method, path, query, body,
                                      client_ip, niceness=niceness,
                                      header_tier=tier,
                                      header_tenant=tenant)
        finally:
            self.nice_gate.exit(niceness)

    def _handle_inner(self, method: str, path: str, query: dict,
                      body: bytes, client_ip: str = "",
                      niceness: int = 0,
                      header_tier: str | None = None,
                      header_tenant: str | None = None
                      ) -> tuple[int, str, str]:
        try:
            if path == "/":
                return 200, self._page_root(), "text/html"
            if path == "/search":
                # autoban runs BEFORE any collection lookup, and read
                # paths never create collections — unauthenticated
                # requests with arbitrary c= names must not mint
                # directory trees on disk (nor bypass the rate limit)
                coll = self._coll_read(query)
                # unknown-collection requests still get the COLL-scope
                # default limit — 404ing must not bypass the rate gate
                limit = int(coll.conf.autoban_qps) if coll is not None \
                    else int(parms_mod.parm("autoban_qps").default)
                if self._autobanned(client_ip, limit):
                    g_stats.count("autoban.rejected")
                    return 429, json.dumps(
                        {"error": "query rate limit (autoban)"}), \
                        "application/json"
                if coll is None and self.sharded is None \
                        and self.cluster is None:
                    return 404, json.dumps(
                        {"error": "no such collection"}), \
                        "application/json"
                # front-door classification (admission plane): explicit
                # tier= param > propagated header > niceness bit, else
                # interactive — bound so scatter legs inherit it
                tier = priority_mod.classify(query, niceness=niceness,
                                             header_tier=header_tier)
                g_stats.count(f"admission.tier.{tier}")
                # the billing tenant IS the collection (the crawlbot
                # customer); a propagated header keeps a scatter leg
                # on its coordinator's quota ledger
                tenant = header_tenant or query.get("c", "main")
                # NOT under the global lock: the micro-batcher would
                # deadlock (its worker takes the lock), and holding it
                # per-request caps the plane at 1/latency qps
                with priority_mod.bind_tier(tier), \
                        priority_mod.bind_tenant(tenant):
                    return self._page_search(query, tier=tier,
                                             tenant=tenant)
            with self._lock:
                return self._route(method, path, query, body)
        except Exception as e:  # noqa: BLE001 — server must not die
            self.stats["errors"] += 1
            log.warning("error handling %s: %s", path, e)
            return 500, json.dumps({"error": str(e)}), "application/json"

    def _route(self, method: str, path: str, query: dict,
               body: bytes) -> tuple[int, str, str]:
        if path == "/get":
            return self._page_get(query)
        if path == "/crawlbot":
            # REST bulk-crawl API (PageCrawlBot.cpp) — admin-gated
            # like every index-mutating endpoint
            if not self._authorized(query):
                self.stats["auth_denied"] += 1
                return 401, json.dumps(
                    {"error": "bad or missing pwd"}), "application/json"
            return self._page_crawlbot(query)
        if path in ("/inject", "/addurl", "/delete"):
            # index-mutating endpoints are admin-gated once a master
            # password is set (the reference gates injection behind the
            # admin password, PageInject/Pages auth)
            if not self._authorized(query):
                self.stats["auth_denied"] += 1
                return 401, json.dumps(
                    {"error": "bad or missing pwd"}), "application/json"
            if path == "/inject":
                return self._page_inject(query, body)
            if path == "/delete":
                return self._page_delete(query)
            return self._page_addurl(query)
        if path == "/metrics":
            # Prometheus-style exposition for EXTERNAL scrapers — like
            # /search it is unauthenticated read-only plumbing, outside
            # the /admin password gate
            return 200, self._metrics_text(), "text/plain"
        if path.startswith("/admin") and not self._authorized(query):
            self.stats["auth_denied"] += 1
            return 401, json.dumps({"error": "bad or missing pwd"}), \
                "application/json"
        if path in ("/admin", "/admin/"):
            return 200, self._page_admin_index(query), "text/html"
        if path == "/admin/profiler":
            return self._page_profiler(query)
        if path == "/admin/graph":
            return 200, self._page_graph(), "image/svg+xml"
        if path == "/admin/stats":
            stats = dict(self.stats)
            # corrupt-run quarantine state (Msg5 error correction)
            q: dict[str, list] = {}
            if self.sharded is not None:
                for s, row in enumerate(self.sharded.grid):
                    for r, coll in enumerate(row):
                        for rn, rdb in coll.rdbs().items():
                            if rdb.quarantined:
                                q[f"shard{s}_r{r}:{rn}"] = rdb.quarantined
            elif self.colldb is not None:
                q = {f"{cn}:{rn}": rdb.quarantined
                     for cn in self.colldb.names()
                     for rn, rdb in self.colldb.get(cn).rdbs().items()
                     if rdb.quarantined}
            if q:
                stats["quarantined_runs"] = q
            return 200, json.dumps(stats), "application/json"
        if path == "/admin/hosts":
            return 200, self._page_hosts(), "application/json"
        if path == "/admin/perf":
            return self._page_perf(query)
        if path == "/admin/mem":
            return self._page_mem(query)
        if path == "/admin/transport":
            return self._page_transport(query)
        if path == "/admin/cache":
            return self._page_cache(query)
        if path == "/admin/traces":
            return self._page_traces(query)
        if path == "/admin/parms":
            return self._page_parms(query)
        if path == "/admin/jit":
            return self._page_jit(query)
        if path == "/admin/hbm":
            return self._page_hbm(query)
        if path == "/admin/device":
            return self._page_device(query)
        if path == "/admin/admission":
            return self._page_admission(query)
        if path == "/admin/tenants":
            return self._page_tenants(query)
        return 404, json.dumps({"error": "no such page"}), \
            "application/json"

    def _coll(self, query: dict):
        return self.colldb.get(query.get("c", "main"))

    def _coll_read(self, query: dict):
        """Read-path collection lookup: NEVER creates on-disk state for
        arbitrary ``c=`` names — except the default collection, which
        stays lazily creatable (a fresh instance must answer
        ``/search?q=x`` with zero results, not 404). Returns None for
        unknown collections."""
        name = query.get("c", "main")
        try:
            return self.colldb.get(name, create=(name == "main"))
        except KeyError:
            return None

    def _page_root(self) -> str:
        return ('<html><body><form action="/search">'
                '<input name="q"><input type="submit" value="search">'
                "</form></body></html>")

    def _page_search(self, query: dict,
                     tier: str = "interactive",
                     tenant: str | None = None) -> tuple[int, str, str]:
        q = query.get("q", "")
        if not q:
            return 400, json.dumps({"error": "missing q"}), \
                "application/json"
        # debug=1: force-sample this query's trace and echo the trace
        # id in the body so the waterfall can be pulled up by id
        debug = query.get("debug", "") not in ("", "0")
        with g_tracer.start("search", sampled=True if debug else None,
                            q=q, tier=tier) as tr:
            # the whole-request latency histogram (cache hits and
            # degraded answers included) — what a single-node SLO
            # reads; the per-tier twin is what the overload harness
            # asserts on (interactive p99 bounded while crawlbot sheds)
            with trace_mod.timed_span("serve.search"), \
                    trace_mod.timed_span(f"serve.search.{tier}"):
                out = self._page_search_traced(query, q, debug, tr,
                                               tier=tier,
                                               tenant=tenant)
        return out

    def _query_deadline(self, query: dict):
        """The per-query budget: ``deadline_ms=`` on the request, else
        the ``OSSE_DEADLINE_MS`` env default; absent/0 = unbudgeted."""
        raw = query.get("deadline_ms", "") \
            or os.environ.get("OSSE_DEADLINE_MS", "")
        try:
            ms = float(raw)
        except (TypeError, ValueError):
            return None
        if ms <= 0:
            return None
        return deadline_mod.Deadline.after(ms / 1000.0)

    def _page_search_traced(self, query: dict, q: str, debug: bool,
                            tr, tier: str = "interactive",
                            tenant: str | None = None
                            ) -> tuple[int, str, str]:
        n = min(int(query.get("n", 10)), 100)
        # deep paging: first result number (reference PageResults s=),
        # bounded so a hostile s can't force a corpus-sized top-k
        s = min(max(int(query.get("s", 0)), 0), 100000)
        fmt = query.get("format", "json")
        self.stats["queries"] += 1
        cname = query.get("c", "main")
        rc_coll = self._coll_read(query)
        ttl = float(getattr(rc_coll.conf, "result_cache_ttl", 0)
                    if rc_coll is not None else 0)
        swr = float(getattr(rc_coll.conf, "result_cache_swr", 0)
                    if rc_coll is not None else 0)
        ckey = gen = None
        # debug requests bypass the result cache both ways: a cached
        # body would echo a STALE trace id, and a debug body must not
        # poison the cache for ordinary requests
        if ttl > 0 and not debug:
            gen = self._result_gen(rc_coll)
            ckey = (cname, q, n, s, fmt)
        dl = self._query_deadline(query)
        # a fresh cache hit bypasses the admission gate entirely —
        # serving from memory costs nothing the gate protects, and
        # under overload the hot head of the Zipf mix must keep
        # answering (the reference's Msg17 hits skip Msg39 queueing)
        if ckey is not None:
            hit, page = self._result_cache.lookup(ckey, gen=gen)
            if hit:
                self.stats["result_cache_hits"] = \
                    self.stats.get("result_cache_hits", 0) + 1
                trace_mod.tag(result_cache="hit")
                return page
        try:
            token = self.admission.admit(tier, deadline=dl,
                                         tenant=tenant)
        except admission_mod.Shed as shed:
            return self._shed_response(shed, ckey, gen)
        try:
            with token, deadline_mod.bind(dl):
                out = self._search_cached(query, q, n, s, fmt, rc_coll,
                                          debug, tr, ckey, gen, ttl,
                                          swr)
            deadline_mod.note_met(dl)
            return out
        except priority_mod.QueueFull:
            # a bounded dispatch queue (batcher/resident) refused the
            # enqueue past the gate — same shed ladder, same accounting
            return self._shed_response(
                admission_mod.Shed("queue_full"), ckey, gen)
        except deadline_mod.DeadlineExceeded:
            # budget burned downstream: the cache plane's just-expired
            # answer (same generation — a write still invalidates)
            # beats a refusal; it goes out marked degraded
            if ckey is not None:
                hit, page = self._result_cache.lookup_stale(ckey,
                                                            gen=gen)
                if hit:
                    g_stats.count("deadline.stale_served")
                    trace_mod.tag(deadline="expired",
                                  results="degraded")
                    self.stats["deadline_stale"] = \
                        self.stats.get("deadline_stale", 0) + 1
                    return page
            g_stats.count("deadline.refused")
            return 504, json.dumps({"error": "deadline exceeded"}), \
                "application/json"

    def _shed_response(self, shed: admission_mod.Shed, ckey, gen
                       ) -> tuple[int, str, str]:
        """The shed ladder, cheapest first: the cache plane's
        same-generation SWR-stale answer marked degraded, else 503 +
        Retry-After. Every shed is counted — the load harness asserts
        none are silently lost."""
        if ckey is not None:
            hit, page = self._result_cache.lookup_stale(ckey, gen=gen)
            if hit:
                g_stats.count("admission.shed.stale")
                trace_mod.tag(admission=shed.reason,
                              results="degraded")
                self.stats["admission_stale"] = \
                    self.stats.get("admission_stale", 0) + 1
                return page
        g_stats.count("admission.shed.refused")
        trace_mod.tag(admission=shed.reason, results="refused")
        self.stats["admission_refused"] = \
            self.stats.get("admission_refused", 0) + 1
        retry = max(1, int(round(shed.retry_after_s)))
        admission_mod.set_response_header("Retry-After", str(retry))
        return 503, json.dumps(
            {"error": f"overloaded ({shed.reason})",
             "retryAfter": retry}), "application/json"

    def _search_cached(self, query: dict, q: str, n: int, s: int,
                       fmt: str, rc_coll, debug: bool, tr, ckey, gen,
                       ttl: float, swr: float) -> tuple[int, str, str]:
        # Msg17/Msg40Cache result cache: identical pages within the TTL
        # serve from memory. Single-node, the LOCAL index version in
        # the key invalidates instantly on mutation; the distributed
        # planes (cluster/sharded) mutate on remote nodes this frontend
        # can't version-watch, so there staleness is bounded by the TTL
        # alone (the reference's Msg17 accepts the same bound).
        deg: dict = {}
        if ckey is not None:
            hit, page = self._result_cache.lookup(ckey, gen=gen)
            if hit:
                self.stats["result_cache_hits"] = \
                    self.stats.get("result_cache_hits", 0) + 1
                trace_mod.tag(result_cache="hit")
                return page
            if swr > 0:
                # stale-while-revalidate for hot SERPs: serve the
                # just-expired page and refresh in the background —
                # never across a generation move (get_or_compute
                # enforces that), so a write still invalidates
                # instantly
                page, status = self._result_cache.get_or_compute(
                    ckey,
                    lambda: self._render_search(query, q, n, s, fmt,
                                                rc_coll, debug, tr,
                                                degraded_out=deg),
                    ttl_s=ttl, gen=gen, swr_s=swr)
                if status in ("hit", "stale", "join"):
                    self.stats["result_cache_hits"] = \
                        self.stats.get("result_cache_hits", 0) + 1
                    trace_mod.tag(result_cache=status)
                if deg.get("degraded"):
                    # a degraded partial must not serve for a TTL as if
                    # it were the full answer
                    self._result_cache.invalidate(ckey)
                return page
        page = self._render_search(query, q, n, s, fmt, rc_coll,
                                   debug, tr, degraded_out=deg)
        if ckey is not None and not deg.get("degraded"):
            self._result_cache.put(ckey, page, ttl_s=ttl, gen=gen)
        return page

    def _result_gen(self, rc_coll) -> tuple:
        """The result cache's generation for one request: whatever
        version vector a write to ANY backing index would move —
        local posdb single-node, every shard's generation on the
        distributed planes (the write-path invalidation contract)."""
        if self.cluster is not None:
            return ("cluster",) + self.cluster.gen_vector()
        if self.sharded is not None:
            return ("sharded",) + tuple(
                coll.posdb.version
                for row in self.sharded.grid for coll in row)
        return ("flat",
                rc_coll.posdb.version if rc_coll is not None else 0)

    def _render_search(self, query: dict, q: str, n: int, s: int,
                       fmt: str, rc_coll, debug: bool, tr,
                       degraded_out: dict | None = None
                       ) -> tuple[int, str, str]:
        if self.cluster is not None:
            # conf is only consulted for PQR factors — never create a
            # local collection just to read it (rc_coll above already
            # did the read-only lookup)
            res = self.cluster.search(
                q, topk=n, offset=s,
                conf=rc_coll.conf if rc_coll else None)
        elif self.sharded is not None:
            if self.conf.serve_mesh:
                # mesh-resident serving: the ticket wave dispatches ONE
                # shard_map program across all chips (in-jit Msg3a merge
                # + site dedup); the ResidentLoop serializes device
                # work, so the lock guards only host post-processing
                res = engine.get_mesh_resident(self.sharded).serve(
                    q, topk=n, offset=s, results_lock=self._lock)
            else:
                from ..parallel import sharded_search
                with self._lock:
                    res = sharded_search(self.sharded, q, topk=n,
                                         offset=s)
        elif self.conf.serve_device:
            # resident-index path through the micro-batcher: concurrent
            # requests share one vmapped dispatch
            try:
                res = self._batcher.search(
                    (query.get("c", "main"), n, s), q)
            except deadline_mod.DeadlineExceeded:
                raise  # serve edge owns expiry (stale-or-504)
            except priority_mod.QueueFull:
                # overload: the host-fallback path below would ADD load
                # exactly when the plane is saturated — shed instead
                raise
            except Exception as e:  # noqa: BLE001 — degrade, don't 500
                log.warning("device search failed (%s); host fallback",
                            e)
                with self._lock:
                    res = engine.search(self._coll(query), q, topk=n,
                                        offset=s)
        else:
            with self._lock:
                res = engine.search(self._coll(query), q, topk=n,
                                    offset=s)
        if getattr(res, "degraded", False):
            # a scatter leg timed out / failed past the hedge: partial
            # answer, stamped so the caller skips the result cache
            if degraded_out is not None:
                degraded_out["degraded"] = True
            self.stats["degraded"] = self.stats.get("degraded", 0) + 1
            trace_mod.tag(results="degraded")
        payload, ctype = render_results(
            res, fmt,
            trace_id=tr.trace_id if (debug and tr is not None) else None)
        return 200, payload, ctype

    def _page_get(self, query: dict) -> tuple[int, str, str]:
        """Cached page w/ optional highlight (PageGet.cpp)."""
        from ..build import docproc
        docid = int(query.get("d", "0"))
        self.stats["gets"] += 1
        if self.cluster is not None:
            rec = self.cluster.get_document(docid)
        elif self.sharded is not None:
            rec = self.sharded.get_document(docid)
        else:
            coll = self._coll_read(query)  # read path: never mint colls
            rec = docproc.get_document(coll, docid=docid) \
                if coll is not None else None
        if rec is None:
            return 404, json.dumps({"error": "not found"}), \
                "application/json"
        content = rec.get("content", rec.get("text", ""))
        terms = [w for w in query.get("q", "").split() if w]
        if terms:
            content = highlight(content, terms,
                                pre='<span style="background:yellow">',
                                post="</span>")
        return 200, content, "text/html"

    def _page_inject(self, query: dict, body: bytes) -> tuple[int, str, str]:
        """Direct content injection (PageInject.cpp / msgtype 0x07)."""
        from ..build import docproc
        url = query.get("u") or query.get("url")
        if not url:
            return 400, json.dumps({"error": "missing u"}), \
                "application/json"
        content = body.decode("utf-8", "replace") if body else \
            query.get("content", "")
        self.stats["injects"] += 1
        if self.cluster is not None:
            docid = self.cluster.index_document(url, content)
            return 200, json.dumps({"docId": int(docid)}), \
                "application/json"
        if self.sharded is not None:
            ml = self.sharded.index_document(url, content)
        else:
            ml = docproc.index_document(self._coll(query), url, content)
        if ml is None:  # tagdb manualban (EDOCBANNED)
            return 403, json.dumps({"error": "banned by tagdb"}), \
                "application/json"
        return 200, json.dumps({"docId": ml.docid,
                                "numKeys": len(ml.posdb_keys)}), \
            "application/json"

    def _page_delete(self, query: dict) -> tuple[int, str, str]:
        """Remove a url from the index (PageInject's delete form /
        msgtype 0x07 with delete=1). The write bumps the backing
        index's generation, which invalidates every dependent cache
        entry — the inject→delete regression test drives this route."""
        from ..build import docproc
        url = query.get("u") or query.get("url")
        if not url:
            return 400, json.dumps({"error": "missing u"}), \
                "application/json"
        self.stats["deletes"] = self.stats.get("deletes", 0) + 1
        if self.cluster is not None:
            self.cluster.remove_document(url)
            return 200, json.dumps({"deleted": url}), \
                "application/json"
        if self.sharded is not None:
            ok = self.sharded.remove_document(url)
        else:
            ok = docproc.remove_document(self._coll(query), url)
        if not ok:
            return 404, json.dumps({"error": "not found"}), \
                "application/json"
        return 200, json.dumps({"deleted": url}), "application/json"

    def _page_addurl(self, query: dict) -> tuple[int, str, str]:
        """Queue a url for spidering (PageAddUrl.cpp)."""
        url = query.get("u") or query.get("url")
        if not url:
            return 400, json.dumps({"error": "missing u"}), \
                "application/json"
        self.stats["addurls"] += 1
        if self.spider is None:
            return 503, json.dumps({"error": "spider not running"}), \
                "application/json"
        self.spider.add_url(url)
        return 200, json.dumps({"queued": url}), "application/json"

    def _page_crawlbot(self, query: dict) -> tuple[int, str, str]:
        """REST crawl jobs (PageCrawlBot.cpp): create/status/pause/
        resume/delete; corpora search via /search?c=crawl_<name>."""
        from .crawlbot import CrawlBot
        # two concurrent first requests must not each build a CrawlBot
        # (the loser's job state would be dropped on publish)
        with self._lock:
            if self._crawlbot is None:
                self._crawlbot = CrawlBot(self.colldb,
                                          fetcher_factory=
                                          self.crawl_fetcher_factory)
            bot = self._crawlbot
        name = query.get("name", "")
        if not name:
            return 200, json.dumps({"jobs": bot.list_jobs()}),                 "application/json"
        action = query.get("action", "")
        if action in ("pause", "resume"):
            job = bot.get(name)
            if job is None:
                return 404, json.dumps({"error": "no such job"}),                     "application/json"
            job.paused = action == "pause"
            return 200, json.dumps(job.status()), "application/json"
        if action == "delete":
            ok = bot.delete(name)
            return (200 if ok else 404), json.dumps({"deleted": ok}),                 "application/json"
        seeds = [u for u in (query.get("seeds", "") or "").replace(
            ",", " ").split() if u]
        if seeds:
            try:
                job = bot.create(
                    name, seeds,
                    max_pages=int(query.get("maxpages", 100)),
                    max_hops=int(query.get("maxhops", 3)),
                    same_host_only=query.get("spanhosts", "0")
                    not in ("1", "true"))
            except ValueError as e:
                return 409, json.dumps({"error": str(e)}),                     "application/json"
            return 200, json.dumps(job.status()), "application/json"
        job = bot.get(name)
        if job is None:
            return 404, json.dumps({"error": "no such job"}),                 "application/json"
        return 200, json.dumps(job.status()), "application/json"

    def _page_parms(self, query: dict) -> tuple[int, str, str]:
        """Parameter view + live update via cgi names — the Parms URL api
        (``&maxmem=...``); updates fire the conf's on_update listeners
        (the 0x3f cluster-broadcast hook)."""
        from ..utils import parms as parms_mod
        coll = self._coll(query)
        updated = {}
        for cgi, value in query.items():
            if cgi in ("c",):
                continue
            for target in (coll.conf,):
                try:
                    target.set_from_cgi(cgi, value)
                    updated[cgi] = value
                    break
                except KeyError:
                    continue
        table = [{
            "name": p.name, "cgi": p.cgi, "type": p.type.__name__,
            "default": p.default, "scope": p.scope, "desc": p.desc,
        } for p in parms_mod.parm_table()]
        return 200, json.dumps({
            "updated": updated,
            "coll": coll.conf.to_dict(),
            "table": table,
        }), "application/json"

    # --- admin HTML (Pages.cpp admin page set) ---------------------------

    def _page_admin_index(self, query: dict) -> str:
        pwd = query.get("pwd", "")
        sfx = f"?pwd={urllib.parse.quote(pwd)}" if pwd else ""
        links = "".join(
            f'<li><a href="/admin/{p}{sfx}">{p}</a></li>'
            for p in ("stats", "hosts", "perf", "mem", "transport",
                      "cache", "traces", "parms", "jit", "hbm",
                      "device", "admission", "tenants", "profiler",
                      "graph")) + '<li><a href="/metrics">metrics</a></li>'
        rows = "".join(f"<tr><td>{k}</td><td>{v}</td></tr>"
                       for k, v in self.stats.items())
        colls = ", ".join(self.colldb.names())
        return (f"<html><head><title>gb admin</title></head><body>"
                f"<h1>admin</h1><p>collections: {colls}</p>"
                f"<ul>{links}</ul><table border=1>{rows}</table>"
                f"</body></html>")

    def _page_admission(self, query: dict) -> tuple[int, str, str]:
        """Admission-plane view: gate occupancy + tier queues, the
        shed/tier counters, and the queue-delay histogram.
        ``?format=json`` returns the raw snapshot."""
        snap = self.admission.snapshot()
        adm = g_stats.prefixed("admission.")
        snap["counters"] = dict(sorted(adm["counters"].items()))
        snap["queue_delay"] = adm["latencies"].get(
            "admission.queue_delay", {})
        if query.get("format") == "json":
            return 200, json.dumps(snap), "application/json"
        qrows = "".join(f"<tr><td>{t}</td><td>{n}</td></tr>"
                        for t, n in snap["queued"].items())
        crows = "".join(f"<tr><td>{k}</td><td>{v}</td></tr>"
                        for k, v in snap["counters"].items()) \
            or "<tr><td colspan=2>none</td></tr>"
        qd = snap["queue_delay"] or {}
        return 200, (
            "<html><head><title>gb admission</title></head><body>"
            "<h1>admission</h1>"
            f"<p>inflight {snap['inflight']}/{snap['max_inflight']}"
            f" &middot; queued {snap['queued_total']}"
            f"/{snap['max_queue']}"
            f" &middot; svc EWMA {snap['svc_ewma_ms']} ms"
            f" &middot; admitted {snap['admitted_total']}"
            f" &middot; shed {snap['shed_total']}</p>"
            "<table border=1><tr><th>tier</th><th>queued</th></tr>"
            f"{qrows}</table>"
            f"<h2>queue delay</h2><p>{json.dumps(qd)}</p>"
            f"<h2>counters</h2><table border=1>{crows}</table>"
            "</body></html>"), "text/html"

    def _page_tenants(self, query: dict) -> tuple[int, str, str]:
        """Tenant-plane view: the resident set with LRU/pin state and
        device bytes (ResidencyManager), cold-start p50/p99, and each
        tenant's admission ledger — weight, share counters, served vs
        shed (the per-tenant SLO burn proxy: shed/(served+shed)).
        ``?format=json`` returns the raw snapshots."""
        from .tenancy import g_residency
        res = g_residency.snapshot()
        adm = self.admission.snapshot().get("tenants", {})
        if query.get("format") == "json":
            return 200, json.dumps(
                {"residency": res, "admission": adm}), \
                "application/json"
        names = sorted(set(res["tenants"]) | set(adm))
        rows = []
        for n in names:
            rt = res["tenants"].get(n, {})
            at = adm.get(n, {})
            served = at.get("served", 0)
            shed = at.get("shed", 0)
            burn = shed / (served + shed) if served + shed else 0.0
            rows.append(
                f"<tr><td>{html_mod.escape(n)}</td>"
                f"<td>{'RESIDENT' if rt.get('resident') else 'parked'}"
                f"{' (pinned)' if rt.get('pinned') else ''}</td>"
                f"<td>{rt.get('device_bytes', 0) / (1 << 20):.2f}</td>"
                f"<td>{rt.get('hits', 0)}</td>"
                f"<td>{rt.get('cold_starts', 0)}</td>"
                f"<td>{at.get('weight', 1.0):g}</td>"
                f"<td>{at.get('inflight', 0)}</td>"
                f"<td>{at.get('queued', 0)}</td>"
                f"<td>{served}</td><td>{shed}</td>"
                f"<td>{100.0 * burn:.1f}%</td></tr>")
        table = "".join(rows) or "<tr><td colspan=11>no tenants</td></tr>"
        return 200, (
            "<html><head><title>gb tenants</title></head><body>"
            "<h1>tenant plane</h1>"
            f"<p>resident {res['resident']}"
            + (f"/{res['max_resident']}" if res['max_resident'] else "")
            + f" &middot; parked {res['parked']}"
            f" &middot; device "
            f"{res['device_bytes'] / (1 << 20):.1f} MB"
            + (f" (cap {res['device_cap'] / (1 << 20):.1f} MB)"
               if res['device_cap'] else "")
            + f" &middot; cold starts {res['coldstarts']}"
            f" (p50 {res['coldstart_p50_ms']:.1f} ms, "
            f"p99 {res['coldstart_p99_ms']:.1f} ms)</p>"
            "<table border=1><tr><th>tenant</th><th>state</th>"
            "<th>device MB</th><th>hits</th><th>cold starts</th>"
            "<th>weight</th><th>inflight</th><th>queued</th>"
            "<th>served</th><th>shed</th><th>shed rate</th></tr>"
            f"{table}</table>"
            "</body></html>"), "text/html"

    def _page_mem(self, query: dict) -> tuple[int, str, str]:
        """Live memory-budget breakdown (the PageStats mem table +
        Mem.cpp printMem role): per-subsystem reserved/gauged bytes
        against the max_mem budget, plus the guardrail counters.
        ``?format=json`` returns the raw snapshot."""
        from ..utils.stats import g_stats
        snap = g_membudget.snapshot()
        counters = g_stats.snapshot()["counters"]
        snap["counters"] = {
            k: v for k, v in sorted(counters.items())
            if k.startswith(("membudget.", "devcheck."))}
        snap["checkify"] = devcheck.enabled()
        if query.get("format") == "json":
            return 200, json.dumps(snap), "application/json"
        mb = lambda n: f"{n / (1 << 20):.2f}"  # noqa: E731
        rows = "".join(
            f"<tr><td>{lb}</td><td>{mb(d['reserved'])}</td>"
            f"<td>{mb(d['gauged'])}</td><td>{d['rejections']}</td></tr>"
            for lb, d in snap["labels"].items())
        crows = "".join(f"<tr><td>{k}</td><td>{v}</td></tr>"
                        for k, v in snap["counters"].items()) \
            or "<tr><td colspan=2>none</td></tr>"
        return 200, (
            "<html><head><title>gb mem</title></head><body>"
            "<h1>memory budget</h1>"
            f"<p>limit {mb(snap['limit'])} MB &middot; "
            f"used {mb(snap['used'])} MB &middot; "
            f"free {mb(snap['free'])} MB &middot; "
            f"high water {mb(snap['high_water'])} MB &middot; "
            f"rejections {snap['rejections']} &middot; "
            f"checkify {'on' if snap['checkify'] else 'off'}</p>"
            "<table border=1><tr><th>label</th><th>reserved MB</th>"
            f"<th>gauged MB</th><th>rejections</th></tr>{rows}</table>"
            f"<h2>guardrail counters</h2>"
            f"<table border=1>{crows}</table>"
            "</body></html>"), "text/html"

    def _fleet_view(self) -> tuple[dict, dict]:
        """(hosts, fleet): per-host ``Stats.wire()`` payloads (None for
        an unreachable host) and their bucket-wise merge. A cluster
        coordinator scrapes every node over ``/rpc/stats``; a
        single-process server is a one-host fleet."""
        from ..utils.stats import g_stats, merge_wire
        if self.cluster is not None:
            sc = self.cluster.scrape()
            return sc["hosts"], sc["fleet"]
        w = g_stats.wire()
        return {"local": w}, merge_wire([w])

    def _page_perf(self, query: dict) -> tuple[int, str, str]:
        """Fleet perf dashboard (PagePerf drawn across hosts + the
        PageStatsdb graphs): one row per latency metric with a p99
        column per host and the MERGED fleet distribution — fleet
        percentiles come from merged histogram buckets, never from
        averaging per-host percentiles. The fleet p99 cell links its
        exemplar trace to /admin/traces; SLO burn rates, gauges,
        counters and qps/p50 sparklines ride below. ``?format=json``
        returns the merged view raw."""
        from ..utils.slo import g_slo
        from ..utils.stats import LatencyStat, g_stats
        hosts, fleet = self._fleet_view()
        # evaluate against the view just scraped so the dashboard is
        # fresh on demand rather than as stale as the last sampler tick
        if g_slo.objectives:
            try:
                g_slo.evaluate(fleet["counters"], fleet["latencies"])
            except Exception:
                g_stats.count("slo.eval_errors")
        slo_status = g_slo.status()
        # operator-visible build alerts: a shard at the runstart pack
        # limit keeps boot-looping on the ValueError until it is split —
        # surface the counter here, where a fleet operator looks first
        alerts = []
        n_ovf = fleet["counters"].get("build.postings_overflow", 0)
        if n_ovf:
            alerts.append({
                "name": "shard_split_needed",
                "count": n_ovf,
                "hint": ("a shard hit the 2^31 stored-postings pack "
                         "limit (build.postings_overflow) — split the "
                         "collection across more shards before the "
                         "node boot-loops"),
            })
        # HBM headroom row from the device telemetry plane: ledger
        # total next to what memory_stats() reports (nulls on a CPU
        # backend / with devwatch off — the row still renders)
        from ..utils import devwatch
        rec = devwatch.reconcile()
        dev0 = rec["devices"][0] if rec["devices"] else {}
        hbm = {"enabled": devwatch.enabled(),
               "ledger_bytes": rec["ledger_bytes"],
               "bytes_in_use": dev0.get("bytes_in_use"),
               "headroom": dev0.get("headroom")}
        if query.get("format") == "json":
            body = {
                "hosts": {
                    a: None if w is None else {
                        k: LatencyStat.from_wire(v).to_dict()
                        for k, v in w.get("latencies", {}).items()}
                    for a, w in hosts.items()},
                "fleet": {
                    "counters": fleet["counters"],
                    "gauges": fleet["gauges"],
                    "latencies": {
                        k: {**st.to_dict(),
                            "exemplars": [
                                {"trace_id": tid, "ms": ms}
                                for _, (tid, ms)
                                in sorted(st.exemplars.items())]}
                        for k, st in fleet["latencies"].items()},
                },
                "slo": slo_status,
                "alerts": alerts,
                "hbm": hbm,
            }
            return 200, json.dumps(body), "application/json"

        pwd = query.get("pwd", "")
        sfx = f"&pwd={urllib.parse.quote(pwd)}" if pwd else ""
        lsfx = f"?pwd={urllib.parse.quote(pwd)}" if pwd else ""
        addrs = sorted(hosts)
        per_host = {
            a: {} if hosts[a] is None else {
                k: LatencyStat.from_wire(v)
                for k, v in hosts[a].get("latencies", {}).items()}
            for a in addrs}
        lat_rows = []
        for name in sorted(fleet["latencies"]):
            st = fleet["latencies"][name]
            cells = "".join(
                f"<td>{per_host[a][name].quantile(0.99):.2f}</td>"
                if name in per_host[a] else "<td>-</td>"
                for a in addrs)
            d = st.to_dict()
            ex = ""
            if st.exemplars:
                tid, _ms = st.exemplars[max(st.exemplars)]
                ex = (f' <a href="/admin/traces?id={tid}{sfx}">'
                      f"ex</a>")
            lat_rows.append(
                f"<tr><td>{name}</td>{cells}"
                f"<td>{d['count']}</td><td>{d['avg_ms']:.2f}</td>"
                f"<td>{d['p50_ms']:.2f}</td>"
                f"<td>{d['p99_ms']:.2f}{ex}</td>"
                f"<td>{d['max_ms']:.2f}</td></tr>")
        hdr = "".join(f"<th>{a} p99</th>" for a in addrs)

        def spark(metric: str, color: str) -> str:
            pts = [(t, m.get(metric))
                   for t, m in g_stats.series(last_s=600)
                   if m.get(metric) is not None]
            if len(pts) < 2:
                return ""
            t0, t1 = pts[0][0], pts[-1][0]
            span = max(t1 - t0, 1.0)
            top = max(v for _, v in pts) or 1.0
            xy = " ".join(f"{(t - t0) / span * 120:.1f},"
                          f"{28.0 - v / top * 24.0:.1f}"
                          for t, v in pts)
            return (f'<svg xmlns="http://www.w3.org/2000/svg" '
                    f'width="124" height="30">'
                    f'<polyline fill="none" stroke="{color}" '
                    f'points="{xy}"/></svg> {metric} (max {top:g})')

        slo_rows = "".join(
            f"<tr><td>{n}</td><td>{st['kind']}</td>"
            f"<td>{st['target']}</td>"
            f"<td>{st['window_total']}</td><td>{st['window_bad']}</td>"
            f"<td>{st['burn_rate']:.3f}</td>"
            f"<td>{st['budget_remaining']:.3f}</td>"
            f"<td>{'BURNING' if st['burning'] else 'ok'}</td></tr>"
            for n, st in sorted(slo_status.items())) \
            or "<tr><td colspan=8>no objectives declared</td></tr>"
        gauge_rows = "".join(
            f"<tr><td>{k}</td><td>{v:g}</td></tr>"
            for k, v in sorted(fleet["gauges"].items()))
        ctr_rows = "".join(
            f"<tr><td>{k}</td><td>{v}</td></tr>"
            for k, v in sorted(fleet["counters"].items()))
        up = sum(1 for w in hosts.values() if w is not None)
        alert_html = "".join(
            f'<p style="color:#fff;background:#c00;padding:6px">'
            f"ALERT {a['name']} (&times;{a['count']}): {a['hint']}</p>"
            for a in alerts)
        return 200, (
            "<html><head><title>gb perf</title></head><body>"
            "<h1>fleet perf</h1>"
            f"{alert_html}"
            f"<p>{up}/{len(hosts)} hosts scraped &middot; "
            f'<a href="/admin/perf?format=json{sfx}">json</a> &middot; '
            f'<a href="/metrics">metrics</a></p>'
            f"<p>HBM: ledger {hbm['ledger_bytes'] >> 20} MB &middot; "
            f"in use {hbm['bytes_in_use'] if hbm['bytes_in_use'] is not None else 'n/a'}"
            f" &middot; headroom "
            f"{hbm['headroom'] if hbm['headroom'] is not None else 'n/a'}"
            f" &middot; devwatch "
            f"{'on' if hbm['enabled'] else 'off'} &middot; "
            f'<a href="/admin/hbm{lsfx}">hbm</a> '
            f'<a href="/admin/device{lsfx}">device</a></p>'
            f"<p>{spark('qps', '#1f77b4')}<br>"
            f"{spark('p50_ms', '#d62728')}</p>"
            f"<h2>latencies (ms)</h2>"
            f"<table border=1><tr><th>metric</th>{hdr}"
            "<th>fleet n</th><th>avg</th><th>p50</th><th>p99</th>"
            f"<th>max</th></tr>{''.join(lat_rows)}</table>"
            "<h2>SLOs</h2>"
            "<table border=1><tr><th>objective</th><th>kind</th>"
            "<th>target</th><th>window n</th><th>bad</th>"
            "<th>burn rate</th><th>budget left</th><th></th></tr>"
            f"{slo_rows}</table>"
            f"<h2>gauges</h2><table border=1>{gauge_rows}</table>"
            f"<h2>counters</h2><table border=1>{ctr_rows}</table>"
            "</body></html>"), "text/html"

    def _metrics_text(self) -> str:
        """Prometheus-style text exposition of the merged fleet view.
        Histogram buckets carry OpenMetrics-style exemplar suffixes
        (``# {trace_id="..."} <ms>``) where a sampled trace landed in
        the bucket. Metric names ride in a ``name`` label so dotted
        internal names pass through unmangled."""
        from ..utils.stats import _bucket_bounds
        hosts, fleet = self._fleet_view()
        lines = [
            "# HELP osse_latency_ms merged fleet latency histogram (ms)",
            "# TYPE osse_latency_ms histogram",
        ]
        for name in sorted(fleet["latencies"]):
            st = fleet["latencies"][name]
            cum = 0
            for idx in sorted(st.buckets):
                cum += st.buckets[idx]
                hi = _bucket_bounds(idx)[1]
                line = (f'osse_latency_ms_bucket{{name="{name}",'
                        f'le="{hi:g}"}} {cum}')
                ex = st.exemplars.get(idx)
                if ex is not None:
                    line += f' # {{trace_id="{ex[0]}"}} {ex[1]:g}'
                lines.append(line)
            lines.append(f'osse_latency_ms_bucket{{name="{name}",'
                         f'le="+Inf"}} {st.count}')
            lines.append(f'osse_latency_ms_sum{{name="{name}"}} '
                         f"{st.total_ms:g}")
            lines.append(f'osse_latency_ms_count{{name="{name}"}} '
                         f"{st.count}")
        # per-tenant request outcomes as proper labels (the quota
        # plane's scrape surface), parsed back out of the dotted
        # admission.tenant.<t>.<outcome> counter namespace
        lines.append("# TYPE osse_tenant_requests_total counter")
        for k, v in sorted(fleet["counters"].items()):
            if not k.startswith("admission.tenant."):
                continue
            t, _, outcome = k[len("admission.tenant."):].rpartition(".")
            if t and outcome in ("served", "shed"):
                lines.append(
                    f'osse_tenant_requests_total{{tenant="{t}",'
                    f'outcome="{outcome}"}} {v}')
        lines.append("# TYPE osse_counter counter")
        lines.extend(f'osse_counter{{name="{k}"}} {v}'
                     for k, v in sorted(fleet["counters"].items()))
        lines.append("# TYPE osse_gauge gauge")
        lines.extend(f'osse_gauge{{name="{k}"}} {v:g}'
                     for k, v in sorted(fleet["gauges"].items()))
        # per-(collection, plane) device residency from the HBM
        # ledger (OSSE_DEVWATCH=1; empty rows when off) — the tenant
        # plane's byte-bounded residency, scrape-visible fleet-wide
        from ..utils import devwatch
        lines.append("# TYPE osse_hbm_bytes gauge")
        for c, planes in sorted(
                devwatch.g_devwatch.ledger_snapshot().items()):
            for p, cols in sorted(planes.items()):
                lines.append(f'osse_hbm_bytes{{collection="{c}",'
                             f'plane="{p}"}} {sum(cols.values())}')
        lines.append(f"osse_hosts_scraped "
                     f"{sum(1 for w in hosts.values() if w is not None)}")
        return "\n".join(lines) + "\n"

    def _page_transport(self, query: dict) -> tuple[int, str, str]:
        """Cluster transport health (the PagePerf slice of the
        Multicast/UdpServer role): per-peer connection pool + RTT
        EWMAs, hedge fired/won counters, connection reuse/dial/retry
        counts, and the hostmap's twin-preference state. JSON, like
        /admin/hosts and /admin/perf."""
        from ..parallel.transport import g_transport
        from ..utils.stats import g_stats
        body = g_stats.prefixed("transport.")
        tr = (self.cluster.transport if self.cluster is not None
              else g_transport)
        body["peers"] = tr.stats()
        if self.cluster is not None:
            hm = self.cluster.hostmap
            body["hostmap"] = {
                f"shard{s}": {
                    "twin_order": hm.twin_order(s),
                    "alive": [bool(a) for a in hm.alive[s]],
                    "rtt_ms": [round(1000.0 * float(v), 3)
                               for v in hm.rtt_s[s]],
                    "addrs": self.cluster.conf.addresses[s],
                } for s in range(hm.n_shards)}
        return 200, json.dumps(body), "application/json"

    def _page_cache(self, query: dict) -> tuple[int, str, str]:
        """The cache plane's admin page (the PageStats cache table
        role): every registered cache with entries/bytes/hit rate/
        generation, a per-cache flush link and a flush-all link.
        ``?format=json`` returns the raw snapshot + the ``cache.*``
        metric namespace; ``?flush=<name>`` / ``?flush=all`` flushes."""
        from ..cache import g_cacheplane
        from ..utils.stats import g_stats
        flush = query.get("flush", "")
        flushed = None
        if flush:
            flushed = g_cacheplane.flush(
                None if flush == "all" else flush)
        snap = g_cacheplane.snapshot()
        if query.get("format") == "json":
            body = {"caches": snap,
                    "enabled": g_cacheplane.enabled,
                    "metrics": g_stats.prefixed("cache.")}
            if flushed is not None:
                body["flushed_bytes"] = flushed
            return 200, json.dumps(body), "application/json"
        pwd = query.get("pwd", "")
        sfx = f"&pwd={urllib.parse.quote(pwd)}" if pwd else ""
        rows = "".join(
            f"<tr><td>{nm}</td><td>{st['entries']}</td>"
            f"<td>{st['bytes'] / (1 << 10):.1f}</td>"
            f"<td>{st['hits']}</td><td>{st['misses']}</td>"
            f"<td>{100.0 * st['hit_rate']:.1f}%</td>"
            f"<td>{st['evictions']}</td><td>{st['stale_served']}</td>"
            f"<td><code>{st['generation']}</code></td>"
            f"<td>{'on' if st['enabled'] else 'off'}</td>"
            f"<td><a href=\"/admin/cache?flush="
            f"{urllib.parse.quote(nm)}{sfx}\">flush</a></td></tr>"
            for nm, st in snap.items()) \
            or "<tr><td colspan=11>no registered caches</td></tr>"
        note = (f"<p>flushed {flushed} bytes</p>"
                if flushed is not None else "")
        return 200, (
            "<html><head><title>gb cache</title></head><body>"
            "<h1>cache plane</h1>"
            f"<p>plane {'enabled' if g_cacheplane.enabled else 'DISABLED'}"
            f" &middot; <a href=\"/admin/cache?flush=all{sfx}\">"
            "flush all</a></p>" + note +
            "<table border=1><tr><th>cache</th><th>entries</th>"
            "<th>KB</th><th>hits</th><th>misses</th><th>hit rate</th>"
            "<th>evict</th><th>stale</th><th>generation</th>"
            f"<th>enabled</th><th></th></tr>{rows}</table>"
            "</body></html>"), "text/html"

    def _page_jit(self, query: dict) -> tuple[int, str, str]:
        """Compile/retrace/transfer attribution from the jit watcher
        (OSSE_JITWATCH=1): every event keyed by (function,
        shape-signature, call-site), so a steady-state retrace or a
        hidden host sync names its line. ``?format=json`` returns the
        raw snapshot."""
        from ..utils import jitwatch
        from ..utils.stats import g_stats
        snap = jitwatch.snapshot()
        counters = g_stats.snapshot()["counters"]
        snap["counters"] = {k: v for k, v in sorted(counters.items())
                            if k.startswith("jit.")}
        if query.get("format") == "json":
            return 200, json.dumps(snap), "application/json"
        t = snap["totals"]
        rows = "".join(
            f"<tr><td>{e['kind']}</td><td>{e['fn']}</td>"
            f"<td>{e['site']}</td><td>{e['count']}</td>"
            f"<td>{e['bytes']}</td>"
            f"<td>{'yes' if e['boundary'] else 'NO'}</td>"
            f"<td>{e['shapes'] or e['last']}</td></tr>"
            for e in snap["events"]) \
            or "<tr><td colspan=7>none</td></tr>"
        return 200, (
            "<html><head><title>gb jit</title></head><body>"
            "<h1>jit plane</h1>"
            f"<p>watcher {'enabled' if snap['enabled'] else 'DISABLED'}"
            f" &middot; compiles {t['compiles']}"
            f" &middot; first traces {t['first_traces']}"
            f" &middot; retraces {t['retraces']}"
            f" &middot; transfers {t['transfers']}"
            f" (off-boundary {t['transfers_offboundary']})</p>"
            "<table border=1><tr><th>kind</th><th>fn</th><th>site</th>"
            "<th>count</th><th>bytes</th><th>boundary</th>"
            f"<th>detail</th></tr>{rows}</table>"
            "</body></html>"), "text/html"

    def _page_hbm(self, query: dict) -> tuple[int, str, str]:
        """HBM ledger (OSSE_DEVWATCH=1): every registered device
        buffer by (collection, plane, column), plane totals, and the
        reconciliation against ``device.memory_stats()`` — live bytes
        the ledger cannot name are allocator slack + unregistered
        temporaries (the fragmentation column). ``?format=json``
        returns the raw ledger."""
        from ..utils import devwatch
        snap = devwatch.snapshot()
        body = {k: snap[k] for k in ("enabled", "ledger", "planes",
                                     "collections", "total_bytes",
                                     "reconcile")}
        if query.get("format") == "json":
            return 200, json.dumps(body), "application/json"
        rows = "".join(
            f"<tr><td>{c}</td><td>{p}</td><td>{col}</td>"
            f"<td>{n}</td></tr>"
            for c, planes in sorted(snap["ledger"].items())
            for p, cols in sorted(planes.items())
            for col, n in sorted(cols.items())) \
            or "<tr><td colspan=4>none</td></tr>"
        dev_rows = "".join(
            f"<tr><td>{d['device']}</td><td>{d['kind']}</td>"
            f"<td>{d['bytes_in_use']}</td>"
            f"<td>{d['peak_bytes_in_use']}</td>"
            f"<td>{d['headroom']}</td>"
            f"<td>{d['fragmentation']}</td></tr>"
            for d in snap["reconcile"]["devices"]) \
            or "<tr><td colspan=6>no devices</td></tr>"
        planes = " &middot; ".join(
            f"{p}: {n >> 20} MB"
            for p, n in sorted(snap["planes"].items())) or "empty"
        return 200, (
            "<html><head><title>gb hbm</title></head><body>"
            "<h1>HBM ledger</h1>"
            f"<p>devwatch {'enabled' if snap['enabled'] else 'DISABLED'}"
            f" &middot; ledger {snap['total_bytes'] >> 20} MB"
            f" &middot; {planes}</p>"
            "<table border=1><tr><th>collection</th><th>plane</th>"
            f"<th>column</th><th>bytes</th></tr>{rows}</table>"
            "<h2>memory_stats reconciliation</h2>"
            "<table border=1><tr><th>device</th><th>kind</th>"
            "<th>bytes_in_use</th><th>peak</th><th>headroom</th>"
            f"<th>fragmentation</th></tr>{dev_rows}</table>"
            "</body></html>"), "text/html"

    def _page_device(self, query: dict) -> tuple[int, str, str]:
        """Wave flight recorder + roofline attribution
        (OSSE_DEVWATCH=1): the recorder ring's issue→wait→collect
        waterfall with per-round escalations, and the per-(kernel,
        shape-bucket) flops/bytes verdicts against the backend peaks.
        ``?format=json`` returns the raw ring + cost table."""
        from ..utils import devwatch
        snap = devwatch.snapshot()
        body = {k: snap[k] for k in ("enabled", "totals", "waves",
                                     "rooflines", "peaks")}
        if query.get("format") == "json":
            return 200, json.dumps(body), "application/json"
        waves = list(snap["waves"])[-64:]
        scale = max((w["total_s"] for w in waves), default=0.0) or 1e-9

        def bar(w):
            return "".join(
                f'<div style="display:inline-block;height:10px;'
                f'width:{max(1, int(300 * w[f] / scale))}px;'
                f'background:{c}"></div>'
                for f, c in (("issue_s", "#4c78a8"),
                             ("wait_s", "#eeca3b"),
                             ("collect_s", "#e45756")))
        rows = "".join(
            f"<tr><td>{w['seq']}</td><td>{w['source']}</td>"
            f"<td>{w.get('coll', '')}</td>"
            f"<td>{w.get('plans', w.get('tickets', ''))}</td>"
            f"<td>{w['total_s'] * 1000:.1f}</td><td>{bar(w)}</td>"
            f"<td>{len(w['rounds'])}</td>"
            f"<td>{sum(r.get('escalations', 0) for r in w['rounds'])}"
            f"</td><td>{w['error'] or ''}</td></tr>"
            for w in reversed(waves)) \
            or "<tr><td colspan=9>none</td></tr>"
        roof = "".join(
            f"<tr><td>{e['kernel']}</td><td>{e['bucket']}</td>"
            f"<td>{e['flops']:.3g}</td><td>{e['bytes']:.3g}</td>"
            f"<td>{e['intensity']:.2f}</td><td>{e['ridge']:.2f}</td>"
            f"<td>{e['verdict']}</td>"
            f"<td>{e['modeled_bytes'] or ''}</td>"
            f"<td>{e['dispatches']}</td></tr>"
            for e in snap["rooflines"]) \
            or "<tr><td colspan=9>none</td></tr>"
        pk = snap["peaks"]
        return 200, (
            "<html><head><title>gb device</title></head><body>"
            "<h1>device plane</h1>"
            f"<p>devwatch {'enabled' if snap['enabled'] else 'DISABLED'}"
            f" &middot; waves {snap['totals']['waves']}"
            f" &middot; rounds {snap['totals']['rounds']}"
            f" &middot; errors {snap['totals']['wave_errors']}"
            f" &middot; peaks {pk['label']}"
            f" ({pk['flops']:.3g} FLOP/s, {pk['bw']:.3g} B/s"
            f"{', assumed' if pk['assumed'] else ''})</p>"
            "<h2>wave waterfall (issue / wait / collect)</h2>"
            "<table border=1><tr><th>seq</th><th>source</th>"
            "<th>coll</th><th>plans</th><th>ms</th><th>split</th>"
            "<th>rounds</th><th>escalations</th><th>error</th></tr>"
            f"{rows}</table>"
            "<h2>roofline per (kernel, shape bucket)</h2>"
            "<table border=1><tr><th>kernel</th><th>bucket</th>"
            "<th>flops</th><th>bytes</th><th>intensity</th>"
            "<th>ridge</th><th>verdict</th><th>modeled bytes</th>"
            f"<th>dispatches</th></tr>{roof}</table>"
            "</body></html>"), "text/html"

    #: waterfall bar palette — one color per host, assigned by hash so
    #: the same host colors the same across traces
    _TRACE_COLORS = ("#4c78a8", "#f58518", "#54a24b", "#e45756",
                     "#72b7b2", "#b279a2", "#eeca3b", "#9d755d")

    def _page_traces(self, query: dict) -> tuple[int, str, str]:
        """Recent sampled traces + the slow-query log, with a per-trace
        waterfall (nested HTML bars, offsets/widths proportional to the
        span's place in the trace, colored by host/shard).

        ``?id=<trace_id>`` shows one trace; ``?format=json`` returns
        the raw ring + slowlog tail."""
        recent = g_tracer.recent()
        slowlog = g_tracer.slowlog_tail(50)
        if query.get("format") == "json":
            return 200, json.dumps(
                {"recent": recent, "slowlog": slowlog,
                 "sample_n": g_tracer.sample_n,
                 "slow_ms": g_tracer.slow_ms}), "application/json"
        tid = query.get("id", "")
        if tid:
            tr = g_tracer.find(tid) or next(
                (t for t in reversed(slowlog)
                 if t.get("trace_id") == tid), None)
            if tr is None:
                return 404, json.dumps({"error": "no such trace"}), \
                    "application/json"
            return 200, (
                "<html><head><title>trace</title></head><body>"
                f"{self._trace_waterfall(tr)}"
                '<p><a href="/admin/traces">all traces</a></p>'
                "</body></html>"), "text/html"
        blocks = "".join(self._trace_waterfall(t)
                         for t in reversed(recent[-20:]))
        slows = "".join(
            f'<tr><td><a href="/admin/traces?id='
            f'{html_mod.escape(str(t.get("trace_id", "")))}">'
            f'{html_mod.escape(str(t.get("trace_id", "")))}</a></td>'
            f'<td>{html_mod.escape(str((t.get("root") or {}).get("tags", {}).get("q", "")))}</td>'
            f'<td>{t.get("dur_ms", 0):.1f}</td></tr>'
            for t in reversed(slowlog)) \
            or "<tr><td colspan=3>empty</td></tr>"
        return 200, (
            "<html><head><title>gb traces</title></head><body>"
            "<h1>traces</h1>"
            f"<p>sampling 1/{g_tracer.sample_n} &middot; slow &ge; "
            f"{g_tracer.slow_ms:.0f} ms &middot; ring "
            f"{len(recent)}</p>"
            "<h2>slow queries (slowlog.jsonl)</h2>"
            "<table border=1><tr><th>trace</th><th>q</th>"
            f"<th>ms</th></tr>{slows}</table>"
            f"<h2>recent traces</h2>{blocks}"
            "</body></html>"), "text/html"

    def _trace_waterfall(self, tr: dict) -> str:
        """One trace → nested HTML bars. Bar offset/width are percent
        of the trace duration; color keys on the span's host."""
        total = max(float(tr.get("dur_ms", 0.0)), 1e-3)
        rows: list[str] = []

        def color(host: str) -> str:
            return self._TRACE_COLORS[hash(host) %
                                      len(self._TRACE_COLORS)]

        def walk(node: dict, depth: int) -> None:
            left = 100.0 * max(float(node.get("start_ms", 0.0)), 0.0) \
                / total
            width = min(100.0 - left,
                        100.0 * float(node.get("dur_ms", 0.0)) / total)
            host = str(node.get("host", ""))
            tags = node.get("tags") or {}
            tagstr = " ".join(f"{k}={v}" for k, v in tags.items())
            label = html_mod.escape(
                f"{node.get('name', '?')} {node.get('dur_ms', 0):.2f}ms"
                + (f" [{host}]" if host else "")
                + (f" {tagstr}" if tagstr else ""))
            rows.append(
                f'<div style="position:relative;height:16px;'
                f'margin-left:{depth * 12}px">'
                f'<div title="{label}" style="position:absolute;'
                f"left:{left:.2f}%;width:{max(width, 0.2):.2f}%;"
                f"height:14px;background:{color(host)};"
                f'overflow:hidden;font-size:10px;color:#fff;'
                f'white-space:nowrap">{label}</div></div>')
            for c in node.get("children", []):
                walk(c, depth + 1)

        root = tr.get("root") or {}
        walk(root, 0)
        head = (f'trace <b>{html_mod.escape(str(tr.get("trace_id")))}'
                f"</b> &middot; {total:.1f} ms"
                + (" &middot; <b>slow</b>" if tr.get("slow") else ""))
        return (f'<div style="border:1px solid #ccc;margin:8px;'
                f'padding:4px"><p>{head}</p>{"".join(rows)}</div>')

    def _page_profiler(self, query: dict) -> tuple[int, str, str]:
        """Per-stage timing table + on-demand SAMPLING profiler (the
        two halves of the Profiler.cpp role: the message-latency stats
        and the realtime stack sampler started/stopped from the admin
        page — ``startRealTimeProfiler``, ``Profiler.cpp:1586``).

        ``?sample=start|stop|reset`` controls the sampler;
        ``?sample=report`` (or format=json with the sampler running)
        returns the aggregated stack histogram."""
        from ..utils.profiler import g_profiler
        from ..utils.stats import g_stats
        action = query.get("sample", "")
        if action == "start":
            g_profiler.start()
            return 200, json.dumps({"sampling": True}), \
                "application/json"
        if action == "stop":
            g_profiler.stop()
            return 200, json.dumps(g_profiler.report()), \
                "application/json"
        if action == "reset":
            g_profiler.reset()
            return 200, json.dumps({"reset": True}), "application/json"
        if action == "report":
            return 200, json.dumps(g_profiler.report()), \
                "application/json"
        snap = g_stats.snapshot()
        if query.get("format") == "json":
            return 200, json.dumps(snap["latencies"]), "application/json"
        rows = "".join(
            f"<tr><td>{html_mod.escape(k)}</td><td>{v['count']}</td>"
            f"<td>{v['avg_ms']:.1f}</td><td>{v['p50_ms']:.1f}</td>"
            f"<td>{v['p99_ms']:.1f}</td><td>{v['max_ms']:.1f}</td></tr>"
            for k, v in sorted(snap["latencies"].items()))
        return 200, (
            "<html><head><title>profiler</title></head><body>"
            "<h1>stage timings (ms)</h1><table border=1>"
            "<tr><th>stage</th><th>n</th><th>avg</th><th>p50</th>"
            f"<th>p99</th><th>max</th></tr>{rows}</table>"
            "</body></html>"), "text/html"

    def _page_graph(self) -> str:
        """qps/latency time-series as inline SVG (PagePerf/Statsdb
        graphs without image deps)."""
        from ..utils.stats import g_stats
        series = g_stats.series(last_s=3600)
        w, h = 600, 160
        if not series:
            return (f'<svg xmlns="http://www.w3.org/2000/svg" '
                    f'width="{w}" height="{h}"><text x="10" y="20">'
                    f"no samples yet</text></svg>")
        t0, t1 = series[0][0], series[-1][0]
        span = max(t1 - t0, 1.0)

        def poly(metric: str, color: str) -> str:
            pts = [(t, m.get(metric)) for t, m in series
                   if m.get(metric) is not None]
            if not pts:
                return ""
            top = max(v for _, v in pts) or 1.0
            xy = " ".join(
                f"{10 + (t - t0) / span * (w - 20):.1f},"
                f"{h - 20 - v / top * (h - 40):.1f}" for t, v in pts)
            return (f'<polyline fill="none" stroke="{color}" '
                    f'points="{xy}"/>'
                    f'<text x="12" y="{h - 6}" fill="{color}" '
                    f'font-size="10">{metric} (max {top:.1f})</text>')
        return (f'<svg xmlns="http://www.w3.org/2000/svg" width="{w}" '
                f'height="{h}" style="background:#fff">'
                + poly("qps", "#1f77b4") + poly("p50_ms", "#d62728")
                + "</svg>")

    # --- statsdb persistence (Statsdb.cpp sample ring) -------------------

    def _sample_loop(self) -> None:
        from ..utils.stats import g_stats
        self._lines_written = 0
        last_q = self.stats["queries"]
        last_t = time.monotonic()
        while not self._stop_sampling.wait(10.0):
            now = time.monotonic()
            dq = self.stats["queries"] - last_q
            qps = dq / max(now - last_t, 1e-9)
            last_q, last_t = self.stats["queries"], now
            full = g_stats.snapshot()
            snap = full["latencies"].get("query.device_batch") or {}
            # guardrail counters ride the same sample ring so PagePerf
            # graphs budget pressure and check trips over time
            rejects = full["counters"].get("membudget.reject", 0)
            trips = full["counters"].get("devcheck.trip", 0)
            g_stats.sample(qps=round(qps, 2),
                           p50_ms=round(snap.get("p50_ms", 0.0), 1),
                           budget_rejects=rejects, check_trips=trips)
            # SLO tick: objectives consume the merged fleet stream on
            # a coordinator, the local registry otherwise; a scrape
            # failure costs one tick, never the sampler thread
            try:
                from ..utils.slo import g_slo
                if g_slo.objectives:
                    if self.cluster is not None:
                        fl = self.cluster.scrape()["fleet"]
                        g_slo.evaluate(fl["counters"],
                                       fl["latencies"])
                    else:
                        g_slo.evaluate()
            except Exception:  # noqa: BLE001 — keep sampling
                g_stats.count("slo.eval_errors")
            try:
                with open(self._statsdb_path, "a",
                          encoding="utf-8") as fh:
                    fh.write(json.dumps(
                        [time.time(), {"qps": round(qps, 2),
                                       "budget_rejects": rejects,
                                       "check_trips": trips}]) + "\n")
                self._lines_written += 1
                if self._lines_written >= 512:  # it IS a ring: rotate
                    tail = self._statsdb_path.read_text(
                        encoding="utf-8").splitlines()[-2000:]
                    self._statsdb_path.write_text(
                        "\n".join(tail) + "\n", encoding="utf-8")
                    self._lines_written = 0
            except OSError:
                pass

    def _load_statsdb(self) -> None:
        from ..utils.stats import g_stats
        if not self._statsdb_path.exists():
            return
        try:
            lines = self._statsdb_path.read_text(
                encoding="utf-8", errors="replace").splitlines()[-500:]
        except OSError:
            return
        # per-line tolerance: a kill-9 mid-append leaves ONE torn line;
        # it must cost one sample, not the whole ring
        for line in lines:
            if not line.strip():
                continue
            try:
                t, m = json.loads(line)
                g_stats.timeseries.append((float(t), m))
            except Exception:  # noqa: BLE001 — torn/corrupt line
                g_stats.count("statsdb.corrupt_lines")

    def _page_hosts(self) -> str:
        """Shard/cluster map (PageHosts.cpp)."""
        if self.sharded is None:
            return json.dumps({"shards": 1, "mode": "single"})
        hm = self.sharded.hostmap
        return json.dumps({
            "shards": hm.n_shards,
            "replicas": hm.n_replicas,
            "alive": hm.alive.tolist(),
            "docsPerShard": [c.num_docs for c in self.sharded.shards],
        })

    # --- lifecycle --------------------------------------------------------

    def start(self) -> None:
        from ..utils import devwatch, jitwatch
        jitwatch.maybe_enable()
        devwatch.maybe_enable()  # OSSE_DEVWATCH=1 arms the hbm plane
        chaos_mod.maybe_enable()  # OSSE_CHAOS=<seed> arms the plane
        # the ROADMAP traffic-plane objective, declared by default so
        # every server exports slo.query_p99.* from boot; operators
        # can declare richer objectives before start()
        from ..utils.slo import g_slo
        if not g_slo.objectives:
            g_slo.declare_latency(
                "query_p99",
                "cluster.query" if self.cluster is not None
                else "serve.search",
                threshold_ms=500.0, target=0.99)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # route to our logger
                log.debug("%s " + fmt, self.client_address[0], *args)

            def _serve(self, method: str):
                parsed = urllib.parse.urlsplit(self.path)
                query = dict(urllib.parse.parse_qsl(parsed.query))
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                try:
                    nice = int(self.headers.get("X-Niceness") or 0)
                except ValueError:
                    nice = 0
                # a scatter leg carries its coordinator's tier and
                # tenant verdicts
                tier = priority_mod.tier_from_header(
                    self.headers.get(priority_mod.PRIORITY_HEADER))
                tenant = priority_mod.tenant_from_header(
                    self.headers.get(priority_mod.TENANT_HEADER))
                status, payload, ctype = outer.handle(
                    method, parsed.path, query, body,
                    client_ip=self.client_address[0], niceness=nice,
                    tier=tier, tenant=tenant)
                data = payload.encode("utf-8")
                self.send_response(status)
                self.send_header("Content-Type", ctype + "; charset=utf-8")
                self.send_header("Content-Length", str(len(data)))
                # shed 503s stash Retry-After on the side channel
                # (handle() runs on this thread, so the contextvar set
                # inside it is visible here)
                for hname, hval in admission_mod.pop_response_headers():
                    self.send_header(hname, hval)
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                self._serve("GET")

            def do_POST(self):
                self._serve("POST")

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        # TLS plane (reference links -lssl and serves https off gb.pem,
        # TcpServer.cpp / Makefile:113): wrap the listening socket when
        # a cert is configured — same handler, same port semantics
        cert = getattr(self.conf, "ssl_cert", "") or ""
        if cert:
            import ssl as _ssl
            ctx = _ssl.SSLContext(_ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(
                cert, keyfile=getattr(self.conf, "ssl_key", "") or None)
            # handshake on first READ (in the per-connection handler
            # thread), NOT in accept(): a stalled ClientHello must not
            # block the single accept loop for every other client
            self._httpd.socket = ctx.wrap_socket(
                self._httpd.socket, server_side=True,
                do_handshake_on_connect=False)
            log.info("TLS enabled (cert=%s)", cert)
        self.port = self._httpd.server_address[1]  # resolve port 0
        g_tracer.configure(host=f"{self.host}:{self.port}")
        self._thread = threads.spawn(f"httpd-{self.port}",
                                     self._httpd.serve_forever)
        if not self._batcher.alive:  # stop()/start() cycle
            self._batcher = QueryBatcher(self._run_device_batch)
        self._load_statsdb()
        self._stop_sampling.clear()
        self._sampler = threads.spawn("statsdb", self._sample_loop)
        log.info("http server on %s:%d", self.host, self.port)

    def stop(self) -> None:
        self._stop_sampling.set()
        self._batcher.stop()
        # park every resident tenant with the batcher that fed it (the
        # residency manager keeps the records, so a start()/stop()
        # cycle cold-starts cleanly from the devcache base)
        from .tenancy import g_residency
        g_residency.stop_all()
        if self.sharded is not None:
            # mesh serving plane: stop its loop too (lazily respawned
            # by MeshResident.serve_loop on restart)
            mr = getattr(self.sharded, "_mesh_resident", None)
            if mr is not None:
                mr.stop()
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


def serve(base_dir, host: str = "127.0.0.1", port: int = 8000,
          sharded=None) -> SearchHTTPServer:
    s = SearchHTTPServer(base_dir, host, port, sharded=sharded)
    s.start()
    return s
