"""HTTP front end — search, cached pages, injection, admin.

Reference: ``HttpServer.cpp`` (nonblocking HTTP server) + ``Pages.cpp``
page table routing (``Pages.cpp:44,577``) + per-page handlers:
``PageResults.cpp`` (SERP in HTML/XML/JSON/CSV, ``PageResults.cpp:274``),
``PageGet.cpp`` (cached page w/ highlighting), ``PageInject.cpp``/
``PageAddUrl.cpp`` (content/url injection), ``PageStats``/``PageHosts``
(admin). Python stdlib threading server — the accept/parse plane is not
the bottleneck (queries are); a C++ front end can slot in front later
exactly like the reference's ``gb proxy`` mode.

Endpoints (reference query-string names kept: ``q``, ``n``, ``c``):

* ``GET /search?q=...&n=10&c=main&format=json|xml|html``
* ``GET /get?d=<docid>&q=...`` — cached page, query terms highlighted
* ``GET|POST /inject?u=<url>`` (body = content) — index a document
* ``GET /addurl?u=<url>`` — queue a url for the spider
* ``GET /admin/stats`` — counters; ``GET /admin/hosts`` — shard map
* ``GET /`` — minimal search form
"""

from __future__ import annotations

import html as html_mod
import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..index.collection import CollectionDb
from ..query import engine
from ..query.summary import highlight
from ..utils.log import get_logger

log = get_logger("http")


def _xml_escape(s: str) -> str:
    return html_mod.escape(s, quote=True)


def render_results(res: engine.SearchResults, fmt: str) -> tuple[str, str]:
    """SERP rendering (PageResults.cpp HTML/XML/JSON/CSV)."""
    if fmt == "json":
        return json.dumps({
            "query": res.query,
            "totalMatches": res.total_matches,
            "clustered": res.clustered,
            "suggestion": res.suggestion,
            "results": [
                {"docId": r.docid, "score": r.score, "url": r.url,
                 "title": r.title, "snippet": r.snippet, "site": r.site}
                for r in res.results
            ],
        }), "application/json"
    if fmt == "xml":
        rows = "".join(
            f"<result><docId>{r.docid}</docId>"
            f"<score>{r.score}</score>"
            f"<url>{_xml_escape(r.url)}</url>"
            f"<title>{_xml_escape(r.title)}</title>"
            f"<snippet>{_xml_escape(r.snippet)}</snippet></result>"
            for r in res.results)
        return (f'<?xml version="1.0" encoding="UTF-8"?>'
                f"<response><query>{_xml_escape(res.query)}</query>"
                f"<totalMatches>{res.total_matches}</totalMatches>"
                f"{rows}</response>", "text/xml")
    if fmt == "csv":
        lines = ["docid,score,url,title"]
        for r in res.results:
            t = r.title.replace('"', '""')
            lines.append(f'{r.docid},{r.score},"{r.url}","{t}"')
        return "\n".join(lines), "text/csv"
    # html
    items = "".join(
        f'<li><a href="{html_mod.escape(r.url)}">'
        f"{html_mod.escape(r.title) or html_mod.escape(r.url)}</a>"
        f"<br><small>{html_mod.escape(r.snippet)}</small>"
        f"<br><code>{html_mod.escape(r.url)}</code> "
        f"<i>{r.score:.1f}</i></li>"
        for r in res.results)
    return (f"<html><head><title>{html_mod.escape(res.query)} - search"
            f"</title></head><body>"
            f'<form action="/search"><input name="q" '
            f'value="{html_mod.escape(res.query)}"><input type="submit" '
            f'value="search"></form>'
            f"<p>{res.total_matches} matches</p><ol>{items}</ol>"
            f"</body></html>", "text/html")


class SearchHTTPServer:
    """Owns the collections + (optionally) a sharded index and serves the
    reference's public endpoints."""

    def __init__(self, base_dir, host: str = "127.0.0.1", port: int = 8000,
                 sharded=None, spider=None, cluster=None):
        self.colldb = CollectionDb(base_dir)
        self.sharded = sharded  # ShardedCollection | None (in-process mesh)
        self.cluster = cluster  # ClusterClient | None (multi-process plane)
        self.spider = spider    # spider queue hook (addurl)
        self.host = host
        self.port = port
        self.stats = {"queries": 0, "injects": 0, "addurls": 0,
                      "gets": 0, "errors": 0}
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        # the Rdb/MemTable/caches are single-writer structures (the
        # reference's whole core is single-threaded event-driven,
        # SURVEY §1); the threaded accept plane serializes at this lock
        self._lock = threading.RLock()

    # --- request handling -------------------------------------------------

    def handle(self, method: str, path: str, query: dict,
               body: bytes) -> tuple[int, str, str]:
        """Route one request → (status, payload, content_type).
        The Pages.cpp s_pages[] table, as a method."""
        try:
            if path == "/":
                return 200, self._page_root(), "text/html"
            with self._lock:
                return self._route(method, path, query, body)
        except Exception as e:  # noqa: BLE001 — server must not die
            self.stats["errors"] += 1
            log.warning("error handling %s: %s", path, e)
            return 500, json.dumps({"error": str(e)}), "application/json"

    def _route(self, method: str, path: str, query: dict,
               body: bytes) -> tuple[int, str, str]:
        if path == "/search":
            return self._page_search(query)
        if path == "/get":
            return self._page_get(query)
        if path == "/inject":
            return self._page_inject(query, body)
        if path == "/addurl":
            return self._page_addurl(query)
        if path == "/admin/stats":
            stats = dict(self.stats)
            # corrupt-run quarantine state (Msg5 error correction)
            q: dict[str, list] = {}
            if self.sharded is not None:
                for s, row in enumerate(self.sharded.grid):
                    for r, coll in enumerate(row):
                        for rn, rdb in coll.rdbs().items():
                            if rdb.quarantined:
                                q[f"shard{s}_r{r}:{rn}"] = rdb.quarantined
            elif self.colldb is not None:
                q = {f"{cn}:{rn}": rdb.quarantined
                     for cn in self.colldb.names()
                     for rn, rdb in self.colldb.get(cn).rdbs().items()
                     if rdb.quarantined}
            if q:
                stats["quarantined_runs"] = q
            return 200, json.dumps(stats), "application/json"
        if path == "/admin/hosts":
            return 200, self._page_hosts(), "application/json"
        if path == "/admin/perf":
            from ..utils.stats import g_stats
            return 200, json.dumps(g_stats.snapshot()), "application/json"
        if path == "/admin/parms":
            return self._page_parms(query)
        return 404, json.dumps({"error": "no such page"}), \
            "application/json"

    def _coll(self, query: dict):
        return self.colldb.get(query.get("c", "main"))

    def _page_root(self) -> str:
        return ('<html><body><form action="/search">'
                '<input name="q"><input type="submit" value="search">'
                "</form></body></html>")

    def _page_search(self, query: dict) -> tuple[int, str, str]:
        q = query.get("q", "")
        if not q:
            return 400, json.dumps({"error": "missing q"}), \
                "application/json"
        n = min(int(query.get("n", 10)), 100)
        fmt = query.get("format", "json")
        self.stats["queries"] += 1
        if self.cluster is not None:
            res = self.cluster.search(q, topk=n)
        elif self.sharded is not None:
            from ..parallel import sharded_search
            res = sharded_search(self.sharded, q, topk=n)
        else:
            res = engine.search(self._coll(query), q, topk=n)
        payload, ctype = render_results(res, fmt)
        return 200, payload, ctype

    def _page_get(self, query: dict) -> tuple[int, str, str]:
        """Cached page w/ optional highlight (PageGet.cpp)."""
        from ..build import docproc
        docid = int(query.get("d", "0"))
        self.stats["gets"] += 1
        if self.cluster is not None:
            rec = self.cluster.get_document(docid)
        elif self.sharded is not None:
            rec = self.sharded.get_document(docid)
        else:
            rec = docproc.get_document(self._coll(query), docid=docid)
        if rec is None:
            return 404, json.dumps({"error": "not found"}), \
                "application/json"
        content = rec.get("content", rec.get("text", ""))
        terms = [w for w in query.get("q", "").split() if w]
        if terms:
            content = highlight(content, terms,
                                pre='<span style="background:yellow">',
                                post="</span>")
        return 200, content, "text/html"

    def _page_inject(self, query: dict, body: bytes) -> tuple[int, str, str]:
        """Direct content injection (PageInject.cpp / msgtype 0x07)."""
        from ..build import docproc
        url = query.get("u") or query.get("url")
        if not url:
            return 400, json.dumps({"error": "missing u"}), \
                "application/json"
        content = body.decode("utf-8", "replace") if body else \
            query.get("content", "")
        self.stats["injects"] += 1
        if self.cluster is not None:
            docid = self.cluster.index_document(url, content)
            return 200, json.dumps({"docId": int(docid)}), \
                "application/json"
        if self.sharded is not None:
            ml = self.sharded.index_document(url, content)
        else:
            ml = docproc.index_document(self._coll(query), url, content)
        if ml is None:  # tagdb manualban (EDOCBANNED)
            return 403, json.dumps({"error": "banned by tagdb"}), \
                "application/json"
        return 200, json.dumps({"docId": ml.docid,
                                "numKeys": len(ml.posdb_keys)}), \
            "application/json"

    def _page_addurl(self, query: dict) -> tuple[int, str, str]:
        """Queue a url for spidering (PageAddUrl.cpp)."""
        url = query.get("u") or query.get("url")
        if not url:
            return 400, json.dumps({"error": "missing u"}), \
                "application/json"
        self.stats["addurls"] += 1
        if self.spider is None:
            return 503, json.dumps({"error": "spider not running"}), \
                "application/json"
        self.spider.add_url(url)
        return 200, json.dumps({"queued": url}), "application/json"

    def _page_parms(self, query: dict) -> tuple[int, str, str]:
        """Parameter view + live update via cgi names — the Parms URL api
        (``&maxmem=...``); updates fire the conf's on_update listeners
        (the 0x3f cluster-broadcast hook)."""
        from ..utils import parms as parms_mod
        coll = self._coll(query)
        updated = {}
        for cgi, value in query.items():
            if cgi in ("c",):
                continue
            for target in (coll.conf,):
                try:
                    target.set_from_cgi(cgi, value)
                    updated[cgi] = value
                    break
                except KeyError:
                    continue
        table = [{
            "name": p.name, "cgi": p.cgi, "type": p.type.__name__,
            "default": p.default, "scope": p.scope, "desc": p.desc,
        } for p in parms_mod.parm_table()]
        return 200, json.dumps({
            "updated": updated,
            "coll": coll.conf.to_dict(),
            "table": table,
        }), "application/json"

    def _page_hosts(self) -> str:
        """Shard/cluster map (PageHosts.cpp)."""
        if self.sharded is None:
            return json.dumps({"shards": 1, "mode": "single"})
        hm = self.sharded.hostmap
        return json.dumps({
            "shards": hm.n_shards,
            "replicas": hm.n_replicas,
            "alive": hm.alive.tolist(),
            "docsPerShard": [c.num_docs for c in self.sharded.shards],
        })

    # --- lifecycle --------------------------------------------------------

    def start(self) -> None:
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # route to our logger
                log.debug("%s " + fmt, self.client_address[0], *args)

            def _serve(self, method: str):
                parsed = urllib.parse.urlsplit(self.path)
                query = dict(urllib.parse.parse_qsl(parsed.query))
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                status, payload, ctype = outer.handle(
                    method, parsed.path, query, body)
                data = payload.encode("utf-8")
                self.send_response(status)
                self.send_header("Content-Type", ctype + "; charset=utf-8")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                self._serve("GET")

            def do_POST(self):
                self._serve("POST")

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]  # resolve port 0
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        log.info("http server on %s:%d", self.host, self.port)

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


def serve(base_dir, host: str = "127.0.0.1", port: int = 8000,
          sharded=None) -> SearchHTTPServer:
    s = SearchHTTPServer(base_dir, host, port, sharded=sharded)
    s.start()
    return s
