"""Tenant plane — device residency manager for 1k-collection serving.

Reference: Gigablast's product was "a custom search engine per
customer" — ``Collectiondb.cpp`` holds multi-tenant CollectionRecs,
each owning a per-collection RdbBase for every database, created by
``addColl`` and torn down by ``delColl``; the crawlbot API
(``PageCrawlBot.cpp``) minted one per REST job. The TPU port's analog
of an RdbBase is much more expensive: a hot collection owns an
HBM-resident :class:`~..query.devindex.DeviceIndex` plus an
always-running :class:`~..query.resident.ResidentLoop`, and before
this module nothing ever released either (engine.get_device_index /
get_resident_loop cached them on the Collection forever) — a few
hundred tenants would exhaust HBM long before the ~1k-collection
scale the ROADMAP asks for.

:class:`ResidencyManager` owns that lifecycle now:

* **LRU-with-pinning hot set.** Every resident tenant is tracked with
  a recency sequence; the set is sized two ways — a count bound
  (``max_resident``, the ``tenant_hot`` parm) and the membudget
  "device" label's soft cap (``set_label_cap``), which sums real
  ``resident_bytes()`` per tenant. ``pin()`` exempts a tenant from
  eviction (the "main" collection of a single-tenant box).
* **Cheap parked state.** Eviction stops the loop and drops the
  device arrays (the gauge goes to zero), but the HOST side of the
  packed columns survives in the DeviceIndex disk base cache
  (``posdb.dir/devcache/base_<fp>.npz``), so a cold start re-enters
  at transfer speed instead of repaying the O(corpus) repack.
* **Single-flight cold start.** Concurrent queries to a cold tenant
  trigger ONE build; riders wait on the leader's flight under their
  own deadline and shed (DeadlineExceeded → the serve edge's
  stale-or-504 ladder) if the budget burns first. The cold start
  itself runs under the caller's admitted token — the admission gate
  already sits in front of every serve-path query.
* **Pressure eviction.** The manager registers as a LOW-priority
  membudget pressure handler, so device pressure sheds cold tenants
  before the cache plane flushes and long before real work is
  refused (the shed-before-refuse ladder, one rung lower).

``/admin/tenants`` (serve/server.py) renders :meth:`snapshot`;
``BENCH_TENANTS=1`` (bench.py) drives a Zipf distribution over ~1k
collections against the gates in the ROADMAP item.
"""

from __future__ import annotations

import time
from collections import deque

from ..utils import deadline as deadline_mod
from ..utils import devwatch
from ..utils import trace as trace_mod
from ..utils.lockcheck import make_event, make_rlock
from ..utils.log import get_logger
from ..utils.membudget import g_membudget
from ..utils.stats import g_stats

log = get_logger("tenancy")

#: riders without a deadline bound their flight wait here (matches
#: Ticket.wait's default — a wedged build must not hang callers forever)
COLD_WAIT_S = 120.0

#: pressure-handler priority: BELOW the cache plane's default (100) so
#: cold tenants shed first — a parked tenant costs one transfer-speed
#: cold start; a flushed cache costs every hot SERP a recompute
PRESSURE_PRIORITY = 10


class _Tenant:
    """One collection's residency record."""

    __slots__ = ("name", "coll", "loop", "pinned", "parked", "seq",
                 "nbytes", "hits", "cold_starts", "promoted_at")

    def __init__(self, name: str, coll):
        self.name = name
        self.coll = coll
        self.loop = None
        self.pinned = False
        self.parked = False
        self.seq = 0
        self.nbytes = 0
        self.hits = 0
        self.cold_starts = 0
        self.promoted_at = 0.0


class _Flight:
    """A single-flight cold start: the leader builds, riders wait."""

    __slots__ = ("ev", "loop", "err")

    def __init__(self):
        self.ev = make_event("tenancy.flight")
        self.loop = None
        self.err: BaseException | None = None


class ResidencyManager:
    """Owns the collection → (DeviceIndex, ResidentLoop) hot set."""

    def __init__(self, max_resident: int = 0):
        #: count bound on the resident set; 0 = unbounded (the byte
        #: bound is the membudget "device" label cap, set separately)
        self.max_resident = int(max_resident)
        self._lock = make_rlock("tenancy.manager")
        self._tenants: dict[str, _Tenant] = {}
        self._flights: dict[str, _Flight] = {}
        self._seq = 0
        #: recent cold-start walls (ms) — /admin/tenants p99 and the
        #: BENCH_TENANTS bound read this, bounded so it never grows
        self.coldstart_ms: deque[float] = deque(maxlen=4096)

    # --- wiring -----------------------------------------------------------

    def configure(self, max_resident: int | None = None) -> None:
        """Live-update knobs (the tenant_hot parm hook)."""
        if max_resident is not None:
            with self._lock:
                self.max_resident = int(max_resident)

    def attach(self, budget=None) -> None:
        """(Re-)register the pressure handler — idempotent via the
        handler key, so server boots after a membudget reset() are
        safe."""
        (budget or g_membudget).add_pressure_handler(
            self._on_pressure, priority=PRESSURE_PRIORITY,
            key="tenancy")

    # --- the hot path -----------------------------------------------------

    def loop_for(self, coll, deadline=None):
        """The collection's ResidentLoop, promoting a cold tenant
        first (single-flight). This IS ``engine.get_resident_loop``
        now — the lifecycle the engine used to open-code lives here."""
        name = getattr(coll, "name", "coll")
        while True:
            stale = False
            with self._lock:
                t = self._tenants.get(name)
                if t is not None and t.coll is not None \
                        and t.coll is not coll:
                    # same name, different Collection OBJECT (deleted
                    # and recreated, or another registry): the record
                    # — and any live loop — belong to the OLD object;
                    # serving from it would alias tenants
                    stale = True
                elif t is not None and not t.parked \
                        and t.loop is not None and t.loop.alive:
                    self._seq += 1
                    t.seq = self._seq
                    t.hits += 1
                    g_stats.count("tenancy.hit")
                    return t.loop
                else:
                    fl = self._flights.get(name)
                    if fl is None:
                        fl = self._flights[name] = _Flight()
                        leader = True
                    else:
                        leader = False
            if stale:
                g_stats.count("tenancy.stale_record")
                self.release(name)  # outside the lock: park joins
                continue
            if leader:
                return self._promote(name, coll, fl)
            loop = self._ride(name, fl, deadline)
            if loop is not None:
                return loop
            # leader failed without a result (or the loop died between
            # flights): retake the fast path / a fresh flight

    def _ride(self, name: str, fl: _Flight, deadline):
        """Wait out another thread's cold start under OUR deadline —
        an expired rider sheds instead of queueing blind behind a
        build it can no longer use."""
        g_stats.count("tenancy.singleflight_join")
        budget = deadline_mod.Deadline.after(COLD_WAIT_S)
        if deadline is not None and deadline.at < budget.at:
            budget = deadline
        while not fl.ev.is_set():
            left = budget.remaining()
            if left <= 0:
                if deadline is not None and deadline.expired():
                    g_stats.count("tenancy.rider_shed")
                    raise deadline_mod.DeadlineExceeded(
                        f"deadline exceeded waiting for cold start "
                        f"of {name!r}")
                raise TimeoutError(
                    f"cold start of {name!r} timed out")
            fl.ev.wait(min(left, 0.5))
        if fl.err is not None:
            raise fl.err
        return fl.loop

    def _promote(self, name: str, coll, fl: _Flight):
        """The leader's cold start: build (or delta-refresh) the
        device base, spawn the loop, account the bytes, evict LRU
        tenants past the hot-set bounds."""
        from ..query import engine
        from ..query.resident import ResidentLoop
        t0 = time.perf_counter()
        try:
            di = engine.get_device_index(coll)
            loop = ResidentLoop(
                lambda: engine.get_device_index(coll),
                gen_fn=lambda: coll.posdb.version,
                name=name)
            coll._resident_loop = loop  # back-compat introspection
            # the HBM ledger (when on) is the source of truth behind
            # the membudget "device" label — it saw every column the
            # refresh registered; resident_bytes() is the always-on
            # fallback computing the same sum from shapes
            nbytes = int(devwatch.collection_bytes(name)
                         or di.resident_bytes())
            with self._lock:
                t = self._tenants.get(name)
                if t is None:
                    t = self._tenants[name] = _Tenant(name, coll)
                self._seq += 1
                t.coll = coll
                t.loop = loop
                t.parked = False
                t.seq = self._seq
                t.nbytes = nbytes
                t.cold_starts += 1
                t.promoted_at = time.time()
            t1 = time.perf_counter()
            self.coldstart_ms.append((t1 - t0) * 1000.0)
            g_stats.count("tenancy.coldstart")
            # trace.record feeds g_stats AND the caller's waterfall —
            # a rider-visible cold start must show up in the trace
            trace_mod.record("tenancy.coldstart", t0, t1, tenant=name)
            fl.loop = loop
            fl.ev.set()
            # OUTSIDE self._lock: the gauge can breach the device cap,
            # whose relief re-enters park() on this manager
            g_membudget.set_gauge("device", f"di:{name}", nbytes)
            self._evict_over_count(keep=name)
            return loop
        except BaseException as exc:
            fl.err = exc
            fl.ev.set()
            raise
        finally:
            with self._lock:
                self._flights.pop(name, None)

    # --- eviction / parking ----------------------------------------------

    def _evict_over_count(self, keep: str | None = None) -> None:
        """LRU-evict unpinned tenants past ``max_resident`` (the byte
        bound rides the membudget device cap instead)."""
        while True:
            with self._lock:
                if self.max_resident <= 0:
                    return
                resident = [t for t in self._tenants.values()
                            if not t.parked]
                if len(resident) <= self.max_resident:
                    return
                victims = [t for t in resident
                           if not t.pinned and t.name != keep]
                if not victims:
                    return
                victim = min(victims, key=lambda t: t.seq).name
            g_stats.count("tenancy.evict")
            self.park(victim)

    def park(self, name: str) -> int:
        """Demote to the cheap parked state: loop stopped, device
        buffers dropped (the jax arrays die with the DeviceIndex),
        host-side packed columns retained on disk by the devindex base
        cache so the next cold start skips the repack. Returns the
        freed device bytes."""
        with self._lock:
            t = self._tenants.get(name)
            if t is None or t.parked:
                return 0
            loop, freed = t.loop, t.nbytes
            t.loop = None
            t.parked = True
            t.nbytes = 0
        if loop is not None:
            loop.stop()
        coll = t.coll
        if coll is not None:
            coll._resident_loop = None
            coll._device_index = None  # device arrays GC → HBM freed
        g_membudget.set_gauge("device", f"di:{name}", 0)
        devwatch.drop(name)  # every plane: columns die with the index
        g_stats.count("tenancy.park")
        log.info("parked tenant %s (%d MB device)", name, freed >> 20)
        return freed

    def _on_pressure(self, need: int) -> int:
        """Membudget pressure: shed cold (least-recent, unpinned)
        tenants before anyone refuses work — or flushes a cache."""
        freed = 0
        while freed < int(need):
            with self._lock:
                victims = [t for t in self._tenants.values()
                           if not t.parked and not t.pinned
                           and t.loop is not None]
                if len(victims) > 1:
                    # spare the hottest tenant — parking the one most
                    # likely mid-request trades a shed for a failed
                    # query (and re-promotes next hit anyway)
                    victims.remove(max(victims, key=lambda t: t.seq))
                if not victims:
                    break
                victim = min(victims, key=lambda t: t.seq).name
            g_stats.count("tenancy.pressure_evict")
            got = self.park(victim)
            if got <= 0:
                break
            freed += got
        return freed

    def pin(self, name: str) -> None:
        """Exempt from eviction (never from release())."""
        with self._lock:
            t = self._tenants.get(name)
            if t is not None:
                t.pinned = True

    def unpin(self, name: str) -> None:
        with self._lock:
            t = self._tenants.get(name)
            if t is not None:
                t.pinned = False

    # --- teardown ---------------------------------------------------------

    def release(self, name: str) -> bool:
        """Full teardown for a DELETED collection (crawlbot delColl /
        the delete lifecycle fix): stop the loop, drop device buffers
        and the gauge, forget the tenant. Unlike park(), pinning does
        not protect — the collection is gone."""
        self.park(name)
        with self._lock:
            return self._tenants.pop(name, None) is not None

    def stop_all(self) -> None:
        """Server shutdown: park everything (records survive, so a
        start()/stop() cycle cold-starts cleanly)."""
        with self._lock:
            names = list(self._tenants)
        for n in names:
            self.park(n)

    def reset(self) -> None:
        """Test isolation: stop loops, drop all records and knobs."""
        self.stop_all()
        with self._lock:
            self._tenants.clear()
            self._flights.clear()
            self.max_resident = 0
            self.coldstart_ms.clear()

    # --- observability ----------------------------------------------------

    def resident_names(self) -> list[str]:
        with self._lock:
            return sorted(t.name for t in self._tenants.values()
                          if not t.parked)

    def snapshot(self) -> dict:
        cs = list(self.coldstart_ms)
        cs.sort()

        def pct(p: float) -> float:
            return round(cs[min(int(p * len(cs)), len(cs) - 1)], 3) \
                if cs else 0.0

        with self._lock:
            tenants = {
                t.name: {
                    "resident": not t.parked,
                    "pinned": t.pinned,
                    "device_bytes": t.nbytes,
                    "hits": t.hits,
                    "cold_starts": t.cold_starts,
                    "lru_seq": t.seq,
                } for t in self._tenants.values()}
            return {
                "max_resident": self.max_resident,
                "resident": sum(1 for t in self._tenants.values()
                                if not t.parked),
                "parked": sum(1 for t in self._tenants.values()
                              if t.parked),
                "device_cap": g_membudget.label_cap("device"),
                "device_bytes": g_membudget.used("device"),
                "coldstart_p50_ms": pct(0.50),
                "coldstart_p99_ms": pct(0.99),
                "coldstarts": len(cs),
                "tenants": tenants,
            }


#: process-wide singleton (the g_collectiondb analog for residency);
#: engine.get_resident_loop routes through it, SearchHTTPServer wires
#: its knobs from the parms and attach()es the pressure handler
g_residency = ResidencyManager()
