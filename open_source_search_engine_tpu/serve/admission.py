"""Admission plane — bounded, tiered, shed-before-refuse overload gate.

Reference: the engine survived open-internet traffic because Msg40 /
HttpServer degraded instead of collapsing — AutoBan rejected abusive
sources at the door, queries queued into Msg39 waves instead of
spawning unbounded work, cached answers went out when fresh ones were
over budget (``maxQueryTime``), and spider traffic yielded the niceness
bit. This module is that discipline for the device-serving planes: a
bounded gate in front of ``QueryBatcher``/``ResidentLoop`` that admits
by priority tier and sheds *cheaply* — a same-generation SWR-stale
answer marked degraded, else 503 + Retry-After — long before the
membudget has to refuse real work.

Shed triggers, in the order they are consulted:

1. the bounded queue is full (``admission.queue_full``) — an overload
   burst must never grow host memory without bound;
2. the SLO tracker reports a burning error budget (``slo.degraded``)
   or the membudget is out of headroom — background tiers shed at the
   door while the signal stands;
3. the *predicted* queue delay (waiters ahead x EWMA service time)
   would eat the request's deadline — shedding now is strictly cheaper
   than timing out later (the metastable-collapse preventer: work that
   cannot finish in time never enters the queue).

A gate can also be **drained** (``drain()``): every new arrival sheds
with reason ``draining`` while admitted work runs to completion —
the rolling-restart sequence the fleet plane uses (stop admitting,
``quiesce()`` until in-flight waves collect, checkpoint, then kill the
process; its twin absorbs the shed traffic via transport hedging).

The tier vocabulary (names, header, contextvar) lives in
``utils/priority.py`` so ``parallel/`` can stamp scatter legs without
importing the serve layer.
"""

from __future__ import annotations

import contextvars
import threading
import time
from collections import deque

from ..utils import deadline as deadline_mod
from ..utils import trace as trace_mod
from ..utils.membudget import g_membudget
from ..utils.priority import TIERS
from ..utils.slo import g_slo
from ..utils.stats import g_stats


class Shed(RuntimeError):
    """The gate refused this request. ``reason`` names the trigger
    (``queue_full``/``signal``/``deadline``/``timeout``);
    ``retry_after_s`` is the Retry-After hint for the 503 path."""

    def __init__(self, reason: str, retry_after_s: float = 1.0):
        super().__init__(f"admission shed: {reason}")
        self.reason = reason
        self.retry_after_s = max(float(retry_after_s), 1.0)


class _Admitted:
    """The held slot; a context manager so the release (and the
    service-time EWMA feeding the delay predictor) can't be skipped."""

    __slots__ = ("_gate", "_t0")

    def __init__(self, gate: "AdmissionGate"):
        self._gate = gate
        self._t0 = time.monotonic()

    def __enter__(self) -> "_Admitted":
        return self

    def __exit__(self, *exc) -> None:
        # monotonic delta = budget arithmetic for the predictor, not a
        # reported latency (those ride trace.record below)
        self._gate._release(time.monotonic() - self._t0)


class AdmissionGate:
    """Bounded admission in front of the device dispatch planes.

    ``max_inflight`` bounds concurrently *running* requests (past the
    gate, the QueryBatcher/ResidentLoop coalesce them into waves);
    ``max_queue`` bounds waiters across all tiers. Waiters wake in
    strict tier order — interactive first, FIFO within a tier — so a
    crawlbot burst can delay at most the wave in flight, never the
    queue ahead of a human."""

    def __init__(self, max_inflight: int = 32, max_queue: int = 256,
                 max_wait_s: float = 2.0,
                 degraded_fn=None, pressure_fn=None):
        self.max_inflight = int(max_inflight)
        self.max_queue = int(max_queue)
        self.max_wait_s = float(max_wait_s)
        #: overridable overload signals (tests aim them; defaults are
        #: the live SLO burn-rate and membudget headroom planes)
        self._degraded_fn = degraded_fn or (lambda: g_slo.degraded())
        self._pressure_fn = pressure_fn or self._mem_pressure
        self._cv = threading.Condition()
        self._inflight = 0
        self._draining = False
        self._waiting: dict[str, deque] = {t: deque() for t in TIERS}
        #: EWMA of admitted service time (s) — the queue-delay
        #: predictor's clock; seeded pessimistically so a cold gate
        #: sheds late rather than early
        self._svc_s = 0.020
        self.admitted_total = 0
        self.shed_total = 0

    @staticmethod
    def _mem_pressure() -> bool:
        # under ~6% headroom the next reserve is likely to refuse real
        # work — shed background traffic first (the OOM-merge-defer
        # ancestor: degrade cheap things before expensive things fail)
        return g_membudget.free() < (g_membudget.limit >> 4)

    # --- admission --------------------------------------------------------

    def admit(self, tier: str, deadline=None) -> _Admitted:
        """Admit or raise :class:`Shed`. Blocks (bounded by the
        request deadline and ``max_wait_s``) while the gate is full."""
        if tier not in TIERS:
            tier = "interactive"
        t_enq = time.perf_counter()
        with self._cv:
            if self._draining:
                # draining gates shed unconditionally — cheaper for the
                # caller to hedge to the twin than to queue behind a
                # node that is about to checkpoint and exit
                raise self._shed_locked(tier, "draining")
            n_wait = sum(len(q) for q in self._waiting.values())
            if n_wait >= self.max_queue:
                g_stats.count("admission.queue_full")
                raise self._shed_locked(tier, "queue_full")
            if tier != "interactive" and \
                    (self._degraded_fn() or self._pressure_fn()):
                # the cheap early shed: while the error budget burns or
                # memory headroom is gone, background tiers never enter
                raise self._shed_locked(tier, "signal")
            est = self._est_wait_locked(tier)
            if deadline is not None and (
                    deadline.expired() or est > deadline.remaining()):
                raise self._shed_locked(tier, "deadline")
            if self._inflight < self.max_inflight and \
                    not self._ahead_locked(tier):
                self._inflight += 1
                self.admitted_total += 1
            else:
                self._wait_locked(tier, deadline)
        g_stats.count("admission.admitted")
        trace_mod.record("admission.queue_delay", t_enq)
        return _Admitted(self)

    def _ahead_locked(self, tier: str) -> bool:
        """Any waiter at the same or higher priority? (FIFO within a
        tier; a new arrival never jumps its own class.)"""
        for t in TIERS:
            if self._waiting[t]:
                return True
            if t == tier:
                return False
        return False

    def _est_wait_locked(self, tier: str) -> float:
        """Predicted queue delay: slots drain at ``max_inflight`` per
        EWMA service time; this tier waits behind every same-or-higher
        waiter plus the waves in flight."""
        ahead = 0
        for t in TIERS:
            ahead += len(self._waiting[t])
            if t == tier:
                break
        backlog = ahead + self._inflight
        if backlog < self.max_inflight:
            return 0.0
        return (backlog / max(self.max_inflight, 1)) * self._svc_s

    def _wait_locked(self, tier: str, deadline) -> None:
        w = {"go": False}
        self._waiting[tier].append(w)
        g_stats.count("admission.queued")
        budget = deadline_mod.Deadline.after(self.max_wait_s)
        if deadline is not None and deadline.at < budget.at:
            budget = deadline
        while not w["go"] and not self._draining:
            left = budget.remaining()
            if left <= 0:
                break
            self._cv.wait(left)
        if not w["go"]:
            # grant pops under this lock, so un-granted => still queued
            self._waiting[tier].remove(w)
            if self._draining:
                raise self._shed_locked(tier, "draining")
            raise self._shed_locked(
                tier, "deadline" if deadline is not None
                and deadline.expired() else "timeout")
        self.admitted_total += 1  # _grant_locked took the slot for us

    def _shed_locked(self, tier: str, reason: str) -> Shed:
        self.shed_total += 1
        g_stats.count(f"admission.shed.reason.{reason}")
        retry = max(self._est_wait_locked(tier), self._svc_s)
        return Shed(reason, retry_after_s=retry)

    def _release(self, service_s: float) -> None:
        with self._cv:
            self._svc_s += 0.2 * (max(service_s, 0.0) - self._svc_s)
            self._inflight -= 1
            self._grant_locked()
            self._cv.notify_all()

    def _grant_locked(self) -> None:
        while self._inflight < self.max_inflight:
            w = None
            for t in TIERS:
                if self._waiting[t]:
                    w = self._waiting[t].popleft()
                    break
            if w is None:
                return
            w["go"] = True
            self._inflight += 1

    # --- drain (rolling-restart sequencing) -------------------------------

    def drain(self) -> None:
        """Stop admitting: new arrivals (and queued waiters) shed with
        reason ``draining``; work already admitted runs to completion.
        Idempotent."""
        with self._cv:
            self._draining = True
            self._cv.notify_all()
        g_stats.count("admission.drain")

    def resume(self) -> None:
        """Reopen a drained gate (operator aborted the restart)."""
        with self._cv:
            self._draining = False

    @property
    def draining(self) -> bool:
        return self._draining

    def quiesce(self, timeout_s: float = 10.0) -> bool:
        """Block until nothing is admitted and nothing waits — the
        let-in-flight-waves-collect step between ``drain()`` and the
        checkpoint. False if the gate did not empty in time."""
        dl = deadline_mod.Deadline.after(float(timeout_s))
        with self._cv:
            while self._inflight > 0 or any(
                    self._waiting[t] for t in TIERS):
                if dl.expired():
                    return False
                self._cv.wait(dl.clamp(0.05))
            return True

    # --- observability ----------------------------------------------------

    def snapshot(self) -> dict:
        with self._cv:
            return {
                "inflight": self._inflight,
                "draining": self._draining,
                "max_inflight": self.max_inflight,
                "max_queue": self.max_queue,
                "queued": {t: len(self._waiting[t]) for t in TIERS},
                "queued_total": sum(len(q)
                                    for q in self._waiting.values()),
                "svc_ewma_ms": round(self._svc_s * 1000.0, 3),
                "admitted_total": self.admitted_total,
                "shed_total": self.shed_total,
            }

    def idle(self) -> bool:
        """Nothing admitted and nothing waiting — the post-burst
        drained state the load harness polls for."""
        with self._cv:
            return self._inflight == 0 and not any(
                self._waiting[t] for t in TIERS)


# --- response-header side channel -----------------------------------------
# handle() returns (status, payload, ctype); the shed path needs to add
# Retry-After without widening that contract for every route. The gate
# stashes extra headers in a contextvar the HTTP handler drains on the
# same thread (direct handle() callers also get the value in the JSON
# body, so tests and bench never need the header channel).

_resp_headers: contextvars.ContextVar = contextvars.ContextVar(
    "osse-admission-resp-headers", default=None)


def set_response_header(name: str, value: str) -> None:
    cur = _resp_headers.get()
    if cur is None:
        cur = []
        _resp_headers.set(cur)
    cur.append((name, value))


def pop_response_headers() -> list:
    cur = _resp_headers.get()
    if cur:
        _resp_headers.set(None)
        return list(cur)
    return []
