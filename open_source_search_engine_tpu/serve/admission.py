"""Admission plane — bounded, tiered, shed-before-refuse overload gate.

Reference: the engine survived open-internet traffic because Msg40 /
HttpServer degraded instead of collapsing — AutoBan rejected abusive
sources at the door, queries queued into Msg39 waves instead of
spawning unbounded work, cached answers went out when fresh ones were
over budget (``maxQueryTime``), and spider traffic yielded the niceness
bit. This module is that discipline for the device-serving planes: a
bounded gate in front of ``QueryBatcher``/``ResidentLoop`` that admits
by priority tier and sheds *cheaply* — a same-generation SWR-stale
answer marked degraded, else 503 + Retry-After — long before the
membudget has to refuse real work.

Shed triggers, in the order they are consulted:

1. the bounded queue is full (``admission.queue_full``) — an overload
   burst must never grow host memory without bound;
2. the SLO tracker reports a burning error budget (``slo.degraded``)
   or the membudget is out of headroom — background tiers shed at the
   door while the signal stands;
3. the *predicted* queue delay (waiters ahead x EWMA service time)
   would eat the request's deadline — shedding now is strictly cheaper
   than timing out later (the metastable-collapse preventer: work that
   cannot finish in time never enters the queue);
4. the tenant is over its weighted-fair queue share while other
   tenants are active (``quota``) — the crawlbot-API story: one
   aggressive customer must not starve the others. Shares borrow when
   idle (a lone tenant may use the whole gate), and a full queue owes
   an under-share tenant room: the arrival displaces the newest waiter
   of an over-share tenant instead of shedding.

A gate can also be **drained** (``drain()``): every new arrival sheds
with reason ``draining`` while admitted work runs to completion —
the rolling-restart sequence the fleet plane uses (stop admitting,
``quiesce()`` until in-flight waves collect, checkpoint, then kill the
process; its twin absorbs the shed traffic via transport hedging).

The tier vocabulary (names, header, contextvar) lives in
``utils/priority.py`` so ``parallel/`` can stamp scatter legs without
importing the serve layer.
"""

from __future__ import annotations

import contextvars
import time
from collections import deque

from ..utils import deadline as deadline_mod
from ..utils import trace as trace_mod
from ..utils.lockcheck import make_condition
from ..utils.membudget import g_membudget
from ..utils.priority import TIERS
from ..utils.slo import g_slo
from ..utils.stats import g_stats


class Shed(RuntimeError):
    """The gate refused this request. ``reason`` names the trigger
    (``queue_full``/``signal``/``deadline``/``timeout``/``quota``);
    ``retry_after_s`` is the Retry-After hint for the 503 path."""

    def __init__(self, reason: str, retry_after_s: float = 1.0):
        super().__init__(f"admission shed: {reason}")
        self.reason = reason
        self.retry_after_s = max(float(retry_after_s), 1.0)


class _Admitted:
    """The held slot; a context manager so the release (and the
    service-time EWMA feeding the delay predictor) can't be skipped."""

    __slots__ = ("_gate", "_t0", "tenant")

    def __init__(self, gate: "AdmissionGate", tenant: str | None = None):
        self._gate = gate
        self.tenant = tenant
        self._t0 = time.monotonic()

    def __enter__(self) -> "_Admitted":
        return self

    def __exit__(self, *exc) -> None:
        # monotonic delta = budget arithmetic for the predictor, not a
        # reported latency (those ride trace.record below)
        self._gate._release(time.monotonic() - self._t0,
                            tenant=self.tenant)


class AdmissionGate:
    """Bounded admission in front of the device dispatch planes.

    ``max_inflight`` bounds concurrently *running* requests (past the
    gate, the QueryBatcher/ResidentLoop coalesce them into waves);
    ``max_queue`` bounds waiters across all tiers. Waiters wake in
    strict tier order — interactive first, FIFO within a tier — so a
    crawlbot burst can delay at most the wave in flight, never the
    queue ahead of a human."""

    def __init__(self, max_inflight: int = 32, max_queue: int = 256,
                 max_wait_s: float = 2.0,
                 degraded_fn=None, pressure_fn=None):
        self.max_inflight = int(max_inflight)
        self.max_queue = int(max_queue)
        self.max_wait_s = float(max_wait_s)
        #: overridable overload signals (tests aim them; defaults are
        #: the live SLO burn-rate and membudget headroom planes)
        self._degraded_fn = degraded_fn or (lambda: g_slo.degraded())
        self._pressure_fn = pressure_fn or self._mem_pressure
        self._cv = make_condition("admission.cv")
        self._inflight = 0
        self._draining = False
        self._waiting: dict[str, deque] = {t: deque() for t in TIERS}
        #: EWMA of admitted service time (s) — the queue-delay
        #: predictor's clock; seeded pessimistically so a cold gate
        #: sheds late rather than early
        self._svc_s = 0.020
        self.admitted_total = 0
        self.shed_total = 0
        #: the tier × tenant weighted-fair ledger; callers passing
        #: ``tenant=None`` bypass it entirely (legacy behavior)
        self._t_weight: dict[str, float] = {}
        self._t_inflight: dict[str, int] = {}
        self._t_queued: dict[str, int] = {}
        self._t_served: dict[str, int] = {}
        self._t_shed: dict[str, int] = {}

    @staticmethod
    def _mem_pressure() -> bool:
        # under ~6% headroom the next reserve is likely to refuse real
        # work — shed background traffic first (the OOM-merge-defer
        # ancestor: degrade cheap things before expensive things fail)
        return g_membudget.free() < (g_membudget.limit >> 4)

    # --- admission --------------------------------------------------------

    def admit(self, tier: str, deadline=None,
              tenant: str | None = None) -> _Admitted:
        """Admit or raise :class:`Shed`. Blocks (bounded by the
        request deadline and ``max_wait_s``) while the gate is full.
        ``tenant`` opts the request into the weighted-fair ledger: a
        tenant over its queue share sheds with reason ``quota`` while
        other tenants contend (shares borrow when idle, so a lone
        tenant may use the whole gate)."""
        if tier not in TIERS:
            tier = "interactive"
        t_enq = time.perf_counter()
        with self._cv:
            if self._draining:
                # draining gates shed unconditionally — cheaper for the
                # caller to hedge to the twin than to queue behind a
                # node that is about to checkpoint and exit
                raise self._shed_locked(tier, "draining", tenant)
            if tenant is not None:
                self._t_weight.setdefault(tenant, 1.0)
            n_wait = sum(len(q) for q in self._waiting.values())
            if n_wait >= self.max_queue:
                # a full queue still owes an under-share tenant room:
                # displace an over-share tenant's newest waiter rather
                # than shed the quiet arrival (the fairness half of
                # queue_full)
                if tenant is None or not self._displace_locked(tenant):
                    g_stats.count("admission.queue_full")
                    raise self._shed_locked(tier, "queue_full", tenant)
            if tier != "interactive" and \
                    (self._degraded_fn() or self._pressure_fn()):
                # the cheap early shed: while the error budget burns or
                # memory headroom is gone, background tiers never enter
                raise self._shed_locked(tier, "signal", tenant)
            est = self._est_wait_locked(tier)
            if deadline is not None and (
                    deadline.expired() or est > deadline.remaining()):
                raise self._shed_locked(tier, "deadline", tenant)
            if self._inflight < self.max_inflight and \
                    not self._ahead_locked(tier):
                self._inflight += 1
                self.admitted_total += 1
                if tenant is not None:
                    self._t_inflight[tenant] = \
                        self._t_inflight.get(tenant, 0) + 1
            else:
                # the queue path is where shares bind: an over-share
                # tenant sheds at the door instead of eating a slot a
                # quieter tenant is owed
                if tenant is not None and \
                        self._t_queued.get(tenant, 0) + 1 > \
                        self._share_locked(tenant):
                    raise self._shed_locked(tier, "quota", tenant)
                self._wait_locked(tier, deadline, tenant)
            if tenant is not None:
                self._t_served[tenant] = \
                    self._t_served.get(tenant, 0) + 1
        g_stats.count("admission.admitted")
        if tenant is not None:
            g_stats.count(f"admission.tenant.{tenant}.served")
        trace_mod.record("admission.queue_delay", t_enq)
        return _Admitted(self, tenant)

    def set_tenant_weight(self, tenant: str, weight: float) -> None:
        """Relative fair-share weight (default 1.0; <= 0 resets)."""
        with self._cv:
            self._t_weight[tenant] = \
                float(weight) if weight > 0 else 1.0

    def _share_locked(self, tenant: str,
                      extra: str | None = None) -> float:
        """The tenant's fair queue share: ``max_queue`` split by
        weight across *active* tenants (inflight or queued, plus the
        arrival). Idle tenants donate their share — a lone tenant gets
        the whole queue; everyone is owed at least one slot. ``extra``
        counts a not-yet-queued arrival as active (displacement asks
        for the VICTIM's share as it will be once the arrival joins)."""
        active = {t for t, n in self._t_inflight.items() if n > 0}
        active.update(t for t, n in self._t_queued.items() if n > 0)
        active.add(tenant)
        if extra is not None:
            active.add(extra)
        if len(active) <= 1:
            return float("inf")
        total_w = sum(self._t_weight.get(t, 1.0) for t in active)
        return max(self.max_queue
                   * self._t_weight.get(tenant, 1.0)
                   / max(total_w, 1e-9), 1.0)

    def _displace_locked(self, tenant: str) -> bool:
        """queue_full fairness: when the arriving tenant is under its
        share, evict an over-share tenant's newest waiter (lowest tier
        first) with reason ``quota`` to make room. False leaves the
        arrival to shed ``queue_full`` itself."""
        if self._t_queued.get(tenant, 0) + 1 > \
                self._share_locked(tenant):
            return False
        for t in reversed(TIERS):
            q = self._waiting[t]
            for i in range(len(q) - 1, -1, -1):
                victim = q[i]
                vt = victim.get("tenant")
                if vt is None or vt == tenant:
                    continue
                if self._t_queued.get(vt, 0) > \
                        self._share_locked(vt, extra=tenant):
                    del q[i]
                    self._t_queued[vt] = self._t_queued.get(vt, 1) - 1
                    victim["shed"] = "quota"
                    self._cv.notify_all()  # its thread raises on wake
                    return True
        return False

    def _ahead_locked(self, tier: str) -> bool:
        """Any waiter at the same or higher priority? (FIFO within a
        tier; a new arrival never jumps its own class.)"""
        for t in TIERS:
            if self._waiting[t]:
                return True
            if t == tier:
                return False
        return False

    def _est_wait_locked(self, tier: str) -> float:
        """Predicted queue delay: slots drain at ``max_inflight`` per
        EWMA service time; this tier waits behind every same-or-higher
        waiter plus the waves in flight."""
        ahead = 0
        for t in TIERS:
            ahead += len(self._waiting[t])
            if t == tier:
                break
        backlog = ahead + self._inflight
        if backlog < self.max_inflight:
            return 0.0
        return (backlog / max(self.max_inflight, 1)) * self._svc_s

    def _wait_locked(self, tier: str, deadline,
                     tenant: str | None = None) -> None:
        w = {"go": False, "tenant": tenant, "shed": None}
        self._waiting[tier].append(w)
        if tenant is not None:
            self._t_queued[tenant] = self._t_queued.get(tenant, 0) + 1
        g_stats.count("admission.queued")
        budget = deadline_mod.Deadline.after(self.max_wait_s)
        if deadline is not None and deadline.at < budget.at:
            budget = deadline
        while not w["go"] and w["shed"] is None and not self._draining:
            left = budget.remaining()
            if left <= 0:
                break
            self._cv.wait(left)
        if w["shed"] is not None:
            # displaced by an under-share arrival; the displacer
            # already removed us from the queue and the ledger
            raise self._shed_locked(tier, w["shed"], tenant)
        if not w["go"]:
            # grant pops under this lock, so un-granted => still queued
            self._waiting[tier].remove(w)
            if tenant is not None:
                self._t_queued[tenant] = \
                    self._t_queued.get(tenant, 1) - 1
            if self._draining:
                raise self._shed_locked(tier, "draining", tenant)
            raise self._shed_locked(
                tier, "deadline" if deadline is not None
                and deadline.expired() else "timeout", tenant)
        self.admitted_total += 1  # _grant_locked took the slot for us

    def _shed_locked(self, tier: str, reason: str,
                     tenant: str | None = None) -> Shed:
        self.shed_total += 1
        g_stats.count(f"admission.shed.reason.{reason}")
        if tenant is not None:
            self._t_shed[tenant] = self._t_shed.get(tenant, 0) + 1
            g_stats.count(f"admission.tenant.{tenant}.shed")
        retry = max(self._est_wait_locked(tier), self._svc_s)
        return Shed(reason, retry_after_s=retry)

    def _release(self, service_s: float,
                 tenant: str | None = None) -> None:
        with self._cv:
            self._svc_s += 0.2 * (max(service_s, 0.0) - self._svc_s)
            self._inflight -= 1
            if tenant is not None:
                n = self._t_inflight.get(tenant, 1) - 1
                if n <= 0:
                    self._t_inflight.pop(tenant, None)
                else:
                    self._t_inflight[tenant] = n
            self._grant_locked()
            self._cv.notify_all()

    def _grant_locked(self) -> None:
        while self._inflight < self.max_inflight:
            w = None
            for t in TIERS:
                q = self._waiting[t]
                if not q:
                    continue
                # weighted-fair within the tier: wake the waiter whose
                # tenant holds the least inflight per unit weight
                # (strict < keeps FIFO on ties, and all-legacy queues
                # — tenant None, load 0 — degenerate to pure FIFO)
                best_i, best = 0, None
                for i, cand in enumerate(q):
                    ct = cand.get("tenant")
                    load = 0.0 if ct is None else (
                        self._t_inflight.get(ct, 0)
                        / self._t_weight.get(ct, 1.0))
                    if best is None or load < best:
                        best, best_i = load, i
                w = q[best_i]
                del q[best_i]
                break
            if w is None:
                return
            w["go"] = True
            self._inflight += 1
            wt = w.get("tenant")
            if wt is not None:
                self._t_queued[wt] = self._t_queued.get(wt, 1) - 1
                self._t_inflight[wt] = self._t_inflight.get(wt, 0) + 1

    # --- drain (rolling-restart sequencing) -------------------------------

    def drain(self) -> None:
        """Stop admitting: new arrivals (and queued waiters) shed with
        reason ``draining``; work already admitted runs to completion.
        Idempotent."""
        with self._cv:
            self._draining = True
            self._cv.notify_all()
        g_stats.count("admission.drain")

    def resume(self) -> None:
        """Reopen a drained gate (operator aborted the restart)."""
        with self._cv:
            self._draining = False

    @property
    def draining(self) -> bool:
        return self._draining

    def quiesce(self, timeout_s: float = 10.0) -> bool:
        """Block until nothing is admitted and nothing waits — the
        let-in-flight-waves-collect step between ``drain()`` and the
        checkpoint. False if the gate did not empty in time."""
        dl = deadline_mod.Deadline.after(float(timeout_s))
        with self._cv:
            while self._inflight > 0 or any(
                    self._waiting[t] for t in TIERS):
                if dl.expired():
                    return False
                self._cv.wait(dl.clamp(0.05))
            return True

    # --- observability ----------------------------------------------------

    def snapshot(self) -> dict:
        with self._cv:
            return {
                "inflight": self._inflight,
                "draining": self._draining,
                "max_inflight": self.max_inflight,
                "max_queue": self.max_queue,
                "queued": {t: len(self._waiting[t]) for t in TIERS},
                "queued_total": sum(len(q)
                                    for q in self._waiting.values()),
                "svc_ewma_ms": round(self._svc_s * 1000.0, 3),
                "admitted_total": self.admitted_total,
                "shed_total": self.shed_total,
                "tenants": {
                    t: {
                        "weight": self._t_weight.get(t, 1.0),
                        "inflight": self._t_inflight.get(t, 0),
                        "queued": self._t_queued.get(t, 0),
                        "served": self._t_served.get(t, 0),
                        "shed": self._t_shed.get(t, 0),
                    }
                    for t in sorted(set(self._t_weight)
                                    | set(self._t_served)
                                    | set(self._t_shed))
                },
            }

    def idle(self) -> bool:
        """Nothing admitted and nothing waiting — the post-burst
        drained state the load harness polls for."""
        with self._cv:
            return self._inflight == 0 and not any(
                self._waiting[t] for t in TIERS)


# --- response-header side channel -----------------------------------------
# handle() returns (status, payload, ctype); the shed path needs to add
# Retry-After without widening that contract for every route. The gate
# stashes extra headers in a contextvar the HTTP handler drains on the
# same thread (direct handle() callers also get the value in the JSON
# body, so tests and bench never need the header channel).

_resp_headers: contextvars.ContextVar = contextvars.ContextVar(
    "osse-admission-resp-headers", default=None)


def set_response_header(name: str, value: str) -> None:
    cur = _resp_headers.get()
    if cur is None:
        cur = []
        _resp_headers.set(cur)
    cur.append((name, value))


def pop_response_headers() -> list:
    cur = _resp_headers.get()
    if cur:
        _resp_headers.set(None)
        return list(cur)
    return []
