"""PostQueryRerank — final demotion pass over the gathered top results.

Reference: ``PostQueryRerank.cpp`` (Msg40 runs it over the first
``m_pqr_docsToScan`` results after the merge): a set of multiplicative
*demotion factors* — foreign language/country, query terms appearing
only as a subphrase, too many results from one site/domain, paywall-ish
urls, etc. — each controlled by a per-collection parm, rescales the
final scores and the page is re-sorted.

Ours keeps the same shape (multiplicative factors over the merged top
page, host-side — the candidates are ≤ a page, so this is list work,
not device work) with the three demotions that still carry their
weight on a modern corpus:

* ``pqr_lang`` — foreign-language demotion beyond the kernel's
  SAMELANGMULT boost (reference m_pqr_demFactForeignLanguage);
* ``pqr_site`` — the k-th result from one registrable domain demotes
  geometrically (m_pqr_demFactSubPhrase family's diversity role —
  softer than Msg51's hard 2-per-site clustering, and applied even
  when clustering is off);
* ``pqr_paths`` — deep-path urls demote slightly when scores are
  close (m_pqr_demFactPageSize/QualityScore spirit: prefer canonical
  pages over deep leaf urls at equal relevance).

Factors are in (0, 1]; 1.0 disables a rule. Stable re-sort preserves
the original order for untouched results.
"""

from __future__ import annotations

from ..utils import trace
from ..utils.url import normalize


def post_query_rerank(results, qlang: int = 0, *,
                      lang_demote: float = 0.8,
                      site_demote: float = 0.85,
                      depth_demote: float = 0.97,
                      langid_of=None) -> int:
    """Rescale ``results`` (list of engine.Result) in place and stably
    re-sort by the adjusted scores. Returns how many results moved.

    ``langid_of``: optional docid → langid lookup (clusterdb column);
    without it the language rule is skipped — the titlerec fetch isn't
    worth it for a demotion."""
    if not results:
        return 0
    orig_order = [r.docid for r in results]
    per_domain: dict[str, int] = {}
    for r in results:
        f = 1.0
        try:
            u = normalize(r.url)
            dom = u.domain
            depth = max(len([s for s in u.path.split("/") if s]) - 1, 0)
        except Exception:  # noqa: BLE001 — junk urls stay untouched
            dom, depth = "", 0
        if dom:
            seen = per_domain.get(dom, 0)
            per_domain[dom] = seen + 1
            if seen:  # 2nd result of a domain × f, 3rd × f², ...
                f *= site_demote ** seen
        if depth:
            f *= depth_demote ** min(depth, 4)
        if langid_of is not None and qlang:
            dl = langid_of(r.docid)
            if dl and dl != qlang:
                f *= lang_demote
        r.score *= f
    results.sort(key=lambda r: -r.score)  # timsort: stable for ties
    moved = sum(1 for r, d in zip(results, orig_order) if r.docid != d)
    trace.tag(moved=moved)
    return moved
