"""Resident serving loop — double-buffered device dispatch.

BENCH_r04 pinned single-query p50 at the ~117ms dispatch+fetch RTT:
every ``search_batch`` call paid a full issue→block round trip even
when the device was idle half the time. This loop breaks that floor
the way the reference's UdpServer loop did for network I/O — ONE
always-running consumer owns the device, callers only enqueue:

* ``submit()`` appends to a queue and returns a :class:`Ticket`; it
  never touches jax (no host↔device traffic on the caller's thread —
  the osselint ``device-sync`` rule fences this file).
* The loop thread issues wave N+1 (``DeviceIndex.issue_batch``: plan,
  route, async dispatch — no fetch) while wave N is still computing,
  then collects the oldest in-flight wave (``collect_batch``: the one
  ``device_get`` + escalation reissues). Steady-state dispatch cost is
  one async enqueue; the host sync overlaps the next wave's compute.
* Depth is bounded at :data:`DEPTH` so a burst cannot pipeline
  unbounded device memory.

Freshness protocol (the generation rule the tests pin down): the loop
re-resolves its DeviceIndex via ``di_fn`` ONLY while nothing is in
flight. If ``gen_fn()`` (the Rdb version) moves while waves are in
flight, those waves finish against the base they were issued on — but
the loop drains them all BEFORE refreshing, so any ticket submitted
after the write is guaranteed to be issued against a refreshed base
(``Ticket.generation`` records which). Refreshing mid-flight would be
worse than stale: ``refresh()`` donates the packed buffers a dispatched
wave is still reading.

The issue/collect split is mesh-aware by construction: the loop is
generic over any index duck-typing ``issue_batch(plans, topk, lang) →
pending`` / ``collect_batch(pending)`` / ``_built_version`` (any
equality-comparable value). The single-chip plane drives a
``DeviceIndex``; the mesh serving plane drives a
:class:`~..parallel.sharded.MeshServeIndex`, whose issue dispatches ONE
``shard_map`` program across all chips per ticket wave and whose
generation is the (corpus, serving-topology, per-twin version) tuple —
so a twin death rides the same drain-before-refresh protocol: in-flight
waves finish on the base they were packed from, the next wave packs
from the surviving twin, and no ticket is ever lost to a failover.
"""

from __future__ import annotations

from collections import deque
from typing import Callable

from ..utils import deadline as deadline_mod
from ..utils import devwatch
from ..utils import threads as _threads
from ..utils.chaos import g_chaos
from ..utils.lockcheck import make_condition, make_event
from ..utils.log import get_logger
from ..utils.membudget import g_membudget
from ..utils.priority import QueueFull
from ..utils.stats import g_stats

log = get_logger("resident")

#: in-flight wave bound: issue N+1 while N computes (double-buffer);
#: deeper pipelines buy nothing once the device is saturated and cost
#: HBM for every staged wave
DEPTH = 2

#: bounded submit queue (admission plane): an overload burst fails
#: fast with QueueFull — counted, charged to the membudget "serve"
#: label — instead of growing host memory without bound
MAX_QUEUE = 1024

#: per-ticket footprint estimate for the membudget gauge (plans list +
#: ticket slots + event)
QUEUE_ENTRY_COST = 2048


class Ticket:
    """One submit()'s handle: wait() blocks until the loop resolves it.

    After resolution, ``di`` is the index the wave actually ran
    against (DeviceIndex or MeshServeIndex) and ``generation`` its
    ``_built_version`` at issue time — callers use ``di`` for
    post-processing (sitehash/langid lookups must come from the same
    snapshot that scored)."""

    __slots__ = ("plans", "topk", "lang", "deadline", "di",
                 "generation", "_ev", "_res", "_err")

    def __init__(self, plans, topk: int, lang: int, deadline=None):
        self.plans = plans
        self.topk = topk
        self.lang = lang
        self.deadline = deadline
        self.di = None
        self.generation: int | None = None
        self._ev = make_event("resident.ticket")
        self._res = None
        self._err: BaseException | None = None

    def _resolve(self, res) -> None:
        self._res = res
        self._ev.set()

    def _fail(self, err: BaseException) -> None:
        self._err = err
        self._ev.set()

    def wait(self, timeout: float = 120.0):
        """Block for the wave's raw results ([(docids, scores, n)] per
        plan). Raises the loop's error if the wave failed."""
        if not self._ev.wait(timeout):
            raise TimeoutError("resident loop ticket timed out")
        if self._err is not None:
            raise self._err
        return self._res


class _Wave:
    """An issued-but-uncollected wave and the tickets riding it.
    ``obs`` is the devwatch flight-recorder record opened at issue
    (None when the telemetry plane is off)."""

    __slots__ = ("pending", "tickets", "di", "obs")

    def __init__(self, pending, tickets, di, obs=None):
        self.pending = pending
        self.tickets = tickets
        self.di = di
        self.obs = obs


class ResidentLoop:
    """The per-collection dispatch loop (see module docstring).

    ``di_fn`` resolves the current DeviceIndex (and refreshes it when
    the Rdb moved — ``engine.get_device_index``); ``gen_fn`` reads the
    live Rdb version so the loop can detect a mid-flight write without
    touching the index."""

    def __init__(self, di_fn: Callable[[], object],
                 gen_fn: Callable[[], int],
                 max_batch: int = 64, name: str = "coll",
                 max_queue: int = MAX_QUEUE):
        self._di_fn = di_fn
        self._gen_fn = gen_fn
        self.name = name
        self._max_batch = max_batch
        self._max_queue = max_queue
        self._cv = make_condition("resident.cv")
        self._queue: deque[Ticket] = deque()
        self._inflight: deque[_Wave] = deque()
        self._alive = True
        self.waves_issued = 0
        self.drains_for_freshness = 0
        self._thread = _threads.spawn(f"resident-loop-{name}",
                                      self._run)

    @property
    def alive(self) -> bool:
        return self._alive and self._thread.is_alive()

    def submit(self, plans, *, topk: int = 64, lang: int = 0,
               deadline: deadline_mod.Deadline | None = None) -> Ticket:
        """Enqueue compiled plans; returns immediately. The hot path is
        a list append + notify — no device work on this thread. A
        ``deadline`` rides the ticket: the loop abandons the wave before
        issue if the budget ran out while the ticket queued."""
        t = Ticket(list(plans), topk, lang, deadline)
        with self._cv:
            if not self._alive:
                t._fail(RuntimeError("resident loop stopped"))
                return t
            if len(self._queue) >= self._max_queue:
                # bounded admission: fail the ticket, never the loop —
                # the serve edge turns QueueFull into shed-stale-or-503
                g_stats.count("admission.queue_full")
                t._fail(QueueFull("resident loop queue full"))
                return t
            self._queue.append(t)
            self._gauge_locked()
            self._cv.notify_all()
        return t

    def _gauge_locked(self) -> None:
        g_membudget.set_gauge(
            "serve", self, len(self._queue) * QUEUE_ENTRY_COST)

    def stop(self) -> None:
        """Kill the loop; queued and in-flight waiters fail fast."""
        with self._cv:
            self._alive = False
            self._cv.notify_all()

    # ------------------------------------------------------------- loop

    def _run(self) -> None:
        try:
            while True:
                with self._cv:
                    while self._alive and not self._queue \
                            and not self._inflight:
                        self._cv.wait()
                    if not self._alive:
                        self._abort_locked(
                            RuntimeError("resident loop stopped"))
                        return
                if not self._inflight and self._queue:
                    # fill-or-flush: the device is IDLE — launch now
                    # with whatever is queued (a collect window in
                    # front of idle hardware is pure added latency);
                    # while waves are in flight, submitters coalesce
                    # naturally until the pipeline frees a slot
                    g_stats.count("resident.idle_flush")
                if len(self._inflight) < DEPTH:
                    self._issue_one()
                if self._inflight and (
                        len(self._inflight) >= DEPTH
                        or not self._queue):
                    self._collect_one()
        except BaseException as exc:  # noqa: BLE001 — waiters must wake
            log.exception("resident loop died")
            with self._cv:
                self._alive = False
                self._abort_locked(exc)

    def _abort_locked(self, exc: BaseException) -> None:
        for t in self._queue:
            t._fail(exc)
        self._queue.clear()
        self._gauge_locked()
        for w in self._inflight:
            for t in w.tickets:
                t._fail(exc)
        self._inflight.clear()

    def _take_batch(self) -> list[Ticket]:
        """Longest same-(topk, lang) PREFIX of the queue — prefix, not
        filter, so resolution order is exactly submit order."""
        with self._cv:
            if not self._queue:
                return []
            head = self._queue[0]
            batch, nplans = [], 0
            while self._queue and len(batch) < self._max_batch:
                t = self._queue[0]
                if (t.topk, t.lang) != (head.topk, head.lang):
                    break
                if batch and nplans + len(t.plans) > self._max_batch:
                    break
                batch.append(self._queue.popleft())
                nplans += len(t.plans)
            self._gauge_locked()
            return batch

    def _index_for_issue(self):
        """The freshness protocol (module docstring): never re-resolve
        the index while waves are in flight — drain first if the Rdb
        moved, else keep issuing against the in-flight snapshot."""
        if self._inflight:
            di = self._inflight[-1].di
            if self._gen_fn() != di._built_version:
                self.drains_for_freshness += 1
                while self._inflight:
                    self._collect_one()
                return self._di_fn()
            return di
        return self._di_fn()

    def _issue_one(self) -> None:
        batch = self._take_batch()
        if not batch:
            return
        live = []
        for t in batch:
            # the coordinator's budget may have run out while the
            # ticket queued — abandon before the device wave, not after
            if deadline_mod.check_abandon("resident.issue", t.deadline):
                t._fail(deadline_mod.DeadlineExceeded(
                    "deadline exceeded before resident issue"))
            else:
                live.append(t)
        batch = live
        if not batch:
            return
        if g_chaos.enabled:
            g_chaos.resident_fault("issue")
        obs = devwatch.wave_begin("resident", coll=self.name,
                                  tickets=len(batch),
                                  queue=len(self._queue))
        try:
            di = self._index_for_issue()
            plans = [p for t in batch for p in t.plans]
            pending = di.issue_batch(plans, topk=batch[0].topk,
                                     lang=batch[0].lang)
            devwatch.wave_issued(obs, plans=len(plans),
                                 generation=di._built_version)
            for t in batch:
                t.di = di
                t.generation = di._built_version
            self._inflight.append(_Wave(pending, batch, di, obs))
            self.waves_issued += 1
            g_stats.count("resident.issue")
        except BaseException as exc:  # noqa: BLE001
            devwatch.wave_end(obs, error=type(exc).__name__)
            for t in batch:
                t._fail(exc)

    def _collect_one(self) -> None:
        wave = self._inflight.popleft()
        try:
            if g_chaos.enabled:
                g_chaos.resident_fault("collect")
            devwatch.wave_collect(wave.obs)
            results = wave.di.collect_batch(wave.pending)
            off = 0
            for t in wave.tickets:
                t._resolve(results[off:off + len(t.plans)])
                off += len(t.plans)
            devwatch.wave_end(wave.obs)
        except BaseException as exc:  # noqa: BLE001
            devwatch.wave_end(wave.obs, error=type(exc).__name__)
            for t in wave.tickets:
                t._fail(exc)
