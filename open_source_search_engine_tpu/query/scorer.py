"""Device scorer — the TPU-native ``PosdbTable::intersectLists10_r``.

Reference hot loop (``Posdb.cpp:5437``, ``docIdLoop:`` at 6137): per docid,
align term sublists, mini-merge positions, then (a) single-term scores
(``getSingleTermScore`` 3087: top-MAX_TOP position scores deduped by mapped
hashgroup, squared weights, × termfreq²), (b) pair scores via a sliding
window over body positions with non-body "sub-outs" at FIXED_DISTANCE
(``evalSlidingWindow`` 1275, ``getTermPairScoreForWindow`` 3557,
``getTermPairScoreForNonBody`` 3305), (c) final =
min(pair mins, single mins) × (siterank·⅓+1) × language boost
(``Posdb.cpp:7226-7257``), pushed into TopTree.

TPU-first reformulation — no per-docid pointer walk, one fused XLA program:

* postings scatter into a dense ``[D, T, P]`` position cube (D candidate
  docs × T term groups × P position slots) — the mini-merge becomes a
  gather-free memory layout;
* the sliding window disappears: where the reference approximates "best
  pair placement" by sliding over body positions (CPU-cheap), we take the
  exact max over the full P×P position cross product per term pair —
  dense masked compute the MXU/VPU eats for breakfast, and a strictly
  better optimum than the window heuristic;
* TopTree becomes ``lax.top_k`` over the scored doc axis.

Distance semantics per position pair (both reference paths unified):
both-in-body → plain distance (window algo, fixedDistance=0); mixed
body/non-body → FIXED_DISTANCE=400 (the window algo's sub-out);
both-non-body → distance capped to FIXED_DISTANCE beyond 50
(``getTermPairScoreForNonBody`` 3372), incompatible pairs (either in body)
excluded there but covered by the body path here. qdist=2 subtracted when
≥, +1 out-of-order penalty (3596-3600).

Everything here is shape-static; the packer buckets (T, L, D) to powers of
two so the jit cache stays small.

Memory layout (TPU-critical): the cube is ``[T, P, D]`` with the doc axis
**minor**. The TPU vector unit tiles the two minor dimensions to (8, 128);
with D minor every elementwise op runs on full lanes, and the per-pair
position cross products become ``[P, P, D]`` — again D minor, fully
vectorized. The transposed ``[D, T, P]`` layout (P=16 minor) pads 16→128
lanes and 4→8 sublanes, i.e. ~16× wasted HBM traffic on every op in the
scoring chain — measured ~10× slower end-to-end on v5e.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from ..index.posdb import HASHGROUP_END, HASHGROUP_INLINKTEXT
from ..utils import trace
from . import weights
from .packer import MAX_POSITIONS, TABLE_SIZE, PackedQuery, _bucket

QDIST = 2.0  # default query-distance (Posdb.cpp:6886)

#: max query-term index distance for pair scoring (bounds the unrolled
#: P×P cross products at wide T buckets; pairs of distant query words
#: contribute least under the min algorithm)
MAX_PAIR_SPAN = 4


def _decode(payload: jnp.ndarray):
    """Unpack the uint32 posting payload (packer bit layout)."""
    wordpos = (payload & jnp.uint32(0x3FFFF)).astype(jnp.int32)
    hg = ((payload >> jnp.uint32(18)) & jnp.uint32(0xF)).astype(jnp.int32)
    den = ((payload >> jnp.uint32(22)) & jnp.uint32(0x1F)).astype(jnp.int32)
    spam = ((payload >> jnp.uint32(27)) & jnp.uint32(0xF)).astype(jnp.int32)
    syn = ((payload >> jnp.uint32(31)) & jnp.uint32(1)).astype(jnp.int32)
    return wordpos, hg, den, spam, syn


def _tiny_lookup(table: np.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """Tiny-table lookup, backend-tuned.

    On TPU a gather from an 11-entry table over a [T, P, D] index array
    lowers to scalar gathers (~60 Melem/s — measured to dominate the
    whole scoring kernel), so it becomes a trace-time-unrolled select
    chain that fuses into the surrounding elementwise work. On CPU the
    chain is the slow form and the gather is free — keep the gather."""
    if jax.default_backend() == "cpu":
        return jnp.asarray(table, jnp.float32)[idx]
    out = jnp.full(idx.shape, float(table[0]), jnp.float32)
    for v in range(1, len(table)):
        out = jnp.where(idx == v, jnp.float32(table[v]), out)
    return out


def scatter_cube(doc_idx, payload, slot, valid, n_docs_padded: int,
                 n_positions: int, row_group=None, n_groups: int | None
                 = None):
    """Scatter posting rows into the dense position cube
    ``[n_groups, P, D]`` (+ validity; doc axis minor). ``row_group`` maps
    each row of ``doc_idx`` to its term group — identity when rows ARE
    groups (the host-packed path); the device-resident path gathers one
    row per *sublist* and folds them into groups here (the mini-merge,
    ``Posdb.cpp`` miniMergeBuf, as a scatter index)."""
    R, L = doc_idx.shape
    D = n_docs_padded
    P = n_positions
    T = n_groups if n_groups is not None else R
    if row_group is None:
        g_of = jnp.broadcast_to(jnp.arange(R)[:, None], (R, L))
    else:
        g_of = jnp.broadcast_to(row_group[:, None], (R, L))
    cube = jnp.zeros((T, P, D + 1), jnp.uint32)
    cube = cube.at[g_of, slot, doc_idx].set(payload, mode="drop")
    pvalid = jnp.zeros((T, P, D + 1), jnp.bool_)
    pvalid = pvalid.at[g_of, slot, doc_idx].set(valid, mode="drop")
    return cube[..., :D], pvalid[..., :D]


def position_weights(cube, pvalid):
    """Decode payloads → (posscore, posw, wordpos, hg) in [T, P, D].

    posw is the per-position weight product (hashgroup × density × spam ×
    synonym — the initWeights tables); posscore applies it squared on
    BASE_SCORE (singles square the weight, pairs take one factor per
    side — Posdb.cpp:3118)."""
    wordpos, hg, den, spam, syn = _decode(cube)
    hgw = _tiny_lookup(weights.HASH_GROUP_WEIGHTS, hg)
    # density weight in closed form (min(0.35·1.03445^rank, 1),
    # Posdb.cpp:1117-1125) — cheaper than any lookup
    denw = jnp.minimum(
        jnp.float32(0.35) * jnp.exp(den.astype(jnp.float32)
                                    * jnp.float32(np.log(1.03445))),
        1.0)
    spamf = spam.astype(jnp.float32)
    spamw = jnp.where(hg == HASHGROUP_INLINKTEXT,
                      jnp.sqrt(1.0 + spamf),        # Posdb.cpp:1136
                      (spamf + 1.0) * jnp.float32(1.0 / 16.0))
    synw = jnp.where(syn == 1, weights.SYNONYM_WEIGHT, 1.0)
    posw = hgw * denw * spamw * synw                       # [T, P, D]
    posscore = weights.BASE_SCORE * posw * posw * pvalid   # squared weights
    return posscore, posw, wordpos, hg


def pair_best(posw_i, wordpos_i, in_body_i, pv_i,
              posw_j, wordpos_j, in_body_j, pv_j):
    """Best pair placement for one term pair: max over the P×P position
    cross product of BASE·posw_i·posw_j/(dist+1) with the reference's
    distance semantics (getTermPairScoreForWindow/NonBody unified —
    module docstring). Inputs are per-side [P, ...] arrays with an
    arbitrary minor doc axis; returns the max over both P axes.

    The single definition of the pair math — min_scores and the
    direct-cube kernel both call it, so path parity holds by
    construction."""
    delta = (wordpos_j[None, :, :]
             - wordpos_i[:, None, :]).astype(jnp.float32)
    d_plain = jnp.maximum(jnp.abs(delta), 2.0)         # [P, P, D]
    body_i = in_body_i[:, None, :]
    body_j = in_body_j[None, :, :]
    mixed = body_i != body_j
    both_nb = (~body_i) & (~body_j)
    d_base = jnp.where(
        both_nb & (d_plain > weights.NONBODY_DIST_CAP),
        float(weights.FIXED_DISTANCE), d_plain)
    d_adj = (jnp.where(d_base >= QDIST, d_base - QDIST, d_base)
             + (delta < 0))
    dist = jnp.where(mixed, float(weights.FIXED_DISTANCE), d_adj)
    pv = (pv_i[:, None, :] & pv_j[None, :, :])
    ps = (weights.BASE_SCORE
          * posw_i[:, None, :] * posw_j[None, :, :]
          / (dist + 1.0)) * pv
    return jnp.max(ps, axis=(0, 1))                    # [D]


def min_scores(cube, pvalid, freq_weight, single_counts):
    """The docIdLoop scoring core on a [T, P, D] cube: returns
    (min_score [D] before multipliers, present [T, D]).

    ``single_counts`` [T]: groups participating in the min (scored &
    required, negatives excluded).

    Corpus-wide doc axes on TPU route to the fused Pallas kernel
    (pallas_scores.py): one HBM pass instead of ~30 — this jnp chain
    remains the reference semantics, the small-cube path, and the CPU
    path."""
    T, P, D = cube.shape
    from .pallas_scores import min_scores_fused, use_fused
    if use_fused(D):
        present = jnp.any(pvalid, axis=1)
        ms = min_scores_fused(
            cube, freq_weight, single_counts,
            interpret=jax.default_backend() == "cpu")
        return ms, present
    posscore, posw, wordpos, hg = position_weights(cube, pvalid)
    present = jnp.any(pvalid, axis=1)                      # [T, D]

    # ---- single-term scores (getSingleTermScore) ----
    # dedup by mapped hashgroup: one best position per collapsed group,
    # except INLINKTEXT where every occurrence competes individually
    mhg = _tiny_lookup(weights.MAPPED_HASHGROUP, hg
                       ).astype(jnp.int32)                 # [T, P, D]
    is_inlink = hg == HASHGROUP_INLINKTEXT
    grp_max = [
        jnp.max(jnp.where(mhg == g, posscore, 0.0), axis=1)
        if g != HASHGROUP_INLINKTEXT else jnp.zeros((T, D), posscore.dtype)
        for g in range(HASHGROUP_END)]                     # G × [T, D]
    inlink_scores = jnp.where(is_inlink, posscore, 0.0)    # [T, P, D]
    cand = jnp.concatenate(
        [jnp.stack(grp_max, axis=1), inlink_scores], axis=1)  # [T, G+P, D]
    k10 = min(weights.MAX_TOP, cand.shape[1])
    top_sum = jnp.sum(jnp.sort(cand, axis=1)[:, -k10:, :], axis=1)
    single = top_sum * (freq_weight * freq_weight)[:, None]  # [T, D]

    big = jnp.float32(9.99e8)  # reference's 999999999.0 sentinel
    s_mask = present & single_counts[:, None]
    min_single = jnp.min(jnp.where(s_mask, single, big), axis=0)    # [D]

    # ---- pair scores: exact max over P×P per (i, j) ----
    # pair work is capped at nearby query-term pairs (span ≤ MAX_PAIR_
    # SPAN): a 16-group bucket would otherwise unroll 120 P×P cross
    # products (compile-time and HBM both explode — the reference caps
    # pair work too, MAX_TOP/Posdb.h:817). Queries with ≤ 5 groups are
    # unaffected: every pair is within the span.
    in_body = _tiny_lookup(weights.IN_BODY, hg) > 0.5      # [T, P, D]
    min_pair = jnp.full((D,), big)
    any_pair = jnp.zeros((D,), jnp.bool_)
    for i in range(T):
        for j in range(i + 1, min(i + 1 + MAX_PAIR_SPAN, T)):
            best = pair_best(posw[i], wordpos[i], in_body[i], pvalid[i],
                             posw[j], wordpos[j], in_body[j], pvalid[j])
            wts = best * freq_weight[i] * freq_weight[j]
            pair_ok = (present[i] & present[j]
                       & single_counts[i] & single_counts[j])
            min_pair = jnp.where(pair_ok, jnp.minimum(min_pair, wts),
                                 min_pair)
            any_pair = any_pair | pair_ok

    min_score = jnp.minimum(jnp.where(any_pair, min_pair, big), min_single)
    # a doc with NO present scored group contributes nothing to the min
    # — it scores the filter-only constant 1.0 before multipliers. This
    # is PER-DOC: a boolean query like `site:x OR apple` matches some
    # docs purely through the unscored filter leaf (bare "site:x"
    # queries are the all-docs case of the same rule).
    min_score = jnp.where(jnp.any(s_mask, axis=0), min_score, 1.0)
    return min_score, present


def final_multipliers(siterank, doclang, qlang):
    """Siterank/language multipliers (Posdb.cpp:7250-7257), [D].

    Dtype contract: ``siterank``/``doclang`` may arrive as the packed
    uint8 resident columns (siterank is 4 bits, langid 6 in the posdb
    key) — everything here promotes/casts, so callers ship the narrow
    columns and no f32 copy ever lives in HBM."""
    lang_mult = jnp.where(
        (qlang == 0) | (doclang == 0) | (doclang == qlang),
        weights.SAME_LANG_WEIGHT, 1.0)
    return (siterank.astype(jnp.float32) * weights.SITERANKMULTIPLIER
            + 1.0) * lang_mult


def presence_table_ok(present, table):
    """Boolean-expression gate: pack per-doc presence bits and index the
    query's truth table (Query.h:266 semantics — non-boolean queries
    carry the all-true table and gate purely on required/negative)."""
    T, D = present.shape
    powers = (1 << jnp.arange(T, dtype=jnp.int32))[:, None]
    idx = jnp.sum(present.astype(jnp.int32) * powers, axis=0)
    return table[jnp.clip(idx, 0, TABLE_SIZE - 1)]


def score_cube(cube, pvalid, freq_weight, required, negative, scored,
               counts, table, siterank, doclang, qlang, n_docs,
               topk: int = 64, filt=None, sortc=None,
               use_filter: bool = False, use_sort: bool = False):
    """Score the dense position cube — the docIdLoop replacement.

    Shapes: cube/pvalid [T, P, D] (doc axis minor);
    freq_weight/required/negative/scored/counts [T]; table [TABLE_SIZE];
    siterank/doclang [D]; qlang/n_docs scalars. Returns (match count,
    top scores [k], top doc indices [k]).
    """
    T, P, D = cube.shape
    big = jnp.float32(9.99e8)
    min_score, present = min_scores(cube, pvalid, freq_weight, counts)

    # ---- match mask: every required group present, no negative present,
    #      truth table satisfied, inside the real candidate range ----
    req_ok = jnp.all(jnp.where(required[:, None], present, True), axis=0)
    neg_ok = ~jnp.any(jnp.where(negative[:, None], present, False), axis=0)
    in_range = jnp.arange(D) < n_docs
    match = (req_ok & neg_ok & presence_table_ok(present, table)
             & in_range & (min_score < big))
    if use_filter:
        # numeric range gate (gbmin:/gbmax: over fielddb columns)
        match = match & filt
    if use_sort:
        # gbsortby: the positive sort key IS the ranking score
        final = jnp.where(match, sortc, 0.0)
    else:
        final = min_score * final_multipliers(siterank, doclang, qlang)
        final = jnp.where(match, final, 0.0)

    k = min(topk, D)
    top_scores, top_idx = jax.lax.top_k(final, k)
    n_matched = jnp.sum(match)
    return n_matched, top_scores, top_idx


def score_core(doc_idx, payload, slot, valid, freq_weight, required,
               negative, scored, counts, table, siterank, doclang,
               qlang, n_docs,
               n_positions: int = MAX_POSITIONS, topk: int = 64,
               filt=None, sortc=None, use_filter: bool = False,
               use_sort: bool = False):
    """Host-packed entry: scatter rows (1 row = 1 group) then score.
    Pure traced function — called under plain jit for the single-shard
    path and inside ``shard_map`` for the mesh path."""
    cube, pvalid = scatter_cube(doc_idx, payload, slot, valid,
                                siterank.shape[0], n_positions)
    return score_cube(cube, pvalid, freq_weight, required, negative,
                      scored, counts, table, siterank, doclang, qlang,
                      n_docs, topk=topk, filt=filt, sortc=sortc,
                      use_filter=use_filter, use_sort=use_sort)


score_and_topk = jax.jit(score_core, static_argnames=("n_positions", "topk"))


def merge_dedup_topk(g_scores, g_hi, g_lo, g_sh, out_k: int,
                     max_per_site: int = 2):
    """The Msg3a merge tail for ONE query, pure traced — global top-k
    over the all-gathered per-shard candidate blocks, then the
    clusterdb 2-per-site dedup (Msg51 semantics) applied IN-PROGRAM so
    the recall decision needs no host round trip.

    ``g_scores``/``g_hi``/``g_lo``/``g_sh`` are the gathered ``[S, k]``
    blocks (scores, docid halves, sitehash). Returns, each over the
    merged window ``kk = min(out_k, S·k)`` with survivors compacted to
    a prefix in score order: (n_kept, n_dropped, hi, lo, sitehash,
    scores, cumdrop) — ``cumdrop[i]`` is the EXCLUSIVE count of
    clustered-away rows above survivor row i, which lets the host
    reconstruct the greedy walk's clustered counter at any page cut.

    Parity contract with :func:`..query.engine.build_results`: the
    greedy accept-walk keeps a row iff fewer than ``max_per_site``
    same-site rows were ACCEPTED above it; since only the first
    ``max_per_site`` same-site occurrences are ever accepted, that is
    equivalent to "fewer than ``max_per_site`` same-site LIVE rows
    above it" — an order-independent rank computable as one masked
    [kk, kk] triangular sum. sitehash 0 (no clusterdb record) is
    exempt, exactly like the host walk's ``if sh`` gate."""
    flat = g_scores.reshape(-1)
    kk = min(out_k, flat.shape[0])
    m_sc, m_pos = jax.lax.top_k(flat, kk)
    m_hi = jnp.take(g_hi.reshape(-1), m_pos)
    m_lo = jnp.take(g_lo.reshape(-1), m_pos)
    m_sh = jnp.take(g_sh.reshape(-1), m_pos)
    live = m_sc > 0.0
    # occ[i] = # live same-site rows strictly above i (top_k output is
    # already score-descending, ties by gather position — the same
    # order the host merge's stable argsort visits)
    same = (m_sh[:, None] == m_sh[None, :]) & live[None, :]
    earlier = jnp.tril(jnp.ones((kk, kk), jnp.bool_), k=-1)
    occ = jnp.sum(same & earlier, axis=1)
    keep = live & ((m_sh == 0) | (occ < max_per_site))
    dropped = live & ~keep
    drop32 = dropped.astype(jnp.uint32)
    cumdrop = jnp.cumsum(drop32) - drop32  # exclusive scan
    rank = jnp.arange(kk)
    # stable compaction: survivors first (score order preserved),
    # clustered + dead rows pushed past the survivor prefix
    order = jnp.argsort(jnp.where(keep, rank, kk + rank))
    sc_s = jnp.where(jnp.take(keep, order), jnp.take(m_sc, order), 0.0)
    return (jnp.sum(keep).astype(jnp.uint32),
            jnp.sum(drop32).astype(jnp.uint32),
            jnp.take(m_hi, order), jnp.take(m_lo, order),
            jnp.take(m_sh, order), sc_s, jnp.take(cumdrop, order))


def _score_packed_out(*args, n_positions: int, topk: int,
                      use_filter: bool = False, use_sort: bool = False):
    """score_core with the three outputs packed into ONE uint32 vector:
    ``[n_matched, top_idx…, bitcast(top_scores)…]``. A device→host fetch
    costs a full RPC round trip on tunneled TPU backends (~50 ms each,
    not batched by device_get), so one output array = one round trip."""
    *core_args, filt, sortc = args
    n_matched, ts, ti = score_core(*core_args, n_positions=n_positions,
                                   topk=topk, filt=filt, sortc=sortc,
                                   use_filter=use_filter,
                                   use_sort=use_sort)
    return jnp.concatenate([
        jnp.atleast_1d(n_matched.astype(jnp.uint32)),
        ti.astype(jnp.uint32),
        jax.lax.bitcast_convert_type(ts, jnp.uint32),
    ])


_score_packed = jax.jit(_score_packed_out,
                        static_argnames=("n_positions", "topk",
                                         "use_filter", "use_sort"))


def run_query(pq: PackedQuery, topk: int = 64):
    """Host wrapper: PackedQuery → (docids, scores, total matched)."""
    k = min(topk, len(pq.siterank))
    # the static top-k rides the power-of-two bucket ladder: engine
    # passes max(topk+offset, 64) straight from the request, and an
    # unbucketed static is one fresh compile per distinct page size;
    # top_k sorts descending, so slicing the first k of kb is exact
    kb = min(_bucket(max(topk, 1), 64), len(pq.siterank))
    # one batched device_put: per-arg implicit transfers each pay the
    # tunnel RPC overhead; a single list transfer is ~10× cheaper
    dpad = len(pq.siterank)
    filt = pq.filt if pq.filt is not None else np.zeros(dpad, bool)
    sortc = pq.sortc if pq.sortc is not None \
        else np.zeros(dpad, np.float32)
    up = [pq.doc_idx, pq.payload, pq.slot, pq.valid, pq.freq_weight,
          pq.required, pq.negative, pq.scored, pq.counts, pq.table,
          pq.siterank, pq.doclang,
          np.int32(pq.qlang), np.int32(pq.n_docs), filt, sortc]
    t_dev = time.perf_counter()
    dev = jax.device_put(up)
    out = np.asarray(_score_packed(
        *dev, n_positions=MAX_POSITIONS, topk=kb,
        use_filter=pq.use_filter, use_sort=pq.use_sort))
    # np.asarray blocks on the result — this delta is transfer + kernel
    # (device time); bytes_up/bytes_down are the wire both ways
    trace.record("scorer.device", t_dev,
                 bytes_up=int(sum(np.asarray(a).nbytes for a in up)),
                 bytes_down=int(out.nbytes))
    n_matched = int(out[0])
    top_idx = out[1:1 + kb][:k].astype(np.int64)
    top_scores = out[1 + kb:].view(np.float32)[:k]
    keep = top_scores > 0.0
    idx = top_idx[keep]
    return pq.cand_docids[idx], top_scores[keep], n_matched
