"""Fused Pallas scoring kernels — the scoring chain in ONE HBM pass.

Two kernels share one scoring body (the exact ``scorer.min_scores``
math — payload decode, position weights, single-term top-10 sums, P×P
pair cross products, the min):

* ``min_scores_fused``: [T, P, D] cube already in HBM → min_score [D].
  Replaces the ~30-pass XLA lowering for the generic F2 kernel and the
  host-packed path on corpus-wide doc axes.
* ``fd_scores_fused``: the direct-cube (FD) route WITHOUT ever
  materializing the [T, P, D] cube in HBM: a scalar-prefetch grid DMAs
  each query's T×4 quarter-rows of the RESIDENT cube tile-by-tile into
  VMEM, ORs in the (XLA-scattered) tail cube and the dead mask, scores
  the tile on-chip, and writes one f32 + one presence bitmask per doc.
  The FD assembly chain (gather + synbit + masks, measured ~27 ms/query
  at 250k docs) and the scoring chain (~30 ms) collapse into a single
  bandwidth-bound pass.

Float reduction order differs from the jnp path in the last ulp, which
every consumer tolerates (escalation tolerance 1e-4, bench recall
floor 1e-6); the jnp path remains the reference semantics and the
small-cube / CPU path. Validity rides the payloads: zero payload =
empty slot (the build-side invariant the FD route already relies on).

Packed-layout contract (SURVEY §7 stage-8): these kernels consume the
uint32 payload cubes ONLY — never the f16 impact bounds or uint8
siterank/langid columns the packed index demotes (those feed phase-1
selection and the final multipliers, both outside this kernel). That
is what makes the demotion score-exact: the exact rescore path through
here reads bits the packing never touched.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from ..index.posdb import HASHGROUP_END, HASHGROUP_INLINKTEXT
from . import weights
from .scorer import MAX_PAIR_SPAN, QDIST

#: doc-axis tile width (lane-dim multiple of 128). Sized UP to 1024:
#: the FD grid runs T·4 steps per (query, tile), so step-dispatch
#: overhead — not bandwidth — floors the wave time; 1024-wide tiles
#: halve the step count while the working set (~8 MB at T=8: decode
#: products + two live [P, P, TILE] pair buffers + the [T·4, P4,
#: TILE] cube scratch) still fits v5e's ~16 MB VMEM.
TILE_D = 1024

#: use the fused kernels only where they pay: corpus-wide doc axes.
#: Small phase-2 cubes (κ ≤ 2048) fuse fine under plain XLA.
MIN_D = 8192


def _sel_chain(idx, table):
    """Tiny-table lookup as a select chain (same trick as
    scorer._tiny_lookup — in-register, no gather)."""
    out = jnp.full(idx.shape, float(table[0]), jnp.float32)
    for v in range(1, len(table)):
        out = jnp.where(idx == v, jnp.float32(table[v]), out)
    return out


def _score_tile(cube, fw, cnt, T: int, P: int):
    """The scoring body on one [T, P, TD] VMEM tile → (min_score [TD],
    presence bitmask [TD] int32). Bit-for-bit the scorer.min_scores
    math (modulo reduction order)."""
    big = jnp.float32(9.99e8)

    valid = cube != 0
    wordpos = (cube & jnp.uint32(0x3FFFF)).astype(jnp.int32)
    hg = ((cube >> jnp.uint32(18)) & jnp.uint32(0xF)).astype(jnp.int32)
    den = ((cube >> jnp.uint32(22)) & jnp.uint32(0x1F)).astype(
        jnp.int32)
    spam = ((cube >> jnp.uint32(27)) & jnp.uint32(0xF)).astype(
        jnp.int32)
    syn = ((cube >> jnp.uint32(31)) & jnp.uint32(1)).astype(jnp.int32)
    hgw = _sel_chain(hg, weights.HASH_GROUP_WEIGHTS)
    denw = jnp.minimum(
        jnp.float32(0.35) * jnp.exp(den.astype(jnp.float32)
                                    * jnp.float32(np.log(1.03445))),
        1.0)
    spamf = spam.astype(jnp.float32)
    spamw = jnp.where(hg == HASHGROUP_INLINKTEXT,
                      jnp.sqrt(1.0 + spamf),
                      (spamf + 1.0) * jnp.float32(1.0 / 16.0))
    synw = jnp.where(syn == 1, jnp.float32(weights.SYNONYM_WEIGHT),
                     jnp.float32(1.0))
    posw = hgw * denw * spamw * synw                      # [T, P, TD]
    posscore = (jnp.float32(weights.BASE_SCORE) * posw * posw
                * valid.astype(jnp.float32))
    present = jnp.any(valid, axis=1)                      # [T, TD]

    # singles: top-MAX_TOP over {mapped-hashgroup maxima} ∪ {inlink
    # occurrences} (getSingleTermScore)
    mhg = _sel_chain(hg, weights.MAPPED_HASHGROUP).astype(jnp.int32)
    is_inlink = hg == HASHGROUP_INLINKTEXT
    cands = []
    for g in range(HASHGROUP_END):
        if g == HASHGROUP_INLINKTEXT:
            cands.append(jnp.zeros((T, cube.shape[2]), jnp.float32))
        else:
            cands.append(jnp.max(
                jnp.where(mhg == g, posscore, 0.0), axis=1))
    for p in range(P):
        cands.append(jnp.where(is_inlink[:, p], posscore[:, p], 0.0))
    cand = jnp.stack(cands, axis=1)               # [T, G+P, TD]
    k10 = min(weights.MAX_TOP, cand.shape[1])
    iota_c = jax.lax.broadcasted_iota(jnp.int32, cand.shape, 1)
    top_sum = jnp.zeros((T, cube.shape[2]), jnp.float32)
    work = cand
    for _ in range(k10):
        m = jnp.max(work, axis=1)
        top_sum = top_sum + m
        am = jnp.argmax(work, axis=1)
        work = jnp.where(iota_c == am[:, None, :],
                         jnp.float32(-1.0), work)
    single = top_sum * (fw * fw)[:, None]         # [T, TD]

    # expand dims on the f32 BEFORE comparing: Mosaic cannot reshape
    # sub-32-bit (i1) vectors along the minor dim
    s_mask = present & (cnt[:, None] > 0.5)
    min_single = jnp.min(jnp.where(s_mask, single, big), axis=0)

    # pairs: exact max over P×P per nearby (i, j) (pair_best)
    in_body = _sel_chain(hg, weights.IN_BODY) > 0.5       # [T, P, TD]
    min_pair = jnp.full(min_single.shape, big)
    any_pair = jnp.zeros(min_single.shape, jnp.bool_)
    for i in range(T):
        for j in range(i + 1, min(i + 1 + MAX_PAIR_SPAN, T)):
            delta = (wordpos[j][None, :, :]
                     - wordpos[i][:, None, :]).astype(jnp.float32)
            d_plain = jnp.maximum(jnp.abs(delta), 2.0)    # [P, P, TD]
            bi = in_body[i][:, None, :]
            bj = in_body[j][None, :, :]
            mixed = bi != bj
            both_nb = (~bi) & (~bj)
            d_base = jnp.where(
                both_nb & (d_plain > weights.NONBODY_DIST_CAP),
                jnp.float32(weights.FIXED_DISTANCE), d_plain)
            d_adj = (jnp.where(d_base >= QDIST, d_base - QDIST, d_base)
                     + (delta < 0))
            dist = jnp.where(mixed,
                             jnp.float32(weights.FIXED_DISTANCE),
                             d_adj)
            pvij = (valid[i][:, None, :] & valid[j][None, :, :])
            ps = (jnp.float32(weights.BASE_SCORE)
                  * posw[i][:, None, :] * posw[j][None, :, :]
                  / (dist + 1.0)) * pvij
            best = jnp.max(ps, axis=(0, 1))               # [TD]
            wts = best * fw[i] * fw[j]
            pair_ok = (present[i] & present[j]
                       & (cnt[i] > 0.5) & (cnt[j] > 0.5))
            min_pair = jnp.where(pair_ok,
                                 jnp.minimum(min_pair, wts), min_pair)
            any_pair = any_pair | pair_ok

    ms = jnp.minimum(jnp.where(any_pair, min_pair, big), min_single)
    ms = jnp.where(jnp.any(s_mask, axis=0), ms, jnp.float32(1.0))
    # presence bitmask (T ≤ 16 bits): callers unpack for req/neg/table
    pres = jnp.zeros(ms.shape, jnp.int32)
    for t in range(T):
        pres = pres | (present[t].astype(jnp.int32) << t)
    return ms, pres


# --------------------------------------------------------------- F2 path

def _ms_kernel(cube_ref, fw_ref, cnt_ref, out_ref, *, T: int, P: int):
    ms, _ = _score_tile(cube_ref[0], fw_ref[0], cnt_ref[0], T, P)
    out_ref[0] = ms


def _guard_cube(cube, route: str):
    """Devcheck sweep on a concrete cube before kernel dispatch: every
    nonzero payload must decode to a legal hashgroup. Host-side and
    opt-in (query.devcheck); a no-op under tracing — callers already
    inside a jit get their sweep at the devindex dispatch layer."""
    from . import devcheck
    if not devcheck.enabled() or isinstance(cube, jax.core.Tracer):
        return cube
    cube = devcheck.apply_cube_fault(cube)
    devcheck.check_cube(cube, route=route)
    return cube


def min_scores_fused(cube, freqw, counts, interpret: bool = False):
    """[T, P, D] uint32 cube → min_score [D] f32 (validity = payload
    ≠ 0). ``counts`` bool [T]. Batched callers vmap this; pallas lifts
    the batch axis into the grid."""
    cube = _guard_cube(cube, "pallas.f2")
    return _min_scores_fused(cube, freqw, counts, interpret=interpret)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _min_scores_fused(cube, freqw, counts, interpret: bool = False):
    from jax.experimental import pallas as pl

    T, P, D = cube.shape
    assert D % TILE_D == 0, (T, P, D)
    fw = freqw.astype(jnp.float32).reshape(1, T)
    cnt = counts.astype(jnp.float32).reshape(1, T)
    cube4 = cube.reshape(1, T, P, D)
    out = pl.pallas_call(
        functools.partial(_ms_kernel, T=T, P=P),
        grid=(D // TILE_D,),
        in_specs=[
            pl.BlockSpec((1, T, P, TILE_D),
                         lambda d: (0, 0, 0, d)),
            pl.BlockSpec((1, T), lambda d: (0, 0)),
            pl.BlockSpec((1, T), lambda d: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, TILE_D), lambda d: (0, d)),
        out_shape=jax.ShapeDtypeStruct((1, D), jnp.float32),
        interpret=interpret,
    )(cube4, fw, cnt)
    return out[0]


# --------------------------------------------------------------- FD path

def _fd_kernel(gq_ref, syn_ref, rows_hbm, *rest, T: int, P: int,
               has_tail: bool):
    """Grid (B, D/TILE): ONE step per (query, doc tile). The step
    issues T·4 async DMAs pulling the query's quarter-row slices from
    the HBM-resident cube straight into the VMEM scratch (a grid axis
    per quarter paid ~8 µs of step dispatch to move 16 KB — the DMA
    form is ~16× fewer steps), waits, assembles, scores. Waves whose
    every query is pure quarter-rows (no posting tail — the common FD
    case) compile WITHOUT the tail input, skipping a cube-sized HBM
    write+read per query."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if has_tail:
        tail_ref, dead_ref, fw_ref, cnt_ref, ms_ref, pres_ref, \
            acc_ref, sems = rest
    else:
        dead_ref, fw_ref, cnt_ref, ms_ref, pres_ref, acc_ref, \
            sems = rest

    b = pl.program_id(0)
    d = pl.program_id(1)
    TQ = T * 4
    TD = acc_ref.shape[2]

    def dma(tq):
        return pltpu.make_async_copy(
            rows_hbm.at[gq_ref[b, tq], :, pl.dslice(d * TD, TD)],
            acc_ref.at[tq], sems.at[tq])

    for tq in range(TQ):
        dma(tq).start()
    for tq in range(TQ):
        dma(tq).wait()

    # per-quarter synonym bit, read from the prefetched scalars and
    # OR'd in place (a [TQ]→[TQ,1,1] vector broadcast is an
    # unsupported Mosaic shape cast; the scalar form also skips the
    # no-synonym common case entirely)
    for tq in range(TQ):
        sb = (syn_ref[b, tq].astype(jnp.uint32) << jnp.uint32(31))

        @pl.when(sb != 0)
        def _orsyn(tq=tq, sb=sb):
            r = acc_ref[tq]
            acc_ref[tq] = jnp.where(r != 0, r | sb, r)

    rows = acc_ref[...]                             # [T·4, P4, TD]
    live = dead_ref[0] == 0                         # [TD]
    cube = jnp.where(live[None, None, :], rows.reshape(T, P, TD),
                     jnp.uint32(0))
    if has_tail:
        # tail postings were dead-filtered at scatter time (delta
        # postings of re-added docs live PAST the dead mask) — OR
        # after masking. Slot ranges are disjoint by the slot plan.
        cube = cube | tail_ref[0]
    ms, pres = _score_tile(cube, fw_ref[0, 0], cnt_ref[0, 0], T, P)
    ms_ref[0, 0] = ms
    pres_ref[0, 0] = pres


@functools.partial(jax.jit,
                   static_argnames=("T", "P", "interpret"))
def _fd_scores_fused(g_quarter, g_qsyn, d_cube, tail_cube, dead_i32,
                     freqw, counts, T: int, P: int,
                     interpret: bool = False):
    return _fd_call(g_quarter, g_qsyn, d_cube, tail_cube, dead_i32,
                    freqw, counts, T=T, P=P, interpret=interpret,
                    has_tail=True)


def fd_scores_fused(g_quarter, g_qsyn, d_cube, tail_cube, dead_i32,
                    freqw, counts, T: int, P: int,
                    interpret: bool = False):
    """Tail-carrying variant (see _fd_kernel)."""
    d_cube = _guard_cube(d_cube, "pallas.fd")
    return _fd_scores_fused(g_quarter, g_qsyn, d_cube, tail_cube,
                            dead_i32, freqw, counts, T=T, P=P,
                            interpret=interpret)


@functools.partial(jax.jit,
                   static_argnames=("T", "P", "interpret"))
def fd_scores_fused_notail(g_quarter, g_qsyn, d_cube, dead_i32,
                           freqw, counts, T: int, P: int,
                           interpret: bool = False):
    """No-tail variant: pure quarter-row waves."""
    return _fd_call(g_quarter, g_qsyn, d_cube, None, dead_i32,
                    freqw, counts, T=T, P=P, interpret=interpret,
                    has_tail=False)


def _fd_call(g_quarter, g_qsyn, d_cube, tail_cube, dead_i32,
             freqw, counts, T: int, P: int,
             interpret: bool, has_tail: bool):
    """The direct-cube route, fused: returns (min_score [B, D] f32,
    presence bitmask [B, D] int32).

    ``g_quarter``/``g_qsyn`` [B, T·4] int32 — absolute quarter-row
    indices into the resident cube + per-quarter synonym flags;
    ``d_cube`` the flat resident cube [Vc·P·D]; ``tail_cube``
    [B, T, P, D] uint32 — the XLA-scattered posting tail (zeros where
    the query has none); ``dead_i32`` [1, D]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, TQ = g_quarter.shape
    assert TQ == 4 * T
    D = dead_i32.shape[1]
    assert D % TILE_D == 0
    P4 = P // 4
    Vc4 = d_cube.shape[0] // (P4 * D)
    rows3 = d_cube.reshape(Vc4, P4, D)
    # (B, 1, T) so every block dim equals an array dim (Mosaic requires
    # sublane block dims to match the array or divide 8)
    fw = freqw.astype(jnp.float32).reshape(B, 1, T)
    cnt = counts.astype(jnp.float32).reshape(B, 1, T)

    in_specs = [
        pl.BlockSpec(memory_space=pltpu.ANY),   # resident rows: HBM
    ]
    operands = [rows3]
    if has_tail:
        in_specs.append(
            pl.BlockSpec((1, T, P, TILE_D),
                         lambda b, d, gq, syn: (b, 0, 0, d)))
        operands.append(tail_cube)
    in_specs += [
        pl.BlockSpec((1, TILE_D),
                     lambda b, d, gq, syn: (0, d)),
        pl.BlockSpec((1, 1, T),
                     lambda b, d, gq, syn: (b, 0, 0)),
        pl.BlockSpec((1, 1, T),
                     lambda b, d, gq, syn: (b, 0, 0)),
    ]
    operands += [dead_i32, fw, cnt]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,       # g_quarter, g_qsyn
        grid=(B, D // TILE_D),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, TILE_D),
                         lambda b, d, gq, syn: (b, 0, d)),
            pl.BlockSpec((1, 1, TILE_D),
                         lambda b, d, gq, syn: (b, 0, d)),
        ],
        scratch_shapes=[
            pltpu.VMEM((T * 4, P // 4, TILE_D), jnp.uint32),
            pltpu.SemaphoreType.DMA((T * 4,)),
        ],
    )
    ms, pres = pl.pallas_call(
        functools.partial(_fd_kernel, T=T, P=P, has_tail=has_tail),
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct((B, 1, D), jnp.float32),
                   jax.ShapeDtypeStruct((B, 1, D), jnp.int32)],
        interpret=interpret,
    )(g_quarter, g_qsyn, *operands)
    return ms[:, 0], pres[:, 0]


def use_fused(D: int) -> bool:
    """Route policy: fused kernels on TPU backends for corpus-wide doc
    axes (OSSE_PALLAS=0 disables; =force enables everywhere, which
    tests use with interpret mode on CPU)."""
    mode = os.environ.get("OSSE_PALLAS", "1")
    if mode == "0":
        return False
    if mode == "force":
        return D % TILE_D == 0
    return (D >= MIN_D and D % TILE_D == 0
            and jax.default_backend() not in ("cpu",))
