"""Scoring weight tables — faithful to the reference's ``initWeights``
(``Posdb.cpp:1105-1197``) and scoring constants (``Posdb.h:94-117``).

Every weight is applied *squared* in single-term scoring and once per side
in pair scoring (``getSingleTermScore`` ``Posdb.cpp:3087``,
``getTermPairScoreForWindow`` ``Posdb.cpp:3557``), so tables here hold the
raw (unsquared) values exactly as the reference's static arrays do.

Tables are plain numpy float32; the scorer lifts them to device constants
inside jit (they are closure constants, folded by XLA).
"""

from __future__ import annotations

import numpy as np

from ..index.posdb import (
    HASHGROUP_BODY, HASHGROUP_END, HASHGROUP_HEADING, HASHGROUP_INLINKTEXT,
    HASHGROUP_INLIST, HASHGROUP_INMENU, HASHGROUP_INMETATAG, HASHGROUP_INTAG,
    HASHGROUP_INTERNALINLINKTEXT, HASHGROUP_INURL, HASHGROUP_NEIGHBORHOOD,
    HASHGROUP_TITLE, MAXDENSITYRANK, MAXDIVERSITYRANK, MAXWORDSPAMRANK,
)

# scoring constants (Posdb.h:94-117, 765, 817)
SYNONYM_WEIGHT = 0.90
WIKI_WEIGHT = 0.10
SITERANKMULTIPLIER = 0.33333333
WIKI_BIGRAM_WEIGHT = 1.40
FIXED_DISTANCE = 400
MAX_TOP = 10
#: default same-language boost (Parms.cpp "language weight" m_def 20.0)
SAME_LANG_WEIGHT = 20.0
#: pairs of non-body positions >50 apart get FIXED_DISTANCE
#: (Posdb.cpp:3372 "fix distance if in different non-body hashgroups")
NONBODY_DIST_CAP = 50

BASE_SCORE = 100.0  # every position/pair score starts at 100 (Posdb.cpp:3118)


def _hash_group_weights() -> np.ndarray:
    w = np.zeros(HASHGROUP_END, dtype=np.float32)
    w[HASHGROUP_BODY] = 1.0
    w[HASHGROUP_TITLE] = 8.0
    w[HASHGROUP_HEADING] = 1.5
    w[HASHGROUP_INLIST] = 0.3
    w[HASHGROUP_INMETATAG] = 0.1
    w[HASHGROUP_INLINKTEXT] = 16.0
    w[HASHGROUP_INTAG] = 1.0
    w[HASHGROUP_NEIGHBORHOOD] = 0.0
    w[HASHGROUP_INTERNALINLINKTEXT] = 4.0
    w[HASHGROUP_INURL] = 1.0
    w[HASHGROUP_INMENU] = 0.2
    return w


def _density_weights() -> np.ndarray:
    # sum starts at 0.35, *= 1.03445 per rank, clamped at 1.0
    # (Posdb.cpp:1117-1125)
    w = np.zeros(MAXDENSITYRANK + 1, dtype=np.float32)
    s = 0.35
    for i in range(MAXDENSITYRANK + 1):
        w[i] = min(s, 1.0)
        s *= 1.03445
    return w


def _diversity_weights() -> np.ndarray:
    # disabled in the reference: all 1.0 (Posdb.cpp:1112)
    return np.ones(MAXDIVERSITYRANK + 1, dtype=np.float32)


def _word_spam_weights() -> np.ndarray:
    # (i+1)/(MAX+1) (Posdb.cpp:1128-1129)
    return ((np.arange(MAXWORDSPAMRANK + 1) + 1.0)
            / (MAXWORDSPAMRANK + 1)).astype(np.float32)


def _linker_weights() -> np.ndarray:
    # sqrt(1+i) — inlink text spam slot stores the linker's siterank
    # (Posdb.cpp:1136-1137)
    return np.sqrt(1.0 + np.arange(MAXWORDSPAMRANK + 1)).astype(np.float32)


def _in_body() -> np.ndarray:
    # body-ish hashgroups (Posdb.cpp:1142-1148)
    b = np.zeros(HASHGROUP_END, dtype=bool)
    for hg in (HASHGROUP_BODY, HASHGROUP_HEADING, HASHGROUP_INLIST,
               HASHGROUP_INMENU):
        b[hg] = True
    return b


HASH_GROUP_WEIGHTS = _hash_group_weights()
DENSITY_WEIGHTS = _density_weights()
DIVERSITY_WEIGHTS = _diversity_weights()
WORD_SPAM_WEIGHTS = _word_spam_weights()
LINKER_WEIGHTS = _linker_weights()
IN_BODY = _in_body()

#: mapped hashgroup for single-term dedup: body-ish groups collapse to BODY
#: (Posdb.cpp:3126-3127 "if s_inBody[mhg] mhg = HASHGROUP_BODY")
MAPPED_HASHGROUP = np.where(
    IN_BODY, HASHGROUP_BODY, np.arange(HASHGROUP_END)).astype(np.int32)


def term_freq_weight(term_freq, num_docs) -> np.ndarray:
    """IDF-ish weight in [0.5, 1.0]: 0.5 + min(tf/N, 0.5)
    (``getTermFreqWeight`` ``Posdb.cpp:1225-1252`` — *inverted* because the
    min-algorithm needs common terms to score higher, not lower)."""
    tf = np.asarray(term_freq, dtype=np.float32)
    n = max(float(num_docs), 1.0)
    return (0.5 + np.minimum(tf / n, 0.5)).astype(np.float32)
