"""Device-plane guardrails — checkify/debug_nans harness for the kernels.

The host plane's twin (``utils/membudget.py`` carries the Mem.cpp
budget role): Gigablast's device-free core relied on allocation
canaries and ``checkList_r`` sanity sweeps to turn silent corruption
into loud errors; the TPU-native equivalent (SURVEY §5) is
``jax.experimental.checkify`` + ``jax_debug_nans``. This module wraps
the F1/FD/F2 kernel routes with on-device assertions:

* **score finiteness** — no NaN/inf leaves a scoring wave;
* **top-k monotonicity** — emitted scores are non-increasing (the
  selection contract every consumer — merge, paging, escalation's
  kth-score check — silently depends on);
* **index bounds** — every live (score > 0) top-k row indexes a real
  doc (``idx < n_docs``; the dead-mask/pad contract);
* **cube payload sanity** — nonzero payloads decode to a hashgroup
  ``< HASHGROUP_END`` (a corrupt tile shows up here first: random
  bytes have hashgroup 11–15 with probability 5/16 per position).

Everything is **opt-in**: ``OSSE_CHECKIFY=1`` in the environment or
the ``checkify`` parm (serve wiring calls :func:`set_enabled`). Off,
the hot path pays one dict lookup. A trip raises
:class:`DeviceCheckError` with the failing route and bumps
``devcheck.trip`` counters in ``g_stats`` (statsdb surfaces them).

The fault injector (:func:`inject`) corrupts wave outputs / cube
payloads *before* the checks so tests prove the harness fires — the
reference's "write garbage, watch the canary scream" discipline.
Checks run in both eager ("interpret") and jitted modes; tier-1 CI
exercises both under ``JAX_PLATFORMS=cpu``.
"""

from __future__ import annotations

import contextlib
import functools
import os

import numpy as np

from ..index.posdb import HASHGROUP_END
from ..utils.log import get_logger
from ..utils.stats import g_stats

log = get_logger("devcheck")

#: monotonicity slack: float32 reassociation across kernel variants
_TIE_EPS = 1e-5

#: parm override (None = the OSSE_CHECKIFY env var decides)
_flag: bool | None = None

#: active fault kind (None | "nan" | "oob_docid" | "corrupt_tile")
_fault: str | None = None


class DeviceCheckError(RuntimeError):
    """An on-device guardrail assertion tripped."""


def set_enabled(on: bool | None) -> None:
    """Parm hook: True forces on, None defers to ``OSSE_CHECKIFY``."""
    global _flag
    _flag = on


def enabled() -> bool:
    if _flag is not None:
        return _flag
    return os.environ.get("OSSE_CHECKIFY", "") == "1"


# --------------------------------------------------------------- checks

def _topk_invariants(scores, idx, n_docs):
    import jax.numpy as jnp
    from jax.experimental import checkify
    checkify.check(jnp.all(jnp.isfinite(scores)),
                   "non-finite top-k score left the device "
                   "(nan_count={n})",
                   n=jnp.sum(~jnp.isfinite(scores)))
    if scores.shape[0] > 1:
        checkify.check(
            jnp.all(scores[:-1] >= scores[1:] - _TIE_EPS),
            "top-k scores not monotonic non-increasing "
            "(first violation rank={r})",
            r=jnp.argmax(scores[:-1] < scores[1:] - _TIE_EPS))
    live = scores > 0.0
    in_bounds = (idx >= 0) & (idx < n_docs)
    checkify.check(
        jnp.all(jnp.where(live, in_bounds, True)),
        "out-of-range docid index in live top-k row "
        "(idx={i} >= n_docs={n})",
        i=jnp.max(jnp.where(live, idx, -1)), n=n_docs)
    return jnp.int32(0)


def _cube_invariants(cube):
    import jax.numpy as jnp
    from jax.experimental import checkify
    hg = (cube >> jnp.uint32(18)) & jnp.uint32(0xF)
    bad = (cube != 0) & (hg >= HASHGROUP_END)
    checkify.check(
        ~jnp.any(bad),
        "corrupt position-cube tile: {n} nonzero payloads decode to "
        "hashgroup >= HASHGROUP_END", n=jnp.sum(bad))
    return jnp.int32(0)


@functools.lru_cache(maxsize=None)
def _checked(fn_name: str, use_jit: bool):
    import jax
    from jax.experimental import checkify
    fn = {"topk": _topk_invariants, "cube": _cube_invariants}[fn_name]
    return checkify.checkify(jax.jit(fn) if use_jit else fn)


def _use_jit() -> bool:
    """jit by default; OSSE_CHECKIFY_INTERPRET=1 runs the checks
    eagerly (the interpret-mode CI leg)."""
    return os.environ.get("OSSE_CHECKIFY_INTERPRET", "") != "1"


def _trip(route: str, msg: str) -> None:
    g_stats.count("devcheck.trip")
    if route:
        # route ∈ {f1, f2, fd} — bounded, not a cardinality risk
        g_stats.count(f"devcheck.trip.{route}")  # osselint: ignore[stats-cardinality]
    log.error("devcheck TRIP [%s]: %s", route or "-", msg)
    raise DeviceCheckError(f"[{route or 'device'}] {msg}")


def check_topk(scores, idx, n_docs: int, route: str = "",
               use_jit: bool | None = None) -> None:
    """Assert the emitted top-k invariants (finite, sorted, in-bounds).
    No-op unless :func:`enabled`. Raises :class:`DeviceCheckError`."""
    if not enabled():
        return
    import jax.numpy as jnp
    jit = _use_jit() if use_jit is None else use_jit
    err, _ = _checked("topk", jit)(
        jnp.asarray(scores, jnp.float32),
        jnp.asarray(idx, jnp.int32),
        jnp.int32(n_docs))
    msg = err.get()
    if msg:
        _trip(route, msg)


def check_cube(cube, route: str = "",
               use_jit: bool | None = None) -> None:
    """Assert cube payload sanity (hashgroup bits decode in range).
    No-op unless :func:`enabled`."""
    if not enabled():
        return
    import jax.numpy as jnp
    jit = _use_jit() if use_jit is None else use_jit
    err, _ = _checked("cube", jit)(jnp.asarray(cube, jnp.uint32))
    msg = err.get()
    if msg:
        _trip(route, msg)


# --------------------------------------------------------- fault injector

@contextlib.contextmanager
def inject(kind: str):
    """Corrupt the next checked wave: ``"nan"`` poisons a score,
    ``"oob_docid"`` points a live row past n_docs, ``"corrupt_tile"``
    flips a cube payload's hashgroup bits out of range. Proves the
    checks fire (tests only; the injection happens host-side, after
    fetch / before dispatch, so device state is never corrupted)."""
    global _fault
    assert kind in ("nan", "oob_docid", "corrupt_tile"), kind
    prev = _fault
    _fault = kind
    try:
        yield
    finally:
        _fault = prev


def apply_fault(idx: np.ndarray, scores: np.ndarray, n_docs: int):
    """Apply the armed output fault (if any) to one parsed wave row.
    Returns possibly-replaced (idx, scores) copies."""
    if _fault == "nan":
        scores = np.asarray(scores).copy()
        scores[0] = np.nan
        g_stats.count("devcheck.injected")
    elif _fault == "oob_docid":
        idx = np.asarray(idx).copy()
        scores = np.asarray(scores).copy()
        idx[0] = n_docs + 7
        scores[0] = max(float(scores[0]), 1.0)  # a LIVE row
        g_stats.count("devcheck.injected")
    return idx, scores


def apply_cube_fault(cube):
    """Apply the armed cube fault (if any): one payload with hashgroup
    0xF (>= HASHGROUP_END) and a nonzero wordpos."""
    if _fault != "corrupt_tile":
        return cube
    import jax.numpy as jnp
    cube = jnp.asarray(cube)
    flat = cube.reshape(-1)
    flat = flat.at[0].set(jnp.uint32((0xF << 18) | 1))
    g_stats.count("devcheck.injected")
    return flat.reshape(cube.shape)


# ----------------------------------------------------------- debug_nans

@contextlib.contextmanager
def debug_nans():
    """Scoped ``jax_debug_nans``: every primitive re-runs un-jitted on
    a NaN output and raises at the producing op — the heavyweight
    companion to the checkify sweep (kernel-debugging sessions, not
    serving)."""
    import jax
    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", True)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)
