"""Query plane: compiler → packer → device scorer → results.

The TPU-native replacement for the reference's search layer (SURVEY §2.7):
``Query.cpp`` (compiler), ``Msg2`` (termlist fetch), ``PosdbTable``
(scoring kernel), ``TopTree`` (top-k), ``Msg40`` (orchestration).
"""

from .compiler import QueryPlan, TermGroup, compile_query
from .engine import Result, SearchResults, search
from .packer import PackedQuery, pack_pass, pack_query, prepare_query
from .scorer import run_query, score_and_topk

__all__ = [
    "QueryPlan", "TermGroup", "compile_query", "Result", "SearchResults",
    "search", "PackedQuery", "pack_pass", "pack_query", "prepare_query",
    "run_query", "score_and_topk",
]
