"""Query-biased summaries + highlighting (the Msg20 content plane).

Reference: ``Summary.cpp/h`` — ``Summary::set2`` picks the best excerpt
windows via ``getBestWindow`` (``Summary.h:194``): score a window of words
around each query-term match by summing matched terms' weights, favoring
windows containing *more distinct* query terms, trimmed toward sentence
boundaries; up to ``maxNumLines`` fragments are concatenated. ``Title.cpp``
falls back through title sources; ``Highlight.cpp`` wraps matched words.
``Matches.cpp`` locates term hits in the stored document.

Vectorized rather than pointer-walked: term-match positions become numpy
masks; window scores come from a convolution over the match indicator.
"""

from __future__ import annotations

import re

import numpy as np

_WORD_RE = re.compile(r"\w+", re.UNICODE)

#: window width in words (reference summary excerpt length ~ a sentence)
WINDOW_WORDS = 24
#: distinct-term bonus: a window with k distinct query terms scores
#: k²·this on top of raw match counts (getBestWindow favors diversity)
DISTINCT_BONUS = 4.0


def _tokenize_with_spans(text: str) -> tuple[list[str], list[tuple[int, int]]]:
    words, spans = [], []
    for m in _WORD_RE.finditer(text):
        words.append(m.group(0).lower())
        spans.append(m.span())
    return words, spans


def make_summary(text: str, query_words: list[str], *,
                 max_fragments: int = 2, window: int = WINDOW_WORDS,
                 max_chars: int = 320) -> str:
    """Pick the best-scoring excerpt windows for these query words."""
    if not text:
        return ""
    qset = {w.lower() for w in query_words if w}
    if not qset:
        return text[:max_chars].strip()
    words, spans = _tokenize_with_spans(text)
    if not words:
        return text[:max_chars].strip()
    n = len(words)
    warr = np.array(words)
    hit = np.isin(warr, list(qset))
    if not hit.any():
        return text[:max_chars].strip()

    # term ids for distinct-term counting inside windows
    qlist = sorted(qset)
    qid = {w: i for i, w in enumerate(qlist)}
    ids = np.array([qid.get(w, -1) for w in words], dtype=np.int32)

    win = min(window, n)
    # windowed match count via cumulative sum
    csum = np.concatenate([[0], np.cumsum(hit)])
    counts = csum[win:] - csum[:-win]                    # [n-win+1]
    # distinct terms per window: one-hot over query ids, windowed any()
    onehot = np.zeros((n, len(qlist)), dtype=np.int32)
    rows = np.nonzero(ids >= 0)[0]
    onehot[rows, ids[rows]] = 1
    oc = np.vstack([np.zeros(len(qlist), np.int32),
                    np.cumsum(onehot, axis=0)])
    distinct = ((oc[win:] - oc[:-win]) > 0).sum(axis=1)  # [n-win+1]
    scores = counts + DISTINCT_BONUS * distinct * distinct

    frags: list[tuple[int, int]] = []  # word-index ranges
    sc = scores.astype(np.float64).copy()
    for _ in range(max_fragments):
        best = int(np.argmax(sc))
        if sc[best] <= 0:
            break
        lo, hi = best, min(best + win, n)
        frags.append((lo, hi))
        # suppress overlapping windows for the next fragment
        s = max(0, best - win + 1)
        sc[s:best + win] = -1.0
    frags.sort()

    parts = []
    used = 0
    for lo, hi in frags:
        clo, chi = spans[lo][0], spans[hi - 1][1]
        # extend to sentence-ish boundaries within a small slack
        head = text.rfind(". ", max(0, clo - 60), clo)
        clo2 = head + 2 if head >= 0 else clo
        tail = text.find(". ", chi, chi + 60)
        chi2 = tail + 1 if tail >= 0 else chi
        frag = text[clo2:chi2].strip()
        if clo2 > 0 and head < 0:
            frag = "…" + frag
        if chi2 < len(text) and tail < 0:
            frag += "…"
        if used + len(frag) > max_chars and parts:
            break
        parts.append(frag)
        used += len(frag)
    return " ".join(parts)[: max_chars + 40]


def highlight(text: str, query_words: list[str],
              pre: str = "<b>", post: str = "</b>") -> str:
    """Wrap query-word matches (``Highlight.cpp`` front-tag/back-tag)."""
    qset = {w.lower() for w in query_words if w}
    if not qset:
        return text
    out, last = [], 0
    for m in _WORD_RE.finditer(text):
        if m.group(0).lower() in qset:
            out.append(text[last:m.start()])
            out.append(pre + m.group(0) + post)
            last = m.end()
    out.append(text[last:])
    return "".join(out)
