"""Query-biased summaries + highlighting (the Msg20 content plane).

Reference: ``Summary.cpp/h`` — ``Summary::set2`` picks the best excerpt
windows via ``getBestWindow`` (``Summary.h:194``): score a window of words
around each query-term match by summing matched terms' weights, favoring
windows containing *more distinct* query terms, trimmed toward sentence
boundaries; up to ``maxNumLines`` fragments are concatenated. ``Title.cpp``
falls back through title sources; ``Highlight.cpp`` wraps matched words.
``Matches.cpp`` locates term hits in the stored document.

Vectorized rather than pointer-walked: term-match positions become numpy
masks; window scores come from a convolution over the match indicator.
"""

from __future__ import annotations

import re

import numpy as np

from ..utils import trace

_WORD_RE = re.compile(r"\w+", re.UNICODE)

#: window width in words (reference summary excerpt length ~ a sentence)
WINDOW_WORDS = 24
#: distinct-term bonus: a window with k distinct query terms scores
#: k²·this on top of raw match counts (getBestWindow favors diversity)
DISTINCT_BONUS = 4.0


def _tokenize_with_spans(text: str) -> tuple[list[str], list[tuple[int, int]]]:
    words, spans = [], []
    for m in _WORD_RE.finditer(text):
        words.append(m.group(0).lower())
        spans.append(m.span())
    return words, spans


_SENT_BOUND_RE = re.compile(r"[.!?]\s")


def choose_title(rec: dict, max_len: int = 80) -> str:
    """Title fallback chain (``Title.cpp``): stored <title> → first
    heading (h1) → best inlink anchor text → url-derived words. Never
    returns empty for a doc with a url."""
    for cand in (rec.get("title"), rec.get("h1")):
        if cand and cand.strip():
            return cand.strip()[:max_len]
    # longest anchor text under the cap (Title.cpp scores link texts)
    anchors = sorted((t for t, _sr in (rec.get("inlinks") or []) if t),
                     key=len, reverse=True)
    for a in anchors:
        if a.strip():
            return a.strip()[:max_len]
    url = rec.get("url", "")
    if url:
        from ..utils.url import normalize
        try:
            u = normalize(url)
            tail = [s for s in u.path.split("/") if s]
            seg = tail[-1] if tail else ""
            base, dot, ext = seg.rpartition(".")
            if dot and base and len(ext) <= 5:
                seg = base  # drop the file extension from the title
            # [^\W_]: url slugs separate words with _ as often as -
            words = re.findall(r"[^\W_]+", seg)
            if words:
                return " ".join(words)[:max_len]
            return u.host[:max_len]
        except Exception:  # noqa: BLE001 — junk urls
            return url[:max_len]
    return ""


def field_matches(rec: dict, query_words: list[str]) -> dict[str, int]:
    """Field-aware match positions (``Matches.cpp`` MF_* flags): how
    many distinct query words hit each stored field — a reporting
    helper for result renderers that highlight per-field (the summary
    source choice itself lives in make_summary's fallback chain)."""
    qset = {w.lower() for w in query_words if w}
    out: dict[str, int] = {}
    fields = {
        "title": rec.get("title", ""),
        "h1": rec.get("h1", ""),
        "description": rec.get("meta_description", ""),
        "body": rec.get("text", ""),
        "anchor": " ".join(t for t, _ in (rec.get("inlinks") or [])),
    }
    for name, val in fields.items():
        if not val:
            continue
        hits = {w for w in
                (m.group(0).lower() for m in _WORD_RE.finditer(val))
                if w in qset}
        if hits:
            out[name] = len(hits)
    return out


def make_summary(text: str, query_words: list[str], *,
                 max_fragments: int = 2, window: int = WINDOW_WORDS,
                 max_chars: int = 320, description: str = "") -> str:
    """Trace-wrapped :func:`_make_summary` — one ``summary.make`` span
    per excerpt built (no-op outside a sampled trace)."""
    with trace.span("summary.make", chars=len(text or "")):
        return _make_summary(text, query_words,
                             max_fragments=max_fragments, window=window,
                             max_chars=max_chars, description=description)


def _make_summary(text: str, query_words: list[str], *,
                  max_fragments: int = 2, window: int = WINDOW_WORDS,
                  max_chars: int = 320, description: str = "") -> str:
    """Pick the best-scoring excerpt windows for these query words.

    Fallback order when the body has no match (Summary.cpp's source
    chain): the meta description if IT matches, else the text head,
    else the description itself."""
    if not text and not description:
        return ""
    qset = {w.lower() for w in query_words if w}
    if not qset:
        return (text or description)[:max_chars].strip()
    words, spans = _tokenize_with_spans(text)

    def _fallback() -> str:
        if description:
            dwords = {m.group(0).lower()
                      for m in _WORD_RE.finditer(description)}
            if dwords & qset or not text:
                return description[:max_chars].strip()
        return (text or description)[:max_chars].strip()

    if not words:
        return _fallback()
    n = len(words)
    warr = np.array(words)
    hit = np.isin(warr, list(qset))
    if not hit.any():
        return _fallback()

    # term ids for distinct-term counting inside windows
    qlist = sorted(qset)
    qid = {w: i for i, w in enumerate(qlist)}
    ids = np.array([qid.get(w, -1) for w in words], dtype=np.int32)

    win = min(window, n)
    # windowed match count via cumulative sum
    csum = np.concatenate([[0], np.cumsum(hit)])
    counts = csum[win:] - csum[:-win]                    # [n-win+1]
    # distinct terms per window: one-hot over query ids, windowed any()
    onehot = np.zeros((n, len(qlist)), dtype=np.int32)
    rows = np.nonzero(ids >= 0)[0]
    onehot[rows, ids[rows]] = 1
    oc = np.vstack([np.zeros(len(qlist), np.int32),
                    np.cumsum(onehot, axis=0)])
    distinct = ((oc[win:] - oc[:-win]) > 0).sum(axis=1)  # [n-win+1]
    scores = counts + DISTINCT_BONUS * distinct * distinct

    frags: list[tuple[int, int]] = []  # word-index ranges
    sc = scores.astype(np.float64).copy()
    for _ in range(max_fragments):
        best = int(np.argmax(sc))
        if sc[best] <= 0:
            break
        lo, hi = best, min(best + win, n)
        frags.append((lo, hi))
        # suppress overlapping windows for the next fragment
        s = max(0, best - win + 1)
        sc[s:best + win] = -1.0
    frags.sort()

    # sentence boundaries computed once: fragments snap to REAL
    # sentence bounds (within a slack) instead of raw window edges
    bounds = [0] + [m.end() for m in _SENT_BOUND_RE.finditer(text)] \
        + [len(text)]
    barr = np.array(bounds)

    parts = []
    used = 0
    for lo, hi in frags:
        clo, chi = spans[lo][0], spans[hi - 1][1]
        # nearest sentence start at/before clo (slack-capped so one
        # run-on sentence can't balloon the fragment)
        i = int(np.searchsorted(barr, clo, side="right")) - 1
        head = int(barr[max(i, 0)])
        snap_head = clo - head <= 80
        clo2 = head if snap_head else clo
        j = int(np.searchsorted(barr, chi, side="left"))
        tail = int(barr[min(j, len(bounds) - 1)])
        snap_tail = tail - chi <= 80
        chi2 = tail if snap_tail else chi
        frag = text[clo2:chi2].strip()
        if clo2 > 0 and not snap_head:
            frag = "…" + frag
        if chi2 < len(text) and not snap_tail:
            frag += "…"
        if used + len(frag) > max_chars and parts:
            break
        parts.append(frag)
        used += len(frag)
    return " ".join(parts)[: max_chars + 40]


def highlight(text: str, query_words: list[str],
              pre: str = "<b>", post: str = "</b>") -> str:
    """Wrap query-word matches (``Highlight.cpp`` front-tag/back-tag)."""
    qset = {w.lower() for w in query_words if w}
    if not qset:
        return text
    out, last = [], 0
    for m in _WORD_RE.finditer(text):
        if m.group(0).lower() in qset:
            out.append(text[last:m.start()])
            out.append(pre + m.group(0) + post)
            last = m.end()
    out.append(text[last:])
    return "".join(out)
