"""Speller — "did you mean" suggestions from corpus word popularity.

Reference: ``Speller.{h,cpp}`` — dictionary files + word popularity
(built by ``gb gendict``, ``main.cpp:2719``); query terms with no/low
results get replaced by the most popular near-neighbor. Here the
dictionary IS the corpus: each collection keeps word → document-frequency
counts (fed by the indexer, persisted beside the collection), and
suggestions pick the most frequent word within Damerau-ish edit distance
≤ 2, requiring the suggestion to be strictly more popular than the typo.
"""

from __future__ import annotations

import json
from collections import defaultdict
from pathlib import Path


def _edit_distance_le2(a: str, b: str) -> int | None:
    """Edit distance if ≤ 2 else None (banded DP, early exit)."""
    la, lb = len(a), len(b)
    if abs(la - lb) > 2:
        return None
    prev = list(range(lb + 1))
    for i in range(1, la + 1):
        cur = [i] + [0] * lb
        row_min = i
        for j in range(1, lb + 1):
            cost = 0 if a[i - 1] == b[j - 1] else 1
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + cost)
            row_min = min(row_min, cur[j])
        if row_min > 2:
            return None
        prev = cur
    return prev[lb] if prev[lb] <= 2 else None


class Speller:
    """Per-collection popularity dictionary + suggestion engine."""

    def __init__(self, directory: str | Path):
        self.path = Path(directory) / "speller.json"
        self.counts: dict[str, int] = defaultdict(int)
        self._len_index: dict[int, set[str]] | None = None
        if self.path.exists():
            self.counts = defaultdict(
                int, json.loads(self.path.read_text()))

    # --- dictionary maintenance (gendict, incremental) ---

    def add_doc_words(self, words) -> None:
        for w in set(words):
            if w.isalpha() and 2 < len(w) < 32:
                self.counts[w] += 1
        self._len_index = None

    def remove_doc_words(self, words) -> None:
        for w in set(words):
            if self.counts.get(w, 0) > 0:
                self.counts[w] -= 1
                if not self.counts[w]:
                    del self.counts[w]
        self._len_index = None

    def save(self) -> None:
        self.path.write_text(json.dumps(dict(self.counts)))

    # --- suggestion (Speller::getRecommendation flow) ---

    def _by_len(self) -> dict[int, set[str]]:
        # read once, build into a local, publish once: a concurrent
        # add_doc_words() invalidation can no longer land between the
        # None check and a re-read (we'd return None); worst case two
        # threads build identical indexes and one wins
        ix = self._len_index
        if ix is None:
            ix = defaultdict(set)
            for w in self.counts:
                ix[len(w)].add(w)
            self._len_index = ix
        return ix

    def suggest_word(self, word: str) -> str | None:
        word = word.lower()
        base_pop = self.counts.get(word, 0)
        ix = self._by_len()
        best, best_pop = None, base_pop
        for ln in range(max(1, len(word) - 2), len(word) + 3):
            for cand in ix.get(ln, ()):
                pop = self.counts[cand]
                if pop <= best_pop or cand == word:
                    continue
                d = _edit_distance_le2(word, cand)
                if d is not None and d > 0:
                    best, best_pop = cand, pop
        return best

    def suggest_query(self, words: list[str]) -> str | None:
        """Suggestion for a whole query: replace unknown/rare words;
        None when nothing improves."""
        out, changed = [], False
        for w in words:
            s = self.suggest_word(w)
            if s is not None and self.counts.get(w.lower(), 0) == 0:
                out.append(s)
                changed = True
            else:
                out.append(w)
        return " ".join(out) if changed else None


def merged(spellers: list[Speller]) -> Speller:
    """Read-only merged view over per-shard dictionaries: popularity
    counts summed, so cluster-wide suggestions see the whole corpus
    (used by the sharded zero-result fallback). Not saveable."""
    m = Speller.__new__(Speller)
    m.path = None
    m.counts = defaultdict(int)
    for s in spellers:
        for w, c in s.counts.items():
            m.counts[w] += c
    m._len_index = None
    return m
