"""Device-resident index — the shard's termlists live in HBM.

This is the SURVEY §7 architecture stated plainly: "posting lists as
padded int32/int64 HBM arrays … the device query plane". The host-packed
path (packer.py) ships each query's termlists to the device — correct,
but on tunneled TPU backends the per-query transfer dwarfs the compute.
Here the whole shard's posting store uploads ONCE; a query ships only
its term-run offsets (a few dozen int32s) and gets the packed top-k
back: one RPC up, one down. Queries also batch (vmap over the query
axis) — the throughput mode the reference's per-query callback
architecture fundamentally cannot express.

Round-2 redesign (scale correctness):

* **Docid-tile streaming** — the doc axis is processed in fixed tiles of
  ``TILE_DOCS`` docs via ``lax.scan``, merging top-k across tiles in the
  scan carry. This is the reference's docid-range multipass
  (``Msg39.cpp:277-305`` "docid range splitting") compiled into one XLA
  program: per-query HBM is bounded by the tile cube ``[TD, T, P]``
  regardless of corpus size, and posting runs of ANY length score fully
  (the former 32k-per-run truncation is gone). Only tiles containing
  driver-term postings are scanned (the driver = smallest required
  group, exactly ``setQueryTermInfo``'s "pick smallest list" rule), so
  work scales with the rarest term, not the corpus.
* **Base + delta repack** — the device arrays split into an immutable
  *base* (built from the Rdb's on-disk runs) and a small *delta* (built
  from the memtable). A document add/delete rebuilds only the delta —
  O(memtable), not O(corpus); the base rebuilds only when the run set
  changes (dump/merge), which the Rdb amortizes over its memtable
  budget. This is SURVEY §7 hard part (d): delta memtable → periodic
  repack. Deletions ride a device-side ``dead`` doc mask (memtable
  tombstones cover whole documents — the delete path regenerates the
  full old meta list, ``XmlDoc::getMetaList`` del path — so tombstoned
  docids simply mask their base postings; re-adds live in the delta).

Layout (built from the Rdb, reference Msg2/RdbList read path collapsed):

* postings sorted by (termid, doc-index, wordpos) as resident columns:
  ``docidx`` int32 [N] (posting → doc-table index) and ``payload``
  uint32 [N] (wordpos|hg|density|spam bits, packer layout) — one pair
  for the base, one for the delta;
* host-side term directories termid → [start, end) run (``RdbMap``'s
  role, one binary search per query sublist) with precomputed per-term
  document frequencies (the Msg36/Msg37 termfreq role — exact counts,
  maintained under deletes via tombstone-pair subtraction);
* a doc table: docids uint64 (host) + siterank/langid/dead int32/bool
  [D_cap] (device) — Clusterdb's query-time role.

Per tile the kernel gathers each sublist's run segment, computes
per-(sublist, doc) occurrence ranks (the mini-merge), scatters into the
[TD, T, P] cube and reuses scorer.score_cube — identical semantics to
the host-packed path.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..index import posdb
from ..index.collection import Collection
from ..index.rdblite import merge_batches
from ..utils.log import get_logger
from . import weights
from .compiler import SUB_SYNONYM, QueryPlan, compile_query
from .packer import (MAX_POSITIONS, T_FLOOR, _bucket, _pad1, group_flags,
                     pack_payload)

log = get_logger("devindex")

#: shape-bucket floors (distinct shape tuples = one XLA compile each)
R_FLOOR = 8       # sublist rows
L_FLOOR = 256     # postings per row per tile
NT_FLOOR = 2      # active tiles
DOC_UPD_FLOOR = 64

#: docs per tile — the docid-range slice width (Msg39.cpp:277 multipass).
#: Power of two so the doc-capacity bucket is always tile-aligned.
TILE_DOCS = 2048


def _occ_ranks(termids: np.ndarray, docs: np.ndarray) -> np.ndarray:
    """Occurrence rank within each (termid, doc) run of the sorted
    columns — vectorized running-max scan (the mini-merge slot count)."""
    n = len(termids)
    if n == 0:
        return np.empty(0, np.int64)
    newpair = np.ones(n, bool)
    newpair[1:] = (termids[1:] != termids[:-1]) | (docs[1:] != docs[:-1])
    idx = np.arange(n)
    first = np.maximum.accumulate(np.where(newpair, idx, 0))
    return idx - first


def _term_dfs(termids: np.ndarray, newpair: np.ndarray):
    """(dir_termids, dir_start, df): per-term run bounds + distinct-doc
    counts over sorted columns (the Msg36 termfreq precompute)."""
    n = len(termids)
    if n == 0:
        return (np.empty(0, np.uint64), np.zeros(1, np.int64),
                np.empty(0, np.int64))
    tchange = np.ones(n, bool)
    tchange[1:] = termids[1:] != termids[:-1]
    starts = np.nonzero(tchange)[0]
    df = np.add.reduceat(newpair.astype(np.int64), starts)
    return termids[starts].copy(), np.r_[starts, n].astype(np.int64), df


class _DeltaOverflow(Exception):
    def __init__(self, needed_docs: int):
        self.needed_docs = needed_docs


@dataclass
class ResidentPlan:
    """Host-computed gather plan for one query (all tiny arrays)."""

    tiles: np.ndarray        # int32 [NT] active tile ids (driver's tiles)
    seg_start: np.ndarray    # int32 [R, NT] per-row per-tile run starts
    seg_len: np.ndarray      # int32 [R, NT] segment lengths (0 = empty)
    group: np.ndarray        # int32 [R] row → term group
    base: np.ndarray         # int32 [R] slot base within the group's P
    quota: np.ndarray        # int32 [R] max positions per (sublist, doc)
    is_base: np.ndarray      # bool [R] row reads base (vs delta) columns
    syn: np.ndarray          # uint32 [R] synonym flag (SYNONYM_WEIGHT)
    freq_weight: np.ndarray  # float32 [T]
    required: np.ndarray     # bool [T]
    negative: np.ndarray     # bool [T]
    scored: np.ndarray       # bool [T]
    qlang: int
    matchable: bool  # False = no required group, or one has no postings


class DeviceIndex:
    """One collection's postings, resident on the default device."""

    def __init__(self, coll: Collection, max_positions: int = MAX_POSITIONS,
                 tile_docs: int = TILE_DOCS):
        self.coll = coll
        self.P = max_positions
        self.TD = tile_docs
        self._built_version = -1
        self._base_fp = None
        self.full_rebuilds = 0    # O(corpus) base rebuilds (run-set moved)
        self.delta_rebuilds = 0   # O(memtable) delta-only refreshes
        self.refresh()

    # --- build / refresh -------------------------------------------------

    def refresh(self) -> bool:
        """(Re)build device arrays if the underlying Rdb changed: delta
        only while the run set is stable, full base rebuild when a
        dump/merge moved it (SURVEY §7 hard part (d))."""
        rdb = self.coll.posdb
        if rdb.version == self._built_version:
            return False
        fp = tuple((r.path.name, len(r)) for r in rdb.runs)
        if fp != self._base_fp:
            self._build_base(fp)
        try:
            self._build_delta()
        except _DeltaOverflow as e:
            # delta introduced more new docs than the doc-capacity
            # headroom: rebuild base with room and retry (rare; the next
            # Rdb dump folds the delta into runs anyway)
            self._build_base(fp, min_docs=e.needed_docs)
            self._build_delta()
        self._built_version = rdb.version
        return True

    def _build_base(self, fp, min_docs: int = 0) -> None:
        """Base arrays from the Rdb's immutable runs (merged, tombstones
        annihilated — the Msg5 read collapsed to one columnar merge)."""
        runs = self.coll.posdb.runs
        batch = merge_batches([r.batch() for r in runs]) if runs else None
        if batch is not None and len(batch):
            f = posdb.unpack(batch.keys)
            termids, docids = f["termid"], f["docid"]
            occ = _occ_ranks(termids, docids)
            self.dir_termids, self.dir_start, self.base_df = _term_dfs(
                termids, occ == 0)
            # store-cap: scoring consumes ≤ P positions per (group, doc)
            # (packer slot cap / mini-merge buffer cap), so postings past
            # occurrence P are dead weight in HBM — drop at build
            keep = occ < self.P
            termids, docids = termids[keep], docids[keep]
            payload = pack_payload({k: v[keep] for k, v in f.items()})
            siterank = f["siterank"][keep].astype(np.int32)
            langid = f["langid"][keep].astype(np.int32)
            # re-point run bounds at the capped columns
            tchange = np.ones(len(termids), bool)
            tchange[1:] = termids[1:] != termids[:-1]
            starts = np.nonzero(tchange)[0]
            self.dir_start = np.r_[starts, len(termids)].astype(np.int64)
            self.base_docids = np.unique(docids)
            docidx = np.searchsorted(self.base_docids, docids).astype(
                np.int32)
            n = len(docidx)
        else:
            self.dir_termids = np.empty(0, np.uint64)
            self.dir_start = np.zeros(1, np.int64)
            self.base_df = np.empty(0, np.int64)
            self.base_docids = np.empty(0, np.uint64)
            docidx = np.empty(0, np.int32)
            payload = np.empty(0, np.uint32)
            siterank = langid = np.empty(0, np.int32)
            n = 0
        Db = len(self.base_docids)
        headroom = max(1024, Db // 4)
        self.D_cap = _bucket(max(Db + headroom, min_docs, 1), self.TD)
        sr = np.zeros(self.D_cap, np.int32)
        dl = np.zeros(self.D_cap, np.int32)
        if n:
            # first posting per doc supplies siterank/langid
            # (reference: getSiteRank(miniMergedList[0]), Posdb.cpp:6989)
            first = np.unique(docidx, return_index=True)[1]
            sr[docidx[first]] = siterank[first]
            dl[docidx[first]] = langid[first]
        self.h_docidx = docidx  # host copy: per-query tile segmentation
        pad = lambda a, fill_dtype: a if len(a) else np.zeros(1, fill_dtype)
        self.d_docidx = jax.device_put(pad(docidx, np.int32))
        self.d_payload = jax.device_put(pad(payload, np.uint32))
        self.d_siterank = jax.device_put(sr)
        self.d_doclang = jax.device_put(dl)
        self.d_dead = jax.device_put(np.zeros(self.D_cap, bool))
        self._base_fp = fp
        self.full_rebuilds += 1
        log.info("device base built: %d postings, %d docs, %d terms "
                 "(cap %d)", n, Db, len(self.dir_termids), self.D_cap)

    def _build_delta(self) -> None:
        """Delta arrays from the memtable — O(memtable) per refresh.

        Tombstones (delbit 0) mark their docids dead in the base (whole-
        doc granularity, the delete path's regenerated meta list) and
        subtract from per-term dfs; positives become delta postings,
        with brand-new docids appended to the doc table."""
        Db = len(self.base_docids)
        mem = self.coll.posdb.mem.batch()
        self.tomb_df = np.zeros(len(self.dir_termids), np.int64)
        if not len(mem):
            self._set_empty_delta()
            return
        f = posdb.unpack(mem.keys)
        pos = f["delbit"].astype(bool)

        def base_idx_of(docids_arr):
            """(base doc indexes, found mask) for a docid array."""
            di = np.searchsorted(self.base_docids, docids_arr)
            ok = di < Db
            ok[ok] = self.base_docids[di[ok]] == docids_arr[ok]
            return di, ok

        # --- superseded base docs: explicitly tombstoned OR re-added in
        # the delta. The second case matters because an identical-content
        # re-index annihilates its tombstone/positive pairs inside the
        # memtable (MemTable newest-wins dedup), leaving no tombstone —
        # but the delta positives are authoritative (the indexer always
        # regenerates a doc's FULL meta list), so the base copy must be
        # dead-masked either way or the doc double-serves.
        t_di, t_ok = base_idx_of(f["docid"][~pos])
        p_di, p_ok = base_idx_of(f["docid"][pos])
        dead_idx = np.unique(np.concatenate([t_di[t_ok], p_di[p_ok]]))

        # --- df subtraction: every distinct (term, superseded doc) pair
        # named by a surviving tombstone OR a delta positive subtracts 1
        # from the base df — but only when the pair actually exists in
        # the base (tombstones that don't match the base, e.g. after a
        # tokenizer change, must not underflow the count)
        pair_t = np.concatenate([f["termid"][~pos][t_ok],
                                 f["termid"][pos][p_ok]])
        pair_d = np.concatenate([t_di[t_ok], p_di[p_ok]]).astype(np.int64)
        if len(pair_t):
            order = np.lexsort((pair_d, pair_t))
            pair_t, pair_d = pair_t[order], pair_d[order]
            firstp = np.ones(len(pair_t), bool)
            firstp[1:] = (pair_t[1:] != pair_t[:-1]) | \
                (pair_d[1:] != pair_d[:-1])
            pair_t, pair_d = pair_t[firstp], pair_d[firstp]
            ti = np.searchsorted(self.dir_termids, pair_t)
            ok = ti < len(self.dir_termids)
            ok[ok] = self.dir_termids[ti[ok]] == pair_t[ok]
            for term_i in np.unique(ti[ok]):
                m = ok & (ti == term_i)
                a, b = int(self.dir_start[term_i]), \
                    int(self.dir_start[term_i + 1])
                run = self.h_docidx[a:b]
                ppos = np.searchsorted(run, pair_d[m])
                inb = ppos < len(run)
                inb[inb] = run[ppos[inb]] == pair_d[m][inb]
                self.tomb_df[term_i] = int(inb.sum())

        # --- positives → delta columns ---
        if pos.any():
            fp_ = {k: v[pos] for k, v in f.items()}
            p_doc = fp_["docid"]
            db_pos, in_base = p_di, p_ok
            new_docids = np.unique(p_doc[~in_base])
            if Db + len(new_docids) > self.D_cap:
                raise _DeltaOverflow(Db + len(new_docids))
            docidx = np.where(
                in_base, db_pos,
                Db + np.searchsorted(new_docids, p_doc)).astype(np.int32)
            # delta sort key is (termid, DOC-INDEX, wordpos): new docs'
            # indexes aren't docid-monotonic, and the tile kernel needs
            # docidx-sorted runs for segmentation + rank scans
            order = np.lexsort((fp_["wordpos"], docidx, fp_["termid"]))
            fp_ = {k: v[order] for k, v in fp_.items()}
            docidx = docidx[order]
            occ = _occ_ranks(fp_["termid"], docidx)
            self.dir2_termids, self.dir2_start, self.delta_df = _term_dfs(
                fp_["termid"], occ == 0)
            keep = occ < self.P
            fp_ = {k: v[keep] for k, v in fp_.items()}
            docidx = docidx[keep]
            tchange = np.ones(len(docidx), bool)
            tchange[1:] = fp_["termid"][1:] != fp_["termid"][:-1]
            starts = np.nonzero(tchange)[0]
            self.dir2_start = np.r_[starts, len(docidx)].astype(np.int64)
            self.h2_docidx = docidx
            n2 = len(docidx)
            cap2 = _bucket(max(n2, 1), 256)
            d2d = np.zeros(cap2, np.int32)
            d2d[:n2] = docidx
            d2p = np.zeros(cap2, np.uint32)
            d2p[:n2] = pack_payload(fp_)
            self.d2_docidx = jax.device_put(d2d)
            self.d2_payload = jax.device_put(d2p)
            self.all_docids = np.concatenate([self.base_docids, new_docids])
            # doc-table updates: new docs + re-indexed docs get their
            # siterank/langid from their first delta posting
            first = np.unique(docidx, return_index=True)[1]
            upd_idx = docidx[first].astype(np.int32)
            upd_sr = fp_["siterank"][first].astype(np.int32)
            upd_dl = fp_["langid"][first].astype(np.int32)
        else:
            self._set_empty_delta(keep_tomb=True)
            upd_idx = np.empty(0, np.int32)
            upd_sr = upd_dl = upd_idx

        # apply small device-side updates (bucketed; padding repeats the
        # first element — idempotent writes)
        def bpad(a, fill):
            out = np.full(_bucket(max(len(a), 1), DOC_UPD_FLOOR), fill,
                          a.dtype)
            out[: len(a)] = a
            return out
        if len(upd_idx):
            self.d_siterank, self.d_doclang = _apply_doc_meta(
                self.d_siterank, self.d_doclang,
                bpad(upd_idx, upd_idx[0]), bpad(upd_sr, upd_sr[0]),
                bpad(upd_dl, upd_dl[0]))
        if len(dead_idx):
            di32 = dead_idx.astype(np.int32)
            self.d_dead = _apply_dead(self.d_dead, bpad(di32, di32[0]))
        self.delta_rebuilds += 1

    def _set_empty_delta(self, keep_tomb: bool = False) -> None:
        self.dir2_termids = np.empty(0, np.uint64)
        self.dir2_start = np.zeros(1, np.int64)
        self.delta_df = np.empty(0, np.int64)
        self.h2_docidx = np.empty(0, np.int32)
        self.d2_docidx = jax.device_put(np.zeros(1, np.int32))
        self.d2_payload = jax.device_put(np.zeros(1, np.uint32))
        self.all_docids = self.base_docids
        if not keep_tomb:
            self.delta_rebuilds += 1

    @property
    def n_docs(self) -> int:
        return len(self.all_docids)

    # --- planning --------------------------------------------------------

    def _runs_of(self, termid: int):
        """[(is_base, start, end)] posting runs for a termid — base run
        from the run directory, delta run from the memtable directory."""
        out = []
        for is_base, dirs, starts in (
                (True, self.dir_termids, self.dir_start),
                (False, self.dir2_termids, self.dir2_start)):
            i = int(np.searchsorted(dirs, np.uint64(termid)))
            if i < len(dirs) and dirs[i] == termid:
                a, b = int(starts[i]), int(starts[i + 1])
                if b > a:
                    out.append((is_base, a, b))
        return out

    def _df_of(self, termid: int) -> int:
        """Exact document frequency under pending deletes/re-adds:
        base df − superseded-doc pairs + delta df."""
        df = 0
        i = int(np.searchsorted(self.dir_termids, np.uint64(termid)))
        if i < len(self.dir_termids) and self.dir_termids[i] == termid:
            df += int(self.base_df[i]) - int(self.tomb_df[i])
        j = int(np.searchsorted(self.dir2_termids, np.uint64(termid)))
        if j < len(self.dir2_termids) and self.dir2_termids[j] == termid:
            df += int(self.delta_df[j])
        return max(df, 0)

    def plan(self, qplan: QueryPlan) -> ResidentPlan:
        T = _bucket(max(len(qplan.groups), 1), T_FLOOR)
        rows = []  # (is_base, a, b, group, slot_base, quota, syn)
        dfs = np.zeros(max(len(qplan.groups), 1), np.int64)
        matchable = True
        req_idx = []
        for g_i, g in enumerate(qplan.groups):
            subs = g.sublists
            quota = max(self.P // max(len(subs), 1), 1)
            any_postings = False
            gdf = 0
            for s_i, sub in enumerate(subs):
                syn = 1 if sub.kind == SUB_SYNONYM else 0
                for is_base, a, b in self._runs_of(sub.termid):
                    rows.append((is_base, a, b, g_i, s_i * quota, quota,
                                 syn))
                    any_postings = True
                # group df = max over sublists: exact for word+bigram
                # groups (bigram docs ⊆ word docs by construction) —
                # equals the host packer's np.unique union
                gdf = max(gdf, self._df_of(sub.termid))
            dfs[g_i] = gdf
            if g.required and not g.negative:
                req_idx.append(g_i)
                if not any_postings:
                    matchable = False
        if not req_idx:
            # no positive required group (pure-negative / empty query):
            # nothing can match — the reference's early-out (Msg39)
            matchable = False

        # active tiles = tiles holding driver-group postings (driver =
        # required group with fewest docs, setQueryTermInfo's rule)
        tiles = np.empty(0, np.int64)
        if matchable:
            driver = min(req_idx, key=lambda i: dfs[i])
            parts = []
            for is_base, a, b, g_i, _sb, _q, _sy in rows:
                if g_i != driver:
                    continue
                col = self.h_docidx if is_base else self.h2_docidx
                parts.append(col[a:b] // self.TD)
            tiles = np.unique(np.concatenate(parts)) if parts else tiles
            if not len(tiles):
                matchable = False

        # per-(row, tile) run segments: runs are docidx-sorted, so a
        # tile's slice is one searchsorted pair (RdbMap page walk)
        R, NT = len(rows), len(tiles)
        seg_start = np.zeros((R, NT), np.int32)
        seg_len = np.zeros((R, NT), np.int32)
        if NT:
            lo = (tiles * self.TD).astype(np.int32)
            hi = ((tiles + 1) * self.TD).astype(np.int32)
            for r, (is_base, a, b, *_rest) in enumerate(rows):
                col = self.h_docidx if is_base else self.h2_docidx
                sl = col[a:b]
                s = a + np.searchsorted(sl, lo)
                e = a + np.searchsorted(sl, hi)
                seg_start[r] = s
                seg_len[r] = e - s

        required, negative, scored = group_flags(qplan, T)
        freqw = _pad1(
            weights.term_freq_weight(dfs[: len(qplan.groups)],
                                     max(self.coll.num_docs, 1)), T, 0.5)
        arr = np.array([(g, sb, q, ib, sy) for ib, _a, _b, g, sb, q, sy
                        in rows], np.int64).reshape(-1, 5) if rows else \
            np.zeros((0, 5), np.int64)
        return ResidentPlan(
            tiles=tiles.astype(np.int32), seg_start=seg_start,
            seg_len=seg_len,
            group=arr[:, 0].astype(np.int32),
            base=arr[:, 1].astype(np.int32),
            quota=arr[:, 2].astype(np.int32),
            is_base=arr[:, 3].astype(bool),
            syn=arr[:, 4].astype(np.uint32),
            freq_weight=freqw, required=required, negative=negative,
            scored=scored, qlang=qplan.lang, matchable=matchable)

    # --- execution -------------------------------------------------------

    def search(self, q: str | QueryPlan, topk: int = 64, lang: int = 0):
        """One query → (docids, scores, n_matched)."""
        return self.search_batch([q], topk=topk, lang=lang)[0]

    def search_batch(self, queries, topk: int = 64, lang: int = 0):
        """Batched execution: B queries in ONE device round trip (vmap
        over the query axis), each scanning its active docid tiles."""
        qplans = [q if isinstance(q, QueryPlan) else compile_query(q, lang)
                  for q in queries]
        plans = [self.plan(qp) for qp in qplans]
        live = [i for i, p in enumerate(plans)
                if p.matchable and len(p.tiles) and len(p.group)]
        results = [(np.empty(0, np.uint64), np.empty(0, np.float32), 0)
                   ] * len(plans)
        if not live:
            return results
        # quantize shape buckets (powers of two) — every distinct
        # (B, R, NT, L) tuple is an XLA compile; wasted lanes are masked
        # compute, recompiles are 20-40s stalls
        R = _bucket(max(len(plans[i].group) for i in live), R_FLOOR)
        NT = _bucket(max(len(plans[i].tiles) for i in live), NT_FLOOR)
        L = _bucket(max(int(plans[i].seg_len.max()) for i in live),
                    L_FLOOR)
        T = max(len(plans[i].required) for i in live)
        B = _bucket(len(live), 4)
        k = min(topk, self.D_cap)

        def pad_plan(p: ResidentPlan | None):
            if p is None:  # batch-padding lane: all-empty segments
                return (np.zeros(NT, np.int32), np.zeros((R, NT), np.int32),
                        np.zeros((R, NT), np.int32), np.zeros(R, np.int32),
                        np.zeros(R, np.int32), np.ones(R, np.int32),
                        np.ones(R, bool), np.zeros(R, np.uint32),
                        np.full(T, 0.5, np.float32), np.zeros(T, bool),
                        np.zeros(T, bool), np.zeros(T, bool),
                        np.int32(0))
            r, nt = p.seg_start.shape
            tiles = np.zeros(NT, np.int32)
            tiles[:nt] = p.tiles
            ss = np.zeros((R, NT), np.int32)
            ss[:r, :nt] = p.seg_start
            sl = np.zeros((R, NT), np.int32)
            sl[:r, :nt] = p.seg_len
            pad1 = lambda a, fill: _pad1(a, R, fill)
            return (tiles, ss, sl, pad1(p.group, 0), pad1(p.base, 0),
                    pad1(p.quota, 1), pad1(p.is_base, True),
                    pad1(p.syn, 0),
                    _pad1(p.freq_weight, T, 0.5),
                    _pad1(p.required, T, False),
                    _pad1(p.negative, T, False),
                    _pad1(p.scored, T, False), np.int32(p.qlang))

        padded = [pad_plan(plans[i]) for i in live] \
            + [pad_plan(None)] * (B - len(live))
        args = [np.stack([p[j] for p in padded]) for j in range(13)]
        dev_args = jax.device_put(args)
        out = np.asarray(_resident_tiled(
            self.d_docidx, self.d_payload, self.d2_docidx, self.d2_payload,
            self.d_siterank, self.d_doclang, self.d_dead,
            np.int32(self.n_docs), *dev_args,
            tile_docs=self.TD, n_positions=self.P, run_l=L, n_groups=T,
            topk=k))  # [B, 1 + 2k]

        for b, i in enumerate(live):
            row = out[b]
            n_matched = int(row[0])
            idx = row[1:1 + k].astype(np.int64)
            scores = row[1 + k:].view(np.float32)
            keep = scores > 0.0
            results[i] = (
                self.all_docids[np.clip(idx[keep], 0,
                                        max(self.n_docs - 1, 0))],
                scores[keep], n_matched)
        return results


@jax.jit
def _apply_doc_meta(sr, dl, idx, vsr, vdl):
    return sr.at[idx].set(vsr), dl.at[idx].set(vdl)


@jax.jit
def _apply_dead(dead, idx):
    return dead.at[idx].set(True)


@partial(jax.jit,
         static_argnames=("tile_docs", "n_positions", "run_l", "n_groups",
                          "topk"))
def _resident_tiled(d_docidx, d_payload, d2_docidx, d2_payload,
                    d_siterank, d_doclang, d_dead, n_docs_total,
                    tiles, seg_start, seg_len, group, base, quota,
                    is_base, syn, freqw, required, negative, scored, qlang,
                    tile_docs: int, n_positions: int, run_l: int,
                    n_groups: int, topk: int):
    """vmapped tiled kernel: scan docid tiles, gather run segments →
    rank → cube → score → running top-k merge (the docid-range multipass
    of Msg39.cpp:277 fused into one program)."""
    from .scorer import scatter_cube, score_cube

    TD = tile_docs
    L = run_l
    Nb = d_docidx.shape[0]
    Nd = d2_docidx.shape[0]
    Dc = d_dead.shape[0]
    k_tile = min(topk, TD)

    def one(tiles, seg_start, seg_len, group, base, quota, is_base, syn,
            freqw, required, negative, scored, qlang):
        lane = jnp.arange(L, dtype=jnp.int32)[None, :]

        def tile_step(carry, xs):
            bs, bi, nm = carry
            tile_id, s0, sl = xs            # [], [R], [R]
            base_doc = tile_id * TD
            idx = s0[:, None] + lane
            gb = d_docidx[jnp.clip(idx, 0, Nb - 1)]
            gd = d2_docidx[jnp.clip(idx, 0, Nd - 1)]
            docg = jnp.where(is_base[:, None], gb, gd)
            pb = d_payload[jnp.clip(idx, 0, Nb - 1)]
            pd = d2_payload[jnp.clip(idx, 0, Nd - 1)]
            pay = (jnp.where(is_base[:, None], pb, pd)
                   | syn[:, None] << jnp.uint32(31))
            inlane = lane < sl[:, None]                     # [R, L]
            dead = d_dead[jnp.clip(docg, 0, Dc - 1)]
            # tombstoned docs mask only their BASE postings; a re-added
            # doc's fresh postings live in the delta and stay valid
            valid = inlane & ~(dead & is_base[:, None])
            docrow = jnp.where(inlane, docg - base_doc, TD)
            # occurrence rank within each (row, doc): rows are
            # docidx-sorted, so first-index-of-run is a running max over
            # change markers — an O(L) associative scan
            change = jnp.concatenate(
                [jnp.ones((docrow.shape[0], 1), bool),
                 docrow[:, 1:] != docrow[:, :-1]], axis=1)
            first = jax.lax.associative_scan(
                jnp.maximum,
                jnp.where(change, jnp.broadcast_to(lane, change.shape), 0),
                axis=1)
            rank = lane - first
            slot = base[:, None] + rank
            valid = valid & (rank < quota[:, None])
            # dead lanes go to the drop row so their scatters can never
            # land in a sibling sublist's live slots (duplicate-index
            # scatter order is implementation-defined on TPU)
            docrow = jnp.where(valid, docrow, TD)
            cube, pvalid = scatter_cube(docrow, pay, slot, valid, TD,
                                        n_positions, row_group=group,
                                        n_groups=n_groups)
            sr = jax.lax.dynamic_slice(d_siterank, (base_doc,), (TD,))
            dl = jax.lax.dynamic_slice(d_doclang, (base_doc,), (TD,))
            n_in = jnp.clip(n_docs_total - base_doc, 0, TD)
            nmt, ts, ti = score_cube(
                cube, pvalid, freqw, required, negative, scored,
                sr, dl, qlang, n_in, topk=k_tile)
            cs = jnp.concatenate([bs, ts])
            ci = jnp.concatenate([bi, (base_doc + ti).astype(jnp.int32)])
            nbs, sel = jax.lax.top_k(cs, topk)
            return (nbs, ci[sel], nm + nmt.astype(jnp.int32)), None

        init = (jnp.zeros((topk,), jnp.float32),
                jnp.zeros((topk,), jnp.int32), jnp.zeros((), jnp.int32))
        (bs, bi, nm), _ = jax.lax.scan(
            tile_step, init,
            (tiles, jnp.moveaxis(seg_start, 1, 0),
             jnp.moveaxis(seg_len, 1, 0)))
        return jnp.concatenate([
            jnp.atleast_1d(nm.astype(jnp.uint32)),
            bi.astype(jnp.uint32),
            jax.lax.bitcast_convert_type(bs, jnp.uint32),
        ])

    return jax.vmap(one)(tiles, seg_start, seg_len, group, base, quota,
                         is_base, syn, freqw, required, negative, scored,
                         qlang)
