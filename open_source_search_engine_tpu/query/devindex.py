"""Device-resident index — two-phase pruned search, the shard's postings
and per-(term, doc) impact bounds live in HBM.

This is the SURVEY §7 architecture plus the reference's own pruning idea
compiled into one XLA program. The reference never scores every docid:
``intersectLists10_r`` computes a cheap ``maxPossibleScore`` per docid and
skips docids that cannot beat the TopTree floor (``Posdb.cpp:6052``; the
"pre-advance" pruning around ``docIdLoop:`` 6137). On a TPU the same idea
becomes two dense phases:

* **Phase 1 — candidates.** Per term group, accumulate a per-doc score
  *upper bound* over the whole doc space ``[T, D]``: precomputed
  per-(term, doc) **impact columns** (the hashgroup-deduped sum of
  position scores — an admissible bound on the group's single-term
  score, and exact for docs with ≤ MAX_TOP distinct hashgroups) are
  added — via plain vectorized adds for high-df terms kept as dense
  ``[V, D]`` rows, and one fused gather+scatter for sparse/delta terms.
  Base and delta accumulate separately so the dead-doc vector masks
  only base contributions (re-adds serve from the delta; tombstones
  that no longer match the base still kill the doc). Boolean
  intersection (every required group present, no negative present —
  ``Msg39``'s early-outs) plus the min-over-groups/pairs bound yields
  an admissible per-doc upper bound; ``approx_max_k`` picks the top-κ
  candidates. The exact match count and the exact max bound among
  *non*-selected docs come out of the same pass, so pruning is
  verifiable.
* **Phase 2 — exact.** For the κ candidates only, gather the real
  postings (run starts come from precomputed ``runstart|count`` columns
  — no per-query binary search, no big scatter) into the dense
  ``[T, P, κ]`` position cube and score with the exact docIdLoop
  semantics (scorer.min_scores — identical math to the host-packed
  path, so parity holds by construction).
* **Escalation.** If the max bound among non-candidates exceeds the
  k-th exact score (beyond a 1e-4 tie tolerance), rerun with κ×4
  (rare: bounds are tight). This makes the pruning *lossless* — the
  TPU analog of TopTree's floor check, and of the reference's recall
  re-loop (``Msg40.cpp:2117``).
* **Full-cube path (F2).** Queries whose every required group is a
  high-df term defeat bound pruning — the intersection is most of the
  corpus and pair bounds can't rank it (the pair score's distance term
  is unknowable without positions). The reference grinds these with
  its per-docid loop; here they route to a second kernel that scores
  the WHOLE doc axis exactly: the heaviest terms' position cubes are
  **materialized at build time** as [P, D] rows (plain slices at query
  time — zero gather), smaller sublists (bigrams, deltas) scatter
  their postings in at posting granularity, and the same
  scorer.min_scores runs over [T, P, D]. Dense full-lane compute is
  exactly what the VPU is good at — no pruning needed, no escalation
  ladder, still bit-parity with the host path.

Why this shape: on v5e, scalar gather runs ~60 Melem/s and scatter ~10
Melem/s, while dense row ops and 128-lane block gathers run 10-100×
faster. So the per-query work that scales with the corpus (phase 1) uses
only dense ops + one bounded scatter, and the slow scalar gathers are
confined to phase 2's κ·T·P lanes. The former design (docid-tile scan
with per-tile gather+rank+scatter) paid the scatter price on every
posting of every tile and recompiled per posting-length bucket; this one
has no per-query shape that depends on posting-list length.

Admissibility of the bounds (what makes pruning exact):

* group single-term score = Σ of the top-MAX_TOP hashgroup-deduped
  position scores ≤ the stored impact (Σ over ALL mapped-hashgroup
  maxima + every inlink-text occurrence; synonym sublists score ×0.90²
  at query time — bounded by 1);
* pair score ≤ BASE·maxposw_i·maxposw_j·fw_i·fw_j (min distance term
  ≥ 1 after the qdist adjustment) and BASE·maxposw² ≤ impact, so
  √(impact_i·fw_i²·impact_j·fw_j²) bounds every pair term;
* siterank/language multipliers are exact (dense per-doc columns);
* the final ×(1+1e-5) guards float reassociation (the escalation check
  allows 1e-4 so exact ties don't escalate forever).

Incremental updates (SURVEY §7 hard part (d)): the base columns build
once per Rdb run-set move (dump/merge); a memtable change rewrites only
the delta tail of the preallocated device columns via donated
dynamic-update-slice — O(memtable) transfer, no O(corpus) copies, no
double residency. Document frequencies stay exact under deletes via the
tombstone-pair subtraction (the Msg36/37 termfreq role).

Capacity: run starts are full int32 column offsets (counts ride a
separate uint8 column), so the pack limit is 2^31 stored postings and
HBM binds first — a 16 GB v5e holds roughly 1.3M web pages' columns
plus dense/cube rows. Beyond that the corpus must shard
(``parallel/``), same as the reference's per-host index splits.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..build import devbuild
from ..index import clusterdb as clusterdb_mod
from ..index import posdb
from ..index.collection import Collection
from ..index.rdblite import merge_batches
from ..utils import devwatch, jitwatch, trace
from ..utils.log import get_logger
from ..utils.stats import g_stats
from . import devcheck, weights
from .compiler import SUB_SYNONYM, QueryPlan, compile_query
from .packer import (IMPACT_SCALE, MAX_POSITIONS, T_FLOOR, TABLE_SIZE,
                     _bucket, _pad1, demote_impacts, group_flags,
                     pack_payload, pad_table)
from .scorer import final_multipliers, min_scores, presence_table_ok

log = get_logger("devindex")

# the device layer is the first import on every jit path — turning the
# watcher on here means OSSE_JITWATCH=1 covers tests, bench, and serve
# without each entry point opting in
jitwatch.maybe_enable()
devwatch.maybe_enable()

#: bounded wave-histogram vocabulary. The per-round wave stat used to
#: be built with an f-string over (kind-combo, wave count) — one
#: histogram per distinct count, unbounded cardinality (the osselint
#: ``stats-cardinality`` rule now bans that spelling). This table IS
#: the bound: kind combos × count buckets, fixed at import.
_WAVE_NBUCKETS = (1, 2, 4, 8)
_WAVE_STAT = {(k, n): f"devindex.wave_{k}_n{n}"
              for k in ("f1", "f2", "f1+f2") for n in _WAVE_NBUCKETS}


def _wave_nbucket(n: int) -> int:
    for b in _WAVE_NBUCKETS:
        if n <= b:
            return b
    return _WAVE_NBUCKETS[-1]

#: shape-bucket floors (distinct shape tuples = one XLA compile each)
RD_FLOOR = 4      # dense rows
RS_FLOOR = 4      # sparse rows
#: sparse gather lane buckets (length-bucketed termlist tiles, SURVEY
#: §7 stage-8): waves whose longest sparse run is short ride a short
#: tile instead of paying the full 2048-lane gather per row — the
#: padding bytes were most of the sparse HBM traffic for everyday
#: queries (the dense threshold D_cap//64 keeps runs under the top)
LSP_BUCKETS = (128, 512, 2048)
LSP_FLOOR = LSP_BUCKETS[0]
B_FLOOR = 4
KAPPA_FLOOR = 256  # phase-2 candidate count
DOC_UPD_FLOOR = 64

#: doc-capacity quantum (D_cap bucket unit)
DOC_QUANTUM = 2048

#: HBM budget for dense [V, D_cap] impact+runstart rows (8 bytes/doc/
#: term). Sized so that at web-shard scale (~500k docs) the heaviest
#: ~400 terms are dense, and at 100k docs EVERY df>tau term is — a
#: sparse run that should have been dense pays scalar-scatter for its
#: whole doc run on every query, measured as THE dominant query cost
DENSE_BUDGET_BYTES = 1536 << 20

#: minimum df for a term to earn a dense impact row
DENSE_MIN_DF = 1024

#: sparse doc-runs are CHUNKED to this many lanes per row, so the lane
#: bucket is a compile-time constant (no per-query Lsp recompiles) and
#: pad lanes never exceed one chunk per term — unbudgeted big terms
#: degrade linearly instead of rectangularly
LSP_MAX = 2048

#: HARD CAP for materialized [P, D_cap] cube rows (P·4 bytes/doc/term)
#: — the actual budget is adaptive: after columns + dense rows claim
#: their bytes, the cube gets what HBM can spare (more cube rows →
#: more corpus-wide drivers resolve through the flat-cost direct
#: kernel instead of the assembling F2)
CUBE_BUDGET_BYTES = 5 << 30
#: usable HBM for the resident set (v5e 16 GB minus XLA/runtime slack)
HBM_USABLE_BYTES = 13 << 30
#: head-room reserved for wave intermediates next to the resident set
WAVE_RESERVE_BYTES = 5 << 29

#: direct-kernel scatter tail budget: total non-cube postings a query
#: may scatter into its quarter-built plane before falling back to the
#: generic F2 (scalar scatter runs ~10 Melem/s — keep the tail small)
FD_SCATTER_MAX_LANES = 32768
FD_SCATTER_MAX_ROWS = 32

#: routing: drivers at or below this df use phase-1 pruning (F1);
#: bigger drivers go to the full-cube kernel (F2), whose cost is flat
#: in the driver size (F1's phase-2 gathers scale with κ ≥ driver_df —
#: measured 4× slower at κ=8192 than the whole F2 kernel)
CUBE_MIN_DF = 2048

#: F2 eligibility: non-cube sublists must scatter at most this many
#: postings (the per-row scatter lane bucket cap)
F2_SCATTER_MAX = 16384
F2_LPOST_FLOOR = 4096
F2_B_FLOOR = 4
RC_FLOOR = 4
RP_FLOOR = 4

#: posting/doc column padding quantum
COL_QUANTUM = 1 << 15

#: run starts and counts live in SEPARATE columns (int32 runstart +
#: uint8 count) — the former rs<<5|cnt int32 pack capped a shard at
#: 2^26 stored postings (~500k pages); split, the pack limit is the
#: int32 index space and HBM binds first (~1.3M pages on a 16 GB v5e)
_MAX_POSTINGS = 1 << 31
#: posting doc+occurrence pack: docidx<<4 | occ in one uint32 (occ <
#: MAX_POSITIONS = 16 → 4 bits; doc capacity 2^28) — one gather feeds
#: both fields in the F2/FD scatter paths
_OCC_BITS = 4
_OCC_MASK = 15

#: escalation tie tolerance (× the 1e-5 admissibility inflation)
_TIE_TOL = 1.0001


def _posscore_np(f: dict[str, np.ndarray]) -> np.ndarray:
    """Per-posting single-term score (BASE · posw², the initWeights
    tables — Posdb.cpp:1105-1252), vectorized numpy for build time."""
    hg = f["hashgroup"]
    hgw = weights.HASH_GROUP_WEIGHTS[hg]
    denw = weights.DENSITY_WEIGHTS[f["densityrank"]]
    spamw = np.where(hg == posdb.HASHGROUP_INLINKTEXT,
                     weights.LINKER_WEIGHTS[f["wordspamrank"]],
                     weights.WORD_SPAM_WEIGHTS[f["wordspamrank"]])
    posw = hgw * denw * spamw
    return weights.BASE_SCORE * posw * posw


def _impacts_np(f: dict[str, np.ndarray], termids: np.ndarray,
                docidx: np.ndarray, runstart: np.ndarray) -> np.ndarray:
    """EXACT per-(term, doc) single-term score (pre-freq-weight): Σ over
    the top-MAX_TOP of {per-mapped-hashgroup position maxima} ∪ {every
    inlink-text occurrence individually} — exactly the candidate set
    getSingleTermScore tops-and-sums (Posdb.cpp:3087), exactly cut.
    Equal (mod float association) to what scorer.min_scores computes
    from the stored positions, so (a) it is an admissible AND tight
    phase-1 bound, and (b) the direct-cube kernel can use it AS the
    single-term score without touching positions."""
    n = len(termids)
    if n == 0:
        return np.empty(0, np.float32)
    ps = _posscore_np(f)
    mhg = weights.MAPPED_HASHGROUP[f["hashgroup"]].astype(np.int8)
    is_inlink = f["hashgroup"] == posdb.HASHGROUP_INLINKTEXT
    # candidate pool per (term, doc): one max per non-inlink mapped
    # hashgroup + each inlink occurrence individually. Build it by
    # collapsing non-inlink (term, doc, mhg) groups to their max and
    # keeping inlink rows as-is, then rank within (term, doc).
    o = np.lexsort((mhg, docidx, termids))
    ps_o, mh_o, il_o = ps[o], mhg[o], is_inlink[o]
    t_o, d_o = termids[o], docidx[o]
    gch = np.ones(n, bool)
    gch[1:] = ((t_o[1:] != t_o[:-1]) | (d_o[1:] != d_o[:-1])
               | (mh_o[1:] != mh_o[:-1]))
    # candidates: non-inlink groups contribute their first-row slot
    # (value = group max); inlink rows contribute every row
    gid = np.cumsum(gch) - 1
    gstart = np.nonzero(gch)[0]
    gmax = np.maximum.reduceat(ps_o, gstart)
    cand_mask = il_o | gch
    cval = np.where(il_o, ps_o, gmax[gid])[cand_mask]
    ct = t_o[cand_mask]
    cd = d_o[cand_mask]
    m = len(cval)
    # rank candidates within each (term, doc) pair (descending) and
    # zero everything past MAX_TOP before the pair sum
    pch = np.ones(m, bool)
    pch[1:] = (ct[1:] != ct[:-1]) | (cd[1:] != cd[:-1])
    pstart = np.nonzero(pch)[0]
    pair_id = np.cumsum(pch) - 1               # candidate → owning pair
    order2 = np.lexsort((-cval, pair_id))
    ranked = np.empty(m, np.int64)
    pos_in_pair = np.arange(m) - pstart[pair_id[order2]]
    ranked[order2] = pos_in_pair
    cval_cut = np.where(ranked < weights.MAX_TOP, cval, 0.0)
    imp = np.add.reduceat(cval_cut, pstart)
    assert len(imp) == len(runstart)
    # tiny floor keeps zero-weight hashgroups present-but-worthless
    return np.maximum(imp, 1e-30).astype(np.float32)


def _occ_ranks(termids: np.ndarray, docs: np.ndarray) -> np.ndarray:
    """Occurrence rank within each (termid, doc) run of the sorted
    columns — vectorized running-max scan (the mini-merge slot count)."""
    n = len(termids)
    if n == 0:
        return np.empty(0, np.int64)
    newpair = np.ones(n, bool)
    newpair[1:] = (termids[1:] != termids[:-1]) | (docs[1:] != docs[:-1])
    idx = np.arange(n)
    first = np.maximum.accumulate(np.where(newpair, idx, 0))
    return idx - first


def _term_dfs(termids: np.ndarray, newpair: np.ndarray):
    """(dir_termids, dir_start, df): per-term run bounds + distinct-doc
    counts over sorted columns (the Msg36 termfreq precompute)."""
    n = len(termids)
    if n == 0:
        return (np.empty(0, np.uint64), np.zeros(1, np.int64),
                np.empty(0, np.int64))
    tchange = np.ones(n, bool)
    tchange[1:] = termids[1:] != termids[:-1]
    starts = np.nonzero(tchange)[0]
    df = np.add.reduceat(newpair.astype(np.int64), starts)
    return termids[starts].copy(), np.r_[starts, n].astype(np.int64), df


def _pad_col(a: np.ndarray, size: int) -> np.ndarray:
    out = np.zeros(size, a.dtype)
    out[: len(a)] = a
    return out


def _env_int(name: str, default: int) -> int:
    """Env-overridable tuning constant — tests and the multichip dryrun
    scale the dense/cube thresholds down so TINY per-shard corpora still
    build dense+cube rows and exercise every kernel route (production
    defaults are sized for real shards)."""
    import os
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


@partial(jax.jit, donate_argnums=0)
def _write_tail(buf, tail, offset):
    """Donated in-place rewrite of the delta tail of a device column."""
    return jax.lax.dynamic_update_slice(buf, tail, (offset,))


def _block_topn(x, n_sel: int, per_block: int = 8):
    """Top-``per_block``-per-block candidate selection: (vals [n_sel],
    idx [n_sel], missed_max) — n_sel/per_block blocks, the best
    per_block docs of each selected, ``missed_max`` = the best value
    NOT selected ((per_block+1)-th best over any block).

    This replaces ``lax.top_k``/``approx_max_k`` for candidate
    selection: both lower to sort-like programs that cost 300 ms-2.4 s
    per batch on a [B, 131072] score axis (measured), while this is a
    handful of reshaped max-reduces (~2 ms). per_block sets the
    collision robustness: selecting k winners across nb blocks misses
    only when one block holds > per_block of them — at per_block=8 and
    k ≈ n_sel/4 that's a ≲1% event (Poisson tail), vs near-certain at
    per_block=2 with few blocks. The caller compares ``missed_max``
    against its result floor and escalates with more blocks — the same
    lossless pruning contract as everywhere else."""
    D = x.shape[0]
    nb = max(n_sel // per_block, 1)
    while D % nb:  # D is a power-of-two bucket, but stay safe
        nb //= 2
    R = D // nb
    xb = x.reshape(nb, R)
    iota = jnp.arange(R, dtype=jnp.int32)[None, :]
    base = jnp.arange(nb, dtype=jnp.int32) * R
    vals_l, idx_l = [], []
    cur = xb
    for t in range(per_block):
        m = jnp.max(cur, axis=1)
        a = jnp.argmax(cur, axis=1).astype(jnp.int32)
        vals_l.append(m if t == 0 else jnp.maximum(m, 0.0))
        idx_l.append(base + a)
        cur = jnp.where(iota == a[:, None], -jnp.inf, cur)
    missed = jnp.maximum(jnp.max(cur), 0.0)
    return (jnp.concatenate(vals_l), jnp.concatenate(idx_l), missed)


def _block_top2(x, n_sel: int):
    return _block_topn(x, n_sel, per_block=2)


@partial(jax.jit, static_argnames=("V", "D", "n_lanes"))
def _build_dense_rows(d_doc, d_imp, d_rs, d_cnt, starts, cum,
                      V: int, D: int, n_lanes: int):
    """Dense [V, D] impact + runstart + count rows, built by one
    flattened scatter over the doc-pair columns. Lane → row via
    searchsorted on the cumulative-length table; everything stays on
    device — the host ships only (starts, cum), a few KB."""
    R = starts.shape[0]
    lane = jnp.arange(n_lanes, dtype=jnp.int32)
    row = jnp.clip(jnp.searchsorted(cum, lane, side="right") - 1,
                   0, R - 1).astype(jnp.int32)
    src = jnp.clip(starts[row] + lane - cum[row], 0,
                   d_doc.shape[0] - 1)
    valid = lane < cum[-1]
    doc = d_doc[src].astype(jnp.int32)
    # dst fits int32: V·D ≤ DENSE_BUDGET/7 < 2^31
    dst = jnp.where(valid, row * D + doc, V * D)
    imp = jnp.zeros((V * D,), d_imp.dtype).at[dst].set(
        d_imp[src], mode="drop")
    rs = jnp.zeros((V * D,), jnp.int32).at[dst].set(
        d_rs[src], mode="drop")
    cnt = jnp.zeros((V * D,), jnp.uint8).at[dst].set(
        d_cnt[src], mode="drop")
    return imp.reshape(V, D), rs, cnt


class _DeltaOverflow(Exception):
    def __init__(self, needed_docs: int = 0, needed_cols: int = 0):
        self.needed_docs = needed_docs
        self.needed_cols = needed_cols


@dataclass
class ResidentPlan:
    """Host-computed execution plan for one query (all tiny arrays)."""

    # dense rows: term's doc run lives as a dense [D_cap] impact row
    d_slot: np.ndarray       # int32 [Rd] dense matrix row (-1 = pad)
    d_group: np.ndarray      # int32 [Rd]
    d_base: np.ndarray       # int32 [Rd] slot base within the group's P
    d_quota: np.ndarray      # int32 [Rd]
    d_syn: np.ndarray        # uint32 [Rd]
    # sparse rows: contiguous run of the doc/impact/runstart columns
    s_start: np.ndarray      # int32 [Rs] absolute offset into doc cols
    s_len: np.ndarray        # int32 [Rs]
    s_group: np.ndarray      # int32 [Rs]
    s_base: np.ndarray       # int32 [Rs]
    s_quota: np.ndarray      # int32 [Rs]
    s_syn: np.ndarray        # uint32 [Rs]
    s_isbase: np.ndarray     # bool [Rs] (base postings dead-mask)
    # full-cube (F2) rows: materialized cube slices + posting scatters
    c_slot: np.ndarray       # int32 [Rc] cube matrix row (-1 = pad)
    c_dslot: np.ndarray      # int32 [Rc] dense row (count source)
    c_group: np.ndarray      # int32 [Rc]
    c_base: np.ndarray       # int32 [Rc]
    c_quota: np.ndarray      # int32 [Rc]
    c_syn: np.ndarray        # uint32 [Rc]
    p_start: np.ndarray      # int32 [Rp] absolute posting offset
    p_len: np.ndarray        # int32 [Rp]
    p_group: np.ndarray      # int32 [Rp]
    p_base: np.ndarray       # int32 [Rp]
    p_quota: np.ndarray      # int32 [Rp]
    p_syn: np.ndarray        # uint32 [Rp]
    p_isbase: np.ndarray     # bool [Rp]
    # per-group query state
    freq_weight: np.ndarray  # float32 [T]
    required: np.ndarray     # bool [T]
    negative: np.ndarray     # bool [T]
    scored: np.ndarray       # bool [T]
    counts: np.ndarray       # bool [T] groups entering the min-score
    table: np.ndarray        # bool [TABLE_SIZE] boolean truth table
    qlang: int
    matchable: bool
    driver_df: int = 0       # min required-group df (routes F1 vs F2)
    kappa_min: int = 0       # escalation floor (set on a pruning miss)
    k2_min: int = 0          # phase-2 width floor (escalates with κ so
    #                          the terminal rung scores everything and
    #                          the ladder stays lossless)
    #: direct-cube (FD) eligibility: every group's contributing runs
    #: are base cube rows whose slot_plan layout is quarter-aligned
    #: (1 sublist = full row, 2 = half+half, 3 = half+quarter+quarter).
    #: The group's [P, D] plane is then FOUR quarter-row gathers from
    #: the resident cube — no per-query cube assembly at all.
    direct_ok: bool = False
    g_quarter: np.ndarray | None = None  # int32 [T, 4] absolute quarter
    g_qsyn: np.ndarray | None = None     # uint32 [T, 4] synonym flags
    #: True only for boolean queries — non-boolean waves compile the
    #: truth-table gate out (its [D]-wide gather costs ~140 ms/wave)
    has_table: bool = False
    #: numeric range constraints / sort override (gbmin:/gbmax:/
    #: gbsortby: — waves group by identical specs; the [D] filter and
    #: sort columns are per-wave kernel args)
    filters: tuple = ()
    sortby: tuple | None = None
    #: shift applied to sort keys (keys must stay positive for the
    #: match gate) — the MESH layer passes the cross-shard minimum so
    #: per-shard keys stay comparable under the Msg3a merge
    sort_base: float = 0.0
    #: number of scored∧required groups (the single definition every
    #: routing/k2/κ decision keys on)
    n_scored: int = 0


@dataclass
class PendingBatch:
    """One issued-but-unfetched batch: waves are on the device queue,
    no output has been synced. Produced by ``issue_batch`` (pure async
    enqueue), consumed by ``collect_batch`` (the one host sync). The
    resident serving loop holds up to two of these so batch N+1's
    dispatch rides under batch N's compute; ``search_batch`` is the
    same two halves back-to-back, so the paths cannot diverge."""

    plans: list
    results: list
    waves: list
    k_req: int
    k2v: int
    f2_nsel: int
    bmax: int
    topk: int


class DeviceIndex:
    """One collection's postings + impact bounds, resident in HBM."""

    def __init__(self, coll: Collection, max_positions: int = MAX_POSITIONS,
                 device=None):
        #: device pinning: a mesh of chips serves one shard per chip —
        #: every resident array and kernel dispatch for this index
        #: stays on ``device`` (jit follows committed operands), so N
        #: shards execute concurrently on N chips
        self.device = device
        self.coll = coll
        if max_positions > (1 << _OCC_BITS):
            raise ValueError(
                f"max_positions > {1 << _OCC_BITS} overflows the 4-bit "
                "occurrence field of the docc pack")
        self.P = max_positions
        self._built_version = -1
        self._base_fp = None
        self.full_rebuilds = 0    # O(corpus) base rebuilds (run-set moved)
        self.delta_rebuilds = 0   # O(memtable) delta-only refreshes
        self.escalations = 0      # phase-2 κ escalations (pruning misses)
        #: kernel-route observability: queries initially routed to the
        #: two-phase (f1), direct-cube (fd) and generic full-cube (f2)
        #: kernels (escalation reruns not counted)
        self.route_counts = {"f1": 0, "fd": 0, "f2": 0}
        #: resident-plan cache (the termlist-cache role, RdbCache): the
        #: per-query host planning pass — directory binary searches, df
        #: lookups, slot planning, row layout — repeats byte-identically
        #: for a repeated query until a write moves posdb or fielddb;
        #: generation-keyed on both versions so invalidation is O(1).
        #: Mutations of a cached plan's kappa_min/k2_min escalation
        #: floors are deliberate: a hot query's learned floor persists.
        from ..cache import g_cacheplane
        _coll = coll
        self._plan_cache = g_cacheplane.register(
            f"devindex.plan.{coll.name}", ttl_s=300.0, max_entries=2048,
            gen_fn=lambda: (_coll.posdb.version,
                            _coll.fielddb.rdb.version),
            desc="resident query plans (termlist-cache role)")
        self.refresh()

    def _put(self, a):
        return jax.device_put(a, self.device) if self.device is not None \
            else jax.device_put(a)

    # --- build / refresh -------------------------------------------------

    def refresh(self) -> bool:
        """(Re)build device arrays if the underlying Rdb changed: delta
        only while the run set is stable, full base rebuild when a
        dump/merge moved it (SURVEY §7 hard part (d))."""
        rdb = self.coll.posdb
        if rdb.version == self._built_version:
            return False
        self._sitehash = None  # clusterdb view refreshes lazily
        self._fcols = {}        # fielddb columns re-derive
        self._fswave = {}
        self._docid_sorted = None  # sorted docid view rebuilds
        # content-addressed fingerprint: keys_crc makes a rebuilt run
        # with a coincidentally identical (name, count) miss the cache
        fp = tuple((r.path.name, len(r), r.meta.get("keys_crc"))
                   for r in rdb.runs)
        if fp != self._base_fp:
            self._build_base(fp)
        # the delta can outgrow the doc-capacity headroom AND the
        # preallocated column tails independently — regrow and retry
        min_docs = min_delta = 0
        for _ in range(3):
            try:
                self._build_delta()
                break
            except _DeltaOverflow as e:
                min_docs = max(min_docs, e.needed_docs)
                min_delta = max(min_delta, e.needed_cols)
                self._build_base(fp, min_docs=min_docs,
                                 min_delta=min_delta)
        else:
            self._build_delta()
        self._built_version = rdb.version
        if devwatch.enabled():
            # one registration point covers base, delta and regrow —
            # every rebuild path funnels through here with the final
            # column bindings; the devbuild staging slice is consumed
            # by now, so release it in the same breath
            devwatch.drop("(ingest)", "build")
            devwatch.note_columns(self.coll.name, "devindex",
                                  self._column_map())
        return True

    #: bump when any derived-column computation changes (cache schema)
    _CACHE_SCHEMA = 4  # v4: split rs/cnt columns (2^26 cap lifted)

    def _cache_path(self, fp):
        import hashlib
        h = hashlib.sha1(repr((fp, self.P, self._CACHE_SCHEMA))
                         .encode()).hexdigest()[:16]
        return self.coll.posdb.dir / "devcache" / f"base_{h}.npz"

    def _load_base_cache(self, fp):
        """Derived base columns, cached on disk per run-set fingerprint
        (the expensive host derivation — 25M-posting merge + impact
        bounds — runs once per dump/merge, not once per process; a
        restarted node rebuilds its device mirror at transfer speed)."""
        p = self._cache_path(fp)
        if not p.exists():
            return None
        try:
            z = np.load(p)
            return tuple(z[k] for k in (
                "dir_termids", "base_df", "dir_dstart", "dir_pstart",
                "base_docids", "docidx", "pocc", "payload", "doc_col",
                "imp_col", "rs_col", "cnt_col", "siterank", "langid"))
        except Exception:  # torn write etc. — recompute
            return None

    def _save_base_cache(self, fp, docidx, pocc, payload, doc_col,
                         imp_col, rs_col, cnt_col, siterank,
                         langid) -> None:
        p = self._cache_path(fp)
        p.parent.mkdir(parents=True, exist_ok=True)
        tmp = p.with_suffix(".tmp.npz")
        np.savez(tmp, dir_termids=self.dir_termids,
                 base_df=self.base_df, dir_dstart=self.dir_dstart,
                 dir_pstart=self.dir_pstart,
                 base_docids=self.base_docids, docidx=docidx, pocc=pocc,
                 payload=payload, doc_col=doc_col, imp_col=imp_col,
                 rs_col=rs_col, cnt_col=cnt_col, siterank=siterank,
                 langid=langid)
        tmp.rename(p)
        # stale fingerprints go only AFTER the new cache landed: a crash
        # mid-savez used to leave NO cache at all, forcing a full
        # rebuild on next boot (the classic swap-order bug)
        for old in p.parent.glob("base_*.npz"):
            if old != p:
                old.unlink()  # only the live fingerprint is useful

    def _postings_overflow(self) -> ValueError:
        """The 2^31-postings runstart pack limit, as a counted,
        admin-visible condition (the /admin/perf shard-split alert) —
        a fleet operator sees the counter before the node boot-loops
        on the raise."""
        g_stats.count("build.postings_overflow")
        return ValueError(
            f"shard exceeds {_MAX_POSTINGS} stored postings "
            "(runstart pack limit) — split the collection "
            "across more shards")

    def _build_base(self, fp, min_docs: int = 0, min_delta: int = 0
                    ) -> None:
        """Base columns from the Rdb's immutable runs (merged, tombstones
        annihilated — the Msg5 read collapsed to one columnar merge),
        plus preallocated delta tails."""
        runs = self.coll.posdb.runs
        P = self.P
        cached = self._load_base_cache(fp)
        dv = None
        if cached is not None:
            (self.dir_termids, self.base_df, self.dir_dstart,
             self.dir_pstart, self.base_docids, docidx, pocc, payload,
             doc_col, imp_col, rs_col, cnt_col, siterank,
             langid) = cached
            n = len(docidx)
            batch = None
        else:
            if devbuild.enabled() and runs:
                # the device ingest plane: merge + derive on-chip, the
                # host NumPy pipeline below stays as oracle + fallback
                try:
                    dv = devbuild.build_base(
                        [r.batch().keys for r in runs], self._put)
                except Exception:
                    log.exception("device base build failed — falling "
                                  "back to the host pipeline")
                    g_stats.count("build.devbuild_fallback")
                    dv = None
            batch = None if dv is not None else (
                merge_batches([r.batch() for r in runs])
                if runs else None)
        if cached is not None:
            pass
        elif dv is not None:
            # columns already live in HBM; only the directory tables,
            # docid map and doc_col came back to host
            self.dir_termids = dv.dir_termids
            self.base_df = dv.df
            self.dir_dstart = dv.dir_dstart
            self.dir_pstart = dv.dir_pstart
            self.base_docids = dv.base_docids
            doc_col = dv.h_doc_col
            n = dv.n
            if n >= _MAX_POSTINGS:
                raise self._postings_overflow()
            docidx = pocc = payload = imp_col = rs_col = cnt_col = None
            siterank = langid = None
        elif batch is not None and len(batch):
            f = posdb.unpack(batch.keys)
            termids, docids = f["termid"], f["docid"]
            occ = _occ_ranks(termids, docids)
            self.dir_termids, _, self.base_df = _term_dfs(termids, occ == 0)
            # store-cap: scoring consumes ≤ P positions per (term, doc),
            # so postings past occurrence P are dead weight in HBM
            keep = occ < P
            pocc = occ[keep].astype(np.uint8)
            f = {k: v[keep] for k, v in f.items()}
            termids, docids = f["termid"], f["docid"]
            if len(termids) >= _MAX_POSTINGS:
                raise self._postings_overflow()
            payload = pack_payload(f)
            self.base_docids = np.unique(docids)
            docidx = np.searchsorted(self.base_docids, docids).astype(
                np.int32)
            n = len(docidx)
            # --- doc-level runs: one entry per (term, doc) pair ---
            newpair = np.ones(n, bool)
            newpair[1:] = (termids[1:] != termids[:-1]) | \
                (docidx[1:] != docidx[:-1])
            runstart = np.nonzero(newpair)[0].astype(np.int64)
            doc_col = docidx[newpair]
            count = np.diff(np.r_[runstart, n])
            imp_col = _impacts_np(f, termids, docidx, runstart)
            rs_col = runstart.astype(np.int32)
            cnt_col = np.minimum(count, P).astype(np.uint8)
            tchange = np.ones(n, bool)
            tchange[1:] = termids[1:] != termids[:-1]
            tstarts = np.nonzero(tchange)[0]
            self.dir_dstart = np.r_[
                np.searchsorted(runstart, tstarts), len(runstart)
            ].astype(np.int64)
            self.dir_pstart = np.r_[tstarts, n].astype(np.int64)
            siterank = f["siterank"].astype(np.int32)
            langid = f["langid"].astype(np.int32)
            self._save_base_cache(fp, docidx, pocc, payload, doc_col,
                                  imp_col, rs_col, cnt_col, siterank,
                                  langid)
        else:
            self.dir_termids = np.empty(0, np.uint64)
            self.base_df = np.empty(0, np.int64)
            self.dir_dstart = np.zeros(1, np.int64)
            self.dir_pstart = np.zeros(1, np.int64)
            self.base_docids = np.empty(0, np.uint64)
            docidx = np.empty(0, np.int32)
            pocc = np.empty(0, np.uint8)
            payload = np.empty(0, np.uint32)
            doc_col = np.empty(0, np.int32)
            imp_col = np.empty(0, np.float32)
            rs_col = np.empty(0, np.int32)
            cnt_col = np.empty(0, np.uint8)
            siterank = langid = np.empty(0, np.int32)
            n = 0

        Db = len(self.base_docids)
        headroom = max(1024, Db // 4)
        self.D_cap = _bucket(max(Db + headroom, min_docs, 1), DOC_QUANTUM)
        if self.D_cap > (1 << 28):
            # docc pack ships docidx in the high 28 bits of a uint32
            raise ValueError(
                "docc pack caps a shard at 2^28 docs — shard the corpus")

        # --- doc meta table (first posting per doc supplies siterank/
        # langid — reference getSiteRank(miniMergedList[0]), 6989).
        # uint8 columns: siterank is 4 bits and langid 6 in the posdb
        # key itself, so the old int32 columns shipped 8× the bytes
        # final_multipliers actually needs per doc ---
        sr = np.zeros(self.D_cap, np.uint8)
        dl = np.zeros(self.D_cap, np.uint8)
        if n and dv is None:
            first = np.unique(docidx, return_index=True)[1]
            sr[docidx[first]] = siterank[first]
            dl[docidx[first]] = langid[first]

        # --- dense rows: highest-df terms get a dense [D_cap] impact +
        # runstart row (phase 1 adds them with zero gather/scatter).
        # Built DEVICE-side by one flattened scatter from the doc-pair
        # columns (uploading [V, D] host arrays would ship ~GBs through
        # the host link; the descriptors below are a few KB) ---
        dfs = np.diff(self.dir_dstart)
        tau = max(_env_int("OSSE_DENSE_MIN_DF", DENSE_MIN_DF),
                  self.D_cap // 64)
        # 7 bytes per (term, doc) slot: f16 impact + int32 rs + u8 cnt.
        # The slot count V power-of-two buckets (V is a kernel shape),
        # so the budget must hold for the BUCKETED V — at big D_cap a
        # raw-count budget bucketed up overshot HBM and the int32
        # scatter index space (measured at 250k docs: V 341→512)
        v_cap = 8
        while (2 * v_cap * 7 * self.D_cap <= DENSE_BUDGET_BYTES
               and 2 * v_cap * self.D_cap < (1 << 31)):
            v_cap *= 2
        eligible = np.nonzero(dfs > tau)[0]
        eligible = eligible[np.argsort(-dfs[eligible], kind="stable")]
        dense_terms = eligible[:v_cap]
        V = _bucket(max(len(dense_terms), 1), 8)
        self.dense_slot_of: dict[int, int] = {}
        dr_starts = np.zeros(max(len(dense_terms), 1), np.int32)
        dr_lens = np.zeros(max(len(dense_terms), 1), np.int64)
        for slot, ti in enumerate(dense_terms):
            a, b = int(self.dir_dstart[ti]), int(self.dir_dstart[ti + 1])
            dr_starts[slot] = a
            dr_lens[slot] = b - a
            self.dense_slot_of[int(self.dir_termids[ti])] = slot

        # --- cube rows: the very heaviest terms' [P, D] position cubes,
        # materialized so the full-cube kernel (F2) reads them as plain
        # slices. Built device-side by one scatter from the posting
        # columns — no multi-hundred-MB host upload ---
        # adaptive budget: columns + dense rows are obligatory; the
        # cube takes what HBM can spare up to the hard cap
        nb_est = _bucket(max(n, 1), COL_QUANTUM)
        mb_est = _bucket(max(len(doc_col), 1), COL_QUANTUM)
        n2_est = max(_bucket(max(nb_est // 4, min_delta, 1),
                             COL_QUANTUM), COL_QUANTUM)
        cols_bytes = (nb_est + n2_est) * 8 + (mb_est + n2_est) * 11
        dense_bytes = V * self.D_cap * 7
        cube_bytes = min(
            CUBE_BUDGET_BYTES,
            max(1 << 30, HBM_USABLE_BYTES - cols_bytes - dense_bytes
                - WAVE_RESERVE_BYTES))
        # Vc also buckets to a power of two AND its flat [Vc·P·D] index
        # space must stay inside int32 for the build scatter — budget
        # against the bucketed size (at 250k docs the raw count 161
        # bucketed to 256 → exactly 2^31 elements → overflow)
        vc_cap = 4
        while (2 * vc_cap * P * self.D_cap * 4 <= cube_bytes
               and 2 * vc_cap * P * self.D_cap < (1 << 31)):
            vc_cap *= 2
        # −1: the last slot stays all-zero — the FD kernel's "absent
        # quarter" target (zero payload = invalid by convention)
        cube_terms = dense_terms[:vc_cap - 1]
        Vc = _bucket(len(cube_terms) + 1, 4)
        self.cube_zero_slot = Vc - 1
        self.cube_slot_of: dict[int, int] = {}
        # per-slot posting-run descriptors only — the scatter targets
        # derive on-device from the resident docc column (docidx<<4 |
        # occ), so neither build path ships posting-sized dst arrays
        c_starts = np.zeros(max(len(cube_terms), 1), np.int32)
        c_lens = np.zeros(max(len(cube_terms), 1), np.int64)
        for slot, ti in enumerate(cube_terms):
            a, b = int(self.dir_pstart[ti]), int(self.dir_pstart[ti + 1])
            c_starts[slot] = a
            c_lens[slot] = b - a
            self.cube_slot_of[int(self.dir_termids[ti])] = slot

        # --- device columns: base + preallocated delta tail ---
        self.h_doc_col = doc_col
        self.Nb = _bucket(max(n, 1), COL_QUANTUM)
        self.Mb = _bucket(max(len(doc_col), 1), COL_QUANTUM)
        # delta tail capacity scales with the base (grown on overflow)
        self.N2 = max(_bucket(max(self.Nb // 4, min_delta, 1),
                              COL_QUANTUM), COL_QUANTUM)
        self.M2 = self.N2
        if dv is not None:
            # device-built columns never left HBM: slice/zero-extend
            # them into the base+delta capacity (rows past dv.n are
            # already zero — the _pad_col convention holds on-device)
            self.d_payload = devbuild.fit(dv.cols["payload"],
                                          self.Nb + self.N2)
            self.d_docc = devbuild.fit(dv.cols["docc"],
                                       self.Nb + self.N2)
            self.d_doc = devbuild.fit(dv.cols["doc_col"],
                                      self.Mb + self.M2)
            self.d_imp = devbuild.fit(dv.cols["imp16"],
                                      self.Mb + self.M2)
            self.d_rs = devbuild.fit(dv.cols["rs"], self.Mb + self.M2)
            self.d_cnt = devbuild.fit(dv.cols["cnt"],
                                      self.Mb + self.M2)
        else:
            self.d_payload = self._put(
                _pad_col(payload, self.Nb + self.N2))
            docc = ((docidx.astype(np.uint32) << _OCC_BITS)
                    | pocc.astype(np.uint32))
            self.d_docc = self._put(_pad_col(docc, self.Nb + self.N2))
            self.d_doc = self._put(_pad_col(doc_col, self.Mb + self.M2))
            # packed resident impacts: the disk cache keeps exact f32
            # (the schema is unchanged); demotion to round-up f16
            # happens at device-put time so HBM holds half the impact
            # bytes while the bounds stay admissible (demote_impacts
            # docstring)
            self.d_imp = self._put(_pad_col(demote_impacts(imp_col),
                                            self.Mb + self.M2))
            self.d_rs = self._put(_pad_col(rs_col, self.Mb + self.M2))
            self.d_cnt = self._put(_pad_col(cnt_col, self.Mb + self.M2))
        dr_cum = np.r_[0, np.cumsum(dr_lens)].astype(np.int32)
        (self.d_dense_imp, self.d_dense_rs,
         self.d_dense_cnt) = _build_dense_rows(
            self.d_doc, self.d_imp, self.d_rs, self.d_cnt,
            self._put(dr_starts), self._put(dr_cum),
            V=V, D=self.D_cap,
            n_lanes=_bucket(max(int(dr_cum[-1]), 1), COL_QUANTUM))
        if dv is not None:
            self.d_siterank, self.d_doclang = devbuild.doc_meta(
                self._put(sr), self._put(dl), dv)
        else:
            self.d_siterank = self._put(sr)
            self.d_doclang = self._put(dl)
        self.d_dead = self._put(np.zeros(self.D_cap, bool))
        self.Vc = Vc
        total = Vc * P * self.D_cap
        c_cum = np.r_[0, np.cumsum(c_lens)].astype(np.int32)
        if len(cube_terms):
            self.d_cube = devbuild._cube_rows(
                self.d_payload, self.d_docc, self._put(c_starts),
                self._put(c_cum), D=self.D_cap, n_positions=P,
                total=total,
                n_lanes=_bucket(max(int(c_cum[-1]), 1), COL_QUANTUM))
        else:
            self.d_cube = jnp.zeros((total,), jnp.uint32)
        self._base_fp = fp
        self.full_rebuilds += 1
        log.info("device base built: %d postings, %d docs, %d terms "
                 "(%d dense rows, %d cube rows, cap %d)", n, Db,
                 len(self.dir_termids), len(dense_terms),
                 len(cube_terms), self.D_cap)

    def _build_delta(self) -> None:
        """Delta columns from the memtable — O(memtable) per refresh.

        Tombstones (delbit 0) and re-adds mark their base doc dead
        (phase 1 masks base-side bounds, phase 2 masks base run counts)
        and subtract from per-term dfs; positives become delta postings
        + delta doc columns written into the preallocated tails."""
        Db = len(self.base_docids)
        mem = self.coll.posdb.mem.batch()
        self.tomb_df = np.zeros(len(self.dir_termids), np.int64)
        dead = np.zeros(self.D_cap, bool)
        if not len(mem):
            self._set_empty_delta()
            self.d_dead = self._put(dead)
            self.delta_rebuilds += 1
            return
        f = posdb.unpack(mem.keys)
        pos = f["delbit"].astype(bool)

        def base_idx_of(docids_arr):
            di = np.searchsorted(self.base_docids, docids_arr)
            ok = di < Db
            ok[ok] = self.base_docids[di[ok]] == docids_arr[ok]
            return di, ok

        # superseded base docs: explicitly tombstoned OR re-added in the
        # delta (an identical-content re-index annihilates its pairs in
        # the memtable, so the delta positives are the only witness)
        t_di, t_ok = base_idx_of(f["docid"][~pos])
        p_di, p_ok = base_idx_of(f["docid"][pos])
        dead_idx = np.unique(np.concatenate([t_di[t_ok], p_di[p_ok]]))
        dead[dead_idx] = True

        # distinct (term, superseded-doc) pairs → df subtraction (only
        # where the pair actually exists in the base)
        pair_t = np.concatenate([f["termid"][~pos][t_ok],
                                 f["termid"][pos][p_ok]])
        pair_d = np.concatenate([t_di[t_ok], p_di[p_ok]]).astype(np.int64)
        if len(pair_t):
            order = np.lexsort((pair_d, pair_t))
            pair_t, pair_d = pair_t[order], pair_d[order]
            firstp = np.ones(len(pair_t), bool)
            firstp[1:] = (pair_t[1:] != pair_t[:-1]) | \
                (pair_d[1:] != pair_d[:-1])
            pair_t, pair_d = pair_t[firstp], pair_d[firstp]
            ti = np.searchsorted(self.dir_termids, pair_t)
            ok = ti < len(self.dir_termids)
            ok[ok] = self.dir_termids[ti[ok]] == pair_t[ok]
            for term_i in np.unique(ti[ok]):
                m = ok & (ti == term_i)
                a, b = int(self.dir_dstart[term_i]), \
                    int(self.dir_dstart[term_i + 1])
                run = self.h_doc_col[a:b]
                ppos = np.searchsorted(run, pair_d[m])
                inb = ppos < len(run)
                inb[inb] = run[ppos[inb]] == pair_d[m][inb]
                self.tomb_df[term_i] = int(inb.sum())

        # --- positives → delta columns ---
        if pos.any():
            fp_ = {k: v[pos] for k, v in f.items()}
            p_doc = fp_["docid"]
            new_docids = np.unique(p_doc[~p_ok])
            if Db + len(new_docids) > self.D_cap:
                raise _DeltaOverflow(needed_docs=Db + len(new_docids))
            docidx = np.where(
                p_ok, p_di,
                Db + np.searchsorted(new_docids, p_doc)).astype(np.int32)
            dv2 = None
            if devbuild.enabled():
                try:
                    dv2 = devbuild.build_delta(fp_, docidx, self._put)
                except Exception:
                    log.exception("device delta fold failed — falling "
                                  "back to the host pipeline")
                    g_stats.count("build.devbuild_fallback")
                    dv2 = None
            if dv2 is not None:
                n2, m2 = dv2.n, dv2.n_pairs
                if n2 > self.N2 or m2 > self.M2:
                    raise _DeltaOverflow(needed_cols=max(n2, m2))
                if self.Nb + n2 >= _MAX_POSTINGS:
                    raise self._postings_overflow()
                self.dir2_termids = dv2.dir_termids
                self.delta_df = dv2.df
                self.dir2_dstart = dv2.dir_dstart
                self.dir2_pstart = dv2.dir_pstart
                self.all_docids = np.concatenate(
                    [self.base_docids, new_docids])
                # donated in-place rewrites straight from the derive
                # outputs — the fold never round-trips through host
                self.d_payload = _write_tail(
                    self.d_payload,
                    devbuild.fit(dv2.cols["payload"], self.N2),
                    np.int32(self.Nb))
                self.d_docc = _write_tail(
                    self.d_docc,
                    devbuild.fit(dv2.cols["docc"], self.N2),
                    np.int32(self.Nb))
                self.d_doc = _write_tail(
                    self.d_doc,
                    devbuild.fit(dv2.cols["doc_col"], self.M2),
                    np.int32(self.Mb))
                self.d_imp = _write_tail(
                    self.d_imp,
                    devbuild.fit(dv2.cols["imp16"], self.M2),
                    np.int32(self.Mb))
                self.d_rs = _write_tail(
                    self.d_rs,
                    devbuild.offset_runstarts(dv2, self.Nb, self.M2),
                    np.int32(self.Mb))
                self.d_cnt = _write_tail(
                    self.d_cnt,
                    devbuild.fit(dv2.cols["cnt"], self.M2),
                    np.int32(self.Mb))
                self.d_siterank, self.d_doclang = devbuild.doc_meta(
                    self.d_siterank, self.d_doclang, dv2)
                self.d_dead = self._put(dead)
                self.delta_rebuilds += 1
                return
            # delta sort key is (termid, DOC-INDEX, wordpos): new docs'
            # indexes aren't docid-monotonic
            order = np.lexsort((fp_["wordpos"], docidx, fp_["termid"]))
            fp_ = {k: v[order] for k, v in fp_.items()}
            docidx = docidx[order]
            occ = _occ_ranks(fp_["termid"], docidx)
            self.dir2_termids, _, self.delta_df = _term_dfs(
                fp_["termid"], occ == 0)
            keep = occ < self.P
            pocc2 = occ[keep].astype(np.uint8)
            fp_ = {k: v[keep] for k, v in fp_.items()}
            docidx = docidx[keep]
            n2 = len(docidx)
            newpair = np.ones(n2, bool)
            newpair[1:] = (fp_["termid"][1:] != fp_["termid"][:-1]) | \
                (docidx[1:] != docidx[:-1])
            runstart2 = np.nonzero(newpair)[0].astype(np.int64)
            doc2_col = docidx[newpair]
            if n2 > self.N2 or len(doc2_col) > self.M2:
                raise _DeltaOverflow(needed_cols=max(n2, len(doc2_col)))
            if self.Nb + n2 >= _MAX_POSTINGS:
                raise self._postings_overflow()
            count2 = np.diff(np.r_[runstart2, n2])
            imp2 = _impacts_np(fp_, fp_["termid"], docidx, runstart2)
            # runstarts reference the combined column: delta postings
            # live at [Nb, Nb + n2)
            rs2 = (self.Nb + runstart2).astype(np.int32)
            cnt2 = np.minimum(count2, self.P).astype(np.uint8)
            tchange = np.ones(n2, bool)
            tchange[1:] = fp_["termid"][1:] != fp_["termid"][:-1]
            tstarts = np.nonzero(tchange)[0]
            self.dir2_dstart = np.r_[
                np.searchsorted(runstart2, tstarts), len(runstart2)
            ].astype(np.int64)
            self.dir2_pstart = np.r_[tstarts, n2].astype(np.int64)
            self.all_docids = np.concatenate([self.base_docids, new_docids])
            payload2 = pack_payload(fp_)
            # doc-table updates from first delta posting per doc
            first = np.unique(docidx, return_index=True)[1]
            upd_idx = docidx[first].astype(np.int32)
            upd_sr = fp_["siterank"][first].astype(np.uint8)
            upd_dl = fp_["langid"][first].astype(np.uint8)
            # donated in-place rewrites of the delta tails
            self.d_payload = _write_tail(
                self.d_payload,
                self._put(_pad_col(payload2, self.N2)),
                np.int32(self.Nb))
            docc2 = ((docidx.astype(np.uint32) << _OCC_BITS)
                     | pocc2.astype(np.uint32))
            self.d_docc = _write_tail(
                self.d_docc, self._put(_pad_col(docc2, self.N2)),
                np.int32(self.Nb))
            self.d_doc = _write_tail(
                self.d_doc, self._put(_pad_col(doc2_col, self.M2)),
                np.int32(self.Mb))
            self.d_imp = _write_tail(
                self.d_imp,
                self._put(_pad_col(demote_impacts(imp2), self.M2)),
                np.int32(self.Mb))
            self.d_rs = _write_tail(
                self.d_rs, self._put(_pad_col(rs2, self.M2)),
                np.int32(self.Mb))
            self.d_cnt = _write_tail(
                self.d_cnt, self._put(_pad_col(cnt2, self.M2)),
                np.int32(self.Mb))
        else:
            self._set_empty_delta()
            upd_idx = np.empty(0, np.int32)
            upd_sr = upd_dl = upd_idx

        def bpad(a, fill):
            out = np.full(_bucket(max(len(a), 1), DOC_UPD_FLOOR), fill,
                          a.dtype)
            out[: len(a)] = a
            return out
        if len(upd_idx):
            self.d_siterank, self.d_doclang = _apply_doc_meta(
                self.d_siterank, self.d_doclang,
                bpad(upd_idx, upd_idx[0]), bpad(upd_sr, upd_sr[0]),
                bpad(upd_dl, upd_dl[0]))
        self.d_dead = self._put(dead)
        self.delta_rebuilds += 1

    def _set_empty_delta(self) -> None:
        self.dir2_termids = np.empty(0, np.uint64)
        self.dir2_dstart = np.zeros(1, np.int64)
        self.dir2_pstart = np.zeros(1, np.int64)
        self.delta_df = np.empty(0, np.int64)
        self.all_docids = self.base_docids
        # delta tails keep whatever stale content they hold — nothing
        # references it (dir2 is empty), so no device write is needed

    @property
    def n_docs(self) -> int:
        return len(self.all_docids)

    def _column_map(self) -> dict:
        """The resident device columns by name — the HBM ledger's
        (collection, plane, column) unit and the residency-gate byte
        source; extend here when a rebuild path grows a column."""
        return {"payload": self.d_payload, "docc": self.d_docc,
                "doc": self.d_doc, "imp": self.d_imp,
                "rs": self.d_rs, "cnt": self.d_cnt,
                "dense_imp": self.d_dense_imp,
                "dense_rs": self.d_dense_rs,
                "dense_cnt": self.d_dense_cnt, "cube": self.d_cube,
                "siterank": self.d_siterank,
                "doclang": self.d_doclang, "dead": self.d_dead}

    def resident_bytes(self) -> int:
        """Total device bytes this index holds resident — the number
        the background-rebuild double-residency gate reasons about."""
        import numpy as _np
        return sum(int(_np.prod(a.shape)) * a.dtype.itemsize
                   for a in self._column_map().values())

    def _docid_pos(self, docids_arr: np.ndarray) -> tuple[np.ndarray,
                                                          np.ndarray]:
        """(row positions, found mask) of docids in all_docids.
        all_docids = [sorted base] + [sorted delta] — NOT globally
        sorted once a delta exists, so binary search needs the sorted
        view + inverse permutation (rebuilt per refresh)."""
        if getattr(self, "_docid_sorted", None) is None or \
                len(self._docid_order) != len(self.all_docids):
            self._docid_order = np.argsort(self.all_docids,
                                           kind="stable")
            self._docid_sorted = self.all_docids[self._docid_order]
        pos = np.searchsorted(self._docid_sorted, docids_arr)
        ok = pos < len(self._docid_sorted)
        ok[ok] = self._docid_sorted[pos[ok]] == docids_arr[ok]
        rows = np.zeros(len(docids_arr), np.int64)
        rows[ok] = self._docid_order[pos[ok]]
        return rows, ok

    def _cluster_cols(self):
        """Lazily materialized clusterdb columns aligned to all_docids
        (Clusterdb.h:42 — sitehash + langid per docid, dataless)."""
        if getattr(self, "_sitehash", None) is None:
            cl = self.coll.clusterdb.get_all()
            sh = np.zeros(len(self.all_docids), np.int64)
            lg = np.zeros(len(self.all_docids), np.int64)
            if len(cl):
                f = clusterdb_mod.unpack_key(cl.keys)
                rows, ok = self._docid_pos(f["docid"])
                sh[rows[ok]] = f["sitehash"][ok].astype(np.int64)
                lg[rows[ok]] = f["langid"][ok].astype(np.int64)
            self._sitehash = sh
            self._langid_col = lg
        return self._sitehash, self._langid_col

    def sitehash_of(self, docid: int) -> int:
        """Query-time clusterdb read (Clusterdb.h:42 / Msg51.h:96):
        the docid's 26-bit sitehash from the dataless clusterdb records
        — site clustering runs off this column WITHOUT touching titledb
        until the summary stage. Lazily built, aligned to all_docids."""
        sh, _ = self._cluster_cols()
        rows, ok = self._docid_pos(np.array([docid], np.uint64))
        return int(sh[rows[0]]) if ok[0] else 0

    def langid_of(self, docid: int) -> int:
        """Docid → langid from the same clusterdb columns (feeds the
        PostQueryRerank foreign-language demotion without a titlerec
        fetch)."""
        _, lg = self._cluster_cols()
        rows, ok = self._docid_pos(np.array([docid], np.uint64))
        return int(lg[rows[0]]) if ok[0] else 0

    # --- fielddb columns (gbmin/gbmax/gbsortby — the datedb role) -------

    def _field_col(self, fld: str) -> np.ndarray:
        """Dense f64 [n_docs] column for one field aligned to
        all_docids (NaN = doc has no value), cached per Rdb version."""
        cache = getattr(self, "_fcols", None)
        if cache is None:
            cache = self._fcols = {}
        ver = self.coll.fielddb.rdb.version
        hit = cache.get((fld, ver))
        if hit is not None:
            return hit
        docids, vals = self.coll.fielddb.column(fld)
        col = np.full(len(self.all_docids), np.nan)
        if len(docids):
            rows, ok = self._docid_pos(docids)
            col[rows[ok]] = vals[ok]
        if len(cache) > 32:
            cache.clear()
        cache[(fld, ver)] = col
        return col

    def sort_base_of(self, fld: str, desc: bool) -> float | None:
        """This shard's minimum finite sort key for a field (keys are
        v for descending, -v for ascending); None when the shard has
        no finite values (must not poison the cross-shard min)."""
        col = self._field_col(fld)
        key = col if desc else -col
        fin = np.isfinite(key)
        return float(key[fin].min()) if fin.any() else None

    def _filter_sort_cols(self, p: "ResidentPlan"):
        """(d_filter, d_sort, use_filter, use_sort) for one wave —
        device-cached per (spec, fielddb version). The filter is the
        AND of every field's range mask; the sort column is shifted
        positive (matched docs must stay > 0) with missing-field docs
        ranked below every real value."""
        spec = (p.filters, p.sortby, p.sort_base)
        ver = self.coll.fielddb.rdb.version
        cache = getattr(self, "_fswave", None)
        if cache is None:
            cache = self._fswave = {}
        hit = cache.get((spec, ver))
        if hit is not None:
            return hit
        use_filter = bool(p.filters)
        use_sort = p.sortby is not None
        if use_filter:
            mask = np.ones(len(self.all_docids), bool)
            for fld, (lo, hi) in p.filters:
                col = self._field_col(fld)
                with np.errstate(invalid="ignore"):
                    mask &= (col >= lo) & (col <= hi)  # NaN fails both
            fpad = np.zeros(self.D_cap, bool)
            fpad[: len(mask)] = mask
        else:
            fpad = np.zeros(self.D_cap, bool)
        if use_sort:
            fld, desc = p.sortby
            col = self._field_col(fld).copy()
            key = col if desc else -col
            finite = np.isfinite(key)
            key = np.where(finite, key - p.sort_base + 1.0, 0.25)
            spad = np.zeros(self.D_cap, np.float32)
            spad[: len(key)] = key.astype(np.float32)
        else:
            spad = np.zeros(self.D_cap, np.float32)
        out = (self._put(fpad), self._put(spad), use_filter, use_sort)
        if len(cache) > 16:
            cache.clear()
        cache[(spec, ver)] = out
        return out

    # --- planning --------------------------------------------------------

    def _druns_of(self, termid: int):
        """[(is_base, dstart, dlen, dense_slot, cube_slot, pstart, plen)]
        runs for a termid: doc-column run + posting-column run, with the
        dense/cube row slots (-1 when absent)."""
        out = []
        i = int(np.searchsorted(self.dir_termids, np.uint64(termid)))
        if i < len(self.dir_termids) and self.dir_termids[i] == termid:
            a, b = int(self.dir_dstart[i]), int(self.dir_dstart[i + 1])
            if b > a:
                pa, pb = int(self.dir_pstart[i]), int(self.dir_pstart[i + 1])
                out.append((True, a, b - a,
                            self.dense_slot_of.get(termid, -1),
                            self.cube_slot_of.get(termid, -1),
                            pa, pb - pa))
        j = int(np.searchsorted(self.dir2_termids, np.uint64(termid)))
        if j < len(self.dir2_termids) and self.dir2_termids[j] == termid:
            a, b = int(self.dir2_dstart[j]), int(self.dir2_dstart[j + 1])
            if b > a:
                # delta doc/posting columns live past Mb / Nb
                pa, pb = int(self.dir2_pstart[j]), \
                    int(self.dir2_pstart[j + 1])
                out.append((False, self.Mb + a, b - a, -1, -1,
                            self.Nb + pa, pb - pa))
        return out

    @property
    def df_generation(self):
        """The posdb version this resident base was built from — the
        memo key for cluster-wide df caches (``MeshResident._global_df``
        sums ``_df_of`` across every shard; a shard's sum is stable
        until ITS base moves, so the tuple of these across shards keys
        the whole memo)."""
        return self._built_version

    def _df_of(self, termid: int) -> int:
        """Exact document frequency under pending deletes/re-adds:
        base df − superseded-doc pairs + delta df."""
        df = 0
        i = int(np.searchsorted(self.dir_termids, np.uint64(termid)))
        if i < len(self.dir_termids) and self.dir_termids[i] == termid:
            df += int(self.base_df[i]) - int(self.tomb_df[i])
        j = int(np.searchsorted(self.dir2_termids, np.uint64(termid)))
        if j < len(self.dir2_termids) and self.dir2_termids[j] == termid:
            df += int(self.delta_df[j])
        return max(df, 0)

    def plan(self, qplan: QueryPlan, df_of=None,
             total_docs: int | None = None,
             sort_base_of=None) -> ResidentPlan:
        """``df_of``/``total_docs``/``sort_base_of`` override the
        corpus-wide stats: the mesh layer passes CLUSTER-WIDE dfs (and
        the cluster-wide sort-key base for gbsortby) so every shard
        weighs terms identically and cross-shard scores merge
        comparably (the reference ships global termFreqWeights in the
        Msg39 request)."""
        T = _bucket(max(len(qplan.groups), 1), T_FLOOR)
        drows, srows, crows, prows = [], [], [], []
        dfs = np.zeros(max(len(qplan.groups), 1), np.int64)
        matchable = True
        any_required = False
        driver_df = 1 << 60
        groups_have_postings = []
        # direct-cube qualification: per group, the contributing runs
        zq = 4 * getattr(self, "cube_zero_slot", 0)
        g_quarter = np.full((T, 4), zq, np.int32)
        g_qsyn = np.zeros((T, 4), np.uint32)
        direct_ok = True
        for g_i, g in enumerate(qplan.groups):
            subs = g.sublists
            sub_druns = [self._druns_of(s.termid) for s in subs]
            # quota only over sublists with LIVE postings — df under
            # tombstones, matching the host packer's fetched-list mask
            # (a sublist whose every doc was deleted still has base
            # runs in the directory, but its merged host list is empty;
            # diverging masks would give the two paths different slot
            # plans and break parity)
            sub_live_df = [self._df_of(s.termid) for s in subs]
            sp = g.slot_plan(
                self.P,
                present=[bool(d) and ldf > 0
                         for d, ldf in zip(sub_druns, sub_live_df)],
                # LOCAL live dfs for variant funding on both paths: the
                # host packer passes its fetched-list distinct-doc
                # counts, which equal _df_of under tombstones — the
                # funded-variant pick (and so the packed layout) stays
                # bit-identical across host and device planners. The
                # cluster-wide df_of override stays out of this on
                # purpose: it would diverge from what the host path can
                # compute locally.
                df=sub_live_df)
            any_postings = False
            gdf = 0
            g_runs = []
            for s_i, sub in enumerate(subs):
                syn = 1 if sub.kind == SUB_SYNONYM else 0
                base, quota = sp[s_i]
                for is_base, a, ln, dslot, cslot, pa, pl in \
                        sub_druns[s_i]:
                    g_runs.append((is_base, dslot, cslot, syn, base,
                                   quota))
                    # F1 row split: dense [D] impact row vs sparse run.
                    # Sparse runs chunk at LSP_MAX so the lane bucket is
                    # a constant (one compile) and an unbudgeted big
                    # term costs lanes ∝ its real size, not Rs×max
                    if dslot >= 0:
                        drows.append((dslot, g_i, base, quota, syn))
                    else:
                        for off in range(0, ln, LSP_MAX):
                            srows.append((a + off,
                                          min(ln - off, LSP_MAX),
                                          g_i, base, quota, syn,
                                          is_base))
                    # F2 row split: materialized cube slice vs posting
                    # scatter; oversized runs split into several bounded
                    # scatter rows (postings carry their own doc+occ, so
                    # any partition of the range is valid) — every query
                    # is F2-servable and the F1 κ ladder stays ≤ the
                    # routing cut
                    if cslot >= 0:
                        crows.append((cslot, dslot, g_i, base, quota,
                                      syn))
                    else:
                        for off in range(0, pl, F2_SCATTER_MAX):
                            prows.append((pa + off,
                                          min(pl - off, F2_SCATTER_MAX),
                                          g_i, base, quota, syn,
                                          is_base))
                    any_postings = True
                gdf = max(gdf, (df_of or self._df_of)(sub.termid))
            dfs[g_i] = gdf
            groups_have_postings.append(any_postings)
            # direct-cube qualification: cube runs must be base runs at
            # quarter-aligned (base, quota) so the group plane assembles
            # from quarter-row gathers (quarter q of a term's [P, D]
            # cube row holds its occurrences q·P/4..); non-cube runs go
            # to the bounded posting-scatter tail (checked globally
            # below). Misaligned cube runs → generic F2.
            P4 = self.P // 4
            for is_b, dsl, csl, syn, base, quota in g_runs:
                if csl < 0:
                    continue  # scatter-tail run (prows carry it)
                if not (is_b and base % P4 == 0 and quota % P4 == 0
                        and quota > 0 and base + quota <= self.P):
                    direct_ok = False
                    continue
                for k in range(min(quota, self.P - base) // P4):
                    g_quarter[g_i, base // P4 + k] = 4 * csl + k
                    g_qsyn[g_i, base // P4 + k] = syn
            if g.required and not g.negative:
                any_required = True
                driver_df = min(driver_df, gdf)
                if not any_postings:
                    matchable = False
        # direct route needs the scatter tail bounded: big non-cube doc
        # runs (a heavy term outside the cube budget) must assemble
        # through the generic F2
        if (len(prows) > FD_SCATTER_MAX_ROWS
                or sum(p[1] for p in prows) > FD_SCATTER_MAX_LANES):
            direct_ok = False
        # ... and the group bucket capped at 8: the fused-path HBM
        # budget (_fd_bmax) and the [T,P,D] tail cube both size for
        # T ≤ 8; rare wider conjunctions grind through the generic F2
        if len(qplan.groups) > 8:
            direct_ok = False
        if qplan.bool_table is not None:
            # a boolean query is servable iff SOME satisfying presence
            # assignment uses only groups that have postings; the match
            # bound is the union of all groups (any satisfying doc has
            # ≥1 present group — table[0] is False by construction)
            tbl = qplan.bool_table
            bits = np.arange(len(tbl))
            havemask = sum(1 << i for i, h in
                           enumerate(groups_have_postings) if h)
            matchable = bool(tbl[(bits & ~havemask) == 0].any())
            driver_df = int(min(dfs.sum(), self.coll.num_docs or dfs.sum()))
        elif not any_required:
            matchable = False

        required, negative, scored, counts = group_flags(qplan, T)
        freqw = _pad1(
            weights.term_freq_weight(
                dfs[: len(qplan.groups)],
                max(total_docs if total_docs is not None
                    else self.coll.num_docs, 1)), T, 0.5)
        da = np.array(drows, np.int64).reshape(-1, 5)
        sa = np.array(srows, np.int64).reshape(-1, 7)
        ca = np.array(crows, np.int64).reshape(-1, 6)
        pa_ = np.array(prows, np.int64).reshape(-1, 7)
        return ResidentPlan(
            d_slot=da[:, 0].astype(np.int32),
            d_group=da[:, 1].astype(np.int32),
            d_base=da[:, 2].astype(np.int32),
            d_quota=da[:, 3].astype(np.int32),
            d_syn=da[:, 4].astype(np.uint32),
            s_start=sa[:, 0].astype(np.int32),
            s_len=sa[:, 1].astype(np.int32),
            s_group=sa[:, 2].astype(np.int32),
            s_base=sa[:, 3].astype(np.int32),
            s_quota=sa[:, 4].astype(np.int32),
            s_syn=sa[:, 5].astype(np.uint32),
            s_isbase=sa[:, 6].astype(bool),
            c_slot=ca[:, 0].astype(np.int32),
            c_dslot=ca[:, 1].astype(np.int32),
            c_group=ca[:, 2].astype(np.int32),
            c_base=ca[:, 3].astype(np.int32),
            c_quota=ca[:, 4].astype(np.int32),
            c_syn=ca[:, 5].astype(np.uint32),
            p_start=pa_[:, 0].astype(np.int32),
            p_len=pa_[:, 1].astype(np.int32),
            p_group=pa_[:, 2].astype(np.int32),
            p_base=pa_[:, 3].astype(np.int32),
            p_quota=pa_[:, 4].astype(np.int32),
            p_syn=pa_[:, 5].astype(np.uint32),
            p_isbase=pa_[:, 6].astype(bool),
            freq_weight=freqw, required=required, negative=negative,
            scored=scored, counts=counts,
            table=pad_table(qplan.bool_table),
            qlang=qplan.lang, matchable=matchable,
            driver_df=0 if driver_df == 1 << 60 else int(driver_df),
            n_scored=int(np.sum(counts)),
            direct_ok=direct_ok, g_quarter=g_quarter, g_qsyn=g_qsyn,
            has_table=qplan.bool_table is not None,
            filters=tuple(sorted(
                (f, tuple(v)) for f, v in qplan.filters.items())),
            sortby=qplan.sortby,
            sort_base=(
                ((sort_base_of or self.sort_base_of)(*qplan.sortby)
                 or 0.0)
                if qplan.sortby is not None else 0.0))

    # --- execution -------------------------------------------------------

    def search(self, q: str | QueryPlan, topk: int = 64, lang: int = 0):
        """One query → (docids, scores, n_matched)."""
        return self.search_batch([q], topk=topk, lang=lang)[0]

    def search_batch(self, queries, topk: int = 64, lang: int = 0,
                     df_of=None, total_docs: int | None = None,
                     sort_base_of=None):
        """Batched execution: B queries per device round trip (vmap over
        the query axis). Routing: drivers with a bounded doc set use the
        two-phase pruned kernel (F1); corpus-wide drivers go to the
        full-cube exact kernel (F2) when every sublist fits it.

        One-shot form: issue + collect back-to-back. The resident
        serving loop (query/resident.py) calls the two halves directly
        so batch N+1 dispatches while wave N computes — same code
        either way, so the paths cannot diverge."""
        return self.collect_batch(self.issue_batch(
            queries, topk=topk, lang=lang, df_of=df_of,
            total_docs=total_docs, sort_base_of=sort_base_of))

    def issue_batch(self, queries, topk: int = 64, lang: int = 0,
                    df_of=None, total_docs: int | None = None,
                    sort_base_of=None) -> PendingBatch:
        """Plan + route + dispatch the first round of waves WITHOUT
        fetching anything: every dispatch is async, so this returns as
        soon as the host args are enqueued — no host sync. This is the
        resident loop's steady-state dispatch cost (one enqueue), vs
        the full jit round trip a one-shot ``search_batch`` pays."""
        t_plan = time.perf_counter()
        qplans = [q if isinstance(q, QueryPlan) else compile_query(q, lang)
                  for q in queries]
        # plan cache: only the pure-local form is cacheable — mesh calls
        # override dfs/sort bases with cluster-wide values that change
        # per caller and must not leak between planes
        cacheable = (df_of is None and total_docs is None
                     and sort_base_of is None)
        if cacheable:
            plans = []
            # generation captured BEFORE the plan builds: a write
            # landing mid-build moves it, so the entry we store is
            # already dead instead of a pre-write plan served as fresh
            pgen = self._plan_cache.current_gen()
            for qp in qplans:
                ck = (qp.raw, qp.lang)
                hit, p = self._plan_cache.lookup(ck, gen=pgen)
                if not hit:
                    p = self.plan(qp)
                    self._plan_cache.put(ck, p, gen=pgen)
                plans.append(p)
        else:
            plans = [self.plan(qp, df_of=df_of, total_docs=total_docs,
                               sort_base_of=sort_base_of)
                     for qp in qplans]
        trace.record("devindex.plan", t_plan, queries=len(qplans))
        live = [i for i, p in enumerate(plans) if p.matchable]
        results = [(np.empty(0, np.uint64), np.empty(0, np.float32), 0)
                   ] * len(plans)
        if not live:
            return PendingBatch(plans=plans, results=results, waves=[],
                                k_req=0, k2v=0, f2_nsel=0, bmax=0,
                                topk=topk)
        # corpus-relative routing: a driver matching more than ~1/8th of
        # the corpus (capped at the κ ladder's top rung) prunes badly —
        # full-cube scoring is cheaper than the escalation ladder. With
        # dense impact rows covering mid-df terms, F1 stays cheap up to
        # κ=8192, so only genuinely corpus-wide drivers route to F2
        f2_cut = min(4 * _env_int("OSSE_CUBE_MIN_DF", CUBE_MIN_DF),
                     max(2 * KAPPA_FLOOR, self.n_docs // 8))

        def _route_f2(i):
            p = plans[i]
            if (p.n_scored <= 1 and not p.has_table
                    and len(p.s_start) <= 16):
                # single-scored-group with bounded sparse rows: the
                # phase-1 bound IS the exact single-term score (exact
                # impacts), so F1's top-κ-by-bound is exact ordering at
                # ANY driver df — κ=256 with a 128-wide phase 2 beats
                # full-corpus scoring ~4× per query, and the lossless
                # check still backstops it. The Rs cap keeps the wave
                # inside warmed buckets: a heavy term WITHOUT a dense
                # slot (possible at big shards, where the dense budget
                # caps slots) would otherwise mint an unwarmed
                # Rs=128/256 shape and slow every co-batched lane.
                return False
            if p.driver_df > f2_cut:
                return True
            # heavy multi-group queries that CAN go direct should: the
            # F1 ladder would score a ≥2048-wide phase 2 with loose
            # distance-free bounds (escalation-prone); the direct
            # kernel scores the whole corpus exactly at flat cost and
            # never rungs up
            return (p.direct_ok and p.n_scored > 1
                    and self._kappa_of(p, topk) >= 8 * KAPPA_FLOOR)

        f2 = [i for i in live if _route_f2(i)]
        f1 = [i for i in live if i not in set(f2)]
        self.route_counts["f1"] += len(f1)
        self.route_counts["fd"] += sum(
            1 for i in f2 if plans[i].direct_ok)
        self.route_counts["f2"] += sum(
            1 for i in f2 if not plans[i].direct_ok)

        # wave loop: issue EVERY sub-batch dispatch, fetch ALL outputs
        # in one device_get (one tunnel RTT), then parse; queries whose
        # pruning check failed go into the (rare) next wave with 4x the
        # selection blocks — terminal at D_cap, where selection is
        # complete and the check passes by construction
        # k is bucketed (floor 64, powers of 2) so arbitrary caller topk
        # values don't mint new compile variants; extra rows returned
        # beyond the caller's k are harmless. The KERNEL k2 is pinned to
        # one 128-row value for everyday requests (n ≤ 100 over any s
        # ≤ topk·2 stays under it), so k2 never multiplies the compile
        # grid; only genuinely deep pages mint a bigger variant. k2 is
        # also the phase-2 scoring width (top-k2 by bound), so it sets
        # the dominant gather cost — 128 balances margin vs wave time
        k_req = min(_bucket(max(topk, 1), 64), self.D_cap)
        k2v = min(max(128, k_req), self.D_cap)
        # deep paging (TopTree top-X, X ≫ page): start the F2 selection
        # rung at the requested depth so page-50 doesn't climb a
        # ladder. Big shards start a rung higher: at D ≥ 2^19 the
        # 2048-block selection missed ~2% of queries (each miss reruns
        # a multi-second wave) while the wider top_k costs ~nothing.
        f2_floor = 4096 if self.D_cap >= (1 << 19) else 2048
        f2_nsel = min(max(f2_floor, _bucket(k_req, 2048)), self.D_cap)
        bmax = self._f2_bmax()
        waves = self._issue_waves(plans, f1, f2, topk, k2v, f2_nsel,
                                  bmax)
        return PendingBatch(plans=plans, results=results, waves=waves,
                            k_req=k_req, k2v=k2v, f2_nsel=f2_nsel,
                            bmax=bmax, topk=topk)

    def _issue_waves(self, plans, f1, f2, topk, k2v, f2_nsel, bmax):
        """Build + dispatch one round of waves — all async enqueues;
        the caller fetches every wave's output in ONE device_get."""
        t_issue = time.perf_counter()
        waves = []
        groups: dict[tuple[int, int], list[int]] = {}
        for i in f1:
            kapi = self._kappa_of(plans[i], topk)
            # phase-2 truncation to the top-k2 BY BOUND is only
            # sound-in-practice for single-scored-group plans,
            # where the bound ≈ the exact score; multi-group pair
            # bounds are distance-free (up to ~400× loose), so
            # bound order ≉ exact order and truncation would
            # escalate nearly every query (measured 57%). Multi-
            # group plans score every selected candidate.
            if plans[i].n_scored <= 1:
                k2i = min(max(k2v, plans[i].k2_min), kapi)
            else:
                k2i = kapi
            groups.setdefault(
                (kapi, k2i, plans[i].has_table,
                 plans[i].filters, plans[i].sortby), []).append(i)
        for (kappa, k2g, *_spec), idxs in sorted(
                groups.items(), key=lambda kv: str(kv[0])):
            # terminal rungs chunk small so the [T, P, k2]·B
            # phase-2 intermediates stay bounded at k2 = D_cap
            step = self._f1_bmax() if k2g <= 32 * KAPPA_FLOOR \
                else self._f1_step_terminal()
            for a in range(0, len(idxs), step):
                chunk = idxs[a:a + step]
                waves.append(("f1", kappa, k2g, chunk,
                              self._run_batch(
                                  [plans[i] for i in chunk],
                                  kappa, k2g)))
        fd = [i for i in f2 if plans[i].direct_ok]
        fg = [i for i in f2 if not plans[i].direct_ok]
        # group FD waves by scatter-tail size: the Lp lane bucket is
        # per-wave, so one heavy-tailed query must not make every
        # lane of its wave pay 16384-lane scatters
        def _lp_of(i):
            p = plans[i]
            ml = int(p.p_len.max()) if len(p.p_len) else 0
            if ml == 0:
                return 0  # pure quarter-row wave: no tail cube
            return 512 if ml <= 512 else (
                F2_LPOST_FLOOR if ml <= F2_LPOST_FLOOR
                else F2_SCATTER_MAX)
        # HARD-partition F2/FD waves by (Lp, filter/sort spec):
        # the filter and sort columns are per-wave kernel args, so
        # a chunk must never mix specs
        spec_of = lambda i: (plans[i].filters, plans[i].sortby,
                             plans[i].has_table)
        fd_parts: dict = {}
        for i in fd:
            fd_parts.setdefault((_lp_of(i), spec_of(i)),
                                []).append(i)
        fd_step = self._fd_bmax()
        for _, idxs in sorted(fd_parts.items(),
                              key=lambda kv: str(kv[0])):
            for a in range(0, len(idxs), fd_step):
                chunk = idxs[a:a + fd_step]
                waves.append(("f2", 0, k2v, chunk,
                              self._run_batch_fd(
                                  [plans[i] for i in chunk],
                                  k2v, f2_nsel)))
        fg_parts: dict = {}
        for i in fg:
            fg_parts.setdefault(spec_of(i), []).append(i)
        for _, idxs in sorted(fg_parts.items(),
                              key=lambda kv: str(kv[0])):
            for a in range(0, len(idxs), bmax):
                chunk = idxs[a:a + bmax]
                waves.append(("f2", 0, k2v, chunk,
                              self._run_batch_f2(
                                  [plans[i] for i in chunk],
                                  k2v, f2_nsel)))
        trace.record("devindex.issue", t_issue, waves=len(waves))
        return waves

    def collect_batch(self, pending: PendingBatch):
        """Fetch + parse every issued wave, re-issuing the (rare)
        escalation rungs inline until all queries emit — the ONE
        ``device_get`` per round is the only host sync on the path."""
        plans, results = pending.plans, pending.results
        waves, f2_nsel = pending.waves, pending.f2_nsel
        k_req = pending.k_req
        while waves:
            t_fetch = time.perf_counter()
            outs = jax.device_get([w[4] for w in waves])
            t_got = time.perf_counter()
            kinds = "+".join(sorted({w[0] for w in waves}))
            stat = _WAVE_STAT.get((kinds, _wave_nbucket(len(waves))))
            if stat is not None:
                trace.record(stat, t_fetch, t_got)
            fetched = int(sum(np.asarray(o).nbytes for o in outs))
            # device-time attribution: device_get blocks until every
            # issued wave completes (the block_until_ready delta), so
            # this interval IS the device time of the round, and the
            # fetched buffers are the bytes moved device→host
            trace.record(
                "devindex.device", t_fetch, t_got,
                kinds=kinds, waves=len(waves), bytes=fetched)
            f1_next: list[int] = []
            f2_next: list[int] = []
            for (kind, kappa, k2g, idxs, _), out in zip(waves, outs):
                for row, i in zip(out, idxs):
                    k2p = min(k2g, f2_nsel, self.D_cap) if kind == "f2" \
                        else k2g
                    nm, missed, idx, scores = self._parse_out(row, k2p)
                    kth = float(scores[k_req - 1]) if (
                        k2p >= k_req and scores[k_req - 1] > 0.0) else 0.0
                    if missed > kth * _TIE_TOL:
                        if kind == "f1" and (kappa < self.D_cap
                                             or k2p < self.D_cap):
                            # pruning miss — widen the κ rung AND the
                            # phase-2 width, rerun; terminal at
                            # κ = k2 = D_cap where scoring is complete
                            # and missed is exactly 0
                            plans[i].kappa_min = min(4 * kappa,
                                                     self.D_cap)
                            plans[i].k2_min = min(
                                4 * max(k2p, KAPPA_FLOOR // 2),
                                self.D_cap)
                            f1_next.append(i)
                            continue
                        if kind == "f2" and f2_nsel < self.D_cap:
                            f2_next.append(i)
                            continue
                    if devcheck.enabled():
                        # guardrail sweep on every emitted wave row:
                        # finite, sorted, in-bounds (devcheck docs);
                        # apply_fault is the test-only injector
                        idx, scores = devcheck.apply_fault(
                            idx, scores, self.n_docs)
                        devcheck.check_topk(scores, idx, self.n_docs,
                                            route=kind)
                    self._emit(results, i, nm, idx, scores)
            if f1_next or f2_next:
                self.escalations += len(f1_next) + len(f2_next)
            if devwatch.enabled():
                # flight-recorder round detail: measured device time +
                # fetched bytes next to the modeled F1 wave bytes, so
                # the /admin/device waterfall shows model vs reality
                devwatch.note_round(
                    coll=self.coll.name, kinds=kinds,
                    waves=len(waves), device_s=t_got - t_fetch,
                    bytes_out=fetched,
                    modeled_f1_bytes=int(sum(
                        self.wave_bytes_per_query(
                            [plans[i] for i in w[3]]) * len(w[3])
                        for w in waves if w[0] == "f1")),
                    escalations=len(f1_next) + len(f2_next))
            f2_nsel = min(f2_nsel * 4, self.D_cap)
            waves = self._issue_waves(
                plans, f1_next, f2_next, pending.topk, pending.k2v,
                f2_nsel, pending.bmax) if (f1_next or f2_next) else []
        return results

    def warm(self) -> int:
        """Precompile the shape variants everyday queries hit (one dummy
        dispatch each; results discarded) — bench traces showed cold
        XLA compiles (~20-60 s through the tunnel) landing mid-serving
        and doubling run-to-run variance. Not exhaustive: deep-paging
        k2 sizes, terminal escalation rungs, and >64-row plans still
        compile on first use (rare by construction). Compiles persist
        in the XLA compilation cache, so warm() after a restart is
        cheap."""
        T = T_FLOOR
        z = np.zeros

        def dummy(ns: int = 1, np_rows: int = 1,
                  nd: int = 1) -> ResidentPlan:
            req = z(T, bool)
            req[0] = True
            return ResidentPlan(
                d_slot=z(nd, np.int32), d_group=z(nd, np.int32),
                d_base=z(nd, np.int32), d_quota=np.ones(nd, np.int32),
                d_syn=z(nd, np.uint32),
                s_start=z(ns, np.int32), s_len=np.ones(ns, np.int32),
                s_group=z(ns, np.int32), s_base=z(ns, np.int32),
                s_quota=np.ones(ns, np.int32), s_syn=z(ns, np.uint32),
                s_isbase=np.ones(ns, bool),
                c_slot=z(1, np.int32), c_dslot=z(1, np.int32),
                c_group=z(1, np.int32), c_base=z(1, np.int32),
                c_quota=np.ones(1, np.int32), c_syn=z(1, np.uint32),
                p_start=z(np_rows, np.int32),
                p_len=np.ones(np_rows, np.int32),
                p_group=z(np_rows, np.int32), p_base=z(np_rows, np.int32),
                p_quota=np.ones(np_rows, np.int32),
                p_syn=z(np_rows, np.uint32),
                p_isbase=np.ones(np_rows, bool),
                freq_weight=np.full(T, 0.5, np.float32),
                required=req, negative=z(T, bool), scored=req.copy(),
                counts=req.copy(), table=pad_table(None), qlang=0,
                matchable=True)

        outs = []
        k2 = min(128, self.D_cap)
        kap = min(KAPPA_FLOOR, self.D_cap)
        shape_grid = ((1, 1), (2, 1), (1, 2), (3, 3), (5, 5), (17, 1))
        b1 = self._f1_bmax()
        # one nb per runtime B bucket (4/8/16/32/64), capped by the
        # HBM budget so warm never compiles a shape runtime can't use
        nbs = tuple(sorted({min(nb, b1) for nb in (1, 5, 9, 17, 33)}))
        for ns, nd in shape_grid:          # κ=256 base rung
            for nb in nbs:                 # B buckets the budget allows
                # single-group (k2=128) AND multi-group (k2=κ) widths
                outs.append(self._run_batch(
                    [dummy(ns=ns, nd=nd)] * nb, kap, min(k2, kap)))
                outs.append(self._run_batch(
                    [dummy(ns=ns, nd=nd)] * nb, kap, kap))
        kap8 = min(KAPPA_FLOOR * 8, self.D_cap)
        for ns, nd in ((1, 1), (2, 1), (1, 2), (3, 3)):  # κ=2048 rung
            for nb in (1, 5, 9, 33):     # B = 4 / 8 / 32 / 64
                outs.append(self._run_batch(
                    [dummy(ns=ns, nd=nd)] * nb, kap8, min(k2, kap8)))
                outs.append(self._run_batch(
                    [dummy(ns=ns, nd=nd)] * nb, kap8, kap8))
        # Lsp length buckets: the dummies above (s_len=1) warm the
        # 128-lane tile; mid/long sparse runs hit the 512/2048-lane
        # variants — warm those on the common shapes only
        for lsp_len in LSP_BUCKETS[1:]:
            for ns, nd in ((1, 1), (2, 1), (3, 3)):
                pL = dummy(ns=ns, nd=nd)
                pL.s_len[0] = lsp_len
                for nb in ((1, 5) if b1 > 4 else (1,)):
                    outs.append(self._run_batch(
                        [pL] * nb, kap, min(k2, kap)))
                    outs.append(self._run_batch([pL] * nb, kap, kap))
        # escalation rungs: (κ, k2) widen together, B=4 (few escapees)
        kap32 = min(KAPPA_FLOOR * 32, self.D_cap)
        outs.append(self._run_batch([dummy()], kap8,
                                    min(KAPPA_FLOOR * 2, kap8)))
        outs.append(self._run_batch([dummy()], kap32,
                                    min(KAPPA_FLOOR * 8, kap32)))
        for ns, nd in ((1, 1), (2, 1), (3, 3)):  # multi-group escapees
            outs.append(self._run_batch([dummy(ns=ns, nd=nd)], kap32,
                                        kap32))
            outs.append(self._run_batch([dummy(ns=ns, nd=nd)] * 5,
                                        kap32, kap32))
        # B > 4 buckets exist only when the HBM budget allows them
        nb_big = (1, 5) if self._f2_bmax() > 4 else (1,)
        nb_fd = (1, 5) if self._fd_bmax() > 4 else (1,)
        # selection rungs match search_batch's f2_floor ladder
        ns0 = 4096 if self.D_cap >= (1 << 19) else 2048
        for n_sel in (ns0, 4 * ns0):  # F2 base + first escalation rung
            for np_rows in (1, 9):
                for nb in nb_big:  # B = 4 and (budget allowing) B = bmax
                    p = dummy(np_rows=np_rows)
                    p.p_len[:] = 1
                    outs.append(self._run_batch_f2(
                        [p] * nb, k2, min(n_sel, self.D_cap)))
                    p2 = dummy(np_rows=np_rows)
                    p2.p_len[0] = F2_LPOST_FLOOR + 1  # big-Lp bucket
                    outs.append(self._run_batch_f2(
                        [p2] * nb, k2, min(n_sel, self.D_cap)))
        # FD (direct-cube) shapes: B = 4 and B = 16 buckets, with and
        # without scatter tails (delta postings put every fresh write
        # on the tail, so the Lp=512 and Lp=4096 variants are everyday)
        pd = dummy()
        pd.g_quarter = np.zeros((T, 4), np.int32)
        pd.g_qsyn = np.zeros((T, 4), np.uint32)
        pd0 = dummy()  # no-tail variant (pure quarter-row waves)
        pd0.g_quarter = np.zeros((T, 4), np.int32)
        pd0.g_qsyn = np.zeros((T, 4), np.uint32)
        pd0.p_len[:] = 0
        pt = dummy(np_rows=5)  # Rp=8 bucket
        pt.g_quarter = np.zeros((T, 4), np.int32)
        pt.g_qsyn = np.zeros((T, 4), np.uint32)
        pt.p_len[:] = 1
        pl = dummy()
        pl.g_quarter = np.zeros((T, 4), np.int32)
        pl.g_qsyn = np.zeros((T, 4), np.uint32)
        pl.p_len[0] = 513  # Lp=4096 bucket
        pl2 = dummy()
        pl2.g_quarter = np.zeros((T, 4), np.int32)
        pl2.g_qsyn = np.zeros((T, 4), np.uint32)
        pl2.p_len[0] = F2_LPOST_FLOOR + 1  # Lp=16384 bucket (big
        # bigram scatter tails — one unwarmed hit cost a 91 s compile
        # inside a measured pass)
        for n_sel in (ns0, 4 * ns0):
            for nb in nb_fd:
                outs.append(self._run_batch_fd(
                    [pd] * nb, k2, min(n_sel, self.D_cap)))
                outs.append(self._run_batch_fd(
                    [pd0] * nb, k2, min(n_sel, self.D_cap)))
                if n_sel == ns0:
                    outs.append(self._run_batch_fd(
                        [pt] * nb, k2, min(n_sel, self.D_cap)))
                    outs.append(self._run_batch_fd(
                        [pl] * nb, k2, min(n_sel, self.D_cap)))
                    outs.append(self._run_batch_fd(
                        [pl2] * nb, k2, min(n_sel, self.D_cap)))
        jax.device_get(outs)
        return len(outs)

    def warm_plans(self) -> None:
        """Build-time pre-warm of everything the FIRST query would
        otherwise pay lazily (BENCH_r04: ``devindex.plan`` max 1168ms
        vs 0.3ms min — the cold-plan spike). Host lazies (the docid
        argsort + inverse permutation and the clusterdb sitehash/langid
        columns) are a few ms and always primed; the kernel shape-grid
        ``warm()`` is minutes of XLA compiles, so it runs off-CPU (or
        under ``OSSE_WARM_KERNELS=1``) where those compiles would
        otherwise land mid-serving."""
        self._docid_pos(np.empty(0, np.uint64))
        self._cluster_cols()
        if jax.default_backend() != "cpu" or \
                os.environ.get("OSSE_WARM_KERNELS"):
            self.warm()

    def _parse_out(self, row, k2: int):
        nm = int(row[0])
        missed = float(np.asarray(row[1:2]).view(np.float32)[0])
        idx = row[2:2 + k2].astype(np.int64)
        scores = np.asarray(row[2 + k2:]).view(np.float32)
        return nm, missed, idx, scores

    def _emit(self, results, i, nm, idx, scores):
        keep = scores > 0.0
        results[i] = (
            self.all_docids[np.clip(idx[keep], 0,
                                    max(self.n_docs - 1, 0))],
            scores[keep], nm)

    def _kappa_of(self, p: ResidentPlan, topk: int) -> int:
        """κ rung for a plan.

        Single-scored-group queries get a SPECULATIVE small κ even when
        the driver matches far more docs: with one group the phase-1
        bound is the impact itself — nearly the exact score — so the
        top-κ-by-bound almost always contains the top-k exact and the
        lossless missed-vs-kth check just passes (escalation covers the
        rare miss). Phase-2 gather cost is ∝ κ·T·P, so this is the
        difference between ~9 ms and ~70 ms for a hot single-term
        query. Multi-group queries rung by driver_df as before: their
        pair bounds are distance-free (loose), and a small κ would
        escalate every time."""
        if p.n_scored <= 1:
            # top-MAX_TOP-cut impacts make the single-group bound the
            # exact score (mod float association): the smallest rung
            # suffices and phase-2 cost collapses to κ=256 gathers
            need = max(KAPPA_FLOOR, 2 * topk, p.kappa_min)
        else:
            need = max(KAPPA_FLOOR, 2 * topk, p.driver_df, p.kappa_min)
        for rung in (KAPPA_FLOOR, 8 * KAPPA_FLOOR, 32 * KAPPA_FLOOR):
            if need <= rung:
                return min(rung, self.D_cap)
        return min(_bucket(need, KAPPA_FLOOR), self.D_cap)

    def _f1_bmax(self) -> int:
        """Largest F1 wave B the HBM budget allows (power of two ≤ 64):
        phase-1 intermediates run ~128·D bytes per lane (the single
        [T, D] scatter target — base dead-masking happens at gather
        time — plus the [T, D] bound chains) — at 100k docs B=64 fits
        easily; at a 1M-doc shard it must drop or the wave OOMs next
        to the ~9 GB resident set."""
        cap = max(4, (2 << 30) // (128 * self.D_cap))
        b = 4
        while b * 2 <= cap and b < 64:
            b *= 2
        return b

    def _f1_step_terminal(self) -> int:
        """Terminal-rung (k2 = D_cap) chunk size: the exact-scoring
        cube chain costs ~2048·D bytes per lane."""
        return max(1, min(4, (2 << 30) // (2048 * self.D_cap)))

    def _f2_bmax(self) -> int:
        """F2 batch cap: full-cube intermediates are ~48 bytes/doc/query
        ([T,P,D] cube+validity+scores) — bound them to ~1.5 GB (wave
        RTT is ~100 ms, so doubling B nearly halves F2 wall time)."""
        per_q = 48 * MAX_POSITIONS * self.D_cap
        return max(4, min(16, (1536 << 20) // max(per_q, 1)))

    def _fd_bmax(self) -> int:
        """FD batch cap. The fused Pallas route never materializes the
        per-query cube — its only [T,P,D]-scale HBM is the posting-tail
        scatter target — so it batches ~4× deeper than the generic F2
        envelope at big D (T ≤ 8 worst case)."""
        from .pallas_scores import use_fused
        if use_fused(self.D_cap):
            per_q = 8 * MAX_POSITIONS * self.D_cap * 4
            return max(4, min(16, (4 << 30) // max(per_q, 1)))
        return max(4, min(16, self._f2_bmax()))

    def wave_bytes_per_query(self, plans: list[ResidentPlan],
                             packed: bool = True) -> float:
        """Modelled HBM bytes the F1 wave path streams per query —
        under the live packed layout (f16 impacts, uint8 doc meta,
        length-bucketed Lsp tiles) or the legacy unpacked one (f32
        impacts, int32 meta, flat 2048-lane tiles). Shares _run_batch's
        bucket ladders so the model moves when the layout does; the
        per-plan Lsp tile is the fine-grained bound (real waves pay
        their rung-group's max). BENCH_DISPATCH enforces packed/legacy
        ≤ 0.7 on this model with a nonzero exit."""
        imp = 2 if packed else 4
        meta = 1 if packed else 4
        V = self.d_dense_imp.shape[0]
        D = self.D_cap
        B = max(len(plans), 1)
        total = 0.0
        for p in plans:
            mrs = max(len(p.s_start), 1)
            Rs = 2 if mrs <= 2 else (4 if mrs <= 4 else (
                16 if mrs <= 16 else _bucket(mrs, 64)))
            mls = int(p.s_len.max()) if len(p.s_len) else 0
            Lsp = next(b for b in LSP_BUCKETS if mls <= b) if packed \
                else LSP_BUCKETS[-1]
            mrd = max(len(p.d_slot), 1)
            Rd = 2 if mrd <= 2 else (4 if mrd <= 4 else (
                16 if mrd <= 16 else _bucket(mrd, 64)))
            T = max(len(p.required), 1)
            k2 = min(128, D)
            # sparse lane gathers: doc4 + imp + rs4 + cnt1 + dead1
            total += Rs * Lsp * (4 + imp + 4 + 1 + 1)
            # doc-meta columns the multiplier/alive gates stream [D]
            total += D * (meta + meta + 1)
            # phase-2 payload + dense rs/cnt gathers (layout-invariant)
            total += k2 * T * self.P * 4 + Rd * k2 * 5
        # the [V, D] dense impact matrix streams once per WAVE
        total += V * D * imp
        return total / B

    def _costed(self, name: str, bucket: tuple, modeled_bytes,
                fn, *args, **statics):
        """Dispatch a jitted kernel, roofline-attributing its
        (kernel, shape-bucket) on first sight: devwatch pulls
        flops/bytes from ``lower().compile().cost_analysis()`` once
        per bucket (a dict hit afterwards), so every warmed shape has
        a bandwidth/compute verdict next to the modeled wave bytes."""
        if devwatch.enabled():
            devwatch.note_cost(
                name, bucket,
                lambda: fn.lower(*args, **statics).compile(),
                modeled_bytes=modeled_bytes)
        return fn(*args, **statics)

    def _run_batch(self, plans: list[ResidentPlan], kappa: int, k2: int):
        # pinned bucket ladders — every (Rd, Rs, κ, B) combination that
        # everyday queries can hit is finite and enumerable, so warm()
        # can precompile ALL of them and the measured path never eats a
        # ~60 s tunnel compile (run-to-run bench variance traced to
        # exactly that)
        mrd = max([len(p.d_slot) for p in plans] + [1])
        Rd = 2 if mrd <= 2 else (4 if mrd <= 4 else (
            16 if mrd <= 16 else _bucket(mrd, 64)))
        mrs = max([len(p.s_start) for p in plans] + [1])
        Rs = 2 if mrs <= 2 else (4 if mrs <= 4 else (
            16 if mrs <= 16 else _bucket(mrs, 64)))
        # length-bucketed lane tile: the wave pays for its LONGEST
        # sparse run's bucket (runs chunk at LSP_MAX in the planner, so
        # the top bucket always fits); short-list waves stop paying
        # 2048-lane padding — most of their sparse HBM bytes
        mls = max([int(p.s_len.max()) if len(p.s_len) else 0
                   for p in plans] + [0])
        Lsp = next(b for b in LSP_BUCKETS if mls <= b)
        T = max(len(p.required) for p in plans)
        # B buckets: every per-lane cost (phase-1 chains, phase-2
        # gathers) scales with B INCLUDING pad lanes, while the ~105 ms
        # tunnel RTT is fixed — so big batches amortize, small ones
        # (single-query latency, minority rungs) drop to B=4. κ no
        # longer constrains B: phase 2 is k2-wide (k2 ≪ κ), so big-κ
        # rungs only pay a wider selection pass
        bmax = self._f1_bmax()
        if len(plans) <= 4:
            B = 4
        elif len(plans) <= 8:
            B = 8
        elif len(plans) <= 16:
            B = 16
        elif len(plans) <= 32:
            B = 32
        else:
            B = 64
        B = min(B, bmax)
        if len(plans) > B:  # stray caller overshoot: correctness first
            B = _bucket(len(plans), 4)

        def pad_plan(p: ResidentPlan | None):
            if p is None:
                return (np.full(Rd, -1, np.int32), np.zeros(Rd, np.int32),
                        np.zeros(Rd, np.int32), np.ones(Rd, np.int32),
                        np.zeros(Rd, np.uint32),
                        np.zeros(Rs, np.int32), np.zeros(Rs, np.int32),
                        np.zeros(Rs, np.int32), np.zeros(Rs, np.int32),
                        np.ones(Rs, np.int32), np.zeros(Rs, np.uint32),
                        np.ones(Rs, bool),
                        np.full(T, 0.5, np.float32), np.zeros(T, bool),
                        np.zeros(T, bool), np.zeros(T, bool),
                        np.zeros(T, bool), np.ones(TABLE_SIZE, bool),
                        np.int32(0))
            pr = lambda a, n, fill: _pad1(a, n, fill)
            return (pr(p.d_slot, Rd, -1), pr(p.d_group, Rd, 0),
                    pr(p.d_base, Rd, 0), pr(p.d_quota, Rd, 1),
                    pr(p.d_syn, Rd, 0),
                    pr(p.s_start, Rs, 0), pr(p.s_len, Rs, 0),
                    pr(p.s_group, Rs, 0), pr(p.s_base, Rs, 0),
                    pr(p.s_quota, Rs, 1), pr(p.s_syn, Rs, 0),
                    pr(p.s_isbase, Rs, True),
                    _pad1(p.freq_weight, T, 0.5),
                    _pad1(p.required, T, False),
                    _pad1(p.negative, T, False),
                    _pad1(p.scored, T, False),
                    _pad1(p.counts, T, False), p.table,
                    np.int32(p.qlang))

        padded = [pad_plan(p) for p in plans] \
            + [pad_plan(None)] * (B - len(plans))
        args = [np.stack([p[j] for p in padded]) for j in range(19)]
        # few-hot selector for the phase-1 dense matmul: one 1.0 per
        # dense row occurrence at (query, group, dense slot)
        V = self.d_dense_imp.shape[0]
        sel = np.zeros((B, T, V), np.float32)
        for b, p in enumerate(plans):
            for slot, g in zip(p.d_slot, p.d_group):
                if slot >= 0:
                    sel[b, g, slot] += 1.0
        log.debug("f1 wave: B=%d Rd=%d Rs=%d Lsp=%d kappa=%d k2=%d",
                  B, Rd, Rs, Lsp, kappa, k2)
        # host args ride the (async) dispatch; returned WITHOUT fetching
        # — the caller fetches every wave's output in ONE device_get
        # (each separate blocking fetch costs a full ~100 ms tunnel RTT)
        d_filter, d_sort, uf, us = self._filter_sort_cols(plans[0])
        modeled = self.wave_bytes_per_query(plans) * B \
            if devwatch.enabled() else None
        return self._costed(
            "devindex._two_phase", (B, Rd, Rs, Lsp, kappa, k2),
            modeled, _two_phase,
            self.d_payload, self.d_doc, self.d_imp, self.d_rs,
            self.d_cnt, self.d_dense_imp, self.d_dense_rs,
            self.d_dense_cnt,
            self.d_siterank, self.d_doclang, self.d_dead,
            np.int32(self.n_docs), d_filter, d_sort, sel, *args,
            n_positions=self.P, lsp=Lsp, kappa=kappa, k2=k2,
            use_table=any(p.has_table for p in plans),
            use_filter=uf, use_sort=us)

    def _run_batch_f2(self, plans: list[ResidentPlan], k2: int,
                      n_sel: int):
        Rc = _bucket(max([len(p.c_slot) for p in plans] + [1]), 8)
        mrp = max([len(p.p_start) for p in plans] + [1])
        Rp = 8 if mrp <= 8 else (32 if mrp <= 32 else _bucket(mrp, 64))
        maxlen = max([int(p.p_len.max()) if len(p.p_len) else 1
                      for p in plans] + [1])
        Lp = F2_LPOST_FLOOR if maxlen <= F2_LPOST_FLOOR else F2_SCATTER_MAX
        T = max(len(p.required) for p in plans)
        # two B buckets: the latency path (≤4 real queries) must not
        # pay a full B=bmax wave of [T, P, D] work for its pad lanes
        B = 4 if len(plans) <= 4 else max(self._f2_bmax(), len(plans))

        def pad_plan(p: ResidentPlan | None):
            if p is None:
                return (np.full(Rc, -1, np.int32), np.zeros(Rc, np.int32),
                        np.zeros(Rc, np.int32), np.zeros(Rc, np.int32),
                        np.ones(Rc, np.int32), np.zeros(Rc, np.uint32),
                        np.zeros(Rp, np.int32), np.zeros(Rp, np.int32),
                        np.zeros(Rp, np.int32), np.zeros(Rp, np.int32),
                        np.ones(Rp, np.int32), np.zeros(Rp, np.uint32),
                        np.ones(Rp, bool),
                        np.full(T, 0.5, np.float32), np.zeros(T, bool),
                        np.zeros(T, bool), np.zeros(T, bool),
                        np.zeros(T, bool), np.ones(TABLE_SIZE, bool),
                        np.int32(0))
            pr = lambda a, n, fill: _pad1(a, n, fill)
            return (pr(p.c_slot, Rc, -1), pr(p.c_dslot, Rc, 0),
                    pr(p.c_group, Rc, 0), pr(p.c_base, Rc, 0),
                    pr(p.c_quota, Rc, 1), pr(p.c_syn, Rc, 0),
                    pr(p.p_start, Rp, 0), pr(p.p_len, Rp, 0),
                    pr(p.p_group, Rp, 0), pr(p.p_base, Rp, 0),
                    pr(p.p_quota, Rp, 1), pr(p.p_syn, Rp, 0),
                    pr(p.p_isbase, Rp, True),
                    _pad1(p.freq_weight, T, 0.5),
                    _pad1(p.required, T, False),
                    _pad1(p.negative, T, False),
                    _pad1(p.scored, T, False),
                    _pad1(p.counts, T, False), p.table,
                    np.int32(p.qlang))

        padded = [pad_plan(p) for p in plans] \
            + [pad_plan(None)] * (B - len(plans))
        args = [np.stack([p[j] for p in padded]) for j in range(20)]
        log.debug("f2 wave: B=%d Rc=%d Rp=%d Lp=%d k2=%d n_sel=%d",
                  B, Rc, Rp, Lp, k2, n_sel)
        d_filter, d_sort, uf, us = self._filter_sort_cols(plans[0])
        return self._costed(
            "devindex._full_cube",
            (B, Rc, Rp, Lp, k2, min(n_sel, self.D_cap)),
            None, _full_cube,
            self.d_payload, self.d_docc, self.d_cube,
            self.d_dense_cnt, self.d_siterank, self.d_doclang,
            self.d_dead, np.int32(self.n_docs), d_filter, d_sort,
            *args,
            n_positions=self.P, lpost=Lp, k2=k2,
            n_sel=min(n_sel, self.D_cap),
            use_table=any(p.has_table for p in plans),
            use_filter=uf, use_sort=us)

    def _run_batch_fd(self, plans: list[ResidentPlan], k2: int,
                      n_sel: int):
        """Direct-cube (FD) wave: heavy sublists read as quarter-rows
        of the resident cube, small ones ride a bounded scatter tail —
        no per-query cube assembly."""
        T = max(len(p.required) for p in plans)
        B = 4 if len(plans) <= 4 else max(self._fd_bmax(), len(plans))
        zq = 4 * getattr(self, "cube_zero_slot", 0)
        cs = np.full((B, T, 4), zq, np.int32)
        sy = np.zeros((B, T, 4), np.uint32)
        for b, p in enumerate(plans):
            cs[b, : len(p.g_quarter)] = p.g_quarter
            sy[b, : len(p.g_qsyn)] = p.g_qsyn
        mrp = max([len(p.p_start) for p in plans] + [1])
        Rp = 4 if mrp <= 4 else _bucket(mrp, 8)
        maxlen = max([int(p.p_len.max()) if len(p.p_len) else 0
                      for p in plans] + [0])
        # Lp = 0: every query in the wave is pure quarter-rows — the
        # fused kernel then compiles without a tail input at all
        Lp = 0 if maxlen == 0 else (512 if maxlen <= 512 else (
            F2_LPOST_FLOOR if maxlen <= F2_LPOST_FLOOR
            else F2_SCATTER_MAX))

        def pad_plan(p: ResidentPlan | None):
            if p is None:
                return (np.zeros(Rp, np.int32), np.zeros(Rp, np.int32),
                        np.zeros(Rp, np.int32), np.zeros(Rp, np.int32),
                        np.ones(Rp, np.int32), np.zeros(Rp, np.uint32),
                        np.ones(Rp, bool),
                        np.full(T, 0.5, np.float32), np.zeros(T, bool),
                        np.zeros(T, bool), np.zeros(T, bool),
                        np.zeros(T, bool), np.ones(TABLE_SIZE, bool),
                        np.int32(0))
            pr = lambda a, n, fill: _pad1(a, n, fill)
            return (pr(p.p_start, Rp, 0), pr(p.p_len, Rp, 0),
                    pr(p.p_group, Rp, 0), pr(p.p_base, Rp, 0),
                    pr(p.p_quota, Rp, 1), pr(p.p_syn, Rp, 0),
                    pr(p.p_isbase, Rp, True),
                    _pad1(p.freq_weight, T, 0.5),
                    _pad1(p.required, T, False),
                    _pad1(p.negative, T, False),
                    _pad1(p.scored, T, False),
                    _pad1(p.counts, T, False), p.table,
                    np.int32(p.qlang))

        padded = [pad_plan(p) for p in plans] \
            + [pad_plan(None)] * (B - len(plans))
        args = [np.stack([p[j] for p in padded]) for j in range(14)]
        log.debug("fd wave: B=%d T=%d Rp=%d Lp=%d k2=%d n_sel=%d",
                  B, T, Rp, Lp, k2, n_sel)
        d_filter, d_sort, uf, us = self._filter_sort_cols(plans[0])
        d_cube = self.d_cube
        if devcheck.enabled():
            # guardrail sweep over the resident position cube before
            # the wave reads it: nonzero payloads must decode to a
            # legal hashgroup (a corrupt/torn tile fails this with
            # probability 5/16 per word). Host-side, pre-dispatch —
            # _direct_cube itself is jitted so checkify can't run there
            d_cube = devcheck.apply_cube_fault(d_cube)
            devcheck.check_cube(d_cube, route="fd")
        return self._costed(
            "devindex._direct_cube",
            (B, T, Rp, Lp, k2, min(n_sel, self.D_cap)),
            None, _direct_cube,
            d_cube, self.d_payload, self.d_docc,
            self.d_siterank, self.d_doclang, self.d_dead,
            np.int32(self.n_docs), d_filter, d_sort, cs, sy, *args,
            n_positions=self.P, lpost=Lp, k2=k2,
            n_sel=min(n_sel, self.D_cap),
            use_table=any(p.has_table for p in plans),
            use_filter=uf, use_sort=us)


@jax.jit
def _apply_doc_meta(sr, dl, idx, vsr, vdl):
    return sr.at[idx].set(vsr), dl.at[idx].set(vdl)


@partial(jax.jit, static_argnames=("n_positions", "lsp", "kappa", "k2",
                                   "use_table", "use_filter",
                                   "use_sort"))
def _two_phase(d_payload, d_doc, d_imp, d_rs, d_cnt,
               d_dense_imp, d_dense_rs, d_dense_cnt,
               d_siterank, d_doclang, d_dead, n_docs_total,
               d_filter, d_sort, d_sel,
               d_slot, d_group, d_base, d_quota, d_syn,
               s_start, s_len, s_group, s_base, s_quota, s_syn, s_isbase,
               freqw, required, negative, scored, counts, table, qlang,
               n_positions: int, lsp: int, kappa: int, k2: int,
               use_table: bool = True, use_filter: bool = False,
               use_sort: bool = False):
    """The fused two-phase kernel, vmapped over the query axis.

    Phase 1 = dense upper bounds + intersection + approx top-κ (the
    maxPossibleScore prune, Posdb.cpp:6052); phase 2 = exact cube scoring
    of the κ candidates (docIdLoop semantics via scorer.min_scores).
    Output per query: [n_matched, bitcast(max missed bound), κ-top-k2
    doc indices, bitcast(exact scores)]."""
    D = d_dead.shape[0]
    V = d_dense_imp.shape[0]
    M = d_doc.shape[0]
    N = d_payload.shape[0]
    P = n_positions
    big = jnp.float32(9.99e8)

    # ---- phase 1 dense accumulation as ONE matmul on the MXU:
    # ubb[b, t, :] = Σ_v sel[b, t, v] · dense_imp[v, :]. The selector
    # [B·T, V] is a few-hot host-built matrix; the whole batch reads
    # the [V, D] impact matrix ONCE at bandwidth speed. The former
    # per-row dynamic slices cost ~91 ms/wave at B=32 (per-lane row
    # copies); this is ~1 ms. The impact matrix is packed f16 at
    # 1/IMPACT_SCALE (round-up demoted, so scaled-back values stay ≥
    # the exact f32 impact); the selector's small integer counts are
    # f16-exact, and the product accumulates in f32
    # (preferred_element_type) — the bound stays admissible and the
    # in-kernel ×1.00001 inflation covers the f32 accumulation
    # reassociation as before. The exponent shift is undone exactly
    # (power of two) on the f32 result.
    B, Ts, _ = d_sel.shape
    ubb_mm = jax.lax.dot_general(
        d_sel.reshape(B * Ts, V).astype(d_dense_imp.dtype), d_dense_imp,
        (((1,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32).reshape(B, Ts, D) \
        * jnp.float32(IMPACT_SCALE)

    def one(ubb, d_slot, d_group, d_base, d_quota, d_syn,
            s_start, s_len, s_group, s_base, s_quota, s_syn, s_isbase,
            freqw, required, negative, scored, counts, table, qlang):
        T = required.shape[0]
        Rd = d_slot.shape[0]
        Rs = s_start.shape[0]
        t_ax = jnp.arange(T)
        live = ~d_dead                                        # [D]

        # ---- phase 1: group upper bounds over the full doc axis
        # (dense-row part arrives precomputed from the batch matmul) ----
        dgate = (d_slot >= 0)
        # sparse rows: one fused contiguous gather + bounded scatter-add
        # into [T, D]. Base-row lanes of dead docs zero at GATHER time
        # (a [Rs, Lsp] gather of the dead vector) so base and delta
        # share one scatter target — half the [2, T, D] footprint the
        # former base/delta target split paid per lane
        lane = jnp.arange(lsp, dtype=jnp.int32)
        sidx = s_start[:, None] + lane[None, :]               # [Rs, Lsp]
        smask = lane[None, :] < s_len[:, None]
        sidxc = jnp.clip(sidx, 0, M - 1)
        sdoc = d_doc[sidxc]
        # gather moves the packed f16 bytes; the cast to f32 (and the
        # exact IMPACT_SCALE shift back) happens in registers so the
        # scatter-add target stays full precision
        simp = d_imp[sidxc].astype(jnp.float32) * jnp.float32(
            IMPACT_SCALE)
        srs = d_rs[sidxc]
        scnt = d_cnt[sidxc]
        sdead = d_dead[jnp.clip(sdoc, 0, D - 1)]              # [Rs, Lsp]
        skeep = smask & ~(s_isbase[:, None] & sdead)
        tgt = jnp.where(skeep, s_group[:, None] * D + sdoc, T * D)
        ubs = jnp.zeros((T * D,), jnp.float32).at[tgt.ravel()].add(
            jnp.where(skeep, simp, 0.0).ravel(), mode="drop"
        ).reshape(T, D)
        ub = ubb * live[None, :] + ubs                        # [T, D]
        rstgt = jnp.where(
            smask, jnp.arange(Rs, dtype=jnp.int32)[:, None] * D + sdoc,
            Rs * D)
        rsacc = jnp.zeros((Rs * D,), jnp.int32).at[rstgt.ravel()].set(
            jnp.where(smask, srs, 0).ravel(), mode="drop")
        cntacc = jnp.zeros((Rs * D,), jnp.uint8).at[rstgt.ravel()].set(
            jnp.where(smask, scnt, jnp.uint8(0)).ravel(), mode="drop")

        # intersection + admissible min bound
        present = ub > 0.0                                    # [T, D]
        sc = counts
        ubw = ub * (freqw * freqw)[:, None]
        req_ok = jnp.all(jnp.where(required[:, None], present, True),
                         axis=0)
        neg_ok = ~jnp.any(jnp.where(negative[:, None], present, False),
                          axis=0)
        # the truth-table gate is a [D]-wide gather from a 1024-entry
        # table — ~140 ms/wave at B=64 (scalar gather) — and non-
        # boolean queries carry the all-true table, so the lookup is
        # compiled out unless the wave really holds boolean queries
        tok = presence_table_ok(present, table) if use_table else True
        alive = (req_ok & neg_ok & tok
                 & (jnp.arange(D) < n_docs_total))
        if use_filter:
            # numeric range gate (gbmin:/gbmax: — a host-ANDed boolean
            # column over however many fields the query constrained)
            alive = alive & d_filter
        m1 = present & sc[:, None]
        ubw_m = jnp.where(m1, ubw, big)
        min_single_ub = jnp.min(ubw_m, axis=0)
        from .scorer import MAX_PAIR_SPAN
        if T <= MAX_PAIR_SPAN + 1:
            # every pair is within the span, so the pair-bound min has
            # a closed form: min over pairs of √(a_i·a_j) = √(min1·min2)
            # over the two smallest present scored bounds — O(T·D)
            # instead of the unrolled pair loop (~79 ms/wave at B=32)
            npres = jnp.sum(m1, axis=0)                       # [D]
            am = jnp.argmin(ubw_m, axis=0)                    # [D]
            min2 = jnp.min(
                jnp.where(t_ax[:, None] == am[None, :], big, ubw_m),
                axis=0)
            min_pair_ub = jnp.sqrt(min_single_ub * min2)
            any_pair = npres >= 2
        else:
            min_pair_ub = jnp.full((D,), big)
            any_pair = jnp.zeros((D,), bool)
            for i in range(T):
                for j in range(i + 1, min(i + 1 + MAX_PAIR_SPAN, T)):
                    ok = present[i] & present[j] & sc[i] & sc[j]
                    pu = jnp.sqrt(ubw[i] * ubw[j])
                    min_pair_ub = jnp.where(
                        ok, jnp.minimum(min_pair_ub, pu), min_pair_ub)
                    any_pair = any_pair | ok
        ubmin = jnp.minimum(jnp.where(any_pair, min_pair_ub, big),
                            min_single_ub)
        # per-doc filter-only fallback (mirrors scorer.min_scores)
        ubmin = jnp.where(jnp.any(m1, axis=0), ubmin, 1.0)
        mult = final_multipliers(d_siterank, d_doclang, qlang)
        if use_sort:
            # gbsortby: rank purely by the positive sort column — the
            # per-doc "bound" IS the exact sort key, so selection is
            # exact and the escalation check passes by construction
            ubfinal = jnp.where(alive, d_sort, 0.0)
        else:
            ubfinal = jnp.where(alive, ubmin * mult * 1.00001, 0.0)
        nm = jnp.sum(alive)

        # candidate selection via top-8-per-block max-reduces:
        # approx_max_k/top_k lower to sort-like programs costing
        # hundreds of ms on a [B, 131072] axis (measured ~190 ms fixed
        # per wave); _block_topn is ~2 ms and its missed_max feeds the
        # SAME lossless escalation check
        cval, cand, ub_missed = _block_topn(ubfinal, kappa)

        # phase 2 scores only the top-k2 BY BOUND: the (k2+1)-th-best
        # bound folds into the missed-max, so an unscored candidate
        # that could have ranked triggers the same lossless escalation.
        # Phase-2 gather cost is ∝ rows·P·κ·B (the dominant wave cost
        # at ~13-56 Melem/s scalar gather), so κ=2048 rungs score 128
        # candidates, not 2048 — the selection rung and the scoring
        # width decouple
        kap2 = kappa
        if k2 < kappa:
            vals, idxs = jax.lax.top_k(cval, k2 + 1)
            cand = cand[idxs[:k2]]
            cval = vals[:k2]
            ub_missed = jnp.maximum(ub_missed, vals[k2])
            kap2 = k2

        # ---- phase 2: exact scoring of the κ candidates ----
        dead_c = d_dead[cand]                                 # [κ]
        p_ax = jnp.arange(P, dtype=jnp.int32)[:, None]        # [P, 1]
        cube = jnp.zeros((T, P, kap2), jnp.uint32)
        pv = jnp.zeros((T, P, kap2), bool)

        def add_row(cube, pv, rs, cnt_c, group, base, quota, syn,
                    is_base):
            cnt = cnt_c.astype(jnp.int32)                     # [κ]
            cnt = jnp.where(is_base & dead_c, 0, cnt)
            q = p_ax - base                                   # [P, κ]
            sel = (q >= 0) & (q < jnp.minimum(cnt, quota)[None, :])
            src = rs[None, :] + q
            val = (d_payload[jnp.clip(src, 0, N - 1)]
                   | (syn.astype(jnp.uint32) << jnp.uint32(31)))
            gmask = (group == t_ax)[:, None, None]            # [T, 1, 1]
            cube = cube + jnp.where(sel, val, jnp.uint32(0))[None] \
                * gmask.astype(jnp.uint32)
            pv = pv | (sel[None] & gmask)
            return cube, pv

        dslotc = jnp.clip(d_slot, 0, V - 1)[:, None] * D + cand[None, :]
        dense_rs_c = d_dense_rs[dslotc]
        dense_cnt_c = d_dense_cnt[dslotc]
        for r in range(Rd):
            rs_c = jnp.where(dgate[r], dense_rs_c[r], 0)
            cnt_c = jnp.where(dgate[r], dense_cnt_c[r], jnp.uint8(0))
            cube, pv = add_row(cube, pv, rs_c, cnt_c, d_group[r],
                               d_base[r], d_quota[r], d_syn[r], True)
        for r in range(Rs):
            cube, pv = add_row(cube, pv, rsacc[r * D + cand],
                               cntacc[r * D + cand], s_group[r],
                               s_base[r], s_quota[r], s_syn[r],
                               s_isbase[r])

        min_sc, present2 = min_scores(cube, pv, freqw, sc)
        req_ok2 = jnp.all(jnp.where(required[:, None], present2, True),
                          axis=0)
        neg_ok2 = ~jnp.any(jnp.where(negative[:, None], present2, False),
                           axis=0)
        tok2 = presence_table_ok(present2, table) if use_table \
            else True
        match2 = (req_ok2 & neg_ok2 & tok2
                  & (cval > 0.0) & (min_sc < big))
        if use_sort:
            final = jnp.where(match2, d_sort[cand], 0.0)
        else:
            final = jnp.where(
                match2,
                min_sc * final_multipliers(d_siterank[cand],
                                           d_doclang[cand], qlang),
                0.0)
        ts, tl = jax.lax.top_k(final, k2)
        ti = cand[tl]
        return jnp.concatenate([
            jnp.atleast_1d(nm.astype(jnp.uint32)),
            jax.lax.bitcast_convert_type(jnp.atleast_1d(ub_missed),
                                         jnp.uint32),
            ti.astype(jnp.uint32),
            jax.lax.bitcast_convert_type(ts, jnp.uint32),
        ])

    return jax.vmap(one)(ubb_mm, d_slot, d_group, d_base, d_quota,
                         d_syn, s_start, s_len, s_group, s_base,
                         s_quota, s_syn, s_isbase, freqw, required,
                         negative, scored, counts, table, qlang)


@partial(jax.jit, static_argnames=("n_positions", "lpost", "k2", "n_sel",
                                   "use_table", "use_filter",
                                   "use_sort"))
def _full_cube(d_payload, d_docc, d_cube, d_dense_cnt,
               d_siterank, d_doclang, d_dead, n_docs_total,
               d_filter, d_sort,
               c_slot, c_dslot, c_group, c_base, c_quota, c_syn,
               p_start, p_len, p_group, p_base, p_quota, p_syn, p_isbase,
               freqw, required, negative, scored, counts, table, qlang,
               n_positions: int, lpost: int, k2: int, n_sel: int,
               use_table: bool = True, use_filter: bool = False,
               use_sort: bool = False):
    """Full-corpus exact kernel (F2) for corpus-wide drivers.

    Builds the [T, P, D] position cube over the WHOLE doc axis — the
    heaviest terms from materialized cube rows (plain slices), the rest
    by a bounded posting-granular scatter — then runs the exact
    docIdLoop scoring (scorer.min_scores) on every doc at once. This is
    the reference's intersectLists10_r docIdLoop with the loop axis
    vectorized away; no pruning, no escalation ladder.
    Output format matches _two_phase."""
    D = d_dead.shape[0]
    N = d_payload.shape[0]
    P = n_positions
    VcPD = d_cube.shape[0]
    big = jnp.float32(9.99e8)

    def one(c_slot, c_dslot, c_group, c_base, c_quota, c_syn,
            p_start, p_len, p_group, p_base, p_quota, p_syn, p_isbase,
            freqw, required, negative, scored, counts, table, qlang):
        T = required.shape[0]
        Rc = c_slot.shape[0]
        Rp = p_start.shape[0]
        t_ax = jnp.arange(T)
        live = ~d_dead
        p_ax = jnp.arange(P, dtype=jnp.int32)[:, None]        # [P, 1]

        cube = jnp.zeros((T, P, D), jnp.uint32)
        pv = jnp.zeros((T, P, D), bool)
        # materialized cube rows: slice + count-mask (cube rows are
        # always base postings, so the dead vector masks them)
        V = d_dense_cnt.shape[0] // D
        for r in range(Rc):
            gate = c_slot[r] >= 0
            row = jax.lax.dynamic_slice(
                d_cube, (jnp.clip(c_slot[r], 0, VcPD // (P * D) - 1)
                         * P * D,), (P * D,)).reshape(P, D)
            cnt = jax.lax.dynamic_slice(
                d_dense_cnt, (jnp.clip(c_dslot[r], 0, V - 1) * D,),
                (D,)).astype(jnp.int32)
            # shift the row to the sublist's slot range [base,
            # base+quota): out[p] = row[p - base]. Done as a contiguous
            # dynamic_slice on a zero-padded [2P, D] image — a traced-
            # index take here lowers to a ~P·D scalar gather per row
            # per lane, measured as THE dominant F2 cost (~270 ms/wave)
            q = p_ax[:, 0] - c_base[r]                    # [P]
            padded = jnp.concatenate(
                [jnp.zeros((P, D), row.dtype), row], axis=0)
            row = jax.lax.dynamic_slice(
                padded,
                (jnp.int32(P) - jnp.clip(c_base[r], 0, P)
                 .astype(jnp.int32), jnp.int32(0)), (P, D))
            pvr = ((q[:, None] >= 0)
                   & (q[:, None]
                      < jnp.minimum(cnt, c_quota[r])[None, :])
                   & live[None, :] & gate)
            val = row | (c_syn[r].astype(jnp.uint32) << jnp.uint32(31))
            gmask = (c_group[r] == t_ax)[:, None, None]
            cube = cube + jnp.where(pvr, val, jnp.uint32(0))[None] \
                * gmask.astype(jnp.uint32)
            pv = pv | (pvr[None] & gmask)
        # posting-granular scatter rows (bigrams, deltas, small terms)
        lane = jnp.arange(lpost, dtype=jnp.int32)
        idx = p_start[:, None] + lane[None, :]                # [Rp, Lp]
        m = lane[None, :] < p_len[:, None]
        idxc = jnp.clip(idx, 0, N - 1)
        docc = d_docc[idxc]
        doc = (docc >> jnp.uint32(_OCC_BITS)).astype(jnp.int32)
        occ = (docc & jnp.uint32(_OCC_MASK)).astype(jnp.int32)
        pay = (d_payload[idxc]
               | (p_syn[:, None].astype(jnp.uint32) << jnp.uint32(31)))
        dead_l = d_dead[jnp.clip(doc, 0, D - 1)]
        ok = (m & (occ < p_quota[:, None])
              & ~(dead_l & p_isbase[:, None]))
        slot = p_base[:, None] + occ
        tgt = jnp.where(ok, (p_group[:, None] * P + slot) * D + doc,
                        T * P * D)
        cube = cube.reshape(-1).at[tgt.ravel()].add(
            jnp.where(ok, pay, jnp.uint32(0)).ravel(), mode="drop"
        ).reshape(T, P, D)
        pv = pv.reshape(-1).at[tgt.ravel()].set(
            ok.ravel(), mode="drop").reshape(T, P, D)

        min_sc, present = min_scores(cube, pv, freqw, counts)
        req_ok = jnp.all(jnp.where(required[:, None], present, True),
                         axis=0)
        neg_ok = ~jnp.any(jnp.where(negative[:, None], present, False),
                          axis=0)
        tok = presence_table_ok(present, table) if use_table else True
        match = (req_ok & neg_ok & tok
                 & (jnp.arange(D) < n_docs_total) & (min_sc < big))
        if use_filter:
            match = match & d_filter
        if use_sort:
            final = jnp.where(match, d_sort, 0.0)
        else:
            final = jnp.where(
                match, min_sc * final_multipliers(d_siterank, d_doclang,
                                                  qlang), 0.0)
        nm = jnp.sum(match)
        # block-winners then a cheap exact top-k over the winners;
        # escalation reruns with 4x the blocks, terminal at n_sel == D
        # where every doc is selected and missed is exactly 0
        w_vals, w_idx, missed = _block_topn(final, min(n_sel, D))
        ts, tl = jax.lax.top_k(w_vals, min(k2, min(n_sel, D)))
        ti = w_idx[tl]
        return jnp.concatenate([
            jnp.atleast_1d(nm.astype(jnp.uint32)),
            jax.lax.bitcast_convert_type(jnp.atleast_1d(missed),
                                         jnp.uint32),
            ti.astype(jnp.uint32),
            jax.lax.bitcast_convert_type(ts, jnp.uint32),
        ])

    return jax.vmap(one)(c_slot, c_dslot, c_group, c_base, c_quota,
                         c_syn, p_start, p_len, p_group, p_base, p_quota,
                         p_syn, p_isbase, freqw, required, negative,
                         scored, counts, table, qlang)


@partial(jax.jit, static_argnames=("n_positions", "lpost", "k2",
                                   "n_sel", "use_table", "use_filter",
                                   "use_sort"))
def _direct_cube(d_cube, d_payload, d_docc, d_siterank,
                 d_doclang, d_dead, n_docs_total, d_filter, d_sort,
                 g_quarter, g_qsyn,
                 p_start, p_len, p_group, p_base, p_quota, p_syn,
                 p_isbase,
                 freqw, required, negative, scored, counts, table, qlang,
                 n_positions: int, lpost: int, k2: int, n_sel: int,
                 use_table: bool = True, use_filter: bool = False,
                 use_sort: bool = False):
    """Direct full-corpus kernel (FD) — the F2 fast path for queries
    whose every group assembles from quarter-aligned base cube rows
    (1 sublist = full row; original+bigram = half+half;
    original+synonym+bigram = half+quarter+quarter — the slot_plan
    layouts).

    No per-query [T, P, D] cube is scattered together from per-lane
    dynamic slices, traced shifts and masked adds (measured as the
    dominant F2 cost at ~24 ms/query): the group planes are quarter-row
    gathers from the resident cube (quarter q of a term's [P, D] row
    holds its occurrences q·P/4..), with a zero payload marking an
    empty slot (real postings always carry densityrank ≥ 1, so
    payload ≠ 0 — a build-side invariant; the cube's last slot is kept
    all-zero as the absent-quarter target). Small non-cube sublists
    (bigrams, deltas) add through a BOUNDED posting-scatter tail —
    the same scatter the generic F2 runs, capped by the planner at
    FD_SCATTER_MAX_LANES. Scoring is the very same ``min_scores``
    every other path runs, so parity is bit-for-bit by construction.
    Output format matches _full_cube."""
    D = d_dead.shape[0]
    P = n_positions
    P4 = P // 4
    N = d_payload.shape[0]
    Vc = d_cube.shape[0] // (P * D)
    big = jnp.float32(9.99e8)
    quarter_rows = d_cube.reshape(Vc * 4, P4 * D)

    from .pallas_scores import fd_scores_fused, use_fused
    if use_fused(D):
        return _direct_cube_fused(
            d_cube, d_payload, d_docc, d_siterank, d_doclang, d_dead,
            n_docs_total, d_filter, d_sort, g_quarter, g_qsyn,
            p_start, p_len, p_group, p_base, p_quota, p_syn, p_isbase,
            freqw, required, negative, scored, counts, table, qlang,
            n_positions=n_positions, lpost=lpost, k2=k2, n_sel=n_sel,
            use_table=use_table, use_filter=use_filter,
            use_sort=use_sort)

    def one(g_quarter, g_qsyn, p_start, p_len, p_group, p_base,
            p_quota, p_syn, p_isbase, freqw, required, negative,
            scored, counts, table, qlang):
        T = required.shape[0]
        live = ~d_dead
        sc = counts
        rows = quarter_rows[
            jnp.clip(g_quarter, 0, Vc * 4 - 1)].reshape(T, 4, P4, D)
        synbit = (g_qsyn.astype(jnp.uint32)
                  << jnp.uint32(31))[:, :, None, None]
        rows = jnp.where(rows != 0, rows | synbit, rows)
        rows = rows.reshape(T, P, D)
        pvr = (rows != 0) & live[None, None, :]               # [T, P, D]
        # dead docs' base values must not pollute scatter-adds below
        cube = jnp.where(pvr, rows, jnp.uint32(0))
        # posting-granular scatter tail (bigrams, deltas, small terms —
        # same semantics as _full_cube's scatter block)
        lane = jnp.arange(lpost, dtype=jnp.int32)
        idx = p_start[:, None] + lane[None, :]                # [Rp, Lp]
        m = lane[None, :] < p_len[:, None]
        idxc = jnp.clip(idx, 0, N - 1)
        docc = d_docc[idxc]
        doc = (docc >> jnp.uint32(_OCC_BITS)).astype(jnp.int32)
        occ = (docc & jnp.uint32(_OCC_MASK)).astype(jnp.int32)
        pay = (d_payload[idxc]
               | (p_syn[:, None].astype(jnp.uint32) << jnp.uint32(31)))
        dead_l = d_dead[jnp.clip(doc, 0, D - 1)]
        ok = (m & (occ < p_quota[:, None])
              & ~(dead_l & p_isbase[:, None]))
        slot = p_base[:, None] + occ
        tgt = jnp.where(ok, (p_group[:, None] * P + slot) * D + doc,
                        T * P * D)
        cube = cube.reshape(-1).at[tgt.ravel()].add(
            jnp.where(ok, pay, jnp.uint32(0)).ravel(), mode="drop"
        ).reshape(T, P, D)
        pvr = pvr.reshape(-1).at[tgt.ravel()].set(
            ok.ravel(), mode="drop").reshape(T, P, D)
        min_sc, present = min_scores(cube, pvr, freqw, sc)
        req_ok = jnp.all(jnp.where(required[:, None], present, True),
                         axis=0)
        neg_ok = ~jnp.any(jnp.where(negative[:, None], present, False),
                          axis=0)
        tok = presence_table_ok(present, table) if use_table else True
        match = (req_ok & neg_ok & tok
                 & (jnp.arange(D) < n_docs_total) & (min_sc < big))
        if use_filter:
            match = match & d_filter
        if use_sort:
            final = jnp.where(match, d_sort, 0.0)
        else:
            final = jnp.where(
                match, min_sc * final_multipliers(d_siterank, d_doclang,
                                                  qlang), 0.0)
        nm = jnp.sum(match)
        w_vals, w_idx, missed = _block_topn(final, min(n_sel, D))
        ts, tl = jax.lax.top_k(w_vals, min(k2, n_sel, D))
        ti = w_idx[tl]
        return jnp.concatenate([
            jnp.atleast_1d(nm.astype(jnp.uint32)),
            jax.lax.bitcast_convert_type(jnp.atleast_1d(missed),
                                         jnp.uint32),
            ti.astype(jnp.uint32),
            jax.lax.bitcast_convert_type(ts, jnp.uint32),
        ])

    return jax.vmap(one)(g_quarter, g_qsyn, p_start, p_len, p_group,
                         p_base, p_quota, p_syn, p_isbase, freqw,
                         required, negative, scored, counts, table,
                         qlang)


def _direct_cube_fused(d_cube, d_payload, d_docc, d_siterank,
                       d_doclang, d_dead, n_docs_total, d_filter,
                       d_sort, g_quarter, g_qsyn,
                       p_start, p_len, p_group, p_base, p_quota,
                       p_syn, p_isbase,
                       freqw, required, negative, scored, counts,
                       table, qlang,
                       n_positions: int, lpost: int, k2: int,
                       n_sel: int, use_table: bool, use_filter: bool,
                       use_sort: bool):
    """FD via the fused Pallas kernel: the per-query [T, P, D] cube
    never materializes in HBM — only the (usually small) posting TAIL
    is scattered in XLA; assembly of the resident quarter-rows and the
    whole scoring chain run tile-by-tile in VMEM
    (pallas_scores.fd_scores_fused). Same outputs as _direct_cube."""
    from .pallas_scores import fd_scores_fused

    D = d_dead.shape[0]
    P = n_positions
    N = d_payload.shape[0]
    B, T, _ = g_quarter.shape
    big = jnp.float32(9.99e8)

    # ---- XLA: per-query tail cubes (zeros when the query has none);
    # dead-masking for base tail postings happens HERE, so the kernel
    # only applies the dead mask to the resident quarters ----
    def tail_of(p_start, p_len, p_quota, p_group, p_base, p_syn,
                p_isbase):
        lane = jnp.arange(lpost, dtype=jnp.int32)
        idx = p_start[:, None] + lane[None, :]
        m = lane[None, :] < p_len[:, None]
        idxc = jnp.clip(idx, 0, N - 1)
        docc = d_docc[idxc]
        doc = (docc >> jnp.uint32(_OCC_BITS)).astype(jnp.int32)
        occ = (docc & jnp.uint32(_OCC_MASK)).astype(jnp.int32)
        pay = (d_payload[idxc]
               | (p_syn[:, None].astype(jnp.uint32) << jnp.uint32(31)))
        dead_l = d_dead[jnp.clip(doc, 0, D - 1)]
        ok = (m & (occ < p_quota[:, None])
              & ~(dead_l & p_isbase[:, None]))
        slot = p_base[:, None] + occ
        tgt = jnp.where(ok, (p_group[:, None] * P + slot) * D + doc,
                        T * P * D)
        return jnp.zeros((T * P * D,), jnp.uint32).at[tgt.ravel()].add(
            jnp.where(ok, pay, jnp.uint32(0)).ravel(), mode="drop"
        ).reshape(T, P, D)

    from .pallas_scores import fd_scores_fused_notail
    interp = jax.default_backend() == "cpu"
    if lpost == 0:
        # pure quarter-row wave: no tail cube at all
        ms, presbits = fd_scores_fused_notail(
            g_quarter.reshape(B, T * 4),
            g_qsyn.reshape(B, T * 4).astype(jnp.int32),
            d_cube, d_dead.astype(jnp.int32).reshape(1, D),
            freqw, counts.astype(jnp.float32), T=T, P=P,
            interpret=interp)
    else:
        tails = jax.vmap(tail_of)(p_start, p_len, p_quota, p_group,
                                  p_base, p_syn, p_isbase)
        ms, presbits = fd_scores_fused(
            g_quarter.reshape(B, T * 4),
            g_qsyn.reshape(B, T * 4).astype(jnp.int32),
            d_cube, tails, d_dead.astype(jnp.int32).reshape(1, D),
            freqw, counts.astype(jnp.float32), T=T, P=P,
            interpret=interp)

    # ---- XLA tail: match gates + selection (cheap [T, D]/[D] work) --
    def finish(ms, bits, freqw, required, negative, counts, table,
               qlang):
        t_ax = jnp.arange(T, dtype=jnp.int32)
        present = ((bits[None, :] >> t_ax[:, None]) & 1) > 0  # [T, D]
        req_ok = jnp.all(jnp.where(required[:, None], present, True),
                         axis=0)
        neg_ok = ~jnp.any(jnp.where(negative[:, None], present,
                                    False), axis=0)
        tok = presence_table_ok(present, table) if use_table else True
        match = (req_ok & neg_ok & tok
                 & (jnp.arange(D) < n_docs_total) & (ms < big))
        if use_filter:
            match = match & d_filter
        if use_sort:
            final = jnp.where(match, d_sort, 0.0)
        else:
            final = jnp.where(
                match, ms * final_multipliers(d_siterank, d_doclang,
                                              qlang), 0.0)
        nm = jnp.sum(match)
        w_vals, w_idx, missed = _block_topn(final, min(n_sel, D))
        ts, tl = jax.lax.top_k(w_vals, min(k2, n_sel, D))
        ti = w_idx[tl]
        return jnp.concatenate([
            jnp.atleast_1d(nm.astype(jnp.uint32)),
            jax.lax.bitcast_convert_type(jnp.atleast_1d(missed),
                                         jnp.uint32),
            ti.astype(jnp.uint32),
            jax.lax.bitcast_convert_type(ts, jnp.uint32),
        ])

    return jax.vmap(finish)(ms, presbits, freqw, required, negative,
                            counts, table, qlang)
