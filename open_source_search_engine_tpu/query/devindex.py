"""Device-resident index — the shard's termlists live in HBM.

This is the SURVEY §7 architecture stated plainly: "posting lists as
padded int32/int64 HBM arrays … the device query plane". The host-packed
path (packer.py) ships each query's termlists to the device — correct,
but on tunneled TPU backends the per-query transfer dwarfs the compute.
Here the whole shard's posting store uploads ONCE; a query ships only
its term-run offsets (a few dozen int32s) and gets the packed top-k
back: one RPC up, one down. Queries also batch (vmap over the query
axis) — the throughput mode the reference's per-query callback
architecture fundamentally cannot express.

Layout (built from the Rdb, reference Msg2/RdbList read path collapsed):

* postings sorted by (termid, docid, wordpos) — posdb key order — as two
  resident columns: ``docidx`` int32 [N] (posting → doc-table index) and
  ``payload`` uint32 [N] (wordpos|hg|density|spam bits, packer layout);
* a host-side term directory termid → [start, end) run (``RdbMap``'s
  role, one binary search per query sublist);
* a doc table: docids uint64 [D] (host) + siterank/langid int32 [D]
  (device) — Clusterdb's query-time role.

Per query the device kernel gathers each sublist's run, computes
per-(sublist, doc) occurrence ranks (the mini-merge), scatters into the
[D, T, P] cube and reuses scorer.score_cube — identical semantics to the
host-packed path, bit for bit.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..index import posdb
from ..index.collection import Collection
from ..utils.log import get_logger
from . import weights
from .compiler import QueryPlan, compile_query
from .packer import (MAX_POSITIONS, T_FLOOR, _bucket, _pad1, group_flags)
from .scorer import scatter_cube, score_cube

log = get_logger("devindex")

#: row-plan bucket floors (distinct (R, L) pairs = one compile each)
R_FLOOR = 8
RUN_FLOOR = 512
#: per-sublist run cap — the reference's tiered termlist truncation
#: (SURVEY §5 long-context: IndexReadInfo bounded list reads); runs
#: longer than this score only their first MAX_RUN postings, while
#: term-frequency weights still use the full document frequency
MAX_RUN = 1 << 15


@dataclass
class ResidentPlan:
    """Host-computed gather plan for one query (all tiny arrays)."""

    start: np.ndarray    # int32 [R] posting-run starts
    length: np.ndarray   # int32 [R] run lengths (0 = empty sublist)
    group: np.ndarray    # int32 [R] row → term group
    base: np.ndarray     # int32 [R] slot base within the group's P slots
    quota: np.ndarray    # int32 [R] max positions per (row, doc)
    freq_weight: np.ndarray  # float32 [T]
    required: np.ndarray     # bool [T]
    negative: np.ndarray     # bool [T]
    scored: np.ndarray       # bool [T]
    qlang: int
    matchable: bool      # False = a required group has no postings


class DeviceIndex:
    """One collection's postings, resident on the default device."""

    def __init__(self, coll: Collection, max_positions: int = MAX_POSITIONS):
        self.coll = coll
        self.P = max_positions
        self._built_version = -1
        self.refresh()

    # --- build / refresh -------------------------------------------------

    def refresh(self) -> bool:
        """(Re)build device arrays if the underlying Rdb changed — the
        dump/merge→repack cycle of SURVEY §7 hard part (d)."""
        v = self.coll.posdb.version
        if v == self._built_version:
            return False
        batch = self.coll.posdb.get_all()
        f = posdb.unpack(batch.keys) if len(batch) else None
        if f is None:
            n = 0
            termids = np.empty(0, np.uint64)
            docids = np.empty(0, np.uint64)
            payload = np.empty(0, np.uint32)
            siterank = langid = np.empty(0, np.uint64)
        else:
            n = len(batch)
            termids = f["termid"]
            docids = f["docid"]
            payload = (
                f["wordpos"].astype(np.uint32)
                | f["hashgroup"].astype(np.uint32) << np.uint32(18)
                | f["densityrank"].astype(np.uint32) << np.uint32(22)
                | f["wordspamrank"].astype(np.uint32) << np.uint32(27)
            )
            siterank = f["siterank"]
            langid = f["langid"]

        # doc table (sorted unique docids); posting → doc index
        self.doc_docids = np.unique(docids)
        D = len(self.doc_docids)
        self.D_pad = _bucket(max(D, 1), 256)
        docidx = np.searchsorted(self.doc_docids, docids).astype(np.int32) \
            if n else np.empty(0, np.int32)
        dsr = np.zeros(self.D_pad, np.int32)
        dlang = np.zeros(self.D_pad, np.int32)
        if n:
            # first posting per doc supplies siterank/langid
            # (reference: getSiteRank(miniMergedList[0]), Posdb.cpp:6989)
            first = np.unique(docidx, return_index=True)[1]
            dsr[docidx[first]] = siterank[first].astype(np.int32)
            dlang[docidx[first]] = langid[first].astype(np.int32)

        # term directory: termid → posting run (the RdbMap role)
        self.dir_termids, dir_first = np.unique(termids, return_index=True)
        self.dir_start = np.r_[dir_first, n].astype(np.int64)

        self.n_postings = n
        self.h_docidx = docidx  # host copy: exact per-group doc freqs
        self.d_docidx = jax.device_put(docidx)
        self.d_payload = jax.device_put(payload)
        self.d_siterank = jax.device_put(dsr)
        self.d_doclang = jax.device_put(dlang)
        self._built_version = v
        log.info("device index built: %d postings, %d docs, %d terms",
                 n, D, len(self.dir_termids))
        return True

    @property
    def n_docs(self) -> int:
        return len(self.doc_docids)

    # --- planning --------------------------------------------------------

    def _run_of(self, termid: int) -> tuple[int, int]:
        i = int(np.searchsorted(self.dir_termids, np.uint64(termid)))
        if i >= len(self.dir_termids) or self.dir_termids[i] != termid:
            return 0, 0
        return int(self.dir_start[i]), int(self.dir_start[i + 1])

    def plan(self, qplan: QueryPlan) -> ResidentPlan:
        T = _bucket(max(len(qplan.groups), 1), T_FLOOR)
        rows = []
        freq = np.zeros(len(qplan.groups), np.int64)
        matchable = True
        for g_i, g in enumerate(qplan.groups):
            subs = g.sublists
            quota = max(self.P // max(len(subs), 1), 1)
            runs = []
            for s_i, sub in enumerate(subs):
                a, b = self._run_of(sub.termid)
                rows.append((a, min(b - a, MAX_RUN), g_i, s_i * quota,
                             quota))
                if b > a:
                    runs.append((a, b))
            if runs:
                # group document frequency = unique docs across the
                # group's sublists (a doc holding both the word and its
                # bigram counts once — matches the host packer's
                # np.unique over the mini-merged list)
                freq[g_i] = len(np.unique(np.concatenate(
                    [self.h_docidx[a:b] for a, b in runs])))
            elif g.required and not g.negative:
                matchable = False
        required, negative, scored = group_flags(qplan, T)
        freqw = _pad1(
            weights.term_freq_weight(freq, max(self.coll.num_docs, 1)),
            T, 0.5)
        r = np.array(rows, np.int64).reshape(-1, 5) if rows else \
            np.zeros((0, 5), np.int64)
        return ResidentPlan(
            start=r[:, 0].astype(np.int32), length=r[:, 1].astype(np.int32),
            group=r[:, 2].astype(np.int32), base=r[:, 3].astype(np.int32),
            quota=r[:, 4].astype(np.int32),
            freq_weight=freqw, required=required, negative=negative,
            scored=scored, qlang=qplan.lang, matchable=matchable)

    def _pad_plan(self, p: ResidentPlan, R: int):
        def pad(a, fill=0):
            out = np.full(R, fill, a.dtype)
            out[: len(a)] = a
            return out
        return (pad(p.start), pad(p.length), pad(p.group), pad(p.base),
                pad(p.quota, 1))

    # --- execution -------------------------------------------------------

    def search(self, q: str | QueryPlan, topk: int = 64, lang: int = 0):
        """One query → (docids, scores, n_matched)."""
        out = self.search_batch([q], topk=topk, lang=lang)
        return out[0]

    def search_batch(self, queries, topk: int = 64, lang: int = 0):
        """Batched execution: B queries in ONE device round trip (vmap
        over the query axis). Returns [(docids, scores, n_matched)] per
        query, order preserved."""
        qplans = [q if isinstance(q, QueryPlan) else compile_query(q, lang)
                  for q in queries]
        plans = [self.plan(qp) for qp in qplans]
        live = [i for i, p in enumerate(plans)
                if p.matchable and len(p.start)]
        results = [(np.empty(0, np.uint64), np.empty(0, np.float32), 0)
                   ] * len(plans)
        if not live:
            return results
        # quantize shape buckets coarsely (powers of four) — every
        # distinct (B, R, L) triple is an XLA compile; wasted lanes are
        # masked compute, recompiles are 20-40s stalls
        R = _bucket(max(len(plans[i].start) for i in live), R_FLOOR)
        L = RUN_FLOOR
        need_l = max((int(plans[i].length.max()) for i in live), default=1)
        while L < need_l:
            L <<= 2
        T = max(len(plans[i].required) for i in live)
        # pad the batch axis to a bucket too: a single query rides the
        # same compiled kernel as a small batch (padding rows are empty
        # plans — near-free lanes)
        B = _bucket(len(live), 4)
        pad_n = B - len(live)
        k = min(topk, self.D_pad)

        # per-group arrays re-pad to the BATCH-wide T bucket (plans in
        # one batch may straddle the T_FLOOR boundary)
        stack = lambda f: np.stack(
            [_pad1(f(plans[i]), T, 0) for i in live]
            + [_pad1(f(plans[live[0]]) * 0, T, 0) for _ in range(pad_n)])
        padded = ([self._pad_plan(plans[i], R) for i in live]
                  + [tuple(np.zeros_like(x)
                           for x in self._pad_plan(plans[live[0]], R))
                     ] * pad_n)
        args = (
            np.stack([p[0] for p in padded]),  # start [B, R]
            np.stack([p[1] for p in padded]),  # length
            np.stack([p[2] for p in padded]),  # group
            np.stack([p[3] for p in padded]),  # base
            np.stack([p[4] for p in padded]),  # quota
            stack(lambda p: p.freq_weight),
            stack(lambda p: p.required),
            stack(lambda p: p.negative),
            stack(lambda p: p.scored),
            np.array([plans[i].qlang for i in live]
                     + [0] * pad_n, np.int32),
        )
        dev_args = jax.device_put(list(args))
        out = np.asarray(_resident_batch(
            self.d_docidx, self.d_payload, self.d_siterank, self.d_doclang,
            *dev_args, n_docs=self.n_docs, n_positions=self.P,
            run_l=L, n_groups=T, topk=k))  # [B, 1 + 2k]

        for b, i in enumerate(live):
            row = out[b]
            n_matched = int(row[0])
            idx = row[1:1 + k].astype(np.int64)
            scores = row[1 + k:].view(np.float32)
            keep = scores > 0.0
            results[i] = (self.doc_docids[np.clip(idx[keep], 0,
                                                  max(self.n_docs - 1, 0))],
                          scores[keep], n_matched)
        return results


@partial(jax.jit,
         static_argnames=("n_docs", "n_positions", "run_l", "n_groups",
                          "topk"))
def _resident_batch(d_docidx, d_payload, d_siterank, d_doclang,
                    start, length, group, base, quota, freqw, required,
                    negative, scored, qlang,
                    n_docs: int, n_positions: int, run_l: int,
                    n_groups: int, topk: int):
    """vmapped resident kernel: gather runs → rank → cube → score."""
    D = d_siterank.shape[0]
    N = max(d_docidx.shape[0], 1)
    L = run_l

    def one(start, length, group, base, quota, freqw, required, negative,
            scored, qlang):
        lane = jnp.arange(L, dtype=jnp.int32)[None, :]
        idx = jnp.clip(start[:, None] + lane, 0, N - 1)
        valid = lane < length[:, None]                      # [R, L]
        docrow = jnp.where(valid, d_docidx[idx], D)         # sorted per row
        payrow = d_payload[idx]
        # occurrence rank within each (row, doc): rows are docid-sorted,
        # so the first index of each docid run is a running max over
        # change markers — an O(L) associative scan (searchsorted here
        # would be O(L·logL) of gathers, pathological on TPU)
        change = jnp.concatenate(
            [jnp.ones((docrow.shape[0], 1), bool),
             docrow[:, 1:] != docrow[:, :-1]], axis=1)
        first = jax.lax.associative_scan(
            jnp.maximum, jnp.where(change, lane, 0), axis=1)
        rank = lane - first
        slot = base[:, None] + rank
        valid = valid & (rank < quota[:, None])
        cube, pvalid = scatter_cube(docrow, payrow, slot, valid, D,
                                    n_positions, row_group=group,
                                    n_groups=n_groups)
        n_matched, ts, ti = score_cube(
            cube, pvalid, freqw, required, negative, scored,
            d_siterank, d_doclang, qlang, jnp.int32(n_docs), topk=topk)
        return jnp.concatenate([
            jnp.atleast_1d(n_matched.astype(jnp.uint32)),
            ti.astype(jnp.uint32),
            jax.lax.bitcast_convert_type(ts, jnp.uint32),
        ])

    return jax.vmap(one)(start, length, group, base, quota, freqw,
                         required, negative, scored, qlang)
