"""Device-resident index — two-phase pruned search, the shard's postings
and per-(term, doc) impact bounds live in HBM.

This is the SURVEY §7 architecture plus the reference's own pruning idea
compiled into one XLA program. The reference never scores every docid:
``intersectLists10_r`` computes a cheap ``maxPossibleScore`` per docid and
skips docids that cannot beat the TopTree floor (``Posdb.cpp:6052``; the
"pre-advance" pruning around ``docIdLoop:`` 6137). On a TPU the same idea
becomes two dense phases:

* **Phase 1 — candidates.** Per term group, accumulate a per-doc score
  *upper bound* over the whole doc space ``[T, D]``: precomputed
  per-(term, doc) **impact columns** (the hashgroup-deduped sum of
  position scores — an admissible bound on the group's single-term
  score, and exact for docs with ≤ MAX_TOP distinct hashgroups) are
  added — via plain vectorized adds for high-df terms kept as dense
  ``[V, D]`` rows, and one fused gather+scatter for sparse/delta terms.
  Base and delta accumulate separately so the dead-doc vector masks
  only base contributions (re-adds serve from the delta; tombstones
  that no longer match the base still kill the doc). Boolean
  intersection (every required group present, no negative present —
  ``Msg39``'s early-outs) plus the min-over-groups/pairs bound yields
  an admissible per-doc upper bound; ``approx_max_k`` picks the top-κ
  candidates. The exact match count and the exact max bound among
  *non*-selected docs come out of the same pass, so pruning is
  verifiable.
* **Phase 2 — exact.** For the κ candidates only, gather the real
  postings (run starts come from precomputed ``runstart|count`` columns
  — no per-query binary search, no big scatter) into the dense
  ``[T, P, κ]`` position cube and score with the exact docIdLoop
  semantics (scorer.min_scores — identical math to the host-packed
  path, so parity holds by construction).
* **Escalation.** If the max bound among non-candidates exceeds the
  k-th exact score (beyond a 1e-4 tie tolerance), rerun with κ×4
  (rare: bounds are tight). This makes the pruning *lossless* — the
  TPU analog of TopTree's floor check, and of the reference's recall
  re-loop (``Msg40.cpp:2117``).

Why this shape: on v5e, scalar gather runs ~60 Melem/s and scatter ~10
Melem/s, while dense row ops and 128-lane block gathers run 10-100×
faster. So the per-query work that scales with the corpus (phase 1) uses
only dense ops + one bounded scatter, and the slow scalar gathers are
confined to phase 2's κ·T·P lanes. The former design (docid-tile scan
with per-tile gather+rank+scatter) paid the scatter price on every
posting of every tile and recompiled per posting-length bucket; this one
has no per-query shape that depends on posting-list length.

Admissibility of the bounds (what makes pruning exact):

* group single-term score = Σ of the top-MAX_TOP hashgroup-deduped
  position scores ≤ the stored impact (Σ over ALL mapped-hashgroup
  maxima + every inlink-text occurrence; synonym sublists score ×0.90²
  at query time — bounded by 1);
* pair score ≤ BASE·maxposw_i·maxposw_j·fw_i·fw_j (min distance term
  ≥ 1 after the qdist adjustment) and BASE·maxposw² ≤ impact, so
  √(impact_i·fw_i²·impact_j·fw_j²) bounds every pair term;
* siterank/language multipliers are exact (dense per-doc columns);
* the final ×(1+1e-5) guards float reassociation (the escalation check
  allows 1e-4 so exact ties don't escalate forever).

Incremental updates (SURVEY §7 hard part (d)): the base columns build
once per Rdb run-set move (dump/merge); a memtable change rewrites only
the delta tail of the preallocated device columns via donated
dynamic-update-slice — O(memtable) transfer, no O(corpus) copies, no
double residency. Document frequencies stay exact under deletes via the
tombstone-pair subtraction (the Msg36/37 termfreq role).

Capacity: run starts pack into 26 bits (count in the low 5 of an
int32), capping a shard at 2^26 ≈ 67M stored postings (~500k web
pages) — beyond that the corpus must shard (``parallel/``), same as
the reference's per-host index splits.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..index import posdb
from ..index.collection import Collection
from ..index.rdblite import merge_batches
from ..utils.log import get_logger
from . import weights
from .compiler import SUB_SYNONYM, QueryPlan, compile_query
from .packer import (MAX_POSITIONS, T_FLOOR, _bucket, _pad1, group_flags,
                     pack_payload)
from .scorer import final_multipliers, min_scores

log = get_logger("devindex")

#: shape-bucket floors (distinct shape tuples = one XLA compile each)
RD_FLOOR = 4      # dense rows
RS_FLOOR = 4      # sparse rows
LSP_FLOOR = 512   # sparse gather lanes
B_FLOOR = 4
KAPPA_FLOOR = 256  # phase-2 candidate count
DOC_UPD_FLOOR = 64

#: doc-capacity quantum (D_cap bucket unit)
DOC_QUANTUM = 2048

#: HBM budget for dense [V, D_cap] impact+runstart rows (8 bytes/doc/term)
DENSE_BUDGET_BYTES = 128 << 20

#: posting/doc column padding quantum
COL_QUANTUM = 1 << 15

_RS_SHIFT = 5          # runstart<<5 | count  (count ≤ MAX_POSITIONS=16)
_CNT_MASK = 31
_MAX_POSTINGS = 1 << (31 - _RS_SHIFT)  # int32 rs|cnt pack limit (2^26)

#: escalation tie tolerance (× the 1e-5 admissibility inflation)
_TIE_TOL = 1.0001


def _posscore_np(f: dict[str, np.ndarray]) -> np.ndarray:
    """Per-posting single-term score (BASE · posw², the initWeights
    tables — Posdb.cpp:1105-1252), vectorized numpy for build time."""
    hg = f["hashgroup"]
    hgw = weights.HASH_GROUP_WEIGHTS[hg]
    denw = weights.DENSITY_WEIGHTS[f["densityrank"]]
    spamw = np.where(hg == posdb.HASHGROUP_INLINKTEXT,
                     weights.LINKER_WEIGHTS[f["wordspamrank"]],
                     weights.WORD_SPAM_WEIGHTS[f["wordspamrank"]])
    posw = hgw * denw * spamw
    return weights.BASE_SCORE * posw * posw


def _impacts_np(f: dict[str, np.ndarray], termids: np.ndarray,
                docidx: np.ndarray, runstart: np.ndarray) -> np.ndarray:
    """Admissible per-(term, doc) single-score bound, tight for the
    common case: Σ over mapped hashgroups of the max position score,
    plus every inlink-text occurrence individually — exactly the
    candidate set getSingleTermScore tops-and-sums (Posdb.cpp:3087),
    summed without the top-MAX_TOP cut (≥ the exact score, equal when a
    doc has ≤ MAX_TOP contributing groups)."""
    n = len(termids)
    if n == 0:
        return np.empty(0, np.float32)
    ps = _posscore_np(f)
    mhg = weights.MAPPED_HASHGROUP[f["hashgroup"]].astype(np.int8)
    is_inlink = f["hashgroup"] == posdb.HASHGROUP_INLINKTEXT
    # order within each (term, doc) run by mapped hashgroup: runs are
    # tiny (≤ P) so a stable argsort of the group key within runs via
    # one global lexsort is fine
    o = np.lexsort((mhg, docidx, termids))
    ps_o, mh_o, il_o = ps[o], mhg[o], is_inlink[o]
    t_o, d_o = termids[o], docidx[o]
    gch = np.ones(n, bool)
    gch[1:] = ((t_o[1:] != t_o[:-1]) | (d_o[1:] != d_o[:-1])
               | (mh_o[1:] != mh_o[:-1]))
    gstart = np.nonzero(gch)[0]
    gmax = np.maximum.reduceat(ps_o, gstart)
    gsum = np.add.reduceat(ps_o, gstart)
    gval = np.where(il_o[gstart], gsum, gmax)
    pch = np.ones(len(gstart), bool)
    pch[1:] = ((t_o[gstart][1:] != t_o[gstart][:-1])
               | (d_o[gstart][1:] != d_o[gstart][:-1]))
    imp = np.add.reduceat(gval, np.nonzero(pch)[0])
    assert len(imp) == len(runstart)
    # tiny floor keeps zero-weight hashgroups present-but-worthless
    return np.maximum(imp, 1e-30).astype(np.float32)


def _occ_ranks(termids: np.ndarray, docs: np.ndarray) -> np.ndarray:
    """Occurrence rank within each (termid, doc) run of the sorted
    columns — vectorized running-max scan (the mini-merge slot count)."""
    n = len(termids)
    if n == 0:
        return np.empty(0, np.int64)
    newpair = np.ones(n, bool)
    newpair[1:] = (termids[1:] != termids[:-1]) | (docs[1:] != docs[:-1])
    idx = np.arange(n)
    first = np.maximum.accumulate(np.where(newpair, idx, 0))
    return idx - first


def _term_dfs(termids: np.ndarray, newpair: np.ndarray):
    """(dir_termids, dir_start, df): per-term run bounds + distinct-doc
    counts over sorted columns (the Msg36 termfreq precompute)."""
    n = len(termids)
    if n == 0:
        return (np.empty(0, np.uint64), np.zeros(1, np.int64),
                np.empty(0, np.int64))
    tchange = np.ones(n, bool)
    tchange[1:] = termids[1:] != termids[:-1]
    starts = np.nonzero(tchange)[0]
    df = np.add.reduceat(newpair.astype(np.int64), starts)
    return termids[starts].copy(), np.r_[starts, n].astype(np.int64), df


def _pad_col(a: np.ndarray, size: int) -> np.ndarray:
    out = np.zeros(size, a.dtype)
    out[: len(a)] = a
    return out


@partial(jax.jit, donate_argnums=0)
def _write_tail(buf, tail, offset):
    """Donated in-place rewrite of the delta tail of a device column."""
    return jax.lax.dynamic_update_slice(buf, tail, (offset,))


class _DeltaOverflow(Exception):
    def __init__(self, needed_docs: int = 0, needed_cols: int = 0):
        self.needed_docs = needed_docs
        self.needed_cols = needed_cols


@dataclass
class ResidentPlan:
    """Host-computed execution plan for one query (all tiny arrays)."""

    # dense rows: term's doc run lives as a dense [D_cap] impact row
    d_slot: np.ndarray       # int32 [Rd] dense matrix row (-1 = pad)
    d_group: np.ndarray      # int32 [Rd]
    d_base: np.ndarray       # int32 [Rd] slot base within the group's P
    d_quota: np.ndarray      # int32 [Rd]
    d_syn: np.ndarray        # uint32 [Rd]
    # sparse rows: contiguous run of the doc/impact/runstart columns
    s_start: np.ndarray      # int32 [Rs] absolute offset into doc cols
    s_len: np.ndarray        # int32 [Rs]
    s_group: np.ndarray      # int32 [Rs]
    s_base: np.ndarray       # int32 [Rs]
    s_quota: np.ndarray      # int32 [Rs]
    s_syn: np.ndarray        # uint32 [Rs]
    s_isbase: np.ndarray     # bool [Rs] (base postings dead-mask)
    # per-group query state
    freq_weight: np.ndarray  # float32 [T]
    required: np.ndarray     # bool [T]
    negative: np.ndarray     # bool [T]
    scored: np.ndarray       # bool [T]
    qlang: int
    matchable: bool
    driver_df: int = 0       # min required-group df (escalation bound)


class DeviceIndex:
    """One collection's postings + impact bounds, resident in HBM."""

    def __init__(self, coll: Collection, max_positions: int = MAX_POSITIONS):
        self.coll = coll
        self.P = max_positions
        self._built_version = -1
        self._base_fp = None
        self.full_rebuilds = 0    # O(corpus) base rebuilds (run-set moved)
        self.delta_rebuilds = 0   # O(memtable) delta-only refreshes
        self.escalations = 0      # phase-2 κ escalations (pruning misses)
        self.refresh()

    # --- build / refresh -------------------------------------------------

    def refresh(self) -> bool:
        """(Re)build device arrays if the underlying Rdb changed: delta
        only while the run set is stable, full base rebuild when a
        dump/merge moved it (SURVEY §7 hard part (d))."""
        rdb = self.coll.posdb
        if rdb.version == self._built_version:
            return False
        fp = tuple((r.path.name, len(r)) for r in rdb.runs)
        if fp != self._base_fp:
            self._build_base(fp)
        # the delta can outgrow the doc-capacity headroom AND the
        # preallocated column tails independently — regrow and retry
        min_docs = min_delta = 0
        for _ in range(3):
            try:
                self._build_delta()
                break
            except _DeltaOverflow as e:
                min_docs = max(min_docs, e.needed_docs)
                min_delta = max(min_delta, e.needed_cols)
                self._build_base(fp, min_docs=min_docs,
                                 min_delta=min_delta)
        else:
            self._build_delta()
        self._built_version = rdb.version
        return True

    def _build_base(self, fp, min_docs: int = 0, min_delta: int = 0
                    ) -> None:
        """Base columns from the Rdb's immutable runs (merged, tombstones
        annihilated — the Msg5 read collapsed to one columnar merge),
        plus preallocated delta tails."""
        runs = self.coll.posdb.runs
        batch = merge_batches([r.batch() for r in runs]) if runs else None
        P = self.P
        if batch is not None and len(batch):
            f = posdb.unpack(batch.keys)
            termids, docids = f["termid"], f["docid"]
            occ = _occ_ranks(termids, docids)
            self.dir_termids, _, self.base_df = _term_dfs(termids, occ == 0)
            # store-cap: scoring consumes ≤ P positions per (term, doc),
            # so postings past occurrence P are dead weight in HBM
            keep = occ < P
            f = {k: v[keep] for k, v in f.items()}
            termids, docids = f["termid"], f["docid"]
            if len(termids) >= _MAX_POSTINGS:
                raise ValueError(
                    f"shard exceeds {_MAX_POSTINGS} stored postings "
                    "(runstart pack limit) — split the collection "
                    "across more shards")
            payload = pack_payload(f)
            self.base_docids = np.unique(docids)
            docidx = np.searchsorted(self.base_docids, docids).astype(
                np.int32)
            n = len(docidx)
            # --- doc-level runs: one entry per (term, doc) pair ---
            newpair = np.ones(n, bool)
            newpair[1:] = (termids[1:] != termids[:-1]) | \
                (docidx[1:] != docidx[:-1])
            runstart = np.nonzero(newpair)[0].astype(np.int64)
            doc_col = docidx[newpair]
            count = np.diff(np.r_[runstart, n])
            imp_col = _impacts_np(f, termids, docidx, runstart)
            rsp_col = ((runstart << _RS_SHIFT)
                       | np.minimum(count, P)).astype(np.int32)
            tchange = np.ones(n, bool)
            tchange[1:] = termids[1:] != termids[:-1]
            tstarts = np.nonzero(tchange)[0]
            self.dir_dstart = np.r_[
                np.searchsorted(runstart, tstarts), len(runstart)
            ].astype(np.int64)
            siterank = f["siterank"].astype(np.int32)
            langid = f["langid"].astype(np.int32)
        else:
            self.dir_termids = np.empty(0, np.uint64)
            self.base_df = np.empty(0, np.int64)
            self.dir_dstart = np.zeros(1, np.int64)
            self.base_docids = np.empty(0, np.uint64)
            docidx = np.empty(0, np.int32)
            payload = np.empty(0, np.uint32)
            doc_col = np.empty(0, np.int32)
            imp_col = np.empty(0, np.float32)
            rsp_col = np.empty(0, np.int32)
            siterank = langid = np.empty(0, np.int32)
            n = 0

        Db = len(self.base_docids)
        headroom = max(1024, Db // 4)
        self.D_cap = _bucket(max(Db + headroom, min_docs, 1), DOC_QUANTUM)

        # --- doc meta table (first posting per doc supplies siterank/
        # langid — reference getSiteRank(miniMergedList[0]), 6989) ---
        sr = np.zeros(self.D_cap, np.int32)
        dl = np.zeros(self.D_cap, np.int32)
        if n:
            first = np.unique(docidx, return_index=True)[1]
            sr[docidx[first]] = siterank[first]
            dl[docidx[first]] = langid[first]

        # --- dense rows: highest-df terms get a dense [D_cap] impact +
        # runstart row (phase 1 adds them with zero gather/scatter) ---
        dfs = np.diff(self.dir_dstart)
        tau = max(1024, self.D_cap // 16)
        slots_budget = max(DENSE_BUDGET_BYTES // (8 * self.D_cap), 1)
        eligible = np.nonzero(dfs > tau)[0]
        eligible = eligible[np.argsort(-dfs[eligible], kind="stable")]
        dense_terms = eligible[:slots_budget]
        V = _bucket(max(len(dense_terms), 1), 8)
        dense_imp = np.zeros((V, self.D_cap), np.float32)
        dense_rsp = np.zeros((V, self.D_cap), np.int32)
        self.dense_slot_of: dict[int, int] = {}
        for slot, ti in enumerate(dense_terms):
            a, b = int(self.dir_dstart[ti]), int(self.dir_dstart[ti + 1])
            dense_imp[slot, doc_col[a:b]] = imp_col[a:b]
            dense_rsp[slot, doc_col[a:b]] = rsp_col[a:b]
            self.dense_slot_of[int(self.dir_termids[ti])] = slot

        # --- device columns: base + preallocated delta tail ---
        self.h_doc_col = doc_col
        self.Nb = _bucket(max(n, 1), COL_QUANTUM)
        self.Mb = _bucket(max(len(doc_col), 1), COL_QUANTUM)
        # delta tail capacity scales with the base (grown on overflow)
        self.N2 = max(_bucket(max(self.Nb // 4, min_delta, 1),
                              COL_QUANTUM), COL_QUANTUM)
        self.M2 = self.N2
        self.d_payload = jax.device_put(
            _pad_col(payload, self.Nb + self.N2))
        self.d_doc = jax.device_put(_pad_col(doc_col, self.Mb + self.M2))
        self.d_imp = jax.device_put(_pad_col(imp_col, self.Mb + self.M2))
        self.d_rsp = jax.device_put(_pad_col(rsp_col, self.Mb + self.M2))
        self.d_dense_imp = jax.device_put(dense_imp)
        self.d_dense_rsp = jax.device_put(dense_rsp.reshape(-1))
        self.d_siterank = jax.device_put(sr)
        self.d_doclang = jax.device_put(dl)
        self.d_dead = jax.device_put(np.zeros(self.D_cap, bool))
        self._base_fp = fp
        self.full_rebuilds += 1
        log.info("device base built: %d postings, %d docs, %d terms "
                 "(%d dense rows, cap %d)", n, Db, len(self.dir_termids),
                 len(dense_terms), self.D_cap)

    def _build_delta(self) -> None:
        """Delta columns from the memtable — O(memtable) per refresh.

        Tombstones (delbit 0) and re-adds mark their base doc dead
        (phase 1 masks base-side bounds, phase 2 masks base run counts)
        and subtract from per-term dfs; positives become delta postings
        + delta doc columns written into the preallocated tails."""
        Db = len(self.base_docids)
        mem = self.coll.posdb.mem.batch()
        self.tomb_df = np.zeros(len(self.dir_termids), np.int64)
        dead = np.zeros(self.D_cap, bool)
        if not len(mem):
            self._set_empty_delta()
            self.d_dead = jax.device_put(dead)
            self.delta_rebuilds += 1
            return
        f = posdb.unpack(mem.keys)
        pos = f["delbit"].astype(bool)

        def base_idx_of(docids_arr):
            di = np.searchsorted(self.base_docids, docids_arr)
            ok = di < Db
            ok[ok] = self.base_docids[di[ok]] == docids_arr[ok]
            return di, ok

        # superseded base docs: explicitly tombstoned OR re-added in the
        # delta (an identical-content re-index annihilates its pairs in
        # the memtable, so the delta positives are the only witness)
        t_di, t_ok = base_idx_of(f["docid"][~pos])
        p_di, p_ok = base_idx_of(f["docid"][pos])
        dead_idx = np.unique(np.concatenate([t_di[t_ok], p_di[p_ok]]))
        dead[dead_idx] = True

        # distinct (term, superseded-doc) pairs → df subtraction (only
        # where the pair actually exists in the base)
        pair_t = np.concatenate([f["termid"][~pos][t_ok],
                                 f["termid"][pos][p_ok]])
        pair_d = np.concatenate([t_di[t_ok], p_di[p_ok]]).astype(np.int64)
        if len(pair_t):
            order = np.lexsort((pair_d, pair_t))
            pair_t, pair_d = pair_t[order], pair_d[order]
            firstp = np.ones(len(pair_t), bool)
            firstp[1:] = (pair_t[1:] != pair_t[:-1]) | \
                (pair_d[1:] != pair_d[:-1])
            pair_t, pair_d = pair_t[firstp], pair_d[firstp]
            ti = np.searchsorted(self.dir_termids, pair_t)
            ok = ti < len(self.dir_termids)
            ok[ok] = self.dir_termids[ti[ok]] == pair_t[ok]
            for term_i in np.unique(ti[ok]):
                m = ok & (ti == term_i)
                a, b = int(self.dir_dstart[term_i]), \
                    int(self.dir_dstart[term_i + 1])
                run = self.h_doc_col[a:b]
                ppos = np.searchsorted(run, pair_d[m])
                inb = ppos < len(run)
                inb[inb] = run[ppos[inb]] == pair_d[m][inb]
                self.tomb_df[term_i] = int(inb.sum())

        # --- positives → delta columns ---
        if pos.any():
            fp_ = {k: v[pos] for k, v in f.items()}
            p_doc = fp_["docid"]
            new_docids = np.unique(p_doc[~p_ok])
            if Db + len(new_docids) > self.D_cap:
                raise _DeltaOverflow(needed_docs=Db + len(new_docids))
            docidx = np.where(
                p_ok, p_di,
                Db + np.searchsorted(new_docids, p_doc)).astype(np.int32)
            # delta sort key is (termid, DOC-INDEX, wordpos): new docs'
            # indexes aren't docid-monotonic
            order = np.lexsort((fp_["wordpos"], docidx, fp_["termid"]))
            fp_ = {k: v[order] for k, v in fp_.items()}
            docidx = docidx[order]
            occ = _occ_ranks(fp_["termid"], docidx)
            self.dir2_termids, _, self.delta_df = _term_dfs(
                fp_["termid"], occ == 0)
            keep = occ < self.P
            fp_ = {k: v[keep] for k, v in fp_.items()}
            docidx = docidx[keep]
            n2 = len(docidx)
            newpair = np.ones(n2, bool)
            newpair[1:] = (fp_["termid"][1:] != fp_["termid"][:-1]) | \
                (docidx[1:] != docidx[:-1])
            runstart2 = np.nonzero(newpair)[0].astype(np.int64)
            doc2_col = docidx[newpair]
            if n2 > self.N2 or len(doc2_col) > self.M2:
                raise _DeltaOverflow(needed_cols=max(n2, len(doc2_col)))
            if self.Nb + n2 >= _MAX_POSTINGS:
                raise ValueError(
                    f"shard exceeds {_MAX_POSTINGS} stored postings — "
                    "split the collection across more shards")
            count2 = np.diff(np.r_[runstart2, n2])
            imp2 = _impacts_np(fp_, fp_["termid"], docidx, runstart2)
            # runstarts reference the combined column: delta postings
            # live at [Nb, Nb + n2)
            rsp2 = (((self.Nb + runstart2) << _RS_SHIFT)
                    | np.minimum(count2, self.P)).astype(np.int32)
            tchange = np.ones(n2, bool)
            tchange[1:] = fp_["termid"][1:] != fp_["termid"][:-1]
            tstarts = np.nonzero(tchange)[0]
            self.dir2_dstart = np.r_[
                np.searchsorted(runstart2, tstarts), len(runstart2)
            ].astype(np.int64)
            self.all_docids = np.concatenate([self.base_docids, new_docids])
            payload2 = pack_payload(fp_)
            # doc-table updates from first delta posting per doc
            first = np.unique(docidx, return_index=True)[1]
            upd_idx = docidx[first].astype(np.int32)
            upd_sr = fp_["siterank"][first].astype(np.int32)
            upd_dl = fp_["langid"][first].astype(np.int32)
            # donated in-place rewrites of the delta tails
            self.d_payload = _write_tail(
                self.d_payload,
                jax.device_put(_pad_col(payload2, self.N2)),
                np.int32(self.Nb))
            self.d_doc = _write_tail(
                self.d_doc, jax.device_put(_pad_col(doc2_col, self.M2)),
                np.int32(self.Mb))
            self.d_imp = _write_tail(
                self.d_imp, jax.device_put(_pad_col(imp2, self.M2)),
                np.int32(self.Mb))
            self.d_rsp = _write_tail(
                self.d_rsp, jax.device_put(_pad_col(rsp2, self.M2)),
                np.int32(self.Mb))
        else:
            self._set_empty_delta()
            upd_idx = np.empty(0, np.int32)
            upd_sr = upd_dl = upd_idx

        def bpad(a, fill):
            out = np.full(_bucket(max(len(a), 1), DOC_UPD_FLOOR), fill,
                          a.dtype)
            out[: len(a)] = a
            return out
        if len(upd_idx):
            self.d_siterank, self.d_doclang = _apply_doc_meta(
                self.d_siterank, self.d_doclang,
                bpad(upd_idx, upd_idx[0]), bpad(upd_sr, upd_sr[0]),
                bpad(upd_dl, upd_dl[0]))
        self.d_dead = jax.device_put(dead)
        self.delta_rebuilds += 1

    def _set_empty_delta(self) -> None:
        self.dir2_termids = np.empty(0, np.uint64)
        self.dir2_dstart = np.zeros(1, np.int64)
        self.delta_df = np.empty(0, np.int64)
        self.all_docids = self.base_docids
        # delta tails keep whatever stale content they hold — nothing
        # references it (dir2 is empty), so no device write is needed

    @property
    def n_docs(self) -> int:
        return len(self.all_docids)

    # --- planning --------------------------------------------------------

    def _druns_of(self, termid: int):
        """[(is_base, dstart, dlen, dense_slot)] doc-column runs for a
        termid (dense_slot ≥ 0 when the base run is a dense row)."""
        out = []
        i = int(np.searchsorted(self.dir_termids, np.uint64(termid)))
        if i < len(self.dir_termids) and self.dir_termids[i] == termid:
            a, b = int(self.dir_dstart[i]), int(self.dir_dstart[i + 1])
            if b > a:
                out.append((True, a, b - a,
                            self.dense_slot_of.get(termid, -1)))
        j = int(np.searchsorted(self.dir2_termids, np.uint64(termid)))
        if j < len(self.dir2_termids) and self.dir2_termids[j] == termid:
            a, b = int(self.dir2_dstart[j]), int(self.dir2_dstart[j + 1])
            if b > a:
                # delta doc columns live at [Mb, Mb + n2)
                out.append((False, self.Mb + a, b - a, -1))
        return out

    def _df_of(self, termid: int) -> int:
        """Exact document frequency under pending deletes/re-adds:
        base df − superseded-doc pairs + delta df."""
        df = 0
        i = int(np.searchsorted(self.dir_termids, np.uint64(termid)))
        if i < len(self.dir_termids) and self.dir_termids[i] == termid:
            df += int(self.base_df[i]) - int(self.tomb_df[i])
        j = int(np.searchsorted(self.dir2_termids, np.uint64(termid)))
        if j < len(self.dir2_termids) and self.dir2_termids[j] == termid:
            df += int(self.delta_df[j])
        return max(df, 0)

    def plan(self, qplan: QueryPlan) -> ResidentPlan:
        T = _bucket(max(len(qplan.groups), 1), T_FLOOR)
        drows, srows = [], []
        dfs = np.zeros(max(len(qplan.groups), 1), np.int64)
        matchable = True
        any_required = False
        driver_df = 1 << 60
        for g_i, g in enumerate(qplan.groups):
            subs = g.sublists
            quota = max(self.P // max(len(subs), 1), 1)
            any_postings = False
            gdf = 0
            for s_i, sub in enumerate(subs):
                syn = 1 if sub.kind == SUB_SYNONYM else 0
                for is_base, a, ln, slot in self._druns_of(sub.termid):
                    if slot >= 0:
                        drows.append((slot, g_i, s_i * quota, quota, syn))
                    else:
                        srows.append((a, ln, g_i, s_i * quota, quota, syn,
                                      is_base))
                    any_postings = True
                gdf = max(gdf, self._df_of(sub.termid))
            dfs[g_i] = gdf
            if g.required and not g.negative:
                any_required = True
                driver_df = min(driver_df, gdf)
                if not any_postings:
                    matchable = False
        if not any_required:
            matchable = False

        required, negative, scored = group_flags(qplan, T)
        freqw = _pad1(
            weights.term_freq_weight(dfs[: len(qplan.groups)],
                                     max(self.coll.num_docs, 1)), T, 0.5)
        da = np.array(drows, np.int64).reshape(-1, 5)
        sa = np.array(srows, np.int64).reshape(-1, 7)
        return ResidentPlan(
            d_slot=da[:, 0].astype(np.int32),
            d_group=da[:, 1].astype(np.int32),
            d_base=da[:, 2].astype(np.int32),
            d_quota=da[:, 3].astype(np.int32),
            d_syn=da[:, 4].astype(np.uint32),
            s_start=sa[:, 0].astype(np.int32),
            s_len=sa[:, 1].astype(np.int32),
            s_group=sa[:, 2].astype(np.int32),
            s_base=sa[:, 3].astype(np.int32),
            s_quota=sa[:, 4].astype(np.int32),
            s_syn=sa[:, 5].astype(np.uint32),
            s_isbase=sa[:, 6].astype(bool),
            freq_weight=freqw, required=required, negative=negative,
            scored=scored, qlang=qplan.lang, matchable=matchable,
            driver_df=0 if driver_df == 1 << 60 else int(driver_df))

    # --- execution -------------------------------------------------------

    def search(self, q: str | QueryPlan, topk: int = 64, lang: int = 0):
        """One query → (docids, scores, n_matched)."""
        return self.search_batch([q], topk=topk, lang=lang)[0]

    def search_batch(self, queries, topk: int = 64, lang: int = 0):
        """Batched execution: B queries in ONE device round trip (vmap
        over the query axis), two-phase pruned scoring each."""
        qplans = [q if isinstance(q, QueryPlan) else compile_query(q, lang)
                  for q in queries]
        plans = [self.plan(qp) for qp in qplans]
        live = [i for i, p in enumerate(plans) if p.matchable]
        results = [(np.empty(0, np.uint64), np.empty(0, np.float32), 0)
                   ] * len(plans)
        if not live:
            return results
        kappa = min(_bucket(max(KAPPA_FLOOR, 2 * topk), KAPPA_FLOOR),
                    self.D_cap)
        k_req = min(topk, self.D_cap)
        pending = live
        while pending:
            k2 = min(k_req, kappa)
            out = self._run_batch([plans[i] for i in pending], kappa, k2)
            escalate = []
            for row, i in zip(out, pending):
                nm = int(row[0])
                ub_missed = float(np.asarray(row[1:2]).view(np.float32)[0])
                idx = row[2:2 + k2].astype(np.int64)
                scores = np.asarray(row[2 + k2:]).view(np.float32)
                keep = scores > 0.0
                kth = float(scores[k_req - 1]) if (k2 >= k_req
                                                   and keep[k_req - 1]
                                                   ) else 0.0
                if ub_missed > kth * _TIE_TOL and kappa < self.D_cap:
                    escalate.append(i)
                    continue
                results[i] = (
                    self.all_docids[np.clip(idx[keep], 0,
                                            max(self.n_docs - 1, 0))],
                    scores[keep], nm)
            if not escalate:
                break
            self.escalations += len(escalate)
            pending = escalate
            kappa = min(kappa * 4, self.D_cap)
        return results

    def _run_batch(self, plans: list[ResidentPlan], kappa: int, k2: int):
        Rd = _bucket(max([len(p.d_slot) for p in plans] + [1]), RD_FLOOR)
        Rs = _bucket(max([len(p.s_start) for p in plans] + [1]), RS_FLOOR)
        Lsp = _bucket(max([int(p.s_len.max()) if len(p.s_len) else 1
                           for p in plans] + [1]), LSP_FLOOR)
        T = max(len(p.required) for p in plans)
        B = _bucket(len(plans), B_FLOOR)

        def pad_plan(p: ResidentPlan | None):
            if p is None:
                return (np.full(Rd, -1, np.int32), np.zeros(Rd, np.int32),
                        np.zeros(Rd, np.int32), np.ones(Rd, np.int32),
                        np.zeros(Rd, np.uint32),
                        np.zeros(Rs, np.int32), np.zeros(Rs, np.int32),
                        np.zeros(Rs, np.int32), np.zeros(Rs, np.int32),
                        np.ones(Rs, np.int32), np.zeros(Rs, np.uint32),
                        np.ones(Rs, bool),
                        np.full(T, 0.5, np.float32), np.zeros(T, bool),
                        np.zeros(T, bool), np.zeros(T, bool), np.int32(0))
            pr = lambda a, n, fill: _pad1(a, n, fill)
            return (pr(p.d_slot, Rd, -1), pr(p.d_group, Rd, 0),
                    pr(p.d_base, Rd, 0), pr(p.d_quota, Rd, 1),
                    pr(p.d_syn, Rd, 0),
                    pr(p.s_start, Rs, 0), pr(p.s_len, Rs, 0),
                    pr(p.s_group, Rs, 0), pr(p.s_base, Rs, 0),
                    pr(p.s_quota, Rs, 1), pr(p.s_syn, Rs, 0),
                    pr(p.s_isbase, Rs, True),
                    _pad1(p.freq_weight, T, 0.5),
                    _pad1(p.required, T, False),
                    _pad1(p.negative, T, False),
                    _pad1(p.scored, T, False), np.int32(p.qlang))

        padded = [pad_plan(p) for p in plans] \
            + [pad_plan(None)] * (B - len(plans))
        args = [np.stack([p[j] for p in padded]) for j in range(17)]
        dev_args = jax.device_put(args)
        out = np.asarray(_two_phase(
            self.d_payload, self.d_doc, self.d_imp, self.d_rsp,
            self.d_dense_imp, self.d_dense_rsp,
            self.d_siterank, self.d_doclang, self.d_dead,
            np.int32(self.n_docs), *dev_args,
            n_positions=self.P, lsp=Lsp, kappa=kappa, k2=k2))
        return out


@jax.jit
def _apply_doc_meta(sr, dl, idx, vsr, vdl):
    return sr.at[idx].set(vsr), dl.at[idx].set(vdl)


@partial(jax.jit, static_argnames=("n_positions", "lsp", "kappa", "k2"))
def _two_phase(d_payload, d_doc, d_imp, d_rsp, d_dense_imp, d_dense_rsp,
               d_siterank, d_doclang, d_dead, n_docs_total,
               d_slot, d_group, d_base, d_quota, d_syn,
               s_start, s_len, s_group, s_base, s_quota, s_syn, s_isbase,
               freqw, required, negative, scored, qlang,
               n_positions: int, lsp: int, kappa: int, k2: int):
    """The fused two-phase kernel, vmapped over the query axis.

    Phase 1 = dense upper bounds + intersection + approx top-κ (the
    maxPossibleScore prune, Posdb.cpp:6052); phase 2 = exact cube scoring
    of the κ candidates (docIdLoop semantics via scorer.min_scores).
    Output per query: [n_matched, bitcast(max missed bound), κ-top-k2
    doc indices, bitcast(exact scores)]."""
    D = d_dead.shape[0]
    V = d_dense_imp.shape[0]
    M = d_doc.shape[0]
    N = d_payload.shape[0]
    P = n_positions
    big = jnp.float32(9.99e8)

    def one(d_slot, d_group, d_base, d_quota, d_syn,
            s_start, s_len, s_group, s_base, s_quota, s_syn, s_isbase,
            freqw, required, negative, scored, qlang):
        T = required.shape[0]
        Rd = d_slot.shape[0]
        Rs = s_start.shape[0]
        t_ax = jnp.arange(T)
        live = ~d_dead                                        # [D]

        # ---- phase 1: group upper bounds over the full doc axis,
        # base and delta separated so dead docs mask only the base ----
        ubb = jnp.zeros((T, D), jnp.float32)
        dimp = d_dense_imp[jnp.clip(d_slot, 0, V - 1)]        # [Rd, D]
        dgate = (d_slot >= 0)
        for r in range(Rd):
            contrib = jnp.where(dgate[r], dimp[r], 0.0)
            ubb = ubb + jnp.where((d_group[r] == t_ax)[:, None],
                                  contrib[None, :], 0.0)
        # sparse rows: one fused contiguous gather + bounded scatter-add
        # into [2 (base/delta), T, D] — lane count is the real run size
        lane = jnp.arange(lsp, dtype=jnp.int32)
        sidx = s_start[:, None] + lane[None, :]               # [Rs, Lsp]
        smask = lane[None, :] < s_len[:, None]
        sidxc = jnp.clip(sidx, 0, M - 1)
        sdoc = d_doc[sidxc]
        simp = d_imp[sidxc]
        srsp = d_rsp[sidxc]
        side = jnp.where(s_isbase, 0, T * D)[:, None]         # [Rs, 1]
        tgt = jnp.where(smask, side + s_group[:, None] * D + sdoc,
                        2 * T * D)
        ub2 = jnp.zeros((2 * T * D,), jnp.float32).at[tgt.ravel()].add(
            jnp.where(smask, simp, 0.0).ravel(), mode="drop"
        ).reshape(2, T, D)
        ubb = ubb + ub2[0]
        ubd = ub2[1]
        ub = ubb * live[None, :] + ubd                        # [T, D]
        rstgt = jnp.where(
            smask, jnp.arange(Rs, dtype=jnp.int32)[:, None] * D + sdoc,
            Rs * D)
        rsacc = jnp.zeros((Rs * D,), jnp.int32).at[rstgt.ravel()].set(
            jnp.where(smask, srsp, 0).ravel(), mode="drop")

        # intersection + admissible min bound
        present = ub > 0.0                                    # [T, D]
        sc = scored & required
        ubw = ub * (freqw * freqw)[:, None]
        req_ok = jnp.all(jnp.where(required[:, None], present, True),
                         axis=0)
        neg_ok = ~jnp.any(jnp.where(negative[:, None], present, False),
                          axis=0)
        alive = req_ok & neg_ok & (jnp.arange(D) < n_docs_total)
        m1 = present & sc[:, None]
        min_single_ub = jnp.min(jnp.where(m1, ubw, big), axis=0)
        min_pair_ub = jnp.full((D,), big)
        any_pair = jnp.zeros((D,), bool)
        for i in range(T):
            for j in range(i + 1, T):
                ok = present[i] & present[j] & sc[i] & sc[j]
                pu = jnp.sqrt(ubw[i] * ubw[j])
                min_pair_ub = jnp.where(ok, jnp.minimum(min_pair_ub, pu),
                                        min_pair_ub)
                any_pair = any_pair | ok
        ubmin = jnp.minimum(jnp.where(any_pair, min_pair_ub, big),
                            min_single_ub)
        ubmin = jnp.where(jnp.any(sc), ubmin, 1.0)
        mult = final_multipliers(d_siterank, d_doclang, qlang)
        ubfinal = jnp.where(alive, ubmin * mult * 1.00001, 0.0)
        nm = jnp.sum(alive)

        cval, cand = jax.lax.approx_max_k(ubfinal, kappa)
        selmask = jnp.zeros((D,), bool).at[cand].set(True)
        ub_missed = jnp.max(jnp.where(selmask, 0.0, ubfinal))

        # ---- phase 2: exact scoring of the κ candidates ----
        dead_c = d_dead[cand]                                 # [κ]
        p_ax = jnp.arange(P, dtype=jnp.int32)[:, None]        # [P, 1]
        cube = jnp.zeros((T, P, kappa), jnp.uint32)
        pv = jnp.zeros((T, P, kappa), bool)

        def add_row(cube, pv, rsp_c, group, base, quota, syn, is_base):
            rs = (rsp_c >> _RS_SHIFT).astype(jnp.int32)       # [κ]
            cnt = rsp_c & _CNT_MASK
            cnt = jnp.where(is_base & dead_c, 0, cnt)
            q = p_ax - base                                   # [P, κ]
            sel = (q >= 0) & (q < jnp.minimum(cnt, quota)[None, :])
            src = rs[None, :] + q
            val = (d_payload[jnp.clip(src, 0, N - 1)]
                   | (syn.astype(jnp.uint32) << jnp.uint32(31)))
            gmask = (group == t_ax)[:, None, None]            # [T, 1, 1]
            cube = cube + jnp.where(sel, val, jnp.uint32(0))[None] \
                * gmask.astype(jnp.uint32)
            pv = pv | (sel[None] & gmask)
            return cube, pv

        dense_rsp_c = d_dense_rsp[
            jnp.clip(d_slot, 0, V - 1)[:, None] * D + cand[None, :]]
        for r in range(Rd):
            rsp_c = jnp.where(dgate[r], dense_rsp_c[r], 0)
            cube, pv = add_row(cube, pv, rsp_c, d_group[r], d_base[r],
                               d_quota[r], d_syn[r], True)
        for r in range(Rs):
            rsp_c = rsacc[r * D + cand]
            cube, pv = add_row(cube, pv, rsp_c, s_group[r], s_base[r],
                               s_quota[r], s_syn[r], s_isbase[r])

        min_sc, present2 = min_scores(cube, pv, freqw, sc)
        req_ok2 = jnp.all(jnp.where(required[:, None], present2, True),
                          axis=0)
        neg_ok2 = ~jnp.any(jnp.where(negative[:, None], present2, False),
                           axis=0)
        match2 = req_ok2 & neg_ok2 & (cval > 0.0) & (min_sc < big)
        final = jnp.where(
            match2,
            min_sc * final_multipliers(d_siterank[cand], d_doclang[cand],
                                       qlang),
            0.0)
        ts, tl = jax.lax.top_k(final, k2)
        ti = cand[tl]
        return jnp.concatenate([
            jnp.atleast_1d(nm.astype(jnp.uint32)),
            jax.lax.bitcast_convert_type(jnp.atleast_1d(ub_missed),
                                         jnp.uint32),
            ti.astype(jnp.uint32),
            jax.lax.bitcast_convert_type(ts, jnp.uint32),
        ])

    return jax.vmap(one)(d_slot, d_group, d_base, d_quota, d_syn,
                         s_start, s_len, s_group, s_base, s_quota, s_syn,
                         s_isbase, freqw, required, negative, scored,
                         qlang)
