"""Search engine front end — query in, ranked results out.

Reference: ``Msg40::getResults`` (``Msg40.cpp:171``) orchestrates
Msg3a (docid ranking fan-out) then Msg20s (per-result title/summary); here
the single-shard path is compile → pack → device score → titledb lookup.
The mesh fan-out (Msg3a/shard_map) layers on top in ``parallel/``.

Docid-range multipass (``Msg39.cpp:277-305`` "docid range splitting"): when
the candidate set exceeds ``max_docs_per_pass``, the engine runs the kernel
over candidate slices and merges top-k across passes — bounding device
memory exactly like the reference bounds RAM.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from ..build import docproc
from ..index.collection import Collection
from ..utils import deadline as deadline_mod
from ..utils import trace
from ..utils.log import get_logger
from ..utils.stats import g_stats
from .compiler import QueryPlan, compile_query
from .packer import pack_pass, prepare_query
from .scorer import run_query

log = get_logger("query")

#: guards first-time creation of a collection's device-index lock
import threading as _threading  # noqa: E402

_DI_CREATE_LOCK = _threading.Lock()

#: compiled-plan cache: compile_query is pure in (raw, lang) and
#: QueryPlan is immutable after compile, so plans never invalidate —
#: no generation, just TTL/LRU bounds (Query.cpp reparsed every time;
#: we don't have to)
from ..cache import g_cacheplane as _g_cacheplane  # noqa: E402

_compiled_cache = _g_cacheplane.register(
    "query.compiled", ttl_s=600.0, max_entries=4096,
    desc="compiled QueryPlans, pure in (raw, lang)")


def _compile_cached(q: str, lang: int) -> QueryPlan:
    ck = (q, lang)
    hit, plan = _compiled_cache.lookup(ck)
    if not hit:
        plan = compile_query(q, lang=lang)
        _compiled_cache.put(ck, plan)
    return plan


#: site-clustering cap: at most this many results per site
#: (reference Msg51/Msg40 "site clustering (max 2/site)", Msg51.h:96)
MAX_PER_SITE = 2


@dataclass
class Result:
    docid: int
    score: float
    url: str = ""
    title: str = ""
    snippet: str = ""
    site: str = ""


@dataclass
class SearchResults:
    query: str
    total_matches: int
    results: list[Result] = field(default_factory=list)
    clustered: int = 0  # results hidden by site clustering (Msg51)
    suggestion: str | None = None  # "did you mean" (Speller)
    #: gbfacet: results — field → [(value, count)], counted over a
    #: SAMPLE of the best-matching docs (the reference likewise
    #: accumulates facets over the result sample, Msg40/PageResults)
    facets: dict = field(default_factory=dict)
    #: True when a whole shard (every twin) was down and its documents
    #: are missing from this answer — the reference surfaces this on
    #: PageHosts; silent partial results are a correctness trap
    degraded: bool = False


def build_results(get_doc, docids, scores, plan: QueryPlan, *,
                  topk: int, with_snippets: bool = True,
                  site_cluster: bool = True,
                  dedup_content: bool = True,
                  site_of=None,
                  page: tuple[int, int] | None = None
                  ) -> tuple[list[Result], int]:
    """Msg40's post-merge stage: walk merged candidates best-first, fetch
    titlerecs from the owning store (Msg20/Msg22), apply content-hash
    dedup (Msg40's checksum dedup of identical pages) and site clustering
    (Msg51: at most MAX_PER_SITE per site, rest hidden), build summaries.

    ``get_doc`` is docid → titlerec dict (routes to the owning shard in
    the mesh path). Returns (results, number hidden by cluster/dedup).

    ``page`` = (offset, n): the rendered page window. When given (and
    clusterdb columns back the clustering, ``site_of``), only ranks in
    the PQR_SCAN rerank prefix or inside the page window fetch a
    titlerec — rows in the gap between them exist solely to hold a rank
    for deep paging, so they carry docid+score only. Content-hash dedup
    needs the titlerec and is therefore skipped for gap rows (site
    clustering is not: the sitehash column works without a fetch)."""
    from . import summary as summary_mod

    words = plan.match_words()
    per_site: dict = {}
    seen_hashes: set[int] = set()
    results: list[Result] = []
    clustered = 0
    for docid, score in zip(docids, scores):
        if len(results) >= topk:
            break
        if score <= 0.0:
            continue
        if site_cluster and site_of is not None:
            # clusterdb-driven clustering (Msg51.h:96): the sitehash
            # column decides BEFORE any titledb fetch, so hidden
            # results never decompress a titlerec
            sh = site_of(int(docid))
            if sh and per_site.get(sh, 0) >= MAX_PER_SITE:
                clustered += 1
                continue
        rank = len(results)
        if (page is not None and site_of is not None
                and rank >= PQR_SCAN
                and not (page[0] <= rank < page[0] + page[1])):
            # gap row: never reranked (rank ≥ PQR_SCAN), never rendered
            # (outside the page) — skip the titledb fetch entirely
            if site_cluster and site_of is not None:
                sh = site_of(int(docid))
                if sh:
                    per_site[sh] = per_site.get(sh, 0) + 1
            results.append(Result(docid=int(docid), score=float(score)))
            continue
        rec = get_doc(int(docid))
        r = Result(docid=int(docid), score=float(score))
        if rec:
            r.url = rec.get("url", "")
            # Title.cpp fallback chain: title → h1 → anchor → url
            r.title = summary_mod.choose_title(rec)
            r.site = rec.get("site", "")
            ch = rec.get("content_hash")
            if dedup_content and ch is not None:
                if ch in seen_hashes:
                    clustered += 1
                    continue
                seen_hashes.add(ch)
            if site_cluster and site_of is not None:
                sh = site_of(int(docid))
                if sh:
                    per_site[sh] = per_site.get(sh, 0) + 1
            elif site_cluster and r.site:
                seen = per_site.get(r.site, 0)
                if seen >= MAX_PER_SITE:
                    clustered += 1
                    continue
                per_site[r.site] = seen + 1
        if rec and with_snippets:
            r.snippet = summary_mod.make_summary(
                rec.get("text", ""), words,
                description=rec.get("meta_description", ""))
        results.append(r)
    return results, clustered


#: PostQueryRerank window: only the top PQR_SCAN merged results are
#: reranked (reference m_pqr_docsToScan) — the window is FIXED by rank,
#: not by the requested page, so pagination stays consistent: every
#: page request reranks the same top-48 and slices its own rows out
PQR_SCAN = 48


def apply_pqr(results, conf=None, qlang: int = 0, langid_of=None) -> None:
    """PostQueryRerank over one result window (PostQueryRerank.cpp
    role; factors from the collection conf, defaults when no conf is
    in reach — the cluster client)."""
    from .rerank import post_query_rerank
    if conf is not None and not conf.pqr_enabled:
        return
    kw = {}
    if conf is not None:
        kw = dict(lang_demote=conf.pqr_lang_demote,
                  site_demote=conf.pqr_site_demote,
                  depth_demote=conf.pqr_depth_demote)
    window = results[:PQR_SCAN]
    post_query_rerank(window, qlang, langid_of=langid_of, **kw)
    results[:PQR_SCAN] = window


def _coll_langid_of(coll: Collection):
    """Docid → langid via a clusterdb point read (host path analog of
    DeviceIndex.langid_of — same records, so flat/resident parity
    holds under the PQR language rule)."""
    from ..index import clusterdb as cdb
    from ..index import titledb

    def f(docid: int) -> int:
        lst = coll.clusterdb.get_list(titledb.start_key(docid),
                                      titledb.end_key(docid))
        if not len(lst):
            return 0
        return int(cdb.unpack_key(lst.keys)["langid"][-1])
    return f


def finish_page(results, *, offset: int, topk: int, conf=None,
                qlang: int = 0, langid_of=None, get_doc=None,
                words=None, with_snippets: bool = True):
    """The shared post-merge tail every search path runs: PQR over the
    fixed top window → slice the requested page → build summaries for
    the page rows only (deep pages must not pay snippets for the rows
    they skip)."""
    from . import summary as summary_mod
    with trace.timed_span("query.rerank", window=min(len(results),
                                                     PQR_SCAN)):
        apply_pqr(results, conf, qlang, langid_of=langid_of)
    page = results[offset:offset + topk]
    if with_snippets and get_doc is not None:
        with trace.timed_span("query.summary", rows=len(page)):
            for r in page:
                if not r.snippet:
                    rec = get_doc(int(r.docid))
                    if rec:
                        r.snippet = summary_mod.make_summary(
                            rec.get("text", ""), words or [],
                            description=rec.get("meta_description", ""))
    return page


def search(coll: Collection, q: str | QueryPlan, *, topk: int = 10,
           lang: int = 0, max_docs_per_pass: int = 1 << 16,
           with_snippets: bool = True,
           site_cluster: bool = True, offset: int = 0) -> SearchResults:
    """Execute a query against one collection (single shard).
    ``offset`` = deep-paging start row (reference ``s=``)."""
    plan = q if isinstance(q, QueryPlan) else _compile_cached(q, lang)
    raw = plan.raw

    g_stats.count("query")
    with trace.timed_span("query.prepare", q=raw):
        prep = prepare_query(coll, plan)

    # over-fetch + escalate: when site clustering leaves the page short,
    # re-score with a larger k (the Msg40 recall loop, Msg40.cpp:2117,
    # as over-fetch per SURVEY §7 hard part (c)); the sharded path has
    # the same loop around its merge
    want = max(topk + offset, PQR_SCAN)
    k = max(want, 64)
    while True:
        # docid-range multipass: fetch+intersect once, then score
        # candidate slices, merging top-k across passes
        all_docids: list[np.ndarray] = []
        all_scores: list[np.ndarray] = []
        total = 0
        # advance by pq.n_docs, not the requested stride: under memory
        # pressure pack_pass shrinks a pass (budget_shrink) and a fixed
        # stride would silently skip the unshrunk remainder
        doc_off = 0
        npass = 0
        while doc_off < len(prep.cand):
            with trace.timed_span("query.pack", npass=npass,
                                  doc_off=doc_off):
                pq = pack_pass(prep, doc_offset=doc_off,
                               max_docs=max_docs_per_pass,
                               budget_shrink=True)
            if pq is None:
                break
            with trace.timed_span("query.score", npass=npass,
                                  n_docs=pq.n_docs):
                docids, scores, n_matched = run_query(pq, topk=k)
            npass += 1
            total += n_matched
            all_docids.append(docids)
            all_scores.append(scores)
            doc_off += pq.n_docs

        if not all_docids:
            return SearchResults(query=raw, total_matches=0,
                                 suggestion=_suggest(coll, plan))
        docids = np.concatenate(all_docids)
        scores = np.concatenate(all_scores)
        order = np.argsort(-scores, kind="stable")

        with trace.timed_span("query.results"):
            results, clustered = build_results(
                lambda d: docproc.get_document(coll, docid=d),
                docids[order], scores[order], plan, topk=want,
                with_snippets=False, site_cluster=site_cluster)
        if (len(results) >= want or clustered == 0
                or k >= len(prep.cand)):
            break
        k *= 4
    page = finish_page(
        results, offset=offset, topk=topk, conf=coll.conf,
        qlang=plan.lang, langid_of=_coll_langid_of(coll),
        get_doc=lambda d: docproc.get_document(coll, docid=d),
        words=plan.match_words(),
        with_snippets=with_snippets)
    return SearchResults(
        query=raw, total_matches=total, results=page,
        clustered=clustered,
        suggestion=_suggest(coll, plan) if total == 0 else None,
        facets=compute_facets(
            plan, docids[order],
            lambda d: docproc.get_document(coll, docid=d)))


#: facet sample size: facet counts come from the stored fields of the
#: top FACET_SAMPLE matched docs (reference Msg40 samples its results)
FACET_SAMPLE = 256


def compute_facets(plan: QueryPlan, docids, get_doc) -> dict:
    """field → [(value, count)] over a sample of matched docs."""
    if not plan.facets:
        return {}
    from collections import Counter
    counters = {f: Counter() for f in plan.facets}
    for d in list(docids)[:FACET_SAMPLE]:
        rec = get_doc(int(d))
        flds = (rec or {}).get("fields") or {}
        for f in plan.facets:
            if f in flds:
                counters[f][flds[f]] += 1
    return {f: c.most_common(16) for f, c in counters.items()}


def _suggest(coll: Collection, plan: QueryPlan) -> str | None:
    """Zero-result fallback: Speller "did you mean" over the query's
    scored words (reference Msg40 spell-check integration)."""
    words = [g.display for g in plan.scored_groups
             if " " not in g.display and ":" not in g.display]
    return coll.speller.suggest_query(words) if words else None


def get_device_index(coll: Collection):
    """The collection's HBM-resident index, built lazily and refreshed
    when the Rdb version moves (cached on the Collection object).

    A run-set move (dump/merge) triggers an O(corpus) base rebuild —
    the reference's RdbDump/RdbMerge never block the loop
    (``RdbDump.h:21``), and neither does this: the rebuild runs in a
    BACKGROUND thread against a fresh DeviceIndex while the old one
    keeps serving its pre-dump view (frozen — bounded staleness for
    the rebuild's duration), then swaps in atomically. Memtable-only
    changes refresh synchronously (O(memtable)). When the HBM can't
    hold two resident sets (big shards), the swap degrades to a
    blocking rebuild rather than an OOM."""
    import threading

    from .devindex import DeviceIndex
    lock = getattr(coll, "_di_lock", None)
    if lock is None:
        with _DI_CREATE_LOCK:
            lock = getattr(coll, "_di_lock", None)
            if lock is None:
                lock = coll._di_lock = threading.Lock()
    di = getattr(coll, "_device_index", None)
    if di is None:
        with lock:
            di = getattr(coll, "_device_index", None)
            if di is None:
                di = DeviceIndex(coll)
                # satellite of the resident-loop PR: pay the cold-plan
                # spike (BENCH_r04: devindex.plan max 1168ms) at build
                # time, not on the first user query
                di.warm_plans()
                coll._device_index = di
        return di

    rdb = coll.posdb
    if rdb.version == di._built_version:
        return di
    fp = tuple((r.path.name, len(r), r.meta.get("keys_crc"))
               for r in rdb.runs)
    if fp == di._base_fp:
        with lock:  # concurrent /search threads must not both mutate
            di.refresh()  # delta-only: O(memtable), synchronous
        return di
    # run set moved → full rebuild. Double-residency check: old + new
    # device arrays must both fit while the swap is in flight.
    if 2 * di.resident_bytes() + (2 << 30) > (14 << 30):
        with lock:
            di.refresh()  # blocking rebuild — two sets would OOM
        return di
    with lock:
        if getattr(coll, "_di_rebuilding", False):
            return di  # a rebuild is in flight: serve the old view

        def _rebuild():
            try:
                fresh = DeviceIndex(coll)
                fresh.warm_plans()  # before the swap: first query on
                # the fresh index must not re-pay the cold-plan spike
                with lock:
                    coll._device_index = fresh
            except Exception:  # noqa: BLE001 — keep serving the old
                log.exception("background device rebuild failed")
            finally:
                with lock:
                    coll._di_rebuilding = False

        coll._di_rebuilding = True
        from ..utils import threads as _threads
        _threads.spawn("devindex-rebuild", _rebuild)
    return di


def get_resident_loop(coll: Collection, deadline=None):
    """The collection's ResidentLoop — owned by the tenant plane's
    :class:`~..serve.tenancy.ResidencyManager` (LRU hot set, parked
    cold tenants, single-flight cold start). The lazy import mirrors
    get_mesh_resident's: the serve layer imports this module at load,
    so the reverse edge resolves at call time only."""
    from ..serve.tenancy import g_residency
    return g_residency.loop_for(coll, deadline=deadline)


def build_device_index(coll, device=None):
    """Sanctioned DeviceIndex factory for the planes that legitimately
    own per-shard bases (the mesh plane's MeshServeIndex). Everything
    else goes through the residency manager — the osselint
    ``residency-bypass`` rule fences direct construction into
    serve/tenancy.py and this module."""
    from .devindex import DeviceIndex
    return DeviceIndex(coll, device=device)


def spawn_resident_loop(di_fn, gen_fn, **kw):
    """Sanctioned ResidentLoop factory (see build_device_index)."""
    from .resident import ResidentLoop
    return ResidentLoop(di_fn, gen_fn=gen_fn, **kw)


def get_mesh_resident(sc):
    """The ShardedCollection's :class:`~..parallel.sharded.MeshResident`
    (mesh-resident serving: per-shard HBM bases + the in-jit Msg3a
    merge under a ResidentLoop), created lazily like the flat device
    index. Imported lazily — parallel.sharded imports this module at
    load."""
    from ..parallel.sharded import MeshResident
    mr = getattr(sc, "_mesh_resident", None)
    if mr is not None:
        return mr
    with _DI_CREATE_LOCK:
        mr = getattr(sc, "_mesh_resident", None)
        if mr is None:
            mr = MeshResident(sc)
            sc._mesh_resident = mr
    return mr


def search_device_batch(coll: Collection, queries, *, topk: int = 10,
                        lang: int = 0, with_snippets: bool = True,
                        site_cluster: bool = True, offset: int = 0,
                        resident: bool = False, results_lock=None
                        ) -> list[SearchResults]:
    """Batched resident-index search: B queries in one device round trip
    (the TPU throughput mode — vmap over queries, SURVEY §7.8).

    ``resident=True`` routes the device work through the collection's
    ResidentLoop: the dispatch is an enqueue onto a loop that is
    already double-buffering waves, not a fresh issue→block round trip.
    ``results_lock``, when given, is held ONLY around the host
    post-processing (titledb reads mutate rdblite state) — never
    around the device wait, so a server can overlap batch N's wave
    with batch N-1's snippets."""
    import contextlib
    plans = [q if isinstance(q, QueryPlan) else _compile_cached(q, lang)
             for q in queries]
    g_stats.count("query", len(plans))
    ktot = max((topk + offset) * 2, 64)
    if deadline_mod.check_abandon("device.dispatch"):
        # the coordinator timed out while this batch queued — abandon
        # before the device wave, not after it
        raise deadline_mod.DeadlineExceeded(
            "deadline exceeded before device dispatch")
    if resident:
        loop = get_resident_loop(coll, deadline=deadline_mod.current())
        with trace.timed_span("query.device_batch", queries=len(plans),
                              topk=ktot, resident=True):
            ticket = loop.submit(plans, topk=ktot, lang=lang,
                                 deadline=deadline_mod.current())
            raw = ticket.wait()
        di = ticket.di  # the index the wave actually ran against
    else:
        di = get_device_index(coll)
        with trace.timed_span("query.device_batch", queries=len(plans),
                              topk=ktot):
            raw = di.search_batch(plans, topk=ktot, lang=lang)

    # one titlerec memo for the whole batch: build_results, PQR,
    # page snippets and facets all re-read the same top docids
    doc_memo: dict[int, dict | None] = {}

    def get_doc(d: int):
        d = int(d)
        if d in doc_memo:
            return doc_memo[d]
        if len(doc_memo) >= 4096:
            doc_memo.clear()
        rec = docproc.get_document(coll, docid=d)
        doc_memo[d] = rec
        return rec

    out = []
    t_res = time.perf_counter()
    lock_ctx = results_lock if results_lock is not None \
        else contextlib.nullcontext()
    with lock_ctx:
        for plan, (docids, scores, n_matched) in zip(plans, raw):
            results, clustered = build_results(
                get_doc,
                docids, scores, plan, topk=max(topk + offset, PQR_SCAN),
                with_snippets=False, site_cluster=site_cluster,
                site_of=di.sitehash_of, page=(offset, topk))
            page = finish_page(
                results, offset=offset, topk=topk, conf=coll.conf,
                qlang=plan.lang, langid_of=di.langid_of,
                get_doc=get_doc,
                words=plan.match_words(),
                with_snippets=with_snippets)
            out.append(SearchResults(
                query=plan.raw, total_matches=n_matched, results=page,
                clustered=clustered,
                suggestion=_suggest(coll, plan)
                if n_matched == 0 else None,
                facets=compute_facets(plan, docids, get_doc)))
    trace.record("query.results_batch", t_res, queries=len(out))
    return out


def search_device(coll: Collection, q, **kw) -> SearchResults:
    """Single-query resident-index search (one RPC up, one down)."""
    return search_device_batch(coll, [q], **kw)[0]
