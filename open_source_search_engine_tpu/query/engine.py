"""Search engine front end — query in, ranked results out.

Reference: ``Msg40::getResults`` (``Msg40.cpp:171``) orchestrates
Msg3a (docid ranking fan-out) then Msg20s (per-result title/summary); here
the single-shard path is compile → pack → device score → titledb lookup.
The mesh fan-out (Msg3a/shard_map) layers on top in ``parallel/``.

Docid-range multipass (``Msg39.cpp:277-305`` "docid range splitting"): when
the candidate set exceeds ``max_docs_per_pass``, the engine runs the kernel
over candidate slices and merges top-k across passes — bounding device
memory exactly like the reference bounds RAM.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..build import docproc
from ..index.collection import Collection
from ..utils.log import get_logger
from .compiler import QueryPlan, compile_query
from .packer import pack_pass, prepare_query
from .scorer import run_query

log = get_logger("query")


@dataclass
class Result:
    docid: int
    score: float
    url: str = ""
    title: str = ""
    snippet: str = ""
    site: str = ""


@dataclass
class SearchResults:
    query: str
    total_matches: int
    results: list[Result] = field(default_factory=list)


def _make_snippet(text: str, words: list[str], radius: int = 90) -> str:
    """Cheap query-biased excerpt: window around the densest match region
    (the full ``Summary::getBestWindow`` port lands with the Msg20 layer)."""
    if not text:
        return ""
    low = text.lower()
    hits = [low.find(w) for w in words]
    hits = [h for h in hits if h >= 0]
    if not hits:
        return text[: 2 * radius].strip()
    center = min(hits)
    lo = max(0, center - radius)
    hi = min(len(text), center + radius)
    out = text[lo:hi].strip()
    if lo > 0:
        out = "…" + out
    if hi < len(text):
        out += "…"
    return out


def search(coll: Collection, q: str | QueryPlan, *, topk: int = 10,
           lang: int = 0, max_docs_per_pass: int = 1 << 16,
           with_snippets: bool = True) -> SearchResults:
    """Execute a query against one collection (single shard)."""
    plan = q if isinstance(q, QueryPlan) else compile_query(q, lang=lang)
    raw = plan.raw

    # docid-range multipass: fetch+intersect once, then score candidate
    # slices, merging top-k across passes
    all_docids: list[np.ndarray] = []
    all_scores: list[np.ndarray] = []
    total = 0
    prep = prepare_query(coll, plan)
    if prep is not None:
        for offset in range(0, len(prep.cand), max_docs_per_pass):
            pq = pack_pass(prep, doc_offset=offset,
                           max_docs=max_docs_per_pass)
            if pq is None:
                break
            docids, scores, n_matched = run_query(pq, topk=max(topk, 64))
            total += n_matched
            all_docids.append(docids)
            all_scores.append(scores)

    if not all_docids:
        return SearchResults(query=raw, total_matches=0)
    docids = np.concatenate(all_docids)
    scores = np.concatenate(all_scores)
    order = np.argsort(-scores, kind="stable")[:topk]

    words = [g.display for g in plan.scored_groups]
    results = []
    for i in order:
        if scores[i] <= 0:
            break
        rec = docproc.get_document(coll, docid=int(docids[i]))
        r = Result(docid=int(docids[i]), score=float(scores[i]))
        if rec:
            r.url = rec.get("url", "")
            r.title = rec.get("title", "")
            r.site = rec.get("site", "")
            if with_snippets:
                r.snippet = _make_snippet(rec.get("text", ""), words)
        results.append(r)
    return SearchResults(query=raw, total_matches=total, results=results)
