"""Query packer — termlists → padded, statically-shaped device arrays.

Reference seam: ``Msg2::getLists`` (fetch one RdbList per query term,
``Msg2.cpp:30``) feeding ``PosdbTable::setQueryTermInfo``/``intersectLists10_r``
(``Posdb.cpp:4354,5437``). The reference walks compressed byte lists per
docid; a TPU wants dense masked tensors with static shapes. So the packer:

1. fetches each group's sublists from the posdb Rdb and concatenates them
   (the "mini-merge" of ``Posdb.cpp:6000ish`` done columnarly up front);
2. picks the **driver**: the required group with the fewest unique docids
   (reference: "pick smallest list as the driver", setQueryTermInfo) — only
   its docids can match an AND query, so the candidate doc axis ``D`` is
   bounded by the driver list length, not the corpus;
3. maps every other list onto the candidate axis with ``searchsorted``
   (host-side vectorized numpy — the CPU analog of the reference's key
   compares, done once per query);
4. emits padded arrays bucketed to powers of two so jit recompiles are
   bounded: per (group, candidate-doc) up to ``P`` positions with a packed
   uint32 payload (wordpos | hashgroup | density | spam | syn).

Docid-range multipass (``Msg39.cpp:277-305``) maps to tiling the candidate
axis: callers cap ``max_docs`` and the engine runs multiple passes, merging
top-k across passes — same memory-bounding trick, TPU-shaped.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..index import posdb
from ..index.collection import Collection
from . import weights
from .compiler import SUB_SYNONYM, QueryPlan

#: max positions kept per (group, doc) — covers MAX_TOP=10 single-term
#: slots plus slack for pair scoring (reference mini-merge buffers cap at
#: MAX_SUBLISTS*256 bytes; we cap per-doc, which is what scoring consumes)
MAX_POSITIONS = 16

# packed payload bit layout (uint32)
_POS_SHIFT = 0          # wordpos: 18 bits
_HG_SHIFT = 18          # hashgroup: 4 bits
_DEN_SHIFT = 22         # densityrank: 5 bits
_SPAM_SHIFT = 27        # wordspamrank: 4 bits
_SYN_SHIFT = 31         # synonym-ish (scored with SYNONYM_WEIGHT): 1 bit


def pack_payload(f: dict[str, np.ndarray], syn: int = 0) -> np.ndarray:
    """Unpacked posdb fields → the scorer's uint32 payload. The single
    definition of the payload bit layout (scorer._decode is its inverse);
    the resident index packs with syn=0 and ORs the query-time synonym
    flag in-kernel."""
    return (
        f["wordpos"].astype(np.uint32) << np.uint32(_POS_SHIFT)
        | f["hashgroup"].astype(np.uint32) << np.uint32(_HG_SHIFT)
        | f["densityrank"].astype(np.uint32) << np.uint32(_DEN_SHIFT)
        | f["wordspamrank"].astype(np.uint32) << np.uint32(_SPAM_SHIFT)
        | np.uint32(syn) << np.uint32(_SYN_SHIFT)
    )


def _bucket(n: int, floor: int = 8) -> int:
    """Next power of two ≥ n (≥ floor) — static-shape jit buckets."""
    b = floor
    while b < n:
        b <<= 1
    return b


#: shape-bucket floors. Each distinct (T, L, D) triple is one XLA
#: compilation (~20-40 s cold on TPU), so floors are set high enough that
#: everyday queries collapse into a handful of buckets; the wasted lanes
#: are masked compute the VPU shrugs off.
T_FLOOR = 4      # term groups
L_FLOOR = 512    # postings per group
D_FLOOR = 256    # candidate docs


@dataclass
class PackedQuery:
    """Device-ready query: everything the scorer jit consumes.

    Shapes: T groups × L postings × (D docs × P positions after scatter).
    All arrays numpy; the scorer moves them to device.
    """

    # per (group, posting): candidate-doc index, packed payload, position
    # slot within (group,doc), validity
    doc_idx: np.ndarray       # int32 [T, L]
    payload: np.ndarray       # uint32 [T, L]
    slot: np.ndarray          # int32 [T, L]
    valid: np.ndarray         # bool [T, L]
    # per group
    freq_weight: np.ndarray   # float32 [T]
    required: np.ndarray      # bool [T]
    negative: np.ndarray      # bool [T]
    scored: np.ndarray        # bool [T]
    # per candidate doc
    cand_docids: np.ndarray   # uint64 [D] (actual candidates; D_pad ≥ D)
    siterank: np.ndarray      # int32 [D_pad]
    doclang: np.ndarray       # int32 [D_pad]
    n_docs: int               # real candidate count (≤ D_pad)
    qlang: int

    @property
    def shape_key(self) -> tuple[int, int, int]:
        return (self.doc_idx.shape[0], self.doc_idx.shape[1],
                len(self.siterank))


@dataclass
class GroupList:
    """One group's fetched+merged postings (columnar)."""

    docids: np.ndarray     # uint64, sorted
    payload: np.ndarray    # uint32, parallel
    siterank: np.ndarray   # int32, parallel (per posting, from the key)
    langid: np.ndarray     # int32, parallel
    sub: np.ndarray        # int32, parallel: originating sublist index
    n_subs: int = 1        # sublist count (sets the per-sublist quota)


def fetch_group_lists(coll: Collection, plan: QueryPlan) -> list[GroupList]:
    """Msg2 equivalent: fetch every group's sublists and mini-merge."""
    out = []
    for g in plan.groups:
        cols = {"docids": [], "payload": [], "siterank": [], "langid": [],
                "sub": []}
        for s_i, sub in enumerate(g.sublists):
            batch = coll.posdb.get_list(posdb.start_key(sub.termid),
                                        posdb.end_key(sub.termid))
            if not len(batch):
                continue
            f = posdb.unpack(batch.keys)
            payload = pack_payload(
                f, syn=1 if sub.kind == SUB_SYNONYM else 0)
            cols["docids"].append(f["docid"])
            cols["payload"].append(payload)
            cols["siterank"].append(f["siterank"].astype(np.int32))
            cols["langid"].append(f["langid"].astype(np.int32))
            cols["sub"].append(np.full(len(batch), s_i, np.int32))
        if cols["docids"]:
            docids = np.concatenate(cols["docids"])
            # stable sort by docid only: within a doc, postings stay
            # sublist-major (then wordpos-ascending) — (doc, sublist)
            # runs are contiguous for the per-sublist slot quota below
            order = np.argsort(docids, kind="stable")
            out.append(GroupList(
                docids=docids[order],
                payload=np.concatenate(cols["payload"])[order],
                siterank=np.concatenate(cols["siterank"])[order],
                langid=np.concatenate(cols["langid"])[order],
                sub=np.concatenate(cols["sub"])[order],
                n_subs=max(len(g.sublists), 1)))
        else:
            out.append(GroupList(
                docids=np.empty(0, np.uint64),
                payload=np.empty(0, np.uint32),
                siterank=np.empty(0, np.int32),
                langid=np.empty(0, np.int32),
                sub=np.empty(0, np.int32),
                n_subs=max(len(g.sublists), 1)))
    return out


def _pad1(a: np.ndarray, n: int, fill) -> np.ndarray:
    """Pad a 1-D per-group array out to the T bucket."""
    if len(a) >= n:
        return a
    out = np.full(n, fill, dtype=a.dtype)
    out[: len(a)] = a
    return out


@dataclass
class PreparedQuery:
    """Fetch+intersect product, computed ONCE per query: multipass slices
    ``cand`` without re-reading the Rdb (the reference's docid-range passes
    likewise reuse the Msg2 lists already in RAM, ``Msg39.cpp:277``)."""

    plan: QueryPlan
    lists: list[GroupList]
    cand: np.ndarray          # uint64, candidate docids (sorted; may be 0)
    driver: int               # -1 when cand is empty
    freq_weight: np.ndarray   # float32 [len(plan.groups)]
    unique_counts: np.ndarray  # int64 [len(plan.groups)] docs per group


def group_flags(plan: QueryPlan, T: int):
    """(required, negative, scored) bool arrays padded to the T bucket —
    pure functions of the plan, shared by every shard/pass."""
    return (
        _pad1(np.array([g.required and not g.negative
                        for g in plan.groups]), T, False),
        _pad1(np.array([g.negative for g in plan.groups]), T, False),
        _pad1(np.array([g.scored and not g.negative
                        for g in plan.groups]), T, False),
    )


def prepare_query(coll: Collection, plan: QueryPlan) -> PreparedQuery:
    """Fetch termlists, pick the driver, intersect candidates.

    ``cand`` comes back empty when no doc can match (an empty required
    list — the reference's early-out, ``Msg39.cpp``) but the fetched
    lists are still returned: cluster-wide term-frequency stats must
    count a shard's postings even when that shard has no candidates.
    """
    lists = fetch_group_lists(coll, plan)
    req = [i for i, g in enumerate(plan.groups)
           if g.required and not g.negative]

    uniques = {i: np.unique(lists[i].docids) for i in req}
    # per-group unique-doc counts for term-frequency stats (scored ⊆
    # required, so required groups' counts are the ones that matter)
    unique_counts = np.array(
        [len(uniques[i]) if i in uniques else
         len(np.unique(lists[i].docids)) if len(lists[i].docids) else 0
         for i in range(len(lists))], dtype=np.int64)
    nd = max(coll.num_docs, 1)
    freqw = weights.term_freq_weight(unique_counts, nd)

    if not req or any(not len(uniques[i]) for i in req):
        return PreparedQuery(plan=plan, lists=lists,
                             cand=np.empty(0, np.uint64), driver=-1,
                             freq_weight=freqw,
                             unique_counts=unique_counts)

    # driver = required group with fewest unique docids
    driver = min(req, key=lambda i: len(uniques[i]))
    cand = uniques[driver]
    # intersect with every other required group's docids (cheap host-side
    # pre-intersection; the device re-checks presence per term anyway)
    for i in req:
        if i != driver and len(cand):
            cand = cand[np.isin(cand, uniques[i], assume_unique=True)]
    return PreparedQuery(plan=plan, lists=lists, cand=cand, driver=driver,
                         freq_weight=freqw, unique_counts=unique_counts)


def pack_pass(prep: PreparedQuery, doc_offset: int = 0,
              max_docs: int | None = None,
              max_positions: int = MAX_POSITIONS) -> PackedQuery | None:
    """Build the PackedQuery for one docid-range pass over the prepared
    candidates (slice [doc_offset : doc_offset+max_docs])."""
    plan, lists = prep.plan, prep.lists
    if max_docs is not None:
        cand = prep.cand[doc_offset:doc_offset + max_docs]
    else:
        cand = prep.cand[doc_offset:] if doc_offset else prep.cand
    if not len(cand):
        return None
    required, negative, scored = group_flags(
        plan, _bucket(len(plan.groups), T_FLOOR))

    T = _bucket(len(plan.groups), T_FLOOR)
    D = len(cand)
    D_pad = _bucket(D, D_FLOOR)

    per_group = []
    max_kept = 1
    for gl in lists:
        if not len(gl.docids):
            per_group.append((np.empty(0, np.int32), np.empty(0, np.uint32),
                              np.empty(0, np.int32)))
            continue
        pos_in_cand = np.searchsorted(cand, gl.docids)
        pos_in_cand_c = np.clip(pos_in_cand, 0, D - 1)
        hit = cand[pos_in_cand_c] == gl.docids
        didx = pos_in_cand_c[hit].astype(np.int32)
        payload = gl.payload[hit]
        sub = gl.sub[hit]
        # per-sublist slot quota within each doc: sublist s owns slots
        # [s·quota, (s+1)·quota) so a spammy word can never starve its
        # bigram/synonym siblings out of the position cube (the resident
        # kernel uses the identical base+rank scheme — parity by
        # construction). (doc, sublist) runs are contiguous: stable
        # docid sort keeps sublist-major order within a doc.
        if len(didx):
            quota = max(max_positions // gl.n_subs, 1)
            n = len(didx)
            boundary = np.ones(n, bool)
            boundary[1:] = (didx[1:] != didx[:-1]) | (sub[1:] != sub[:-1])
            idx = np.arange(n)
            rank = idx - np.maximum.accumulate(np.where(boundary, idx, 0))
            slot = (sub * quota + rank).astype(np.int32)
            keep = (rank < quota) & (slot < max_positions)
            didx, payload, slot = didx[keep], payload[keep], slot[keep]
            max_kept = max(max_kept, len(didx))
        else:
            slot = np.empty(0, np.int32)
        per_group.append((didx, payload, slot))

    L = _bucket(max_kept, L_FLOOR)
    doc_idx = np.full((T, L), D_pad, dtype=np.int32)  # D_pad = drop row
    payload = np.zeros((T, L), dtype=np.uint32)
    slot = np.zeros((T, L), dtype=np.int32)
    valid = np.zeros((T, L), dtype=bool)
    for t, (didx, pl, sl) in enumerate(per_group):
        n = len(didx)
        doc_idx[t, :n] = didx
        payload[t, :n] = pl
        slot[t, :n] = sl
        valid[t, :n] = True

    # per-candidate-doc siterank/langid from the driver group's first
    # posting (reference: getSiteRank(miniMergedList[0]), Posdb.cpp:6989)
    siterank = np.zeros(D_pad, dtype=np.int32)
    doclang = np.zeros(D_pad, dtype=np.int32)
    gl = lists[prep.driver]
    first = np.searchsorted(gl.docids, cand)
    siterank[:D] = gl.siterank[np.clip(first, 0, len(gl.docids) - 1)]
    doclang[:D] = gl.langid[np.clip(first, 0, len(gl.docids) - 1)]

    return PackedQuery(
        doc_idx=doc_idx, payload=payload, slot=slot, valid=valid,
        freq_weight=_pad1(prep.freq_weight, T, 0.5),
        required=required, negative=negative, scored=scored,
        cand_docids=cand,
        siterank=siterank, doclang=doclang,
        n_docs=D, qlang=plan.lang)


def pack_query(coll: Collection, plan: QueryPlan,
               doc_offset: int = 0,
               max_docs: int | None = None) -> PackedQuery | None:
    """One-shot convenience: prepare + pack a single pass (None when no
    candidate can match — pack_pass's empty-cand early-out)."""
    return pack_pass(prepare_query(coll, plan), doc_offset=doc_offset,
                     max_docs=max_docs)
