"""Query packer — termlists → padded, statically-shaped device arrays.

Reference seam: ``Msg2::getLists`` (fetch one RdbList per query term,
``Msg2.cpp:30``) feeding ``PosdbTable::setQueryTermInfo``/``intersectLists10_r``
(``Posdb.cpp:4354,5437``). The reference walks compressed byte lists per
docid; a TPU wants dense masked tensors with static shapes. So the packer:

1. fetches each group's sublists from the posdb Rdb and concatenates them
   (the "mini-merge" of ``Posdb.cpp:6000ish`` done columnarly up front);
2. picks the **driver**: the required group with the fewest unique docids
   (reference: "pick smallest list as the driver", setQueryTermInfo) — only
   its docids can match an AND query, so the candidate doc axis ``D`` is
   bounded by the driver list length, not the corpus;
3. maps every other list onto the candidate axis with ``searchsorted``
   (host-side vectorized numpy — the CPU analog of the reference's key
   compares, done once per query);
4. emits padded arrays bucketed to powers of two so jit recompiles are
   bounded: per (group, candidate-doc) up to ``P`` positions with a packed
   uint32 payload (wordpos | hashgroup | density | spam | syn).

Docid-range multipass (``Msg39.cpp:277-305``) maps to tiling the candidate
axis: callers cap ``max_docs`` and the engine runs multiple passes, merging
top-k across passes — same memory-bounding trick, TPU-shaped.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..index import posdb
from ..index.collection import Collection
from ..utils import trace
from ..utils.membudget import g_membudget
from . import weights
from .compiler import SUB_SYNONYM, QueryPlan

#: max positions kept per (group, doc) — covers MAX_TOP=10 single-term
#: slots plus slack for pair scoring (reference mini-merge buffers cap at
#: MAX_SUBLISTS*256 bytes; we cap per-doc, which is what scoring consumes)
MAX_POSITIONS = 16

# packed payload bit layout (uint32)
_POS_SHIFT = 0          # wordpos: 18 bits
_HG_SHIFT = 18          # hashgroup: 4 bits
_DEN_SHIFT = 22         # densityrank: 5 bits
_SPAM_SHIFT = 27        # wordspamrank: 4 bits
_SYN_SHIFT = 31         # synonym-ish (scored with SYNONYM_WEIGHT): 1 bit


def pack_payload(f: dict[str, np.ndarray], syn: int = 0) -> np.ndarray:
    """Unpacked posdb fields → the scorer's uint32 payload. The single
    definition of the payload bit layout (scorer._decode is its inverse);
    the resident index packs with syn=0 and ORs the query-time synonym
    flag in-kernel."""
    return (
        f["wordpos"].astype(np.uint32) << np.uint32(_POS_SHIFT)
        | f["hashgroup"].astype(np.uint32) << np.uint32(_HG_SHIFT)
        | f["densityrank"].astype(np.uint32) << np.uint32(_DEN_SHIFT)
        | f["wordspamrank"].astype(np.uint32) << np.uint32(_SPAM_SHIFT)
        | np.uint32(syn) << np.uint32(_SYN_SHIFT)
    )


#: packed impacts ride HBM scaled by 1/IMPACT_SCALE (an exact
#: power-of-two exponent shift): inlink-heavy docs can push the raw
#: bound past f16's 65504 max (BASE_SCORE·16²·MAX_TOP ≈ 2.6e5), and an
#: inf in the dense matrix would turn the phase-1 selector matmul's
#: 0-selector lanes into 0·inf = NaN, silently deleting docs from the
#: intersection mask. Scaled, the ceiling is ~16k — comfortably inside
#: range. Consumers multiply back after the f32 cast.
IMPACT_SCALE = 16.0


def demote_impacts(a: np.ndarray) -> np.ndarray:
    """f32 per-(term, doc) impact bounds → float16 at 1/IMPACT_SCALE,
    rounded UP.

    The SURVEY §7 stage-8 packing move (Gigablast demoted full 18-byte
    posdb keys to 12- and 6-byte forms by dropping shared prefixes; the
    HBM analog demotes the rank-component columns to the narrowest type
    the scorer math tolerates). Impacts are phase-1 UPPER BOUNDS, so
    rounding must never go down (a bound below the exact score breaks
    the lossless-pruning contract). The exponent shift is exact in
    both directions (power of two), so admissibility is decided purely
    by the cast: nearest-rounding casts that landed low are nudged up
    one ulp. The 1e-30 presence floor would underflow f16 to 0.0 and
    erase the posting from the intersection mask, so the floor re-lands
    on the smallest f16 subnormal (exact in f32)."""
    s = a * np.float32(1.0 / IMPACT_SCALE)
    h = s.astype(np.float16)
    low = h.astype(np.float32) < s
    h = np.where(low, np.nextafter(h, np.float16(np.inf)), h)
    return np.maximum(h, np.finfo(np.float16).smallest_subnormal)


def _bucket(n: int, floor: int = 8) -> int:
    """Next power of two ≥ n (≥ floor) — static-shape jit buckets."""
    b = floor
    while b < n:
        b <<= 1
    return b


#: shape-bucket floors. Each distinct (T, L, D) triple is one XLA
#: compilation (~20-40 s cold on TPU), so floors are set high enough that
#: everyday queries collapse into a handful of buckets; the wasted lanes
#: are masked compute the VPU shrugs off.
T_FLOOR = 4      # term groups
L_FLOOR = 512    # postings per group
D_FLOOR = 256    # candidate docs


#: truth-table bucket: boolean tables pad to this many entries (2^10 =
#: MAX_BOOL_TERMS); non-boolean queries carry the all-true table (the
#: required/negative masks own match semantics there)
TABLE_SIZE = 1 << 10


def pad_table(table: np.ndarray | None) -> np.ndarray:
    out = np.ones(TABLE_SIZE, bool)
    if table is not None:
        out[:] = False
        out[: len(table)] = table
    return out


@dataclass
class PackedQuery:
    """Device-ready query: everything the scorer jit consumes.

    Shapes: T groups × L postings × (D docs × P positions after scatter).
    All arrays numpy; the scorer moves them to device.
    """

    # per (group, posting): candidate-doc index, packed payload, position
    # slot within (group,doc), validity
    doc_idx: np.ndarray       # int32 [T, L]
    payload: np.ndarray       # uint32 [T, L]
    slot: np.ndarray          # int32 [T, L]
    valid: np.ndarray         # bool [T, L]
    # per group
    freq_weight: np.ndarray   # float32 [T]
    required: np.ndarray      # bool [T]
    negative: np.ndarray      # bool [T]
    scored: np.ndarray        # bool [T]
    counts: np.ndarray        # bool [T] groups entering the min-score
    table: np.ndarray         # bool [TABLE_SIZE] boolean truth table
    # per candidate doc
    cand_docids: np.ndarray   # uint64 [D] (actual candidates; D_pad ≥ D)
    siterank: np.ndarray      # int32 [D_pad]
    doclang: np.ndarray       # int32 [D_pad]
    n_docs: int               # real candidate count (≤ D_pad)
    qlang: int
    #: numeric-operator columns (gbmin/gbmax/gbsortby): filter mask and
    #: positive sort keys over the candidate axis; flags gate the
    #: kernel work (all-false/zero when absent)
    filt: np.ndarray | None = None      # bool [D_pad]
    sortc: np.ndarray | None = None     # float32 [D_pad]
    use_filter: bool = False
    use_sort: bool = False

    @property
    def shape_key(self) -> tuple[int, int, int]:
        return (self.doc_idx.shape[0], self.doc_idx.shape[1],
                len(self.siterank))


@dataclass
class GroupList:
    """One group's fetched+merged postings (columnar)."""

    docids: np.ndarray     # uint64, sorted
    payload: np.ndarray    # uint32, parallel
    siterank: np.ndarray   # int32, parallel (per posting, from the key)
    langid: np.ndarray     # int32, parallel
    sub: np.ndarray        # int32, parallel: originating sublist index
    n_subs: int = 1        # sublist count (sets the per-sublist quota)
    #: max distinct-doc count over the group's sublists — THE group df
    #: (devindex._df_of uses the same definition, so freq weights agree
    #: across paths; a synonym sublist must not inflate the main term's
    #: document frequency)
    group_df: int = 0
    #: per-sublist distinct-doc counts, aligned with the group's
    #: sublists (0 = no postings) — feeds slot_plan's df-ordered
    #: variant funding; the device planner derives the same numbers
    #: from _df_of, so the two paths pick identical funded variants
    sub_df: np.ndarray | None = None


def fetch_group_lists(coll: Collection, plan: QueryPlan) -> list[GroupList]:
    """Msg2 equivalent: fetch every group's sublists and mini-merge."""
    out = []
    for g in plan.groups:
        cols = {"docids": [], "payload": [], "siterank": [], "langid": [],
                "sub": []}
        sub_dfs = [0]
        per_sub_df = np.zeros(max(len(g.sublists), 1), np.int64)
        for s_i, sub in enumerate(g.sublists):
            batch = coll.termlist_cache.get(sub.termid,
                                            coll.posdb.version)
            if batch is None:
                batch = coll.posdb.get_list(posdb.start_key(sub.termid),
                                            posdb.end_key(sub.termid))
                coll.termlist_cache.put(sub.termid, coll.posdb.version,
                                        batch)
            if not len(batch):
                continue
            f = posdb.unpack(batch.keys)
            payload = pack_payload(
                f, syn=1 if sub.kind == SUB_SYNONYM else 0)
            # postings arrive key-sorted (docid ascending within the
            # term), so the distinct-doc count is a boundary count
            d_ = f["docid"]
            sub_dfs.append(int((d_[1:] != d_[:-1]).sum()) + 1)
            per_sub_df[s_i] = sub_dfs[-1]
            cols["docids"].append(f["docid"])
            cols["payload"].append(payload)
            cols["siterank"].append(f["siterank"].astype(np.int32))
            cols["langid"].append(f["langid"].astype(np.int32))
            cols["sub"].append(np.full(len(batch), s_i, np.int32))
        if cols["docids"]:
            docids = np.concatenate(cols["docids"])
            # stable sort by docid only: within a doc, postings stay
            # sublist-major (then wordpos-ascending) — (doc, sublist)
            # runs are contiguous for the per-sublist slot quota below
            order = np.argsort(docids, kind="stable")
            out.append(GroupList(
                docids=docids[order],
                payload=np.concatenate(cols["payload"])[order],
                siterank=np.concatenate(cols["siterank"])[order],
                langid=np.concatenate(cols["langid"])[order],
                sub=np.concatenate(cols["sub"])[order],
                n_subs=max(len(g.sublists), 1),
                group_df=max(sub_dfs),
                sub_df=per_sub_df))
        else:
            out.append(GroupList(
                docids=np.empty(0, np.uint64),
                payload=np.empty(0, np.uint32),
                siterank=np.empty(0, np.int32),
                langid=np.empty(0, np.int32),
                sub=np.empty(0, np.int32),
                n_subs=max(len(g.sublists), 1),
                sub_df=per_sub_df))
    return out


def _pad1(a: np.ndarray, n: int, fill) -> np.ndarray:
    """Pad a 1-D per-group array out to the T bucket."""
    if len(a) >= n:
        return a
    out = np.full(n, fill, dtype=a.dtype)
    out[: len(a)] = a
    return out


@dataclass
class PreparedQuery:
    """Fetch+intersect product, computed ONCE per query: multipass slices
    ``cand`` without re-reading the Rdb (the reference's docid-range passes
    likewise reuse the Msg2 lists already in RAM, ``Msg39.cpp:277``)."""

    plan: QueryPlan
    lists: list[GroupList]
    cand: np.ndarray          # uint64, candidate docids (sorted; may be 0)
    driver: int               # -1 when cand is empty
    freq_weight: np.ndarray   # float32 [len(plan.groups)]
    unique_counts: np.ndarray  # int64 [len(plan.groups)] docs per group
    #: per-candidate numeric-operator arrays (gbmin/gbmax/gbsortby),
    #: None when the query has none
    filt_all: np.ndarray | None = None
    sort_all: np.ndarray | None = None


def group_flags(plan: QueryPlan, T: int):
    """(required, negative, scored, counts) bool arrays padded to the T
    bucket — pure functions of the plan, shared by every shard/pass.

    ``counts`` marks the groups whose single/pair scores enter the
    min-score: scored∧required normally, every scored group under a
    boolean plan (required-ness is meaningless under OR — the truth
    table owns matching; scoring is the min over PRESENT scored
    groups, reference boolean behavior)."""
    boolean = plan.bool_table is not None
    return (
        _pad1(np.array([g.required and not g.negative
                        for g in plan.groups]), T, False),
        _pad1(np.array([g.negative for g in plan.groups]), T, False),
        _pad1(np.array([g.scored and not g.negative
                        for g in plan.groups]), T, False),
        _pad1(np.array([g.scored and not g.negative
                        and (boolean or g.required)
                        for g in plan.groups]), T, False),
    )


def _field_values(coll: Collection, fld: str,
                  cand: np.ndarray) -> np.ndarray:
    """Per-candidate f64 field values (NaN = doc lacks the field) from
    the fielddb column."""
    docids, vals = coll.fielddb.column(fld)
    out = np.full(len(cand), np.nan)
    if len(docids):
        pos = np.searchsorted(docids, cand)
        ok = pos < len(docids)
        ok[ok] = docids[pos[ok]] == cand[ok]
        out[ok] = vals[pos[ok]]
    return out


def local_sort_base(coll: Collection, fld: str,
                    desc: bool) -> float | None:
    """This collection's minimum finite sort key (v desc, -v asc) —
    the shift that keeps device sort keys positive AND small (float32
    resolution collapses at e.g. epoch-seconds magnitude). None when
    the shard has no finite values: an empty shard must not poison the
    cross-shard min with a 0.0 sentinel."""
    _, allvals = coll.fielddb.column(fld)
    av = allvals if desc else -allvals
    fin = np.isfinite(av)
    return float(av[fin].min()) if fin.any() else None


def field_arrays(coll: Collection, plan: QueryPlan, cand: np.ndarray,
                 sort_base: float | None = None):
    """(filt, sortc) candidate arrays for the numeric operators. Sort
    keys shift by ``sort_base`` (callers pass the cross-shard minimum
    on sharded paths; None = this collection's own minimum) so every
    path emits identical, merge-comparable scores."""
    filt = sortc = None
    if plan.filters:
        filt = np.ones(len(cand), bool)
        for fld, (lo, hi) in plan.filters.items():
            dv = _field_values(coll, fld, cand)
            with np.errstate(invalid="ignore"):
                filt &= (dv >= lo) & (dv <= hi)  # NaN fails both
    if plan.sortby is not None:
        fld, desc = plan.sortby
        dv = _field_values(coll, fld, cand)
        key = dv if desc else -dv
        base = sort_base if sort_base is not None \
            else local_sort_base(coll, fld, desc)
        if base is None:
            base = 0.0  # no finite values anywhere: keys are all 0.25
        finite = np.isfinite(key)
        sortc = np.where(finite, key - base + 1.0,
                         0.25).astype(np.float32)
    return filt, sortc


def prepare_query(coll: Collection, plan: QueryPlan,
                  sort_base: float | None = None) -> PreparedQuery:
    """Fetch termlists, pick the driver, intersect candidates.

    ``cand`` comes back empty when no doc can match (an empty required
    list — the reference's early-out, ``Msg39.cpp``) but the fetched
    lists are still returned: cluster-wide term-frequency stats must
    count a shard's postings even when that shard has no candidates.
    """
    with trace.span("query.fetch_lists", groups=len(plan.groups)) as sp:
        lists = fetch_group_lists(coll, plan)
        if sp is not None:
            sp.tag(postings=int(sum(len(gl.docids) for gl in lists)))
    req = [i for i, g in enumerate(plan.groups)
           if g.required and not g.negative]

    # candidate sets: required groups only in conjunctive mode; every
    # group under a boolean plan (the union is the candidate space)
    need_uniq = (range(len(lists)) if plan.bool_table is not None
                 else [i for i in req])
    uniques = {i: np.unique(lists[i].docids) for i in need_uniq}
    unique_counts = np.array(
        [lists[i].group_df for i in range(len(lists))], dtype=np.int64)
    nd = max(coll.num_docs, 1)
    freqw = weights.term_freq_weight(unique_counts, nd)

    if plan.bool_table is not None:
        # boolean plan: candidates = union of every group's docids (any
        # satisfying doc has ≥1 present group — the compiler rejects
        # tables that match the empty presence set); the truth table
        # decides matching on device
        cand = (np.unique(np.concatenate(
            [uniques[i] for i in range(len(lists))]))
            if lists and any(len(u) for u in uniques.values())
            else np.empty(0, np.uint64))
        driver = (max(range(len(lists)), key=lambda i: len(uniques[i]))
                  if lists else -1)
        fa, sa = field_arrays(coll, plan, cand, sort_base=sort_base)
        return PreparedQuery(plan=plan, lists=lists, cand=cand,
                             driver=driver if len(cand) else -1,
                             freq_weight=freqw,
                             unique_counts=unique_counts,
                             filt_all=fa, sort_all=sa)

    if not req or any(not len(uniques[i]) for i in req):
        return PreparedQuery(plan=plan, lists=lists,
                             cand=np.empty(0, np.uint64), driver=-1,
                             freq_weight=freqw,
                             unique_counts=unique_counts)

    # driver = required group with fewest unique docids
    driver = min(req, key=lambda i: len(uniques[i]))
    cand = uniques[driver]
    # intersect with every other required group's docids (cheap host-side
    # pre-intersection; the device re-checks presence per term anyway)
    for i in req:
        if i != driver and len(cand):
            cand = cand[np.isin(cand, uniques[i], assume_unique=True)]
    fa, sa = field_arrays(coll, plan, cand, sort_base=sort_base)
    return PreparedQuery(plan=plan, lists=lists, cand=cand, driver=driver,
                         freq_weight=freqw, unique_counts=unique_counts,
                         filt_all=fa, sort_all=sa)


def pack_pass(prep: PreparedQuery, doc_offset: int = 0,
              max_docs: int | None = None,
              max_positions: int = MAX_POSITIONS,
              budget_shrink: bool = False) -> PackedQuery | None:
    """Build the PackedQuery for one docid-range pass over the prepared
    candidates (slice [doc_offset : doc_offset+max_docs]).

    The padded staging arrays are reserved against the process memory
    budget under the ``pack`` label. With ``budget_shrink=True`` an
    over-budget pass degrades by halving ``max_docs`` until it fits (or
    one doc remains) — callers must then advance by the returned
    ``PackedQuery.n_docs``, not their requested stride. Without it the
    refusal is only counted and the pass proceeds (single-pass callers
    that cannot re-slice)."""
    plan, lists = prep.plan, prep.lists
    if max_docs is not None:
        cand = prep.cand[doc_offset:doc_offset + max_docs]
    else:
        cand = prep.cand[doc_offset:] if doc_offset else prep.cand
    if not len(cand):
        return None
    required, negative, scored, counts = group_flags(
        plan, _bucket(len(plan.groups), T_FLOOR))

    T = _bucket(len(plan.groups), T_FLOOR)
    D = len(cand)
    D_pad = _bucket(D, D_FLOOR)

    per_group = []
    max_kept = 1
    for g_i, gl in enumerate(lists):
        if not len(gl.docids):
            per_group.append((np.empty(0, np.int32), np.empty(0, np.uint32),
                              np.empty(0, np.int32)))
            continue
        pos_in_cand = np.searchsorted(cand, gl.docids)
        pos_in_cand_c = np.clip(pos_in_cand, 0, D - 1)
        hit = cand[pos_in_cand_c] == gl.docids
        didx = pos_in_cand_c[hit].astype(np.int32)
        payload = gl.payload[hit]
        sub = gl.sub[hit]
        # per-sublist slot quotas within each doc (TermGroup.slot_plan:
        # the primary word keeps ≥ half the budget, variants split the
        # rest) so a spammy variant can never starve the primary out of
        # the position cube. The resident kernel uses the identical
        # base+quota scheme — parity by construction. (doc, sublist)
        # runs are contiguous: stable docid sort keeps sublist-major
        # order within a doc.
        if len(didx):
            # quota only over sublists with postings (absent synonyms
            # must not reserve dead slots) — same mask the device
            # planner derives from its druns, so parity holds
            n_subs = len(plan.groups[g_i].sublists)
            have = np.zeros(n_subs, bool)
            have[np.unique(gl.sub)] = True
            sp = plan.groups[g_i].slot_plan(
                max_positions, present=list(have),
                df=None if gl.sub_df is None
                else [int(x) for x in gl.sub_df])
            bases = np.array([b for b, _ in sp], np.int32)
            quotas = np.array([q for _, q in sp], np.int32)
            n = len(didx)
            boundary = np.ones(n, bool)
            boundary[1:] = (didx[1:] != didx[:-1]) | (sub[1:] != sub[:-1])
            idx = np.arange(n)
            rank = idx - np.maximum.accumulate(np.where(boundary, idx, 0))
            slot = (bases[sub] + rank).astype(np.int32)
            keep = (rank < quotas[sub]) & (slot < max_positions)
            didx, payload, slot = didx[keep], payload[keep], slot[keep]
            max_kept = max(max_kept, len(didx))
        else:
            slot = np.empty(0, np.int32)
        per_group.append((didx, payload, slot))

    L = _bucket(max_kept, L_FLOOR)
    # budget gate: the padded [T,L] staging planes + [D_pad] sidecars
    # are the pack's working set. Refused + budget_shrink ⇒ halve the
    # doc slice and retry (the caller advances by n_docs, so nothing is
    # skipped — just more, smaller passes).
    est = T * L * 13 + D_pad * 13
    granted = g_membudget.reserve("pack", est)
    if not granted and budget_shrink and D > 1:
        trace.tag(budget_shrunk=True)
        return pack_pass(prep, doc_offset, max(D // 2, 1),
                         max_positions, budget_shrink)
    try:
        # pack dims on the enclosing query.pack span — the [T,L]/[D]
        # shape is what decides both HBM bytes and kernel time
        trace.tag(T=int(T), L=int(L), D=int(D), bytes=int(est))
        return _pack_arrays(prep, cand, doc_offset, per_group,
                            required, negative, scored, counts,
                            T, D, D_pad, L)
    finally:
        if granted:
            g_membudget.release("pack", est)


def _pack_arrays(prep, cand, doc_offset, per_group, required, negative,
                 scored, counts, T, D, D_pad, L):
    plan, lists = prep.plan, prep.lists
    doc_idx = np.full((T, L), D_pad, dtype=np.int32)  # D_pad = drop row
    payload = np.zeros((T, L), dtype=np.uint32)
    slot = np.zeros((T, L), dtype=np.int32)
    valid = np.zeros((T, L), dtype=bool)
    for t, (didx, pl, sl) in enumerate(per_group):
        n = len(didx)
        doc_idx[t, :n] = didx
        payload[t, :n] = pl
        slot[t, :n] = sl
        valid[t, :n] = True

    # per-candidate-doc siterank/langid from the first posting of a
    # group containing the doc (reference: getSiteRank(miniMergedList[0])
    # Posdb.cpp:6989); under a boolean plan no single group covers every
    # candidate, so walk groups until each doc is filled
    siterank = np.zeros(D_pad, dtype=np.int32)
    doclang = np.zeros(D_pad, dtype=np.int32)
    filled = np.zeros(D, dtype=bool)
    order = [prep.driver] + [i for i in range(len(lists))
                             if i != prep.driver]
    for g_i in order:
        gl = lists[g_i]
        if not len(gl.docids) or filled.all():
            continue
        first = np.clip(np.searchsorted(gl.docids, cand), 0,
                        len(gl.docids) - 1)
        hit = (gl.docids[first] == cand) & ~filled
        siterank[:D][hit] = gl.siterank[first[hit]]
        doclang[:D][hit] = gl.langid[first[hit]]
        filled |= hit
        if plan.bool_table is None:
            break  # driver covers every candidate in conjunctive mode

    filt = sortc = None
    if prep.filt_all is not None:
        filt = np.zeros(D_pad, bool)
        filt[:D] = prep.filt_all[doc_offset:doc_offset + D]
    if prep.sort_all is not None:
        sortc = np.zeros(D_pad, np.float32)
        sortc[:D] = prep.sort_all[doc_offset:doc_offset + D]
    return PackedQuery(
        doc_idx=doc_idx, payload=payload, slot=slot, valid=valid,
        freq_weight=_pad1(prep.freq_weight, T, 0.5),
        required=required, negative=negative, scored=scored,
        counts=counts, table=pad_table(plan.bool_table),
        cand_docids=cand,
        siterank=siterank, doclang=doclang,
        n_docs=D, qlang=plan.lang,
        filt=filt, sortc=sortc,
        use_filter=filt is not None, use_sort=sortc is not None)


def pack_query(coll: Collection, plan: QueryPlan,
               doc_offset: int = 0,
               max_docs: int | None = None) -> PackedQuery | None:
    """One-shot convenience: prepare + pack a single pass (None when no
    candidate can match — pack_pass's empty-cand early-out)."""
    return pack_pass(prepare_query(coll, plan), doc_offset=doc_offset,
                     max_docs=max_docs)
