"""Query compiler — query string → term-group execution plan.

Reference: ``Query.cpp/h`` (``Query::set2``: QueryWords → QueryTerms with
bigrams/synonyms, fielded terms, quoted phrases, +/- signs) and
``PosdbTable::setQueryTermInfo`` (``Posdb.cpp:4354``) which groups each
term with its bigram/synonym variants into a QueryTermInfo whose sublists
are mini-merged at scoring time.

Supported subset (the reference's everyday operators; boolean expression
trees and the ~100 SearchInput parms come with the API layer):

* plain words → one required, scored group per word
* adjacent-pair bigrams attached as sublists of the left word's group
  (reference: bigram sublists share the leading word's position, so a doc
  matching only the bigram still satisfies the group)
* ``"quoted phrase"`` → each word required + the phrase's bigram chain as
  *additional required groups* (positional adjacency enforced via the
  indexed bigram terms rather than a separate phrase machine)
* ``-word`` → negative group: matching docs are excluded
  (reference BF_NEGATIVE)
* ``site:example.com`` → required *filter* group on the site term
  (scored=False — it gates matching but stays out of the min-score; the
  reference carries fielded terms through scoring, but a constant-position
  field term under the min-algorithm would dominate every query)
* **boolean expressions** — ``a AND (b OR c) AND NOT d`` with uppercase
  operators and parentheses (reference ``Query.h:266``: boolean queries
  compile to truth tables over term-presence bits). Here likewise: the
  expression compiles to a :attr:`QueryPlan.bool_table` — a
  ``[2^T]`` bool lookup indexed by the packed per-doc presence bits —
  which every execution path (host-packed, resident two-phase,
  full-cube, sharded) evaluates as one tiny gather. Scoring under a
  boolean query is the min over *present* scored groups (the
  reference's behavior: required-ness is meaningless under OR).
* **synonym sublists** — plain words carry morphological conjugates
  (plural/verb forms, reference ``Synonyms.cpp`` FORM_CONJUGATE /
  ``Posdb.h:21``) as SUB_SYNONYM sublists scoring ×SYNONYM_WEIGHT=0.90;
  slot quotas are asymmetric so variants never starve the primary word
  out of the position budget.

Groups carry ``qpos`` (query word index); pair scoring uses the reference's
default qdist=2 ("get query words as close together as possible",
``Posdb.cpp:6886``).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

import numpy as np

from ..utils import ghash

_WORD_RE = re.compile(r"\w+", re.UNICODE)
# the '-' negation operator only binds at a token boundary (start of query
# or after whitespace) so intra-word hyphens ("covid-19", "state-of-the-art")
# never negate their tail words (reference QueryWord sign parsing requires
# the minus to start the word, Query.cpp)
_TOKEN_RE = re.compile(
    r"""
    (?P<neg>(?:(?<=\s)|^)-)?
    (?:
        (?P<field>[a-zA-Z]+):(?P<fval>"[^"]*"|\S+)
      | "(?P<quote>[^"]*)"
      | (?P<word>\w+)
    )
    """,
    re.UNICODE | re.VERBOSE,
)

#: fields that compile to prefix-hashed filter terms (reference Query.cpp
#: field table — site:, inurl:, etc.; the rest arrive with the API layer)
FILTER_FIELDS = {"site": "site", "inurl": "inurl", "gbcontenthash":
                 "gbcontenthash"}

#: sublist kinds (reference bigram flags BF_* on QueryTermInfo sublists)
SUB_ORIGINAL = 0
SUB_BIGRAM = 1
SUB_SYNONYM = 2

#: max sublists per term group — each sublist needs at least one slot of
#: the group's MAX_POSITIONS=16 position budget (packer quota scheme);
#: the reference caps sublists too (MAX_SUBLISTS, Posdb.h)
MAX_GROUP_SUBLISTS = 16

#: max leaves in a boolean expression (truth table = 2^T entries; the
#: reference's tables cover 16 terms via 64k bitvecs, Query.h:266)
MAX_BOOL_TERMS = 10

#: synonym conjugates attached per word (Synonyms.cpp caps too)
MAX_SYNONYMS = 4


@dataclass
class Sublist:
    termid: int
    kind: int  # SUB_*
    display: str = ""


@dataclass
class TermGroup:
    """One QueryTermInfo: a scoring unit whose sublists are mini-merged."""

    display: str
    sublists: list[Sublist] = field(default_factory=list)
    required: bool = True
    negative: bool = False
    scored: bool = True
    qpos: int = 0

    @property
    def termids(self) -> list[int]:
        return [s.termid for s in self.sublists]

    def slot_plan(self, max_positions: int = 16,
                  present: list[bool] | None = None,
                  df: list[int] | None = None
                  ) -> list[tuple[int, int]]:
        """[(slot_base, quota)] per sublist: the ORIGINAL word keeps at
        least half the position budget; bigram/synonym variants split
        the rest (a spammy variant must never starve the primary word —
        the reference's mini-merge buffers are per-sublist too).

        ``present`` marks sublists that actually have postings: absent
        variants get quota 0 instead of reserving dead slots, so a word
        whose synonyms don't occur in the corpus keeps the FULL position
        budget (the reference's mini-merge has no such reservation —
        slots are a packing artifact here). ``df`` (per-sublist live
        document frequency, parallel to ``sublists``) decides WHICH
        variants win the funded quarter-slots: highest df first — a
        rare conjugate must not burn a slot while a common dictionary
        synonym that actually matches documents gets silently dropped
        (the recall regression is invisible otherwise, so the drop
        count lands in ``query.variants_dropped``). Without ``df`` the
        funding falls back to sublist order (conjugates attach before
        dictionary synonyms). Callers on every path (host packer,
        device planner) pass the same mask AND the same dfs, so parity
        holds."""
        subs = self.sublists
        if present is None:
            present = [True] * len(subs)
        live = [s for s, p in zip(subs, present) if p]
        if len(live) <= 1:
            return [(0, max_positions if p else 0) for p in present]
        any_prim = any(p and s.kind == SUB_ORIGINAL
                       for s, p in zip(subs, present))
        prim = max(max_positions // 2, 1) if any_prim else 0
        # variant quotas stay QUARTER-ALIGNED (P//4): the direct-cube
        # kernel requires quarter-aligned (base, quota) to assemble
        # group planes from resident quarter-rows — a 2-slot variant
        # would silently disqualify common synonym-bearing queries from
        # the FD fast path. Variants past the slot budget get quota 0
        # (the reference's mini-merge buffers cap sublists the same
        # way, MAX_SUBLISTS).
        var = max(max_positions // 4, 1)
        budget = max_positions - prim
        variant_idx = [i for i, (s, p) in enumerate(zip(subs, present))
                       if p and s.kind != SUB_ORIGINAL]
        n_funded = min(len(variant_idx), budget // var)
        ranked = variant_idx if df is None else \
            sorted(variant_idx, key=lambda i: (-int(df[i]), i))
        funded = set(ranked[:n_funded])
        dropped = len(variant_idx) - n_funded
        if dropped > 0:
            from ..utils.stats import g_stats
            g_stats.count("query.variants_dropped", dropped)
        out = []
        base = 0
        for i, (s, p) in enumerate(zip(subs, present)):
            if not p:
                out.append((min(base, max_positions - 1), 0))
                continue
            if s.kind == SUB_ORIGINAL:
                q = prim
            elif i in funded:
                q = var
            else:
                q = 0
            out.append((min(base, max_positions - 1), q))
            base += q
        return out


@dataclass
class QueryPlan:
    raw: str
    groups: list[TermGroup] = field(default_factory=list)
    lang: int = 0  # 0 = any (reference &qlang)
    #: boolean truth table over presence bits (None = plain conjunctive)
    bool_table: np.ndarray | None = None
    #: numeric range constraints: field → [min, max] (gbmin:/gbmax:,
    #: reference fielded numeric terms Query.h:209)
    filters: dict = field(default_factory=dict)
    #: sort override: (field, reverse) — gbsortby:/gbsortbyrev:
    sortby: tuple | None = None
    #: facet requests: field names (gbfacet:field, qa.cpp:2910 qajson)
    facets: list = field(default_factory=list)

    @property
    def scored_groups(self) -> list[TermGroup]:
        return [g for g in self.groups if g.scored and not g.negative]

    def match_words(self) -> list[str]:
        """Every single word a match/highlight pass should light up:
        the scored groups' originals AND their conjugate forms
        (Matches.cpp matches synonym forms too — "run" highlights
        "running"). Bigram displays ("a b") and fielded displays
        ("site:x") are skipped: they never equal a single token."""
        out: list[str] = []
        seen: set[str] = set()
        for g in self.scored_groups:
            for d in [g.display] + [s.display for s in g.sublists]:
                d = (d or "").lower()
                if d and " " not in d and ":" not in d \
                        and not d.startswith('"') and d not in seen:
                    seen.add(d)
                    out.append(d)
        return out

    @property
    def num_terms(self) -> int:
        return len(self.groups)


#: boolean operator detector: uppercase keywords, reference style
#: ("boolean operators must be in UPPER CASE", html/syntax.html)
_BOOL_RE = re.compile(r"(?:^|[\s(])(AND|OR|NOT)(?:[\s)]|$)")


def compile_query(q: str, lang: int = 0,
                  bigrams: bool = True,
                  synonyms: bool = True) -> QueryPlan:
    """Compile a query string into a :class:`QueryPlan`."""
    from ..utils.unicodenorm import nfc
    q = nfc(q)  # match the indexed (NFC) term forms
    if _BOOL_RE.search(q):
        try:
            return _compile_boolean(q, lang, synonyms)
        except ValueError:
            pass  # malformed boolean → fall through as plain words
    plan = QueryPlan(raw=q, lang=lang)
    qpos = 0
    plain_words: list[tuple[int, str]] = []  # (group index, word)

    for m in _TOKEN_RE.finditer(q):
        neg = m.group("neg") is not None
        if m.group("field") is not None:
            fname = m.group("field").lower()
            fval = m.group("fval").strip('"')
            if fname in ("gbmin", "gbmax") and ":" in fval:
                # gbmin:price:10 — numeric range gate on a fielddb
                # column (reference numeric fielded terms, Query.h:209)
                fld, _, num = fval.rpartition(":")
                try:
                    v = float(num)
                except ValueError:
                    continue
                lohi = plan.filters.setdefault(
                    fld.lower(), [float("-inf"), float("inf")])
                if fname == "gbmin":
                    lohi[0] = max(lohi[0], v)
                else:
                    lohi[1] = min(lohi[1], v)
                continue
            if fname in ("gbsortby", "gbsortbyrev"):
                plan.sortby = (fval.lower(), fname == "gbsortby")
                # gbsortby:date descends (newest first) by default —
                # reference gbsortby sorts descending by field value
                continue
            if fname == "gbfacet":
                plan.facets.append(fval.lower())
                continue
            if fname in FILTER_FIELDS:
                tid = ghash.term_id(fval, prefix=FILTER_FIELDS[fname])
                plan.groups.append(TermGroup(
                    display=f"{fname}:{fval}",
                    sublists=[Sublist(tid, SUB_ORIGINAL, f"{fname}:{fval}")],
                    negative=neg, scored=False, qpos=qpos))
                qpos += 1
            else:
                # unknown field → treat the value as plain words
                for w in _WORD_RE.findall(fval.lower()):
                    plan.groups.append(_word_group(w, qpos, neg, synonyms))
                    if not neg:
                        plain_words.append((len(plan.groups) - 1, w))
                    qpos += 1
        elif m.group("quote") is not None:
            words = [w.lower() for w in _WORD_RE.findall(m.group("quote"))]
            if neg and len(words) > 1:
                # negated phrase: exclude docs matching the phrase, NOT docs
                # containing any single word of it. One negative group on
                # the bigram chain — exact for two-word phrases; for longer
                # phrases it conservatively excludes any adjacent sub-pair
                # (reference BF_NEGATIVE phrase semantics)
                subs = [Sublist(ghash.bigram_id(a, b), SUB_BIGRAM, f"{a} {b}")
                        for a, b in zip(words, words[1:])
                        ][:MAX_GROUP_SUBLISTS]
                plan.groups.append(TermGroup(
                    display='-"' + " ".join(words) + '"', sublists=subs,
                    negative=True, scored=False, qpos=qpos))
                qpos += len(words)
                continue
            for i, w in enumerate(words):
                plan.groups.append(_word_group(w, qpos, neg, synonyms))
                qpos += 1
                if i + 1 < len(words):
                    # adjacency gate: the indexed bigram term must match too
                    bid = ghash.bigram_id(w, words[i + 1])
                    plan.groups.append(TermGroup(
                        display=f'"{w} {words[i+1]}"',
                        sublists=[Sublist(bid, SUB_BIGRAM)],
                        negative=neg, scored=False, qpos=qpos))
        else:
            w = m.group("word").lower()
            plan.groups.append(_word_group(w, qpos, neg, synonyms))
            if not neg:
                plain_words.append((len(plan.groups) - 1, w))
            qpos += 1

    # attach adjacent-word bigrams as sublists of the left word's group
    # (setQueryTermInfo: bigram termlists ride the leading term's group)
    if bigrams:
        for (gi, w1), (gj, w2) in zip(plain_words, plain_words[1:]):
            if plan.groups[gi].qpos + 1 == plan.groups[gj].qpos:
                plan.groups[gi].sublists.append(Sublist(
                    ghash.bigram_id(w1, w2), SUB_BIGRAM, f"{w1} {w2}"))
    return plan


def _conjugates(w: str) -> list[str]:
    """Morphological variants (reference Synonyms.cpp FORM_CONJUGATE —
    plural/singular and simple verb forms; the full Wiktionary synonym
    sets are a data file away, the machinery is identical)."""
    out: list[str] = []

    def add(x):
        if x and x != w and x not in out:
            out.append(x)

    if w.endswith("ies") and len(w) > 4:
        add(w[:-3] + "y")
    elif w.endswith("sses"):
        add(w[:-2])
    elif w.endswith("es") and len(w) > 3:
        add(w[:-2])
        add(w[:-1])
    elif w.endswith("s") and not w.endswith("ss") and len(w) > 3:
        add(w[:-1])
    else:
        if w.endswith("y") and len(w) > 3:
            add(w[:-1] + "ies")
        add(w + "s")
        # gerund forms (run→running, make→making, walk→walking):
        # absent junk variants cost nothing (the present mask zeroes
        # their slot quota) — but for SHORT CVC words the non-doubled
        # form is a DIFFERENT word's e-drop gerund (car→caring is
        # "care", hat→hating is "hate"), a real indexed term, so only
        # the doubled form is emitted there
        if len(w) > 2 and not w.endswith("ing"):
            if w.endswith("e"):
                add(w[:-1] + "ing")
            elif w[-1] not in "aeiouy" and w[-2] in "aeiou" \
                    and w[-3] not in "aeiou":
                add(w + w[-1] + "ing")  # CVC doubling
                if len(w) > 4:
                    add(w + "ing")      # visiting-style (no doubling)
            else:
                add(w + "ing")
    if w.endswith("ing") and len(w) > 5:
        base = w[:-3]
        if len(base) > 2 and base[-1] == base[-2]:
            add(base[:-1])  # running → run — BEFORE the raw base:
            # the MAX_SYNONYMS cap must not cut the real word for
            # the doubled-consonant artifact ("runn")
        add(base)
        add(base + "e")
    elif w.endswith("ed") and len(w) > 4:
        add(w[:-2])
        add(w[:-1])
        if len(w) > 5 and w[-3] == w[-4]:
            add(w[:-3])     # stopped → stop
    return out[:MAX_SYNONYMS]


_SYN_DICT: dict[str, list[str]] | None = None

#: dictionary synonyms attached per word (on top of conjugates) — the
#: slot plan must keep room for the primary's half budget
MAX_DICT_SYNONYMS = 2


def _syn_dict() -> dict[str, list[str]]:
    """word → synonym list from data/synonyms.txt (the Synonyms.cpp /
    mysynonyms.txt dictionary — any wordlist dropped into the data
    file extends it; Wiktionary-scale lists are a data problem, the
    machinery here is the same)."""
    global _SYN_DICT
    if _SYN_DICT is None:
        from pathlib import Path
        d: dict[str, list[str]] = {}
        p = Path(__file__).parent / "data" / "synonyms.txt"
        try:
            for line in p.read_text(encoding="utf-8").splitlines():
                line = line.strip()
                if not line or line.startswith("#"):
                    continue
                ws = [w.strip().lower() for w in line.split(",")
                      if w.strip()]
                for w in ws:
                    lst = d.setdefault(w, [])
                    lst.extend(x for x in ws if x != w and x not in lst)
        except OSError:
            pass
        _SYN_DICT = d
    return _SYN_DICT


def _word_group(word: str, qpos: int, neg: bool,
                synonyms: bool = True) -> TermGroup:
    subs = [Sublist(ghash.term_id(word), SUB_ORIGINAL, word)]
    if synonyms and not neg:
        # negatives stay literal: "-apple" must not exclude "apples"
        variants = list(_conjugates(word))
        for s in _syn_dict().get(word.lower(), [])[:MAX_DICT_SYNONYMS]:
            if s not in variants and s != word:
                variants.append(s)
        subs += [Sublist(ghash.term_id(c), SUB_SYNONYM, c)
                 for c in variants]
    return TermGroup(display=word, sublists=subs, negative=neg, qpos=qpos)


# ---------------------------------------------------------------------------
# boolean expressions (Query.h:266 truth tables)
# ---------------------------------------------------------------------------

class _BoolParser:
    """Recursive descent over ``expr := and (OR and)*``,
    ``and := unary ((AND)? unary)*``, ``unary := NOT unary | (expr) |
    term`` — implicit adjacency inside a clause is AND, like the
    reference's default boolean mode."""

    def __init__(self, tokens: list[str], synonyms: bool = True):
        self.toks = tokens
        self.i = 0
        self.synonyms = synonyms
        self.leaves: list[TermGroup] = []

    def peek(self):
        return self.toks[self.i] if self.i < len(self.toks) else None

    def next(self):
        t = self.peek()
        self.i += 1
        return t

    def parse(self):
        node = self.parse_or()
        if self.peek() is not None:
            raise ValueError(f"trailing tokens at {self.peek()!r}")
        return node

    def parse_or(self):
        node = self.parse_and()
        while self.peek() == "OR":
            self.next()
            node = ("or", node, self.parse_and())
        return node

    def parse_and(self):
        node = self.parse_unary()
        while (t := self.peek()) is not None and t not in ("OR", ")"):
            if t == "AND":
                self.next()
            node = ("and", node, self.parse_unary())
        return node

    def parse_unary(self):
        t = self.peek()
        if t is None:
            raise ValueError("unexpected end of expression")
        if t == "NOT":
            self.next()
            return ("not", self.parse_unary())
        if t == "(":
            self.next()
            node = self.parse_or()
            if self.next() != ")":
                raise ValueError("unbalanced parenthesis")
            return node
        tok = self.next()
        # minus-negation inside a boolean expression = NOT (the
        # conjunctive path's exclude semantics, Query.cpp sign parsing)
        if tok.startswith("-") and len(tok) > 1:
            return ("not", ("leaf", self._leaf(tok[1:])))
        return ("leaf", self._leaf(tok))

    def _leaf(self, tok: str) -> int:
        if len(self.leaves) >= MAX_BOOL_TERMS:
            raise ValueError("too many boolean terms")
        m = _TOKEN_RE.match(tok)
        if m is None:
            raise ValueError(f"bad term {tok!r}")
        if m.group("field") is not None:
            fname = m.group("field").lower()
            fval = m.group("fval").strip('"')
            if fname in FILTER_FIELDS:
                tid = ghash.term_id(fval, prefix=FILTER_FIELDS[fname])
                g = TermGroup(display=f"{fname}:{fval}",
                              sublists=[Sublist(tid, SUB_ORIGINAL)],
                              scored=False)
            else:
                g = _word_group(fval.lower(), 0, False, self.synonyms)
        elif m.group("quote") is not None:
            words = [w.lower() for w in
                     _WORD_RE.findall(m.group("quote"))]
            # one group per phrase: the bigram chain gates adjacency
            subs = ([Sublist(ghash.term_id(words[0]), SUB_ORIGINAL,
                             words[0])] if len(words) == 1 else
                    [Sublist(ghash.bigram_id(a, b), SUB_BIGRAM,
                             f"{a} {b}")
                     for a, b in zip(words, words[1:])
                     ][:MAX_GROUP_SUBLISTS])
            g = TermGroup(display='"' + " ".join(words) + '"',
                          sublists=subs)
        else:
            g = _word_group(m.group("word").lower(), 0, False,
                            self.synonyms)
        g.qpos = len(self.leaves)
        g.required = False  # the truth table owns match semantics
        self.leaves.append(g)
        return len(self.leaves) - 1


def _eval_node(node, bits: int) -> bool:
    op = node[0]
    if op == "leaf":
        return bool(bits >> node[1] & 1)
    if op == "not":
        return not _eval_node(node[1], bits)
    a = _eval_node(node[1], bits)
    b = _eval_node(node[2], bits)
    return (a and b) if op == "and" else (a or b)


def _leaf_polarity(node, neg: bool, out: dict) -> None:
    op = node[0]
    if op == "leaf":
        out[node[1]] = out.get(node[1], False) or neg
    elif op == "not":
        _leaf_polarity(node[1], not neg, out)
    else:
        _leaf_polarity(node[1], neg, out)
        _leaf_polarity(node[2], neg, out)


def _compile_boolean(q: str, lang: int, synonyms: bool = True
                     ) -> QueryPlan:
    toks = re.findall(r"\(|\)|\"[^\"]*\"|[^\s()]+", q)
    parser = _BoolParser(toks, synonyms)
    tree = parser.parse()
    if not parser.leaves:
        raise ValueError("no terms")
    # leaves under a NOT stay literal: presence of a conjugate must not
    # exclude a doc the literal term doesn't appear in
    polarity: dict[int, bool] = {}
    _leaf_polarity(tree, False, polarity)
    for li, negged in polarity.items():
        if negged:
            parser.leaves[li].sublists = [
                sl for sl in parser.leaves[li].sublists
                if sl.kind != SUB_SYNONYM] or parser.leaves[li].sublists
    T = len(parser.leaves)
    table = np.array([_eval_node(tree, bits) for bits in range(1 << T)],
                     dtype=bool)
    if table[0]:
        # matches-on-empty-presence (e.g. pure NOT): unservable, like
        # the reference's rejection of unbound negative queries
        raise ValueError("boolean query matches the empty set")
    return QueryPlan(raw=q, lang=lang, groups=parser.leaves,
                     bool_table=table)
