"""Query compiler — query string → term-group execution plan.

Reference: ``Query.cpp/h`` (``Query::set2``: QueryWords → QueryTerms with
bigrams/synonyms, fielded terms, quoted phrases, +/- signs) and
``PosdbTable::setQueryTermInfo`` (``Posdb.cpp:4354``) which groups each
term with its bigram/synonym variants into a QueryTermInfo whose sublists
are mini-merged at scoring time.

Supported subset (the reference's everyday operators; boolean expression
trees and the ~100 SearchInput parms come with the API layer):

* plain words → one required, scored group per word
* adjacent-pair bigrams attached as sublists of the left word's group
  (reference: bigram sublists share the leading word's position, so a doc
  matching only the bigram still satisfies the group)
* ``"quoted phrase"`` → each word required + the phrase's bigram chain as
  *additional required groups* (positional adjacency enforced via the
  indexed bigram terms rather than a separate phrase machine)
* ``-word`` → negative group: matching docs are excluded
  (reference BF_NEGATIVE)
* ``site:example.com`` → required *filter* group on the site term
  (scored=False — it gates matching but stays out of the min-score; the
  reference carries fielded terms through scoring, but a constant-position
  field term under the min-algorithm would dominate every query)

Groups carry ``qpos`` (query word index); pair scoring uses the reference's
default qdist=2 ("get query words as close together as possible",
``Posdb.cpp:6886``).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from ..utils import ghash

_WORD_RE = re.compile(r"\w+", re.UNICODE)
# the '-' negation operator only binds at a token boundary (start of query
# or after whitespace) so intra-word hyphens ("covid-19", "state-of-the-art")
# never negate their tail words (reference QueryWord sign parsing requires
# the minus to start the word, Query.cpp)
_TOKEN_RE = re.compile(
    r"""
    (?P<neg>(?:(?<=\s)|^)-)?
    (?:
        (?P<field>[a-zA-Z]+):(?P<fval>"[^"]*"|\S+)
      | "(?P<quote>[^"]*)"
      | (?P<word>\w+)
    )
    """,
    re.UNICODE | re.VERBOSE,
)

#: fields that compile to prefix-hashed filter terms (reference Query.cpp
#: field table — site:, inurl:, etc.; the rest arrive with the API layer)
FILTER_FIELDS = {"site": "site", "inurl": "inurl", "gbcontenthash":
                 "gbcontenthash"}

#: sublist kinds (reference bigram flags BF_* on QueryTermInfo sublists)
SUB_ORIGINAL = 0
SUB_BIGRAM = 1
SUB_SYNONYM = 2

#: max sublists per term group — each sublist needs at least one slot of
#: the group's MAX_POSITIONS=16 position budget (packer quota scheme);
#: the reference caps sublists too (MAX_SUBLISTS, Posdb.h)
MAX_GROUP_SUBLISTS = 16


@dataclass
class Sublist:
    termid: int
    kind: int  # SUB_*
    display: str = ""


@dataclass
class TermGroup:
    """One QueryTermInfo: a scoring unit whose sublists are mini-merged."""

    display: str
    sublists: list[Sublist] = field(default_factory=list)
    required: bool = True
    negative: bool = False
    scored: bool = True
    qpos: int = 0

    @property
    def termids(self) -> list[int]:
        return [s.termid for s in self.sublists]


@dataclass
class QueryPlan:
    raw: str
    groups: list[TermGroup] = field(default_factory=list)
    lang: int = 0  # 0 = any (reference &qlang)

    @property
    def scored_groups(self) -> list[TermGroup]:
        return [g for g in self.groups if g.scored and not g.negative]

    @property
    def num_terms(self) -> int:
        return len(self.groups)


def compile_query(q: str, lang: int = 0,
                  bigrams: bool = True) -> QueryPlan:
    """Compile a query string into a :class:`QueryPlan`."""
    plan = QueryPlan(raw=q, lang=lang)
    qpos = 0
    plain_words: list[tuple[int, str]] = []  # (group index, word)

    for m in _TOKEN_RE.finditer(q):
        neg = m.group("neg") is not None
        if m.group("field") is not None:
            fname = m.group("field").lower()
            fval = m.group("fval").strip('"')
            if fname in FILTER_FIELDS:
                tid = ghash.term_id(fval, prefix=FILTER_FIELDS[fname])
                plan.groups.append(TermGroup(
                    display=f"{fname}:{fval}",
                    sublists=[Sublist(tid, SUB_ORIGINAL, f"{fname}:{fval}")],
                    negative=neg, scored=False, qpos=qpos))
                qpos += 1
            else:
                # unknown field → treat the value as plain words
                for w in _WORD_RE.findall(fval.lower()):
                    plan.groups.append(_word_group(w, qpos, neg))
                    if not neg:
                        plain_words.append((len(plan.groups) - 1, w))
                    qpos += 1
        elif m.group("quote") is not None:
            words = [w.lower() for w in _WORD_RE.findall(m.group("quote"))]
            if neg and len(words) > 1:
                # negated phrase: exclude docs matching the phrase, NOT docs
                # containing any single word of it. One negative group on
                # the bigram chain — exact for two-word phrases; for longer
                # phrases it conservatively excludes any adjacent sub-pair
                # (reference BF_NEGATIVE phrase semantics)
                subs = [Sublist(ghash.bigram_id(a, b), SUB_BIGRAM, f"{a} {b}")
                        for a, b in zip(words, words[1:])
                        ][:MAX_GROUP_SUBLISTS]
                plan.groups.append(TermGroup(
                    display='-"' + " ".join(words) + '"', sublists=subs,
                    negative=True, scored=False, qpos=qpos))
                qpos += len(words)
                continue
            for i, w in enumerate(words):
                plan.groups.append(_word_group(w, qpos, neg))
                qpos += 1
                if i + 1 < len(words):
                    # adjacency gate: the indexed bigram term must match too
                    bid = ghash.bigram_id(w, words[i + 1])
                    plan.groups.append(TermGroup(
                        display=f'"{w} {words[i+1]}"',
                        sublists=[Sublist(bid, SUB_BIGRAM)],
                        negative=neg, scored=False, qpos=qpos))
        else:
            w = m.group("word").lower()
            plan.groups.append(_word_group(w, qpos, neg))
            if not neg:
                plain_words.append((len(plan.groups) - 1, w))
            qpos += 1

    # attach adjacent-word bigrams as sublists of the left word's group
    # (setQueryTermInfo: bigram termlists ride the leading term's group)
    if bigrams:
        for (gi, w1), (gj, w2) in zip(plain_words, plain_words[1:]):
            if plan.groups[gi].qpos + 1 == plan.groups[gj].qpos:
                plan.groups[gi].sublists.append(Sublist(
                    ghash.bigram_id(w1, w2), SUB_BIGRAM, f"{w1} {w2}"))
    return plan


def _word_group(word: str, qpos: int, neg: bool) -> TermGroup:
    return TermGroup(
        display=word,
        sublists=[Sublist(ghash.term_id(word), SUB_ORIGINAL, word)],
        negative=neg, qpos=qpos)
