"""Command-line entry point — the reference's single ``gb`` binary.

Reference: ``main.cpp:395`` (``main2``) parses a command verb and either
runs a node (HTTP server + spider + autosave event loop) or performs a
one-shot operation (``main.cpp:1084-3887``: ``gb inject``, ``gb dump``,
``gb save``, ``gb spider`` …). Same shape here::

    python -m open_source_search_engine_tpu serve  --dir ./data --port 8000
    python -m open_source_search_engine_tpu inject --dir ./data URL [FILE]
    python -m open_source_search_engine_tpu search --dir ./data "query"
    python -m open_source_search_engine_tpu crawl  --dir ./data --seeds U
    python -m open_source_search_engine_tpu save   --dir ./data
    python -m open_source_search_engine_tpu bench

``serve`` is the long-running node: collections + HTTP API + autosave +
orderly signal shutdown (``Process.cpp:1299`` autosave clock,
``Process.cpp:1595`` save-on-signal). Everything else is a one-shot verb
against the same on-disk state — a restart is lossless (Rdb runs +
memtable ``saved/`` checkpoints).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path


def _add_dir(p: argparse.ArgumentParser) -> None:
    p.add_argument("--dir", default="./osse_data",
                   help="node data directory (default ./osse_data)")
    p.add_argument("--coll", default="main",
                   help="collection name (default main)")


def cmd_serve(args) -> int:
    from .control.process import Process
    from .serve.server import SearchHTTPServer
    from .spider.loop import SpiderLoop

    cluster = None
    if args.hosts:
        from .parallel.cluster import ClusterClient, HostsConf
        cluster = ClusterClient(HostsConf.load(args.hosts))
        if args.spider:
            print("--spider is ignored with --hosts: crawled pages "
                  "would land in the local collection while searches "
                  "go to the cluster", file=sys.stderr)
            args.spider = False
    srv = SearchHTTPServer(args.dir, host=args.host, port=args.port,
                           cluster=cluster)
    coll = srv.colldb.get(args.coll)
    spider = SpiderLoop(coll)
    srv.spider = spider
    proc = Process(autosave_minutes=args.autosave)
    proc.register(srv.colldb)
    proc.install_signal_handlers()
    proc.start_autosave()
    # quiet-hours full merges (DailyMerge.h:11); window from the
    # merge_quiet_hours parm, empty = disabled
    from .control.dailymerge import DailyMerge
    dm = DailyMerge(lambda: [srv.colldb.get(n)
                             for n in srv.colldb.names()], srv.conf)
    dm.start()
    proc.on_shutdown(dm.stop)
    srv.start()
    print(f"node serving on http://{args.host}:{srv.port} "
          f"(coll={args.coll}, dir={args.dir}) — Ctrl-C to save+stop",
          flush=True)
    try:
        while not proc.stopping:
            if args.spider:
                n = spider.crawl_step()
                if n == 0:
                    time.sleep(1.0)
            else:
                time.sleep(0.5)
    except KeyboardInterrupt:
        pass
    proc.shutdown()
    srv.stop()
    return 0


def cmd_proxy(args) -> int:
    """Query-routing front proxy (the ``gb proxy`` mode,
    ``main.cpp:1691`` / ``Proxy.cpp``): a stateless front end that fans
    /search out to the cluster's nodes and serves merged results — no
    local index, no spider; run several behind a load balancer."""
    import tempfile

    from .parallel.cluster import ClusterClient, HostsConf
    from .serve.server import SearchHTTPServer

    cluster = ClusterClient(HostsConf.load(args.hosts))
    srv = SearchHTTPServer(tempfile.mkdtemp(prefix="osse_proxy_"),
                           host=args.host, port=args.port,
                           cluster=cluster)
    srv.start()
    print(f"proxy on http://{args.host}:{srv.port} "
          f"-> cluster {args.hosts} — Ctrl-C to stop", flush=True)
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        pass
    srv.stop()
    cluster.close()
    return 0


def cmd_inject(args) -> int:
    from .build import docproc
    from .index.collection import CollectionDb

    colldb = CollectionDb(args.dir)
    coll = colldb.get(args.coll)
    content = (Path(args.file).read_text(encoding="utf-8", errors="replace")
               if args.file else sys.stdin.read())
    ml = docproc.index_document(coll, args.url, content)
    colldb.save_all()
    if ml is None:
        print(json.dumps({"injected": args.url, "error": "banned"}))
        return 1
    print(json.dumps({"injected": args.url, "docid": int(ml.docid),
                      "docs": coll.num_docs}))
    return 0


def cmd_search(args) -> int:
    from .index.collection import CollectionDb
    from .query import engine

    coll = CollectionDb(args.dir).get(args.coll, create=False)
    search = engine.search_device if args.device else engine.search
    res = search(coll, args.query, topk=args.k)
    out = {
        "query": res.query,
        "total": res.total_matches,
        "degraded": res.degraded,
        "results": [{"url": r.url, "title": r.title,
                     "score": round(r.score, 3), "docid": r.docid,
                     "snippet": r.snippet} for r in res.results],
    }
    if res.suggestion:
        out["suggestion"] = res.suggestion
    print(json.dumps(out, indent=None if args.json else 2))
    return 0


def cmd_crawl(args) -> int:
    from .index.collection import CollectionDb
    from .spider.loop import SpiderLoop

    from .spider.spiderdb import DurableSpiderScheduler

    colldb = CollectionDb(args.dir)
    coll = colldb.get(args.coll)
    sched = DurableSpiderScheduler(
        Path(args.dir) / "spider" / args.coll,
        banned=coll.tagdb.is_banned)
    loop = SpiderLoop(coll, scheduler=sched)
    for seed in (args.seeds or "").split(","):
        if seed.strip():
            loop.add_url(seed.strip())
    stats = loop.crawl(max_pages=args.max_pages)
    colldb.save_all()
    loop.sched.save()
    print(json.dumps({"fetched": stats.fetched, "indexed": stats.indexed,
                      "errors": stats.errors, "docs": coll.num_docs}))
    return 0


def cmd_node(args) -> int:
    """Run one shard-replica node process (the cluster's unit — the
    reference's per-host gb instance; RPC surface in parallel.cluster).
    A fleet supervisor spawns this verb once per (shard, replica) with
    the serialized cluster map (`--hosts`), the node's seat in it, and
    the chaos seed in OSSE_CHAOS — the child arms its own seams so a
    cross-process fault schedule replays deterministically."""
    import os
    import signal

    from .parallel.cluster import HostsConf, ShardNodeServer
    from .utils import chaos as chaos_mod

    chaos_mod.maybe_enable()
    cluster_map = HostsConf.load(args.hosts) if args.hosts else None
    node = ShardNodeServer(args.dir, host=args.host, port=args.port,
                           use_device=args.device, shard=args.shard,
                           replica=args.replica,
                           cluster_map=cluster_map)
    node.start()
    print(json.dumps({"node": f"{args.host}:{node.port}",
                      "docs": node.coll.num_docs,
                      "shard": args.shard, "replica": args.replica,
                      "pid": os.getpid()}), flush=True)
    stop = [False]

    def handler(signum, frame):
        stop[0] = True  # save happens below, under the writer lock

    signal.signal(signal.SIGTERM, handler)
    signal.signal(signal.SIGINT, handler)
    while not stop[0]:
        time.sleep(0.3)
    node.save()
    node.stop()
    return 0


def cmd_save(args) -> int:
    from .index.collection import CollectionDb

    colldb = CollectionDb(args.dir)
    for name in colldb.names():
        colldb.get(name)
    colldb.save_all()
    print(json.dumps({"saved": colldb.names() or [args.coll]}))
    return 0


def cmd_rebalance(args) -> int:
    """gb scale (main.cpp:2356): grow/shrink the shard grid."""
    from .control.rebalance import rebalance

    dst = rebalance(args.coll, args.dir, args.out,
                    old_n_shards=args.old_shards,
                    new_n_shards=args.new_shards,
                    n_replicas=args.replicas)
    print(json.dumps({"shards": dst.n_shards, "docs": dst.num_docs,
                      "out": args.out}))
    return 0


def cmd_repair(args) -> int:
    """Repair.h rebuild: posdb/clusterdb/linkdb from titledb."""
    from .control.rebalance import repair
    from .index.collection import CollectionDb

    colldb = CollectionDb(args.dir)
    coll = colldb.get(args.coll, create=False)
    n = repair(coll)
    print(json.dumps({"repaired": args.coll, "docs": n}))
    return 0


def cmd_bench(args) -> int:
    import runpy

    bench_py = Path(__file__).resolve().parent.parent / "bench.py"
    if not bench_py.exists():
        print("bench.py not found next to the package", file=sys.stderr)
        return 1
    runpy.run_path(str(bench_py), run_name="__main__")
    return 0


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m open_source_search_engine_tpu",
        description="TPU-native search engine node (the gb binary, "
                    "reference main.cpp)")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("serve", help="run a node: HTTP API + autosave")
    _add_dir(p)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--autosave", type=float, default=5.0,
                   help="autosave interval, minutes")
    p.add_argument("--spider", action="store_true",
                   help="also run the crawl loop in-process")
    p.add_argument("--hosts", help="hosts.conf: front a node cluster "
                   "instead of a local collection")
    p.set_defaults(fn=cmd_serve)

    p = sub.add_parser("inject", help="index one document")
    _add_dir(p)
    p.add_argument("url")
    p.add_argument("file", nargs="?", help="HTML file (default: stdin)")
    p.set_defaults(fn=cmd_inject)

    p = sub.add_parser("search", help="query a collection")
    _add_dir(p)
    p.add_argument("query")
    p.add_argument("-k", type=int, default=10)
    p.add_argument("--device", action="store_true",
                   help="use the HBM-resident index path")
    p.add_argument("--json", action="store_true", help="compact JSON")
    p.set_defaults(fn=cmd_search)

    p = sub.add_parser("crawl", help="run the spider")
    _add_dir(p)
    p.add_argument("--seeds", help="comma-separated seed URLs")
    p.add_argument("--max-pages", type=int, default=100)
    p.set_defaults(fn=cmd_crawl)

    p = sub.add_parser("node", help="run one shard-replica node (cluster)")
    p.add_argument("--dir", default="./osse_shard")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--device", action="store_true",
                   help="serve queries from the HBM-resident index")
    p.add_argument("--hosts", help="hosts.conf cluster map handed out "
                   "at spawn (Hostdb: every instance boots knowing "
                   "the topology)")
    p.add_argument("--shard", type=int, default=0,
                   help="this node's shard id in the map")
    p.add_argument("--replica", type=int, default=0,
                   help="this node's twin id within the shard")
    p.set_defaults(fn=cmd_node)

    p = sub.add_parser("proxy", help="query-routing front proxy "
                                     "(gb proxy mode): /search fans "
                                     "out to the cluster, no local "
                                     "index")
    p.add_argument("--hosts", required=True,
                   help="hosts.conf cluster topology")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    p.set_defaults(fn=cmd_proxy)

    p = sub.add_parser("save", help="checkpoint all collections")
    _add_dir(p)
    p.set_defaults(fn=cmd_save)

    p = sub.add_parser("rebalance",
                       help="re-shard a collection grid (gb scale)")
    _add_dir(p)
    p.add_argument("--out", required=True, help="new grid directory")
    p.add_argument("--old-shards", type=int, required=True)
    p.add_argument("--new-shards", type=int, required=True)
    p.add_argument("--replicas", type=int, default=1)
    p.set_defaults(fn=cmd_rebalance)

    p = sub.add_parser("repair",
                       help="rebuild index Rdbs from titledb")
    _add_dir(p)
    p.set_defaults(fn=cmd_repair)

    p = sub.add_parser("bench", help="run the repo benchmark")
    p.set_defaults(fn=cmd_bench)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
