"""Rdb-lite — the host-side LSM record store, one engine for every database.

Reference: the Rdb engine (SURVEY §2.2): ``Rdb.cpp`` (tree + per-collection
bases, ``Rdb::addList`` ``Rdb.cpp:2006``, ``Rdb::dumpTree`` ``Rdb.cpp:1172``),
``RdbTree``/``RdbBuckets`` (in-RAM memtable), ``RdbDump`` (tree→sorted file),
``RdbMerge``/``RdbBase::attemptMerge`` (``RdbBase.cpp:1400``, background
n-way file merge), ``RdbMap`` (per-file sparse page index), ``RdbList``
(sorted run with +/- tombstone annihilation, ``RdbList.cpp`` ``merge_r``),
and ``Msg5`` (read = merge memtable + all files, ``Msg5.h:50``).

TPU-first redesign rather than a port:

* Records are **columnar numpy arrays**, not byte-spliced lists — a sorted
  run is a structured key array (+ optional payload blob with offsets), so
  a termlist range-read is a zero-copy ``searchsorted`` slice that can be
  handed straight to the device packer.
* The memtable is a **sorted-buffer bucket** scheme like ``RdbBuckets``
  (the reference's faster replacement for RdbTree): appends accumulate
  unsorted, reads/sorts amortize via a dirty flag.
* Merge is vectorized: concatenate → stable sort by (key, recency) →
  newest-wins dedup → tombstone annihilation. The optional C++ core in
  ``native/`` does the same streaming for runs that don't fit comfortably
  in RAM.
* Runs are directories of ``.npy`` files loaded with ``mmap_mode='r'`` —
  the ``BigFile``+``RdbMap`` page-read path collapses into OS page-cache
  + searchsorted.

Keys are little-endian structured dtypes whose *reversed* field order is
the comparison order (matching ``key144_t::operator<`` — most-significant
word last in memory). Bit 0 of the least-significant field is the delbit:
1 = positive record, 0 = tombstone (``types.h`` key convention).
"""

from __future__ import annotations

import json
import shutil
from dataclasses import dataclass
from pathlib import Path

import numpy as np

from ..utils.chaos import g_chaos
from ..utils.lockcheck import make_rlock
from ..utils.log import get_logger
from ..utils.membudget import g_membudget
from ..utils.stats import g_stats

log = get_logger("rdb")


def _locked(fn):
    """Serialize a mutating Rdb method on the instance write lock.

    The reference serializes tree writes on the main event loop; here
    writers can be real threads (DailyMerge's forced sweep vs. the
    indexing path), so every mutation takes the per-Rdb RLock —
    reentrant because add→dump→attempt_merge nest."""
    import functools

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        with self._wlock:
            return fn(self, *args, **kwargs)
    return wrapper

#: keys per RdbMap "page" — the reference maps one key per 16KB disk page
#: (``RdbMap.h:64``); ours indexes every PAGE_KEYS keys of a run.
PAGE_KEYS = 4096

#: don't let global budget pressure thrash the memtable into confetti
#: runs: an early (pressure-triggered) dump needs at least this much
#: buffered before it fires.
_EARLY_DUMP_FLOOR = 1 << 20


class CorruptRunError(Exception):
    """A run failed its integrity checks (Msg5.h:50 'Rdb Error
    Correction' — the reference detects out-of-order keys / bad maps at
    read time and patches the list from a twin)."""


def _crc_chunks(arr: np.ndarray, chunk_rows: int = 1 << 22) -> int:
    """CRC32 of an array's bytes, streamed row-chunk-wise so an mmap'd
    multi-GB run never materializes whole in RAM."""
    import zlib
    crc = 0
    for i in range(0, len(arr), chunk_rows):
        crc = zlib.crc32(
            np.ascontiguousarray(arr[i:i + chunk_rows]).tobytes(), crc)
    return crc


def keys_sorted(keys: np.ndarray) -> bool:
    """Vectorized adjacent-pair sortedness check in key-compare order
    (reversed declared fields) — the reference's checkList_r symptom
    for corruption is exactly out-of-order keys."""
    if len(keys) < 2:
        return True
    violated = np.zeros(len(keys) - 1, bool)
    decided = np.zeros(len(keys) - 1, bool)
    for f in reversed(keys.dtype.names):  # most significant first
        a, b = keys[f][:-1], keys[f][1:]
        violated |= (~decided) & (a > b)
        decided |= a != b
    return not violated.any()


# ---------------------------------------------------------------------------
# key-array helpers (generic over structured key dtypes)
# ---------------------------------------------------------------------------

def key_sort_order(keys: np.ndarray) -> np.ndarray:
    """argsort in key-compare order: reversed declared fields, stable."""
    fields = keys.dtype.names
    return np.lexsort(tuple(keys[f] for f in fields))


def keys_as_tuple(keys: np.ndarray) -> tuple[np.ndarray, ...]:
    """(most significant … least significant) field views."""
    return tuple(keys[f] for f in reversed(keys.dtype.names))


def searchsorted_keys(sorted_keys: np.ndarray, probe: np.ndarray,
                      side: str = "left") -> np.ndarray:
    """Searchsorted over structured keys: hierarchical binary search.

    numpy can't searchsorted structured dtypes directly. Because the run
    is sorted by (most-significant field, …, least), each field is
    non-decreasing within the range where all more-significant fields are
    equal — so a probe narrows field-by-field with plain ``searchsorted``:
    O(fields · log n) per probe instead of re-sorting the run (the RdbMap
    page-index + key-compare walk of the reference collapses to this).
    """
    probe = np.atleast_1d(probe)
    n, m = len(sorted_keys), len(probe)
    out = np.empty(m, dtype=np.int64)
    if n == 0:
        out[:] = 0
        return out
    from .. import native
    if native.available():
        for i in range(m):
            out[i] = native.searchsorted(sorted_keys, probe[i:i + 1], side)
        return out
    fields = tuple(reversed(sorted_keys.dtype.names))  # most significant 1st
    cols = {f: sorted_keys[f] for f in fields}
    for i in range(m):
        p = probe[i]
        lo, hi = 0, n
        for j, f in enumerate(fields):
            sub = cols[f][lo:hi]
            v = p[f]
            left = int(np.searchsorted(sub, v, "left"))
            if j == len(fields) - 1:
                lo = lo + (left if side == "left"
                           else int(np.searchsorted(sub, v, "right")))
                break
            right = int(np.searchsorted(sub, v, "right"))
            lo, hi = lo + left, lo + right
            if lo == hi:  # value absent: insertion point found early
                break
        out[i] = lo
    return out


def strip_delbit(keys: np.ndarray) -> np.ndarray:
    """Copy of keys with the delbit (bit 0 of least-significant field)
    cleared — the 'same record' identity used by annihilation."""
    out = keys.copy()
    f0 = keys.dtype.names[0]
    out[f0] = out[f0] & ~np.array(1, dtype=keys.dtype[f0])
    return out


def delbits(keys: np.ndarray) -> np.ndarray:
    f0 = keys.dtype.names[0]
    return (keys[f0] & np.array(1, dtype=keys.dtype[f0])).astype(bool)


# ---------------------------------------------------------------------------
# sorted record batches
# ---------------------------------------------------------------------------

@dataclass
class RecordBatch:
    """A sorted run of records: keys + optional var-length payloads.

    The RdbList equivalent — but columnar: ``keys`` is a structured array,
    ``data``/``offsets`` hold payloads (``data[offsets[i]:offsets[i+1]]`` is
    record i's blob; both None for dataless dbs like posdb).
    """

    keys: np.ndarray
    offsets: np.ndarray | None = None  # int64 [n+1]
    data: np.ndarray | None = None     # uint8 blob

    def __len__(self) -> int:
        return len(self.keys)

    @property
    def has_data(self) -> bool:
        return self.offsets is not None

    def payload(self, i: int) -> bytes:
        assert self.offsets is not None and self.data is not None
        return bytes(self.data[self.offsets[i]:self.offsets[i + 1]])

    def payloads(self) -> list[bytes]:
        return [self.payload(i) for i in range(len(self))]

    @staticmethod
    def from_records(keys: np.ndarray, blobs: list[bytes] | None = None,
                     presorted: bool = False) -> "RecordBatch":
        if blobs is not None:
            assert len(blobs) == len(keys)
        if not presorted:
            order = key_sort_order(keys)
            keys = keys[order]
            if blobs is not None:
                blobs = [blobs[i] for i in order]
        if blobs is None:
            return RecordBatch(keys)
        offsets = np.zeros(len(blobs) + 1, dtype=np.int64)
        np.cumsum([len(b) for b in blobs], out=offsets[1:])
        data = np.frombuffer(b"".join(blobs), dtype=np.uint8)
        return RecordBatch(keys, offsets, data)

    def slice(self, lo: int, hi: int) -> "RecordBatch":
        if not self.has_data:
            return RecordBatch(self.keys[lo:hi])
        offs = self.offsets[lo:hi + 1]
        return RecordBatch(
            self.keys[lo:hi],
            (offs - offs[0]).astype(np.int64),
            self.data[offs[0]:offs[-1]],
        )

    def range(self, start_key: np.ndarray, end_key: np.ndarray) -> "RecordBatch":
        """Records with start_key <= key <= end_key (RdbMap+RdbScan read)."""
        lo = int(searchsorted_keys(self.keys, start_key.reshape(1), "left")[0])
        hi = int(searchsorted_keys(self.keys, end_key.reshape(1), "right")[0])
        return self.slice(lo, hi)


def _dedup_newest(all_keys: np.ndarray, recency: np.ndarray,
                  keep_tombstones: bool) -> np.ndarray:
    """Indices of surviving records: for each key-sans-delbit group the
    highest-recency record wins; surviving tombstones optionally dropped.
    Result indices are in key-sorted order."""
    ident = strip_delbit(all_keys)
    # sort by (key-sans-delbit asc, recency desc) → first of each group is
    # the newest version of that record
    order = np.lexsort((-recency,) + tuple(ident[f] for f in ident.dtype.names))
    ident_sorted = ident[order]
    first_of_group = np.ones(len(order), dtype=bool)
    if len(order) > 1:
        same_as_prev = np.ones(len(order) - 1, dtype=bool)
        for f in ident.dtype.names:
            same_as_prev &= ident_sorted[f][1:] == ident_sorted[f][:-1]
        first_of_group[1:] = ~same_as_prev
    keep = order[first_of_group]
    if not keep_tombstones:
        keep = keep[delbits(all_keys[keep])]
    return keep


def merge_batches(batches: list[RecordBatch],
                  keep_tombstones: bool = False) -> RecordBatch:
    """N-way merge with newest-wins dedup and +/- annihilation.

    Reference semantics (``RdbList.cpp`` ``indexMerge_r``/``merge_r``; Msg5
    final merge): sources are ordered oldest→newest; for records whose keys
    are equal ignoring the delbit, the newest survives; a surviving
    tombstone (delbit 0) annihilates the record — it is dropped from the
    output unless ``keep_tombstones`` (intermediate file merges keep the
    tombstone so it can annihilate matches in files not part of the merge;
    final reads drop them — ``RdbMerge`` vs ``Msg5`` behavior).
    """
    nonempty = [b for b in batches if len(b)]
    if not nonempty:
        if batches:  # preserve the caller's key dtype / data-ness
            return batches[0]
        return RecordBatch(np.empty(0, dtype=np.dtype([("n0", "<u2")])))
    if len(nonempty) == 1 and bool(delbits(nonempty[0].keys).all()):
        return nonempty[0]  # sorted single all-positive source: done
    batches = nonempty
    has_data = batches[0].has_data

    if not has_data:
        # dataless merge rides the native C++ core when built (RdbMerge's
        # merge_r path); identical semantics to the numpy path below
        from .. import native
        merged = native.merge_runs([b.keys for b in batches],
                                   keep_tombstones) \
            if native.available() else None
        if merged is not None:
            return RecordBatch(merged)

    all_keys = np.concatenate([b.keys for b in batches])
    recency = np.concatenate(
        [np.full(len(b), i, dtype=np.int64) for i, b in enumerate(batches)]
    )
    keep = _dedup_newest(all_keys, recency, keep_tombstones)
    kept_keys = all_keys[keep]

    if not has_data:
        return RecordBatch(kept_keys)

    # gather payloads for kept records
    src_idx = np.empty(len(all_keys), dtype=np.int64)
    rec_idx = np.empty(len(all_keys), dtype=np.int64)
    pos0 = 0
    for i, b in enumerate(batches):
        src_idx[pos0:pos0 + len(b)] = i
        rec_idx[pos0:pos0 + len(b)] = np.arange(len(b))
        pos0 += len(b)
    blobs = [batches[src_idx[j]].payload(int(rec_idx[j])) for j in keep]
    return RecordBatch.from_records(kept_keys, blobs, presorted=True)


# ---------------------------------------------------------------------------
# on-disk immutable runs (BigFile + RdbMap + RdbDump equivalent)
# ---------------------------------------------------------------------------

class Run:
    """One immutable sorted run on disk: a directory of mmap'd .npy files.

    ``keys.npy`` (+ ``offsets.npy``/``data.npy`` for payload dbs) and
    ``meta.json`` with the dtype and a sparse page index (first key per
    PAGE_KEYS records — the ``RdbMap`` equivalent, used only as metadata
    now that reads go through mmap+searchsorted).
    """

    def __init__(self, path: Path, verify: bool = True):
        self.path = Path(path)
        try:
            self.meta = json.loads((self.path / "meta.json").read_text())
            self.keys = np.load(self.path / "keys.npy", mmap_mode="r")
            self.offsets = None
            self.data = None
            if (self.path / "offsets.npy").exists():
                self.offsets = np.load(self.path / "offsets.npy",
                                       mmap_mode="r")
                self.data = np.load(self.path / "data.npy", mmap_mode="r")
        except Exception as e:  # torn write, missing file, bad header
            raise CorruptRunError(f"{path}: unreadable ({e})") from e
        if verify:
            self.verify()

    def verify(self) -> None:
        """Integrity check (the Msg5/RdbMap corruption detection):
        record count, key order, offset monotonicity, and — when the
        run was written with them — whole-file CRCs streamed in bounded
        chunks (no 2×-file-size allocation). Raises
        :class:`CorruptRunError`; the Rdb quarantines such runs and a
        twin patches them back (``developer.html`` 'Rdb Error
        Correction')."""
        if self.meta.get("nrecs") != len(self.keys):
            raise CorruptRunError(
                f"{self.path}: nrecs {self.meta.get('nrecs')} != "
                f"{len(self.keys)}")
        if not keys_sorted(self.keys):
            raise CorruptRunError(f"{self.path}: keys out of order")
        if self.offsets is not None:
            offs = np.asarray(self.offsets)
            if len(offs) != len(self.keys) + 1 or offs[0] != 0 \
                    or (np.diff(offs) < 0).any() \
                    or offs[-1] > len(self.data):
                raise CorruptRunError(f"{self.path}: bad offsets")
        crc = self.meta.get("keys_crc")
        if crc is not None and _crc_chunks(self.keys) != crc:
            raise CorruptRunError(f"{self.path}: keys CRC mismatch")
        dcrc = self.meta.get("data_crc")
        if dcrc is not None and self.data is not None \
                and _crc_chunks(self.data) != dcrc:
            raise CorruptRunError(f"{self.path}: data CRC mismatch")

    def __len__(self) -> int:
        return len(self.keys)

    def batch(self) -> RecordBatch:
        return RecordBatch(self.keys, self.offsets, self.data)

    @staticmethod
    def write(path: Path, batch: RecordBatch) -> "Run":
        """RdbDump: persist a sorted batch as an immutable run."""
        path = Path(path)
        tmp = path.with_name(path.name + ".tmp")
        if tmp.exists():
            shutil.rmtree(tmp)
        tmp.mkdir(parents=True)
        keys_c = np.ascontiguousarray(batch.keys)
        np.save(tmp / "keys.npy", keys_c)
        data_crc = None
        if batch.has_data:
            np.save(tmp / "offsets.npy", batch.offsets)
            np.save(tmp / "data.npy", batch.data)
            data_crc = _crc_chunks(batch.data)
        page_firsts = [
            [int(batch.keys[i][f]) for f in batch.keys.dtype.names]
            for i in range(0, len(batch), PAGE_KEYS)
        ]
        (tmp / "meta.json").write_text(json.dumps({
            "nrecs": len(batch),
            "dtype": [[n, str(batch.keys.dtype[n])] for n in batch.keys.dtype.names],
            "has_data": batch.has_data,
            "page_keys": PAGE_KEYS,
            "page_first_keys": page_firsts,
            # whole-file CRCs (the RdbMap's integrity role): verified at
            # load; a mismatch quarantines the run for twin patching
            "keys_crc": _crc_chunks(keys_c),
            "data_crc": data_crc,
        }))
        tmp.rename(path)  # atomic publish
        return Run(path, verify=False)  # just written from RAM


# ---------------------------------------------------------------------------
# memtable (RdbBuckets equivalent)
# ---------------------------------------------------------------------------

class MemTable:
    """Append-mostly in-RAM buffer of records; sorts lazily on read.

    Reference ``RdbBuckets.h:87`` — flat sorted buckets replaced RdbTree
    for posdb because appends dominate. Same idea: O(1) appends into a
    pending list, one vectorized sort when a read needs order.

    Internally the sorted state is **size-tiered segments** (each ≥ the
    next newer one; adjacent segments merge when the invariant breaks),
    so interleaved add/read workloads — every document index does point
    reads — cost amortized O(n log n) instead of the O(n²) a single
    merged buffer costs when every read folds the pending tail in.
    Range reads merge only the per-segment range slices."""

    def __init__(self, key_dtype: np.dtype, has_data: bool):
        self.key_dtype = key_dtype
        self.has_data = has_data
        self._pending_keys: list[np.ndarray] = []
        self._pending_blobs: list[bytes] = []
        self._segments: list[RecordBatch] = []  # oldest → newest
        self.nbytes = 0

    def __len__(self) -> int:
        n = sum(len(k) for k in self._pending_keys)
        return n + sum(len(s) for s in self._segments)

    def add(self, keys: np.ndarray, blobs: list[bytes] | None = None) -> None:
        keys = np.atleast_1d(keys).astype(self.key_dtype, copy=False)
        if self.has_data:
            assert blobs is not None and len(blobs) == len(keys)
            self._pending_blobs.extend(blobs)
            self.nbytes += sum(len(b) for b in blobs)
        self._pending_keys.append(keys)
        self.nbytes += keys.nbytes

    def _seal(self) -> None:
        """Sort the pending tail into a new segment, then restore the
        size-tier invariant by merging newest-first (newest-wins within
        the memtable: later segments are newer; tombstones are kept so
        they still annihilate records in on-disk runs)."""
        if self._pending_keys:
            keys = np.concatenate(self._pending_keys)
            blobs = self._pending_blobs if self.has_data else None
            # newest-wins within the pending stream itself (the RdbTree
            # replaces a node when an equal-sans-delbit key is re-added)
            keep = _dedup_newest(keys, np.arange(len(keys), dtype=np.int64),
                                 keep_tombstones=True)
            self._segments.append(RecordBatch.from_records(
                keys[keep],
                [blobs[int(i)] for i in keep] if blobs is not None else None,
                presorted=True))
            self._pending_keys = []
            self._pending_blobs = []
        while (len(self._segments) >= 2
               and len(self._segments[-2]) < 2 * len(self._segments[-1])):
            newer = self._segments.pop()
            older = self._segments.pop()
            self._segments.append(merge_batches([older, newer],
                                                keep_tombstones=True))

    def range(self, start_key: np.ndarray, end_key: np.ndarray
              ) -> RecordBatch:
        """Merged range read over the segments (newest-wins applied)."""
        self._seal()
        return merge_batches(
            [s.range(start_key, end_key) for s in self._segments],
            keep_tombstones=True)

    def batch(self) -> RecordBatch:
        """Sorted view of everything in RAM (newest-wins within memtable)."""
        self._seal()
        if len(self._segments) > 1:
            self._segments = [merge_batches(self._segments,
                                            keep_tombstones=True)]
        if not self._segments:
            empty = np.empty(0, dtype=self.key_dtype)
            self._segments = [RecordBatch.from_records(
                empty, [] if self.has_data else None)]
        return self._segments[0]

    def clear(self) -> None:
        self._pending_keys = []
        self._pending_blobs = []
        self._segments = []
        self.nbytes = 0


# ---------------------------------------------------------------------------
# the Rdb itself (per-collection base, like RdbBase)
# ---------------------------------------------------------------------------

class Rdb:
    """One named database for one collection: memtable + immutable runs.

    API mirrors the reference verbs: ``add`` (``Rdb::addList``), ``dump``
    (``Rdb::dumpTree``), ``attempt_merge`` (``RdbBase::attemptMerge``),
    ``get_list`` (``Msg5::getList`` — merged memtable+runs range read),
    ``save``/``load`` (RdbTree ``-saved.dat`` checkpoint).
    """

    def __init__(self, name: str, directory: str | Path,
                 key_dtype: np.dtype, has_data: bool = False,
                 max_memtable_bytes: int = 64 << 20,
                 max_runs: int = 8, journal: bool = True):
        self.name = name
        self.dir = Path(directory) / name
        self.dir.mkdir(parents=True, exist_ok=True)
        self.key_dtype = key_dtype
        self.has_data = has_data
        self.max_memtable_bytes = max_memtable_bytes
        self.max_runs = max_runs
        #: write lock: mutations may come from the indexing thread AND
        #: the DailyMerge/autosave threads concurrently
        self._wlock = make_rlock(f"rdb.{name}")
        self.mem = MemTable(key_dtype, has_data)
        self.runs: list[Run] = []
        #: names of runs quarantined at load (corrupt — healed by
        #: :meth:`resync` / twin patching; surfaced on /admin/stats)
        self.quarantined: list[str] = []
        self._next_run_id = 0
        #: bumped on every mutation; device-resident mirrors compare it
        #: to know when to repack (the Rdb dump/merge → repack cycle)
        self.version = 0
        #: write-ahead journal (Msg4 addsinprogress semantics,
        #: ``Msg4.cpp:86,115``): every buffered add/delete appends here
        #: BEFORE touching the memtable and replays on open, so a
        #: kill -9 between dumps loses no acknowledged record. The
        #: journal truncates whenever the memtable persists (dump/save).
        #: Callers with their own journaling (spiderdb) pass False;
        #: OSSE_NO_JOURNAL=1 disables globally for bulk rebuilds where
        #: the source data is itself durable (repair/rebalance — the
        #: ~2× write amplification buys nothing there).
        import os as _os
        self.journal_enabled = journal and \
            _os.environ.get("OSSE_NO_JOURNAL") != "1"
        self._journal_path = self.dir / "addsinprogress.bin"
        self._journal_f = None
        if not self.journal_enabled and self._journal_path.exists() \
                and self._journal_path.stat().st_size > 0:
            # this open won't journal OR truncate-on-dump, so records
            # added now are invisible to the file; a later
            # journal-enabled open would replay the stale batches over
            # newer state (resurrecting tombstoned records). Truncate
            # up front — a journal-less open declares the source data
            # durable, so the stale tail buys nothing.
            log.warning(
                "%s: journaling disabled but %s holds %d stale bytes — "
                "truncating so a later journal-enabled open cannot "
                "replay them", self.name, self._journal_path.name,
                self._journal_path.stat().st_size)
            self._journal_path.write_bytes(b"")
        self._load_existing_runs()

    # --- writes ---

    @_locked
    def add(self, keys: np.ndarray, blobs: list[bytes] | None = None) -> None:
        """Add records; auto-dump when the memtable exceeds budget
        (reference dumps at 90% full, ``Rdb.cpp:1172``). The write
        journals BEFORE it applies (Msg4 addsinprogress)."""
        self._journal_append(keys, blobs)
        self.mem.add(keys, blobs)
        self.version += 1
        g_membudget.set_gauge("memtable", str(self.dir), self.mem.nbytes)
        # dump at the per-tree bound (reference 90%-full trigger) OR
        # early when the PROCESS budget is exhausted — the "flush the
        # memtable" degradation arm of the g_mem gate
        if self.mem.nbytes >= self.max_memtable_bytes or (
                self.mem.nbytes >= _EARLY_DUMP_FLOOR
                and not g_membudget.would_fit(0)):
            self.dump()

    @_locked
    def delete(self, keys: np.ndarray) -> None:
        """Add tombstones for these keys (delbit cleared)."""
        neg = strip_delbit(np.atleast_1d(keys).astype(self.key_dtype, copy=False))
        blobs = [b""] * len(neg) if self.has_data else None
        self._journal_append(neg, blobs)
        self.mem.add(neg, blobs)
        self.version += 1
        g_membudget.set_gauge("memtable", str(self.dir), self.mem.nbytes)

    @_locked
    def wipe(self) -> None:
        """Drop ALL state (memtable + runs) — the Repair rebuild's
        'destroy the secondary instance' step (Repair.h:20)."""
        self.mem.clear()
        g_membudget.set_gauge("memtable", str(self.dir), 0)
        for r in self.runs:
            shutil.rmtree(r.path, ignore_errors=True)
        self.runs = []
        saved = self.dir / "saved"
        if saved.exists():
            shutil.rmtree(saved)
        self._journal_truncate()
        self.version += 1

    @_locked
    def dump(self) -> Run | None:
        """Memtable → new immutable run (RdbDump)."""
        batch = self.mem.batch()
        if not len(batch):
            return None
        run = Run.write(self.dir / f"run_{self._next_run_id:06d}", batch)
        self._next_run_id += 1
        self.runs.append(run)
        self.mem.clear()
        g_membudget.set_gauge("memtable", str(self.dir), 0)
        self.version += 1  # run set moved: device mirrors must re-base
        # the memtable checkpoint is now stale — drop it so a restart can't
        # resurrect records that live in the freshly dumped run
        saved = self.dir / "saved"
        if saved.exists():
            shutil.rmtree(saved)
        self._journal_truncate()  # records now live in the run
        log.debug("%s: dumped run %s (%d recs)", self.name, run.path.name, len(run))
        if len(self.runs) > self.max_runs:
            self.attempt_merge()
        return run

    @_locked
    def attempt_merge(self, force: bool = False) -> None:
        """Merge runs down to bound file count (RdbBase::attemptMerge,
        ``RdbBase.cpp:1400``).

        Write-amplification policy: merge only the NEWEST suffix of
        runs, sized just enough to bring the count under ``max_runs`` —
        the LSM-tiered shape where fresh small dumps fold together
        while the big old base run is left untouched (the reference
        likewise picks the file subset minimizing resort cost instead
        of always rewriting everything). ``force=True`` merges the full
        set (the DailyMerge/manual compaction). Tombstones are kept
        unless the merge includes the oldest run, exactly the
        reference's "don't drop negatives unless merging file 0"."""
        if len(self.runs) <= 1:
            return
        if force:
            start = 0
        elif len(self.runs) > self.max_runs:
            # smallest suffix that restores the run-count bound
            # (len > max_runs ⇒ this keeps exactly max_runs-1 intact)
            start = self.max_runs - 1
        else:
            start = len(self.runs) - 2  # opportunistic: fold newest two
        # budget gate (the g_mem allocation canary): a merge
        # materializes its inputs plus the merged output, ~2× the input
        # bytes. On refusal SHRINK the suffix — merge fewer, newer runs
        # — and if even the smallest 2-run merge is over budget, DEFER:
        # the next dump retries, and an unmerged index is slow but
        # correct while an OOM-killed process is neither.
        start0, est = start, 0
        while True:
            est = 2 * sum(
                int(r.keys.nbytes)
                + (int(r.data.nbytes) if r.data is not None else 0)
                for r in self.runs[start:])
            if g_membudget.reserve("merge", est):
                break
            if start >= len(self.runs) - 2:
                log.warning(
                    "%s: merge deferred — even the 2-run merge "
                    "(%d MB working set) is over budget",
                    self.name, est >> 20)
                return
            start += 1
        if start != start0:
            log.warning("%s: merge shrunk to the newest %d runs "
                        "(budget pressure)", self.name,
                        len(self.runs) - start)
        try:
            suffix = self.runs[start:]
            includes_oldest = start == 0
            merged = merge_batches(
                [r.batch() for r in suffix],
                keep_tombstones=not includes_oldest,
            )
            old = suffix
            # the merged run REPLACES the suffix in recency order: derive a
            # name that sorts right after the surviving prefix
            # name keeps only the first NUMERIC id so repeated merge cycles
            # don't grow the filename; the _m counter keeps recency order
            base_id = int(old[0].path.name.split("_")[1])
            run = Run.write(
                self.dir / f"run_{base_id:06d}_m{self._next_run_id:06d}",
                merged)
            self._next_run_id += 1
            self.runs = self.runs[:start] + [run]
            self.version += 1  # run set moved: device mirrors must re-base
            for r in old:
                shutil.rmtree(r.path)
            log.debug("%s: merged %d newest runs -> %s (%d recs, %d kept)",
                      self.name, len(old), run.path.name, len(run), start)
        finally:
            g_membudget.release("merge", est)

    @_locked
    def scrub(self) -> list[str]:
        """Re-verify every loaded run NOW; quarantine failures (the
        admin-triggered integrity sweep — load-time verification only
        catches corruption present at startup). Returns quarantined
        run names; the caller heals them from a twin."""
        bad: list[str] = []
        keep: list[Run] = []
        for r in self.runs:
            try:
                r.verify()
                keep.append(r)
            except CorruptRunError as e:
                q = r.path.with_name(r.path.name + ".corrupt")
                if q.exists():
                    shutil.rmtree(q)
                r.path.rename(q)
                self.quarantined.append(q.name)
                bad.append(q.name)
                g_stats.count("rdb.corrupt_quarantined")
                log.error("%s: QUARANTINED corrupt run: %s",
                          self.name, e)
        if bad:
            self.runs = keep
            self.version += 1
        return bad

    @_locked
    def replace_with(self, batch: RecordBatch) -> None:
        """Wipe and reload from one merged batch — the twin-patch
        receive side (Msg5 error correction's 'get the list from the
        twin and use it instead')."""
        self.wipe()
        self.quarantined = []
        for p in self.dir.glob("run_*.corrupt"):
            shutil.rmtree(p, ignore_errors=True)
        if len(batch):
            self.mem.add(batch.keys.copy(),
                         batch.payloads() if self.has_data else None)
            self.dump()
        self.version += 1

    # --- reads (Msg5 semantics) ---

    def get_list(self, start_key: np.ndarray, end_key: np.ndarray) -> RecordBatch:
        """Merged range read across runs + memtable, tombstones applied."""
        if g_chaos.enabled:
            g_chaos.rdb_fault(self)
        sources = [r.batch().range(start_key, end_key) for r in self.runs]
        sources.append(self.mem.range(start_key, end_key))
        return merge_batches(sources)

    def get_all(self) -> RecordBatch:
        sources = [r.batch() for r in self.runs]
        sources.append(self.mem.batch())
        return merge_batches(sources)

    # --- checkpoint (Process::saveRdbTrees equivalent) ---

    @_locked
    def save(self) -> None:
        """Persist the memtable so a restart is lossless (``-saved.dat``).

        Publish-then-swap: the new checkpoint is fully written to
        ``saved.new`` BEFORE the old one is removed, so no crash window
        exists where neither checkpoint nor journal holds the records
        (load_saved picks up a stranded ``saved.new``)."""
        batch = self.mem.batch()
        saved = self.dir / "saved"
        newp = self.dir / "saved.new"
        if newp.exists():
            shutil.rmtree(newp)
        if len(batch):
            Run.write(newp, batch)
            if saved.exists():
                shutil.rmtree(saved)
            newp.rename(saved)
        elif saved.exists():
            shutil.rmtree(saved)
        self._journal_truncate()  # checkpoint covers the journal now

    def load_saved(self) -> None:
        saved = self.dir / "saved"
        newp = self.dir / "saved.new"
        if not saved.exists() and newp.exists():
            # crash between publishing saved.new and the swap: the new
            # checkpoint is complete (Run.write is atomic) — adopt it
            try:
                Run(newp)
                newp.rename(saved)
            except CorruptRunError:
                shutil.rmtree(newp)  # torn write: journal still covers
        if saved.exists():
            b = Run(saved).batch()
            self.mem.add(b.keys.copy(),
                         b.payloads() if self.has_data else None)

    def _load_existing_runs(self) -> None:
        for p in sorted(self.dir.glob("run_*")):
            if not p.is_dir() or p.name.endswith(".tmp") \
                    or p.name.endswith(".corrupt"):
                continue
            parts = p.name.split("_")
            self._next_run_id = max(self._next_run_id,
                                    int(parts[1]) + 1)
            if len(parts) > 2 and parts[2].startswith("m"):
                # merged runs carry the id counter in the _m suffix:
                # it must survive restarts or the next merge reuses
                # a live name
                self._next_run_id = max(self._next_run_id,
                                        int(parts[2][1:]) + 1)
            try:
                self.runs.append(Run(p))
            except CorruptRunError as e:
                # quarantine, serve what remains, heal from a twin
                # (Msg5 error correction; the reference likewise drops
                # unreadable lists and patches from the twin host)
                q = p.with_name(p.name + ".corrupt")
                if q.exists():
                    shutil.rmtree(q)
                p.rename(q)
                self.quarantined.append(q.name)
                g_stats.count("rdb.corrupt_quarantined")
                log.error("%s: QUARANTINED corrupt run: %s",
                          self.name, e)
        self.load_saved()
        if self.journal_enabled:
            self._replay_journal()

    # --- write-ahead journal (Msg4 addsinprogress) ---------------------

    def _journal_append(self, keys: np.ndarray,
                        blobs: list[bytes] | None) -> None:
        """One fsync-free append per add batch: header + key image +
        blob table, CRC-protected so a torn tail is detected at replay.
        flush() alone survives kill -9 (the OS page cache outlives the
        process); set OSSE_JOURNAL_FSYNC=1 for power-failure durability
        at ~1 ms/batch."""
        if not self.journal_enabled:
            return
        import os as _os
        import struct
        import zlib as _zlib
        if self._journal_f is None:
            self._journal_f = open(self._journal_path, "ab")  # noqa: SIM115
        kb = np.ascontiguousarray(keys).tobytes()
        if self.has_data:
            blobs = blobs if blobs is not None else [b""] * len(keys)
            lens = np.array([len(b) for b in blobs], np.uint32)
            body = kb + lens.tobytes() + b"".join(blobs)
        else:
            body = kb
        hdr = struct.pack("<IIQ", len(keys),
                          _zlib.crc32(body) & 0xFFFFFFFF, len(body))
        self._journal_f.write(hdr + body)
        self._journal_f.flush()
        if _os.environ.get("OSSE_JOURNAL_FSYNC") == "1":
            _os.fsync(self._journal_f.fileno())

    def _replay_journal(self) -> None:
        """Re-apply journaled batches on open (records added after the
        last dump/save); a torn or corrupt tail batch stops the replay
        — exactly the records that were never acknowledged."""
        if not self._journal_path.exists():
            return
        import struct
        import zlib as _zlib
        data = self._journal_path.read_bytes()
        ks = self.key_dtype.itemsize
        off, n_rec = 0, 0
        while off + 16 <= len(data):
            n, crc, blen = struct.unpack_from("<IIQ", data, off)
            off += 16
            if off + blen > len(data) or \
                    (_zlib.crc32(data[off:off + blen]) & 0xFFFFFFFF) \
                    != crc:
                log.warning("%s: journal torn at byte %d — replay "
                            "stops (unacknowledged tail)", self.name,
                            off - 16)
                # truncate to the valid prefix: appending after the
                # torn batch would strand every later (CRC-valid)
                # batch behind it at the NEXT replay
                import os as _os
                with open(self._journal_path, "r+b") as jf:
                    jf.truncate(off - 16)
                break
            body = data[off:off + blen]
            off += blen
            keys = np.frombuffer(body[: n * ks],
                                 dtype=self.key_dtype).copy()
            blobs = None
            if self.has_data:
                lens = np.frombuffer(body[n * ks: n * ks + 4 * n],
                                     np.uint32)
                p = n * ks + 4 * n
                blobs = []
                for ln in lens:
                    blobs.append(body[p: p + int(ln)])
                    p += int(ln)
            self.mem.add(keys, blobs)
            n_rec += int(n)
        if n_rec:
            self.version += 1
            g_membudget.set_gauge("memtable", str(self.dir),
                                  self.mem.nbytes)
            log.info("%s: replayed %d journaled records "
                     "(addsinprogress)", self.name, n_rec)
            if self.mem.nbytes >= self.max_memtable_bytes:
                self.dump()

    def _journal_truncate(self) -> None:
        if not self.journal_enabled:
            return
        if self._journal_f is not None:
            self._journal_f.close()
            self._journal_f = None
        if self._journal_path.exists():
            self._journal_path.unlink()
