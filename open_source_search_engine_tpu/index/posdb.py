"""Posdb key codec — the positional-index record format, bit-exact to the
reference's 18-byte key (``Posdb.h:4-50`` layout comment, field setters
``Posdb.h:145-235``, ``types.h:431`` ``key144_t``).

An 18-byte key is, in memory (little-endian), ``n0:uint16, n1:uint64,
n2:uint64``; comparison order is ``(n2, n1, n0)`` (``key144_t::operator<``).
Fields:

===========  ====  =========================================================
field        bits  position
===========  ====  =========================================================
termId        48   n2[16:64]
docId         38   n2[0:16] = docId>>22,  n1[42:64] = docId&0x3fffff
siterank       4   n1[37:41]          (bit 41 is the spare '0' bit)
langId(lo5)    5   n1[32:37]          (6th bit lives in n0 bit 3, 'L')
wordpos       18   n1[14:32]
hashgroup      4   n1[10:14]          (HASHGROUP_* below)
wordspamrank   4   n1[6:10]
diversityrank  4   n1[2:6]
synonym form   2   n1[0:2]            (0=orig 1=conjugate 2=synonym 3=hyponym)
densityrank    5   n0[11:16]
outlink bit    1   n0[10]             ('b' — in outlink text)
alignment      1   n0[9]              (always 1 on full keys)
shardByTermId  1   n0[8]              ('N' — nosplit/checksum terms)
multiplier     4   n0[4:8]
langId(hi)     1   n0[3]
compression    2   n0[1:3]            (00 for full 18-byte keys)
delbit         1   n0[0]              (1 = positive, 0 = delete/tombstone)
===========  ====  =========================================================

All codec ops are vectorized numpy over a structured array whose byte image
is exactly the reference's on-disk key — so parity against the reference's
own lists is checkable byte-for-byte.
"""

from __future__ import annotations

import numpy as np

from ..utils.ghash import hash64_array

# field maxima (Posdb.h:62-71)
MAXSITERANK = 0x0F
MAXLANGID = 0x3F
MAXWORDPOS = 0x0003FFFF
MAXDENSITYRANK = 0x1F
MAXWORDSPAMRANK = 0x0F
MAXDIVERSITYRANK = 0x0F
MAXHASHGROUP = 0x0F
MAXMULTIPLIER = 0x0F

# hashgroups (Posdb.h:74-85)
HASHGROUP_BODY = 0
HASHGROUP_TITLE = 1
HASHGROUP_HEADING = 2
HASHGROUP_INLIST = 3
HASHGROUP_INMETATAG = 4
HASHGROUP_INLINKTEXT = 5
HASHGROUP_INTAG = 6
HASHGROUP_NEIGHBORHOOD = 7
HASHGROUP_INTERNALINLINKTEXT = 8
HASHGROUP_INURL = 9
HASHGROUP_INMENU = 10
HASHGROUP_END = 11

# synonym forms (Posdb.h:21-25)
FORM_ORIGINAL = 0
FORM_CONJUGATE = 1
FORM_SYNONYM = 2
FORM_HYPONYM = 3

DOCID_BITS = 38
DOCID_MASK = (1 << DOCID_BITS) - 1
TERMID_BITS = 48
TERMID_MASK = (1 << TERMID_BITS) - 1

KEY_SIZE = 18

#: structured dtype whose byte image == the reference's little-endian key144
KEY_DTYPE = np.dtype([("n0", "<u2"), ("n1", "<u8"), ("n2", "<u8")], align=False)
assert KEY_DTYPE.itemsize == KEY_SIZE

FIELDS = (
    "termid", "docid", "siterank", "langid", "wordpos", "hashgroup",
    "wordspamrank", "diversityrank", "synform", "densityrank",
    "outlink", "shardbytermid", "multiplier", "delbit",
)


def _u64(x) -> np.ndarray:
    return np.asarray(x, dtype=np.uint64)


def pack(
    termid,
    docid,
    wordpos=0,
    densityrank=0,
    diversityrank=MAXDIVERSITYRANK,
    wordspamrank=MAXWORDSPAMRANK,
    siterank=0,
    hashgroup=HASHGROUP_BODY,
    langid=0,
    multiplier=0,
    synform=FORM_ORIGINAL,
    outlink=0,
    shardbytermid=0,
    delbit=1,
) -> np.ndarray:
    """Vectorized key pack (reference ``Posdb::makeKey``). All args broadcast;
    returns a structured array of :data:`KEY_DTYPE`."""
    termid = _u64(termid) & np.uint64(TERMID_MASK)
    docid = _u64(docid) & np.uint64(DOCID_MASK)
    wordpos, densityrank, diversityrank, wordspamrank = (
        _u64(wordpos), _u64(densityrank), _u64(diversityrank),
        _u64(wordspamrank))
    siterank, hashgroup, langid, multiplier = (
        _u64(siterank), _u64(hashgroup), _u64(langid), _u64(multiplier))
    synform, outlink, shardbytermid, delbit = (
        _u64(synform), _u64(outlink), _u64(shardbytermid), _u64(delbit))
    # no broadcast_arrays: the bit expressions broadcast naturally and
    # scalar rank fields stay scalar (materializing 14 full-size arrays
    # per call measured as a top indexing cost)
    shape = np.broadcast_shapes(
        termid.shape, docid.shape, wordpos.shape, densityrank.shape,
        diversityrank.shape, wordspamrank.shape, siterank.shape,
        hashgroup.shape, langid.shape, multiplier.shape, synform.shape,
        outlink.shape, shardbytermid.shape, delbit.shape)

    n2 = (termid << np.uint64(16)) | (docid >> np.uint64(22))
    n1 = (
        ((docid & np.uint64(0x3FFFFF)) << np.uint64(42))
        | ((siterank & np.uint64(0xF)) << np.uint64(37))
        | ((langid & np.uint64(0x1F)) << np.uint64(32))
        | ((wordpos & np.uint64(MAXWORDPOS)) << np.uint64(14))
        | ((hashgroup & np.uint64(0xF)) << np.uint64(10))
        | ((wordspamrank & np.uint64(0xF)) << np.uint64(6))
        | ((diversityrank & np.uint64(0xF)) << np.uint64(2))
        | (synform & np.uint64(0x3))
    )
    n0 = (
        ((densityrank & np.uint64(0x1F)) << np.uint64(11))
        | ((outlink & np.uint64(1)) << np.uint64(10))
        | np.uint64(1 << 9)  # alignment bit, always set on full keys
        | ((shardbytermid & np.uint64(1)) << np.uint64(8))
        | ((multiplier & np.uint64(0xF)) << np.uint64(4))
        | (((langid >> np.uint64(5)) & np.uint64(1)) << np.uint64(3))
        | (delbit & np.uint64(1))
    )
    out = np.empty(shape, dtype=KEY_DTYPE)
    out["n0"] = n0.astype(np.uint16)
    out["n1"] = n1
    out["n2"] = n2
    return out


def unpack(keys: np.ndarray) -> dict[str, np.ndarray]:
    """Vectorized inverse of :func:`pack` (reference per-field getters
    ``Posdb.h`` ``getTermId``/``getDocId``/``getWordPos``/...)."""
    n0 = keys["n0"].astype(np.uint64)
    n1 = keys["n1"]
    n2 = keys["n2"]
    return {
        "termid": n2 >> np.uint64(16),
        "docid": ((n2 & np.uint64(0xFFFF)) << np.uint64(22))
        | (n1 >> np.uint64(42)),
        "siterank": (n1 >> np.uint64(37)) & np.uint64(0xF),
        "langid": ((n1 >> np.uint64(32)) & np.uint64(0x1F))
        | (((n0 >> np.uint64(3)) & np.uint64(1)) << np.uint64(5)),
        "wordpos": (n1 >> np.uint64(14)) & np.uint64(MAXWORDPOS),
        "hashgroup": (n1 >> np.uint64(10)) & np.uint64(0xF),
        "wordspamrank": (n1 >> np.uint64(6)) & np.uint64(0xF),
        "diversityrank": (n1 >> np.uint64(2)) & np.uint64(0xF),
        "synform": n1 & np.uint64(0x3),
        "densityrank": (n0 >> np.uint64(11)) & np.uint64(0x1F),
        "outlink": (n0 >> np.uint64(10)) & np.uint64(1),
        "shardbytermid": (n0 >> np.uint64(8)) & np.uint64(1),
        "multiplier": (n0 >> np.uint64(4)) & np.uint64(0xF),
        "delbit": n0 & np.uint64(1),
    }


def to_bytes(keys: np.ndarray) -> bytes:
    """Byte image — identical to the reference's on-disk key bytes."""
    return keys.tobytes()


def from_bytes(buf: bytes) -> np.ndarray:
    return np.frombuffer(buf, dtype=KEY_DTYPE).copy()


def sort_order(keys: np.ndarray) -> np.ndarray:
    """argsort in reference key order (``key144_t::operator<``: n2,n1,n0)."""
    return np.lexsort((keys["n0"], keys["n1"], keys["n2"]))


def start_key(termid: int) -> np.ndarray:
    """First key of a termlist (reference ``Posdb::makeStartKey``)."""
    k = np.zeros((), dtype=KEY_DTYPE)
    k["n2"] = np.uint64((termid & TERMID_MASK) << 16)
    return k


def end_key(termid: int) -> np.ndarray:
    """Last key of a termlist (reference ``Posdb::makeEndKey``)."""
    k = np.zeros((), dtype=KEY_DTYPE)
    k["n2"] = np.uint64(((termid & TERMID_MASK) << 16) | 0xFFFF)
    k["n1"] = np.uint64(0xFFFFFFFFFFFFFFFF)
    k["n0"] = np.uint16(0xFFFF)
    return k


def shard_of_docid(docid, num_shards: int) -> np.ndarray:
    """docId → shard map (reference ``Hostdb::getShardNum`` for posdb keys,
    ``Hostdb.cpp:2486-2504`` — an 8192-slot map over the docid bits; here a
    stable avalanche hash mod num_shards, same balance property)."""
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    return (hash64_array(_u64(docid)) % np.uint64(num_shards)).astype(np.int32)


def shard_of_termid(termid, num_shards: int) -> np.ndarray:
    """termId → shard for shardByTermId ('nosplit') checksum terms
    (reference ``Hostdb::getShardNumByTermId``, ``Hostdb.cpp:2468``)."""
    if num_shards < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    return (hash64_array(_u64(termid)) % np.uint64(num_shards)).astype(np.int32)


def shard_of_keys(keys: np.ndarray, num_shards: int) -> np.ndarray:
    """Per-key shard assignment honoring the shardByTermId bit
    (reference ``Msg4.cpp``/``XmlDoc.cpp`` nosplit logic)."""
    f = unpack(keys)
    by_doc = shard_of_docid(f["docid"], num_shards)
    by_term = shard_of_termid(f["termid"], num_shards)
    return np.where(f["shardbytermid"].astype(bool), by_term, by_doc)
