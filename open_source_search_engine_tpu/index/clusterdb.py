"""Clusterdb — docid → (site hash, language, family filter) for query-time
site clustering and adult filtering.

Reference: ``Clusterdb.h:42`` — a dataless 16-byte key holding sitehash26,
familyFilter bit and langId, docid-keyed, looked up by Msg51 during result
clustering (max 2 results per site). Ours: a dataless 12-byte key, docid in
n1 (sort by docid), packed meta in n0. At query time the whole table is
materialized into device-resident columnar arrays (ops.pack) so clustering
is a vectorized pass instead of per-docid cache lookups.
"""

from __future__ import annotations

import numpy as np

from .titledb import KEY_DTYPE  # same 12-byte docid-major key shape

SITEHASH_BITS = 24


def pack_key(docid, sitehash, langid=0, adult=0, delbit=1) -> np.ndarray:
    """n1 = docid; n0 = sitehash24<<8 | langid6<<2 | adult<<1 | delbit."""
    docid = np.asarray(docid, dtype=np.uint64)
    sitehash = np.asarray(sitehash, dtype=np.uint64)
    langid_a = np.asarray(langid, dtype=np.uint64)
    adult_a = np.asarray(adult, dtype=np.uint64)
    delbit_a = np.asarray(delbit, dtype=np.uint64)
    docid, sitehash, langid_a, adult_a, delbit_a = np.broadcast_arrays(
        docid, sitehash, langid_a, adult_a, delbit_a)
    out = np.empty(docid.shape, dtype=KEY_DTYPE)
    out["n1"] = docid
    out["n0"] = (
        ((sitehash & np.uint64((1 << SITEHASH_BITS) - 1)) << np.uint64(8))
        | ((langid_a & np.uint64(0x3F)) << np.uint64(2))
        | ((adult_a & np.uint64(1)) << np.uint64(1))
        | (delbit_a & np.uint64(1))
    ).astype(np.uint32)
    return out


def unpack_key(keys: np.ndarray) -> dict[str, np.ndarray]:
    n0 = keys["n0"].astype(np.uint64)
    return {
        "docid": keys["n1"],
        "sitehash": n0 >> np.uint64(8),
        "langid": (n0 >> np.uint64(2)) & np.uint64(0x3F),
        "adult": (n0 >> np.uint64(1)) & np.uint64(1),
        "delbit": n0 & np.uint64(1),
    }
