"""Catdb — the DMOZ-style directory taxonomy (Catdb.h:27 / dmozparse).

The reference parses the DMOZ RDF dump into ``catdb``: a taxonomy of
topics plus url→category assignments; queries can then restrict or
facet by directory topic. DMOZ itself is dead, but the subsystem is
the same with any taxonomy:

* a **category tree** loaded from ``categories.txt`` — one
  ``catid<TAB>parent_catid<TAB>Topic/Path`` line per node (parent 0 =
  root), the dmozparse ``structure.rdf`` role;
* a **site→category Rdb** (dataless keys: sitehash major, catid
  minor) — the catdb records, written by :meth:`assign` (the
  ``content.rdf`` url listings role; bulk loaders call it in a loop);
* **index-time integration**: documents of an assigned site carry
  numeric ``catid``/``catid_top`` fields and ``category``/
  ``category_top`` topic-path string fields, so the EXISTING operators
  do the query-side work — ``gbmin:catid:`` range restriction,
  ``gbfacet:category`` directory drill-down — with no new kernel
  paths. Upward inheritance rides the ``*_top`` fields (restrict or
  facet on the root topic to catch the whole subtree).
"""

from __future__ import annotations

from pathlib import Path

import numpy as np

from ..utils import ghash
from . import rdblite

#: dataless key: n1 = 48-bit sitehash (major), n0 = catid (minor);
#: low bit of n0 is the delbit (tombstones annihilate assignments)
KEY_DTYPE = np.dtype([("n0", "<u4"), ("n1", "<u8")], align=False)

SITEHASH_BITS = 48


def pack_key(site: str, catid: int, delbit: int = 1) -> np.ndarray:
    out = np.zeros(1, KEY_DTYPE)
    out["n1"] = ghash.hash64(site) & ((1 << SITEHASH_BITS) - 1)
    out["n0"] = (np.uint32(catid) << np.uint32(1)) | np.uint32(delbit)
    return out


class Catdb:
    def __init__(self, directory: str | Path):
        self.rdb = rdblite.Rdb("catdb", directory, KEY_DTYPE)
        #: catid → (parent, "Topic/Path")
        self.tree: dict[int, tuple[int, str]] = {}
        self._by_path: dict[str, int] = {}
        p = Path(directory) / "catdb" / "categories.txt"
        if p.exists():
            self.load_tree(p.read_text(encoding="utf-8"))

    # --- taxonomy ------------------------------------------------------

    def load_tree(self, text: str) -> int:
        """Parse the taxonomy file (dmozparse structure role)."""
        n = 0
        for line in text.splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            try:
                cid, parent, path = line.split("\t", 2)
                self.tree[int(cid)] = (int(parent), path)
                self._by_path[path.lower()] = int(cid)
                n += 1
            except ValueError:
                continue
        return n

    def save_tree(self, directory: str | Path | None = None) -> None:
        base = Path(directory) if directory else self.rdb.dir
        lines = [f"{cid}\t{parent}\t{path}"
                 for cid, (parent, path) in sorted(self.tree.items())]
        (base / "categories.txt").write_text(
            "\n".join(lines) + "\n", encoding="utf-8")

    def catid_of_path(self, path: str) -> int | None:
        return self._by_path.get(path.lower())

    def path_of(self, catid: int) -> str:
        return self.tree.get(catid, (0, ""))[1]

    def ancestors(self, catid: int) -> list[int]:
        """catid + every ancestor up to the root (inheritance chain)."""
        out = []
        seen = set()
        while catid and catid in self.tree and catid not in seen:
            out.append(catid)
            seen.add(catid)
            catid = self.tree[catid][0]
        return out

    # --- assignments ---------------------------------------------------

    def assign(self, site: str, catid: int) -> None:
        self.rdb.add(pack_key(site, catid))

    def unassign(self, site: str, catid: int) -> None:
        self.rdb.add(pack_key(site, catid, delbit=0))

    def categories_of(self, site: str) -> list[int]:
        """Directly-assigned catids for a site (newest-wins under
        tombstones)."""
        sh = ghash.hash64(site) & ((1 << SITEHASH_BITS) - 1)
        lo = np.zeros(1, KEY_DTYPE)
        lo["n1"] = sh
        hi = np.zeros(1, KEY_DTYPE)
        hi["n1"] = sh
        hi["n0"] = 0xFFFFFFFF
        lst = self.rdb.get_list(lo[0], hi[0])
        if not len(lst):
            return []
        keys = lst.keys
        live = (keys["n0"] & np.uint32(1)) == 1
        return sorted({int(k) >> 1 for k in keys["n0"][live]})

    def doc_fields(self, site: str) -> dict:
        """The fields an indexed document of this site carries:

        * ``catid`` — the most specific assigned catid (numeric:
          gbmin:/gbmax:/gbsortby: restriction);
        * ``catid_top`` — its ROOT ancestor id (the upward-inheritance
          hook: restricting on the top category catches every site
          filed under its subtree);
        * ``category`` / ``category_top`` — the corresponding topic
          paths (string fields: gbfacet: drill-down at either depth).

        One primary assignment drives the fields (fielddb columns are
        single-valued); additional assignments remain readable via
        :meth:`categories_of`. Empty dict when the site is unfiled."""
        cids = self.categories_of(site)
        if not cids:
            return {}
        cid = cids[0]
        chain = self.ancestors(cid)
        top = chain[-1] if chain else cid
        out: dict = {"catid": float(cid), "catid_top": float(top)}
        if self.path_of(cid):
            out["category"] = self.path_of(cid)
        if self.path_of(top):
            out["category_top"] = self.path_of(top)
        return out
