"""Fielddb — per-document numeric field values (the datedb role,
generalized).

Reference: ``Datedb.h:60`` (an indexdb clone whose score byte carries a
date, enabling date-constrained search) and the numeric facet/range
operators over structured documents (``gbmin:``/``gbmax:``/
``gbsortby:``/``gbfacet:`` fielded terms, ``Query.h:209``; exercised by
``qa.cpp:2910`` qajson). The reference encodes numbers into posting
keys; on a TPU the natural shape is a **per-doc numeric column**: one
dense ``[D]`` f32 per queried field, aligned to the resident doc axis,
consumed by the kernels as a filter mask or a sort override.

Storage is one Rdb: key = fieldhash32 · docid38 · delbit (newest wins,
tombstones annihilate), payload = float64 little-endian. ``date`` is a
built-in field (document timestamp, seconds since epoch) — ``datedb``
is exactly ``fielddb["date"]``.
"""

from __future__ import annotations

import struct
from pathlib import Path

import numpy as np

from ..utils import ghash
from . import rdblite

#: key: fieldhash(32) | docid(38) | delbit(1) packed into 12 bytes
KEY_DTYPE = np.dtype([("lo", "<u8"), ("hi", "<u4")], align=False)

#: the built-in document-timestamp field (the datedb role)
DATE_FIELD = "date"


def field_hash(field: str) -> int:
    return ghash.hash64(field.lower()) & 0xFFFFFFFF


def pack_key(field: str, docid: int, delbit: int = 1) -> np.ndarray:
    fh = field_hash(field)
    lo = (np.uint64(docid & ((1 << 38) - 1)) << np.uint64(1)) \
        | np.uint64(delbit & 1)
    lo |= np.uint64(fh & 0x1FFFFFF) << np.uint64(39)
    hi = np.uint32(fh >> 25)
    out = np.zeros(1, KEY_DTYPE)
    out["lo"] = lo
    out["hi"] = hi
    return out


def unpack_keys(keys: np.ndarray) -> dict[str, np.ndarray]:
    lo = keys["lo"].astype(np.uint64)
    hi = keys["hi"].astype(np.uint64)
    return {
        "delbit": (lo & np.uint64(1)).astype(np.uint8),
        "docid": (lo >> np.uint64(1)) & np.uint64((1 << 38) - 1),
        "fieldhash": ((lo >> np.uint64(39)) & np.uint64(0x1FFFFFF))
        | (hi << np.uint64(25)),
    }


def _range_of(field: str) -> tuple[np.ndarray, np.ndarray]:
    fh = field_hash(field)
    start = np.zeros(1, KEY_DTYPE)
    end = np.zeros(1, KEY_DTYPE)
    start["lo"] = np.uint64(fh & 0x1FFFFFF) << np.uint64(39)
    start["hi"] = np.uint32(fh >> 25)
    end["lo"] = (np.uint64(fh & 0x1FFFFFF) << np.uint64(39)) \
        | np.uint64((1 << 39) - 1)
    end["hi"] = np.uint32(fh >> 25)
    return start[0], end[0]


class Fielddb:
    """Per-collection numeric field store over one Rdb."""

    def __init__(self, directory: str | Path):
        self.rdb = rdblite.Rdb("fielddb", directory, KEY_DTYPE,
                               has_data=True)

    @property
    def empty(self) -> bool:
        return len(self.rdb.mem) == 0 and not self.rdb.runs

    def add(self, keys: np.ndarray, blobs) -> None:
        self.rdb.add(keys, blobs)

    def column(self, field: str) -> tuple[np.ndarray, np.ndarray]:
        """(docids sorted u64, values f64) for one field — the merged,
        tombstone-annihilated view."""
        start, end = _range_of(field)
        batch = self.rdb.get_list(start, end)
        if not len(batch):
            return np.empty(0, np.uint64), np.empty(0, np.float64)
        f = unpack_keys(batch.keys)
        vals = np.empty(len(batch), np.float64)
        for i in range(len(batch)):
            payload = batch.payload(i)
            vals[i] = struct.unpack("<d", payload)[0] if payload \
                else 0.0
        return f["docid"], vals

    def save(self) -> None:
        self.rdb.save()


def make_records(docid: int, fields: dict[str, float], delbit: int = 1):
    """(keys, blobs) for one document's numeric fields."""
    items = [(f, v) for f, v in sorted(fields.items())
             if isinstance(v, (int, float)) and not isinstance(v, bool)]
    if not items:
        return np.empty(0, KEY_DTYPE), []
    keys = np.concatenate([pack_key(f, docid, delbit) for f, _ in items])
    blobs = [b"" if not delbit else struct.pack("<d", float(v))
             for _, v in items]
    return keys, blobs
