"""Host-side index/storage layer.

Reference layers L1 (the Rdb LSM engine, ``Rdb.cpp``/``RdbTree``/``RdbList``
/``RdbMerge``) and L3 (the named databases with key schemas: ``Posdb``,
``Titledb``, ``Clusterdb``, ``Linkdb``, ``Tagdb``, ``Spiderdb`` — SURVEY
§2.2/§2.3). On TPU the storage engine stays on the host (numpy + optional
C++ core in ``native/``); posting lists are packed out of it into padded
device arrays by :mod:`~open_source_search_engine_tpu.ops.pack`.
"""
