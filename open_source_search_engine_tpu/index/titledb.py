"""Titledb — docid-keyed store of compressed document records.

Reference: ``Titledb.h:34`` (12-byte docid key + zlib-compressed TitleRec
payload holding the page content and LinkInfo; built by
``XmlDoc::getTitleRecBuf`` ``XmlDoc.cpp:5385``). Ours: a 12-byte
``(n0:u32, n1:u64)`` key — docid in n1 so the sort is docid order, a url
hash in n0 for collision discrimination — and a zlib-compressed JSON
payload (the TitleRec equivalent: url, title, visible text, links, site,
language, timestamp).
"""

from __future__ import annotations

import json
import zlib

import numpy as np

from ..utils.ghash import hash64

KEY_DTYPE = np.dtype([("n0", "<u4"), ("n1", "<u8")], align=False)
assert KEY_DTYPE.itemsize == 12


def pack_key(docid, urlhash32=0, delbit=1) -> np.ndarray:
    """docid-major key: n1 = docid, n0 = urlhash31<<1 | delbit."""
    docid = np.asarray(docid, dtype=np.uint64)
    urlhash32 = np.asarray(urlhash32, dtype=np.uint64)
    delbit_a = np.asarray(delbit, dtype=np.uint32)
    docid, urlhash32, delbit_a = np.broadcast_arrays(docid, urlhash32, delbit_a)
    out = np.empty(docid.shape, dtype=KEY_DTYPE)
    out["n1"] = docid
    out["n0"] = (((urlhash32 & np.uint64(0x7FFFFFFF)) << np.uint64(1))
                 | delbit_a.astype(np.uint64)).astype(np.uint32)
    return out


def unpack_key(keys: np.ndarray) -> dict[str, np.ndarray]:
    return {
        "docid": keys["n1"],
        "urlhash32": (keys["n0"] >> np.uint32(1)).astype(np.uint64),
        "delbit": keys["n0"] & np.uint32(1),
    }


def start_key(docid: int) -> np.ndarray:
    k = np.zeros((), dtype=KEY_DTYPE)
    k["n1"] = np.uint64(docid)
    return k


def end_key(docid: int) -> np.ndarray:
    k = np.zeros((), dtype=KEY_DTYPE)
    k["n1"] = np.uint64(docid)
    k["n0"] = np.uint32(0xFFFFFFFF)
    return k


def make_title_rec(url: str, title: str, text: str, links: list,
                   site: str, langid: int, siterank: int = 0,
                   content_hash: int = 0, ts: float = 0.0,
                   extra: dict | None = None) -> bytes:
    """Serialize + zlib-compress a TitleRec (reference compresses with zlib
    too, ``XmlDoc.cpp:5385``)."""
    rec = {
        "url": url, "title": title, "text": text, "links": links,
        "site": site, "langid": langid, "siterank": siterank,
        "content_hash": content_hash, "ts": ts,
    }
    if extra:
        rec.update(extra)
    # level 1: ~3× faster than 6 for ~10% larger recs — indexing is
    # compute-bound (the reference's niceness-2 build competes for the
    # same cores that serve queries)
    return zlib.compress(json.dumps(rec).encode("utf-8"), level=1)


def read_title_rec(blob: bytes) -> dict:
    return json.loads(zlib.decompress(blob).decode("utf-8"))


def urlhash32(url: str) -> int:
    return hash64(url) & 0x7FFFFFFF
