"""Collection — one searchable corpus: the set of Rdbs plus per-collection
config.

Reference: ``Collectiondb.cpp/h`` (``Collectiondb.h:39`` — multi-tenant
CollectionRecs, each owning per-collection RdbBases for every database) and
the per-Rdb init calls in ``main.cpp:3395-3500``. A Collection here owns
posdb (positional index, dataless 18B keys), titledb (doc records),
clusterdb (site/lang meta) — linkdb/spiderdb/tagdb attach in the crawler
milestone — plus doc/term counters used for ranking (termFreqWeight needs
numDocsInColl, reference ``Posdb.cpp:1225``).
"""

from __future__ import annotations

import json
from pathlib import Path

from ..utils.log import get_logger
from ..utils.parms import CollectionConf
from . import clusterdb, posdb, rdblite, titledb

log = get_logger("collection")


class TermlistCache:
    """(termid, rdb version) → RecordBatch, byte-bounded LRU."""

    def __init__(self, max_bytes: int = 256 << 20):
        from collections import OrderedDict
        self._d: "OrderedDict[tuple[int, int], object]" = OrderedDict()
        self.max_bytes = max_bytes
        self.nbytes = 0
        self._version = -1

    def _roll(self, version: int) -> None:
        # a version bump strands every entry: drop them all so dead
        # batches can't pin memory or evict live ones
        if version != self._version:
            self._d.clear()
            self.nbytes = 0
            self._version = version

    def get(self, termid: int, version: int):
        from ..utils.stats import g_stats
        self._roll(version)
        key = (termid, version)
        hit = self._d.get(key)
        if hit is not None:
            self._d.move_to_end(key)
            g_stats.count("termlist_cache.hit")
            return hit
        g_stats.count("termlist_cache.miss")
        return None

    def put(self, termid: int, version: int, batch) -> None:
        self._roll(version)
        key = (termid, version)
        if key in self._d:
            return
        sz = int(batch.keys.nbytes)
        self._d[key] = batch
        self.nbytes += sz
        while self.nbytes > self.max_bytes and self._d:
            _, old = self._d.popitem(last=False)
            self.nbytes -= int(old.keys.nbytes)


class Collection:
    def __init__(self, name: str, base_dir: str | Path,
                 conf: CollectionConf | None = None):
        self.name = name
        self.dir = Path(base_dir) / "coll" / name
        self.dir.mkdir(parents=True, exist_ok=True)
        self.conf = conf or CollectionConf(name)
        # per-collection config persists alongside the Rdbs (reference
        # coll.conf) — a broadcast parm survives the node's restart
        self._conf_path = self.dir / "coll.conf"
        if conf is None and self._conf_path.exists():
            try:
                self.conf.load(self._conf_path)
            except Exception as exc:  # noqa: BLE001 — defaults win
                log.warning("%s: coll.conf unreadable (%s) — using "
                            "defaults", name, exc)
        self.posdb = rdblite.Rdb("posdb", self.dir, posdb.KEY_DTYPE)
        self.titledb = rdblite.Rdb("titledb", self.dir, titledb.KEY_DTYPE,
                                   has_data=True)
        self.clusterdb = rdblite.Rdb("clusterdb", self.dir,
                                     clusterdb.KEY_DTYPE)
        from ..spider.linkdb import Linkdb
        self.linkdb = Linkdb(self.dir)
        from .tagdb import Tagdb
        self.tagdb = Tagdb(self.dir)
        from .sectiondb import Sectiondb
        self.sectiondb = Sectiondb(self.dir)
        from .fielddb import Fielddb
        self.fielddb = Fielddb(self.dir)
        from .catdb import Catdb
        self.catdb = Catdb(self.dir)
        from ..query.speller import Speller
        self.speller = Speller(self.dir)
        self._stats_path = self.dir / "collstats.json"
        self.num_docs = 0
        self._load_stats()
        #: parsed-titlerec cache keyed by docid (the reference keeps a
        #: dedicated RdbCache in front of titledb for Msg22 lookups,
        #: ``RdbCache.h:50``); bounded, dropped wholesale when full
        self.titlerec_cache: dict[int, dict | None] = {}
        self.titlerec_cache_max = 16384
        #: termlist cache (RdbCache.h:50's biggest customer): merged
        #: posdb range reads keyed by (termid, posdb version) — any
        #: write bumps the version, so stale lists can never serve.
        #: LRU-bounded by total key bytes.
        self.termlist_cache = TermlistCache()

    def rdbs(self) -> dict[str, "rdblite.Rdb"]:
        """Every named Rdb this collection owns (the per-coll RdbBase
        set, ``Collectiondb.h:39``) — repair/resync/scrub iterate this."""
        return {"posdb": self.posdb, "titledb": self.titledb,
                "clusterdb": self.clusterdb, "linkdb": self.linkdb.rdb,
                "tagdb": self.tagdb.rdb,
                "sectiondb": self.sectiondb.rdb,
                "fielddb": self.fielddb.rdb,
                "catdb": self.catdb.rdb}

    # --- stats used by ranking ---

    def _load_stats(self) -> None:
        if self._stats_path.exists():
            self.num_docs = json.loads(self._stats_path.read_text())["num_docs"]

    def _save_stats(self) -> None:
        self._stats_path.write_text(json.dumps({"num_docs": self.num_docs}))

    def doc_added(self, n: int = 1) -> None:
        self.num_docs += n

    def doc_removed(self, n: int = 1) -> None:
        self.num_docs = max(0, self.num_docs - n)

    # --- lifecycle (Process::saveRdbTrees equivalent) ---

    def save(self) -> None:
        for db in self.rdbs().values():
            db.save()
        self.speller.save()
        self.conf.save(self._conf_path)
        self._save_stats()

    def dump_all(self) -> None:
        for db in self.rdbs().values():
            db.dump()
        self._save_stats()

    def close(self) -> None:
        """Release process-wide accounting for a collection being
        deleted or unloaded (the delColl half Collectiondb.cpp pairs
        with addColl): zero every Rdb's memtable gauge — the budget
        would otherwise bill a purged corpus forever — and drop the
        host-side caches. Disk state is untouched; delete callers
        rmtree separately."""
        from ..utils.membudget import g_membudget
        for db in self.rdbs().values():
            g_membudget.set_gauge("memtable", str(db.dir), 0)
        self.titlerec_cache.clear()
        self.termlist_cache = TermlistCache()


class CollectionDb:
    """Registry of collections (reference ``g_collectiondb``)."""

    def __init__(self, base_dir: str | Path):
        self.base_dir = Path(base_dir)
        self.colls: dict[str, Collection] = {}
        import threading
        self._lock = threading.Lock()  # lazy-open is check-then-create

    def get(self, name: str = "main", create: bool = True) -> Collection:
        with self._lock:
            if name not in self.colls:
                if not create and not (self.base_dir / "coll"
                                       / name).exists():
                    raise KeyError(f"no such collection: {name}")
                self.colls[name] = Collection(name, self.base_dir)
            return self.colls[name]

    def drop(self, name: str) -> Collection | None:
        """Unregister and ``close()`` a collection — the registry half
        of delColl. The caller owns the directory's fate (and any
        serve-layer residency teardown; this layer cannot import
        serve)."""
        with self._lock:
            coll = self.colls.pop(name, None)
        if coll is not None:
            coll.close()
        return coll

    def names(self) -> list[str]:
        disk = {p.name for p in (self.base_dir / "coll").glob("*") if p.is_dir()}
        return sorted(disk | set(self.colls))

    def save(self) -> None:
        """Alias so a CollectionDb can register as a Process savable."""
        self.save_all()

    def save_all(self) -> None:
        for c in self.colls.values():
            c.save()
