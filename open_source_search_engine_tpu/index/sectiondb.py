"""Sectiondb — per-site repeated-section votes for boilerplate demotion.

Reference: ``Sections.cpp/h`` (``Sections.h:330``, ~18k LoC) builds a
tag-path section tree per page and stores per-section content hashes in
**sectiondb**, keyed by site; sections whose hash repeats across many of
a site's pages are navigation/footer boilerplate, and their words get
demoted at scoring time (the section "dup votes" flow through the
scoring weights).

Lite redesign, same behavior where it matters for ranking: the
tokenizer tags every token with a tag-path section id; the indexer
hashes each section's word content and looks the hash up here — a
section already seen on ``BOILER_MIN_PAGES`` other pages of the same
site is boilerplate, and its tokens' wordspamrank is docked to
``BOILER_SPAMRANK`` (weight (r+1)/16 — the reference likewise routes
the demotion through the spam/quality slot). Records are one per
(site, section hash, page), so the vote count is a single range read.

Keys: n1 = sitehash32<<32 | secthash32 (sort: one site's one section
is a contiguous range), n0 = urlhash63<<1 | delbit.
"""

from __future__ import annotations

import numpy as np

from ..utils import ghash
from . import rdblite

KEY_DTYPE = np.dtype([("n0", "<u8"), ("n1", "<u8")], align=False)

#: a section seen on this many OTHER pages of the site is boilerplate
BOILER_MIN_PAGES = 2

#: wordspamrank for boilerplate-section tokens (weight 6/16 = 0.375)
BOILER_SPAMRANK = 5

#: ignore tiny sections (a 1-2 word <div> is noise, not boilerplate)
MIN_SECTION_WORDS = 3


def _h32(s: str) -> int:
    return ghash.hash64(s) & 0xFFFFFFFF


def pack_key(site: str, secthash: int, url: str,
             delbit: int = 1) -> np.ndarray:
    k = np.zeros((), dtype=KEY_DTYPE)
    k["n1"] = np.uint64((_h32(site) << 32) | (secthash & 0xFFFFFFFF))
    k["n0"] = np.uint64(((ghash.hash64(url) & 0x7FFFFFFFFFFFFFFF) << 1)
                        | (delbit & 1))
    return k


def _range(site: str, secthash: int):
    n1 = np.uint64((_h32(site) << 32) | (secthash & 0xFFFFFFFF))
    lo = np.zeros((), dtype=KEY_DTYPE)
    lo["n1"] = n1
    hi = np.zeros((), dtype=KEY_DTYPE)
    hi["n1"] = n1
    hi["n0"] = np.uint64(0xFFFFFFFFFFFFFFFF)
    return lo, hi


class Sectiondb:
    """Per-node section-vote database (an Rdb like the others)."""

    def __init__(self, directory):
        self.rdb = rdblite.Rdb("sectiondb", directory, KEY_DTYPE)

    def add_page_sections(self, site: str, url: str,
                          secthashes) -> None:
        if not secthashes:
            return
        keys = np.concatenate([pack_key(site, h, url).reshape(1)
                               for h in secthashes])
        self.rdb.add(keys)

    def remove_page_sections(self, site: str, url: str,
                             secthashes) -> None:
        if not secthashes:
            return
        keys = np.concatenate([pack_key(site, h, url, delbit=0).reshape(1)
                               for h in secthashes])
        self.rdb.add(keys)

    def page_count(self, site: str, secthash: int) -> int:
        """How many of the site's pages contain this exact section."""
        return int(len(self.rdb.get_list(*_range(site, secthash))))

    def boiler_set(self, site: str, secthashes) -> set[int]:
        """The subset of a page's sections that are site boilerplate
        (already on ≥ BOILER_MIN_PAGES other pages)."""
        if self.rdb.mem.nbytes == 0 and not self.rdb.runs:
            return set()
        return {h for h in secthashes
                if self.page_count(site, h) >= BOILER_MIN_PAGES}

    def save(self) -> None:
        self.rdb.save()
