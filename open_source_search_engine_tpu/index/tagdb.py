"""Tagdb — per-site metadata tags: manual bans, site boundary overrides,
siterank overrides, and freeform operator tags.

Reference: ``Tagdb.{h,cpp}`` (``Tagdb.h:323``) — an Rdb of ``Tag``
records keyed by site hash; ``TagRec`` accumulates every tag that
applies to a url by probing progressively wider containers (subdomain,
then registrable domain — ``Tagdb.cpp`` getTagRec issues one read per
candidate site string). Well-known tag types include ``manualban``
(operator bans a site outright), ``sitenuminlinks`` (cached link-quality
count), and the ruleset/site-boundary overrides ``SiteGetter.cpp``
consults to decide whether a "site" is a whole host or a subdirectory
(user homepages on a hosting domain).

Ours is the same shape on the columnar Rdb: one record per (site, tag
name), newest write wins (rdblite's recency dedup — a re-set replaces,
a tombstone deletes), value is a small JSON payload. Site-boundary
detection (:meth:`Tagdb.site_of`) implements the SiteGetter contract:
default site = host, but a ``sitepathdepth`` tag on the host or domain
widens it to host + first N path segments, so ``users.example.com/~a/``
and ``/~b/`` cluster and rank as distinct sites.
"""

from __future__ import annotations

import json
import time

import numpy as np

from ..utils import ghash
from ..utils.url import Url, normalize
from . import rdblite

KEY_DTYPE = np.dtype([("n0", "<u8"), ("n1", "<u8")], align=False)

#: tag names with engine-defined semantics (any other name is a
#: freeform operator annotation, stored and returned verbatim)
TAG_MANUAL_BAN = "manualban"
TAG_SITE_PATH_DEPTH = "sitepathdepth"
TAG_SITE_RANK = "siterank"
TAG_SITE_NUM_INLINKS = "sitenuminlinks"


def _apply_path_depth(u: Url, depth) -> str:
    """host + first ``depth`` path directories (SiteGetter truncates at
    directory boundaries — a trailing FILENAME segment never counts, so
    ``/page.html`` at the root stays on the host site)."""
    if not depth or int(depth) <= 0:
        return u.host
    segs = [s for s in u.path.split("/") if s]
    if segs and not u.path.endswith("/"):
        segs = segs[:-1]  # drop the filename
    if len(segs) < int(depth):
        return u.host
    return u.host + "/" + "/".join(segs[: int(depth)]) + "/"


def pack_key(site: str, name: str, delbit: int = 1) -> np.ndarray:
    """n1 = sitehash64 (sort: all of a site's tags are one range read);
    n0 = taghash32<<32 | delbit — one slot per (site, tag name), so a
    re-set supersedes the old record by rdblite recency."""
    k = np.zeros((), dtype=KEY_DTYPE)
    k["n1"] = np.uint64(ghash.hash64(site))
    k["n0"] = np.uint64(((ghash.hash64(name) & 0xFFFFFFFF) << 32)
                        | (delbit & 1))
    return k


def _site_range(site: str) -> tuple[np.ndarray, np.ndarray]:
    h = np.uint64(ghash.hash64(site))
    lo = np.zeros((), dtype=KEY_DTYPE)
    lo["n1"] = h
    hi = np.zeros((), dtype=KEY_DTYPE)
    hi["n1"] = h
    hi["n0"] = np.uint64(0xFFFFFFFFFFFFFFFF)
    return lo, hi


class Tagdb:
    """Per-node tag database (an Rdb instance like the others)."""

    def __init__(self, directory):
        self.rdb = rdblite.Rdb("tagdb", directory, KEY_DTYPE,
                               has_data=True)
        #: (site, rdb version) → tags dict — tagdb reads sit on the
        #: index hot path (one probe per container site per doc), and
        #: tags change rarely; any write bumps the version so stale
        #: entries can never serve (the RdbCache.h:50 pattern)
        self._cache: dict[tuple[str, int], dict[str, object]] = {}

    @property
    def empty(self) -> bool:
        """Fast path: with no tags anywhere, every lookup is a no-op —
        the indexer checks this before probing container sites."""
        return not self.rdb.runs and not len(self.rdb.mem)

    # --- writes ---

    def set_tag(self, site: str, name: str, value,
                user: str = "admin") -> None:
        """Set one tag on a site string (host, domain, or a
        subdirectory site like ``host/~user/``)."""
        payload = json.dumps(
            {"n": name, "v": value, "ts": int(time.time()), "u": user},
            separators=(",", ":")).encode()
        self.rdb.add(pack_key(site, name).reshape(1), [payload])

    def remove_tag(self, site: str, name: str) -> None:
        self.rdb.delete(pack_key(site, name, delbit=0).reshape(1))

    # --- reads ---

    def tags_for_site(self, site: str) -> dict[str, object]:
        """All tags set directly on one site string (one range read,
        version-cached)."""
        ck = (site, self.rdb.version)
        hit = self._cache.get(ck)
        if hit is not None:
            return hit
        batch = self.rdb.get_list(*_site_range(site))
        out: dict[str, object] = {}
        for i in range(len(batch)):
            try:
                rec = json.loads(batch.payload(i))
            except (ValueError, UnicodeDecodeError):
                continue
            if "n" in rec:
                out[rec["n"]] = rec["v"]
        if len(self._cache) > 65536:
            self._cache.clear()
        self._cache[ck] = out
        return out

    def _candidate_sites(self, u: Url) -> list[str]:
        """Narrowest-first container sites for a url: subdirectory
        prefixes (deepest first), host, registrable domain — the
        TagRec probe order (url site before domain)."""
        cands: list[str] = []
        segs = [s for s in u.path.split("/") if s]
        if segs and not u.path.endswith("/"):
            segs = segs[:-1]  # directories only, never the filename
        # the exact normalized input always probes first: site strings
        # deeper than the probe cap (site_of can produce them whenever
        # sitepathdepth exceeds it) must round-trip through set_tag/
        # get_tag
        if len(segs) > 3:
            cands.append(u.host + "/" + "/".join(segs) + "/")
        for depth in range(min(len(segs), 3), 0, -1):
            cands.append(u.host + "/" + "/".join(segs[:depth]) + "/")
        cands.append(u.host)
        if u.domain != u.host:
            cands.append(u.domain)
        return cands

    def get_tag(self, url_or_site: str, name: str, default=None):
        """The tag value that applies to a url: narrowest container
        wins (subdirectory site over host over domain)."""
        try:
            u = normalize(url_or_site if "://" in url_or_site
                          else "http://" + url_or_site)
        except Exception:
            return default
        for site in self._candidate_sites(u):
            tags = self.tags_for_site(site)
            if name in tags:
                return tags[name]
        return default

    def tag_rec(self, url_or_site: str) -> dict[str, object]:
        """Every tag applying to a url, narrowest-container-wins merge
        (the reference's TagRec)."""
        try:
            u = normalize(url_or_site if "://" in url_or_site
                          else "http://" + url_or_site)
        except Exception:
            return {}
        merged: dict[str, object] = {}
        for site in reversed(self._candidate_sites(u)):
            merged.update(self.tags_for_site(site))
        return merged

    # --- engine-defined semantics ---

    def is_banned(self, url_or_site: str) -> bool:
        """Operator ban: ``manualban`` on any containing site
        (``Tagdb.h`` manualban; XmlDoc indexDoc's EDOCBANNED check)."""
        if self.empty:
            return False
        return bool(self.get_tag(url_or_site, TAG_MANUAL_BAN, False))

    def site_of(self, u: Url | str) -> str:
        """Site boundary (SiteGetter.cpp): host, unless a
        ``sitepathdepth`` tag on the host or domain widens it to
        host + first N path DIRECTORIES."""
        if not isinstance(u, Url):
            try:
                u = normalize(u if "://" in u else "http://" + u)
            except Exception:
                return str(u)
        if self.empty:
            return u.host
        depth = None
        for site in (u.host, u.domain):
            tags = self.tags_for_site(site)
            if TAG_SITE_PATH_DEPTH in tags:
                depth = int(tags[TAG_SITE_PATH_DEPTH])
                break
        return _apply_path_depth(u, depth)

    def index_gate(self, u: Url) -> tuple[bool, str, int | None]:
        """One container walk → (banned, site, siterank override):
        everything ``XmlDoc::indexDoc`` needs from tagdb for one
        document, without three separate probes."""
        if self.empty:
            return False, u.host, None
        cands = self._candidate_sites(u)
        per = [self.tags_for_site(s) for s in cands]

        def first(name, only=None):
            for s, t in zip(cands, per):
                if only is not None and s not in only:
                    continue
                if name in t:
                    return t[name]
            return None

        banned = bool(first(TAG_MANUAL_BAN) or False)
        depth = first(TAG_SITE_PATH_DEPTH, only={u.host, u.domain})
        sr = first(TAG_SITE_RANK)
        return (banned, _apply_path_depth(u, depth),
                int(sr) if sr is not None else None)

    def siterank_override(self, url_or_site: str) -> int | None:
        """Operator-pinned siterank (the reference lets tagdb override
        link-derived site quality via ruleset tags)."""
        if self.empty:
            return None
        v = self.get_tag(url_or_site, TAG_SITE_RANK)
        return int(v) if v is not None else None

    def save(self) -> None:
        self.rdb.save()
