"""Cache plane — the RdbCache role as one subsystem.

Gigablast put a single cache class behind every hot lookup (termlists,
title recs, DNS, robots, the Msg17 result cache); this package is that
idea for the TPU port: a registry of named, membudget-charged caches
with generation-based invalidation and single-flight miss suppression.
See :mod:`.plane`.
"""

from .plane import CachePlane, GenCache, g_cacheplane

__all__ = ["CachePlane", "GenCache", "g_cacheplane"]
