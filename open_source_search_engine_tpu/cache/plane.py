"""Unified cache plane — named generation-keyed caches behind one registry.

Reference: ``RdbCache.cpp`` is the ONE cache class behind every hot
lookup in the original engine — termlists (``Msg0``'s disk-page cache),
title recs (``Msg22``), DNS and robots.txt (``Msg13``), and the query
result cache (``Msg17``/``Msg40Cache``). One implementation meant one
accounting story (``Mem.cpp`` labels), one invalidation trick and one
admin page. Our reproduction had grown four ad-hoc caches instead; this
module is the consolidation:

* :class:`GenCache` — keyed TTL entries stamped with a **generation**
  (any equality-comparable value, usually the owning Rdb's ``version``
  or a tuple of shard versions). A write bumps the owner's version, so
  every dependent entry goes stale in O(1) with zero scanning — the
  termlist-cache trick from the reference, generalized.
* **Single-flight** (:meth:`GenCache.get_or_compute`) — N concurrent
  identical misses share ONE compute; followers block on the leader's
  result instead of stampeding the device (dogpile suppression).
* **Stale-while-revalidate** — within ``swr_s`` past expiry a hot key
  serves the stale value immediately and refreshes in the background
  (generation mismatches are NEVER served stale: staleness bounded by
  TTL is acceptable, staleness across a write is not).
* **Membudget charging** — every cache reports its byte estimate as a
  ``cache``-label gauge in :data:`~..utils.membudget.g_membudget`, and
  the plane registers a pressure handler: under memory pressure caches
  shed (biggest first) BEFORE real work (the query packer, a merge) is
  refused. A cache is the definition of droppable memory.
* **Observability** — per-cache hit/miss/evict/inflight counters and
  gauges in :data:`~..utils.stats.g_stats` (``cache.<name>.*``), fills
  timed under ``trace.timed_span`` so cache fills show up in query
  waterfalls, and ``/admin/cache`` lists every registered cache with a
  flush button.

The registry (:class:`CachePlane`, singleton :data:`g_cacheplane`)
holds caches weakly: a cache dies with its owner (a DeviceIndex swap, a
test's ClusterClient) and drops off the admin page and the membudget
gauges without ceremony.

``OSSE_CACHE=0`` disables the whole plane (every lookup misses, every
put is dropped) — the A/B switch the cache bench and cluster client use.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Hashable

import numpy as np

from ..utils import threads, trace as trace_mod
from ..utils.lockcheck import make_event, make_lock
from ..utils.log import get_logger
from ..utils.membudget import g_membudget
from ..utils.stats import g_stats

log = get_logger("cache")

#: membudget label every cache charges under (one row on /admin/mem)
MEM_LABEL = "cache"

#: sentinel: "no generation supplied on this call — use the cache's
#: gen_fn (or None)"; distinct from gen=None, a legal generation value
_UNSET = object()


def _estimate_cost(value: Any, _depth: int = 0) -> int:
    """Rough byte cost of a cached value (strings/arrays dominate every
    real payload here; exactness doesn't matter, ordering under
    pressure does). Bounded recursion so adversarial nesting can't make
    a put() O(deep)."""
    if value is None or isinstance(value, (bool, int, float)):
        return 16
    if isinstance(value, (str, bytes, bytearray)):
        return len(value) + 48
    if isinstance(value, np.ndarray):
        return int(value.nbytes) + 96
    if _depth >= 4:
        return 64
    if isinstance(value, dict):
        return 64 + sum(_estimate_cost(k, _depth + 1)
                        + _estimate_cost(v, _depth + 1)
                        for k, v in value.items())
    if isinstance(value, (list, tuple, set, frozenset)):
        return 56 + sum(_estimate_cost(v, _depth + 1) for v in value)
    # dataclass-ish object (a ResidentPlan, a SearchResults): charge
    # its array/str attributes
    d = getattr(value, "__dict__", None)
    if isinstance(d, dict) and d:
        return 64 + sum(_estimate_cost(v, _depth + 1)
                        for v in d.values())
    return 128


class _Flight:
    """One in-flight compute (the single-flight unit): the leader fills
    ``value``/``err`` and sets the event; followers wait on it. The
    flight remembers the generation its leader started under — a caller
    whose generation differs must NOT join: the leader's result is
    pre-write from that caller's point of view."""

    __slots__ = ("event", "value", "err", "gen")

    def __init__(self, gen: Any = None):
        self.event = make_event("cache.flight")
        self.value: Any = None
        self.err: BaseException | None = None
        self.gen = gen


class GenCache:
    """One named cache on the plane: TTL + generation entries, byte
    accounting, single-flight, optional stale-while-revalidate.

    Entries are ``key -> (expiry, gen, cost, value)``. A lookup hits
    only when the entry is unexpired AND its generation equals the
    current one (per-call ``gen=``, else the cache's ``gen_fn()``, else
    None). Generations are compared by ``==`` so ints, tuples of shard
    versions, or vectors all work.
    """

    def __init__(self, name: str, ttl_s: float = 60.0,
                 max_entries: int = 4096,
                 gen_fn: Callable[[], Any] | None = None,
                 cost_fn: Callable[[Any], int] | None = None,
                 desc: str = ""):
        self.name = name
        self.ttl_s = float(ttl_s)
        self.max_entries = int(max_entries)
        self.gen_fn = gen_fn
        self.cost_fn = cost_fn or _estimate_cost
        self.desc = desc
        #: per-cache kill switch (the bench's A/B lever): False makes
        #: every lookup miss and every put a no-op
        self.enabled = True
        self._d: dict[Hashable, tuple[float, Any, int, Any]] = {}
        self._bytes = 0
        self._lock = make_lock("cache.gencache")
        self._inflight: dict[Hashable, _Flight] = {}
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.stale_served = 0

    # --- generation -------------------------------------------------------

    def _gen(self, gen: Any) -> Any:
        if gen is not _UNSET:
            return gen
        return self.gen_fn() if self.gen_fn is not None else None

    def current_gen(self) -> Any:
        """The generation new entries would be stamped with right now
        (admin-page display; None when the cache is ungenerated)."""
        return self._gen(_UNSET)

    # --- accounting -------------------------------------------------------

    def _charge_locked(self) -> None:
        g_membudget.set_gauge(MEM_LABEL, self.name, self._bytes)
        g_stats.gauge(f"cache.{self.name}.entries", len(self._d))
        g_stats.gauge(f"cache.{self.name}.bytes", self._bytes)

    def _evict_locked(self, now: float, gen: Any) -> None:
        """Room-making sweep (the ttlcache satellite's rule, shared):
        dead-generation and already-expired entries go first — they are
        free wins — and only then the stalest half by expiry."""
        dead = [k for k, (exp, g, _, _) in self._d.items()
                if exp < now or g != gen]
        for k in dead:
            exp, g, cost, _ = self._d.pop(k)
            self._bytes -= cost
        evicted = len(dead)
        if len(self._d) >= self.max_entries:
            for k in sorted(self._d, key=lambda k: self._d[k][0])[
                    : max(self.max_entries // 2, 1)]:
                self._bytes -= self._d.pop(k)[2]
                evicted += 1
        if evicted:
            self.evictions += evicted
            g_stats.count(f"cache.{self.name}.evict", evicted)

    # --- core ops ---------------------------------------------------------

    def lookup(self, key: Hashable, gen: Any = _UNSET
               ) -> tuple[bool, Any]:
        """``(hit, value)`` — a miss is ``(False, None)``. Values may
        legitimately BE None (negative DNS answers), hence the flag."""
        if not self.enabled:
            return False, None
        g = self._gen(gen)
        now = time.monotonic()
        with self._lock:
            e = self._d.get(key)
            if e is not None and e[0] >= now and e[1] == g:
                self.hits += 1
                g_stats.count(f"cache.{self.name}.hit")
                return True, e[3]
            self.misses += 1
            g_stats.count(f"cache.{self.name}.miss")
            return False, None

    def get(self, key: Hashable, gen: Any = _UNSET,
            default: Any = None) -> Any:
        hit, v = self.lookup(key, gen=gen)
        return v if hit else default

    def lookup_stale(self, key: Hashable, gen: Any = _UNSET
                     ) -> tuple[bool, Any]:
        """``(hit, value)`` ignoring TTL expiry — but never crossing a
        generation move (a write still invalidates; only time is
        softened). The deadline plane uses this: a just-expired answer
        served as degraded beats refusing an over-budget query."""
        if not self.enabled:
            return False, None
        g = self._gen(gen)
        with self._lock:
            e = self._d.get(key)
            if e is not None and e[1] == g:
                self.stale_served += 1
                g_stats.count(f"cache.{self.name}.stale")
                return True, e[3]
            return False, None

    def put(self, key: Hashable, value: Any, ttl_s: float | None = None,
            gen: Any = _UNSET, cost: int | None = None) -> None:
        if not self.enabled:
            return
        g = self._gen(gen)
        c = int(cost if cost is not None else self.cost_fn(value))
        now = time.monotonic()
        with self._lock:
            old = self._d.get(key)
            if old is not None:
                self._bytes -= old[2]
            elif len(self._d) >= self.max_entries:
                self._evict_locked(now, g)
            self._d[key] = (now + (self.ttl_s if ttl_s is None
                                   else float(ttl_s)), g, c, value)
            self._bytes += c
            self._charge_locked()

    def invalidate(self, key: Hashable) -> None:
        with self._lock:
            e = self._d.pop(key, None)
            if e is not None:
                self._bytes -= e[2]
                self._charge_locked()

    def flush(self) -> int:
        """Drop everything; returns the bytes freed (pressure-handler
        accounting)."""
        with self._lock:
            freed = self._bytes
            self._d.clear()
            self._bytes = 0
            self._charge_locked()
        return freed

    # --- single-flight + stale-while-revalidate ---------------------------

    def get_or_compute(self, key: Hashable, compute: Callable[[], Any],
                       ttl_s: float | None = None, gen: Any = _UNSET,
                       swr_s: float = 0.0) -> tuple[Any, str]:
        """The full-service read: ``(value, status)`` where status is
        ``"hit"`` (fresh), ``"stale"`` (expired-but-within-swr, same
        generation — served immediately, refresh fired in background),
        ``"join"`` (waited on another caller's identical in-flight
        compute), or ``"miss"`` (this caller computed).

        Single-flight: concurrent identical misses elect one leader;
        the rest block on its result. A leader failure propagates to
        every waiter of that flight (retrying N times in lockstep is
        the stampede this exists to prevent). A caller only joins a
        flight whose leader started under the SAME generation — if a
        write moved the generation since the leader began, the leader's
        result is pre-write and the caller computes its own. Stale
        serves never cross a generation move — a write invalidates
        instantly; only TTL expiry is softened. The generation is
        captured once at entry and stamps the stored entry, so a write
        landing during the compute yields a dead entry (a later miss),
        never a pre-write value passing as fresh.
        """
        if not self.enabled:
            return compute(), "miss"
        g = self._gen(gen)
        now = time.monotonic()
        with self._lock:
            e = self._d.get(key)
            if e is not None and e[1] == g:
                if e[0] >= now:
                    self.hits += 1
                    g_stats.count(f"cache.{self.name}.hit")
                    return e[3], "hit"
                if now <= e[0] + swr_s:
                    # hot key just past TTL: serve stale, refresh once
                    self.hits += 1
                    self.stale_served += 1
                    g_stats.count(f"cache.{self.name}.hit")
                    g_stats.count(f"cache.{self.name}.stale")
                    self._spawn_refresh_locked(key, compute, ttl_s, g)
                    return e[3], "stale"
            self.misses += 1
            g_stats.count(f"cache.{self.name}.miss")
            fl = self._inflight.get(key)
            if fl is not None and fl.gen == g:
                leader = False
            else:
                # no flight, or the in-flight leader started under a
                # different generation (a write landed since it began):
                # its value is pre-write for us, so compute our own
                # rather than join; only register in the flight map
                # when the slot is actually free
                registered = fl is None
                fl = _Flight(g)
                if registered:
                    self._inflight[key] = fl
                leader = True
            g_stats.gauge(f"cache.{self.name}.inflight",
                          len(self._inflight))
        if not leader:
            g_stats.count(f"cache.{self.name}.join")
            fl.event.wait()
            if fl.err is not None:
                raise fl.err
            return fl.value, "join"
        try:
            with trace_mod.timed_span(f"cache.{self.name}.fill"):
                value = compute()
            fl.value = value
            # stamp with the generation captured at ENTRY, not re-read
            # now: a write landing during the compute must leave this
            # entry dead (a miss), never stale-served-fresh
            self.put(key, value, ttl_s=ttl_s, gen=g)
        except BaseException as exc:
            fl.err = exc
            raise
        finally:
            # value/err are published BEFORE the event: a follower must
            # never wake to an unfilled flight
            with self._lock:
                if self._inflight.get(key) is fl:
                    del self._inflight[key]
                g_stats.gauge(f"cache.{self.name}.inflight",
                              len(self._inflight))
            fl.event.set()
        return value, "miss"

    def _spawn_refresh_locked(self, key, compute, ttl_s, g) -> None:
        """Background SWR refresh, deduped through the in-flight map
        (caller holds the lock). ``g`` is the resolved generation the
        stale serve happened under — the refreshed entry is stamped
        with it, so a write landing mid-refresh leaves a dead entry
        rather than a stale one passing as fresh."""
        if key in self._inflight:
            return  # a refresh (or a concurrent miss) already runs
        fl = self._inflight[key] = _Flight(g)

        def _refresh():
            try:
                with trace_mod.timed_span(f"cache.{self.name}.refresh"):
                    value = compute()
                fl.value = value
                self.put(key, value, ttl_s=ttl_s, gen=g)
            except BaseException as exc:  # noqa: BLE001 — background
                fl.err = exc
                log.warning("swr refresh of %s[%r] failed: %s",
                            self.name, key, exc)
            finally:
                with self._lock:
                    if self._inflight.get(key) is fl:
                        del self._inflight[key]
                fl.event.set()

        threads.spawn(f"swr-{self.name}", _refresh)

    # --- introspection ----------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            gen = None
            try:
                gen = self.current_gen()
            except Exception as exc:  # noqa: BLE001 — owner half-dead
                g_stats.count(f"cache.{self.name}.gen_error")
                log.debug("gen_fn of %s failed: %s", self.name, exc)
            return {
                "entries": len(self._d),
                "bytes": self._bytes,
                "max_entries": self.max_entries,
                "ttl_s": self.ttl_s,
                "hits": self.hits,
                "misses": self.misses,
                "hit_rate": (self.hits / total) if total else 0.0,
                "evictions": self.evictions,
                "stale_served": self.stale_served,
                "inflight": len(self._inflight),
                "generation": repr(gen),
                "enabled": self.enabled,
                "desc": self.desc,
            }

    def __del__(self):  # noqa: D105 — drop the membudget gauge with us
        try:
            g_membudget.set_gauge(MEM_LABEL, self.name, 0)
        except Exception:  # osselint: ignore[silent-except] — teardown
            pass


class CachePlane:
    """Registry of every live :class:`GenCache` (weakly held) plus the
    one membudget pressure hook for all of them."""

    def __init__(self):
        import weakref
        self._lock = make_lock("cache.plane")
        self._caches: "weakref.WeakValueDictionary[str, GenCache]" = \
            weakref.WeakValueDictionary()
        #: plane-wide kill switch, seeded from OSSE_CACHE (0 = off)
        self.enabled = os.environ.get("OSSE_CACHE", "1") != "0"
        g_membudget.add_pressure_handler(self._on_pressure)

    def register(self, name: str, ttl_s: float = 60.0,
                 max_entries: int = 4096,
                 gen_fn: Callable[[], Any] | None = None,
                 cost_fn: Callable[[Any], int] | None = None,
                 desc: str = "") -> GenCache:
        """Create + register a cache. A live-name collision uniquifies
        (``name#2``): a background DeviceIndex rebuild registers its
        plan cache while the old index still serves."""
        with self._lock:
            final = name
            n = 2
            while final in self._caches:
                final = f"{name}#{n}"
                n += 1
            c = GenCache(final, ttl_s=ttl_s, max_entries=max_entries,
                         gen_fn=gen_fn, cost_fn=cost_fn, desc=desc)
            c.enabled = self.enabled
            self._caches[final] = c
            return c

    def get(self, name: str) -> GenCache | None:
        with self._lock:
            return self._caches.get(name)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._caches.keys())

    def flush(self, name: str | None = None) -> int:
        """Flush one cache (or all); returns bytes freed."""
        with self._lock:
            caches = [self._caches[name]] if name in self._caches \
                else (list(self._caches.values()) if name is None
                      else [])
        return sum(c.flush() for c in caches)

    def snapshot(self) -> dict:
        """name → stats for every live cache (the /admin/cache body)."""
        with self._lock:
            caches = sorted(self._caches.items())
        return {nm: c.stats() for nm, c in caches}

    def _on_pressure(self, need: int) -> int:
        """Membudget relief hook: shed caches biggest-first until the
        shortfall is covered (or everything cached is gone). Caches are
        by definition droppable — they MUST empty before real work (a
        pack pass, a merge) gets refused."""
        with self._lock:
            caches = sorted(self._caches.values(),
                            key=lambda c: -c._bytes)
        freed = 0
        for c in caches:
            if freed >= need:
                break
            b = c.flush()
            if b:
                freed += b
                g_stats.count("cache.pressure_flush")
                log.info("memory pressure: flushed cache %s (%d KB)",
                         c.name, b >> 10)
        return freed


#: process-wide registry (the g_cacheDB... there is no reference
#: singleton name — RdbCache instances were globals; ours meet here)
g_cacheplane = CachePlane()
