"""TPU-native search-engine framework with the capabilities of Gigablast.

A ground-up re-design of ``cxcx/open-source-search-engine`` (Gigablast — a
distributed crawler + LSM record store + positional inverted index + sharded
query engine, reference at ``/root/reference``) for TPU hardware:

* the host data plane (LSM store, document pipeline, crawler, control plane)
  lives in :mod:`~open_source_search_engine_tpu.index`,
  :mod:`~open_source_search_engine_tpu.build` and
  :mod:`~open_source_search_engine_tpu.serve`;
* the device query plane — Gigablast's ``PosdbTable::intersectLists10_r``
  posting-list intersection and proximity scorer (reference
  ``Posdb.cpp:5437``) behind the Msg39 RPC boundary — is a vmapped segmented
  intersection + top-k in :mod:`~open_source_search_engine_tpu.ops`;
* cross-shard scatter-gather (reference ``Msg3a.cpp:971``) is a
  ``shard_map`` over a :class:`jax.sharding.Mesh` with an all-gather top-k
  merge in :mod:`~open_source_search_engine_tpu.parallel`.

The package directory uses underscores (``open_source_search_engine_tpu``)
because Python module names cannot contain hyphens.
"""

__version__ = "0.1.0"
