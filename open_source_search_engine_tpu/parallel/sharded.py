"""Sharded index + scatter-gather query over a device mesh.

Reference mapping (SURVEY §2.5, §3.2):

* **Document partitioning** — every record routed by docid hash
  (``Hostdb::getShardNum`` ``Hostdb.cpp:2486``; checksum terms by termid,
  ``getShardNumByTermId`` ``Hostdb.cpp:2468``) →
  :class:`ShardedCollection` splits each document's meta list across
  per-shard Collections with the same hash functions.
* **Msg3a scatter-gather** — fan Msg39 out to every shard, k-way merge
  per-shard top-k (``Msg3a.cpp:971``) → one ``shard_map`` over the
  ``shards`` mesh axis: each device scores its own shard's candidates
  (the Msg39 intersect, now :func:`..query.scorer.score_core`), then an
  **in-mesh all-gather top-k merge** replaces the UDP reply + host-side
  merge — the collective rides ICI, and every shard finishes holding the
  replicated global top-k.
* **Msg20 summaries** — per-result titlerec lookups go to the shard
  owning the docid (``Msg20.cpp:90``) → host-side reads from the owning
  shard's titledb.

Per-shard packed shapes are padded to the fleet-wide bucket so the
stacked [S, ...] arrays are rectangular; empty shards ship a zero-valid
dummy block (the reference's empty Msg39 reply).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from functools import partial
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..build import docproc
from ..index import posdb
from ..index.collection import Collection
from ..index.tagdb import Tagdb
from ..query import weights
from ..query.compiler import QueryPlan, compile_query
from ..query.engine import MAX_PER_SITE, SearchResults, build_results
from ..query.packer import (MAX_POSITIONS, PackedQuery, PreparedQuery,
                            pad_table,
                            _bucket, _pad1, group_flags, pack_pass,
                            prepare_query)
from ..query.scorer import merge_dedup_topk, score_core
from ..utils import devwatch
from ..utils.log import get_logger
from ..utils.membudget import g_membudget
from .hostmap import SHARD_AXIS, HostMap, make_mesh

log = get_logger("parallel")


def _shard_map(f, mesh, in_specs, out_specs):
    """jax.shard_map moved out of experimental across jax releases and
    renamed check_rep → check_vma; dispatch on what this jax has."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map
    return shard_map(f, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=False)


def _docid_of(url: str) -> int:
    from ..utils import ghash
    from ..utils.url import normalize
    return ghash.doc_id(normalize(url).full)


class ShardedTagdb(Tagdb):
    """Tag records routed by sitehash to their owning shard — the
    reference shards tagdb like any Rdb (``Tagdb.h:323``), and TagRec
    probes each candidate container site on that site's own shard.
    Writes fan out to every twin (Msg1 semantics); reads hit the
    serving replica. The container-walk logic (get_tag / tag_rec /
    site_of / index_gate) is inherited unchanged."""

    def __init__(self, sc: "ShardedCollection"):
        self._sc = sc  # no local Rdb — per-site routing below

    @property
    def empty(self) -> bool:
        return all(c.tagdb.empty for row in self._sc.grid for c in row)

    def _shard_of(self, site: str) -> int:
        return int(self._sc.hostmap.shard_of_site(site))

    def set_tag(self, site: str, name: str, value,
                user: str = "admin") -> None:
        for c in self._sc.replicas_of(self._shard_of(site)):
            c.tagdb.set_tag(site, name, value, user)

    def remove_tag(self, site: str, name: str) -> None:
        for c in self._sc.replicas_of(self._shard_of(site)):
            c.tagdb.remove_tag(site, name)

    def tags_for_site(self, site: str) -> dict[str, object]:
        return self._sc.shards[self._shard_of(site)].tagdb \
            .tags_for_site(site)

    def save(self) -> None:  # per-shard Collections save their own
        pass


class ShardedCollection:
    """One logical collection partitioned across N shards.

    Each shard is a full Collection (posdb/titledb/clusterdb) under
    ``base_dir/shard_XXX/`` — the analog of one gb instance's working dir.
    """

    def __init__(self, name: str, base_dir: str | Path, n_shards: int,
                 n_replicas: int = 1):
        self.name = name
        self.base_dir = Path(base_dir)
        self.hostmap = HostMap(n_shards, n_replicas)
        # grid[s][r]: replica r of shard s — the reference's twins
        # within a shard group (Hostdb "num-mirrors"); replica 0 keeps
        # the unsuffixed directory so single-replica layouts carry over
        self.grid = [
            [Collection(name, self.base_dir /
                        (f"shard_{s:03d}" if r == 0
                         else f"shard_{s:03d}_r{r}"))
             for r in range(n_replicas)]
            for s in range(n_shards)
        ]
        #: monotonic corpus mutation counter (invalidates merged-view
        #: caches even when a replace leaves num_docs unchanged)
        self.mutations = 0
        #: site-routed tag store (bans / boundaries / overrides)
        self.tagdb = ShardedTagdb(self)
        # budget-pressure hook: over-budget reserve() asks us to dump
        # fat memtables across the grid before it refuses (held by
        # weakref, so registration never pins a dead collection)
        g_membudget.add_pressure_handler(self._relieve_memory)

    def _relieve_memory(self, need: int) -> int:
        """Flush the grid's largest memtables until ~``need`` bytes are
        freed (the 'dump the tree' arm of the g_mem gate)."""
        freed = 0
        rdbs = [rdb for row in self.grid for coll in row
                for rdb in coll.rdbs().values()
                if rdb.mem.nbytes >= 1 << 20]
        rdbs.sort(key=lambda r: r.mem.nbytes, reverse=True)
        for rdb in rdbs:
            if freed >= need:
                break
            freed += rdb.mem.nbytes
            rdb.dump()
        if freed:
            log.info("budget pressure: dumped %d MB of memtables",
                     freed >> 20)
        return freed

    @property
    def n_shards(self) -> int:
        return self.hostmap.n_shards

    @property
    def shards(self) -> list[Collection]:
        """Serving replica per shard (Multicast pick-best-twin); falls
        back to replica 0 when the whole shard is dead — reads then
        degrade at the query layer, which checks liveness itself."""
        return [self.grid[s][self.hostmap.serving_replica(s) or 0]
                for s in range(self.n_shards)]

    def replicas_of(self, shard: int) -> list[Collection]:
        """All twins of a shard — the write fan-out set (Msg1 adds go to
        every twin, ``Msg1.cpp:20``)."""
        return self.grid[shard]

    @property
    def num_docs(self) -> int:
        return sum(row[0].num_docs for row in self.grid)

    # --- build plane: route records by shard (Msg4 / Msg1 semantics) ---

    def _linkdb_of(self, site: str):
        """The serving linkdb for a site's records (linkee-site routed,
        like the reference's RDB_LINKDB shard map) — read side."""
        return self.shards[self.hostmap.shard_of_site(site)].linkdb

    def _linkdbs_all(self, site: str):
        """All twins' linkdbs for a site — write fan-out."""
        return [c.linkdb for c in
                self.replicas_of(self.hostmap.shard_of_site(site))]

    def site_num_inlinks(self, site: str) -> int:
        return self._linkdb_of(site).site_num_inlinks(site)

    def index_document(self, url: str, content: str, *, is_html: bool = True,
                       siterank: int = 0, langid: int | None = None,
                       propagate: bool = True):
        """Index one document, scattering its records to owning shards
        (the reference's Msg4 meta-list add: posdb keys split by docid/
        termid shard, titledb+clusterdb to the docid's shard, linkdb
        edges to the linkee site's shard)."""
        from ..utils.url import normalize
        u = normalize(url)
        # tagdb gate (XmlDoc::indexDoc EDOCBANNED + SiteGetter boundary
        # + siterank override) — same semantics as the single-node path
        banned, site, sr_override = self.tagdb.index_gate(u)
        if banned:
            self.remove_document(url, propagate=propagate)
            return None
        if sr_override is not None:
            siterank = sr_override
        self.mutations += 1
        old = self.remove_document(url, propagate=False)
        inlinks = self._linkdb_of(site).inlinks_for_url(site, u.full)
        from ..build.tokenizer import tokenize_html, tokenize_text
        tdoc = (tokenize_html(content, u.full) if is_html
                else tokenize_text(content))
        sect_shard = int(self.hostmap.shard_of_site(site))
        boiler = self.shards[sect_shard].sectiondb.boiler_set(
            site, docproc.doc_section_hashes(tdoc).values())
        ml = docproc.build_meta_list(url, content, is_html=is_html,
                                     siterank=siterank, langid=langid,
                                     inlinks=inlinks, site=site,
                                     site_resolver=self.tagdb.site_of,
                                     tdoc=tdoc, boiler_sections=boiler)
        home = int(self.hostmap.shard_of_docid(ml.docid))
        key_shards = self.hostmap.shard_of_keys(ml.posdb_keys)
        # every record goes to ALL twins of its owning shard (the Msg1
        # twin-add fan-out, Msg1.cpp:20)
        for s in np.unique(key_shards):
            for coll in self.replicas_of(int(s)):
                coll.posdb.add(ml.posdb_keys[key_shards == s])
        for coll in self.replicas_of(home):
            coll.titledb.add(ml.titledb_key.reshape(1), [ml.title_rec])
            coll.clusterdb.add(ml.clusterdb_key.reshape(1))
            if ml.fielddb_keys is not None and len(ml.fielddb_keys):
                coll.fielddb.add(ml.fielddb_keys, ml.fielddb_blobs)
            coll.titlerec_cache.pop(ml.docid, None)
            coll.doc_added()
            if ml.words:
                coll.speller.add_doc_words(ml.words)
        for coll in self.replicas_of(sect_shard):
            coll.sectiondb.add_page_sections(site, u.full, ml.sections)
        # outlink edges → linkee-site shards; refresh affected linkees
        # (shared propagate step, including the old version's linkees)
        edges = ml.edges
        for linkee, anchor in edges:
            lk_site = ml.edge_sites.get(linkee.full, linkee.site)
            for ldb in self._linkdbs_all(lk_site):
                ldb.add_link(
                    lk_site, site, u.full, linkee_url=linkee.full,
                    anchor_text=anchor, linker_siterank=siterank)
        ml.refresh_targets = [e[0] for e in edges]
        if old:
            ml.refresh_targets += old.refresh_targets
        if propagate:
            self._refresh_linkees(ml.refresh_targets, site)
        return ml

    def _refresh_linkees(self, linkees, own_site: str) -> None:
        """Breadth-first anchor propagation (iterative worklist in
        :func:`docproc.refresh_linkees`; each reindex is non-propagating
        and feeds its own affected linkees back into the queue)."""
        from ..spider.linkdb import site_rank
        docproc.refresh_linkees(
            linkees, own_site,
            get_doc=lambda lk: self.get_document(_docid_of(lk.full)),
            linkdb_of=self._linkdb_of,
            reindex=lambda lk, rec: self.index_document(
                lk.full, rec.get("content", rec["text"]),
                is_html=rec.get("is_html", True),
                siterank=site_rank(self.site_num_inlinks(
                    self.tagdb.site_of(lk))),
                langid=rec.get("langid"), propagate=False),
            site_of=self.tagdb.site_of)

    def remove_document(self, url: str, propagate: bool = True):
        from ..spider.linkdb import pack_key as link_key
        from ..utils.url import normalize
        self.mutations += 1
        docid = _docid_of(url)
        home = int(self.hostmap.shard_of_docid(docid))
        ml = docproc.get_document(self.shards[home], url=url)
        if ml is None:
            return None
        # regenerate tombstones and scatter them the same way (all twins)
        dead = docproc.tombstone_meta_list(ml)
        key_shards = self.hostmap.shard_of_keys(dead.posdb_keys)
        for s in np.unique(key_shards):
            for coll in self.replicas_of(int(s)):
                coll.posdb.add(dead.posdb_keys[key_shards == s])
        for coll in self.replicas_of(home):
            coll.titledb.add(dead.titledb_key.reshape(1), [b""])
            coll.clusterdb.add(dead.clusterdb_key.reshape(1))
            if dead.fielddb_keys is not None and len(dead.fielddb_keys):
                coll.fielddb.add(dead.fielddb_keys, dead.fielddb_blobs)
            coll.titlerec_cache.pop(dead.docid, None)
            if dead.words:
                coll.speller.remove_doc_words(dead.words)
            coll.doc_removed()
        u = normalize(url)
        for coll in self.replicas_of(
                int(self.hostmap.shard_of_site(dead.site))):
            coll.sectiondb.remove_page_sections(
                dead.site, u.full, ml.get("sections") or [])
        edges = dead.edges
        for linkee, _anchor in edges:
            # delete under the boundary frozen at add time (titlerec map)
            lk_site = dead.edge_sites.get(linkee.full) \
                or self.tagdb.site_of(linkee)
            if lk_site == dead.site:
                continue
            for ldb in self._linkdbs_all(lk_site):
                ldb.rdb.delete(
                    link_key(lk_site, linkee.full, dead.site,
                             u.full).reshape(1))
        dead.refresh_targets = [e[0] for e in edges]
        if propagate:
            self._refresh_linkees(dead.refresh_targets, dead.site)
        return dead

    def get_document(self, docid: int) -> dict | None:
        """Msg22 titlerec fetch from the owning shard."""
        home = int(self.hostmap.shard_of_docid(docid))
        return docproc.get_document(self.shards[home], docid=docid)

    # --- twin patching / replica resync (Msg5 error correction +
    # recovered-twin catch-up) ------------------------------------------

    def scrub(self) -> dict[str, list[str]]:
        """Integrity sweep over every replica's every Rdb; corrupt runs
        are quarantined and immediately healed from a live twin."""
        report: dict[str, list[str]] = {}
        to_heal: list[tuple[int, int]] = []
        # pass 1: scrub EVERY replica before any resync — healing from
        # a not-yet-scrubbed sibling could install ITS undetected
        # corruption over recoverable state (each twin may hold the
        # good copy of a different Rdb)
        for s in range(self.n_shards):
            for r, coll in enumerate(self.grid[s]):
                for name, rdb in coll.rdbs().items():
                    rdb.scrub()
                # includes runs quarantined at LOAD time — a restarted
                # node with corruption found then still needs the patch
                bad = [f"{name}/{run}"
                       for name, rdb in coll.rdbs().items()
                       for run in rdb.quarantined]
                if bad:
                    report[f"shard{s}_r{r}"] = bad
                    to_heal.append((s, r))
        # pass 2: heal
        for s, r in to_heal:
            self.resync_replica(s, r)
        return report

    def resync_replica(self, shard: int, replica: int) -> bool:
        """Rebuild one twin from a healthy sibling — both the corrupt-
        run patch (``Msg5.h:50`` twin correction) and the recovered-
        dead-twin catch-up the reference performs before letting a host
        rejoin its group. Returns False when no healthy source exists."""
        row = self.grid[shard]
        src = None
        for r, cand in enumerate(row):
            if r != replica and self.hostmap.alive[shard, r]:
                src = cand
                break
        if src is None:
            return False
        dst = row[replica]
        for name, srdb in src.rdbs().items():
            drdb = dst.rdbs()[name]
            drdb.replace_with(srdb.get_all())
        dst.num_docs = src.num_docs
        dst._save_stats()
        from collections import defaultdict
        dst.speller.counts = defaultdict(int, src.speller.counts)
        dst.speller._len_index = None
        dst.titlerec_cache.clear()
        self.mutations += 1
        self.hostmap.mark_alive(shard, replica)
        log.info("resynced shard %d replica %d from a twin", shard,
                 replica)
        return True

    def save(self) -> None:
        for row in self.grid:
            for c in row:
                c.save()


# ---------------------------------------------------------------------------
# the sharded kernel (Msg39 per shard + Msg3a merge, one program)
# ---------------------------------------------------------------------------

def _pad_packed(pq: PackedQuery | None, T: int, L: int, D: int,
                plan: QueryPlan, freqw: np.ndarray) -> PackedQuery:
    """Pad one shard's pack to the fleet-wide (T, L, D) bucket; ``None``
    becomes an all-invalid dummy block (empty Msg39 reply)."""
    fl = plan.filters or plan.sortby is not None
    if pq is None:
        required, negative, scored, counts = group_flags(plan, T)
        return PackedQuery(
            doc_idx=np.full((T, L), D, np.int32),
            payload=np.zeros((T, L), np.uint32),
            slot=np.zeros((T, L), np.int32),
            valid=np.zeros((T, L), bool),
            freq_weight=_pad1(freqw, T, 0.5),
            required=required, negative=negative, scored=scored,
            counts=counts, table=pad_table(plan.bool_table),
            cand_docids=np.empty(0, np.uint64),
            siterank=np.zeros(D, np.int32), doclang=np.zeros(D, np.int32),
            n_docs=0, qlang=plan.lang,
            filt=np.zeros(D, bool) if fl else None,
            sortc=np.zeros(D, np.float32) if fl else None,
            use_filter=bool(plan.filters),
            use_sort=plan.sortby is not None)
    t, l = pq.doc_idx.shape
    d = len(pq.siterank)
    doc_idx = np.full((T, L), D, np.int32)
    # re-point this shard's dump row (== its old D pad) at the new one
    di = pq.doc_idx.copy()
    di[di >= d] = D
    doc_idx[:t, :l] = di
    payload = np.zeros((T, L), np.uint32)
    payload[:t, :l] = pq.payload
    slot = np.zeros((T, L), np.int32)
    slot[:t, :l] = pq.slot
    valid = np.zeros((T, L), bool)
    valid[:t, :l] = pq.valid
    siterank = np.zeros(D, np.int32)
    siterank[:d] = pq.siterank
    doclang = np.zeros(D, np.int32)
    doclang[:d] = pq.doclang
    filt = sortc = None
    if pq.filt is not None or pq.sortc is not None or fl:
        filt = np.zeros(D, bool)
        sortc = np.zeros(D, np.float32)
        if pq.filt is not None:
            filt[: len(pq.filt)] = pq.filt
        if pq.sortc is not None:
            sortc[: len(pq.sortc)] = pq.sortc
    return PackedQuery(
        doc_idx=doc_idx, payload=payload, slot=slot, valid=valid,
        freq_weight=_pad1(freqw, T, 0.5),
        required=pq.required, negative=pq.negative,
        scored=pq.scored, counts=pq.counts, table=pq.table,
        cand_docids=pq.cand_docids,
        siterank=siterank, doclang=doclang, n_docs=pq.n_docs,
        qlang=pq.qlang, filt=filt, sortc=sortc,
        use_filter=pq.use_filter, use_sort=pq.use_sort)


@partial(jax.jit, static_argnames=("mesh", "local_k", "out_k",
                                   "n_positions", "use_filter",
                                   "use_sort"))
def _sharded_score(mesh, doc_idx, payload, slot, valid, freq_weight,
                   required, negative, scored, counts, table, siterank,
                   doclang, qlang,
                   n_docs, filt, sortc, local_k: int, out_k: int,
                   n_positions: int = MAX_POSITIONS,
                   use_filter: bool = False, use_sort: bool = False):
    """shard_map program: per-shard intersect+score, in-mesh top-k merge.

    Inputs carry a leading shard axis [S, ...]; outputs are replicated:
    (total matches, merged scores [out_k], owning shard [out_k],
    local idx [out_k]). ``local_k`` caps each shard's contribution (≤ its
    candidate count); the merge then takes the global ``out_k`` best of
    the S·local_k gathered survivors.
    """
    spec = P(SHARD_AXIS)
    rep = P()

    def per_shard(di, pl, sl, va, fw, rq, ng, sc, ct, tb, sr, dl, ql,
                  nd, ft, so):
        n_matched, ts, ti = score_core(
            di[0], pl[0], sl[0], va[0], fw[0], rq[0], ng[0], sc[0],
            ct[0], tb[0], sr[0], dl[0], ql[0], nd[0],
            n_positions=n_positions, topk=local_k,
            filt=ft[0], sortc=so[0],
            use_filter=use_filter, use_sort=use_sort)
        k = ts.shape[0]
        # Msg3a merge as an ICI collective: gather every shard's top-k,
        # take the global top-k (replicated on all shards)
        g_sc = jax.lax.all_gather(ts, SHARD_AXIS)        # [S, k]
        g_ix = jax.lax.all_gather(ti, SHARD_AXIS)        # [S, k]
        g_nm = jax.lax.all_gather(n_matched, SHARD_AXIS)  # [S]
        flat = g_sc.reshape(-1)
        m_sc, m_pos = jax.lax.top_k(flat, min(out_k, flat.shape[0]))
        m_shard = (m_pos // k).astype(jnp.uint32)
        m_local = g_ix.reshape(-1)[m_pos].astype(jnp.uint32)
        # one packed output vector = one host RPC round trip (tunneled
        # backends charge ~50ms per fetched array): [total, shard…,
        # local…, bitcast(score)…]
        return jnp.concatenate([
            jnp.atleast_1d(jnp.sum(g_nm).astype(jnp.uint32)),
            m_shard, m_local,
            jax.lax.bitcast_convert_type(m_sc, jnp.uint32),
        ])

    return _shard_map(
        per_shard, mesh=mesh,
        in_specs=(spec,) * 16,
        out_specs=rep,
    )(doc_idx, payload, slot, valid, freq_weight, required, negative,
      scored, counts, table, siterank, doclang, qlang, n_docs, filt,
      sortc)


def _global_freq_weights(preps: list[PreparedQuery | None],
                         plan: QueryPlan, num_docs: int) -> np.ndarray:
    """Cluster-wide term-frequency weights: per-shard unique-doc counts
    summed — including shards with no candidates, whose postings still
    count toward document frequency (the reference ships global
    termFreqWeights in the Msg39 request, computed at the Msg3a layer).
    Fully-dead shards (None) can't be counted — degraded stats."""
    counts = sum(p.unique_counts for p in preps if p is not None)
    if isinstance(counts, int):  # every shard down
        counts = np.zeros(len(plan.groups), np.int64)
    return weights.term_freq_weight(counts, max(num_docs, 1))


def sharded_search(sc: ShardedCollection, q: str | QueryPlan, *,
                   mesh=None, topk: int = 10, lang: int = 0,
                   offset: int = 0,
                   with_snippets: bool = True,
                   site_cluster: bool = True) -> SearchResults:
    """Scatter-gather query over the mesh (Msg40→Msg3a→Msg39 path)."""
    plan = q if isinstance(q, QueryPlan) else compile_query(q, lang=lang)
    if mesh is None:
        mesh = make_mesh(sc.n_shards)

    # a shard with NO alive twin contributes nothing — not even term
    # stats; the answer is flagged degraded (the reference surfaces dead
    # hosts on PageHosts; silent partial results are a correctness trap)
    serving = [sc.hostmap.serving_replica(s) for s in range(sc.n_shards)]
    degraded = any(r is None for r in serving)
    # cross-shard sort-key base (gbsortby): every shard shifts by the
    # same minimum or the merged ordering is wrong
    sort_base = None
    if plan.sortby is not None:
        from ..query.packer import local_sort_base
        bases = [b for i, c in enumerate(sc.shards)
                 if serving[i] is not None
                 and (b := local_sort_base(c, *plan.sortby)) is not None]
        sort_base = min(bases) if bases else 0.0
    preps = [prepare_query(c, plan, sort_base=sort_base)
             if serving[i] is not None else None
             for i, c in enumerate(sc.shards)]
    freqw = _global_freq_weights(preps, plan, sc.num_docs)

    # dead shards contribute an empty block: the query degrades instead
    # of failing, like Multicast skipping dead twins (Multicast.cpp:520);
    # with replicas configured the replica's collection serves instead
    packs = [pack_pass(p) if p is not None else None for p in preps]
    live = [p for p in packs if p is not None]
    if not live:
        return SearchResults(query=plan.raw, total_matches=0,
                             degraded=degraded,
                             suggestion=suggest_sharded(sc, plan))
    T = max(p.doc_idx.shape[0] for p in live)
    L = max(p.doc_idx.shape[1] for p in live)
    D = max(len(p.siterank) for p in live)
    packs = [_pad_packed(p, T, L, D, plan, freqw) for p in packs]

    # local_k rides the power-of-two bucket ladder: topk+offset is
    # request-controlled, and _sharded_score takes it as a STATIC, so
    # an unbucketed value would mint one shard_map compile per page
    # size (the Msg39 retrace cliff the jit-unstable-static lint bans)
    k = min(_bucket(max(topk + offset, 64), 64), D)
    stack = lambda f: np.stack([f(p) for p in packs])
    args = dict(
        doc_idx=stack(lambda p: p.doc_idx),
        payload=stack(lambda p: p.payload),
        slot=stack(lambda p: p.slot),
        valid=stack(lambda p: p.valid),
        freq_weight=stack(lambda p: p.freq_weight),
        required=stack(lambda p: p.required),
        negative=stack(lambda p: p.negative),
        scored=stack(lambda p: p.scored),
        counts=stack(lambda p: p.counts),
        table=stack(lambda p: p.table),
        siterank=stack(lambda p: p.siterank),
        doclang=stack(lambda p: p.doclang),
        qlang=np.full(sc.n_shards, plan.lang, np.int32),
        n_docs=stack(lambda p: np.int32(p.n_docs)),
        filt=stack(lambda p: p.filt if p.filt is not None
                   else np.zeros(len(p.siterank), bool)),
        sortc=stack(lambda p: p.sortc if p.sortc is not None
                    else np.zeros(len(p.siterank), np.float32)),
    )
    # lay the shard axis over the mesh so each device holds its own block
    sharded_args = {
        name: jax.device_put(
            a, NamedSharding(mesh, P(SHARD_AXIS,
                                     *([None] * (a.ndim - 1)))))
        for name, a in args.items()
    }
    # over-fetch + escalate: if site clustering leaves the page short,
    # re-merge with a larger out_k (the reference's Msg40 recall loop,
    # Msg40.cpp:2117, redesigned as k·c over-fetch per SURVEY §7 hard
    # part (c) — the per-shard scoring is cached, only the merge regrows)
    from ..query.engine import PQR_SCAN, finish_page
    want = max(topk + offset, PQR_SCAN)
    out_k = max(want, 64)
    max_out = sc.n_shards * k
    while True:
        # out_k is static too — bucket it so the escalation ladder
        # (×4 per round) revisits the same compiled programs
        kk = min(_bucket(out_k, 64), max_out)
        out = np.asarray(_sharded_score(
            mesh, sharded_args["doc_idx"], sharded_args["payload"],
            sharded_args["slot"], sharded_args["valid"],
            sharded_args["freq_weight"], sharded_args["required"],
            sharded_args["negative"], sharded_args["scored"],
            sharded_args["counts"], sharded_args["table"],
            sharded_args["siterank"], sharded_args["doclang"],
            sharded_args["qlang"], sharded_args["n_docs"],
            sharded_args["filt"], sharded_args["sortc"],
            local_k=k, out_k=kk,
            use_filter=bool(plan.filters),
            use_sort=plan.sortby is not None))
        total = int(out[0])
        m_shard = out[1:1 + kk].astype(np.int64)
        m_local = out[1 + kk:1 + 2 * kk].astype(np.int64)
        m_sc = out[1 + 2 * kk:].view(np.float32).copy()

        # map (owning shard, local candidate idx) → docid; padded-slot
        # hits score 0 and are filtered inside build_results
        docids = np.zeros(len(m_sc), np.uint64)
        for i, (shard, local) in enumerate(zip(m_shard, m_local)):
            cd = packs[int(shard)].cand_docids
            if int(local) < len(cd):
                docids[i] = cd[int(local)]
            else:
                m_sc[i] = 0.0
        results, clustered = build_results(
            sc.get_document, docids, m_sc, plan, topk=want,
            with_snippets=False, site_cluster=site_cluster)
        if (len(results) >= want or clustered == 0 or out_k >= max_out):
            break
        out_k *= 4
    from ..query.engine import _coll_langid_of
    page = finish_page(
        results, offset=offset, topk=topk,
        conf=sc.shards[0].conf, qlang=plan.lang,
        get_doc=sc.get_document,
        langid_of=lambda d: _coll_langid_of(
            sc.shards[int(sc.hostmap.shard_of_docid(d))])(d),
        words=plan.match_words(),
        with_snippets=with_snippets)
    return SearchResults(
        query=plan.raw, total_matches=int(total), results=page,
        clustered=clustered, degraded=degraded,
        suggestion=suggest_sharded(sc, plan) if total == 0 else None)


# ---------------------------------------------------------------------------
# mesh-resident serving: the Msg3a merge ON the device (one program/wave)
# ---------------------------------------------------------------------------

def _site_cols(coll: Collection):
    """One shard's clusterdb lookup columns (sorted docids + aligned
    sitehash/langid), cached on the clusterdb Rdb version — pack-time
    candidate sitehash columns become one vectorized searchsorted
    instead of D point reads per query."""
    ver = coll.clusterdb.version
    cached = getattr(coll, "_mesh_site_cols", None)
    if cached is not None and cached[0] == ver:
        return cached[1]
    from ..index import clusterdb as cdb
    lst = coll.clusterdb.get_all()
    if len(lst):
        f = cdb.unpack_key(lst.keys)
        order = np.argsort(f["docid"], kind="stable")
        cols = (f["docid"][order].astype(np.uint64),
                f["sitehash"][order].astype(np.uint32),
                f["langid"][order].astype(np.uint32))
    else:
        cols = (np.empty(0, np.uint64), np.empty(0, np.uint32),
                np.empty(0, np.uint32))
    coll._mesh_site_cols = (ver, cols)
    return cols


def _cand_site_cols(coll: Collection, cand: np.ndarray):
    """Candidate docids → (sitehash, langid) uint32 columns. Duplicate
    clusterdb records per docid keep the LATEST (side='right' − 1, the
    same last-wins rule as ``_coll_langid_of``); missing records map to
    0 — exempt from site clustering, like the host walk."""
    docids, sh, lg = _site_cols(coll)
    out_sh = np.zeros(len(cand), np.uint32)
    out_lg = np.zeros(len(cand), np.uint32)
    if len(docids) and len(cand):
        pos = np.searchsorted(docids, cand, side="right") - 1
        ok = pos >= 0
        ok[ok] = docids[pos[ok]] == cand[ok]
        out_sh[ok] = sh[pos[ok]]
        out_lg[ok] = lg[pos[ok]]
    return out_sh, out_lg


def mesh_generation(sc: ShardedCollection) -> tuple:
    """The mesh serving generation: corpus mutations × read topology ×
    per-serving-twin posdb versions. Any write, twin death (mark_dead)
    or recovery moves this tuple; the ResidentLoop's freshness protocol
    then drains in-flight waves against their issue-time base and packs
    the next wave from the NEW serving twins — which is exactly the
    twin-failover story: a dead chip's shard degrades to its twin's
    base with zero lost queries."""
    serving = sc.hostmap.serving_vector()
    return (sc.mutations, serving,
            tuple(sc.grid[s][r].posdb.version if r is not None else -1
                  for s, r in enumerate(serving)))


@partial(jax.jit, static_argnames=("mesh", "local_k", "out_k",
                                   "n_positions", "use_filter",
                                   "use_sort"))
def _mesh_serve(mesh, doc_idx, payload, slot, valid, freq_weight,
                required, negative, scored, counts, table, siterank,
                doclang, qlang, n_docs, filt, sortc, dochi, doclo,
                shash, n_cand, local_k: int, out_k: int,
                n_positions: int = MAX_POSITIONS,
                use_filter: bool = False, use_sort: bool = False):
    """The mesh-resident serving program: one ``shard_map`` per ticket
    wave doing per-shard intersection + scoring (vmapped over the query
    batch), the in-jit all-gather top-k merge, AND the clusterdb
    2-per-site dedup as over-fetch k·c — no host hop anywhere between
    shard search and merged, deduped top-k.

    Inputs carry [S, B, ...]; ``dochi``/``doclo`` are the split uint32
    halves of each shard's candidate docids and ``shash`` the per-
    candidate sitehash ([S, B, D]), so the merge output needs no host
    (shard, local)→docid resolution. ``n_cand`` [S, B] masks pad rows.
    Output is replicated uint32 [B, 3 + 5·out_k]: per query
    ``[total, n_kept, n_dropped, hi…, lo…, sitehash…, bitcast(score)…,
    cumdrop…]`` with survivors compacted to a score-ordered prefix —
    the final tiny block that crosses at the wave's collect boundary.
    """
    spec = P(SHARD_AXIS)

    def one_query(di, pl, sl, va, fw, rq, ng, sc, ct, tb, sr, dl, ql,
                  nd, ft, so, dh, dlo, sh, nc):
        n_matched, ts, ti = score_core(
            di, pl, sl, va, fw, rq, ng, sc, ct, tb, sr, dl, ql, nd,
            n_positions=n_positions, topk=local_k, filt=ft, sortc=so,
            use_filter=use_filter, use_sort=use_sort)
        # pad-candidate hits (idx ≥ this shard's real count) score 0
        ts = jnp.where(ti < nc, ts, 0.0)
        return (n_matched.astype(jnp.uint32), ts, jnp.take(dh, ti),
                jnp.take(dlo, ti), jnp.take(sh, ti))

    def per_shard(di, pl, sl, va, fw, rq, ng, sc, ct, tb, sr, dl, ql,
                  nd, ft, so, dh, dlo, sh, nc):
        # strip the unit shard axis, run the Msg39 intersect for the
        # whole batch on this shard's chip
        nm, ts, hh, ll, shh = jax.vmap(one_query)(
            di[0], pl[0], sl[0], va[0], fw[0], rq[0], ng[0], sc[0],
            ct[0], tb[0], sr[0], dl[0], ql[0], nd[0], ft[0], so[0],
            dh[0], dlo[0], sh[0], nc[0])
        # Msg3a as an ICI collective: every shard's [B, k] block
        g_nm = jax.lax.all_gather(nm, SHARD_AXIS)    # [S, B]
        g_sc = jax.lax.all_gather(ts, SHARD_AXIS)    # [S, B, k]
        g_hh = jax.lax.all_gather(hh, SHARD_AXIS)
        g_ll = jax.lax.all_gather(ll, SHARD_AXIS)
        g_sh = jax.lax.all_gather(shh, SHARD_AXIS)

        def merge_one(sc_q, hh_q, ll_q, sh_q, nm_q):
            n_kept, n_drop, hi, lo, shq, scq, cum = merge_dedup_topk(
                sc_q, hh_q, ll_q, sh_q, out_k,
                max_per_site=MAX_PER_SITE)
            pad = out_k - scq.shape[0]
            if pad:
                z = jnp.zeros(pad, jnp.uint32)
                hi, lo, shq, cum = (jnp.concatenate([a, z]) for a in
                                    (hi, lo, shq, cum))
                scq = jnp.concatenate([scq, jnp.zeros(pad,
                                                      jnp.float32)])
            # explicit uint32 on the reductions: x64 mode promotes
            # uint32 sums to uint64, which would widen the whole row
            return jnp.concatenate([
                jnp.atleast_1d(jnp.sum(nm_q).astype(jnp.uint32)),
                jnp.atleast_1d(n_kept), jnp.atleast_1d(n_drop),
                hi, lo, shq,
                jax.lax.bitcast_convert_type(scq, jnp.uint32),
                cum]).astype(jnp.uint32)

        return jax.vmap(merge_one, in_axes=(1, 1, 1, 1, 1))(
            g_sc, g_hh, g_ll, g_sh, g_nm)

    return _shard_map(per_shard, mesh=mesh, in_specs=(spec,) * 20,
                      out_specs=P())(
        doc_idx, payload, slot, valid, freq_weight, required, negative,
        scored, counts, table, siterank, doclang, qlang, n_docs, filt,
        sortc, dochi, doclo, shash, n_cand)


#: query-batch bucket floor (waves pad to the next power of two so the
#: mesh program's B static revisits compiled shapes)
B_FLOOR = 4

#: over-fetch factor c of the in-program recall ladder: the first
#: merge window is k·c so a page's worth of 2-per-site survivors
#: usually exists without escalation (SURVEY §7 hard part (c))
OVERFETCH_C = 2


@dataclass
class _MeshWave:
    """One dispatched mesh program (a sub-wave of a ticket: plans
    sharing the filter/sort statics). ``args`` keeps the staged device
    operands so the recall escalation re-merges WITHOUT re-packing or
    re-staging — only the merge window (``out_k``) regrows."""
    out: object           # replicated device output [B, 3 + 5·out_k]
    args: dict            # sharded device operands
    qidx: list            # plan indices served by this wave
    local_k: int
    out_k: int
    max_out: int
    use_filter: bool
    use_sort: bool
    stage_key: str = ""   # devwatch mesh_stage ledger column ("" = off)


@dataclass
class MeshPending:
    plans: list
    want: int
    waves: list


class MeshServeIndex:
    """The mesh wave engine behind :class:`MeshResident`'s serving
    path — a ResidentLoop-compatible index (duck type: ``issue_batch``
    / ``collect_batch`` / ``_built_version`` + ``sitehash_of`` /
    ``langid_of``) whose issue dispatches ONE ``shard_map`` program
    across all chips per ticket wave.

    The serving replica set and per-twin posdb versions are frozen
    into ``_built_version`` at build; the loop's drain-before-refresh
    protocol swaps in a fresh index (new twins, new corpus) between
    waves, never under one. Needs ≥ n_shards visible devices (CI
    forces 8 host devices via XLA_FLAGS, conftest.py)."""

    def __init__(self, sc: ShardedCollection, mesh=None):
        self.sc = sc
        self.mesh = mesh if mesh is not None else make_mesh(sc.n_shards)
        self._built_version = mesh_generation(sc)
        serving = sc.hostmap.serving_vector()
        #: pack-time read set: the serving twin per shard, None where
        #: the whole shard is down (its block degrades to the empty
        #: Msg39 reply and the answer is flagged degraded)
        self.colls = [sc.grid[s][r] if r is not None else None
                      for s, r in enumerate(serving)]
        self.degraded = any(c is None for c in self.colls)
        self.total_docs = sc.num_docs

    # --- host-side post-processing lookups (Msg20/Msg51 point reads) ---

    def _home(self, docid: int) -> Collection | None:
        return self.colls[int(self.sc.hostmap.shard_of_docid(docid))]

    def sitehash_of(self, docid: int) -> int:
        c = self._home(docid)
        if c is None:
            return 0
        sh, _ = _cand_site_cols(c, np.asarray([docid], np.uint64))
        return int(sh[0])

    def langid_of(self, docid: int) -> int:
        c = self._home(docid)
        if c is None:
            return 0
        _, lg = _cand_site_cols(c, np.asarray([docid], np.uint64))
        return int(lg[0])

    # --- the issue/collect split the ResidentLoop drives ---------------

    def issue_batch(self, queries, topk: int = 64, lang: int = 0
                    ) -> MeshPending:
        """Pack the wave (host), stage it onto the mesh, dispatch the
        program — returns without blocking on device results."""
        plans = [q if isinstance(q, QueryPlan) else
                 compile_query(q, lang=lang) for q in queries]
        want = max(int(topk), 1)
        # sub-waves by the program's filter/sort statics (a mixed
        # ticket still dispatches before any collect)
        groups: dict[tuple, list[int]] = {}
        for i, plan in enumerate(plans):
            key = (bool(plan.filters), plan.sortby is not None)
            groups.setdefault(key, []).append(i)
        waves = []
        for (use_f, use_s), qidx in groups.items():
            wave = self._issue_wave([plans[i] for i in qidx], qidx,
                                    want, use_f, use_s)
            waves.append(wave)
        return MeshPending(plans=plans, want=want, waves=waves)

    def _issue_wave(self, plans, qidx, want, use_f, use_s):
        sc = self.sc
        S = sc.n_shards
        per_q = []      # (packs[s] | None, freqw) per plan
        for plan in plans:
            sort_base = None
            if plan.sortby is not None:
                from ..query.packer import local_sort_base
                bases = [b for c in self.colls if c is not None
                         and (b := local_sort_base(c, *plan.sortby))
                         is not None]
                sort_base = min(bases) if bases else 0.0
            preps = [prepare_query(c, plan, sort_base=sort_base)
                     if c is not None else None for c in self.colls]
            freqw = _global_freq_weights(preps, plan, self.total_docs)
            per_q.append(([pack_pass(p) if p is not None else None
                           for p in preps], freqw))
        live = [p for packs, _ in per_q for p in packs if p is not None]
        if not live:
            return _MeshWave(out=None, args={}, qidx=list(qidx),
                             local_k=0, out_k=0, max_out=0,
                             use_filter=use_f, use_sort=use_s)
        # fleet-wide buckets across the whole wave: rectangular
        # [S, B, ...] stacks, one compiled program per bucket tuple
        T = max(p.doc_idx.shape[0] for p in live)
        L = max(p.doc_idx.shape[1] for p in live)
        D = max(len(p.siterank) for p in live)
        local_k = min(_bucket(max(want, 64), 64), D)
        B = _bucket(max(len(plans), 1), B_FLOOR)
        rows = []   # per padded-query: (packs[s], plan, freqw)
        for (packs, freqw), plan in zip(per_q, plans):
            rows.append(([_pad_packed(p, T, L, D, plan, freqw)
                          for p in packs], plan, freqw))
        while len(rows) < B:    # pad the batch with empty queries
            plan, freqw = plans[0], per_q[0][1]
            rows.append(([_pad_packed(None, T, L, D, plan, freqw)
                          for _ in range(S)], plan, freqw))

        def cand_cols(s, packs):
            cand = packs[s].cand_docids
            hi = np.zeros(D, np.uint32)
            lo = np.zeros(D, np.uint32)
            sh = np.zeros(D, np.uint32)
            d = len(cand)
            if d and self.colls[s] is not None:
                hi[:d] = (cand >> np.uint64(32)).astype(np.uint32)
                lo[:d] = (cand & np.uint64(0xFFFFFFFF)).astype(
                    np.uint32)
                sh[:d], _ = _cand_site_cols(self.colls[s], cand)
            return hi, lo, sh, d

        stack = lambda f: np.stack(
            [np.stack([f(packs[s]) for packs, _, _ in rows])
             for s in range(S)])
        cols = [[cand_cols(s, packs) for packs, _, _ in rows]
                for s in range(S)]
        args = dict(
            doc_idx=stack(lambda p: p.doc_idx),
            payload=stack(lambda p: p.payload),
            slot=stack(lambda p: p.slot),
            valid=stack(lambda p: p.valid),
            freq_weight=stack(lambda p: p.freq_weight),
            required=stack(lambda p: p.required),
            negative=stack(lambda p: p.negative),
            scored=stack(lambda p: p.scored),
            counts=stack(lambda p: p.counts),
            table=stack(lambda p: p.table),
            siterank=stack(lambda p: p.siterank),
            doclang=stack(lambda p: p.doclang),
            qlang=np.stack([np.asarray([plan.lang for _, plan, _
                                        in rows], np.int32)] * S),
            n_docs=stack(lambda p: np.int32(p.n_docs)),
            filt=stack(lambda p: p.filt if p.filt is not None
                       else np.zeros(len(p.siterank), bool)),
            sortc=stack(lambda p: p.sortc if p.sortc is not None
                        else np.zeros(len(p.siterank), np.float32)),
            dochi=np.stack([np.stack([c[0] for c in cs])
                            for cs in cols]),
            doclo=np.stack([np.stack([c[1] for c in cs])
                            for cs in cols]),
            shash=np.stack([np.stack([c[2] for c in cs])
                            for cs in cols]),
            n_cand=np.stack([np.asarray([c[3] for c in cs], np.int32)
                             for cs in cols]),
        )
        sharded_args = {
            name: jax.device_put(
                a, NamedSharding(self.mesh,
                                 P(SHARD_AXIS, *([None] * (a.ndim - 1)))))
            for name, a in args.items()
        }
        max_out = S * local_k
        out_k = min(_bucket(max(OVERFETCH_C * want, 64), 64), max_out)
        wave = _MeshWave(out=None, args=sharded_args, qidx=list(qidx),
                         local_k=local_k, out_k=out_k, max_out=max_out,
                         use_filter=use_f, use_sort=use_s)
        if devwatch.enabled():
            # transient mesh staging in the HBM ledger: the sharded
            # operands live on-chip from dispatch until collect drops
            # the slot (slot keys cycle mod 8 — bounded vocabulary,
            # and in-flight waves never exceed the loop DEPTH)
            self._stage_seq = getattr(self, "_stage_seq", 0) + 1
            wave.stage_key = f"wave{self._stage_seq % 8}"
            devwatch.note_buffer(
                getattr(self.sc, "name", "mesh"), "mesh_stage",
                wave.stage_key,
                int(sum(a.nbytes for a in args.values())))
        wave.out = self._dispatch(wave)
        return wave

    def _dispatch(self, wave: _MeshWave):
        a = wave.args
        return _mesh_serve(
            self.mesh, a["doc_idx"], a["payload"], a["slot"],
            a["valid"], a["freq_weight"], a["required"], a["negative"],
            a["scored"], a["counts"], a["table"], a["siterank"],
            a["doclang"], a["qlang"], a["n_docs"], a["filt"],
            a["sortc"], a["dochi"], a["doclo"], a["shash"],
            a["n_cand"], local_k=wave.local_k, out_k=wave.out_k,
            use_filter=wave.use_filter, use_sort=wave.use_sort)

    def collect_batch(self, pending: MeshPending):
        """Block on the wave's device output; escalate the merge window
        (×4 out_k, same staged operands — the in-program Msg40 recall
        loop) while a query's survivor prefix is short of ``want`` AND
        its window was fully live. One device fetch per round.

        Returns per plan: ``(docids, scores, total_matches, clustered,
        sitehash)`` — survivors only, already site-deduped."""
        want = pending.want
        results: list = [None] * len(pending.plans)
        empty = (np.empty(0, np.uint64), np.empty(0, np.float32), 0, 0,
                 np.empty(0, np.uint32))
        for wave in pending.waves:
            if wave.out is None:        # every shard down
                for qi in wave.qidx:
                    results[qi] = empty
                continue
            device_s = 0.0
            redispatches = 0
            while True:
                # the mesh wave's ONE blessed host sync (the collect
                # boundary — jitwatch BOUNDARY_SITES lists this file)
                t_fetch = time.perf_counter()
                out = np.asarray(jax.device_get(wave.out))  # osselint: ignore[device-sync] — wave collect boundary
                t_got = time.perf_counter()
                device_s += t_got - t_fetch
                K = wave.out_k
                need_more = False
                for row, qi in zip(out, wave.qidx):
                    n_kept = int(row[1])
                    n_drop = int(row[2])
                    if (n_kept < want and n_kept + n_drop >= K
                            and K < wave.max_out):
                        need_more = True
                        break
                if not need_more:
                    break
                wave.out_k = min(_bucket(wave.out_k * 4, 64),
                                 wave.max_out)
                wave.out = self._dispatch(wave)
                redispatches += 1
            if devwatch.enabled():
                devwatch.note_round(
                    coll=getattr(self.sc, "name", "mesh"),
                    kinds="mesh", waves=1, device_s=device_s,
                    bytes_out=int(out.nbytes), out_k=wave.out_k,
                    escalations=redispatches)
                if wave.stage_key:
                    devwatch.drop_buffer(
                        getattr(self.sc, "name", "mesh"),
                        "mesh_stage", wave.stage_key)
            for row, qi in zip(out, wave.qidx):
                total = int(row[0])
                n_kept = int(row[1])
                n_drop = int(row[2])
                hh = row[3:3 + K].astype(np.uint64)
                ll = row[3 + K:3 + 2 * K].astype(np.uint64)
                sh = row[3 + 2 * K:3 + 3 * K].astype(np.uint32)
                scs = row[3 + 3 * K:3 + 4 * K].view(np.float32)
                cum = row[3 + 4 * K:3 + 5 * K]
                # the greedy walk's clustered counter at the page cut:
                # cumdrop is EXCLUSIVE, so survivor want-1 carries the
                # drops the host walk would have counted before its
                # topk-th accept (it breaks at the top of the next
                # iteration, build_results)
                clustered = (n_drop if n_kept < want
                             else int(cum[want - 1]))
                docids = (hh << np.uint64(32)) | ll
                results[qi] = (docids[:n_kept],
                               scs[:n_kept].astype(np.float32),
                               total, clustered, sh[:n_kept])
        return results


class MeshResident:
    """The PRODUCTION resident index on a device mesh: one
    HBM-resident :class:`~..query.devindex.DeviceIndex` per shard,
    PINNED to its own chip — N shards execute their two-phase /
    direct-cube kernels concurrently on N devices (jit dispatches
    follow the committed operands' device; the host thread pool only
    overlaps the dispatch+fetch round trips).

    Two merge seams coexist here, and which one serves is a mode:

    * ``search_batch`` — the HOST merge: each shard routes every query
      adaptively (F1 κ rung vs direct-cube) by ITS OWN term statistics
      and runs its own lossless escalation ladder, a host-driven loop
      per shard — the reference's Msg39 boundary (``Msg39.cpp:74``)
      with Msg3a merging the tiny top-k replies in numpy.
    * ``serve_batch`` — the MESH-RESIDENT path (the production serving
      mode): one :func:`_mesh_serve` ``shard_map`` program per ticket
      wave under a :class:`~..query.resident.ResidentLoop`, with the
      Msg3a merge, the 2-per-site dedup AND the recall over-fetch all
      in-jit — no host hop between shard search and merge; only the
      final [B, k] (docid, score, sitehash) block crosses at the
      wave's collect boundary.

    Cross-shard score comparability holds on both paths because every
    shard plans with CLUSTER-WIDE term frequencies (global dfs), like
    the reference's Msg39Request termFreqWeights.
    """

    def __init__(self, sc: ShardedCollection, devices=None):
        self.sc = sc
        if devices is None:
            devices = jax.devices()
        if len(devices) < sc.n_shards:
            # fewer chips than shards: wrap (several shards per chip —
            # still correct, just time-shared)
            devices = [devices[s % len(devices)]
                       for s in range(sc.n_shards)]
        # per-shard bases via the sanctioned factory (osselint
        # residency-bypass): the mesh plane owns their lifecycle as a
        # unit — MeshResident.stop(), not per-tenant LRU eviction
        from ..query.engine import build_device_index
        self.indexes = [build_device_index(sc.shards[s],
                                           device=devices[s])
                        for s in range(sc.n_shards)]
        from concurrent.futures import ThreadPoolExecutor
        self._pool = ThreadPoolExecutor(max(sc.n_shards, 1))
        # cluster-wide df memo (satellite of the mesh-serving PR):
        # key = termid, valid while every shard's resident base stays
        # on the generation the memo was filled under
        self._df_memo: dict[int, int] = {}
        self._df_memo_gen = None
        self._serve_idx: MeshServeIndex | None = None
        self._serve_loop = None

    def refresh(self) -> None:
        for di in self.indexes:
            di.refresh()

    def warm(self) -> None:
        list(self._pool.map(lambda di: di.warm(), self.indexes))

    def _global_df(self, termid: int) -> int:
        """Cluster-wide document frequency, memoized per (termid,
        resident-base generation tuple): repeated terms — every wave
        re-plans the same hot query words — pay the S per-shard
        ``_df_of`` walks ONCE per corpus generation instead of per
        plan."""
        gen = tuple(di.df_generation for di in self.indexes)
        if gen != self._df_memo_gen:
            self._df_memo.clear()
            self._df_memo_gen = gen
        df = self._df_memo.get(termid)
        if df is None:
            df = sum(di._df_of(termid) for di in self.indexes)
            self._df_memo[termid] = df
        return df

    def _global_sort_base(self, fld: str, desc: bool) -> float:
        bases = [b for di in self.indexes
                 if (b := di.sort_base_of(fld, desc)) is not None]
        return min(bases) if bases else 0.0

    def search_batch(self, queries, topk: int = 10, lang: int = 0,
                     offset: int = 0, with_snippets: bool = True,
                     site_cluster: bool = True) -> list[SearchResults]:
        """B queries × S shards: per-shard resident kernels run
        concurrently (different chips), then the Msg3a merge + the
        shared Msg40 tail per query."""
        from ..query.engine import PQR_SCAN, finish_page
        sc = self.sc
        plans = [q if isinstance(q, QueryPlan) else
                 compile_query(q, lang=lang) for q in queries]
        total_docs = sc.num_docs
        want = max(topk + offset, PQR_SCAN)
        k_shard = max(want * 2, 64)

        def run_shard(di):
            return di.search_batch(
                plans, topk=k_shard, lang=lang,
                df_of=self._global_df, total_docs=total_docs,
                sort_base_of=self._global_sort_base)

        per_shard = list(self._pool.map(run_shard, self.indexes))

        out = []
        for qi, plan in enumerate(plans):
            docids = np.concatenate(
                [per_shard[s][qi][0] for s in range(sc.n_shards)])
            scores = np.concatenate(
                [per_shard[s][qi][1] for s in range(sc.n_shards)])
            total = sum(int(per_shard[s][qi][2])
                        for s in range(sc.n_shards))
            order = np.argsort(-scores, kind="stable")

            def site_of(docid, _sc=sc):
                home = int(_sc.hostmap.shard_of_docid(docid))
                return self.indexes[home].sitehash_of(docid)

            results, clustered = build_results(
                sc.get_document, docids[order], scores[order], plan,
                topk=want, with_snippets=False,
                site_cluster=site_cluster, site_of=site_of)
            page = finish_page(
                results, offset=offset, topk=topk,
                conf=sc.shards[0].conf, qlang=plan.lang,
                langid_of=lambda d: self.indexes[
                    int(sc.hostmap.shard_of_docid(d))].langid_of(d),
                get_doc=sc.get_document,
                words=plan.match_words(),
                with_snippets=with_snippets)
            from ..query.engine import compute_facets
            out.append(SearchResults(
                query=plan.raw, total_matches=total, results=page,
                clustered=clustered,
                suggestion=suggest_sharded(sc, plan)
                if total == 0 else None,
                facets=compute_facets(plan, docids[order],
                                      sc.get_document)))
        return out

    def search(self, q, **kw) -> SearchResults:
        return self.search_batch([q], **kw)[0]

    # --- the mesh-resident serving path (in-jit Msg3a merge) -----------

    def _serve_index(self) -> MeshServeIndex:
        """Fresh-or-cached :class:`MeshServeIndex` for the CURRENT mesh
        generation — the ResidentLoop's ``di_fn``. A write or a twin
        death moves :func:`mesh_generation`; the loop drains in-flight
        waves first, then this hands it an index packing from the new
        serving twins."""
        idx = self._serve_idx
        if idx is None or idx._built_version != mesh_generation(self.sc):
            idx = MeshServeIndex(self.sc)
            self._serve_idx = idx
        return idx

    def serve_loop(self):
        """The mesh ResidentLoop, spawned lazily (and respawned if
        stopped) — one ticket wave dispatches one mesh program across
        all chips."""
        from ..query.engine import spawn_resident_loop
        loop = self._serve_loop
        if loop is not None and loop.alive:
            return loop
        loop = spawn_resident_loop(
            self._serve_index,
            gen_fn=lambda: mesh_generation(self.sc),
            name=f"mesh-{self.sc.name}")
        self._serve_loop = loop
        return loop

    def serve_batch(self, queries, topk: int = 10, lang: int = 0,
                    offset: int = 0, with_snippets: bool = True,
                    site_cluster: bool = True,
                    results_lock=None) -> list[SearchResults]:
        """The mesh-resident serving path: submit one ticket, get back
        already-merged, already-site-deduped survivors (plus the
        program's clustered counter), run only the shared Msg40 tail
        (summaries/PQR/facets) on the host.

        ``site_cluster=False`` has no in-program variant (the dedup is
        part of the compiled merge) — it routes through the host-merge
        ``search_batch``. ``results_lock`` guards ONLY the host
        post-processing, like ``search_device_batch``."""
        if not site_cluster:
            return self.search_batch(queries, topk=topk, lang=lang,
                                     offset=offset,
                                     with_snippets=with_snippets,
                                     site_cluster=False)
        import contextlib
        from ..query.engine import (PQR_SCAN, compute_facets,
                                    finish_page)
        sc = self.sc
        plans = [q if isinstance(q, QueryPlan) else
                 compile_query(q, lang=lang) for q in queries]
        want = max(topk + offset, PQR_SCAN)
        ticket = self.serve_loop().submit(plans, topk=want, lang=lang)
        raw = ticket.wait()
        msi = ticket.di     # the index the wave actually ran against
        out = []
        lock_ctx = results_lock if results_lock is not None \
            else contextlib.nullcontext()
        with lock_ctx:
            for plan, (docids, scores, total, clustered, shash) in \
                    zip(plans, raw):
                site_map = {int(d): int(h)
                            for d, h in zip(docids, shash)}
                # survivors are already ≤ MAX_PER_SITE per site; the
                # host walk re-counts only drops the program cannot
                # see (content-hash dedup freeing a site slot)
                results, host_cl = build_results(
                    sc.get_document, docids, scores, plan, topk=want,
                    with_snippets=False, site_cluster=True,
                    site_of=lambda d: site_map.get(int(d), 0))
                page = finish_page(
                    results, offset=offset, topk=topk,
                    conf=sc.shards[0].conf, qlang=plan.lang,
                    langid_of=msi.langid_of, get_doc=sc.get_document,
                    words=plan.match_words(),
                    with_snippets=with_snippets)
                out.append(SearchResults(
                    query=plan.raw, total_matches=total, results=page,
                    clustered=clustered + host_cl,
                    degraded=msi.degraded,
                    suggestion=suggest_sharded(sc, plan)
                    if total == 0 else None,
                    facets=compute_facets(plan, docids,
                                          sc.get_document)))
        return out

    def serve(self, q, **kw) -> SearchResults:
        return self.serve_batch([q], **kw)[0]

    def stop(self) -> None:
        """Tear down the serving loop + shard pool (server shutdown)."""
        if self._serve_loop is not None:
            self._serve_loop.stop()
        self._pool.shutdown(wait=False)


def suggest_sharded(sc: ShardedCollection, plan: QueryPlan) -> str | None:
    """Cluster-wide "did you mean": per-shard popularity dictionaries
    merged so a word common on ONE shard is not misdiagnosed as a typo
    (the reference's Speller dict is host-global; ours shards with the
    docs, so the Msg3a layer merges counts). The merged view is cached
    per topology+corpus version — zero-result queries must stay cheap."""
    from ..query.speller import merged
    words = [g.display for g in plan.scored_groups
             if " " not in g.display and ":" not in g.display]
    if not words:
        return None
    serving = [(s, r) for s in range(sc.n_shards)
               if (r := sc.hostmap.serving_replica(s)) is not None]
    if not serving:
        return None
    live = [sc.grid[s][r].speller for s, r in serving]
    # key on the serving (shard, replica) topology, not id(speller):
    # CPython reuses addresses, so a dead speller's id can alias a
    # fresh one and serve a stale merged dictionary
    key = (sc.mutations, tuple(serving))
    cached = getattr(sc, "_merged_speller", None)
    if cached is None or cached[0] != key:
        cached = (key, merged(live))
        sc._merged_speller = cached
    return cached[1].suggest_query(words)
