"""Host/shard map — the cluster topology (Hostdb equivalent).

Reference: ``Hostdb.cpp/h`` — parses ``hosts.conf`` (``num-mirrors:``,
``index-splits:``, host lines, ``Hostdb.cpp:124``), maps keys to shards
(``getShardNum`` ``Hostdb.cpp:2486``), tracks per-host liveness for
failover. On TPU the "hosts" of one slice are mesh devices: one chip ≈ one
index shard (document partition); replicas (the reference's "twins",
``num-mirrors:``) become a second mesh axis when configured, served across
DCN for availability rather than intra-query.

The docid→shard function lives in :mod:`..index.posdb`
(``shard_of_docid``/``shard_of_keys``) so the build plane routes records
identically — this module owns topology + mesh construction.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import numpy as np
from jax.sharding import Mesh

from ..index import posdb

SHARD_AXIS = "shards"
REPLICA_AXIS = "replicas"


def make_mesh(n_shards: int | None = None,
              n_replicas: int = 1,
              devices=None) -> Mesh:
    """Build the query mesh: ``shards`` (× optional ``replicas``) axes.

    With ``n_shards=None`` all visible devices become shards (the
    reference's default one-host-per-shard ``hosts.conf``).
    """
    devices = list(devices if devices is not None else jax.devices())
    if n_shards is None:
        n_shards = len(devices) // n_replicas
    need = n_shards * n_replicas
    if need > len(devices):
        raise ValueError(
            f"mesh needs {need} devices ({n_shards} shards × "
            f"{n_replicas} replicas) but only {len(devices)} visible")
    arr = np.array(devices[:need])
    if n_replicas > 1:
        return Mesh(arr.reshape(n_replicas, n_shards),
                    (REPLICA_AXIS, SHARD_AXIS))
    return Mesh(arr.reshape(n_shards), (SHARD_AXIS,))


@dataclass
class HostMap:
    """Topology record: shard count, replication, and key routing.

    The reference's ``hosts.conf`` distilled: ``index-splits:`` →
    ``n_shards``, ``num-mirrors:`` → ``n_replicas - 1``. Liveness is
    per (shard, replica) — the reference's per-host ping state.
    """

    n_shards: int
    n_replicas: int = 1
    alive: np.ndarray = field(default=None)  # bool [n_shards, n_replicas]
    #: per-twin read-RTT EWMA seconds — the ``pickBestHost`` load
    #: signal (``Multicast.cpp:520`` prefers the less-loaded twin from
    #: its ping/load info; here a twin bogged down by a merge or heal
    #: answers slower and organically sheds read traffic)
    rtt_s: np.ndarray = field(default=None)  # float [n_shards, n_replicas]

    def __post_init__(self):
        if self.alive is None:
            self.alive = np.ones((self.n_shards, self.n_replicas),
                                 dtype=bool)
        if self.rtt_s is None:
            self.rtt_s = np.zeros((self.n_shards, self.n_replicas),
                                  dtype=np.float64)

    def shard_of_docid(self, docid) -> np.ndarray:
        return posdb.shard_of_docid(docid, self.n_shards)

    def shard_of_keys(self, keys: np.ndarray) -> np.ndarray:
        return posdb.shard_of_keys(keys, self.n_shards)

    def shard_of_site(self, site: str) -> int:
        """Linkdb routing: records shard by LINKEE SITE hash so site
        inlink counts and anchor harvests are single-shard reads
        (reference ``getShardNum(RDB_LINKDB)`` keys by linkee site,
        ``Hostdb.cpp:~2514``). Derived from the same 32-bit site hash
        the linkdb KEY embeds, so Rebalance can re-route linkdb records
        from raw keys (linkdb.shard_of_keys agrees by construction)."""
        from ..spider.linkdb import _h32
        from ..utils import ghash
        return int(ghash.hash64_array(
            np.asarray([_h32(site)], np.uint64))[0]
            % np.uint64(self.n_shards))

    def mark_dead(self, shard: int, replica: int = 0) -> None:
        """PingServer dead-host marking (``PingServer.h:61``)."""
        self.alive[shard, replica] = False

    def mark_alive(self, shard: int, replica: int = 0) -> None:
        self.alive[shard, replica] = True

    def serving_replica(self, shard: int) -> int | None:
        """First alive replica of a shard — the read-side twin pick
        (``Multicast::pickBestHost`` skips dead twins,
        ``Multicast.cpp:520``); None when the whole shard is down."""
        for r in range(self.n_replicas):
            if self.alive[shard, r]:
                return r
        return None

    def serving_vector(self) -> tuple:
        """Read-side topology snapshot: the serving replica per shard
        (None where a whole shard is down). Part of the mesh serving
        GENERATION — when a twin dies (``mark_dead``) this tuple moves,
        the ResidentLoop drains in-flight waves against their
        issue-time bases, and the next wave packs from the surviving
        twin; a kill therefore loses zero queries."""
        return tuple(self.serving_replica(s)
                     for s in range(self.n_shards))

    def hosts_up(self) -> int:
        """Live host count across the whole grid (the fleet scrape's
        ``cluster.scrape_hosts_up`` gauge, from this map's view)."""
        return int(self.alive.sum())

    def serving_ok(self) -> bool:
        """Every shard has at least one alive twin — the availability
        predicate a rolling restart must hold between node stops (take
        one host down only while its twin can absorb the traffic)."""
        return all(self.serving_replica(s) is not None
                   for s in range(self.n_shards))

    def observe_rtt(self, shard: int, replica: int, dt_s: float) -> None:
        """Fold one completed read's latency into the twin's EWMA."""
        prev = self.rtt_s[shard, replica]
        self.rtt_s[shard, replica] = (dt_s if prev == 0.0
                                      else 0.8 * prev + 0.2 * dt_s)

    def penalize(self, shard: int, replica: int,
                 dt_s: float = 1.0) -> None:
        """Degrade a twin's load signal without a completed read (it
        failed a request or sat on one past the hedge delay) — slow is
        not dead, but it should stop being the primary."""
        self.rtt_s[shard, replica] += dt_s

    def decay_rtt(self, shard: int, replica: int,
                  factor: float = 0.9) -> None:
        """Shrink a twin's penalty toward zero — called for each twin
        that answers a health ping, so penalty earned while it was dead
        or wedged drains once it recovers instead of demoting it
        forever (the EWMA only improves through reads it will never be
        offered as long as it sorts last)."""
        self.rtt_s[shard, replica] *= factor

    def twin_order(self, shard: int) -> list[int]:
        """Replicas of a shard in read-preference order: alive first,
        then fastest observed — the hedged read launches down this
        list."""
        return sorted(range(self.n_replicas),
                      key=lambda r: (not self.alive[shard, r],
                                     float(self.rtt_s[shard, r])))
