"""Fleet plane — every shard node a REAL OS process under one supervisor.

Reference: Gigablast ran one ``gb`` binary per host across ~200 servers.
``hosts.conf`` (Hostdb.cpp:124) was the cluster map every instance got
at boot; ``gb start`` ssh'd the fleet up, PingServer probed it,
``gb stop``/``gb save`` broadcast orderly shutdown/checkpoint, and parm
changes rode the 0x3f broadcast to every host live (SURVEY §6, §7
stage 7). Until this module, our "cluster" was threads in one Python
process — one GIL, one fate domain, shared caches — so the transport's
hedging, the chaos kills, and the fleet scrape had never crossed a real
process boundary.

:class:`FleetManager` is that ancestry on one machine:

* spawns the ``node`` subcommand (``python -m <pkg> node``) once per
  (shard, replica), each child booting from its checkpoint dir with the
  serialized hosts.conf map, its seat in it, and the chaos seed in
  ``OSSE_CHAOS`` (rate 0: seams armed, only aimed faults fire);
* waits on a ``/rpc/ping`` readiness probe over the pooled transport;
* supervises children — an UNEXPECTED death (the chaos plane's real
  SIGKILL) respawns with exponential backoff, and the node's journal
  replay is what makes that restart lossless;
* tears down by process group: children are session leaders
  (``start_new_session``), so ``killpg`` reaps them and anything they
  spawned, and an ``atexit`` finalizer per manager guarantees no test
  run leaks orphans even when the caller never reaches ``shutdown()``;
* ``rolling_restart`` drains each node through its admission gate
  (stop admitting → in-flight waves collect → ``/rpc/save`` →
  SIGTERM, SIGKILL on timeout) while the twin absorbs traffic via the
  transport's hedging;
* ``broadcast_parms`` is the live 0x3f update: applied on every node,
  no restarts (the replies carry pids to prove it).

Data dirs use ShardedCollection's naming (``shard_SSS[_rR]``), so a
fleet base dir doubles as a grid for the offline ``rebalance`` path —
the cross-process shard-split gate in bench.py rides that.
"""

from __future__ import annotations

import atexit
import os
import signal
import socket
import subprocess
import sys
import time
from pathlib import Path

from ..utils import deadline as deadline_mod
from ..utils import threads
from ..utils.lockcheck import make_lock
from ..utils.log import get_logger
from ..utils.stats import g_stats
from . import transport as transport_mod
from .cluster import HostsConf

log = get_logger("fleet")

PKG = "open_source_search_engine_tpu"

READY_TIMEOUT_S = 120.0   # cold child = full jax import before bind
STOP_TIMEOUT_S = 15.0     # SIGTERM grace (save under the writer lock)
BACKOFF_BASE_S = 0.25
BACKOFF_CAP_S = 5.0
SUPERVISE_INTERVAL_S = 0.1


def _grid_dirname(shard: int, replica: int) -> str:
    """ShardedCollection's layout (replica 0 unsuffixed) so the fleet
    base dir IS a loadable shard grid for rebalance/repair."""
    return (f"shard_{shard:03d}" if replica == 0
            else f"shard_{shard:03d}_r{replica}")


class _Child:
    """One supervised node process slot (survives respawns)."""

    __slots__ = ("shard", "replica", "port", "data_dir", "proc",
                 "restarts", "expected_exit", "next_respawn_at")

    def __init__(self, shard: int, replica: int, port: int,
                 data_dir: Path):
        self.shard = shard
        self.replica = replica
        self.port = port
        self.data_dir = data_dir
        self.proc: subprocess.Popen | None = None
        #: unexpected-death respawn count (backoff driver; reset once
        #: the respawned child answers a readiness probe)
        self.restarts = 0
        #: set before an ON-PURPOSE stop so the supervisor does not
        #: fight the operator by resurrecting a drained node
        self.expected_exit = False
        self.next_respawn_at = 0.0


class FleetManager:
    """Spawn, probe, supervise, and reap a grid of real node processes."""

    def __init__(self, base_dir: str | Path, n_shards: int = 2,
                 n_replicas: int = 2, host: str = "127.0.0.1",
                 chaos_seed: int | None = None, supervise: bool = True,
                 env: dict | None = None,
                 ready_timeout_s: float = READY_TIMEOUT_S):
        self.base_dir = Path(base_dir)
        self.base_dir.mkdir(parents=True, exist_ok=True)
        self.host = host
        self.chaos_seed = chaos_seed
        self.supervise = supervise
        self.extra_env = dict(env or {})
        self.ready_timeout_s = float(ready_timeout_s)
        self.transport = transport_mod.Transport()
        self._lock = make_lock("fleet.manager")
        self._stopping = False
        self._supervisor = None
        #: wall-clock-seeded like ClusterClient's parm counter so a
        #: fresh manager never replays below a node's persisted seq
        self._parm_seq = int(time.time() * 1000)
        ports = self._reserve_ports(n_shards * n_replicas)
        self.conf = HostsConf(
            n_shards, n_replicas,
            [[f"{host}:{ports[s * n_replicas + r]}"
              for r in range(n_replicas)] for s in range(n_shards)])
        self.hosts_path = self.base_dir / "hosts.conf"
        self.hosts_path.write_text(self.conf.dump())
        self._children = {
            (s, r): _Child(s, r, ports[s * n_replicas + r],
                           self.base_dir / _grid_dirname(s, r))
            for s in range(n_shards) for r in range(n_replicas)}
        # the orphan-reaper guarantee: registered per manager (no
        # module-global registry to share between request threads),
        # unregistered again once shutdown() has reaped everything
        atexit.register(self._atexit_reap)

    # --- topology helpers -------------------------------------------------

    @property
    def n_shards(self) -> int:
        return self.conf.n_shards

    @property
    def n_replicas(self) -> int:
        return self.conf.n_replicas

    def addr(self, shard: int, replica: int) -> str:
        return self.conf.addresses[shard][replica]

    def addrs(self) -> list[str]:
        return [self.conf.addresses[s][r]
                for s in range(self.n_shards)
                for r in range(self.n_replicas)]

    def data_dir(self, shard: int, replica: int) -> Path:
        return self._children[(shard, replica)].data_dir

    def pid(self, shard: int, replica: int) -> int | None:
        proc = self._children[(shard, replica)].proc
        return proc.pid if proc is not None else None

    def pids(self) -> dict[tuple[int, int], int | None]:
        return {sr: (c.proc.pid if c.proc else None)
                for sr, c in self._children.items()}

    def alive(self, shard: int, replica: int) -> bool:
        proc = self._children[(shard, replica)].proc
        return proc is not None and proc.poll() is None

    def surviving_pids(self) -> list[int]:
        """Child pids still alive RIGHT NOW — the teardown-hygiene
        assertion every fleet test makes (empty after shutdown)."""
        out = []
        for c in self._children.values():
            if c.proc is None:
                continue
            if c.proc.poll() is not None:
                continue  # exited (poll also reaps a zombie child)
            try:
                os.kill(c.proc.pid, 0)
            except (ProcessLookupError, PermissionError):
                continue
            out.append(c.proc.pid)
        return out

    @staticmethod
    def _reserve_ports(n: int) -> list[int]:
        """Bind-to-0 / record / close: the kernel hands out n distinct
        free ports the children re-bind moments later (the window is a
        loopback race accepted everywhere this pattern appears)."""
        socks, ports = [], []
        for _ in range(n):
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            socks.append(s)
            ports.append(s.getsockname()[1])
        for s in socks:
            s.close()
        return ports

    # --- spawn / readiness ------------------------------------------------

    def _child_env(self) -> dict:
        env = dict(os.environ)
        repo_root = str(Path(__file__).resolve().parents[2])
        env["PYTHONPATH"] = repo_root + (
            os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH") else "")
        # children default to CPU: N node processes fighting over one
        # TPU would serialize on device init; override via env= to put
        # a fleet on real devices deliberately
        env.setdefault("JAX_PLATFORMS", "cpu")
        if self.chaos_seed is not None:
            env["OSSE_CHAOS"] = str(self.chaos_seed)
            # seams armed + replayable, zero AMBIENT faults: only what
            # the parent aims (fleet_fault, configure over /rpc) fires
            env.setdefault("OSSE_CHAOS_RATE", "0")
        env.update(self.extra_env)
        return env

    def _spawn(self, child: _Child) -> None:
        argv = [sys.executable, "-m", PKG, "node",
                "--dir", str(child.data_dir),
                "--host", self.host, "--port", str(child.port),
                "--hosts", str(self.hosts_path),
                "--shard", str(child.shard),
                "--replica", str(child.replica)]
        # start_new_session: the child leads its own session AND
        # process group (pgid == pid), so killpg reaps it plus any
        # grandchildren, and our own SIGINT never propagates into it
        child.proc = subprocess.Popen(
            argv, env=self._child_env(), start_new_session=True,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        child.expected_exit = False
        g_stats.count("fleet.spawn")
        log.info("spawned node s%dr%d pid=%d port=%d", child.shard,
                 child.replica, child.proc.pid, child.port)

    def start_all(self) -> None:
        """Spawn the whole grid, wait until every node answers ping,
        then start the supervisor."""
        for child in self._children.values():
            self._spawn(child)
        for (s, r) in self._children:
            self.wait_ready(s, r)
        if self.supervise and self._supervisor is None:
            self._supervisor = threads.spawn("fleet-supervisor",
                                             self._supervise_loop)

    def wait_ready(self, shard: int, replica: int,
                   timeout_s: float | None = None) -> dict:
        """Poll ``/rpc/ping`` until the node answers; returns the ping
        reply (identity-checked). Raises on timeout or when the child
        died and nobody will respawn it."""
        child = self._children[(shard, replica)]
        addr = self.addr(shard, replica)
        dl = deadline_mod.Deadline.after(
            timeout_s if timeout_s is not None else self.ready_timeout_s)
        while not dl.expired():
            out = self.transport.probe(addr, timeout=1.0)
            if out is not None:
                if ("shard" in out
                        and (out["shard"], out["replica"])
                        != (shard, replica)):
                    raise RuntimeError(
                        f"node at {addr} reports seat "
                        f"s{out['shard']}r{out['replica']}, expected "
                        f"s{shard}r{replica}")
                child.restarts = 0  # healthy: reset the backoff ladder
                return out
            proc = child.proc
            dead = proc is None or proc.poll() is not None
            will_respawn = (self._supervisor is not None
                            and not child.expected_exit)
            if dead and not will_respawn:
                raise RuntimeError(
                    f"node s{shard}r{replica} exited "
                    f"(rc={proc.poll() if proc else None}) before "
                    "answering ping")
            time.sleep(0.05)
        raise TimeoutError(
            f"node s{shard}r{replica} at {addr} not ready in time")

    # --- supervision (restart-and-backoff) --------------------------------

    def _supervise_loop(self) -> None:
        while not self._stopping:
            time.sleep(SUPERVISE_INTERVAL_S)
            now = time.monotonic()
            for child in self._children.values():
                with self._lock:
                    proc = child.proc
                    if (self._stopping or child.expected_exit
                            or proc is None or proc.poll() is None):
                        continue
                    if child.next_respawn_at == 0.0:
                        # first sighting of this corpse: schedule the
                        # respawn one backoff step out
                        delay = min(BACKOFF_CAP_S,
                                    BACKOFF_BASE_S * (2 ** child.restarts))
                        child.next_respawn_at = now + delay
                        g_stats.count("fleet.child_died")
                        log.warning(
                            "node s%dr%d died (rc=%s); respawn in "
                            "%.2fs", child.shard, child.replica,
                            proc.poll(), delay)
                        continue
                    if now < child.next_respawn_at:
                        continue
                    child.restarts += 1
                    child.next_respawn_at = 0.0
                    self._spawn(child)
                    g_stats.count("fleet.restart")

    # --- chaos entry points ----------------------------------------------

    def kill(self, shard: int, replica: int,
             sig: int = signal.SIGKILL) -> int:
        """Signal a node like the chaos plane would (default kill -9 —
        no save, no atexit; journal replay is the recovery). The
        supervisor treats the death as unexpected and respawns."""
        child = self._children[(shard, replica)]
        if child.proc is None:
            raise RuntimeError(f"node s{shard}r{replica} not running")
        pid = child.proc.pid
        os.kill(pid, sig)
        g_stats.count("fleet.kill")
        return pid

    # --- orderly stop / restart -------------------------------------------

    def stop_node(self, shard: int, replica: int,
                  timeout_s: float = STOP_TIMEOUT_S) -> int | None:
        """SIGTERM (the node saves + exits via its signal handler),
        escalate to killpg-SIGKILL past the grace window."""
        child = self._children[(shard, replica)]
        proc = child.proc
        if proc is None:
            return None
        with self._lock:
            child.expected_exit = True
        try:
            os.kill(proc.pid, signal.SIGTERM)
        except ProcessLookupError:
            pass
        try:
            rc = proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            log.warning("node s%dr%d ignored SIGTERM; killpg", shard,
                        replica)
            self._killpg(proc, signal.SIGKILL)
            rc = proc.wait()
        return rc

    def start_node(self, shard: int, replica: int,
                   wait: bool = True) -> None:
        """(Re)spawn one node slot on its reserved port/dir."""
        child = self._children[(shard, replica)]
        with self._lock:
            if child.proc is not None and child.proc.poll() is None:
                raise RuntimeError(
                    f"node s{shard}r{replica} already running")
            self._spawn(child)
        if wait:
            self.wait_ready(shard, replica)

    def rolling_restart(self, drain_timeout_s: float = 10.0) -> dict:
        """Restart every node, one at a time, the reference's orderly
        way: drain through the admission gate (new work sheds to the
        twin via hedging / the client's parked write queue), let
        in-flight waves collect, checkpoint via ``/rpc/save``, SIGTERM,
        respawn, and only move on once the reborn node answers ping —
        so at most one twin per shard is ever down."""
        report: dict = {"nodes": [], "sheds": 0}
        for (s, r) in sorted(self._children):
            addr = self.addr(s, r)
            drained = self._rpc(addr, "/rpc/drain",
                                {"timeout_s": drain_timeout_s},
                                timeout=drain_timeout_s + 5.0)
            saved = self._rpc(addr, "/rpc/save", {}, timeout=60.0)
            self.stop_node(s, r)
            self.start_node(s, r, wait=True)
            report["nodes"].append({
                "node": f"s{s}r{r}",
                "drained": bool(drained and drained.get("drained")),
                "saved": bool(saved and saved.get("ok")),
                "sheds": int(drained.get("sheds", 0)) if drained
                else 0})
            report["sheds"] += report["nodes"][-1]["sheds"]
            g_stats.count("fleet.rolled")
        return report

    # --- live parm broadcast ----------------------------------------------

    def broadcast_parms(self, parms: dict) -> dict[str, dict | None]:
        """The 0x3f live-update, fleet-wide and bulk: one ``/rpc/parms``
        to every node, one sequence number for the batch; applied with
        no restart (replies carry each node's pid so callers can prove
        it)."""
        with self._lock:
            self._parm_seq += 1
            seq = self._parm_seq
        return self.transport.broadcast(
            self.addrs(), "/rpc/parms",
            {"parms": dict(parms), "seq": seq}, timeout=10.0)

    def _rpc(self, addr: str, path: str, payload: dict,
             timeout: float = 10.0) -> dict | None:
        try:
            return self.transport.request(addr, path, payload,
                                          timeout=timeout)
        except Exception as e:  # noqa: BLE001 — callers gate on None
            log.warning("fleet rpc %s %s failed: %s", addr, path, e)
            return None

    # --- teardown ---------------------------------------------------------

    @staticmethod
    def _killpg(proc: subprocess.Popen, sig: int) -> None:
        try:
            os.killpg(proc.pid, sig)  # pgid == pid (start_new_session)
        except (ProcessLookupError, PermissionError):
            pass

    def shutdown(self, timeout_s: float = STOP_TIMEOUT_S) -> None:
        """Reap the whole fleet: SIGTERM every process group, escalate
        to SIGKILL past the grace window, wait, and only then drop the
        atexit finalizer. Idempotent; never leaves orphans."""
        self._stopping = True
        with self._lock:
            for child in self._children.values():
                child.expected_exit = True
        live = [c.proc for c in self._children.values()
                if c.proc is not None and c.proc.poll() is None]
        for proc in live:
            self._killpg(proc, signal.SIGTERM)
        dl = deadline_mod.Deadline.after(timeout_s)
        for proc in live:
            try:
                proc.wait(timeout=max(0.05, dl.remaining()))
            except subprocess.TimeoutExpired:
                pass
        for proc in live:
            if proc.poll() is None:
                self._killpg(proc, signal.SIGKILL)
                proc.wait()
        self.transport.close()
        atexit.unregister(self._atexit_reap)
        log.info("fleet down (%d processes reaped)", len(live))

    def _atexit_reap(self) -> None:
        """Last-resort orphan reaper: if the owner never reached
        shutdown() (test body raised, operator ^C'd), nuke every child
        process group on interpreter exit."""
        for child in self._children.values():
            proc = child.proc
            if proc is not None and proc.poll() is None:
                self._killpg(proc, signal.SIGKILL)

    # --- context manager sugar --------------------------------------------

    def __enter__(self) -> "FleetManager":
        self.start_all()
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()
