"""Multi-process host plane — shards as separate node processes.

This is the reference's L2 made real across process boundaries: a node
process owns one shard replica (a :class:`~..index.collection.Collection`
plus its device index) and serves a small RPC surface; a client-side
:class:`ClusterClient` routes work by the same key→shard maps the
in-process plane uses. Reference semantics carried over:

* **Topology from a hosts.conf-style file** (``Hostdb.cpp:124``):
  ``num-mirrors: M`` then one ``host:port`` line per node; the first
  ``n_shards`` lines are replica 0, the next ``n_shards`` replica 1, …
* **Writes go to ALL twins, retry-forever to dead ones**
  (``Msg1.cpp:20``): a failed delivery parks in a per-host retry queue
  that redelivers in the background until the twin answers — a
  restarted node catches up from the queue (plus its own durable Rdb
  state) without any resync ceremony.
* **Reads pick the serving twin and reroute on failure**
  (``Multicast.cpp:520`` ``pickBestHost``): a connection error or
  timeout marks the host dead and retries the next twin immediately;
  when every twin of a shard is down the query still answers, flagged
  ``degraded=True`` (the silent-partial-results trap from round 2).
* **Heartbeats** (``PingServer.h:61``): a background prober pings every
  node and maintains the alive matrix; recovered hosts are marked
  alive again and immediately serve.

The courier is :mod:`.transport` (stdlib HTTP, but no longer boring):
pooled keep-alive connections per host, hedged twin reads with RTT
EWMAs, per-shard query batching, and a negotiated binary codec for the
bulk routes — the ``UdpServer.cpp``/``Multicast.cpp`` roles over HTTP.
The *semantics* here stay the work: scatter-gather queries (the Msg3a
merge) run the per-shard execution in parallel and merge top-k
host-side; inside each node the query still runs on the TPU-resident
two-phase kernel, so ICI does the per-shard heavy lifting and this
plane is the DCN/control story.
"""

from __future__ import annotations

import json
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

import numpy as np

from ..cache import g_cacheplane
from ..index.collection import Collection
from ..utils import chaos as chaos_mod
from ..utils import deadline as deadline_mod
from ..utils import ghash
from ..utils import priority as priority_mod
from ..utils import threads
from ..utils import trace as trace_mod
from ..utils.lockcheck import make_lock, make_rlock
from ..utils.log import get_logger
from ..utils.stats import g_stats, merge_wire
from . import transport as transport_mod
from .hostmap import HostMap
from .transport import BIN_CONTENT_TYPE, RpcError, Transport, as_array

log = get_logger("cluster")

RPC_TIMEOUT_S = 10.0
#: interactive reads that can legitimately run long (deep paging, big
#: escalations) get their own budget — a 10 s cap would reroute to the
#: twin (doubling work) and falsely mark slow-but-alive hosts dead
SEARCH_TIMEOUT_S = 60.0
PING_TIMEOUT_S = 1.5
SCRAPE_TIMEOUT_S = 2.0
RETRY_INTERVAL_S = 1.0
HEARTBEAT_INTERVAL_S = 1.0


# ---------------------------------------------------------------------------
# topology file (hosts.conf, Hostdb.cpp:124)
# ---------------------------------------------------------------------------

@dataclass
class HostsConf:
    """Parsed hosts.conf: addresses[shard][replica] = "host:port"."""

    n_shards: int
    n_replicas: int
    addresses: list[list[str]]  # [shard][replica]

    @classmethod
    def parse(cls, text: str) -> "HostsConf":
        mirrors = 0
        hosts: list[str] = []
        for line in text.splitlines():
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            if line.startswith("num-mirrors:"):
                mirrors = int(line.split(":", 1)[1])
            else:
                hosts.append(line)
        n_replicas = mirrors + 1
        if not hosts or len(hosts) % n_replicas:
            raise ValueError(
                f"hosts.conf: {len(hosts)} hosts not divisible by "
                f"{n_replicas} replicas")
        n_shards = len(hosts) // n_replicas
        addresses = [[hosts[r * n_shards + s] for r in range(n_replicas)]
                     for s in range(n_shards)]
        return cls(n_shards, n_replicas, addresses)

    @classmethod
    def load(cls, path: str | Path) -> "HostsConf":
        return cls.parse(Path(path).read_text())

    def dump(self) -> str:
        lines = [f"num-mirrors: {self.n_replicas - 1}"]
        for r in range(self.n_replicas):
            lines += [self.addresses[s][r] for s in range(self.n_shards)]
        return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# node side (the per-shard RPC server)
# ---------------------------------------------------------------------------

class ShardNodeServer:
    """One shard replica as a process: Collection + RPC surface.

    Endpoints (the live msgType registry, SURVEY §2.4, as paths):
    ``/rpc/index`` (Msg7/Msg4 add), ``/rpc/remove``, ``/rpc/search``
    (Msg39 per-shard exec), ``/rpc/doc`` (Msg22 titlerec), ``/rpc/ping``
    (PingServer), ``/rpc/save`` (gb save broadcast).
    """

    def __init__(self, data_dir: str | Path, host: str = "127.0.0.1",
                 port: int = 0, use_device: bool = False,
                 use_cache: bool = True, shard: int = 0,
                 replica: int = 0,
                 cluster_map: "HostsConf | None" = None):
        self.coll = Collection("shard", data_dir)
        #: this node's seat in the fleet and the Hostdb-style map it was
        #: handed at spawn (hosts.conf semantics: every gb instance
        #: boots knowing the whole topology, Hostdb.cpp:124) — lets the
        #: node name its twins for heal pulls and report its identity
        #: on /rpc/ping so the supervisor can verify placement
        self.shard = int(shard)
        self.replica = int(replica)
        self.cluster_map = cluster_map
        # per-shard results feed the CLIENT-side merge, which applies
        # PostQueryRerank once over the merged page — node-side PQR
        # would demote twice and skew the cross-shard merge
        self.coll.conf.pqr_enabled = False
        self.host = host
        self.port = port
        self.use_device = use_device
        self._httpd: ThreadingHTTPServer | None = None
        self._lock = make_rlock("cluster.node_writer")  # single-writer core
        #: TCP connections accepted since start — with a pooled client
        #: this stays ~1 per peer; it climbing with request count means
        #: keep-alive broke somewhere
        self.accepts = 0
        self._accept_lock = make_lock("cluster.accepts")
        #: live accepted sockets: stop() must sever them, or a handler
        #: thread parked on a keep-alive connection outlives the
        #: "stopped" server and keeps answering for a dead node
        self._conns: set = set()
        #: background RPCs (X-Niceness: 1 — spider writes, heal pulls)
        #: yield to in-flight interactive reads at the door, BEFORE
        #: contending for the writer lock (UdpProtocol.h niceness bit)
        from ..utils.nice import NicenessGate
        self.nice_gate = NicenessGate()
        # crash journal (Msg4.cpp:115 addsinprogress.dat): adds are
        # journaled BEFORE they are acked, replayed on restart, and the
        # journal truncates whenever the memtable state is saved — so a
        # SIGKILL'd node recovers every acked write
        self._journal_path = Path(data_dir) / "addsinprogress.jsonl"
        self._replay_journal()
        self._recount_docs()
        self._journal = open(self._journal_path, "a",  # noqa: SIM115
                             encoding="utf-8")
        self._writes_since_save = 0
        #: writes accepted while a heal pull is in flight (replayed on
        #: top of the pulled snapshot — see heal_from)
        self._heal_buffer: list[dict] | None = None
        #: last applied parm-broadcast sequence per name (0x3f dedup)
        self._parm_seq: dict[str, int] = {}
        #: per-shard search-result cache (the Msg39 leg of the RdbCache
        #: story): normalized (total, docids, scores) per (q, topk,
        #: lang), generation-keyed on posdb.version so any accepted
        #: write invalidates everything in O(1). Checked inside
        #: handle(), so coalesced batch riders hit it too.
        _coll = self.coll
        self._search_cache = g_cacheplane.register(
            "node.search", ttl_s=30.0, max_entries=4096,
            gen_fn=lambda: _coll.posdb.version,
            desc="per-shard /rpc/search replies (Msg39 result cache)")
        if not use_cache:
            self._search_cache.enabled = False
        #: metrics registry served by /rpc/stats — the process-wide
        #: g_stats by default; in-process multi-node tests inject a
        #: private Stats per node so a scrape-merge is a real merge
        #: instead of the singleton merged with itself
        self.stats_registry = g_stats
        #: per-node admission door on the data-plane RPCs. Configured as
        #: a pure capacity + drain gate (the SLO/membudget degrade
        #: ladder stays at the coordinator, so the signal fns are off):
        #: its job here is bounding concurrent work per process and
        #: being the point a rolling restart closes before checkpoint.
        #: Runtime-layer import: parallel/ stays import-light on serve/
        #: (the tier vocabulary already lives in utils/priority).
        from ..serve.admission import AdmissionGate
        self.admission = AdmissionGate(max_inflight=64, max_queue=512,
                                       max_wait_s=5.0,
                                       degraded_fn=lambda: False,
                                       pressure_fn=lambda: False)

    def _replay_journal(self) -> None:
        from ..build import docproc

        if not self._journal_path.exists():
            return
        n = 0
        for line in self._journal_path.read_text(
                encoding="utf-8").splitlines():
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
                if rec.get("op") == "remove":
                    docproc.remove_document(self.coll, rec["url"])
                else:
                    docproc.index_document(self.coll, rec["url"],
                                           rec["content"])
                n += 1
            except Exception as e:  # noqa: BLE001 — torn tail line etc.
                log.warning("journal replay skipped a record: %s", e)
        if n:
            log.info("replayed %d journaled adds", n)

    def _recount_docs(self) -> None:
        """collstats.json is save-time state — a kill -9 loses it even
        though BOTH journal layers (rdblite's addsinprogress + ours)
        restore every acked record, and replaying an add whose titlerec
        survived is a replace that never re-counts. On boot, trust the
        Rdbs: the live doc count is the merged titledb's positive
        keys."""
        from ..index import titledb as titledb_mod

        batch = self.coll.titledb.get_all()
        n = 0
        if len(batch):
            n = int((titledb_mod.unpack_key(batch.keys)["delbit"]
                     == 1).sum())
        if n != self.coll.num_docs:
            log.info("doc count recomputed from titledb: %d "
                     "(collstats said %d)", n, self.coll.num_docs)
            self.coll.num_docs = n
            self.coll._save_stats()

    def _journal_write(self, rec: dict) -> None:
        self._journal.write(json.dumps(rec) + "\n")
        self._journal.flush()
        os.fsync(self._journal.fileno())

    # --- request handlers -------------------------------------------------

    #: data-plane routes pass the per-node admission door; control
    #: routes (ping/stats/drain/save/parm[s]/heal) must keep answering
    #: while the gate is draining — a rolling restart still needs to
    #: probe, checkpoint, and observe the node it is about to stop
    GATED_RPCS = frozenset({"/rpc/index", "/rpc/remove", "/rpc/search",
                            "/rpc/doc", "/rpc/pull", "/rpc/pull-all"})

    def handle(self, path: str, payload: dict) -> dict:
        if path == "/rpc/drain":
            # stop admitting, let in-flight waves collect. Shed write
            # RPCs reply ok=False, so they park in the coordinator's
            # ordered twin queue and redeliver after the restart; shed
            # reads 503 into the transport's instant twin failover.
            self.admission.drain()
            quiesced = self.admission.quiesce(
                float(payload.get("timeout_s", 10.0)))
            snap = self.admission.snapshot()
            return {"ok": True, "drained": bool(quiesced),
                    "inflight": snap["inflight"],
                    "sheds": snap["shed_total"]}
        if path == "/rpc/undrain":
            self.admission.resume()
            return {"ok": True}
        if path in self.GATED_RPCS:
            from ..serve.admission import Shed
            tier = priority_mod.current_tier() or "interactive"
            try:
                ticket = self.admission.admit(
                    tier, deadline_mod.current())
            except Shed as e:
                return {"ok": False, "error": f"shed:{e.reason}",
                        "shed": e.reason,
                        "retry_after_s": e.retry_after_s}
            with ticket:
                return self._handle(path, payload)
        return self._handle(path, payload)

    def _handle(self, path: str, payload: dict) -> dict:
        from ..build import docproc
        from ..query import engine

        if path == "/rpc/ping":
            # lock-free: a long write/checkpoint must not fail heartbeats
            return {"ok": True, "docs": self.coll.num_docs,
                    "accepts": self.accepts,
                    "shard": self.shard, "replica": self.replica,
                    "pid": os.getpid(),
                    "draining": self.admission.draining}
        if path == "/rpc/conf":
            # read-only conf dump (ops + broadcast verification)
            return {"ok": True, "conf": self.coll.conf.to_dict()}
        if path == "/rpc/stats":
            # lock-free like ping: a wedged writer must not blind the
            # fleet scrape. Raw histogram buckets, not percentiles —
            # the coordinator merges distributions (Tail at Scale).
            return {"ok": True, "host": self.host, "port": self.port,
                    "stats": self.stats_registry.wire()}
        if path == "/rpc/heal":
            # outside the writer lock: heal_from pulls for minutes and
            # takes the lock only for its atomic apply step — holding
            # it here would block every index/search on this node
            n = self.heal_from(payload["from"])
            return {"ok": True, "healed_rdbs": n}
        with self._lock:
            if path == "/rpc/index":
                self._journal_write({"url": payload["url"],
                                     "content": payload["content"]})
                if self._heal_buffer is not None:
                    self._heal_buffer.append(
                        {"url": payload["url"],
                         "content": payload["content"]})
                ml = docproc.index_document(
                    self.coll, payload["url"], payload["content"])
                self._maybe_checkpoint_locked()
                if ml is None:  # tagdb manualban — the DELIVERY
                    # succeeded (ok), the document was refused; ok=False
                    # would park the write and wedge the ordered queue
                    return {"ok": True, "banned": True,
                            "gen": self.coll.posdb.version}
                return {"ok": True, "docid": int(ml.docid),
                        "gen": self.coll.posdb.version}
            if path == "/rpc/remove":
                self._journal_write({"op": "remove",
                                     "url": payload["url"]})
                if self._heal_buffer is not None:
                    self._heal_buffer.append({"op": "remove",
                                              "url": payload["url"]})
                ok = docproc.remove_document(self.coll, payload["url"])
                return {"ok": bool(ok),
                        "gen": self.coll.posdb.version}
            if path == "/rpc/search":
                if deadline_mod.check_abandon("node.search"):
                    # second checkpoint past the dequeue one: the wait
                    # for the writer lock may have eaten what was left
                    # of the budget — abandon before the device wave
                    raise deadline_mod.DeadlineExceeded(
                        "deadline exceeded")
                topk = int(payload.get("topk", 10))
                lang = int(payload.get("lang", 0))
                # replies are cached per (q, topk, lang) under the
                # CURRENT posdb generation — stable while we hold the
                # writer lock, so a reply can never mix generations
                gen = self.coll.posdb.version
                if "queries" in payload:
                    # batched scatter-gather: the client coalesces
                    # concurrent callers per shard; one device dispatch
                    # (search_device_batch vmaps the whole batch)
                    # instead of a request per query. Cache is checked
                    # PER RIDER: a repeated query that coalesced into a
                    # fresh batch still hits.
                    qs = [str(q) for q in payload["queries"]]
                    entries: list = [None] * len(qs)
                    miss = []
                    for i, q in enumerate(qs):
                        hit, e = self._search_cache.lookup(
                            (q, topk, lang), gen=gen)
                        if hit:
                            entries[i] = e
                        else:
                            miss.append(i)
                    if miss:
                        mqs = [qs[i] for i in miss]
                        if self.use_device:
                            many = engine.search_device_batch(
                                self.coll, mqs, topk=topk, lang=lang,
                                with_snippets=False, site_cluster=False)
                        else:
                            many = [engine.search(
                                self.coll, q, topk=topk, lang=lang,
                                with_snippets=False, site_cluster=False)
                                for q in mqs]
                        for i, r in zip(miss, many):
                            e = {"total": r.total_matches,
                                 "docids": np.asarray(
                                     [int(x.docid) for x in r.results],
                                     dtype=np.int64),
                                 "scores": np.asarray(
                                     [float(x.score)
                                      for x in r.results],
                                     dtype=np.float64)}
                            self._search_cache.put((qs[i], topk, lang),
                                                   e, gen=gen)
                            entries[i] = e
                    g_stats.count("transport.node_batched_q", len(qs))
                    return {"ok": True, "results": entries, "gen": gen}
                q = str(payload["q"])
                hit, e = self._search_cache.lookup((q, topk, lang),
                                                   gen=gen)
                if not hit:
                    search = (engine.search_device if self.use_device
                              else engine.search)
                    res = search(self.coll, q, topk=topk,
                                 lang=lang,
                                 with_snippets=False,
                                 site_cluster=False)
                    e = {"total": res.total_matches,
                         "docids": np.asarray(
                             [int(r.docid) for r in res.results],
                             dtype=np.int64),
                         "scores": np.asarray(
                             [float(r.score) for r in res.results],
                             dtype=np.float64)}
                    self._search_cache.put((q, topk, lang), e, gen=gen)
                return {
                    "ok": True,
                    "total": e["total"],
                    "docids": [int(x) for x in e["docids"]],
                    "scores": [float(x) for x in e["scores"]],
                    "gen": gen,
                }
            if path == "/rpc/doc":
                from ..build.docproc import get_document
                rec = get_document(self.coll,
                                   docid=int(payload["docid"]))
                return {"ok": rec is not None, "doc": rec}
            if path == "/rpc/save":
                self.save()
                return {"ok": True}
            if path == "/rpc/parm":
                # live parm update (the 0x3f broadcast receive side,
                # Parms.cpp:21683): host0's client sequences updates;
                # stale/replayed sequence numbers are acked but not
                # applied (retry-forever redelivery may duplicate)
                seq = int(payload.get("seq", 0))
                name = payload["name"]
                if seq <= self._parm_seq.get(name, -1):
                    return {"ok": True, "stale": True}
                try:
                    self.coll.conf.set(name, payload["value"],
                                       _from_sync=True)
                except KeyError as e:
                    return {"ok": False, "error": str(e)}
                self._parm_seq[name] = seq
                # persist: the parm must survive this node's restart
                self.coll.conf.save(self.coll._conf_path)
                log.info("parm %s=%r applied (seq %d)", name,
                         payload["value"], seq)
                return {"ok": True}
            if path == "/rpc/parms":
                # bulk live-update (the whole `gb save`-style broadcast
                # in one RPC): same per-name sequence dedup as
                # /rpc/parm, one conf.save for the batch, applied with
                # NO process restart — the reply carries this node's
                # pid so the caller can prove that
                seq = int(payload.get("seq", 0))
                applied: list[str] = []
                errors: dict[str, str] = {}
                for name, value in dict(payload.get("parms",
                                                    {})).items():
                    if seq <= self._parm_seq.get(name, -1):
                        continue
                    try:
                        self.coll.conf.set(name, value, _from_sync=True)
                    except KeyError as e:
                        errors[name] = str(e)
                        continue
                    self._parm_seq[name] = seq
                    applied.append(name)
                if applied:
                    self.coll.conf.save(self.coll._conf_path)
                    log.info("parms %s applied (seq %d)",
                             ",".join(applied), seq)
                return {"ok": not errors, "applied": applied,
                        "errors": errors, "pid": os.getpid()}
            if path == "/rpc/pull":
                # twin-patch send side (Msg5 error correction): ship one
                # Rdb's full merged content to a healing sibling
                name = payload["name"]
                if name == "speller":
                    return {"ok": True,
                            "counts": dict(self.coll.speller.counts)}
                rdb = self.coll.rdbs().get(name)
                if rdb is None:
                    return {"ok": False, "error": f"no rdb {name}"}
                return {"ok": True, "batch": _encode_batch(rdb.get_all()),
                        "num_docs": self.coll.num_docs}
            if path == "/rpc/pull-all":
                # single CONSISTENT cut: every Rdb + speller + num_docs
                # snapshotted under the writer lock — a healing sibling
                # must never mix Rdb generations (titledb holding a doc
                # whose posdb postings are missing)
                return {
                    "ok": True,
                    "rdbs": {name: _encode_batch(rdb.get_all())
                             for name, rdb in self.coll.rdbs().items()},
                    "counts": dict(self.coll.speller.counts),
                    "num_docs": self.coll.num_docs,
                }
        raise KeyError(path)

    def scrub(self) -> list[str]:
        """Integrity sweep over this node's Rdbs (quarantines corrupt
        runs; the operator heals via /rpc/heal from a twin)."""
        with self._lock:
            return [f"{name}/{run}"
                    for name, rdb in self.coll.rdbs().items()
                    for run in rdb.scrub()]

    def heal_from(self, addr: str) -> int:
        """Twin-patch receive side: replace every local Rdb with the
        sibling's content (also the recovered-twin catch-up — a node
        that was dead while writes flowed rejoins consistent).

        Consistency, both directions: the SOURCE snapshots all Rdbs in
        ONE /rpc/pull-all held under its writer lock (a single cut —
        never titledb from one generation and posdb from another), and
        the RECEIVER keeps accepting writes during the multi-second
        pull, buffering them and replaying them on top of the applied
        snapshot — so nothing delivered in the pull window is lost."""
        from ..build import docproc

        with self._lock:
            if self._heal_buffer is not None:
                log.warning("heal from %s refused: heal already in "
                            "progress", addr)
                return 0
            self._heal_buffer = []
        try:
            out = _rpc(addr, "/rpc/pull-all", {}, timeout=300.0,
                       niceness=1)
            if not out.get("ok"):
                raise RuntimeError(out.get("error", "pull-all not ok"))
            pulled = out["rdbs"]
            missing = [n for n in self.coll.rdbs() if n not in pulled]
            if missing:
                # apply nothing: a partial snapshot would leave mixed
                # Rdb generations — the exact state heal exists to fix
                raise RuntimeError(f"snapshot missing rdbs {missing}")
        except Exception as e:  # noqa: BLE001 — transport/sibling death
            with self._lock:
                self._heal_buffer = None
            log.error("heal from %s aborted before applying: %s",
                      addr, e)
            return 0
        with self._lock:
            try:
                for name, rdb in self.coll.rdbs().items():
                    rdb.replace_with(_decode_batch(pulled[name]))
                self.coll.num_docs = out.get("num_docs",
                                             self.coll.num_docs)
                if "counts" in out:
                    from collections import defaultdict
                    self.coll.speller.counts = defaultdict(
                        int, out["counts"])
                    self.coll.speller._len_index = None
                self.coll.titlerec_cache.clear()
                # replay the pull-window writes on the fresh snapshot
                # (they were applied to the OLD state, which
                # replace_with just discarded; the journal still holds
                # them for crash safety)
                buf = self._heal_buffer or []
                for rec in buf:
                    try:
                        if rec.get("op") == "remove":
                            docproc.remove_document(self.coll,
                                                    rec["url"])
                        else:
                            docproc.index_document(
                                self.coll, rec["url"], rec["content"])
                    except Exception as e:  # noqa: BLE001
                        log.warning("heal replay skipped a record: %s",
                                    e)
                self.coll._save_stats()
                log.info("healed %d rdbs from %s (+%d pull-window "
                         "writes replayed)", len(pulled), addr,
                         len(buf))
                return len(pulled)
            finally:
                self._heal_buffer = None

    def save(self) -> None:
        """Checkpoint under the writer lock; the saved state supersedes
        the journal (Msg4 truncates addsinprogress once trees save)."""
        with self._lock:
            self.coll.save()
            self._journal.seek(0)
            self._journal.truncate()
            self._writes_since_save = 0

    def _maybe_checkpoint_locked(self) -> None:
        """Bound journal growth/replay cost: checkpoint every few
        hundred acked writes (caller holds the writer lock)."""
        self._writes_since_save += 1
        if self._writes_since_save >= 512:
            self.coll.save()
            self._journal.seek(0)
            self._journal.truncate()
            self._writes_since_save = 0

    # --- lifecycle --------------------------------------------------------

    def start(self) -> None:
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # keep-alive is the whole point of the client's connection
            # pool, and HTTP/1.0 (the BaseHTTPRequestHandler default)
            # closes after every response — 1.1 + the explicit
            # Content-Length below keeps the socket open
            protocol_version = "HTTP/1.1"
            # headers and body go out as two writes; with Nagle on, the
            # body write stalls on the peer's delayed ACK (~40 ms) on
            # every KEEP-ALIVE request — fresh dials dodge it via
            # quick-ack, which would make the pool look slower than
            # dial-per-call
            disable_nagle_algorithm = True

            def setup(self):
                super().setup()
                # one setup() per ACCEPTED connection (many requests
                # ride each under keep-alive) — the pool-effectiveness
                # signal surfaced via /rpc/ping
                with outer._accept_lock:
                    outer.accepts += 1
                    outer._conns.add(self.connection)

            def finish(self):
                with outer._accept_lock:
                    outer._conns.discard(self.connection)
                super().finish()

            def log_message(self, fmt, *args):
                log.debug("%s " + fmt, self.client_address[0], *args)

            def do_POST(self):
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b"{}"
                try:
                    nice = int(self.headers.get("X-Niceness") or 0)
                except ValueError:
                    nice = 0
                # honor the coordinator's priority verdict: a crawlbot
                # leg yields inside this host too (its tier maps to the
                # niceness bit the gate below already enforces), and
                # the tier is re-bound so further fan-out keeps it
                tier = priority_mod.tier_from_header(
                    self.headers.get(priority_mod.PRIORITY_HEADER))
                if tier is not None:
                    g_stats.count(f"admission.node.{tier}")
                    nice = max(nice, priority_mod.tier_niceness(tier))
                # the tenant rides the same way: re-bound so this
                # node's accounting (and any further fan-out) bills
                # the coordinator's ledger
                tenant = priority_mod.tenant_from_header(
                    self.headers.get(priority_mod.TENANT_HEADER))
                accept_bin = BIN_CONTENT_TYPE in (
                    self.headers.get("Accept") or "")
                # adopt an incoming trace context: run the handler
                # under a local root span and ship the finished
                # subtree back in the reply for the coordinator to
                # graft into its tree (Dapper-style child spans)
                tr_hdr = trace_mod.parse_header(
                    self.headers.get(trace_mod.TRACE_HEADER) or "")
                # rebuild the coordinator's deadline from the budget it
                # shipped (wall clocks don't cross hosts; budgets do)
                dl = deadline_mod.Deadline.from_header(
                    self.headers.get(deadline_mod.DEADLINE_HEADER))
                outer.nice_gate.enter(nice)
                try:
                    if chaos_mod.g_chaos.enabled:
                        chaos_mod.g_chaos.node_fault(outer)
                    if deadline_mod.check_abandon("node.dequeue", dl):
                        # the coordinator already timed out — abandon
                        # at the door, before the writer lock and the
                        # device wave burn work nobody is waiting for
                        out, code = {"ok": False,
                                     "error": "deadline exceeded"}, 504
                    else:
                        payload = transport_mod.decode_body(
                            body, self.headers.get("Content-Type", ""))
                        with deadline_mod.bind(dl), \
                                priority_mod.bind_tier(tier), \
                                priority_mod.bind_tenant(tenant):
                            if tr_hdr is not None:
                                with trace_mod.g_tracer.adopt(
                                        tr_hdr[0], tr_hdr[1],
                                        self.path.lstrip("/"),
                                        host=f"{outer.host}:{outer.port}"
                                        ) as adopted:
                                    out = outer.handle(self.path,
                                                       payload)
                                if isinstance(out, dict):
                                    out["_trace"] = adopted.export()
                            else:
                                out = outer.handle(self.path, payload)
                        code = 200
                except KeyError:
                    out, code = {"error": "no such rpc"}, 404
                except Exception as e:  # noqa: BLE001 — node must not die
                    out, code = {"error": str(e)}, 500
                finally:
                    outer.nice_gate.exit(nice)
                # reply codec: binary only when the peer advertised it
                # (old clients never do → JSON wire, unchanged bytes);
                # errors stay JSON so any peer can read them
                data, ctype = transport_mod.encode_body(
                    out, accept_bin and code == 200)
                try:
                    self.send_response(code)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(data)))
                    # every reply advertises this node's Rdb generation:
                    # the client cache plane folds it in (transport
                    # gen_observer) so even a read reply reveals that a
                    # write landed — no stale window beyond one
                    # in-flight read
                    self.send_header(transport_mod.GEN_HEADER,
                                     str(outer.coll.posdb.version))
                    self.end_headers()
                    self.wfile.write(data)
                except OSError:
                    # connection severed under us (stop() / a chaos
                    # kill) — the client's hedge already treats this
                    # leg as failed; don't let the handler thread die
                    # loudly
                    self.close_connection = True

            do_GET = do_POST

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        threads.spawn(f"shard-node-{self.port}",
                      self._httpd.serve_forever)
        log.info("shard node on %s:%d (%d docs)", self.host, self.port,
                 self.coll.num_docs)

    def stop(self) -> None:
        # claim-then-close so concurrent stops (a chaos kill from a
        # side thread racing a test/operator teardown) are safe: only
        # one caller gets the live httpd, the rest see None
        httpd, self._httpd = self._httpd, None
        if httpd:
            httpd.shutdown()
            httpd.server_close()
        # sever live keep-alive connections: their handler threads
        # would otherwise keep serving this "stopped" node (a process
        # kill severs them for free; in-process stop must match)
        with self._accept_lock:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            try:
                import socket as _socket
                c.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass


# ---------------------------------------------------------------------------
# client side (Msg1 writes / Msg0+Multicast reads / Msg3a merge)
# ---------------------------------------------------------------------------

def _encode_batch(batch) -> dict:
    """RecordBatch → wire dict of raw ndarrays. The transport layer
    picks the codec per peer: length-prefixed raw frames on the binary
    wire, base64 ``.npy`` strings on the JSON fallback (byte-compatible
    with the pre-pool wire, so old clients keep decoding)."""
    out = {}
    for nm, arr in (("keys", batch.keys), ("offsets", batch.offsets),
                    ("data", batch.data)):
        if arr is None:
            continue
        out[nm] = np.ascontiguousarray(arr)
    return out


def _decode_batch(d: dict):
    """Wire dict (raw ndarrays OR base64 .npy strings) → RecordBatch."""
    from ..index.rdblite import RecordBatch
    arrs = {nm: as_array(v) for nm, v in d.items()}
    return RecordBatch(arrs["keys"], arrs.get("offsets"),
                       arrs.get("data"))


def _rpc(addr: str, path: str, payload: dict,
         timeout: float = RPC_TIMEOUT_S, niceness: int = 0) -> dict:
    """One RPC over the process-wide pooled transport. ``niceness``
    rides an X-Niceness header (the UdpProtocol.h niceness bit): 1 =
    background traffic the receiving node may hold while interactive
    requests are in flight."""
    return transport_mod.g_transport.request(addr, path, payload,
                                             timeout=timeout,
                                             niceness=niceness)


@dataclass
class _Pending:
    """One undelivered write (the Msg1 retry-forever unit)."""

    shard: int
    replica: int
    path: str
    payload: dict
    attempts: int = 0


class _HostQueue:
    """Per-host ORDERED redelivery queue.

    Ordering is the point: once a host has parked writes, every later
    write to that host must line up behind them — delivering a new
    write around an old one would make the stale version the newest
    memtable insertion on the twin (newest-wins would then resurrect
    it). Drains stop at the first failure so order is preserved."""

    def __init__(self):
        self.items: list[_Pending] = []
        self.lock = make_lock("cluster.hostqueue")
        self.in_flight = False

    def __len__(self) -> int:
        with self.lock:
            return len(self.items)


class _ShardSearchBatcher:
    """Per-shard query coalescing — the cluster-plane analog of the
    serving side's ``QueryBatcher``: concurrent callers hitting the
    same shard within one batching window ride ONE ``/rpc/search``
    carrying a query list, which the node executes as a single
    ``search_device_batch`` dispatch. On loopback the window is ~2 ms;
    across DCN it is hidden entirely inside the shard RTT."""

    WINDOW_S = 0.002
    MAX_B = 64

    def __init__(self, client: "ClusterClient", shard: int):
        self.client = client
        self.shard = shard
        self._cv = threading.Condition()
        #: (key, query, holder) — key groups compatible requests
        self._queue: list[tuple] = []
        self._thread: threading.Thread | None = None

    def submit(self, q: str, topk: int, lang: int,
               timeout: float, parent_span=None,
               deadline=None, tier=None,
               tenant=None) -> dict | None:
        holder = {"done": False, "out": None}
        with self._cv:
            self._queue.append(((topk, lang), q, holder, parent_span,
                                deadline, tier, tenant))
            if self._thread is None or not self._thread.is_alive():
                self._thread = threads.spawn(
                    f"shard{self.shard}-qbatch", self._run)
            self._cv.notify_all()
        wait_dl = deadline_mod.Deadline.after(timeout + 5.0)
        with self._cv:
            while not holder["done"]:
                left = wait_dl.remaining()
                if left <= 0:
                    break
                self._cv.wait(left)
        return holder["out"]

    def _run(self) -> None:
        while True:
            with self._cv:
                if not self._queue:
                    self._cv.wait(timeout=5.0)
                    if not self._queue:
                        self._thread = None
                        return  # idle — next submit restarts us
            time.sleep(self.WINDOW_S)  # let concurrent callers pile in
            with self._cv:
                key = self._queue[0][0]
                batch = [e for e in self._queue if e[0] == key]
                batch = batch[: self.MAX_B]
                for e in batch:
                    self._queue.remove(e)
            try:
                self._issue(key, batch)
            except Exception as e:  # noqa: BLE001 — keep the lane alive
                log.warning("shard %d batch failed: %s", self.shard, e)
                with self._cv:
                    for entry in batch:
                        entry[2]["done"] = True
                    self._cv.notify_all()

    def _issue(self, key: tuple, batch: list) -> None:
        topk, lang = key
        qs = [e[1] for e in batch]
        # the batcher runs in its own thread (empty contextvars
        # context); re-attach the first waiter's span so the coalesced
        # RPC lands in SOME trace, and give every other waiter a
        # completed "coalesced" marker span covering the same interval
        parents = [e[3] for e in batch if e[3] is not None]
        primary = parents[0] if parents else None
        # the coalesced RPC carries the LONGEST rider budget — a
        # short-deadline rider must not abandon every other rider's
        # answer (its own coordinator still times out client-side) —
        # and the HIGHEST rider tier (a crawlbot rider must not demote
        # an interactive rider's leg on the node planes)
        dls = [e[4] for e in batch if e[4] is not None]
        dl = max(dls, key=lambda d: d.at) if dls else None
        tiers = [e[5] for e in batch if e[5] is not None]
        tier = (min(tiers, key=priority_mod.TIERS.index)
                if tiers else None)
        # riders of one coalesced leg share a coordinator/collection,
        # so the first bound tenant speaks for the wave
        tenants = [e[6] for e in batch
                   if len(e) > 6 and e[6] is not None]
        tenant = tenants[0] if tenants else None
        t0 = time.perf_counter()
        with trace_mod.attach(primary), deadline_mod.bind(dl), \
                priority_mod.bind_tier(tier), \
                priority_mod.bind_tenant(tenant):
            # span_parent rides along so the hedged read's per-attempt
            # spans (hedge fired/won) land in the primary rider's trace
            out = self.client._read_shard(
                self.shard, "/rpc/search",
                {"queries": qs, "topk": topk, "lang": lang},
                timeout=SEARCH_TIMEOUT_S, span_parent=primary)
            results = out.get("results") if out else None
            if not isinstance(results, list) or len(results) != len(qs):
                # old node (no batch support → 404 on "queries") or a
                # malformed reply: legacy single-query wire, one per entry
                g_stats.count("transport.batch_fallback")
                results = [self.client._read_shard(
                    self.shard, "/rpc/search",
                    {"q": q, "topk": topk, "lang": lang},
                    timeout=SEARCH_TIMEOUT_S) for q in qs]
        for p in parents[1:]:
            p.record("rpc/search", t0, coalesced=True,
                     shard=self.shard, batch=len(qs))
        with self._cv:
            for e, res in zip(batch, results):
                e[2]["out"] = res
                e[2]["done"] = True
            self._cv.notify_all()


class ClusterClient:
    """Routes adds/reads/queries across the node processes."""

    def __init__(self, conf: HostsConf, use_heartbeat: bool = True,
                 parms=None, transport: Transport | None = None,
                 use_cache: bool = True):
        self.conf = conf
        #: optional global Conf (utils.parms) — supplies alert_cmd etc.
        self.parms = parms
        #: pooled/hedged courier — own instance so tests can isolate
        #: pools, but any Transport (e.g. a JSON-only one) drops in
        self.transport = transport or Transport()
        self.hostmap = HostMap(conf.n_shards, conf.n_replicas)
        # --- cache-plane generation tracking (per shard) -----------------
        # A shard's generation is the PAIR (local write counter, highest
        # node gen observed). The local counter bumps BEFORE a write is
        # sent — dependent entries die the instant the write is
        # initiated, not when the node acks, so there is no stale
        # window. The node half folds in X-OSSE-Gen reply headers: a
        # write from ANOTHER client shows up at our next read of any
        # kind and invalidates our entries too.
        self._gen_lock = make_lock("cluster.gen")
        self._gen_local = [0] * conf.n_shards
        self._gen_node = [0] * conf.n_shards
        self._addr_shard = {conf.addresses[s][r]: s
                            for s in range(conf.n_shards)
                            for r in range(conf.n_replicas)}
        self.transport.gen_observer = self._observe_gen
        #: per-(shard, query) leg cache: the Msg0/termlist-cache role —
        #: one shard's raw top-k for one query; generation = that
        #: shard's pair only, so a write on shard 1 never flushes
        #: shard 0's legs
        self._leg_cache = g_cacheplane.register(
            "cluster.legs", ttl_s=30.0, max_entries=8192,
            desc="per-shard raw search legs (Msg0 role)")
        #: merged front result cache: the Msg17/Msg40Cache role — the
        #: whole scatter-gather+merge+titlerec answer; generation = the
        #: full shard-gen vector (any shard's write invalidates)
        self._result_cache = g_cacheplane.register(
            "cluster.results", ttl_s=30.0, max_entries=1024,
            gen_fn=self.gen_vector,
            desc="merged cluster SERPs (Msg17/Msg40Cache role)")
        if not use_cache:
            self._leg_cache.enabled = False
            self._result_cache.enabled = False
        self._queues = {(s, r): _HostQueue()
                        for s in range(conf.n_shards)
                        for r in range(conf.n_replicas)}
        self._batchers = {s: _ShardSearchBatcher(self, s)
                          for s in range(conf.n_shards)}
        #: 0x3f broadcast sequencer (this client == the host0 role).
        #: Seeded from the wall clock so a RESTARTED host0 client's
        #: sequence numbers stay above everything the nodes have seen
        #: (an in-memory counter restarting at 0 would make every
        #: post-restart broadcast look stale and be silently dropped)
        self._parm_counter = int(time.time() * 1000)
        self._stop = threading.Event()
        self._pool = ThreadPoolExecutor(
            max_workers=max(4, 2 * conf.n_shards * conf.n_replicas))
        #: reads get their own pool: a wedged twin blocking long search
        #: reads must not starve write delivery of workers
        self._read_pool = ThreadPoolExecutor(
            max_workers=max(16, 4 * conf.n_shards * conf.n_replicas))
        self._retry_thread = threads.spawn("msg1-retry",
                                           self._retry_loop)
        self._hb_thread = None
        if use_heartbeat:
            self._hb_thread = threads.spawn("pingserver",
                                            self._heartbeat_loop)

    def close(self) -> None:
        self._stop.set()
        self._pool.shutdown(wait=False)
        if self.transport.gen_observer == self._observe_gen:
            self.transport.gen_observer = None
        self.transport.close()

    # --- cache-plane generations -----------------------------------------

    def _observe_gen(self, addr: str, gen: int) -> None:
        """Transport hook: an X-OSSE-Gen reply header from any node of
        shard s raises that shard's observed node generation."""
        s = self._addr_shard.get(addr)
        if s is None:
            return
        with self._gen_lock:
            if gen > self._gen_node[s]:
                self._gen_node[s] = gen

    def shard_gen(self, shard: int) -> tuple[int, int]:
        with self._gen_lock:
            return (self._gen_local[shard], self._gen_node[shard])

    def gen_vector(self) -> tuple:
        """All shards' generation pairs — the front result cache's
        generation (equality-compared; any component moving kills
        dependent entries)."""
        with self._gen_lock:
            return tuple(zip(self._gen_local, self._gen_node))

    def _bump_local_gen(self, shard: int) -> None:
        with self._gen_lock:
            self._gen_local[shard] += 1

    @property
    def pending_writes(self) -> int:
        return sum(len(q) for q in self._queues.values())

    # --- fleet metrics scrape (PagePerf-across-hosts) --------------------

    def scrape(self, timeout: float = SCRAPE_TIMEOUT_S) -> dict:
        """Pull ``/rpc/stats`` from every host and merge into the fleet
        view. Returns ``{"hosts": {addr: wire|None}, "fleet":
        {"counters", "latencies" (name -> LatencyStat), "gauges"}}`` —
        fleet percentiles come from the merged histograms, never from
        averaging per-host percentiles. Dead hosts appear as ``None``
        in ``hosts`` and are simply absent from the merge (a scrape is
        a read, not a liveness verdict)."""
        addrs = [self.conf.addresses[s][r]
                 for s in range(self.conf.n_shards)
                 for r in range(self.conf.n_replicas)]
        with trace_mod.timed_span("cluster.scrape", hosts=len(addrs)):
            replies = self.transport.broadcast(
                addrs, "/rpc/stats", {}, timeout)
        hosts = {a: (r.get("stats") if r is not None and r.get("ok")
                     else None)
                 for a, r in replies.items()}
        fleet = merge_wire([w for w in hosts.values() if w is not None])
        g_stats.count("cluster.scrape")
        g_stats.gauge("cluster.scrape_hosts_up",
                      sum(1 for w in hosts.values() if w is not None))
        return {"hosts": hosts, "fleet": fleet}

    # --- liveness (PingServer) -------------------------------------------

    def _ping(self, shard: int, replica: int) -> bool:
        try:
            out = self.transport.request(
                self.conf.addresses[shard][replica], "/rpc/ping", {},
                timeout=PING_TIMEOUT_S)
            return bool(out.get("ok"))
        except Exception:  # noqa: BLE001
            return False

    def check_hosts(self) -> None:
        """One heartbeat sweep over every host. Liveness TRANSITIONS
        fire the operator alert hook (the reference PingServer emails/
        SMSes admins on host death, ``PingServer.h:77`` — here a log
        line plus an optional ``alert_cmd``)."""
        for s in range(self.conf.n_shards):
            for r in range(self.conf.n_replicas):
                was = bool(self.hostmap.alive[s, r])
                now = self._ping(s, r)
                if now:
                    self.hostmap.mark_alive(s, r)
                    # a ping answer drains fault penalty so a
                    # recovered twin re-enters the read rotation
                    # (reads alone can't fix an EWMA it never gets)
                    self.hostmap.decay_rtt(s, r)
                else:
                    self.hostmap.mark_dead(s, r)
                if was != now:
                    self._alert("recovered" if now else "dead", s, r)

    def _alert(self, event: str, shard: int, replica: int) -> None:
        """Operator alert on a liveness transition: always logged; the
        ``alert_cmd`` parm (or OSSE_ALERT_CMD env) additionally runs a
        command with the event in its environment — the email/SMS/
        pager seam without baking in a delivery mechanism."""
        addr = self.conf.addresses[shard][replica]
        log.warning("ALERT host %s (shard %d replica %d) %s",
                    addr, shard, replica, event)
        cmd = os.environ.get("OSSE_ALERT_CMD", "") or \
            getattr(self.parms, "alert_cmd", "")
        if not cmd:
            return
        try:
            import subprocess
            env = dict(os.environ,
                       OSSE_ALERT_EVENT=event,
                       OSSE_ALERT_HOST=addr,
                       OSSE_ALERT_SHARD=str(shard),
                       OSSE_ALERT_REPLICA=str(replica))
            subprocess.Popen(  # osselint: ignore[proc-spawn] — the
                # operator's pager hook (OSSE_ALERT_CMD) is an external
                # command by design; it manages no fleet child
                cmd, shell=True, env=env,
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL)
        except Exception as e:  # noqa: BLE001 — alerting must not kill
            log.warning("alert_cmd failed: %s", e)

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(HEARTBEAT_INTERVAL_S):
            self.check_hosts()

    # --- writes (Msg1: all twins, retry forever) -------------------------

    def _deliver(self, p: _Pending) -> bool:
        try:
            # writes are background traffic (reference Msg4 adds run at
            # niceness 1): the receiving node lets interactive queries
            # go first. NEVER hedged: writes are not idempotent at the
            # ordered-queue layer — one delivery path per twin.
            out = self.transport.request(
                self.conf.addresses[p.shard][p.replica], p.path,
                p.payload, timeout=RPC_TIMEOUT_S, niceness=1)
            return bool(out.get("ok"))
        except Exception as e:  # noqa: BLE001
            log.debug("deliver to %d/%d failed: %s", p.shard, p.replica, e)
            return False

    def _drain_host(self, key: tuple[int, int]) -> None:
        """Redeliver one host's parked writes IN ORDER, stopping at the
        first failure (retry forever, Msg1.cpp:20)."""
        q = self._queues[key]
        try:
            while not self._stop.is_set():
                with q.lock:
                    if not q.items:
                        return
                    p = q.items[0]
                if self._deliver(p):
                    self.hostmap.mark_alive(p.shard, p.replica)
                    with q.lock:
                        q.items.pop(0)
                else:
                    p.attempts += 1
                    self.hostmap.mark_dead(p.shard, p.replica)
                    return  # next sweep retries; order preserved
        finally:
            with q.lock:
                q.in_flight = False

    def _retry_loop(self) -> None:
        """Sweep: kick an independent drain per backlogged host — a
        hung host never head-of-line-blocks a healthy one."""
        while not self._stop.wait(RETRY_INTERVAL_S):
            for key, q in self._queues.items():
                with q.lock:
                    if not q.items or q.in_flight:
                        continue
                    q.in_flight = True
                self._pool.submit(self._drain_host, key)

    def _send_one(self, shard: int, r: int, p: _Pending) -> None:
        q = self._queues[(shard, r)]
        with q.lock:
            # ordering: never overtake parked writes OR an in-flight
            # send/drain to this host — concurrent direct sends could
            # otherwise land out of order and newest-wins would keep a
            # stale version
            if q.items or q.in_flight:
                q.items.append(p)
                return
            q.in_flight = True
        try:
            if not self._deliver(p):
                self.hostmap.mark_dead(shard, r)
                with q.lock:
                    q.items.insert(0, p)
        finally:
            with q.lock:
                q.in_flight = False

    def _write_all_twins(self, shard: int, path: str, payload: dict
                         ) -> None:
        # twins deliver concurrently: a hung twin costs its own timeout,
        # not every caller's write latency × replicas
        futs = [self._pool.submit(self._send_one, shard, r,
                                  _Pending(shard, r, path, payload))
                for r in range(self.conf.n_replicas)]
        for f in futs:
            f.result()

    # --- parm broadcast (0x3f from host0, Parms.cpp:21683) ---------------

    def broadcast_parm(self, name: str, value) -> None:
        """Cluster-wide live parameter update: sequenced, delivered to
        EVERY node (all shards, all twins) through the same ordered
        retry-forever queues as writes — a dead node receives the parm
        when it comes back, in order (Parms.h:497 broadcastParmList).
        This client plays the reference's host0 role: the single
        sequencer."""
        self._parm_counter += 1
        payload = {"name": name, "value": value,
                   "seq": self._parm_counter}
        for s in range(self.conf.n_shards):
            self._write_all_twins(s, "/rpc/parm", payload)

    def attach_conf(self, conf) -> None:
        """Wire a CollectionConf's live updates to the cluster: any
        ``conf.set(...)`` on this (host0) process broadcasts to every
        node, unless the parm is flagged broadcast=False (e.g.
        passwords)."""
        from ..utils import parms as parms_mod

        def fanout(name: str, value) -> None:
            try:
                if not parms_mod.parm(name).broadcast:
                    return
            except KeyError:
                return
            self.broadcast_parm(name, value)
        conf.on_update(fanout)

    def index_document(self, url: str, content: str) -> int:
        docid = ghash.doc_id(url)
        shard = int(self.hostmap.shard_of_docid(docid))
        # bump BEFORE sending: entries must be dead while the write is
        # in flight (the no-stale-window half of the cache contract)
        self._bump_local_gen(shard)
        self._write_all_twins(shard, "/rpc/index",
                              {"url": url, "content": content})
        return docid

    def remove_document(self, url: str) -> None:
        docid = ghash.doc_id(url)
        shard = int(self.hostmap.shard_of_docid(docid))
        self._bump_local_gen(shard)
        self._write_all_twins(shard, "/rpc/remove", {"url": url})

    def save_all(self) -> None:
        for s in range(self.conf.n_shards):
            self._write_all_twins(s, "/rpc/save", {})

    # --- reads (Multicast serving-twin pick + reroute) -------------------

    def _read_shard(self, shard: int, path: str, payload: dict,
                    timeout: float = RPC_TIMEOUT_S,
                    span_parent=None) -> dict | None:
        """Hedged twin read: the primary goes to the currently-fastest
        live twin (Multicast.cpp:520 pickBestHost — alive first, then
        lowest RTT EWMA); if it fails outright the next twin launches
        immediately, and if it merely dawdles past the hedge delay the
        SAME request races on the other twin and the first good answer
        wins (Dean & Barroso hedged requests). None = whole shard down.

        A failed read dead-marks the host only when a follow-up ping
        ALSO fails — one slow deep-paging query must not take a
        healthy twin out of rotation (the reference distinguishes
        request timeout from host death the same way: PingServer owns
        liveness, Multicast only reroutes). A twin that completed with
        a mere not-ok answer is healthy by construction — no ping, no
        penalty."""
        order = self.hostmap.twin_order(shard)
        addrs = [self.conf.addresses[shard][r] for r in order]
        t0 = time.monotonic()
        out, winner, failures = self.transport.hedged(
            addrs, path, payload, timeout=timeout,
            span_parent=span_parent)
        for i, err in failures:
            r = order[i]
            if isinstance(err, transport_mod.NotOkError):
                continue
            if isinstance(err, transport_mod.RefusedError):
                # actively refused the dial: known dead RIGHT NOW, not
                # merely slow — no ping grace, out of rotation at once
                # (the transport already penalized its EWMA)
                self.hostmap.mark_dead(shard, r)
                self.hostmap.penalize(shard, r, 1.0)
                continue
            if self._ping(shard, r):
                # alive but slow/failed on this request: penalize its
                # load signal, keep it alive
                self.hostmap.penalize(shard, r, 1.0)
            else:
                self.hostmap.mark_dead(shard, r)
        if out is None:
            return None
        r = order[winner]
        self.hostmap.mark_alive(shard, r)
        self.hostmap.observe_rtt(shard, r, time.monotonic() - t0)
        # a twin still wedged in flight when the hedge won gets its
        # load signal bumped inside Transport.hedged (the abandoned
        # request never reports a latency sample) — mirror that into
        # the hostmap twin ordering
        for i in range(winner):
            if all(f[0] != i for f in failures):
                self.hostmap.penalize(shard, order[i],
                                      time.monotonic() - t0)
        return out

    def get_document(self, docid: int) -> dict | None:
        shard = int(self.hostmap.shard_of_docid(docid))
        out = self._read_shard(shard, "/rpc/doc", {"docid": int(docid)})
        return out.get("doc") if out else None

    # --- scatter-gather query (Msg3a) ------------------------------------

    def _search_shard(self, shard: int, q: str, topk: int,
                      lang: int, parent_span=None,
                      deadline=None, tier=None,
                      tenant=None) -> dict | None:
        """One shard's leg of the scatter: rides the per-shard batcher
        so concurrent queries coalesce into one (hedged) RPC.
        ``parent_span`` carries the caller's trace across the
        read-pool thread hop (contextvars don't follow threads).

        The leg cache is checked here with the shard's generation
        captured BEFORE the RPC: a write racing the read moves the
        generation, so the entry we store is already dead — correctness
        over hit rate."""
        key = (shard, q, topk, lang)
        gen = self.shard_gen(shard)
        hit, out = self._leg_cache.lookup(key, gen=gen)
        if hit:
            if parent_span is not None:
                parent_span.tag(leg_cache="hit")
            return out
        out = self._batchers[shard].submit(q, topk, lang,
                                           SEARCH_TIMEOUT_S,
                                           parent_span=parent_span,
                                           deadline=deadline,
                                           tier=tier,
                                           tenant=tenant)
        if out is not None and out.get("ok", True):
            self._leg_cache.put(key, out, gen=gen)
        return out

    def search_batch(self, queries: list[str], topk: int = 10,
                     lang: int = 0, with_snippets: bool = True,
                     site_cluster: bool = True, offset: int = 0,
                     conf=None) -> list:
        """Many queries, answered concurrently: each runs the normal
        scatter-gather merge, but their per-shard legs coalesce in the
        shard batchers into batched ``/rpc/search`` RPCs — one
        ``search_device_batch`` dispatch per shard per window instead
        of one RPC per (query, shard). Results come back in input
        order."""
        if not queries:
            return []
        from ..query.engine import SearchResults
        with ThreadPoolExecutor(
                max_workers=min(32, len(queries))) as ex:
            futs = [ex.submit(self.search, q, topk=topk, lang=lang,
                              with_snippets=with_snippets,
                              site_cluster=site_cluster,
                              offset=offset, conf=conf)
                    for q in queries]
            out = []
            for q, f in zip(queries, futs):
                try:
                    out.append(f.result())
                except Exception as e:  # noqa: BLE001 — one bad query
                    # must not sink its batchmates: degrade to an
                    # empty, uncacheable answer (same contract as a
                    # timed-out scatter leg)
                    log.warning("search_batch: %r failed: %s", q, e)
                    g_stats.count("results.degraded")
                    out.append(SearchResults(
                        query=q, total_matches=0, results=[],
                        degraded=True))
            return out

    def search(self, q: str, topk: int = 10, lang: int = 0,
               with_snippets: bool = True, site_cluster: bool = True,
               offset: int = 0, conf=None):
        """Fan out to every shard's serving twin, merge top-k, then
        fetch titlerecs from the owning shards (Msg20).

        Wrapped by the front result cache (Msg17/Msg40Cache role):
        keyed on the full request shape, generation = the shard-gen
        vector, single-flight so a stampede of one hot query runs the
        scatter once."""
        # conf enters the ranking only through the PQR factors
        # (engine.apply_pqr), so key on those values — never id(conf):
        # CPython reuses object ids, and equal confs should share
        pqr = None if conf is None else (
            bool(conf.pqr_enabled), float(conf.pqr_lang_demote),
            float(conf.pqr_site_demote), float(conf.pqr_depth_demote))
        key = (q, topk, lang, with_snippets, site_cluster, offset, pqr)
        # the user-observed latency metric (cache hits included) — the
        # histogram the query_p99 SLO reads
        with trace_mod.timed_span("cluster.query"):
            out, _ = self._result_cache.get_or_compute(
                key, lambda: self._search_uncached(
                    q, topk=topk, lang=lang,
                    with_snippets=with_snippets,
                    site_cluster=site_cluster, offset=offset,
                    conf=conf))
        if getattr(out, "degraded", False):
            # a partial answer (shard down) must not be pinned for a
            # whole TTL — serve it once, recompute next time
            self._result_cache.invalidate(key)
        return out

    def _search_uncached(self, q: str, topk: int = 10, lang: int = 0,
                         with_snippets: bool = True,
                         site_cluster: bool = True,
                         offset: int = 0, conf=None):
        from ..query.compiler import compile_query
        from ..query.engine import (PQR_SCAN, SearchResults,
                                    build_results, finish_page)

        want = max(topk + offset, PQR_SCAN)
        over = max(want * 2, 16)
        # the scatter span (and the query deadline + tier + tenant)
        # are handed to each leg explicitly: the legs run on read-pool
        # threads, where contextvars do not follow
        scatter_sp = trace_mod.begin("scatter",
                                     shards=self.conf.n_shards)
        dl = deadline_mod.current()
        tier = priority_mod.current_tier()
        tenant = priority_mod.current_tenant()
        futs = [self._read_pool.submit(
            self._search_shard, s, q, over, lang, scatter_sp, dl,
            tier, tenant)
            for s in range(self.conf.n_shards)]
        total = 0
        docids: list[int] = []
        scores: list[float] = []
        degraded = False
        for f in futs:
            try:
                # overall deadline: one wedged shard degrades the
                # answer instead of hanging the caller for the full
                # per-twin timeout ladder
                out = f.result(timeout=SEARCH_TIMEOUT_S + 5.0)
            except Exception:  # noqa: BLE001 — timeout → partial
                out = None
            if out is None:
                degraded = True  # whole shard down: partial answer
                continue
            total += int(out.get("total", 0))
            docids += [int(x) for x in as_array(out.get("docids", []))]
            scores += [float(x)
                       for x in as_array(out.get("scores", []))]
        if degraded:
            # normalized partial answer (shard down / leg timeout):
            # stamped in stats, tagged in the trace, and the SERP is
            # never cached (search() invalidates; the serve layer skips
            # its page cache too)
            g_stats.count("results.degraded")
        if scatter_sp is not None:
            scatter_sp.tag(degraded=degraded)
            scatter_sp.finish()
        with trace_mod.timed_span("query.merge", docs=len(docids)):
            order = np.argsort(-np.asarray(scores, dtype=np.float64),
                               kind="stable")
            plan = compile_query(q, lang=lang)
        # prefetch the likely titlerecs concurrently (the reference
        # launches its Msg20 summary requests in parallel,
        # Msg40::launchMsg20s); build_results then reads the cache
        prefetch = [docids[i] for i in order[: want + 8]]
        with trace_mod.span("query.prefetch", docs=len(prefetch)):
            fetched = dict(zip(prefetch,
                               self._read_pool.map(self.get_document,
                                                   prefetch)))
        get_doc = lambda d: fetched.get(d) if d in fetched \
            else self.get_document(d)
        results, clustered = build_results(
            get_doc,
            [docids[i] for i in order], [scores[i] for i in order],
            plan, topk=want, with_snippets=False,
            site_cluster=site_cluster)
        page = finish_page(
            results, offset=offset, topk=topk, conf=conf, qlang=lang,
            get_doc=get_doc,
            langid_of=lambda d: (fetched.get(d) or {}).get("langid", 0),
            words=plan.match_words(),
            with_snippets=with_snippets)
        return SearchResults(
            query=q, total_matches=total, results=page,
            clustered=clustered, degraded=degraded)
