"""Distributed plane: device mesh, docid sharding, scatter-gather query.

The TPU-native replacement for the reference's cluster layer (SURVEY
§2.4-2.5): ``Hostdb`` (cluster map) → :mod:`hostmap`; Msg1/Msg4 sharded
record routing → :class:`sharded.ShardedCollection` adds; Msg3a/Msg39
scatter-gather with per-shard intersect + cross-shard top-k merge →
``shard_map`` over a ``jax.sharding.Mesh`` with an in-mesh all-gather
merge (ICI collectives instead of reliable-UDP fan-out); the
UdpServer/Multicast/PingServer host plane (shards as separate node
processes, twin failover, retry-forever writes) → :mod:`cluster`.
"""

from .cluster import ClusterClient, HostsConf, ShardNodeServer
from .hostmap import HostMap, make_mesh
from .sharded import (MeshResident, MeshServeIndex, ShardedCollection,
                      sharded_search)

__all__ = ["ClusterClient", "HostMap", "HostsConf", "MeshResident",
           "MeshServeIndex", "ShardNodeServer", "ShardedCollection",
           "make_mesh", "sharded_search"]
